"""State-integrity plane: on-device invariant auditing + row repair.

The supervisor catches a plane that *stops* and the governor catches a
plane that *slows*; this module catches a plane that keeps ticking while
its state is silently wrong (a NaN'd mixer row, a munger cursor that
jumped backwards, a bit flipped by the fault injector). Two halves:

* ``audit_plane(state, mirror)`` — a jitted, fused reduction over the
  whole PlaneState that piggybacks on the tick every
  ``integrity.audit_every_ticks`` ticks. It emits a per-room violation
  bitmask [R] plus tiny per-rule counters; the only host round-trip is
  fetching those few dozen bytes alongside the tick outputs. Under the
  mesh path the reductions shard with the state (GSPMD partitions the
  per-room all/any just like the tick kernels).

* ``IntegrityMonitor`` — the host-side repair ladder. A flagged room is
  quarantined same-tick (fan-out masked in ``_fan_out``, egress muted
  via the governor's effective-ctrl overlay), then repaired by restoring
  ONLY that row from the supervisor's last verified checkpoint via the
  existing row serialization. Bounded attempts; row repair failing or a
  violation storm escalates to a supervisor full restart-from-snapshot
  (restart cause ``integrity``, vs the watchdog's ``stall``).

Audit rules (bit per rule, see AUDIT_RULES):

  bit 0  nonfinite — any NaN/Inf in a float leaf of the room's state
  bit 1  range     — |x| > 1e30 in a float leaf (a single high-exponent
                     bitflip usually stays finite; this catches it)
  bit 2  cursor    — per-stream (ext seqnum, received) went BACKWARDS
                     vs the previous audit's mirror while the stream
                     identity (started + first_sn) is unchanged, so
                     legitimate stream resets don't trip it
  bit 3  ctrl      — max_spatial/max_temporal outside their valid range
  bit 4  bounds    — selector layers or BWE ring cursor out of bounds
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.models import plane
from livekit_server_tpu.ops import bwe, selector
from livekit_server_tpu.utils.checksum import ChecksumError
from livekit_server_tpu.utils.logger import Logger

AUDIT_RULES = ("nonfinite", "range", "cursor", "ctrl", "bounds")
NUM_RULES = len(AUDIT_RULES)

BIT_NONFINITE = 1 << 0
BIT_RANGE = 1 << 1
BIT_CURSOR = 1 << 2
BIT_CTRL = 1 << 3
BIT_BOUNDS = 1 << 4
# Set by the PAGED runtime's map_audit_mask, not by audit_plane: the
# device page table diverged from the pager's canonical host mirrors
# (an SDC hit on the indirection layer itself). The table rows are
# repaired from the host copy immediately; the rooms that computed
# through the corrupt mapping still quarantine + row-repair like any
# other violation.
BIT_TABLE = 1 << 5

# Finite values past this are treated as corruption: no real rate, byte
# count, jitter, or audio level in the plane approaches 1e30, but a
# flipped exponent bit on any normal float32 lands far above it.
RANGE_LIMIT = 1e30


class AuditMirror(NamedTuple):
    """Stream-cursor registers from the previous audit, [R, T*L].

    ext_sn folds the wrap counter in (sn_cycles * 65536 + highest_sn) so
    a legitimate 16-bit SN wrap between audits is still monotonic.
    """

    started: jax.Array   # bool
    first_sn: jax.Array  # int32
    ext_sn: jax.Array    # int32
    received: jax.Array  # int32


def init_mirror(state: plane.PlaneState) -> AuditMirror:
    s = state.stats
    return AuditMirror(
        started=jnp.zeros_like(s.started),
        first_sn=jnp.zeros_like(s.first_sn),
        ext_sn=jnp.zeros_like(s.highest_sn),
        received=jnp.zeros_like(s.received),
    )


def audit_plane(
    state: plane.PlaneState, mirror: AuditMirror
) -> tuple[jax.Array, jax.Array, AuditMirror]:
    """Fused integrity reduction -> (mask [R] int32, counts [5] int32,
    new mirror). Designed to be jitted and to shard with the state."""
    num_rooms = state.audio_state.smoothed_level.shape[0]

    bad_finite = jnp.zeros((num_rooms,), jnp.bool_)
    bad_range = jnp.zeros((num_rooms,), jnp.bool_)
    for leaf in jax.tree_util.tree_leaves(state):
        if jnp.issubdtype(leaf.dtype, jnp.inexact):
            flat = leaf.reshape(num_rooms, -1)
            bad_finite |= ~jnp.isfinite(flat).all(axis=1)
            bad_range |= (jnp.abs(flat) > RANGE_LIMIT).any(axis=1)

    s = state.stats
    ext_sn = s.sn_cycles * 65536 + s.highest_sn
    same_stream = mirror.started & s.started & (s.first_sn == mirror.first_sn)
    went_back = same_stream & (
        (ext_sn < mirror.ext_sn) | (s.received < mirror.received)
    )
    bad_cursor = went_back.any(axis=1)
    new_mirror = AuditMirror(
        started=s.started, first_sn=s.first_sn, ext_sn=ext_sn, received=s.received
    )

    c = state.ctrl
    bad_ctrl = (
        (c.max_spatial < 0)
        | (c.max_spatial >= plane.MAX_LAYERS)
        | (c.max_temporal < 0)
        | (c.max_temporal >= plane.MAX_TEMPORAL)
    ).reshape(num_rooms, -1).any(axis=1)

    sel = state.sel
    layer_oob = jnp.zeros((num_rooms,), jnp.bool_)
    for arr, hi in (
        (sel.current_spatial, plane.MAX_LAYERS),
        (sel.target_spatial, plane.MAX_LAYERS),
        (sel.current_temporal, plane.MAX_TEMPORAL),
        (sel.target_temporal, plane.MAX_TEMPORAL),
    ):
        layer_oob |= (
            (arr < selector.INVALID_LAYER) | (arr >= hi)
        ).reshape(num_rooms, -1).any(axis=1)
    ring = state.bwe_state.ring_pos
    layer_oob |= (
        (ring < 0) | (ring >= bwe.WINDOW)
    ).reshape(num_rooms, -1).any(axis=1)

    rules = (bad_finite, bad_range, bad_cursor, bad_ctrl, layer_oob)
    mask = jnp.zeros((num_rooms,), jnp.int32)
    for bit, bad in enumerate(rules):
        mask |= jnp.where(bad, jnp.int32(1 << bit), 0)
    counts = jnp.stack([bad.sum().astype(jnp.int32) for bad in rules])
    return mask, counts, new_mirror


@functools.lru_cache(maxsize=None)
def _build_audit():
    # The mirror is consumed every audit; donating it keeps the buffer
    # count flat on device. State is NOT donated — the tick owns it.
    return jax.jit(audit_plane, donate_argnums=(1,))


class IntegrityMonitor:
    """Host driver for the audit kernel and the repair ladder.

    Threading contract mirrors the governor's: ``maybe_audit`` runs on
    the device-step worker thread with state_lock held by the caller
    (enforced by GC01 on the call site); it only reads device state and
    mutates plain-Python monitor fields, which is GIL-safe. ``process``
    runs on the event loop at the serving loop's window edge (outside
    the tick's lock region) and takes state_lock lexically around each
    row repair.
    """

    def __init__(
        self,
        runtime: Any,
        *,
        audit_every_ticks: int = 16,
        max_row_repairs: int = 3,
        storm_threshold: int = 4,
        log: Logger | None = None,
    ) -> None:
        self.runtime = runtime
        self.audit_every = max(1, int(audit_every_ticks))
        self.max_row_repairs = max(1, int(max_row_repairs))
        self.storm_threshold = max(1, int(storm_threshold))
        self.log = (log or Logger()).with_fields(component="integrity")

        # () -> decoded full-plane snapshot dict or None; wired to the
        # supervisor's last verified checkpoint generation.
        self.snapshot_provider: Callable[[], dict | None] | None = None
        # (reason) -> None; wired to supervisor.request_restart.
        self.escalate_cb: Callable[[str], None] | None = None

        self.quarantined: set[int] = set()
        self._pending_repair: set[int] = set()
        self._row_attempts: dict[int, int] = {}
        self._mirror: AuditMirror | None = None
        self._audit = _build_audit()
        self._escalated_epoch = -1
        # Latched between escalate_cb and on_full_restore: the restart
        # request and the restore land on the event loop while the worker
        # thread keeps ticking (and auditing) the still-corrupt state —
        # possibly in the new run_epoch, which the epoch guard alone
        # would treat as fresh corruption and escalate again.
        self._restore_pending = False

        self.audits = 0
        self.violations_total = 0
        self.rows_quarantined = 0
        self.rows_repaired = 0
        self.repair_failures = 0
        self.escalations = 0
        self.rule_violations = {name: 0 for name in AUDIT_RULES}
        self.last_audit_tick = -1
        self.last_mask: list[int] = []
        self.audit_s = 0.0

    # -- device-step side ------------------------------------------------

    def maybe_audit(self, tick_index: int) -> None:
        """Run the audit kernel if this tick is on the audit cadence.

        Called from PlaneRuntime._device_step AFTER the new state is
        committed; the caller holds state_lock (GC01 lock_held).
        """
        if tick_index % self.audit_every:
            return
        rt = self.runtime
        t0 = time.perf_counter()
        if self._mirror is None:
            self._mirror = init_mirror(rt.state)
        mask_dev, counts_dev, self._mirror = self._audit(rt.state, self._mirror)
        mask = np.asarray(mask_dev)
        counts = np.asarray(counts_dev)
        # Paged layout: the audit ran over POOLED page rows; the runtime
        # maps the per-page mask to per-room (OR of the room's pages) and
        # folds in its page-table SDC check (BIT_TABLE). Dense runtimes
        # have no mapper — the mask is already per-room.
        mapper = getattr(rt, "map_audit_mask", None)
        if mapper is not None:
            mask = mapper(mask)
        self.audit_s += time.perf_counter() - t0
        self.audits += 1
        self.last_audit_tick = tick_index
        self.last_mask = [int(m) for m in mask]
        if not mask.any():
            # Rooms that audited clean and are out of quarantine have
            # demonstrably recovered; forget their repair attempts.
            for row in list(self._row_attempts):
                if row not in self.quarantined:
                    del self._row_attempts[row]
            return
        self._handle_violations(mask, counts, tick_index)

    def _handle_violations(
        self, mask: np.ndarray, counts: np.ndarray, tick_index: int
    ) -> None:
        rt = self.runtime
        flagged = [int(r) for r in np.nonzero(mask)[0]]
        for name, n in zip(AUDIT_RULES, counts):
            self.rule_violations[name] += int(n)
        self.violations_total += len(flagged)
        self.log.warn(
            "integrity audit flagged rooms",
            tick=tick_index,
            rooms=flagged,
            mask=[int(mask[r]) for r in flagged],
        )
        # Quarantine first — even when escalating, flagged rooms stop
        # fanning out corrupt media the same tick.
        from livekit_server_tpu.runtime.trace import EV_QUARANTINE

        bb = getattr(rt, "blackbox", None)
        for row in flagged:
            if row not in self.quarantined:
                self.quarantined.add(row)
                self.rows_quarantined += 1
                if bb is not None:
                    bb.emit(row, EV_QUARANTINE, float(tick_index))
                    bb.dump_to(row, "quarantine")
        rt._ctrl_dirty = True
        if self._restore_pending:
            # A full restore is already in flight; what we just audited
            # is the same corruption, pre-restore. The rows stay
            # quarantined — don't burn repair attempts or escalate again.
            return
        if len(flagged) > self.storm_threshold:
            self._escalate(
                f"integrity storm: {len(flagged)} rooms flagged at tick {tick_index}"
            )
            return
        for row in flagged:
            attempts = self._row_attempts.get(row, 0) + 1
            self._row_attempts[row] = attempts
            if attempts > self.max_row_repairs:
                self._escalate(
                    f"room {row} still corrupt after {attempts - 1} row repairs"
                )
                return
            self._pending_repair.add(row)

    def _escalate(self, reason: str) -> None:
        rt = self.runtime
        if self._restore_pending or self._escalated_epoch == rt.run_epoch:
            return  # one full restart per plane epoch / in-flight restore
        self._escalated_epoch = rt.run_epoch
        self.escalations += 1
        self._pending_repair.clear()
        bb = getattr(rt, "blackbox", None)
        if bb is not None:
            from livekit_server_tpu.runtime.trace import EV_ESCALATE

            bb.emit(bb.NODE, EV_ESCALATE, float(self.escalations))
            bb.dump_to(bb.NODE, "integrity_escalation")
        self.log.error("integrity escalation: full restart requested", reason=reason)
        if self.escalate_cb is not None:
            self.escalate_cb(reason)
            self._restore_pending = True

    # -- event-loop side -------------------------------------------------

    async def process(self) -> None:
        """Drain the repair queue: restore each flagged row from the last
        verified checkpoint. Called from PlaneRuntime._run at the window
        edge (and after _complete on the step_once path), never with
        state_lock already held."""
        if not self._pending_repair:
            return
        rt = self.runtime
        rows = sorted(self._pending_repair)
        self._pending_repair.clear()
        snap = self.snapshot_provider() if self.snapshot_provider else None
        for row in rows:
            if row not in self.quarantined:
                continue
            if snap is None:
                self.repair_failures += 1
                self._escalate(
                    f"room {row} corrupt and no verified checkpoint to repair from"
                )
                return
            try:
                row_snap = rt.row_snapshot_from_full(snap, row)
                async with rt.state_lock:
                    rt.repair_room_row(row, row_snap)
            except (ChecksumError, ValueError, KeyError, IndexError) as e:
                self.repair_failures += 1
                self.log.warn("row repair rejected", room=row, error=str(e))
                bb = getattr(rt, "blackbox", None)
                if bb is not None:
                    from livekit_server_tpu.runtime.trace import EV_REPAIR_FAIL

                    bb.emit(row, EV_REPAIR_FAIL)
                    bb.dump_to(row, "repair_failed")
                self._escalate(f"row repair failed for room {row}: {e}")
                return
            self.quarantined.discard(row)
            # The row's cursors legitimately rewound to checkpoint time;
            # drop the mirror so the next audit re-baselines instead of
            # flagging the rewind.
            self._mirror = None
            rt._ctrl_dirty = True
            self.rows_repaired += 1
            self.log.info("room row repaired from checkpoint", room=row)
            bb = getattr(rt, "blackbox", None)
            if bb is not None:
                from livekit_server_tpu.runtime.trace import EV_REPAIR_OK

                bb.emit(row, EV_REPAIR_OK)
                bb.dump_to(row, "repair_ok")

    # -- restore hooks ---------------------------------------------------

    def on_row_restore(self, row: int) -> None:
        """A row was legitimately rewritten (migration adopt / handoff
        restore): clear its quarantine history and re-baseline cursors."""
        self.quarantined.discard(row)
        self._pending_repair.discard(row)
        self._row_attempts.pop(row, None)
        self._mirror = None

    def on_layout_change(self) -> None:
        """The paged runtime applied a page-table delta (alloc / free /
        grow / compaction): page rows changed identity under the audit
        mirror's cursors, so re-baseline instead of flagging relocated
        streams as rewinds."""
        self._mirror = None

    def on_full_restore(self) -> None:
        """The whole plane was restored (supervisor restart)."""
        self.quarantined.clear()
        self._pending_repair.clear()
        self._row_attempts.clear()
        self._mirror = None
        self._restore_pending = False

    # -- introspection ---------------------------------------------------

    def stats_dict(self) -> dict:
        return {
            "audits": self.audits,
            "violations_total": self.violations_total,
            "violations_by_rule": dict(self.rule_violations),
            "rows_quarantined": self.rows_quarantined,
            "rows_repaired": self.rows_repaired,
            "repair_failures": self.repair_failures,
            "escalations": self.escalations,
            "quarantined_rows": sorted(self.quarantined),
            "audit_every_ticks": self.audit_every,
            "last_audit_tick": self.last_audit_tick,
            "audit_s": self.audit_s,
        }
