"""UDP media transport: plain RTP in, rewritten RTP out.

Reference parity: the reference's media path is Pion WebRTC over
ICE/DTLS/SRTP on the UDP mux (pkg/rtc/config.go UDPMux, rtcconfig). This
build's native path is deliberately simpler wire-wise — plain RTP over
UDP with SSRC-based session binding (the `add_track` signal response
carries the SSRC the server assigned; E2EE payloads pass through
untouched, matching the reference's encryption passthrough stance) — but
occupies the same architectural seat: socket → native batch parse
(livekit_server_tpu.native.rtp) → IngestBuffer, and egress →
native header rewrite → socket.

A client's source address latches on first packet per SSRC (ICE-lite-ish
latching, like the reference's UDP mux address learning).
"""

from __future__ import annotations

import asyncio
import secrets
from dataclasses import dataclass

import numpy as np

from livekit_server_tpu.native import rtp
from livekit_server_tpu.runtime.ingest import IngestBuffer, PacketIn

VP8_PT = 96
OPUS_PT = 111
AUDIO_LEVEL_EXT_ID = 1

# Subscriber address punch: a client proves it owns the address it wants
# media sent to by sending this magic + its 32-bit punch id from that
# socket (the ICE-connectivity-check analog; a client-supplied address in
# a signal message is never trusted — traffic-reflection hardening).
PUNCH_REQ = b"LKPUNCH0"
PUNCH_ACK = b"LKPUNCH1"


@dataclass
class SSRCBinding:
    room: int            # room row
    track: int           # track col
    is_video: bool
    layer: int = 0       # simulcast spatial layer carried by this SSRC


class UDPMediaTransport(asyncio.DatagramProtocol):
    """One socket for the whole node (the reference's single-port UDPMux)."""

    def __init__(self, ingest: IngestBuffer):
        self.ingest = ingest
        self.transport: asyncio.DatagramTransport | None = None
        self.bindings: dict[int, SSRCBinding] = {}       # ssrc → coords
        self.addrs: dict[int, tuple] = {}                # ssrc → latched addr
        self.sub_addrs: dict[tuple, tuple] = {}          # (room,sub) → addr
        self.sub_ssrc: dict[tuple, dict[int, int]] = {}  # (room,sub) → {track: ssrc}
        self.track_kind: dict[tuple, bool] = {}          # (room,track) → is_video
        self.punch_ids: dict[int, list] = {}             # punch id → [key, latched_addr|None]
        self._punch_by_sub: dict[tuple, int] = {}        # (room,sub) → punch id
        self._rx_pending: list[tuple[bytes, tuple]] = []
        self._rx_scheduled = False
        self.stats = {
            "rx": 0, "tx": 0, "unknown_ssrc": 0, "parse_errors": 0,
            "addr_mismatch": 0, "bad_punch": 0,
        }

    # -- control-plane API ------------------------------------------------
    def _new_ssrc(self) -> int:
        """Random 32-bit SSRC (unguessable — a sequential counter would let
        an off-path sender inject media into live tracks)."""
        while True:
            ssrc = secrets.randbits(32) | 0x10000
            if ssrc not in self.bindings:
                return ssrc

    def assign_ssrc(self, room: int, track: int, is_video: bool, layer: int = 0) -> int:
        """Bind a fresh SSRC to one (track, simulcast layer); sent back in
        signal. Simulcast publishers get one SSRC per layer, matching the
        reference's per-layer SSRCs (mediatrack.go layer SSRC bookkeeping)."""
        ssrc = self._new_ssrc()
        self.bindings[ssrc] = SSRCBinding(room, track, is_video, layer)
        self.track_kind[(room, track)] = is_video
        return ssrc

    def release_ssrc(self, ssrc: int) -> None:
        self.bindings.pop(ssrc, None)
        self.addrs.pop(ssrc, None)

    def release_track(self, room: int, track: int) -> None:
        """Track unpublished: drop its kind entry + every layer SSRC."""
        self.track_kind.pop((room, track), None)
        for ssrc in [
            s for s, b in self.bindings.items() if b.room == room and b.track == track
        ]:
            self.release_ssrc(ssrc)

    def set_track_kind(self, room: int, track: int, is_video: bool) -> None:
        """Record media kind for egress PT selection (any transport)."""
        self.track_kind[(room, track)] = is_video

    def register_subscriber(self, room: int, sub: int, addr: tuple) -> None:
        """Trusted-caller egress registration (tests / in-process tooling).
        The signal plane must NOT call this with a client-supplied address —
        it hands out a punch id instead (assign_subscriber_punch)."""
        self.sub_addrs[(room, sub)] = addr

    def assign_subscriber_punch(self, room: int, sub: int, rotate: bool = False) -> int:
        """Mint an unguessable punch id for a subscriber. The client proves
        address ownership by sending PUNCH_REQ+id from its media socket;
        only then does egress flow to that source address.

        One outstanding id per (room, sub): repeated subscription signals
        reuse it (no unbounded growth, no widening of the guessable-id
        set; a same-address retry of a latched id just re-acks). Once
        latched, the id binds to its first source address — a replayed
        PUNCH_REQ from anywhere else is rejected, so an observer of the
        cleartext handshake cannot re-aim the stream. `rotate=True`
        (client sent udp_repunch) invalidates the old id and mints a
        fresh one: the recovery path for a NAT rebind — only the
        authenticated signal session can trigger it, never the old id."""
        key = (room, sub)
        existing = self._punch_by_sub.get(key)
        if existing is not None:
            if not rotate:
                return existing
            del self.punch_ids[existing]
        while True:
            pid = secrets.randbits(32)
            if pid and pid not in self.punch_ids:
                break
        self.punch_ids[pid] = [key, None]
        self._punch_by_sub[key] = pid
        return pid

    def release_subscriber(self, room: int, sub: int) -> None:
        """Subscriber left: stop egress and free its SSRC map (prevents
        media leaking to a stale address once the sub col is reused)."""
        self.sub_addrs.pop((room, sub), None)
        self.sub_ssrc.pop((room, sub), None)
        pid = self._punch_by_sub.pop((room, sub), None)
        if pid is not None:
            self.punch_ids.pop(pid, None)

    def release_room(self, room: int) -> None:
        """Room closed: drop every binding on its row."""
        for ssrc in [s for s, b in self.bindings.items() if b.room == room]:
            self.release_ssrc(ssrc)
        for key in [k for k in self.sub_addrs if k[0] == room]:
            del self.sub_addrs[key]
        for key in [k for k in self.sub_ssrc if k[0] == room]:
            del self.sub_ssrc[key]
        for key in [k for k in self.track_kind if k[0] == room]:
            del self.track_kind[key]
        for key in [k for k in self._punch_by_sub if k[0] == room]:
            self.punch_ids.pop(self._punch_by_sub.pop(key), None)

    def subscriber_ssrc(self, room: int, sub: int, track: int) -> int:
        """Per-(subscriber, track) egress SSRC (DownTrack's own SSRC)."""
        m = self.sub_ssrc.setdefault((room, sub), {})
        if track not in m:
            m[track] = self._new_ssrc()
        return m[track]

    # -- datagram path ----------------------------------------------------
    def connection_made(self, transport) -> None:
        self.transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        self.stats["rx"] += 1
        if data[:8] == PUNCH_REQ:
            self._handle_punch(data, addr)
            return
        # Coalesce: datagrams arriving in the same event-loop iteration are
        # parsed by ONE native parse_batch call (the batch design this
        # module documents; under media load the loop wakes with many
        # datagrams ready and the per-packet Python overhead amortizes).
        self._rx_pending.append((data, addr))
        if not self._rx_scheduled:
            self._rx_scheduled = True
            asyncio.get_running_loop().call_soon(self._flush_rx)

    def _handle_punch(self, data: bytes, addr) -> None:
        if len(data) < 12:
            self.stats["bad_punch"] += 1
            return
        pid = int.from_bytes(data[8:12], "big")
        entry = self.punch_ids.get(pid)
        if entry is None:
            self.stats["bad_punch"] += 1
            return
        key, latched = entry
        if latched is not None and latched != addr:
            # id already bound to another source: replay/hijack attempt
            self.stats["bad_punch"] += 1
            return
        entry[1] = addr
        self.sub_addrs[key] = addr
        if self.transport is not None:
            self.transport.sendto(PUNCH_ACK + data[8:12], addr)

    def _flush_rx(self) -> None:
        self._rx_scheduled = False
        pending, self._rx_pending = self._rx_pending, []
        if not pending:
            return
        lengths = np.asarray([len(d) for d, _ in pending], np.int32)
        offsets = np.zeros(len(pending), np.int32)
        np.cumsum(lengths[:-1], out=offsets[1:])
        blob = b"".join(d for d, _ in pending)
        parsed = rtp.parse_batch(
            blob, offsets, lengths,
            audio_level_ext=AUDIO_LEVEL_EXT_ID, vp8_pts={VP8_PT},
        )
        for i, (data, addr) in enumerate(pending):
            p = parsed[i]
            if int(p["payload_len"]) < 0:
                self.stats["parse_errors"] += 1
                continue
            ssrc = int(p["ssrc"])
            binding = self.bindings.get(ssrc)
            if binding is None:
                self.stats["unknown_ssrc"] += 1
                continue
            # First packet latches the source address; later packets from a
            # different address are dropped (UDP-mux address learning —
            # without this, anyone who learns an SSRC could inject media).
            latched = self.addrs.setdefault(ssrc, addr)
            if latched != addr:
                self.stats["addr_mismatch"] += 1
                continue
            off, ln = int(p["payload_off"]), int(p["payload_len"])
            self.ingest.push(
                PacketIn(
                    room=binding.room,
                    track=binding.track,
                    sn=int(p["sn"]),
                    ts=int(p["ts"]),
                    size=ln,
                    payload=data[off : off + ln],
                    marker=bool(p["marker"]),
                    layer=binding.layer,
                    temporal=int(p["tid"]),
                    keyframe=bool(p["keyframe"]),
                    layer_sync=bool(p["layer_sync"]) or bool(p["keyframe"]),
                    begin_pic=bool(p["begin_pic"]),
                    pid=max(int(p["picture_id"]), 0),
                    tl0=max(int(p["tl0picidx"]), 0),
                    keyidx=max(int(p["keyidx"]), 0),
                    frame_ms=20 if not binding.is_video else 0,
                    audio_level=int(p["audio_level"]),
                    arrival_rtp=int(p["ts"]),
                )
            )

    def send_egress(self, packets) -> None:
        """Rewrite + send a tick's EgressPackets: assemble all datagrams in
        one buffer, ONE native rewrite call (headers + VP8 payload
        descriptors), then sendto per datagram (the batched write half of
        DownTrack.WriteRTP + pacer)."""
        if self.transport is None:
            return
        buf = bytearray()
        offsets: list[int] = []
        lengths: list[int] = []
        sns: list[int] = []
        tss: list[int] = []
        ssrcs: list[int] = []
        pids: list[int] = []
        tl0s: list[int] = []
        keyidxs: list[int] = []
        vp8_flags: list[int] = []
        addrs: list[tuple] = []
        for pkt in packets:
            addr = self.sub_addrs.get((pkt.room, pkt.sub))
            if addr is None or not pkt.payload:
                continue
            is_video = self.track_kind.get((pkt.room, pkt.track), False)
            header = bytearray(12)
            header[0] = 0x80
            header[1] = (0x80 if pkt.marker else 0) | (VP8_PT if is_video else OPUS_PT)
            offsets.append(len(buf))
            buf += header + pkt.payload
            lengths.append(12 + len(pkt.payload))
            sns.append(pkt.sn)
            tss.append(pkt.ts)
            ssrcs.append(self.subscriber_ssrc(pkt.room, pkt.sub, pkt.track))
            # Device-munged VP8 descriptor values reach the wire here
            # (codecmunger/vp8.go:161): after a simulcast switch or
            # temporal drop, receivers need contiguous picture ids.
            pids.append(pkt.pid if is_video else -1)
            tl0s.append(pkt.tl0 if is_video else -1)
            keyidxs.append(pkt.keyidx if is_video else -1)
            vp8_flags.append(1 if is_video else 0)
            addrs.append(addr)
        if not offsets:
            return
        rtp.rewrite_vp8_batch(
            buf,
            np.asarray(offsets, np.int32),
            np.asarray(lengths, np.int32),
            np.asarray(sns, np.uint16),
            np.asarray(tss, np.uint32),
            np.asarray(ssrcs, np.uint32),
            np.asarray(pids, np.int32),
            np.asarray(tl0s, np.int32),
            np.asarray(keyidxs, np.int32),
            np.asarray(vp8_flags, np.uint8),
        )
        view = memoryview(buf)
        for off, ln, addr in zip(offsets, lengths, addrs):
            self.transport.sendto(bytes(view[off : off + ln]), addr)
            self.stats["tx"] += 1


async def start_udp_transport(
    ingest: IngestBuffer, host: str = "0.0.0.0", port: int = 7882
) -> UDPMediaTransport:
    loop = asyncio.get_running_loop()
    transport, protocol = await loop.create_datagram_endpoint(
        lambda: UDPMediaTransport(ingest), local_addr=(host, port)
    )
    return protocol
