"""UDP media transport: plain RTP in, rewritten RTP out.

Reference parity: the reference's media path is Pion WebRTC over
ICE/DTLS/SRTP on the UDP mux (pkg/rtc/config.go UDPMux, rtcconfig). This
build's native path is deliberately simpler wire-wise — plain RTP over
UDP with SSRC-based session binding (the `add_track` signal response
carries the SSRC the server assigned; E2EE payloads pass through
untouched, matching the reference's encryption passthrough stance) — but
occupies the same architectural seat: socket → native batch parse
(livekit_server_tpu.native.rtp) → IngestBuffer, and egress →
native header rewrite → socket.

A client's source address latches on first packet per SSRC (ICE-lite-ish
latching, like the reference's UDP mux address learning).
"""

from __future__ import annotations

import asyncio
import secrets
import time
from dataclasses import dataclass

import numpy as np

from livekit_server_tpu.native import egress as native_egress, rtp
from livekit_server_tpu.ops.pacer import WIRE_OVERHEAD_BYTES
from livekit_server_tpu.runtime import crypto as _crypto

# ops/pacer (a device-ops module that must not import host runtime code)
# hardcodes the per-packet wire overhead; pin it to the real frame layout
# here so a crypto-header change cannot silently drift the pacer budgets.
# Explicit raise, not assert: the tripwire must survive `python -O`.
if WIRE_OVERHEAD_BYTES != _crypto.HEADER_LEN + 16 + 12:
    raise ImportError(
        "ops/pacer.WIRE_OVERHEAD_BYTES out of sync with sealed-frame layout"
    )
from livekit_server_tpu.runtime.crypto import (
    DIR_C2S,
    MAGIC as CRYPTO_MAGIC,
    MediaCryptoRegistry,
    MediaCryptoSession,
    parse_key_id,
)
from livekit_server_tpu.runtime.ingest import IngestBuffer

VP8_PT = 96
OPUS_PT = 111
RED_PT = 63           # RFC 2198 redundancy for Opus (redreceiver.go seat)
AUDIO_LEVEL_EXT_ID = 1
PLAYOUT_DELAY_EXT_ID = 6  # one-byte ext id for playout-delay (playoutdelay.go)
DD_EXT_ID = 8             # dependency-descriptor ext id (sfu/dependencydescriptor)
SVC_PT = 98               # single-stream SVC VP9 (picture-header parse + DD)
AV1_PT = 99               # single-stream SVC AV1 (DD only — an AV1 payload
                          # must never hit the VP9 descriptor branch: its
                          # aggregation header would misparse as frame bits)
H264_PT = 100             # H264 (RFC 6184) — keyframes from NALU types

# Subscriber address punch: a client proves it owns the address it wants
# media sent to by sending this magic + its 32-bit punch id from that
# socket (the ICE-connectivity-check analog; a client-supplied address in
# a signal message is never trusted — traffic-reflection hardening).
PUNCH_REQ = b"LKPUNCH0"
PUNCH_ACK = b"LKPUNCH1"
# Sentinel for "SSRC has no latched address yet" in the vectorized rx
# path; outside both the IPv4 code space (≥ 0) and the synthetic negative
# codes (small negatives).
_NO_LATCH = -(1 << 62)

# RTCP payload types (rtcp-mux demux range per RFC 5761: byte1 in 192-223).
RTCP_SR = 200
RTCP_RR = 201
RTCP_RTPFB = 205   # FMT 1 = generic NACK, FMT 15 = transport-wide feedback
TWCC_FMT = 15
# Send-time ring depth per (room, sub): must cover the feedback RTT's worth
# of outstanding sealed sends (~300 pps × 200 ms ≈ 60; power of two).
TWCC_RING = 256
RTCP_PSFB = 206    # FMT 1 = PLI, FMT 15 = REMB (application layer feedback)
PLI_THROTTLE_MS = 500.0  # min spacing of upstream keyframe requests per
                         # track (pliThrottle — sfu/buffer config default)
# Probe padding payload: a maximal RTP pad run — 254 zeros + the count
# byte (255) that RFC 3550 §5.1 puts last when the P bit is set.
PAD_RUN = bytes(254) + b"\xff"


class ForwardLatencyProbe:
    """Wall-clock packet-in → wire-out latency histogram.

    The reference's implicit forwarding-latency spec is per-packet and
    measured on the wire (a packet enters `buffer.Buffer.Write` and leaves
    at the pacer's socket write). Here every media datagram is stamped
    when its receive batch returns from recvmmsg (rx_batch →
    IngestBuffer.t_arr) and observed when the native egress send returns —
    so the recorded latency INCLUDES tick-queueing wait, staging, the
    device step, and the kernel send, with no composed/estimated terms.

    Log-spaced bins, vectorized updates (one searchsorted+bincount per
    tick); cheap enough to stay always-on and feed /debug."""

    N_BINS = 96

    def __init__(self, lo_s: float = 5e-5, hi_s: float = 60.0):
        import threading

        self.edges = np.logspace(np.log10(lo_s), np.log10(hi_s), self.N_BINS)
        self.counts = np.zeros(self.N_BINS + 1, np.int64)
        self.n = 0
        self.sum_s = 0.0
        self.max_s = 0.0
        # Observations can come from the event loop AND the pacer worker
        # thread (paced sends run do_send off-loop); numpy += is not
        # atomic, so histogram updates serialize here. One uncontended
        # acquire per tick — noise next to the send itself.
        self._lock = threading.Lock()

    def observe(self, lat_s: np.ndarray) -> None:
        if lat_s.size == 0:
            return
        binned = np.bincount(
            np.searchsorted(self.edges, lat_s), minlength=self.N_BINS + 1
        )
        with self._lock:
            self.counts += binned
            self.n += int(lat_s.size)
            self.sum_s += float(lat_s.sum())
            m = float(lat_s.max())
            if m > self.max_s:
                self.max_s = m

    def _quantile_from(self, counts, n: int, max_s: float, q: float) -> float:
        if n == 0:
            return 0.0
        cum = np.cumsum(counts)
        b = int(np.searchsorted(cum, q * n))
        if b >= self.N_BINS:
            # Overflow bin (beyond the 60 s top edge): the exact maximum is
            # a tighter answer than the collapsed last-edge value.
            return max_s
        return float(self.edges[b])

    def quantile(self, q: float) -> float:
        """Approximate quantile in seconds (upper edge of the q-bin)."""
        with self._lock:
            counts, n, max_s = self.counts.copy(), self.n, self.max_s
        return self._quantile_from(counts, n, max_s, q)

    def reset(self) -> None:
        with self._lock:
            self.counts[:] = 0
            self.n = 0
            self.sum_s = 0.0
            self.max_s = 0.0

    def summary(self) -> dict:
        # Snapshot under the lock: the pacer worker mutates these fields
        # concurrently and /debug must not read torn stats.
        with self._lock:
            counts = self.counts.copy()
            n, sum_s, max_s = self.n, self.sum_s, self.max_s
        return {
            "n": n,
            "mean_ms": round(sum_s / n * 1000.0, 3) if n else 0.0,
            "p50_ms": round(self._quantile_from(counts, n, max_s, 0.50) * 1000.0, 3),
            "p90_ms": round(self._quantile_from(counts, n, max_s, 0.90) * 1000.0, 3),
            "p99_ms": round(self._quantile_from(counts, n, max_s, 0.99) * 1000.0, 3),
            "p999_ms": round(self._quantile_from(counts, n, max_s, 0.999) * 1000.0, 3),
            "max_ms": round(max_s * 1000.0, 3),
        }


def _red_primary(blob: bytes, start: int, length: int) -> tuple[int, int]:
    """RFC 2198 walk: (absolute offset, length) of the primary block's
    payload, or (-1, -1) if malformed (redprimaryreceiver.go decap)."""
    end = start + length
    q = start
    blocks = 0
    while q < end and blob[q] & 0x80:
        if q + 4 > end:
            return -1, -1
        blocks += ((blob[q + 2] & 0x03) << 8) | blob[q + 3]
        q += 4
    if q >= end:
        return -1, -1
    q += 1  # primary's 1-byte header (F=0 | PT)
    data_off = q + blocks
    if data_off > end:
        return -1, -1
    return data_off, end - data_off


def build_nack(sender_ssrc: int, media_ssrc: int, sns) -> bytes:
    """Generic NACK (RFC 4585 §6.2.1): (PID, BLP) pairs from a SN list."""
    sns = sorted(set(s & 0xFFFF for s in sns))
    fci = bytearray()
    i = 0
    while i < len(sns):
        pid = sns[i]
        blp = 0
        j = i + 1
        while j < len(sns) and 0 < ((sns[j] - pid) & 0xFFFF) <= 16:
            blp |= 1 << (((sns[j] - pid) & 0xFFFF) - 1)
            j += 1
        fci += pid.to_bytes(2, "big") + blp.to_bytes(2, "big")
        i = j
    length_words = 2 + len(fci) // 4
    return (
        bytes([0x80 | 1, RTCP_RTPFB])
        + length_words.to_bytes(2, "big")
        + sender_ssrc.to_bytes(4, "big")
        + media_ssrc.to_bytes(4, "big")
        + bytes(fci)
    )


def build_twcc_feedback(
    sender_ssrc: int, media_ssrc: int, entries: list[tuple[int, int]]
) -> bytes:
    """Transport-wide feedback (RTPFB fmt 15 seat, own-wire FCI): the
    client acks sealed-frame counters with its receive timestamps.

        FCI = base_ctr(8) | base_recv_us(8) | n(2) | pad(2)
              | n × (ctr_off u16 | recv_delta_us i32)

    `entries` = [(counter, recv_time_us), ...]; counters within a frame
    must span < 65536 and deltas < ±2147 s (split frames otherwise)."""
    if not entries:
        return b""
    base_ctr = min(c for c, _ in entries)
    base_us = min(u for _, u in entries)
    fci = bytearray(
        base_ctr.to_bytes(8, "big")
        + base_us.to_bytes(8, "big")
        + len(entries).to_bytes(2, "big")
        + b"\x00\x00"
    )
    for c, u in entries:
        fci += (c - base_ctr).to_bytes(2, "big")
        fci += (u - base_us).to_bytes(4, "big", signed=True)
    if len(fci) % 4:
        fci += bytes(4 - len(fci) % 4)
    length_words = 2 + len(fci) // 4
    return (
        bytes([0x80 | TWCC_FMT, RTCP_RTPFB])
        + length_words.to_bytes(2, "big")
        + sender_ssrc.to_bytes(4, "big")
        + media_ssrc.to_bytes(4, "big")
        + bytes(fci)
    )


_TWCC_ENTRY = np.dtype([("off", ">u2"), ("delta", ">i4")])


def parse_nack_fci(fci: bytes) -> list[int]:
    sns = []
    for i in range(0, len(fci) - 3, 4):
        pid = int.from_bytes(fci[i : i + 2], "big")
        blp = int.from_bytes(fci[i + 2 : i + 4], "big")
        sns.append(pid)
        for b in range(16):
            if blp & (1 << b):
                sns.append((pid + b + 1) & 0xFFFF)
    return sns


def ntp_now() -> int:
    """64-bit NTP timestamp (RFC 3550 SR wallclock)."""
    t = time.time() + 2208988800.0  # Unix → NTP epoch (1900)
    sec = int(t)
    frac = int((t - sec) * (1 << 32)) & 0xFFFFFFFF
    return ((sec & 0xFFFFFFFF) << 32) | frac


def ntp_mid32(ntp64: int) -> int:
    """Middle 32 bits of an NTP timestamp (the RR LSR/DLSR unit)."""
    return (ntp64 >> 16) & 0xFFFFFFFF


def build_sr(ssrc: int, ntp64: int, rtp_ts: int, pkts: int, octets: int) -> bytes:
    """Sender report, no report blocks (RFC 3550 §6.4.1)."""
    return (
        bytes([0x80, RTCP_SR, 0, 6])
        + (ssrc & 0xFFFFFFFF).to_bytes(4, "big")
        + ntp64.to_bytes(8, "big")
        + (rtp_ts & 0xFFFFFFFF).to_bytes(4, "big")
        + (pkts & 0xFFFFFFFF).to_bytes(4, "big")
        + (octets & 0xFFFFFFFF).to_bytes(4, "big")
    )


def parse_sr(chunk: bytes):
    """SR → (ssrc, ntp64, rtp_ts); None if truncated."""
    if len(chunk) < 28:
        return None
    return (
        int.from_bytes(chunk[4:8], "big"),
        int.from_bytes(chunk[8:16], "big"),
        int.from_bytes(chunk[16:20], "big"),
    )


def build_ext_section(exts: list[tuple[int, bytes]]) -> bytes:
    """Serialize an RTP header-extension section (RFC 8285): one-byte
    profile when every element fits, two-byte otherwise (DD structures
    exceed the one-byte form's 16-byte cap on keyframes)."""
    two_byte = any(len(d) > 16 or len(d) == 0 or i > 14 for i, d in exts)
    body = bytearray()
    if two_byte:
        profile = 0x1000
        for i, d in exts:
            body += bytes([i, len(d)]) + d
    else:
        profile = 0xBEDE
        for i, d in exts:
            body += bytes([(i << 4) | (len(d) - 1)]) + d
    body += bytes((-len(body)) % 4)
    return (
        profile.to_bytes(2, "big")
        + (len(body) // 4).to_bytes(2, "big")
        + bytes(body)
    )


def build_rr(sender_ssrc: int, media_ssrc: int, fraction_lost: int) -> bytes:
    """Receiver report with one block carrying only fraction_lost (the
    upstream loss signal of medialossproxy.go → buffer
    SetLastFractionLostReport: publishers enable Opus FEC on it)."""
    block = (
        (media_ssrc & 0xFFFFFFFF).to_bytes(4, "big")
        + bytes([fraction_lost & 0xFF])
        + b"\x00" * 19
    )
    return (
        bytes([0x80 | 1, RTCP_RR, 0, 7])
        + (sender_ssrc & 0xFFFFFFFF).to_bytes(4, "big")
        + block
    )


def build_pli(sender_ssrc: int, media_ssrc: int) -> bytes:
    return (
        bytes([0x80 | 1, RTCP_PSFB, 0, 2])
        + sender_ssrc.to_bytes(4, "big")
        + media_ssrc.to_bytes(4, "big")
    )


def build_remb(sender_ssrc: int, bitrate_bps: float, media_ssrcs) -> bytes:
    """REMB (draft-alvestrand-rmcat-remb): exp/mantissa bitrate + SSRC list."""
    bitrate = max(0, int(bitrate_bps))
    exp = 0
    while bitrate >= (1 << 18):
        bitrate >>= 1
        exp += 1
    fci = (
        b"REMB"
        + bytes([len(media_ssrcs)])
        + ((exp << 18) | bitrate).to_bytes(3, "big")
        + b"".join(s.to_bytes(4, "big") for s in media_ssrcs)
    )
    length_words = 2 + len(fci) // 4
    return (
        bytes([0x80 | 15, RTCP_PSFB])
        + length_words.to_bytes(2, "big")
        + sender_ssrc.to_bytes(4, "big")
        + (0).to_bytes(4, "big")
        + fci
    )


def parse_remb(fci: bytes) -> tuple[float, list[int]]:
    if fci[:4] != b"REMB" or len(fci) < 8:
        return 0.0, []
    n = fci[4]
    raw = int.from_bytes(fci[5:8], "big")
    bitrate = float((raw & 0x3FFFF) << (raw >> 18))
    ssrcs = [
        int.from_bytes(fci[8 + 4 * i : 12 + 4 * i], "big")
        for i in range(n)
        if 12 + 4 * i <= len(fci)
    ]
    return bitrate, ssrcs


@dataclass
class SSRCBinding:
    room: int            # room row
    track: int           # track col
    is_video: bool
    layer: int = 0       # simulcast spatial layer carried by this SSRC
    session: MediaCryptoSession | None = None  # publisher's crypto session
    svc: bool = False    # single-stream SVC (VP9/AV1): layers ride the
                         # dependency-descriptor extension, not SSRCs


class UDPMediaTransport(asyncio.DatagramProtocol):
    """One socket for the whole node (the reference's single-port UDPMux)."""

    def __init__(
        self,
        ingest: IngestBuffer,
        crypto: MediaCryptoRegistry | None = None,
        require_encryption: bool = False,
        nack_resolver=None,
    ):
        self.ingest = ingest
        # NACK → replay-packet resolver (PlaneRuntime.resolve_nacks);
        # None = RTX disabled (bare-ingest tooling/tests).
        self.nack_resolver = nack_resolver
        # Standards-lane WebRTC gateway (ICE-lite + DTLS-SRTP); created on
        # demand by enable_gateway() — the sealed lane needs none of it.
        self.gateway = None
        # MCU seat (runtime/mixer.py): per-room Opus decode → mix →
        # per-sub re-encode. None until a subscriber opts in.
        self.audio_mixer = None
        # AEAD media-wire crypto (runtime/crypto.py — the DTLS-SRTP seat).
        # require_encryption drops every plaintext RTP/RTCP/punch datagram;
        # False keeps the legacy cleartext path for in-process tooling.
        self.crypto = crypto
        self.require_encryption = require_encryption
        self.sub_sessions: dict[tuple, MediaCryptoSession] = {}  # (room,sub)→session
        self.tcp_sinks: dict[int, object] = {}  # key_id → TCP frame writer
        self.transport: asyncio.DatagramTransport | None = None
        self.bindings: dict[int, SSRCBinding] = {}       # ssrc → coords
        self.addrs: dict[int, tuple] = {}                # ssrc → latched addr
        # Integer address identities for the vectorized rx path: IPv4
        # addresses code as (ip << 16) | port; anything else (IPv6 via the
        # asyncio endpoint) gets a synthetic negative code. Latch
        # comparisons then run as one numpy equality over the batch
        # instead of tuple hashing per packet.
        self._addr_code: dict[int, int] = {}    # ssrc → latched addr code
        self._tuple_code: dict[tuple, int] = {} # addr tuple → code
        self._code_tuple: dict[int, tuple] = {} # code → addr tuple
        self._syn_code = -2
        self.sub_addrs: dict[tuple, tuple] = {}          # (room,sub) → addr
        self.sub_ssrc: dict[tuple, dict[int, int]] = {}  # (room,sub) → {track: ssrc}
        self.track_kind: dict[tuple, bool] = {}          # (room,track) → is_video
        self.punch_ids: dict[int, list] = {}             # punch id → [key, latched_addr|None]
        self._punch_by_sub: dict[tuple, int] = {}        # (room,sub) → punch id
        self._rx_pending: list[tuple[bytes, tuple]] = []
        self._rx_scheduled = False
        self.egress_rev: dict[int, tuple] = {}           # downtrack ssrc → (room,sub,track)
        self.node_ssrc = secrets.randbits(32)            # our RTCP sender SSRC
        # Upstream loss detection (buffer.go doNACKs): per publisher SSRC.
        self._rx_hi: dict[int, int] = {}                 # ssrc → highest ext SN
        self._rx_missing: dict[int, dict[int, list]] = {}  # ssrc → {sn: [tries, due_ms]}
        self.on_pli = None                               # cb(room, track) for non-UDP publishers
        # Egress SR bookkeeping: per downtrack SSRC [pkts, octets, last_ts];
        # LSR echo table for RR → RTT (RFC 3550 A.8).
        self._tx_sr: dict[int, list] = {}
        self._sr_sent: dict[int, list] = {}              # ssrc → recent SR mid32s
        self._last_sr_ms = 0.0
        # Publisher-side SR state: upstream ssrc → (ntp64, rtp_ts) — the
        # cross-layer timestamp anchor (forwarder.go processSourceSwitch).
        # _ts_delta[(room, track, layer)] = layer's RTP-TS offset relative
        # to layer 0 at a common wallclock instant; ingest subtracts it so
        # every simulcast layer rides ONE timeline and the device munger
        # needs no TS re-anchor at a source switch.
        self.pub_sr: dict[int, tuple[int, int]] = {}
        self._ts_delta: dict[tuple, int] = {}
        self._last_pli_ms: dict[tuple, float] = {}       # (room,track) → throttle
        # Vectorized egress mirrors (the batch path reads arrays, not
        # dicts): per-(room, sub, track) downtrack SSRC, per-(room, track)
        # payload type, and SR accumulators folded at SR cadence.
        dims = ingest.dims
        R, T, S = dims.rooms, dims.tracks, dims.subs
        self._egress_ssrc_arr = np.zeros((R, S, T), np.uint32)
        self._track_pt = np.full((R, T), OPUS_PT, np.uint8)
        self._track_is_video = np.zeros((R, T), bool)
        self._track_svc = np.zeros((R, T), bool)
        # Persistent per-(room, sub) destination/session arrays: the batch
        # egress reads these with pure numpy gathers (no per-tick Python
        # loop over subscribers — the loop would scale with subscriber
        # count at north-star shapes). Resynced from the dicts only when
        # subscription state changes (`_subs_rev` bump or dict-length
        # drift from out-of-band writers like tests/bench).
        self._sub_ip = np.zeros((R, S), np.uint32)
        self._sub_port = np.zeros((R, S), np.uint16)
        self._sub_tcp = np.zeros((R, S), bool)
        self._sub_red_arr = np.zeros((R, S), bool)
        self._sub_sess_idx = np.full((R, S), -1, np.int32)
        self._sessions: list = []
        self._sess_keys = np.zeros((0, 16), np.uint8)
        self._sess_keyids = np.zeros(0, np.uint32)
        self._sess_active = np.zeros(0, np.uint8)
        self._sess_ctr = np.zeros(0, np.uint64)
        self._subs_rev = 0
        self._subs_synced = (-1, -1, -1)  # (rev, len(sub_addrs), len(sub_sessions))
        self._txsr_pkts = np.zeros((R, S, T), np.int64)
        self._txsr_oct = np.zeros((R, S, T), np.int64)
        self._txsr_ts = np.zeros((R, S, T), np.uint32)
        self._txsr_ms = np.zeros((R, S, T), np.float64)
        # TWCC send-time rings (pkg/rtc/transport.go:253-374 seat): the
        # sealed-frame counter IS the transport-wide sequence number; the
        # client acks (counter, recv_time) pairs and the host matches them
        # here to produce the delay/rate samples ops/bwe's send-side
        # estimator consumes. Sealed-path flows only — cleartext frames
        # carry no counter (those subs keep the estimate-driven budget).
        self._twcc_ms = np.zeros((R, S, TWCC_RING), np.float64)
        self._twcc_ctr = np.full((R, S, TWCC_RING), -1, np.int64)
        self._twcc_len = np.zeros((R, S, TWCC_RING), np.int32)
        # Cumulative per-(room, sub) send counters (never reset — the SR
        # accumulators fold away at SR cadence): window deltas over these
        # are the per-participant egress rates
        # (participant_traffic_load.go seat).
        self.tx_pkts = np.zeros((R, S), np.int64)
        self.tx_bytes = np.zeros((R, S), np.int64)
        # Last acked (ctr, send, recv) per sub: delay deltas must span
        # feedback-frame boundaries or one-ack-per-frame cadences would
        # never produce a delay-variation sample at all.
        self._twcc_last_ctr = np.full((R, S), -1, np.int64)
        self._twcc_last_send = np.zeros((R, S), np.float64)
        self._twcc_last_recv = np.zeros((R, S), np.float64)
        self.egress_threads = 4
        # Sharded egress orchestrator (runtime/egress_plane.py). Attached
        # by the room manager after PlaneRuntime construction; when set,
        # send_egress_batch routes through the native sharded fan-out
        # (egress_plane_send) instead of the flat n_threads pool.
        self._egress_plane = None
        # Always-on packet-in→wire-out latency histogram (stamps: rx_batch
        # return → native egress send return; includes tick-queue wait).
        self.fwd_latency = ForwardLatencyProbe()
        # Express-lane twin: arrival-driven sends skip the tick queue, so
        # their latency distribution answers a different question (decide+
        # munge+seal cost) — kept separate or the batched tail would bury
        # the express p99 (and vice versa).
        self.fwd_latency_express = ForwardLatencyProbe()
        # Sampled wire-latency stage decomposer (runtime/trace.py
        # LatencyAttribution); attached by the server/bench alongside the
        # egress plane. None = no per-stage attribution.
        self.wire_stages = None
        # Express lane (runtime/express.py): attached by the room manager
        # when plane.express_max_subs > 0; rx_batch hands each receive
        # batch to it right after staging.
        self._express = None
        # config rtc.congestion_control.send_side_bwe — set ONCE at
        # startup (before any subscriber registers): flipping it later
        # does not refresh already-registered subscribers' fb_enabled
        # entries (the gate is evaluated on bind/register/punch events).
        self.send_side_bwe = True
        # RED (RFC 2198) opt-in per subscriber + per-(room, audio track)
        # ring of recent primary payloads (the byte half of the device's
        # encode plan; redreceiver.go).
        self.sub_red: set[tuple] = set()
        self._red_ring: dict[tuple, object] = {}
        # Playout-delay header extension on video egress
        # (rtpextension/playoutdelay.go): (min_ms, max_ms) or None.
        self.playout_delay: tuple[int, int] | None = None
        # Pacer window (pkg/sfu/pacer "no-queue"): spread a tick's
        # sendmmsg chunks across this many ms; 0 = burst. Paced sends
        # run on a dedicated worker thread (they sleep).
        self.pacer_spread_ms: float = 0.0
        # Leaky-bucket pacing (pkg/sfu/pacer leaky_bucket.go:47-200 seat):
        # per-(room, sub) byte budgets computed by the device pacer op;
        # over-budget UDP entries defer FIFO to later ticks (bounded).
        self.pacer_mode: str = ""
        self._pacer_queue: list = []
        self._pace_pool = None
        self._pace_pending = None
        # Media-loss proxy (medialossproxy.go): max subscriber-reported
        # fraction_lost per audio track, relayed upstream ~1/s so the
        # publisher's Opus encoder can enable FEC.
        self._down_frac_lost: dict[tuple, int] = {}  # (room, track) → byte
        # SVC (VP9/AV1) dependency-descriptor state: per-track structure
        # cache (structures ride keyframes only; runtime/dd.py parses) —
        # packets between keyframes resolve layers via the cached table.
        self._svc_tracks: set[tuple] = set()
        # (room, track) → [(version, Structure), ...] (last 2 kept):
        # staged packets are stamped with the version they were parsed
        # under, so egress patching one tick later never mixes an old
        # packet with a newer structure's field widths.
        self._dd_structs: dict[tuple, list] = {}
        self.stats = {
            "rx": 0, "tx": 0, "unknown_ssrc": 0, "parse_errors": 0,
            "addr_mismatch": 0, "bad_punch": 0,
            "rtcp_rx": 0, "rtcp_bad": 0, "nacks_rx": 0, "nacks_tx": 0,
            "plis_rx": 0, "plis_tx": 0, "rtx_tx": 0,
            "bad_frame": 0, "plaintext_drop": 0, "session_mismatch": 0,
        }

    # -- control-plane API ------------------------------------------------
    def _new_ssrc(self) -> int:
        """Random 32-bit SSRC (unguessable — a sequential counter would let
        an off-path sender inject media into live tracks)."""
        while True:
            ssrc = secrets.randbits(32) | 0x10000
            if ssrc not in self.bindings:
                return ssrc

    def assign_ssrc(
        self, room: int, track: int, is_video: bool, layer: int = 0,
        session: MediaCryptoSession | None = None, svc: bool = False,
        mime: str = "",
    ) -> int:
        """Bind a fresh SSRC to one (track, simulcast layer); sent back in
        signal. Simulcast publishers get one SSRC per layer, matching the
        reference's per-layer SSRCs (mediatrack.go layer SSRC bookkeeping).
        `session` pins the SSRC to its publisher's crypto session: media
        sealed under any other key is rejected even if the SSRC matches.
        `mime` picks the payload type (and thereby the ingest parser's
        codec branch): h264 → NALU keyframe scan, vp9/av1 → SVC PT (DD
        when present, VP9 picture headers otherwise), else VP8."""
        ssrc = self._new_ssrc()
        self.bindings[ssrc] = SSRCBinding(room, track, is_video, layer, session, svc)
        self._set_track_media(room, track, is_video, svc, mime)
        return ssrc

    def enable_gateway(self):
        """Create (or return) the standards-lane WebRTC gateway: ICE-lite
        STUN on this socket, DTLS-SRTP termination, SDP negotiation
        (runtime/webrtc_gateway.py; the reference's Pion seat,
        pkg/rtc/transport.go:253-374)."""
        if self.gateway is None:
            from livekit_server_tpu.runtime.webrtc_gateway import WebRtcGateway

            self.gateway = WebRtcGateway(self)
        return self.gateway

    def enable_audio_mixer(self):
        """Create (or return) the MCU-seat audio mixer (runtime/mixer.py;
        BASELINE config 2's batched active-speaker mix)."""
        if self.audio_mixer is None:
            from livekit_server_tpu.runtime.mixer import AudioMixer

            self.audio_mixer = AudioMixer(self)
        return self.audio_mixer

    def bind_client_ssrc(
        self, ssrc: int, room: int, track: int, is_video: bool,
        layer: int = 0, session: MediaCryptoSession | None = None,
        svc: bool = False, mime: str = "",
    ) -> bool:
        """Bind a CLIENT-chosen SSRC (from a gateway peer's SDP offer) to a
        plane track — assign_ssrc's twin for the standards lane, where the
        publisher picks its own SSRCs. Collisions with existing bindings
        are rejected (first owner wins, matching the latching rule for
        addresses); returns whether the bind took, so the caller never
        claims — or later releases — another publisher's SSRC."""
        if ssrc in self.bindings:
            return False
        self.bindings[ssrc] = SSRCBinding(room, track, is_video, layer, session, svc)
        self._set_track_media(room, track, is_video, svc, mime)
        return True

    def _set_track_media(
        self, room: int, track: int, is_video: bool, svc: bool, mime: str
    ) -> None:
        """Track-level media metadata shared by assign_ssrc and
        bind_client_ssrc: kind, SVC flag, and the egress payload type."""
        self.track_kind[(room, track)] = is_video
        if svc:
            self._svc_tracks.add((room, track))
            self._track_svc[room, track] = True
        m = (mime or "").lower()
        if not is_video:
            pt = OPUS_PT
        elif "av1" in m:
            pt = AV1_PT
        elif svc or "vp9" in m:
            pt = SVC_PT
        elif "h264" in m:
            pt = H264_PT
        else:
            pt = VP8_PT
        self._track_pt[room, track] = pt
        self._track_is_video[room, track] = is_video

    def bind_sub_session(
        self, room: int, sub: int, session: MediaCryptoSession
    ) -> None:
        """Attach a subscriber's crypto session: egress to (room, sub) is
        sealed under it, and its key routes TCP-fallback frames."""
        self.sub_sessions[(room, sub)] = session
        session.room = room
        session.sub = sub
        self._touch_subs()
        self._refresh_fb_enabled(room, sub)

    def _refresh_fb_enabled(self, room: int, sub: int) -> None:
        """TWCC applies to subs whose egress is actually sealed over UDP
        (counters on the wire): session bound + UDP address + sealing
        active (require_encryption, or the client spoke sealed first).
        `send_side_bwe` is the operator off-switch (config
        rtc.congestion_control.send_side_bwe)."""
        addr = self.sub_addrs.get((room, sub))
        sess = self.sub_sessions.get((room, sub))
        self.ingest.fb_enabled[room, sub] = (
            self.send_side_bwe
            and addr is not None
            and not (
                isinstance(addr, tuple) and addr
                and addr[0] in ("tcp", "srtp")
            )
            and sess is not None
            and (self.require_encryption or sess.client_active)
        )

    def _sendto(self, data: bytes, addr, session=None) -> None:
        """Single egress chokepoint: seal under the session, then route to
        the UDP socket or a TCP-fallback sink. TCP sinks are addressed as
        ("tcp", key_id) in the same addr maps the UDP path uses, so every
        consumer of sub_addrs/addrs works unchanged.

        Sealing is opportunistic in cleartext-allowed mode: a client that
        has ever spoken sealed frames (session.client_active) gets sealed
        egress; a legacy cleartext client gets cleartext. In
        require_encryption mode everything is sealed. TCP is ALWAYS
        sealed — its framing carries nothing else. Gateway peers
        (standards lane) always get SRTP/SRTCP."""
        if isinstance(addr, tuple) and addr and addr[0] == "srtp":
            if self.gateway is not None:
                self.gateway.protect_and_send(data, addr[1])
            return
        if self.gateway is not None and isinstance(addr, tuple):
            # Server-originated RTCP toward a gateway publisher's latched
            # address (PLI/NACK/RR) must ride SRTCP, never cleartext.
            if self.gateway.send_to_peer_addr(data, addr):
                return
        if isinstance(addr, tuple) and addr and addr[0] == "tcp":
            if session is None:
                return
            sink = self.tcp_sinks.get(addr[1])
            if sink is not None:
                sink(session.seal(data))
            return
        if session is not None and (self.require_encryption or session.client_active):
            data = session.seal(data)
        if self.transport is not None:
            self.transport.sendto(data, addr)

    def release_ssrc(self, ssrc: int) -> None:
        self.bindings.pop(ssrc, None)
        self.addrs.pop(ssrc, None)
        self._addr_code.pop(ssrc, None)
        self._rx_hi.pop(ssrc, None)
        self._rx_missing.pop(ssrc, None)
        self.pub_sr.pop(ssrc, None)

    def release_track(self, room: int, track: int) -> None:
        """Track unpublished: drop its kind entry + every layer SSRC."""
        self.track_kind.pop((room, track), None)
        self._last_pli_ms.pop((room, track), None)
        for key in [k for k in self._ts_delta if k[:2] == (room, track)]:
            del self._ts_delta[key]
        for ssrc in [
            s for s, b in self.bindings.items() if b.room == room and b.track == track
        ]:
            self.release_ssrc(ssrc)
        # SVC/RED state must not leak to the column's next tenant (a new
        # publisher would inherit the wrong DD template table).
        self._svc_tracks.discard((room, track))
        self._dd_structs.pop((room, track), None)
        self._red_ring.pop((room, track), None)
        self._track_pt[room, track] = OPUS_PT
        self._track_is_video[room, track] = False
        self._track_svc[room, track] = False
        if self.audio_mixer is not None:
            self.audio_mixer.release_track(room, track)

    def set_track_kind(self, room: int, track: int, is_video: bool) -> None:
        """Record media kind for egress PT selection (any transport)."""
        self.track_kind[(room, track)] = is_video

    def set_sub_red(self, room: int, sub: int, enabled: bool) -> None:
        """Subscriber negotiated RED audio (subscription signal field):
        audio egress to it is RFC 2198-encapsulated with the device plan's
        redundancy blocks (redreceiver.go; toggled per capability)."""
        if enabled:
            self.sub_red.add((room, sub))
        else:
            self.sub_red.discard((room, sub))
        self._touch_subs()

    def register_subscriber(self, room: int, sub: int, addr: tuple) -> None:
        """Trusted-caller egress registration (tests / in-process tooling).
        The signal plane must NOT call this with a client-supplied address —
        it hands out a punch id instead (assign_subscriber_punch)."""
        self.sub_addrs[(room, sub)] = addr
        self._touch_subs()
        self._refresh_fb_enabled(room, sub)

    def assign_subscriber_punch(self, room: int, sub: int, rotate: bool = False) -> int:
        """Mint an unguessable punch id for a subscriber. The client proves
        address ownership by sending PUNCH_REQ+id from its media socket;
        only then does egress flow to that source address.

        One outstanding id per (room, sub): repeated subscription signals
        reuse it (no unbounded growth, no widening of the guessable-id
        set; a same-address retry of a latched id just re-acks). Once
        latched, the id binds to its first source address — a replayed
        PUNCH_REQ from anywhere else is rejected, so an observer of the
        cleartext handshake cannot re-aim the stream. `rotate=True`
        (client sent udp_repunch) invalidates the old id and mints a
        fresh one: the recovery path for a NAT rebind — only the
        authenticated signal session can trigger it, never the old id."""
        key = (room, sub)
        existing = self._punch_by_sub.get(key)
        if existing is not None:
            if not rotate:
                return existing
            del self.punch_ids[existing]
        while True:
            pid = secrets.randbits(32)
            if pid and pid not in self.punch_ids:
                break
        self.punch_ids[pid] = [key, None]
        self._punch_by_sub[key] = pid
        return pid

    def release_subscriber(self, room: int, sub: int) -> None:
        """Subscriber left: stop egress and free its SSRC map (prevents
        media leaking to a stale address once the sub col is reused)."""
        self.sub_addrs.pop((room, sub), None)
        sess = self.sub_sessions.pop((room, sub), None)
        if sess is not None:
            self.tcp_sinks.pop(sess.key_id, None)
        for ssrc in (self.sub_ssrc.pop((room, sub), None) or {}).values():
            self.egress_rev.pop(ssrc, None)
            self._tx_sr.pop(ssrc, None)
            self._sr_sent.pop(ssrc, None)
        self._egress_ssrc_arr[room, sub, :] = 0
        self._txsr_pkts[room, sub, :] = 0
        self._txsr_oct[room, sub, :] = 0
        self.sub_red.discard((room, sub))
        self._touch_subs()
        self.ingest.fb_enabled[room, sub] = False
        self.ingest.sub_reset[room, sub] = True  # device per-sub state reset
        self._twcc_ctr[room, sub, :] = -1
        self._twcc_last_ctr[room, sub] = -1
        pid = self._punch_by_sub.pop((room, sub), None)
        if pid is not None:
            self.punch_ids.pop(pid, None)
        if self.audio_mixer is not None:
            self.audio_mixer.enable_sub(room, sub, False)

    def release_room(self, room: int) -> None:
        """Room closed: drop every binding on its row."""
        for ssrc in [s for s, b in self.bindings.items() if b.room == room]:
            self.release_ssrc(ssrc)
        for key in [k for k in self.sub_addrs if k[0] == room]:
            del self.sub_addrs[key]
        for key in [k for k in self.sub_ssrc if k[0] == room]:
            for ssrc in self.sub_ssrc[key].values():
                self.egress_rev.pop(ssrc, None)
                self._tx_sr.pop(ssrc, None)
                self._sr_sent.pop(ssrc, None)
            del self.sub_ssrc[key]
        for key in [k for k in self.track_kind if k[0] == room]:
            del self.track_kind[key]
        for key in [k for k in self._last_pli_ms if k[0] == room]:
            del self._last_pli_ms[key]
        self._egress_ssrc_arr[room] = 0
        self._track_pt[room] = OPUS_PT
        self._track_is_video[room] = False
        self.tx_pkts[room] = 0
        self.tx_bytes[room] = 0
        self.ingest.rx_pkts[room] = 0
        self.ingest.rx_bytes[room] = 0
        self._txsr_pkts[room] = 0
        self._txsr_oct[room] = 0
        self.sub_red = {k for k in self.sub_red if k[0] != room}
        for key in [k for k in self._red_ring if k[0] == room]:
            del self._red_ring[key]
        self._svc_tracks = {k for k in self._svc_tracks if k[0] != room}
        self._touch_subs()
        self._track_svc[room] = False
        for key in [k for k in self._dd_structs if k[0] == room]:
            del self._dd_structs[key]
        for key in [k for k in self._ts_delta if k[0] == room]:
            del self._ts_delta[key]
        for key in [k for k in self.sub_sessions if k[0] == room]:
            sess = self.sub_sessions.pop(key)
            self.tcp_sinks.pop(sess.key_id, None)
        for key in [k for k in self._punch_by_sub if k[0] == room]:
            self.punch_ids.pop(self._punch_by_sub.pop(key), None)
        if self.audio_mixer is not None:
            self.audio_mixer.release_room(room)

    def subscriber_ssrc(self, room: int, sub: int, track: int) -> int:
        """Per-(subscriber, track) egress SSRC (DownTrack's own SSRC)."""
        m = self.sub_ssrc.setdefault((room, sub), {})
        if track not in m:
            m[track] = self._new_ssrc()
            self.egress_rev[m[track]] = (room, sub, track)
            self._egress_ssrc_arr[room, sub, track] = m[track]
        return m[track]

    # -- datagram path ----------------------------------------------------
    def connection_made(self, transport) -> None:
        self.transport = transport

    def _mark_client_active(self, session) -> None:
        """First frame that opens under a session latches sealed egress;
        the array mirror must track the exact session at that slot."""
        if session.client_active:
            return
        session.client_active = True
        j = getattr(session, "_arr_idx", None)
        if (
            j is not None
            and j < len(self._sessions)
            and self._sessions[j] is session
        ):
            self._sess_active[j] = 1
        # Sealing just latched for this client: if it's a subscriber, its
        # egress now carries counters — TWCC becomes applicable.
        room, sub = getattr(session, "room", -1), getattr(session, "sub", -1)
        if room >= 0 and sub >= 0:
            self._refresh_fb_enabled(room, sub)

    def _prune_addr_caches(self) -> None:
        """Bound the addr↔code mirrors under a spoofed-source flood while
        keeping every entry a latched SSRC still points at — evicting a
        live latch would permanently sever a non-IPv4 client, whose
        synthetic code cannot be re-derived from its tuple."""
        live = set(self._addr_code.values())
        self._code_tuple = {
            c: t for c, t in self._code_tuple.items() if c in live
        }
        self._tuple_code = {
            t: c for t, c in self._tuple_code.items() if c in live
        }

    def _addr_code_of(self, addr) -> int:
        """Integer identity for an address tuple (see __init__)."""
        c = self._tuple_code.get(addr)
        if c is None:
            import socket as _socket

            try:
                ip = int.from_bytes(_socket.inet_aton(addr[0]), "big")
                c = (ip << 16) | (int(addr[1]) & 0xFFFF)
            except (OSError, IndexError, TypeError, ValueError):
                c = self._syn_code   # non-IPv4: synthetic negative code
                self._syn_code -= 1
            if len(self._tuple_code) >= 8192 or len(self._code_tuple) >= 8192:
                self._prune_addr_caches()
            self._tuple_code[addr] = c
            self._code_tuple[c] = addr
        return c

    def _tuple_of_code(self, code: int) -> tuple:
        t = self._code_tuple.get(code)
        if t is None:
            import socket as _socket

            if code < 0:
                return ("0.0.0.0", 0)  # unknown synthetic code (never live)
            t = (
                _socket.inet_ntoa(int(code >> 16).to_bytes(4, "big")),
                int(code) & 0xFFFF,
            )
            if len(self._tuple_code) >= 8192 or len(self._code_tuple) >= 8192:
                self._prune_addr_caches()
            self._code_tuple[code] = t
            self._tuple_code[t] = code
        return t

    def feed_batch(self, blob, offs, lens, ips, ports, n,
                   t_rx: float = 0.0) -> None:
        """Batch ingress from the native recvmmsg reader: sealed frames are
        opened with ONE native AES-GCM batch call (replay windows and the
        client-active latch stay host-side), datagrams are classified
        vectorized (punch / RTCP / RTP media), and all media goes through
        ONE array demux+stage pass (_process_media_arrays) — no per-packet
        Python objects on the media path."""
        self.stats["rx"] += int(n)
        offs = offs[:n]
        lens = lens[:n]
        ips = ips[:n]
        ports = ports[:n]
        valid = lens > 0
        b0 = np.where(valid, blob[np.minimum(offs, len(blob) - 1)], 0xFF)
        sealed = (
            (b0 == CRYPTO_MAGIC) & valid
            if self.crypto is not None else np.zeros(n, bool)
        )
        addr_code = (ips.astype(np.int64) << 16) | ports.astype(np.int64)
        now_ms = asyncio.get_event_loop().time() * 1000.0
        if t_rx == 0.0:
            t_rx = time.perf_counter()

        if sealed.any():
            si = np.nonzero(sealed)[0]
            o = offs[si].astype(np.int64)
            kid = (
                (blob[o + 1].astype(np.uint32) << 24)
                | (blob[o + 2].astype(np.uint32) << 16)
                | (blob[o + 3].astype(np.uint32) << 8)
                | blob[o + 4]
            )
            sessions = {int(k): self.crypto.get(int(k)) for k in np.unique(kid)}
            keyrows: list[bytes] = []
            kmap: dict[int, int] = {}
            for k, sess in sessions.items():
                if sess is not None:
                    kmap[k] = len(keyrows)
                    keyrows.append(sess.key)
            kidx = np.array([kmap.get(int(k), -1) for k in kid], np.int32)
            keys = (
                np.frombuffer(b"".join(keyrows), np.uint8).reshape(-1, 16)
                if keyrows else np.zeros((1, 16), np.uint8)
            )
            out, ooff, olen = native_egress.open_batch(
                blob, offs[si], lens[si], kidx, keys, DIR_C2S
            )
            ctr = np.zeros(len(si), np.uint64)
            for b in range(8):
                ctr = (ctr << np.uint64(8)) | blob[o + 6 + b].astype(np.uint64)
            # Replay windows are inherently sequential per session; the
            # loop is per *sealed* packet but does dict/bitmask work only.
            good = np.zeros(len(si), bool)
            scodes = np.zeros(len(si), np.int64)
            for j in range(len(si)):
                if olen[j] < 0:
                    self.stats["bad_frame"] += 1
                    continue
                sess = sessions[int(kid[j])]
                if not sess.replay.check(int(ctr[j])):
                    self.stats["bad_frame"] += 1
                    continue
                self._mark_client_active(sess)
                good[j] = True
                scodes[j] = int(kid[j]) + 1
            gi = np.nonzero(good)[0]
            if len(gi):
                self._classify_and_process(
                    out, ooff[gi].astype(np.int32), olen[gi],
                    addr_code[si[gi]], scodes[gi], sessions, kid[gi], now_ms,
                    t_rx,
                )

        clear = valid & ~sealed
        nclear = int(clear.sum())
        if nclear:
            if self.require_encryption and self.gateway is None:
                # Secure mode: the cleartext media wire does not exist —
                # but punch probes ride sealed frames only, so anything
                # cleartext here is droppable wholesale.
                self.stats["plaintext_drop"] += nclear
            else:
                # With a gateway, "cleartext" includes STUN/DTLS/SRTP
                # (their own crypto); _classify_and_process drops the
                # rest when gateway_only is set — mirroring the
                # per-datagram path, which demuxes gateway traffic
                # BEFORE the require_encryption drop.
                ci = np.nonzero(clear)[0]
                self._classify_and_process(
                    blob, offs[ci], lens[ci], addr_code[ci],
                    np.zeros(len(ci), np.int64), None, None, now_ms, t_rx,
                    gateway_only=self.require_encryption,
                )

    def _classify_and_process(self, blob, offs, lens, addr_code, sess_code,
                              sessions, kid, now_ms, t_rx: float = 0.0,
                              gateway_only: bool = False) -> None:
        """Split one (possibly decrypted) datagram batch into punch / RTCP
        (cold, per-packet) and RTP media (hot, one vectorized pass).
        `gateway_only` (require_encryption + gateway): gateway traffic is
        processed, every other cleartext datagram is dropped."""
        b0 = blob[np.minimum(offs.astype(np.int64), len(blob) - 1)]
        b1 = blob[np.minimum(offs.astype(np.int64) + 1, len(blob) - 1)]
        maybe_punch = (b0 == PUNCH_REQ[0]) & (lens >= 12)
        is_rtcp = ~maybe_punch & (b1 >= 192) & (b1 <= 223) & (lens >= 8)
        media = ~maybe_punch & ~is_rtcp
        if self.gateway is not None and sessions is None:
            # Standards-lane demux on the cleartext batch (RFC 7983):
            # STUN/DTLS control per-packet (low rate); SRTP *and* SRTCP
            # from latched gateway addresses go through the unprotect
            # lane — SRTCP's cleartext first 8 bytes would otherwise
            # satisfy the plain-RTCP byte1 test and feed the RTCP handler
            # ciphertext.
            gw_ctl = ((b0 < 4) & (b0 != CRYPTO_MAGIC)) | ((b0 >= 20) & (b0 <= 63))
            for i in np.nonzero(gw_ctl)[0]:
                oo = int(offs[i])
                self.gateway.handle_datagram(
                    bytes(blob[oo : oo + int(lens[i])]),
                    self._tuple_of_code(int(addr_code[i])),
                )
            gw_media = np.zeros(len(offs), bool)
            if self.gateway.peers_by_addr:
                owned = np.isin(
                    addr_code,
                    np.fromiter(self.gateway.peers_by_addr, np.int64,
                                len(self.gateway.peers_by_addr)),
                )
                gw_media = ~gw_ctl & ~maybe_punch & owned & (b0 >= 128)
                if gw_media.any():
                    pkts = [
                        (bytes(blob[int(offs[i]) : int(offs[i]) + int(lens[i])]),
                         int(addr_code[i]))
                        for i in np.nonzero(gw_media)[0]
                    ]
                    self._gateway_media(pkts, t_rx)
            media = media & ~gw_ctl & ~gw_media
            is_rtcp = is_rtcp & ~gw_media
        if gateway_only:
            leftover = int(media.sum()) + int(is_rtcp.sum()) + int(
                maybe_punch.sum()
            )
            if leftover:
                self.stats["plaintext_drop"] += leftover
            return
        for i in np.nonzero(maybe_punch)[0]:
            oo = int(offs[i])
            d = bytes(blob[oo : oo + int(lens[i])])
            sess = sessions.get(int(kid[i])) if sessions is not None else None
            if d[:8] == PUNCH_REQ:
                self._handle_punch(d, self._tuple_of_code(int(addr_code[i])), sess)
            # else: first byte 'L' is not a valid RTP version — drop like
            # the parser would.
        for i in np.nonzero(is_rtcp)[0]:
            oo = int(offs[i])
            self._handle_rtcp(
                bytes(blob[oo : oo + int(lens[i])]),
                self._tuple_of_code(int(addr_code[i])),
            )
        mi = np.nonzero(media)[0]
        if len(mi):
            self._process_media_arrays(
                blob, offs[mi], lens[mi], addr_code[mi], sess_code[mi], now_ms,
                t_rx,
            )

    def datagram_received(self, data: bytes, addr) -> None:
        self.stats["rx"] += 1
        if not data:
            return
        if self.gateway is not None:
            b0 = data[0]
            # RFC 7983 demux: STUN (0-3, requests are 0x00 so the sealed
            # magic 0x01 never collides), DTLS (20-63). SRTP media shares
            # the RTP first-byte range and demuxes by latched address.
            if (b0 < 4 and b0 != CRYPTO_MAGIC) or 20 <= b0 <= 63:
                if self.gateway.handle_datagram(data, addr):
                    return
            elif b0 >= 128 and self.gateway.owns_addr(self._addr_code_of(addr)):
                self._gateway_media([(data, self._addr_code_of(addr))],
                                    time.perf_counter())
                return
        # Sealed frames lead with the crypto magic (0x01 — impossible as an
        # RTP/RTCP version byte or the punch magic 'L').
        if data[0] == CRYPTO_MAGIC and self.crypto is not None:
            key_id = parse_key_id(data)
            session = self.crypto.get(key_id) if key_id is not None else None
            inner = session.open(data) if session is not None else None
            if inner is None:
                self.stats["bad_frame"] += 1
                return
            self._mark_client_active(session)
            self._dispatch_inner(inner, addr, session)
            return
        if self.require_encryption:
            # Secure mode: the cleartext media wire does not exist.
            self.stats["plaintext_drop"] += 1
            return
        self._dispatch_inner(data, addr, None)

    def _dispatch_inner(self, data: bytes, addr, session) -> None:
        """Route one (decrypted) datagram: punch / RTCP / RTP. Shared by
        the UDP socket and the TCP-fallback framing."""
        if data[:8] == PUNCH_REQ:
            self._handle_punch(data, addr, session)
            return
        # rtcp-mux demux (RFC 5761): RTCP PTs land in byte1 192-223 — a
        # range RTP reserves — so one byte splits the flows.
        if len(data) >= 8 and 192 <= data[1] <= 223:
            self._handle_rtcp(data, addr)
            return
        # Coalesce: datagrams arriving in the same event-loop iteration are
        # parsed by ONE native parse_batch call (the batch design this
        # module documents; under media load the loop wakes with many
        # datagrams ready and the per-packet Python overhead amortizes).
        self._rx_pending.append((data, addr, session))
        if not self._rx_scheduled:
            self._rx_scheduled = True
            asyncio.get_running_loop().call_soon(self._flush_rx)

    def _handle_twcc(self, room: int, sub: int, fci: bytes) -> None:
        """Match one transport-wide feedback frame against the send-time
        ring and accumulate this tick's delay/rate reductions (the host
        half of the ops/bwe send-side estimator). All array math; acked
        slots are invalidated so replayed/duplicate feedback is inert."""
        if len(fci) < 20:
            return
        base_ctr = int.from_bytes(fci[0:8], "big")
        base_us = int.from_bytes(fci[8:16], "big")
        n = int.from_bytes(fci[16:18], "big")
        body = fci[20 : 20 + 6 * n]
        if n == 0 or len(body) < 6 * n:
            return
        ent = np.frombuffer(body, _TWCC_ENTRY)
        ctrs = base_ctr + ent["off"].astype(np.int64)
        recv_us = base_us + ent["delta"].astype(np.int64)
        # Dedup within the frame: repeated entries would otherwise all
        # match before the slot is invalidated, inflating acked_bytes and
        # diluting the delay mean — exactly the client manipulation this
        # measurement path exists to resist.
        ctrs, first = np.unique(ctrs, return_index=True)
        recv_us = recv_us[first]
        slots = (ctrs & (TWCC_RING - 1)).astype(np.int64)
        ok = self._twcc_ctr[room, sub, slots] == ctrs
        self.stats["twcc_rx"] = self.stats.get("twcc_rx", 0) + int(n)
        if not ok.any():
            return
        ctrs, recv_us, slots = ctrs[ok], recv_us[ok], slots[ok]
        order = np.argsort(ctrs)
        ctrs, recv_us, slots = ctrs[order], recv_us[order], slots[order]
        send_ms = self._twcc_ms[room, sub, slots]
        acked_bytes = int(self._twcc_len[room, sub, slots].sum())
        self._twcc_ctr[room, sub, slots] = -1  # spend the acks
        recv_ms = recv_us.astype(np.float64) / 1000.0
        # Chain in the previous frame's last ack: deltas must span frame
        # boundaries, or a one-ack-per-frame cadence never yields a
        # delay-variation sample.
        last_c = int(self._twcc_last_ctr[room, sub])
        if 0 <= last_c < int(ctrs[0]):
            send_ms = np.r_[self._twcc_last_send[room, sub], send_ms]
            recv_ms = np.r_[self._twcc_last_recv[room, sub], recv_ms]
        self._twcc_last_ctr[room, sub] = int(ctrs[-1])
        self._twcc_last_send[room, sub] = send_ms[-1]
        self._twcc_last_recv[room, sub] = recv_ms[-1]
        # Delay-variation samples: how much more the recv gap grew than the
        # send gap (positive ⇒ queue building).
        if len(recv_ms) >= 2:
            dd = np.diff(recv_ms) - np.diff(send_ms)
            delay_sum, n_d = float(dd.sum()), len(dd)
            # Measured span, floored only against degenerate timestamps;
            # flooring to a full tick here would under-report the receive
            # rate of clients that ack in several sub-tick frames.
            span = max(float(recv_ms[-1] - recv_ms[0]), 0.1)
        else:
            # Single-ack frame: no span — bill one tick's worth.
            delay_sum, n_d = 0.0, 1
            span = float(self.ingest.tick_ms)
        self.ingest.push_twcc_feedback(
            room, sub, delay_sum, n_d, acked_bytes, span
        )

    def _handle_rtcp(self, data: bytes, addr) -> None:
        """Compound RTCP walk: NACK → sequencer lookup, PLI → keyframe
        request, REMB → BWE estimate sample, RR → loss/RTT bookkeeping
        (the RTCP half of buffer.Buffer — buffer.go:673 onwards)."""
        self.stats["rtcp_rx"] += 1
        off = 0
        while off + 8 <= len(data):
            fmt = data[off] & 0x1F
            pt = data[off + 1]
            length = (int.from_bytes(data[off + 2 : off + 4], "big") + 1) * 4
            chunk = data[off : off + length]
            off += length
            if len(chunk) < 12:
                # Valid 8-byte chunks exist (empty RR, BYE) — skip, keep
                # walking the compound; only truncation is malformed.
                if len(chunk) < 8:
                    self.stats["rtcp_bad"] += 1
                    return
                continue
            media_ssrc = int.from_bytes(chunk[8:12], "big")
            if pt == RTCP_RTPFB and fmt == 1:
                dest = self.egress_rev.get(media_ssrc)
                if dest is None:
                    continue
                room, sub, track = dest
                # Anti-spoof: feedback must come from the sub's own address.
                if self.sub_addrs.get((room, sub)) != addr:
                    self.stats["addr_mismatch"] += 1
                    continue
                sns = parse_nack_fci(chunk[12:])
                self.stats["nacks_rx"] += len(sns)
                # BWE loss channel (count) + immediate host-side replay
                # (sequencer.go:263 — answered at RTCP time, not on the
                # next tick; the reference replies immediately too).
                self.ingest.push_nack(room, sub, track, sns)
                if self.nack_resolver is not None:
                    replays = self.nack_resolver(room, sub, track, sns)
                    if replays:
                        self.send_egress(replays, rtx=True)
            elif pt == RTCP_RTPFB and fmt == TWCC_FMT:
                dest = self.egress_rev.get(media_ssrc)
                if dest is None:
                    continue
                room, sub, _track = dest
                if self.sub_addrs.get((room, sub)) != addr:
                    self.stats["addr_mismatch"] += 1
                    continue
                self._handle_twcc(room, sub, chunk[12:])
            elif pt == RTCP_PSFB and fmt == 1:
                dest = self.egress_rev.get(media_ssrc)
                if dest is None:
                    continue
                room, sub, track = dest
                if self.sub_addrs.get((room, sub)) != addr:
                    self.stats["addr_mismatch"] += 1
                    continue
                self.stats["plis_rx"] += 1
                self.send_pli(room, track)
            elif pt == RTCP_PSFB and fmt == 15:
                bitrate, ssrcs = parse_remb(chunk[12:])
                if bitrate <= 0:
                    continue
                for s in ssrcs:
                    dest = self.egress_rev.get(s)
                    if dest is None:
                        continue
                    room, sub, _track = dest
                    if self.sub_addrs.get((room, sub)) != addr:
                        self.stats["addr_mismatch"] += 1
                        break
                    self.ingest.push_feedback(room, sub, estimate=bitrate)
                    break  # one estimate per REMB: the channel is per-sub
            elif pt == RTCP_SR:
                # Publisher sender report: the (NTP, RTP-TS) anchor for
                # cross-layer timestamp alignment (forwarder.go:1456
                # processSourceSwitch reads exactly this pair).
                sr = parse_sr(chunk)
                if sr is not None:
                    ssrc, ntp64, rtp_ts = sr
                    b = self.bindings.get(ssrc)
                    if b is not None and self.addrs.get(ssrc) == addr:
                        self.pub_sr[ssrc] = (ntp64, rtp_ts)
                        self._update_ts_deltas(b.room, b.track)
            elif pt == RTCP_RR:
                # Report blocks carry subscriber-observed loss per downtrack
                # SSRC; fraction_lost feeds the BWE nack channel as a loss
                # signal (nacktracker.go ratio semantics), and LSR/DLSR
                # against our SR echo table yields RTT (RFC 3550 A.8).
                count = fmt  # RC field shares the FMT bits
                blocks = chunk[8:]
                for i in range(count):
                    b = blocks[i * 24 : i * 24 + 24]
                    if len(b) < 24:
                        break
                    ssrc = int.from_bytes(b[0:4], "big")
                    fraction = b[4] / 256.0
                    dest = self.egress_rev.get(ssrc)
                    if dest is None:
                        continue
                    room, sub, _track = dest
                    if self.sub_addrs.get((room, sub)) != addr:
                        continue
                    # Media-loss proxy (medialossproxy.go HandleMaxLoss
                    # Feedback): audio downstream loss aggregates to the
                    # per-track max and is relayed upstream at SR cadence.
                    if not self.track_kind.get((room, _track), False):
                        # Zero-loss reports are recorded too: the relay
                        # must tell the publisher when loss RECOVERS, or
                        # its Opus FEC latches on forever.
                        key = (room, _track)
                        self._down_frac_lost[key] = max(
                            self._down_frac_lost.get(key, 0), b[4]
                        )
                    # Loss itself is NOT fed to BWE here: the NACK path
                    # already counts it (push_nack → _nacks); adding
                    # fraction_lost would double-count the same event.
                    lsr = int.from_bytes(b[16:20], "big")
                    dlsr = int.from_bytes(b[20:24], "big")
                    if lsr and lsr in self._sr_sent.get(ssrc, ()):
                        units = (ntp_mid32(ntp_now()) - lsr - dlsr) & 0xFFFFFFFF
                        rtt_ms = units * 1000.0 / 65536.0
                        if 0 < rtt_ms < 10_000:
                            self.ingest.set_rtt(room, sub, rtt_ms)

    def _update_ts_deltas(self, room: int, track: int) -> None:
        """Recompute per-layer TS offsets from the latest SR anchors
        (forwarder.go:1456-1650 processSourceSwitch's NTP alignment): at a
        common wallclock instant t, layer l's RTP clock reads
        sr_rtp_l + (t - sr_ntp_l)·90k; delta_l is its lead over layer 0."""
        anchors: dict[int, tuple[int, int]] = {}
        for ssrc, b in self.bindings.items():
            if b.room == room and b.track == track and ssrc in self.pub_sr:
                anchors[b.layer] = self.pub_sr[ssrc]
        if 0 not in anchors:
            return
        ntp0, rtp0 = anchors[0]
        for layer, (ntp, rtp) in anchors.items():
            dt_s = (ntp - ntp0) / float(1 << 32)  # ntp64 is 32.32 fixed point
            delta = int(round(rtp - rtp0 - dt_s * 90_000.0))
            self._ts_delta[(room, track, layer)] = delta & 0xFFFFFFFF

    def send_pli(self, room: int, track: int) -> None:
        """Keyframe request toward the publisher: RTCP PLI to every latched
        layer SSRC of the track (downtrack.go keyframe request path); falls
        back to the on_pli callback for signal-plane (WS) publishers.
        Throttled per track (pliThrottle analog) so a PLI-spamming
        subscriber cannot force a keyframe storm on the publisher."""
        now_ms = asyncio.get_event_loop().time() * 1000.0
        if now_ms - self._last_pli_ms.get((room, track), -1e12) < PLI_THROTTLE_MS:
            return
        self._last_pli_ms[(room, track)] = now_ms
        sent = False
        if self.transport is not None or self.tcp_sinks:
            for ssrc, b in self.bindings.items():
                if b.room == room and b.track == track:
                    addr = self.addrs.get(ssrc)
                    if addr is not None:
                        self._sendto(build_pli(self.node_ssrc, ssrc), addr, b.session)
                        self.stats["plis_tx"] += 1
                        sent = True
        if not sent and self.on_pli is not None:
            self.on_pli(room, track)

    def _track_upstream_loss(self, ssrc: int, sn: int, now_ms: float) -> None:
        """Extend the per-SSRC highest-SN watermark; queue NACKs for gaps
        (buffer.go:673 doNACKs). Late arrivals clear their missing entry."""
        ext = sn & 0xFFFF
        hi = self._rx_hi.get(ssrc)
        if hi is None:
            self._rx_hi[ssrc] = ext
            return
        diff = (ext - hi) & 0xFFFF
        missing = self._rx_missing.setdefault(ssrc, {})
        if diff == 0:
            return  # duplicate of the watermark
        if diff < 0x8000:
            # In-order advance; SNs (hi+1 .. ext-1) are now missing.
            for gap in range(1, min(diff, 17)):
                missing[(hi + gap) & 0xFFFF] = [0, now_ms]
            if diff > 17:
                missing.clear()  # burst loss beyond window: resync, PLI path recovers
            self._rx_hi[ssrc] = ext
        else:
            # Out-of-order arrival: it fills a hole if we were tracking one.
            missing.pop(ext, None)

    def _send_upstream_nacks(self, now_ms: float) -> None:
        if self.transport is None and not self.tcp_sinks:
            return
        for ssrc, missing in self._rx_missing.items():
            if not missing:
                continue
            addr = self.addrs.get(ssrc)
            if addr is None:
                missing.clear()
                continue
            due = [sn for sn, st in missing.items() if st[1] <= now_ms]
            if not due:
                continue
            for sn in due:
                st = missing[sn]
                st[0] += 1
                if st[0] >= 3:  # reference's maxNackTimes
                    del missing[sn]
                else:
                    st[1] = now_ms + 30.0 * st[0]  # backoff between retries
            if due:
                b = self.bindings.get(ssrc)
                self._sendto(
                    build_nack(self.node_ssrc, ssrc, due), addr,
                    b.session if b is not None else None,
                )
                self.stats["nacks_tx"] += len(due)

    def _handle_punch(self, data: bytes, addr, session=None) -> None:
        if len(data) < 12:
            self.stats["bad_punch"] += 1
            return
        pid = int.from_bytes(data[8:12], "big")
        entry = self.punch_ids.get(pid)
        if entry is None:
            self.stats["bad_punch"] += 1
            return
        key, latched = entry
        if latched is not None and latched != addr:
            # id already bound to another source: replay/hijack attempt
            self.stats["bad_punch"] += 1
            return
        if session is not None and (session.room, session.sub) != key:
            # sealed punch under the wrong participant's key
            self.stats["bad_punch"] += 1
            return
        entry[1] = addr
        self.sub_addrs[key] = addr
        self._touch_subs()
        self._refresh_fb_enabled(*key)
        self._sendto(PUNCH_ACK + data[8:12], addr, session)

    def _flush_rx(self) -> None:
        """Drain the asyncio per-datagram queue (datagram_received / TCP
        framing path) into the shared array demux. The native recvmmsg
        reader bypasses this entirely — feed_batch goes straight to
        _process_media_arrays."""
        self._rx_scheduled = False
        pending, self._rx_pending = self._rx_pending, []
        if not pending:
            return
        now_ms = asyncio.get_event_loop().time() * 1000.0
        n = len(pending)
        lengths = np.fromiter((len(d) for d, _, _ in pending), np.int32, n)
        offsets = np.zeros(n, np.int32)
        np.cumsum(lengths[:-1], out=offsets[1:])
        blob = np.frombuffer(b"".join(d for d, _, _ in pending), np.uint8)
        addr_code = np.fromiter(
            (self._addr_code_of(a) for _, a, _ in pending), np.int64, n
        )
        sess_code = np.fromiter(
            (0 if s is None else s.key_id + 1 for _, _, s in pending),
            np.int64, n,
        )
        self._process_media_arrays(
            blob, offsets, lengths, addr_code, sess_code, now_ms
        )

    def _gateway_media(self, pkts: list, t_rx: float) -> None:
        """SRTP datagrams from latched gateway peers → per-packet
        unprotect (interop lane) → the SAME vectorized ingest stage the
        sealed lane uses, pinned by the peer's session code."""
        blob, offs, lens, codes, scodes = self.gateway.unprotect_media(pkts)
        if len(offs):
            now_ms = asyncio.get_event_loop().time() * 1000.0
            self._process_media_arrays(
                blob, offs.astype(np.int32), lens, codes, scodes, now_ms,
                t_rx,
            )

    def _process_media_arrays(
        self, blob, offsets, lengths, addr_code, sess_code, now_ms,
        t_rx: float = 0.0,
    ) -> None:
        """One native parse + one vectorized ingest stage per receive
        batch. Per-PACKET Python is limited to rare paths (RED decap, DD
        descriptors, loss-gap fallback); binding resolution is per UNIQUE
        SSRC; everything else is numpy group math. `blob` is one
        contiguous uint8 array; `addr_code`/`sess_code` are the integer
        identities from _addr_code_of / key_id + 1 (0 = plaintext)."""
        if not isinstance(blob, np.ndarray):
            blob = np.frombuffer(blob, np.uint8)
        parsed = rtp.parse_batch(
            blob, offsets, lengths,
            audio_level_ext=AUDIO_LEVEL_EXT_ID, vp8_pts={VP8_PT},
            dd_ext_id=DD_EXT_ID if self._svc_tracks else 0,
            vp9_pts={SVC_PT}, h264_pts={H264_PT},  # AV1_PT: DD-only, no
                                                   # payload-descriptor parse
        )

        # RED-publishing clients (pt 63): strip to the primary block before
        # staging (redprimaryreceiver.go; redundancy recovery rides NACK).
        if (parsed["pt"] == RED_PT).any():
            for i in np.nonzero(
                (parsed["payload_len"] > 0) & (parsed["pt"] == RED_PT)
            )[0]:
                st = int(offsets[i]) + int(parsed["payload_off"][i])
                po2, pl2 = _red_primary(blob, st, int(parsed["payload_len"][i]))
                if pl2 < 0:
                    parsed["payload_len"][i] = -1
                    continue
                parsed["payload_off"][i] = po2 - int(offsets[i])
                parsed["payload_len"][i] = pl2
                self.stats["red_rx"] = self.stats.get("red_rx", 0) + 1

        plen = parsed["payload_len"].astype(np.int64)
        ok = plen >= 0
        self.stats["parse_errors"] += int((~ok).sum())

        # Binding / alignment resolution per UNIQUE SSRC (dict work scales
        # with streams, not packets).
        ssrcs = parsed["ssrc"]
        uniq, inv = np.unique(ssrcs, return_inverse=True)
        U = len(uniq)
        u_known = np.zeros(U, bool)
        u_room = np.zeros(U, np.int32)
        u_track = np.zeros(U, np.int32)
        u_layer = np.zeros(U, np.int32)
        u_video = np.zeros(U, bool)
        u_svc = np.zeros(U, bool)
        u_keyed = np.zeros(U, bool)
        u_scode = np.zeros(U, np.int64)       # bound session's key_id + 1
        u_aligned = np.zeros(U, bool)
        u_delta = np.zeros(U, np.int64)
        u_latch = np.full(U, _NO_LATCH, np.int64)  # latched addr code
        for j, sv in enumerate(uniq.tolist()):
            b = self.bindings.get(sv)
            if b is None:
                continue
            u_known[j] = True
            u_room[j] = b.room
            u_track[j] = b.track
            u_layer[j] = b.layer
            u_video[j] = b.is_video
            u_svc[j] = b.svc
            if b.session is not None:
                u_keyed[j] = True
                u_scode[j] = b.session.key_id + 1
            delta = self._ts_delta.get((b.room, b.track, b.layer))
            if delta is not None:
                u_aligned[j] = True
                u_delta[j] = delta
            code = self._addr_code.get(sv)
            if code is None and sv in self.addrs:
                # Latched before the code mirror existed (restore paths).
                code = self._addr_code[sv] = self._addr_code_of(self.addrs[sv])
            if code is not None:
                u_latch[j] = code

        known = ok & u_known[inv]
        self.stats["unknown_ssrc"] += int((ok & ~u_known[inv]).sum())
        # SSRC pinned to its publisher's key: valid media sealed under a
        # DIFFERENT participant's session must not inject here. In
        # cleartext-allowed mode a plaintext packet (sess_code 0) is
        # legal even for a keyed SSRC (legacy client).
        keyed = u_keyed[inv]
        same = (sess_code == u_scode[inv]) & (u_scode[inv] > 0)
        mismatch = keyed & ~same & ((sess_code != 0) | self.require_encryption)
        self.stats["session_mismatch"] += int((known & mismatch).sum())
        cand = known & ~mismatch

        # First packet latches the source address; later packets from a
        # different address are dropped (UDP-mux address learning — without
        # this, anyone who learns an SSRC could inject media).
        first = np.full(U, -1, np.int64)
        pos = np.nonzero(cand)[0]
        first[inv[pos][::-1]] = pos[::-1]  # smallest position wins
        for j in np.nonzero((u_latch == _NO_LATCH) & (first >= 0))[0]:
            code = int(addr_code[first[j]])
            sv = int(uniq[j])
            self.addrs[sv] = self._tuple_of_code(code)
            self._addr_code[sv] = code
            u_latch[j] = code
        addr_ok = addr_code == u_latch[inv]
        self.stats["addr_mismatch"] += int((cand & ~addr_ok).sum())
        final = cand & addr_ok

        # NACK generation is video-only (the reference negotiates NACK for
        # video; audio loss is concealed, never replayed). Fast path: an
        # SSRC whose batch continues its watermark contiguously with no
        # tracked holes needs no per-packet work at all — loss is the
        # exception, so per-packet Python runs only on gap/reorder ticks.
        sn_arr = parsed["sn"]
        vid_pkts = np.nonzero(final & u_video[inv])[0]
        if len(vid_pkts):
            v_inv = inv[vid_pkts]
            order = np.argsort(v_inv, kind="stable")   # per-SSRC, arrival order
            sel = vid_pkts[order]
            v_sorted = v_inv[order]
            nv = len(sel)
            grp = np.concatenate(
                [[0], np.nonzero(np.diff(v_sorted))[0] + 1]
            )
            sns = sn_arr[sel].astype(np.int64)
            # Per-group watermark continuity check, fully vectorized: the
            # predecessor of each group's first packet is its SSRC's
            # stored watermark; every other predecessor is the previous
            # packet in the group.
            prev = np.empty(nv, np.int64)
            prev[1:] = sns[:-1]
            g_ssrc = [int(ssrcs[sel[g]]) for g in grp.tolist()]
            g_hi = [self._rx_hi.get(sv) for sv in g_ssrc]
            prev[grp] = [h if h is not None else -1 for h in g_hi]
            contiguous = ((sns - prev) & 0xFFFF) == 1
            g_ok = np.logical_and.reduceat(contiguous, grp)
            g_last = np.concatenate([grp[1:], [nv]]) - 1
            for gi_, (g, sv, hi) in enumerate(zip(grp.tolist(), g_ssrc, g_hi)):
                if (
                    g_ok[gi_]
                    and hi is not None
                    and not self._rx_missing.get(sv)
                ):
                    self._rx_hi[sv] = int(sns[g_last[gi_]])
                    continue
                e = int(grp[gi_ + 1]) if gi_ + 1 < len(grp) else nv
                for sn_v in sns[g:e].tolist():
                    self._track_upstream_loss(sv, sn_v, now_ms)

        if self.sub_red:
            # Primary-payload ring per audio track — the bytes the RED
            # egress plan references by source SN.
            from collections import deque

            for i in np.nonzero(final & ~u_video[inv])[0]:
                key = (int(u_room[inv[i]]), int(u_track[inv[i]]))
                ring = self._red_ring.get(key)
                if ring is None:
                    from livekit_server_tpu.ops.red import RED_DISTANCE

                    # Depth: the plan references packets up to D behind the
                    # CURRENT tick's packets, which also enter this ring —
                    # a flush can stage up to K packets, so keep D + K.
                    ring = self._red_ring[key] = deque(
                        maxlen=RED_DISTANCE + self.ingest.dims.pkts
                    )
                st = int(offsets[i]) + int(parsed["payload_off"][i])
                ring.appendleft(
                    (int(sn_arr[i]), bytes(blob[st : st + int(plen[i])]))
                )

        idx = np.nonzero(final)[0]
        if len(idx):
            e_inv = inv[idx]
            raw_ts = parsed["ts"][idx].astype(np.int64)
            aligned = u_aligned[e_inv]
            # SR-based cross-layer alignment: subtract this layer's delta so
            # all simulcast layers share layer 0's timeline; the munger then
            # carries TS straight through a source switch (ts_aligned ⇒
            # ts_jump = -1 on device).
            ts = np.where(aligned, (raw_ts - u_delta[e_inv]) & 0xFFFFFFFF, raw_ts)
            kf = parsed["keyframe"][idx].astype(bool)
            is_vid = u_video[e_inv]
            layer = u_layer[e_inv].copy()
            temporal = parsed["tid"][idx].astype(np.int32)
            begin_pic = parsed["begin_pic"][idx].astype(bool)
            layer_sync = parsed["layer_sync"][idx].astype(bool)
            end_frame = parsed["end_frame"][idx].astype(bool)
            dd_start = np.full(len(idx), -1, np.int64)
            dd_length = np.zeros(len(idx), np.int32)
            dd_ver = np.full(len(idx), -1, np.int32)
            # Plain-VP9 SVC (no DD extension on the packet): the spatial
            # layer comes from the VP9 picture header's SID
            # (buffer.go:599-671 → vp9.go:43) — without this, DD-less VP9
            # silently loses layer switching.
            vp9_sid = parsed["sid"][idx].astype(np.int32)
            use_sid = (
                u_svc[e_inv] & (parsed["dd_off"][idx] < 0) & (vp9_sid >= 0)
            )
            layer = np.where(use_sid, vp9_sid, layer)
            svc_dd = np.nonzero(u_svc[e_inv] & (parsed["dd_off"][idx] >= 0))[0]
            if len(svc_dd):
                from livekit_server_tpu.runtime import dd as dd_mod

                for j in svc_dd:
                    i = idx[j]
                    key = (int(u_room[e_inv[j]]), int(u_track[e_inv[j]]))
                    raw = bytes(blob[
                        int(parsed["dd_off"][i]) :
                        int(parsed["dd_off"][i]) + int(parsed["dd_len"][i])
                    ])
                    hist = self._dd_structs.get(key)
                    struct = hist[-1][1] if hist else None
                    ver = hist[-1][0] if hist else -1
                    try:
                        desc = (
                            dd_mod.parse(raw) if struct is None
                            else dd_mod.parse_with_structure(raw, struct)
                        )
                    except dd_mod.NeedStructure:
                        # Cold structure cache (restart mid-stream): the
                        # descriptor can't be interpreted, but its bytes
                        # are forwardable as-is — keep them on the packet
                        # (ver -1 ⇒ egress never rewrites the mask).
                        dd_start[j] = int(parsed["dd_off"][i])
                        dd_length[j] = int(parsed["dd_len"][i])
                        continue
                    except ValueError:
                        continue  # malformed: keep defaults, strip DD
                    if desc.structure is not None:
                        struct = desc.structure
                        ver += 1
                        hist = (hist or []) + [(ver, struct)]
                        self._dd_structs[key] = hist[-2:]
                        kf[j] = True            # structures ride keyframes
                        layer_sync[j] = True
                    if struct is not None:
                        # refine_layer honors per-frame custom DTIs: a frame
                        # skipped for low decode targets gets its effective
                        # temporal raised so layer selection drops it for
                        # those subscribers (the reference's custom-dti
                        # precedence in the DD selector).
                        sp, tp = desc.refine_layer(struct)
                        layer[j] = sp
                        temporal[j] = tp
                    begin_pic[j] = desc.first_packet_in_frame
                    end_frame[j] = desc.last_packet_in_frame
                    dd_start[j] = int(parsed["dd_off"][i])
                    dd_length[j] = int(parsed["dd_len"][i])
                    dd_ver[j] = ver
            self.ingest.push_batch(
                room=u_room[e_inv],
                track=u_track[e_inv],
                layer=layer,
                sn=sn_arr[idx].astype(np.int64),
                ts=ts,
                ts_aligned=aligned,
                temporal=temporal,
                keyframe=kf,
                layer_sync=layer_sync | kf,
                begin_pic=begin_pic,
                marker=parsed["marker"][idx].astype(bool),
                end_frame=end_frame,
                pid=np.maximum(parsed["picture_id"][idx], 0),
                tl0=np.maximum(parsed["tl0picidx"][idx], 0),
                keyidx=np.maximum(parsed["keyidx"][idx], 0),
                size=plen[idx].astype(np.int32),
                frame_ms=np.where(is_vid, 0, 20).astype(np.int32),
                audio_level=parsed["audio_level"][idx].astype(np.int32),
                arrival_rtp=parsed["ts"][idx].astype(np.int64),
                pay_start=offsets[idx].astype(np.int64)
                + parsed["payload_off"][idx].astype(np.int64),
                pay_length=plen[idx],
                blob=blob,
                dd_start=dd_start,
                dd_length=dd_length,
                dd_version=dd_ver,
                t_rx=t_rx if t_rx else time.perf_counter(),
            )
            # (Express lane hand-off happens inside push_batch via
            # ingest.on_put — active rooms' arrivals are decided/munged/
            # sealed on arrival there, covering TCP/gateway/bridge
            # staging paths too, not just this one.)
            # MCU tap: audio payloads of mix-enabled rooms feed the Opus
            # decoders (per-packet work, gated to enabled rooms only).
            if self.audio_mixer is not None and self.audio_mixer.rooms:
                for j in np.nonzero(
                    ~is_vid & self.audio_mixer.room_mask(u_room[e_inv])
                )[0]:
                    i = idx[j]
                    st = int(offsets[i]) + int(parsed["payload_off"][i])
                    self.audio_mixer.push(
                        int(u_room[e_inv[j]]), int(u_track[e_inv[j]]),
                        int(parsed["ts"][i]),
                        bytes(blob[st : st + int(plen[i])]),
                    )
        self._send_upstream_nacks(now_ms)

    def _send_srs(self, now_ms: float) -> None:
        """~1/s sender reports per downtrack SSRC: RTT echo anchors + the
        receiver-side sync clients need (rtcpSenderWorker analog)."""
        if now_ms - self._last_sr_ms < 1000.0:
            return
        self._last_sr_ms = now_ms
        self._fold_txsr()
        ntp = ntp_now()
        mid = ntp_mid32(ntp)
        for ssrc, st in self._tx_sr.items():
            dest = self.egress_rev.get(ssrc)
            if dest is None:
                continue
            addr = self.sub_addrs.get((dest[0], dest[1]))
            if addr is None:
                continue
            # RFC 3550 §6.4.1: the SR's RTP TS must correspond to the SAME
            # instant as its NTP TS — extrapolate from the last packet's TS
            # by the wallclock elapsed since it was sent, else the anchor
            # skews by a frame (or unboundedly on a paused track) and
            # receiver lip-sync drifts.
            clock = 90_000 if self.track_kind.get((dest[0], dest[2]), True) else 48_000
            rtp_ts = (st[2] + int((now_ms - st[3]) * clock / 1000.0)) & 0xFFFFFFFF
            self._sendto(
                build_sr(ssrc, ntp, rtp_ts, st[0], st[1]), addr,
                self.sub_sessions.get((dest[0], dest[1])),
            )
            # Keep the last few mids: an RR may echo an SR one or two
            # behind; anything else is a stale/garbage LSR we must not
            # let poison rtt_ms (it throttles NACK replays).
            mids = self._sr_sent.setdefault(ssrc, [])
            mids.append(mid)
            del mids[:-4]
        # Media-loss proxy upstream relay (medialossproxy.go:82
        # maybeUpdateLoss, downLostUpdateDelta = 1 s): one RR per audio
        # publisher SSRC carrying the window's max subscriber loss.
        if self._down_frac_lost:
            window, self._down_frac_lost = self._down_frac_lost, {}
            for ssrc, b in self.bindings.items():
                frac = window.get((b.room, b.track))
                if frac is None:
                    continue
                addr = self.addrs.get(ssrc)
                if addr is not None:
                    self._sendto(build_rr(self.node_ssrc, ssrc, frac), addr, b.session)

    def _pacer_gate(self, batch, allowed, udp_mask) -> np.ndarray:
        """Leaky-bucket egress gate: drain the deferred queue under this
        tick's per-(room, sub) byte budgets, then admit in-batch UDP
        entries FIFO until each subscriber's budget runs out. Returns the
        admit mask; over-budget entries are queued as packets (bounded —
        overflow drops newest, a pacer is loss-tolerant by design)."""
        PACER_QUEUE_MAX = 4096
        remaining = np.asarray(allowed, np.float64).copy()
        blocked: set = set()
        if self._pacer_queue:
            send_now, keep = [], []
            for pkt in self._pacer_queue:
                key = (pkt.room, pkt.sub)
                cost = pkt.size + WIRE_OVERHEAD_BYTES
                if key in blocked or remaining[key] < cost:
                    blocked.add(key)   # FIFO per sub: block all behind it
                    keep.append(pkt)
                else:
                    remaining[key] -= cost
                    send_now.append(pkt)
            self._pacer_queue = keep
            if send_now:
                self.send_egress(send_now)
        n = len(batch)
        r, t, k, s = batch.rooms, batch.tracks, batch.ks, batch.subs
        # Budgets model wire bytes: charge the fixed per-packet overhead the
        # device bucket charges too (ops/pacer.WIRE_OVERHEAD_BYTES), or the
        # host admits a few percent more wire bytes than the bucket granted.
        sizes = (
            np.maximum(batch.payloads.length[r, t, k].astype(np.int64), 0)
            + WIRE_OVERHEAD_BYTES
        )
        S = remaining.shape[1]
        key = r.astype(np.int64) * S + s
        order = np.argsort(key, kind="stable")          # per-sub FIFO kept
        ks_ = key[order]
        cs = np.cumsum(np.where(udp_mask[order], sizes[order], 0))
        grp_first = np.r_[True, ks_[1:] != ks_[:-1]] if n else np.zeros(0, bool)
        first_idx = np.flatnonzero(grp_first)
        base = np.repeat(
            np.r_[0, cs[first_idx[1:] - 1]] if len(first_idx) else np.zeros(0),
            np.diff(np.r_[first_idx, n]),
        )
        cum = cs - base
        rem_sorted = remaining[r[order], s[order]]
        blk = np.zeros(n, bool)
        if blocked:
            blk = np.fromiter(
                ((int(a), int(b)) in blocked
                 for a, b in zip(r[order], s[order])), bool, n,
            )
        ok_sorted = (cum <= rem_sorted) & ~blk
        mask = np.empty(n, bool)
        mask[order] = ok_sorted
        mask |= ~udp_mask                                # pace UDP only
        defer = ~mask & udp_mask
        if defer.any():
            deferred = batch.to_packets(defer)
            space = PACER_QUEUE_MAX - len(self._pacer_queue)
            if len(deferred) > space:
                self.stats["pacer_dropped"] = (
                    self.stats.get("pacer_dropped", 0) + len(deferred) - space
                )
                deferred = deferred[:space]
            self._pacer_queue.extend(deferred)
            self.stats["pacer_deferred"] = (
                self.stats.get("pacer_deferred", 0) + len(deferred)
            )
        return mask

    def attach_egress_plane(self, plane) -> None:
        """Adopt the runtime's sharded egress orchestrator
        (runtime/egress_plane.py). From the next tick on,
        send_egress_batch routes through the native plane path —
        room-aligned shards on the persistent worker pool with
        multicast-shaped canonical staging — and reports per-shard
        stage timings back through `plane.record_send`."""
        self._egress_plane = plane
        if plane is not None:
            plane.warm()

    def attach_express(self, lane) -> None:
        """Bind an ExpressLane (runtime/express.py): this transport
        supplies its UDP-fast-path subscriber set and carries its wire
        sends; rx_batch hands each receive batch to the lane right after
        staging."""
        self._express = lane
        lane.sub_provider = self._express_sub_provider
        lane.sender = self._send_express

    def _express_sub_provider(self) -> np.ndarray:
        """[R, S] bool — subscribers the express lane may own: plain UDP
        fast-path only. TCP-fallback, SRTP-gateway, WebSocket, and RED
        subscribers keep riding the batched tick (their egress paths
        re-encapsulate per frame and don't fit the small-batch seal)."""
        self._maybe_resync_subs()
        return (self._sub_port != 0) & ~self._sub_tcp & ~self._sub_red_arr

    def _send_express(self, cols) -> int:
        """Express-lane egress: one receive batch's forwarding decisions
        → wire, now.

        The small-batch twin of send_egress_batch: same destination
        gathers, seal/counter discipline, TWCC stamping, and SR/tx
        bookkeeping, but no shard planning, no pacer gate, and no RED/DD
        handling (RED subs and SVC rooms are express-ineligible). The
        native egress_express_send entry reuses the persistent worker
        pool, key-schedule cache, and P3FA staging of the batch path.
        Returns datagrams handed to the kernel."""
        n = len(cols)
        if n == 0:
            return 0
        self._maybe_resync_subs()
        r, t, s = cols.rooms, cols.tracks, cols.subs
        e_port = self._sub_port[r, s]
        # Re-filter against live destination state: a sub can churn (or
        # flip to TCP fallback) between the lane's retier and this
        # arrival; the batched tier will NOT cover it (the room row is
        # masked), so a dropped entry here is at worst one lost datagram
        # to a disconnecting sub.
        idx = np.nonzero(
            (e_port != 0) & ~self._sub_tcp[r, s] & (cols.pay_len > 0)
        )[0]
        if not len(idx):
            return 0
        use_native = (
            native_egress is not None and self.transport is not None
            and hasattr(native_egress, "send_express")
        )
        if not use_native:
            # Toolchain-free fallback: per-packet Python path (sealing
            # and protection happen inside send_egress).
            from livekit_server_tpu.runtime.plane_runtime import EgressPacket

            slab = cols.slab
            pkts = []
            for j in idx:
                off, ln = int(cols.pay_off[j]), int(cols.pay_len[j])
                pkts.append(EgressPacket(
                    room=int(r[j]), track=int(t[j]), sub=int(s[j]),
                    sn=int(cols.sn[j]) & 0xFFFF,
                    ts=int(cols.ts[j]) & 0xFFFFFFFF,
                    pid=int(cols.pid[j]), tl0=int(cols.tl0[j]),
                    keyidx=int(cols.keyidx[j]), size=ln,
                    payload=bytes(slab[off:off + ln]),
                    marker=bool(cols.marker[j]),
                    t_arr=float(cols.t_arr[j]),
                ))
            _t_send0 = time.perf_counter()
            self.send_egress(pkts)
            send_now = time.perf_counter()
            if self._egress_plane is not None:
                self._egress_plane.record_express(
                    len(pkts), int((send_now - _t_send0) * 1e9)
                )
            if self.wire_stages is not None:
                self.wire_stages.observe_express(
                    cols.sn[idx], cols.t_arr[idx], send_now
                )
            return len(pkts)
        # Destination-major stable order (GSO runs in the native sender);
        # entries arrive in k-order per stream, the stable sort keeps it.
        _S = self._sub_port.shape[1]
        _T = self.ingest.dims.tracks
        composite = (r[idx].astype(np.int64) * _S + s[idx]) * _T + t[idx]
        idx = idx[np.argsort(composite, kind="stable")]
        rr_, tt_, ss_ = r[idx], t[idx], s[idx]
        ssrc = self._egress_ssrc_arr[rr_, ss_, tt_].copy()
        for m_ in np.nonzero(ssrc == 0)[0]:  # first send of a new sub only
            ssrc[m_] = self.subscriber_ssrc(
                int(rr_[m_]), int(ss_[m_]), int(tt_[m_])
            )
        try:
            now_ms = asyncio.get_event_loop().time() * 1000.0
        except RuntimeError:
            now_ms = time.monotonic() * 1000.0
        # Seal + per-session counter blocks: identical discipline to the
        # batch path — counters come from the SAME per-session array, so
        # express and batched sends never collide on a nonce.
        e_sess = self._sub_sess_idx[rr_, ss_]
        n_sess = len(self._sessions)
        if n_sess:
            seal = (e_sess >= 0) & (
                self.require_encryption
                | (self._sess_active[np.maximum(e_sess, 0)] > 0)
            )
        else:
            seal = np.zeros(len(idx), bool)
        key_idx = np.where(seal, e_sess, -1).astype(np.int32)
        ctr = np.zeros(len(idx), np.uint64)
        if seal.any():
            sealed_pos = np.nonzero(seal)[0]
            es = e_sess[sealed_pos]
            u, cnts = np.unique(es, return_counts=True)
            base = np.zeros(n_sess, np.uint64)
            base[u] = self._sess_ctr[u]
            self._sess_ctr[u] += cnts.astype(np.uint64)
            order = np.argsort(es, kind="stable")
            sorted_es = es[order]
            grp_start = np.r_[0, np.nonzero(np.diff(sorted_es))[0] + 1]
            sizes = np.diff(np.r_[grp_start, len(es)])
            ranks = np.empty(len(es), np.int64)
            ranks[order] = np.arange(len(es)) - np.repeat(grp_start, sizes)
            ctr[sealed_pos] = base[es] + ranks.astype(np.uint64)
            sp_r, sp_s = rr_[sealed_pos], ss_[sealed_pos]
            sp_slot = (ctr[sealed_pos] & np.uint64(TWCC_RING - 1)).astype(np.int64)
            self._twcc_ms[sp_r, sp_s, sp_slot] = now_ms
            self._twcc_ctr[sp_r, sp_s, sp_slot] = ctr[sealed_pos].astype(np.int64)
            self._twcc_len[sp_r, sp_s, sp_slot] = (
                cols.pay_len[idx][sealed_pos] + WIRE_OVERHEAD_BYTES
            )
        keys = self._sess_keys if n_sess else np.zeros((1, 16), np.uint8)
        key_ids = self._sess_keyids if n_sess else np.zeros(1, np.uint32)
        # Header extensions: playout-delay only (one shared 3-byte
        # section). SVC rooms are express-ineligible, so no DD patching.
        ext_blob, ext_off, ext_len = b"", None, None
        if self.playout_delay is not None:
            is_vid = self._track_is_video[rr_, tt_]
            if is_vid.any():
                mn, mx = self.playout_delay
                val = (min(mn // 10, 4095) << 12) | min(mx // 10, 4095)
                sec = build_ext_section(
                    [(PLAYOUT_DELAY_EXT_ID, val.to_bytes(3, "big"))]
                )
                ext_blob = sec
                ext_off = np.zeros(len(idx), np.int64)
                ext_len = np.where(is_vid, len(sec), 0).astype(np.int32)
        fd = self.transport.get_extra_info("socket").fileno()
        _t_send0 = time.perf_counter()
        _, _, _, sent, _ = native_egress.send_express(
            fd=fd, slab=cols.slab,
            pay_off=cols.pay_off[idx], pay_len=cols.pay_len[idx],
            marker=cols.marker[idx],
            pt=self._track_pt[rr_, tt_],
            vp8=(
                self._track_is_video[rr_, tt_] & ~self._track_svc[rr_, tt_]
            ).astype(np.uint8),
            sn=(cols.sn[idx] & 0xFFFF).astype(np.uint16),
            ts=(cols.ts[idx].astype(np.int64) & 0xFFFFFFFF).astype(np.uint32),
            ssrc=ssrc,
            pid=cols.pid[idx], tl0=cols.tl0[idx], kidx=cols.keyidx[idx],
            ip=self._sub_ip[rr_, ss_], port=e_port[idx],
            seal=seal.astype(np.uint8), key_idx=key_idx,
            keys=keys, key_ids=key_ids, counters=ctr,
            ext_blob=ext_blob, ext_off=ext_off, ext_len=ext_len,
        )
        self.stats["tx"] += sent
        if sent < len(idx):
            self.stats["tx_drop"] = (
                self.stats.get("tx_drop", 0) + len(idx) - sent
            )
        send_now = time.perf_counter()
        if self._egress_plane is not None:
            # Express sends count toward the host-egress pps/wall stats
            # (ISSUE-12 satellite: today only the batched path reports).
            self._egress_plane.record_express(
                int(sent), int((send_now - _t_send0) * 1e9)
            )
        t_arr = cols.t_arr[idx]
        stamped = t_arr[t_arr > 0.0]
        if stamped.size:
            self.fwd_latency_express.observe(send_now - stamped)
        if self.wire_stages is not None:
            self.wire_stages.observe_express(cols.sn[idx], t_arr, send_now)
        # SR/tx bookkeeping (add.at — express batches are tiny relative
        # to the plane, bincount temporaries never pay off here).
        S = self.ingest.dims.subs
        flat = (rr_.astype(np.int64) * S + ss_) * _T + tt_
        np.add.at(self._txsr_pkts.reshape(-1), flat, 1)
        np.add.at(self._txsr_oct.reshape(-1), flat, cols.pay_len[idx])
        self._txsr_ts[rr_, ss_, tt_] = (
            cols.ts[idx].astype(np.int64) & 0xFFFFFFFF
        ).astype(np.uint32)
        self._txsr_ms[rr_, ss_, tt_] = now_ms
        flat_rs = rr_.astype(np.int64) * S + ss_
        np.add.at(self.tx_pkts.reshape(-1), flat_rs, 1)
        np.add.at(
            self.tx_bytes.reshape(-1), flat_rs,
            cols.pay_len[idx].astype(np.int64) + WIRE_OVERHEAD_BYTES,
        )
        return int(sent)

    def send_egress_batch(self, batch, red_plan=None, layer_caps=None,
                          pacer_allowed=None) -> np.ndarray:
        """Vectorized tick egress (the hot half of DownTrack.WriteRTP +
        pion/srtp + pacer socket writes): per-entry field arrays are
        assembled with numpy index math and handed to ONE native call that
        builds datagrams, patches VP8 descriptors, seals, and sendmmsg()s
        across a small thread fan-out. No per-packet Python objects.

        Returns a [N] bool mask of entries that have a UDP/TCP media
        destination — the caller delivers the complement over WebSocket.
        """
        n = len(batch)
        if n == 0:
            # A quiet tick still drains the pacer's deferred queue.
            if (self.pacer_mode == "leaky-bucket" and pacer_allowed is not None
                    and self._pacer_queue):
                self._pacer_gate(batch, pacer_allowed, np.zeros(0, bool))
            return np.zeros(0, bool)
        r, t, k, s = batch.rooms, batch.tracks, batch.ks, batch.subs
        # Destination resolution: pure array gathers from the persistent
        # per-(room, sub) mirrors (resynced only on subscription churn) —
        # no per-subscriber Python loop on the per-tick path.
        self._maybe_resync_subs()
        e_port = self._sub_port[r, s]
        e_tcp = self._sub_tcp[r, s]
        has_dest = (e_port != 0) | e_tcp
        pacing = self.pacer_mode == "leaky-bucket" and pacer_allowed is not None

        if native_egress is None or self.transport is None:
            # Toolchain-free fallback: the per-packet Python path.
            pace_ok = (
                self._pacer_gate(batch, pacer_allowed, e_port != 0)
                if pacing else np.ones(n, bool)
            )
            if self.transport is not None or self.tcp_sinks:
                self.send_egress(batch.to_packets(has_dest & pace_ok))
            return has_dest

        # Shared flat index for the slab-field gathers (off/length/marker).
        _T = batch.payloads.off.shape[1]
        _K = batch.payloads.off.shape[2]
        flat_rtk = (r.astype(np.int64) * _T + t) * _K + k
        po = batch.payloads.off.reshape(-1)[flat_rtk]
        pl = batch.payloads.length.reshape(-1)[flat_rtk]
        # RED-negotiated audio entries leave the fast path: their payloads
        # are re-encapsulated per RFC 2198 from the device's plan.
        now_ms = asyncio.get_event_loop().time() * 1000.0
        red_mask = np.zeros(n, bool)
        if self.sub_red and red_plan is not None and red_plan[0].size:
            red_mask = (
                self._sub_red_arr[r, s] & (e_port != 0) & (po >= 0)
                & ~self._track_is_video[r, t]
            )
            if red_mask.any():
                self._send_red(batch, red_plan, red_mask, po, pl, now_ms)
        # RED entries already left on the wire above, so the pacer must not
        # also defer them (duplicate delivery); low-rate RED audio rides
        # unpaced, like the reference pacer's priority audio.
        pace_ok = (
            self._pacer_gate(batch, pacer_allowed, (e_port != 0) & ~red_mask)
            if pacing else np.ones(n, bool)
        )
        idx = np.nonzero((e_port != 0) & (po >= 0) & ~red_mask & pace_ok)[0]
        if len(idx):
            # Destination-major order (stable in k): consecutive entries to
            # one subscriber make long equal-size runs the native sender
            # collapses into single GSO messages — the syscall count drops
            # from per-datagram to per-(subscriber, track) burst. Within a
            # (room, sub, track) stream k-order is preserved, so SNs still
            # leave the host in order. One composite-key argsort instead of
            # a 4-key lexsort: each lexsort pass re-permutes all keys, the
            # fused int64 key sorts once (dims bound each factor).
            _S = self._sub_port.shape[1]
            composite = (
                ((r[idx].astype(np.int64) * _S + s[idx]) * _T + t[idx]) * _K
                + k[idx]
            )
            idx = idx[np.argsort(composite, kind="stable")]
            rr_, tt_, ss_ = r[idx], t[idx], s[idx]
            kk_ = k[idx]
            ssrc = self._egress_ssrc_arr[rr_, ss_, tt_].copy()
            for m_ in np.nonzero(ssrc == 0)[0]:  # first tick of a new sub only
                ssrc[m_] = self.subscriber_ssrc(int(rr_[m_]), int(ss_[m_]), int(tt_[m_]))
            e_sess = self._sub_sess_idx[rr_, ss_]
            n_sess = len(self._sessions)
            if n_sess:
                seal = (e_sess >= 0) & (
                    self.require_encryption
                    | (self._sess_active[np.maximum(e_sess, 0)] > 0)
                )
            else:
                seal = np.zeros(len(idx), bool)
            key_idx = np.where(seal, e_sess, -1).astype(np.int32)
            ctr = np.zeros(len(idx), np.uint64)
            if seal.any():
                # Allocate each session a contiguous counter block for this
                # batch, fully vectorized over the shared counter array
                # (sessions seal RTCP between ticks through the SAME array
                # slot — crypto.bind_counter — so nonces never collide).
                sealed_pos = np.nonzero(seal)[0]
                es = e_sess[sealed_pos]
                u, cnts = np.unique(es, return_counts=True)
                base = np.zeros(n_sess, np.uint64)
                base[u] = self._sess_ctr[u]
                self._sess_ctr[u] += cnts.astype(np.uint64)
                order = np.argsort(es, kind="stable")
                sorted_es = es[order]
                grp_start = np.r_[0, np.nonzero(np.diff(sorted_es))[0] + 1]
                sizes = np.diff(np.r_[grp_start, len(es)])
                ranks = np.empty(len(es), np.int64)
                ranks[order] = np.arange(len(es)) - np.repeat(grp_start, sizes)
                ctr[sealed_pos] = base[es] + ranks.astype(np.uint64)
                # TWCC send-time ring: every sealed datagram's counter is
                # its transport-wide sequence number — record send time +
                # wire size for the feedback matcher (_handle_twcc).
                sp_r, sp_s = rr_[sealed_pos], ss_[sealed_pos]
                sp_slot = (ctr[sealed_pos] & np.uint64(TWCC_RING - 1)).astype(np.int64)
                self._twcc_ms[sp_r, sp_s, sp_slot] = now_ms
                self._twcc_ctr[sp_r, sp_s, sp_slot] = ctr[sealed_pos].astype(np.int64)
                self._twcc_len[sp_r, sp_s, sp_slot] = (
                    pl[idx][sealed_pos] + WIRE_OVERHEAD_BYTES
                )
            keys = self._sess_keys if n_sess else np.zeros((1, 16), np.uint8)
            key_ids = self._sess_keyids if n_sess else np.zeros(1, np.uint32)
            ext_blob, ext_off, ext_len = b"", None, None
            if self.playout_delay is not None or self._svc_tracks:
                ext_blob, ext_off, ext_len = self._build_ext_sections(
                    batch, rr_, tt_, kk_, ss_, layer_caps
                )
            pace_us = int(self.pacer_spread_ms * 1000)
            fd = self.transport.get_extra_info("socket").fileno()
            if pace_us > 0:
                # Paced sends sleep inside the native call; run them OFF
                # the event loop (one worker: tick order preserved). If
                # the previous paced send hasn't drained, burst this one
                # inline instead of queueing stale media.
                if self._pace_pool is None:
                    from concurrent.futures import ThreadPoolExecutor

                    self._pace_pool = ThreadPoolExecutor(
                        max_workers=1, thread_name_prefix="pacer"
                    )
                if self._pace_pending is not None and not self._pace_pending.done():
                    pace_us = 0
            send_args = dict(
                fd=fd,
                slab=batch.payloads.data,
                pay_off=po[idx], pay_len=pl[idx],
                marker=batch.payloads.marker.reshape(-1)[
                    flat_rtk[idx]
                ].astype(np.uint8),
                pt=self._track_pt[rr_, tt_],
                vp8=(
                    self._track_is_video[rr_, tt_] & ~self._track_svc[rr_, tt_]
                ).astype(np.uint8),
                sn=(batch.sn[idx] & 0xFFFF).astype(np.uint16),
                ts=(batch.ts[idx].astype(np.int64) & 0xFFFFFFFF).astype(np.uint32),
                ssrc=ssrc,
                pid=batch.pid[idx], tl0=batch.tl0[idx], kidx=batch.keyidx[idx],
                ip=self._sub_ip[rr_, ss_], port=e_port[idx],
                seal=seal.astype(np.uint8), key_idx=key_idx,
                keys=keys, key_ids=key_ids, counters=ctr,
                ext_blob=ext_blob, ext_off=ext_off, ext_len=ext_len,
                pace_window_us=pace_us,
            )
            n_entries = len(idx)
            plane = self._egress_plane
            use_plane = plane is not None and hasattr(native_egress, "send_sharded")
            if use_plane:
                # Sharded plane path: room-aligned entry ranges on the
                # persistent pool, canonical-group slots for the
                # multicast-shaped assembly, per-shard timings recorded.
                sh_lo, sh_hi = plane.entry_plan(rr_)
                grp, grp_slots = plane.group_slots(
                    flat_rtk[idx], tt_, kk_, _T, _K
                )
                if grp is None:
                    grp = np.full(n_entries, -1, np.int32)
                    grp_slots = 0
                send_args.update(
                    shard_lo=sh_lo, shard_hi=sh_hi,
                    rooms=rr_.astype(np.int32), grp=grp, grp_slots=grp_slots,
                )
                n_grouped = int((grp >= 0).sum())
            else:
                send_args["n_threads"] = self.egress_threads
            t_arr = (
                batch.payloads.t_arr.reshape(-1)[flat_rtk[idx]]
                if batch.payloads.t_arr is not None else None
            )

            def do_send(args=send_args, n_entries=n_entries, t_arr=t_arr,
                        sn_s=batch.sn[idx], ws=self.wire_stages,
                        t_disp=getattr(batch, "t_dispatch", 0.0),
                        t_dev=getattr(batch, "t_device_end", 0.0)):
                if use_plane:
                    (_, _, _, sent, sh_sent, sh_built,
                     sh_ns) = native_egress.send_sharded(**args)
                    plane.record_send(
                        n_entries, n_grouped, sent,
                        args["shard_lo"], args["shard_hi"],
                        sh_sent, sh_built, sh_ns,
                    )
                else:
                    _, _, _, sent = native_egress.send(**args)
                self.stats["tx"] += sent
                if sent < n_entries:
                    self.stats["tx_drop"] = (
                        self.stats.get("tx_drop", 0) + n_entries - sent
                    )
                if t_arr is not None:
                    # Wire-out stamp: the kernel has every datagram now.
                    send_now = time.perf_counter()
                    stamped = t_arr[t_arr > 0.0]
                    if stamped.size:
                        self.fwd_latency.observe(send_now - stamped)
                    if ws is not None:
                        # Sampled per-stage decomposition: arrival →
                        # dispatch (staging+queue wait), dispatch →
                        # device end, device end → wire.
                        ws.observe_batch(sn_s, t_arr, t_disp, t_dev, send_now)

            if pace_us > 0:
                self._pace_pending = self._pace_pool.submit(do_send)
            else:
                do_send()
            # SR bookkeeping accumulators, folded at SR cadence. bincount
            # allocates plane-sized temporaries — only worth it when the
            # batch is a sizable fraction of the plane; otherwise add.at
            # scales with entries sent.
            R, T, S = (self.ingest.dims.rooms, self.ingest.dims.tracks,
                       self.ingest.dims.subs)
            flat = (rr_.astype(np.int64) * S + ss_) * T + tt_
            if R * S * T <= 4 * len(flat):
                self._txsr_pkts += np.bincount(
                    flat, minlength=R * S * T
                ).reshape(R, S, T)
                self._txsr_oct += np.bincount(
                    flat, weights=pl[idx].astype(np.float64), minlength=R * S * T
                ).astype(np.int64).reshape(R, S, T)
            else:
                np.add.at(self._txsr_pkts.reshape(-1), flat, 1)
                np.add.at(self._txsr_oct.reshape(-1), flat, pl[idx])
            self._txsr_ts[rr_, ss_, tt_] = (
                batch.ts[idx].astype(np.int64) & 0xFFFFFFFF
            ).astype(np.uint32)
            self._txsr_ms[rr_, ss_, tt_] = now_ms
            flat_rs = rr_.astype(np.int64) * S + ss_
            np.add.at(self.tx_pkts.reshape(-1), flat_rs, 1)
            np.add.at(
                self.tx_bytes.reshape(-1), flat_rs,
                pl[idx].astype(np.int64) + WIRE_OVERHEAD_BYTES,
            )
        if (e_tcp & (po >= 0)).any():
            # TCP-fallback + SRTP-gateway subscribers: cold path,
            # per-frame sealing/protection via _sendto.
            self.send_egress(batch.to_packets(e_tcp & (po >= 0)))
        self._send_srs(now_ms)
        if self.gateway is not None:
            self.gateway.service_timers()
        if self.audio_mixer is not None:
            self.audio_mixer.maybe_tick()
        return has_dest

    def _maybe_resync_subs(self) -> None:
        """Rebuild the destination/session arrays from the dicts when
        subscription state changed (register/release/punch/bind bump
        `_subs_rev`; the length checks catch direct dict writers)."""
        import socket as _socket

        key = (self._subs_rev, len(self.sub_addrs), len(self.sub_sessions))
        if key == self._subs_synced:
            return
        self._sub_ip[:] = 0
        self._sub_port[:] = 0
        self._sub_tcp[:] = False
        self._sub_red_arr[:] = False
        self._sub_sess_idx[:] = -1
        R, S = self._sub_ip.shape
        for (room, sub), addr in self.sub_addrs.items():
            if not (0 <= room < R and 0 <= sub < S):
                continue
            if addr[0] in ("tcp", "srtp"):
                # Non-UDP-fast-path lanes (TCP fallback, SRTP gateway):
                # egress rides the per-packet cold path via _sendto.
                self._sub_tcp[room, sub] = True
            else:
                try:
                    self._sub_ip[room, sub] = int.from_bytes(
                        _socket.inet_aton(addr[0]), "big"
                    )
                except OSError:
                    # Loud enough to find: a hostname here means a caller
                    # bypassed the resolve step; the sub gets no egress.
                    self.stats["bad_sub_addr"] = self.stats.get("bad_sub_addr", 0) + 1
                    continue
                self._sub_port[room, sub] = addr[1]
        for room, sub in self.sub_red:
            if 0 <= room < R and 0 <= sub < S:
                self._sub_red_arr[room, sub] = True
        sessions = []
        sess_idx_by_id: dict[int, int] = {}
        for (room, sub), sess in self.sub_sessions.items():
            if not (0 <= room < R and 0 <= sub < S):
                continue
            # Dedup by identity: a session bound under two keys must get
            # ONE counter slot — two slots seeded alike would hand out
            # duplicate GCM nonces under one key.
            j = sess_idx_by_id.get(id(sess))
            if j is None:
                j = sess_idx_by_id[id(sess)] = len(sessions)
                sessions.append(sess)
            self._sub_sess_idx[room, sub] = j
        self._sessions = sessions
        n = len(sessions)
        self._sess_keys = np.frombuffer(
            b"".join(x.key for x in sessions), np.uint8
        ).reshape(n, 16) if n else np.zeros((0, 16), np.uint8)
        self._sess_keyids = np.array([x.key_id for x in sessions], np.uint32)
        self._sess_active = np.array(
            [1 if x.client_active else 0 for x in sessions], np.uint8
        )
        # Shared counter slots: GCM nonces must be unique per key, so both
        # the vectorized bulk allocation and per-frame seal() draw from
        # the same array cell (crypto.bind_counter).
        self._sess_ctr = np.zeros(n, np.uint64)
        for j, x in enumerate(sessions):
            x.bind_counter(self._sess_ctr, j)
            x._arr_idx = j
        self._subs_synced = key

    def _touch_subs(self) -> None:
        self._subs_rev += 1

    def _build_ext_sections(self, batch, rr_, tt_, kk_, ss_, layer_caps):
        """Per-entry RTP header-extension sections for the native builder:
        playout delay on video, and for SVC entries the re-attached
        dependency descriptor (sfu/dependencydescriptor) with the
        active-decode-targets bitmask patched to the subscriber's layer
        caps (videolayerselector/dependencydescriptor.go:65 selection →
        writer :254 bitmask rewrite). Sections are deduped per
        (source packet, mask) — subscribers with identical caps share
        bytes."""
        from livekit_server_tpu.runtime import dd as dd_mod

        n = len(rr_)
        off = np.zeros(n, np.int64)
        ln = np.zeros(n, np.int32)
        parts: list[bytes] = []
        total = 0
        pd_bytes = b""
        pd_section_off = -1
        if self.playout_delay is not None:
            mn, mx = self.playout_delay
            # Clamp to the extension's 12-bit fields (playoutdelay.go).
            val = (min(mn // 10, 4095) << 12) | min(mx // 10, 4095)
            pd_bytes = val.to_bytes(3, "big")
            sec = build_ext_section([(PLAYOUT_DELAY_EXT_ID, pd_bytes)])
            parts.append(sec)
            pd_section_off = 0
            total += len(sec)

        is_vid = self._track_is_video[rr_, tt_]
        dd_offs = batch.payloads.dd_off
        if dd_offs is not None:
            has_dd = dd_offs[rr_, tt_, kk_] >= 0
        else:
            has_dd = np.zeros(n, bool)
        if pd_section_off >= 0:
            m = is_vid & ~has_dd
            off[m] = pd_section_off
            ln[m] = len(parts[0])

        if has_dd.any():
            max_sp, max_tp = layer_caps if layer_caps is not None else (None, None)
            data = batch.payloads.data
            cache: dict = {}
            dt_layers_cache: dict = {}
            dd_vers = batch.payloads.dd_ver
            for i in np.nonzero(has_dd)[0]:
                rr, tt, kk, ss = int(rr_[i]), int(tt_[i]), int(kk_[i]), int(ss_[i])
                ver = int(dd_vers[rr, tt, kk]) if dd_vers is not None else -1
                struct = None
                for v, st in self._dd_structs.get((rr, tt), ()):  # last 2
                    if v == ver:
                        struct = st
                        break
                mask = None
                if struct is not None and max_sp is not None:
                    layers = dt_layers_cache.get(id(struct))
                    if layers is None:
                        layers = dt_layers_cache[id(struct)] = (
                            struct.decode_target_layers()
                        )
                    sp_cap = int(max_sp[rr, tt, ss])
                    tp_cap = int(max_tp[rr, tt, ss])
                    mask = 0
                    for d_i, (sp, tp) in enumerate(layers):
                        if sp <= sp_cap and tp <= tp_cap:
                            mask |= 1 << d_i
                ck = (rr, tt, kk, mask)
                hit = cache.get(ck)
                if hit is None:
                    o = int(dd_offs[rr, tt, kk])
                    raw = data[o : o + int(batch.payloads.dd_len[rr, tt, kk])]
                    if (
                        struct is not None
                        and mask is not None
                        and mask != (1 << struct.num_decode_targets) - 1
                    ):
                        try:
                            desc = dd_mod.parse_with_structure(raw, struct)
                            buf = bytearray(raw)
                            if dd_mod.patch_active_mask(buf, 0, desc, mask):
                                raw = bytes(buf)
                        except ValueError:
                            pass  # unparseable DD forwards unmodified
                    exts = [(DD_EXT_ID, raw)]
                    if pd_bytes:
                        exts.append((PLAYOUT_DELAY_EXT_ID, pd_bytes))
                    sec = build_ext_section(exts)
                    hit = cache[ck] = (total, len(sec))
                    parts.append(sec)
                    total += len(sec)
                off[i], ln[i] = hit
        return b"".join(parts), off, ln

    def _send_red(self, batch, red_plan, red_mask, po, pl, now_ms) -> None:
        """RFC 2198 encapsulation for RED subscribers (redreceiver.go):
        primary payload + up to D redundancy blocks chosen by the device
        plan, bytes from the per-track primary ring. Cold-ish path — runs
        only for opted-in subscribers' audio packets."""
        red_sn, red_off, red_ok = red_plan
        data = batch.payloads.data
        r, t, k, s = batch.rooms, batch.tracks, batch.ks, batch.subs
        mk = batch.payloads.marker
        D = red_sn.shape[-1]
        rings: dict[tuple, dict] = {}
        for i in np.nonzero(red_mask)[0]:
            rr, tt, kk, ss = int(r[i]), int(t[i]), int(k[i]), int(s[i])
            addr = self.sub_addrs.get((rr, ss))
            if addr is None:
                continue
            prim = data[int(po[i]) : int(po[i]) + int(pl[i])]
            ring = rings.get((rr, tt))
            if ring is None:
                ring = rings[(rr, tt)] = dict(self._red_ring.get((rr, tt), ()))
            blocks = []
            for d in range(D - 1, -1, -1):  # oldest first (RFC 2198 order)
                if not red_ok[rr, tt, kk, d]:
                    continue
                pay = ring.get(int(red_sn[rr, tt, kk, d]) & 0xFFFF)
                if pay is not None and len(pay) <= 1023:
                    blocks.append((int(red_off[rr, tt, kk, d]), pay))
            payload = bytearray()
            for off_, pay in blocks:
                payload += bytes([
                    0x80 | OPUS_PT, (off_ >> 6) & 0xFF,
                    ((off_ & 0x3F) << 2) | (len(pay) >> 8), len(pay) & 0xFF,
                ])
            payload.append(OPUS_PT)
            for _, pay in blocks:
                payload += pay
            payload += prim
            hdr = bytearray(12)
            hdr[0] = 0x80
            hdr[1] = (0x80 if mk[rr, tt, kk] else 0) | RED_PT
            hdr[2:4] = (int(batch.sn[i]) & 0xFFFF).to_bytes(2, "big")
            hdr[4:8] = (int(batch.ts[i]) & 0xFFFFFFFF).to_bytes(4, "big")
            ssrc = self.subscriber_ssrc(rr, ss, tt)
            hdr[8:12] = ssrc.to_bytes(4, "big")
            self._sendto(bytes(hdr + payload), addr, self.sub_sessions.get((rr, ss)))
            self.stats["tx"] += 1
            self.stats["red_tx"] = self.stats.get("red_tx", 0) + 1
            # SR bookkeeping (same accumulators the fast path feeds).
            self._txsr_pkts[rr, ss, tt] += 1
            self._txsr_oct[rr, ss, tt] += len(payload)
            self._txsr_ts[rr, ss, tt] = int(batch.ts[i]) & 0xFFFFFFFF
            self._txsr_ms[rr, ss, tt] = now_ms
            self.tx_pkts[rr, ss] += 1
            self.tx_bytes[rr, ss] += len(payload) + WIRE_OVERHEAD_BYTES

    def _fold_txsr(self) -> None:
        """Merge batch-path SR accumulators into the per-SSRC table (runs
        at SR cadence, so the per-SSRC loop is 1/s, not per tick)."""
        nz = np.nonzero(self._txsr_pkts)
        for rr, ss, tt in zip(*nz):
            ssrc = int(self._egress_ssrc_arr[rr, ss, tt])
            if ssrc == 0:
                continue
            st = self._tx_sr.get(ssrc)
            if st is None:
                st = self._tx_sr[ssrc] = [0, 0, 0, 0.0]
            st[0] += int(self._txsr_pkts[rr, ss, tt])
            st[1] += int(self._txsr_oct[rr, ss, tt])
            st[2] = int(self._txsr_ts[rr, ss, tt])
            st[3] = float(self._txsr_ms[rr, ss, tt])
        self._txsr_pkts[:] = 0
        self._txsr_oct[:] = 0

    def send_egress(self, packets, rtx: bool = False) -> None:
        """Rewrite + send a tick's EgressPackets: assemble all datagrams in
        one buffer, ONE native rewrite call (headers + VP8 payload
        descriptors), then sendto per datagram (the batched write half of
        DownTrack.WriteRTP + pacer)."""
        if self.transport is None and not self.tcp_sinks:
            return  # no UDP socket and no TCP-fallback connections
        buf = bytearray()
        offsets: list[int] = []
        lengths: list[int] = []
        sns: list[int] = []
        tss: list[int] = []
        ssrcs: list[int] = []
        pids: list[int] = []
        tl0s: list[int] = []
        keyidxs: list[int] = []
        vp8_flags: list[int] = []
        addrs: list[tuple] = []
        sessions: list = []
        stamps: list[float] = []
        n_pad_sent = 0
        for pkt in packets:
            addr = self.sub_addrs.get((pkt.room, pkt.sub))
            is_padding = getattr(pkt, "padding", False)
            if addr is None or (not pkt.payload and not is_padding):
                continue
            is_video = self.track_kind.get((pkt.room, pkt.track), False)
            is_svc = bool(self._track_svc[pkt.room, pkt.track])
            header = bytearray(12)
            header[0] = 0x80 | (0x20 if is_padding else 0)  # P bit on padding
            # The hot path stamps _track_pt; the cold path (RTX replays,
            # TCP fallback, pacer-deferred) must match it exactly or a
            # retransmitted H264 packet arrives under a different PT than
            # its stream and is discarded.
            header[1] = (0x80 if pkt.marker else 0) | int(
                self._track_pt[pkt.room, pkt.track]
            )
            # Header extensions on this cold path too: DD for SVC packets
            # (unpatched — per-sub mask rewrite is the batch path's job)
            # and playout delay on video.
            exts = []
            if getattr(pkt, "dd", b"") and not is_padding:
                exts.append((DD_EXT_ID, pkt.dd))
            if self.playout_delay is not None and is_video and not is_padding:
                mn, mx = self.playout_delay
                val = (min(mn // 10, 4095) << 12) | min(mx // 10, 4095)
                exts.append((PLAYOUT_DELAY_EXT_ID, val.to_bytes(3, "big")))
            ext = build_ext_section(exts) if exts else b""
            if ext:
                header[0] |= 0x10
            # Probe padding carries a pure pad run: N-1 zeros + the pad
            # length byte (WritePaddingRTP's wire shape, downtrack.go:764).
            payload = pkt.payload if pkt.payload else PAD_RUN
            n_pad_sent += is_padding
            offsets.append(len(buf))
            buf += header + ext + payload
            lengths.append(12 + len(ext) + len(payload))
            sns.append(pkt.sn)
            tss.append(pkt.ts)
            ssrcs.append(self.subscriber_ssrc(pkt.room, pkt.sub, pkt.track))
            # Device-munged VP8 descriptor values reach the wire here
            # (codecmunger/vp8.go:161): after a simulcast switch or
            # temporal drop, receivers need contiguous picture ids.
            # Padding has no descriptor to rewrite.
            has_vp8 = is_video and not is_padding and not is_svc
            pids.append(pkt.pid if has_vp8 else -1)
            tl0s.append(pkt.tl0 if has_vp8 else -1)
            keyidxs.append(pkt.keyidx if has_vp8 else -1)
            vp8_flags.append(1 if has_vp8 else 0)
            addrs.append(addr)
            sessions.append(self.sub_sessions.get((pkt.room, pkt.sub)))
            if getattr(pkt, "t_arr", 0.0) > 0.0:
                stamps.append(pkt.t_arr)
            self.tx_pkts[pkt.room, pkt.sub] += 1
            # Actual wire bytes: padding packets carry PAD_RUN, not their
            # (empty) payload, and extensions count too — probe bursts are
            # exactly when egress-rate accuracy matters.
            self.tx_bytes[pkt.room, pkt.sub] += (
                len(payload) + len(ext) + WIRE_OVERHEAD_BYTES
            )
        if not offsets:
            return
        rtp.rewrite_vp8_batch(
            buf,
            np.asarray(offsets, np.int32),
            np.asarray(lengths, np.int32),
            np.asarray(sns, np.uint16),
            np.asarray(tss, np.uint32),
            np.asarray(ssrcs, np.uint32),
            np.asarray(pids, np.int32),
            np.asarray(tl0s, np.int32),
            np.asarray(keyidxs, np.int32),
            np.asarray(vp8_flags, np.uint8),
        )
        view = memoryview(buf)
        for off, ln, addr, sess in zip(offsets, lengths, addrs, sessions):
            self._sendto(bytes(view[off : off + ln]), addr, sess)
            self.stats["tx"] += 1
        # Latency probe: this cold path carries pacer-deferred and
        # TCP-fallback media whose delay is exactly the tail the histogram
        # must not lose (deferral adds whole ticks).
        if stamps:
            self.fwd_latency.observe(time.perf_counter() - np.array(stamps))
        if rtx:
            if n_pad_sent:
                self.stats["pad_tx"] = self.stats.get("pad_tx", 0) + n_pad_sent
            if len(offsets) > n_pad_sent:
                self.stats["rtx_tx"] = self.stats.get("rtx_tx", 0) + len(offsets) - n_pad_sent
        else:
            # SR bookkeeping rides the primary path only (replays re-send
            # old timestamps and must not advance the SR anchor).
            now_ms = asyncio.get_event_loop().time() * 1000.0
            for ssrc, ln, ts in zip(ssrcs, lengths, tss):
                st = self._tx_sr.get(ssrc)
                if st is None:
                    st = self._tx_sr[ssrc] = [0, 0, 0, 0.0]
                st[0] += 1
                st[1] += ln - 12
                st[2] = ts & 0xFFFFFFFF
                st[3] = now_ms
            self._send_srs(now_ms)


class _RawDatagramTransport:
    """Minimal DatagramTransport stand-in over a raw non-blocking socket
    (the native batch-receive path owns reads via loop.add_reader)."""

    def __init__(self, sock, loop):
        self._sock = sock
        self._loop = loop
        self._closed = False

    def sendto(self, data, addr) -> None:
        try:
            self._sock.sendto(data, addr)
        except (BlockingIOError, OSError):
            pass  # full buffer / teardown race: drop like the kernel would

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._loop.remove_reader(self._sock.fileno())
        except (OSError, ValueError):
            pass
        self._sock.close()

    def get_extra_info(self, name, default=None):
        if name == "socket":
            return self._sock
        if name == "sockname":
            return self._sock.getsockname()
        return default


async def start_udp_transport(
    ingest: IngestBuffer,
    host: str = "0.0.0.0",
    port: int = 7882,
    crypto: MediaCryptoRegistry | None = None,
    require_encryption: bool = False,
    nack_resolver=None,
) -> UDPMediaTransport:
    import socket as _socket

    loop = asyncio.get_running_loop()
    protocol = UDPMediaTransport(ingest, crypto, require_encryption, nack_resolver)
    is_v4 = ":" not in host  # rx_batch parses sockaddr_in (IPv4) only
    if native_egress is not None and is_v4:
        # Native batch-receive path: raw socket + recvmmsg per event-loop
        # wake + one batch AEAD open, instead of one asyncio protocol
        # callback (and one Python AES call) per datagram.
        sock = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVBUF, 4 << 20)
        sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_SNDBUF, 4 << 20)
        sock.bind((host, port))
        sock.setblocking(False)
        tr = _RawDatagramTransport(sock, loop)
        protocol.connection_made(tr)
        MAXN, MAXD = 1024, 2048
        scratch = np.zeros(MAXN * MAXD, np.uint8)
        offs = np.zeros(MAXN, np.int32)
        lens = np.zeros(MAXN, np.int32)
        ips = np.zeros(MAXN, np.uint32)
        ports_a = np.zeros(MAXN, np.uint16)
        fd = sock.fileno()

        def on_readable():
            # ONE batch per wake: the reader is level-triggered, so a
            # still-full socket re-fires immediately — but other event-loop
            # work (ticks, flushes, timers) gets to run in between instead
            # of being starved by a sustained flood.
            nn = native_egress.rx_batch(fd, scratch, offs, lens, ips, ports_a, MAXD)
            if nn > 0:
                protocol.feed_batch(
                    scratch, offs, lens, ips, ports_a, nn,
                    t_rx=time.perf_counter(),
                )

        loop.add_reader(fd, on_readable)
        return protocol
    transport, _ = await loop.create_datagram_endpoint(
        lambda: protocol, local_addr=(host, port)
    )
    return protocol
