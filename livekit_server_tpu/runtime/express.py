"""Express lane: on-arrival forwarding for small interactive rooms.

The batched tick (plane_runtime._run) gives every room worst-case
batching delay — a packet arriving right after a drain waits a full
window before the device even sees it, then one more pipeline depth
before its bytes leave. For a 2-party call that delay buys nothing: the
forwarding decision for a handful of subscribers is a dozen integer ops.

The express lane runs exactly those ops on the host, per receive batch,
against a MIRROR of the device selector state:

  - Eligibility (`retier`, once per tick boundary): rooms whose
    subscriber count is within `plane.express_max_subs` (or pinned via
    the API), with no SVC tracks published, not frozen for migration,
    and not under chaos injection. The effective control tensors
    (governor shed overlay + integrity quarantine applied) drive both
    eligibility and the forwarding base, so the overload and integrity
    seams bind the express tier exactly as they bind the batched tier.
  - Decision (`on_arrivals`, on the rx path): the simulcast selection
    scan from ops/selector.py — bit for bit the same algebra the device
    kernel runs — applied to the arriving packets with the mirrored
    current/target layers. The mirror is refreshed from the committed
    device state every tick, so decisions are bounded ≤1 tick stale and
    bit-equivalent to what the device would decide for the same mirror.
  - Rewrite: HostMunger.apply_arrivals advances the SAME per-(room,
    track, sub) SN/TS/VP8 lanes the batched fan-out uses — the two
    tiers share one sequencing space, so promotion and demotion never
    break a subscriber's RTP continuity.
  - Send: the caller-provided `sender` (udp.RtpUdpServer._send_express)
    seals and ships the columns through native egress_express_send.

Rooms the lane handled during a window are masked out of that tick's
batched fan-out (sub-granular: only the lane's UDP fast-path subscriber
bits are cleared; WS/TCP/RED subscribers of the same room keep riding
the batched tier). The device still sees every packet — BWE, audio
levels, quality scoring, speaker detection, and the selector shadow all
stay authoritative on the device; the lane moves only WHERE the
forwarding decision/rewrite/send happens.

Tier handover ordering: demotion is exact (the batched tier resumes
with strictly newer packets). Promotion takes over the closing window
synchronously at the tick boundary (`takeover`), so in low-latency mode
— where each tick's fan-out completes inside its own window — the
munger lanes advance in strict arrival order across the switch. In
pipelined mode one prior window's deferred fan-out can interleave a
promotion; the worst case is a transient one-SN gap on the promoted
room's lanes (perceived loss, recovered by NACK), never corruption.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class ExpressColumns:
    """One receive batch's express sends, column-major (the same shape
    udp.send_egress_batch consumes, plus the payload locators into the
    LIVE ingest staging slab — express sends happen before the drain
    copies it)."""

    rooms: np.ndarray   # int32 [N]
    tracks: np.ndarray  # int32 [N]
    ks: np.ndarray      # int32 [N] staging slot (this window)
    subs: np.ndarray    # int32 [N]
    sn: np.ndarray      # int32 [N] munged
    ts: np.ndarray      # int32 [N] munged (uint32 bit pattern)
    pid: np.ndarray     # int32 [N] munged VP8 picture id
    tl0: np.ndarray     # int32 [N]
    keyidx: np.ndarray  # int32 [N]
    orig_sn: np.ndarray  # int32 [N] wire SN at ingest (replay-log guard)
    pay_off: np.ndarray  # int64 [N] into `slab`
    pay_len: np.ndarray  # int32 [N]
    marker: np.ndarray   # uint8 [N]
    t_arr: np.ndarray    # float64 [N] ingress perf_counter stamp
    slab: bytearray      # the ingest staging slab (borrowed, send-time only)

    def __len__(self) -> int:
        return len(self.rooms)


class ExpressLog:
    """The window's express sends, merged for the replay ring
    (HostSequencer.record duck-types on these fields). `orig_sn` lets
    the fan-out drop entries whose staging slot was permuted by the
    drain's reorder pass before they are recorded — a filtered entry is
    a replay miss (client re-NACKs), never a wrong payload."""

    __slots__ = ("rooms", "tracks", "ks", "subs", "sn", "ts", "pid",
                 "tl0", "keyidx", "orig_sn")

    def __init__(self, rooms, tracks, ks, subs, sn, ts, pid, tl0,
                 keyidx, orig_sn):
        self.rooms, self.tracks, self.ks, self.subs = rooms, tracks, ks, subs
        self.sn, self.ts, self.pid, self.tl0 = sn, ts, pid, tl0
        self.keyidx, self.orig_sn = keyidx, orig_sn

    def __len__(self) -> int:
        return len(self.rooms)

    def take(self, mask: np.ndarray) -> "ExpressLog":
        return ExpressLog(*(getattr(self, f)[mask] for f in self.__slots__))

    @classmethod
    def merge(cls, batches: list) -> "ExpressLog | None":
        if not batches:
            return None
        return cls(
            *(np.concatenate([np.asarray(getattr(b, f)) for b in batches])
              for f in cls.__slots__)
        )


class ExpressLane:
    """Host-side on-arrival forwarding tier (see module docstring)."""

    def __init__(self, runtime, max_subs: int, max_rooms: int = 16):
        self.rt = runtime
        dims = runtime.dims
        R, T, _, S = dims
        self.max_subs = int(max_subs)
        self.max_rooms = int(max_rooms)
        # Pin API: 0 = auto (eligibility), 1 = force express, -1 = force
        # batched. Forced-express rooms still must pass the hard gates
        # (SVC, freeze, chaos).
        self.pin = np.zeros(R, np.int8)
        self.desired = np.zeros(R, bool)    # eligible, pre-mirror gate
        self.active = np.zeros(R, bool)     # handled THIS window
        self.mirror_ok = np.zeros(R, bool)  # a fresh mirror has landed
        self.express_subs = np.zeros((R, S), bool)  # UDP fast-path subs
        self.base = np.zeros((R, T, S), bool)       # forwarding base
        self.words = np.zeros((R, (S + 31) // 32), np.int32)
        # Mirrored selector state (device sel, ≤1 tick stale). The lane's
        # own scan advances `cur_*` between mirrors; targets re-sync from
        # the device every tick, currents only on (re)promotion — an
        # active room's own scan IS the exact continuation.
        self.cur_sp = np.full((R, T, S), -1, np.int32)
        self.cur_tp = np.zeros((R, T, S), np.int32)
        self.tgt_sp = np.full((R, T, S), -1, np.int32)
        self.tgt_tp = np.zeros((R, T, S), np.int32)
        self._pending_mirror: tuple | None = None  # posted by device thread
        # Wired by udp.attach_express; None in runtime-only tests (all
        # subs treated as fast-path, sends collected via the log).
        self.sub_provider = None
        self.sender = None
        self._active_any = False
        self._log: list[ExpressColumns] = []
        self.stats = {
            "express_pkts": 0, "express_entries": 0, "express_dgrams": 0,
            "promotes": 0, "demotes": 0, "takeover_pkts": 0,
            "replay_drops": 0,
        }
        # Arrival hook on the ingest itself (not the UDP transport): the
        # fan-out masks active rooms' rows wholesale, so EVERY staging
        # path — UDP batch, TCP/gateway per-packet, bridge replays — must
        # hand its arrivals over or their media would silently vanish.
        runtime.ingest.on_put = self._on_put

    def _on_put(self, r_, t_, k_) -> None:
        if self._active_any:
            self.on_arrivals(np.asarray(r_), np.asarray(t_), np.asarray(k_),
                             self.rt.ingest)

    # -- control API ------------------------------------------------------
    def set_pin(self, room: int, pin: bool | None) -> None:
        """Pin one room to a tier: True = express, False = batched,
        None = automatic (subscriber-count eligibility)."""
        self.pin[room] = 0 if pin is None else (1 if pin else -1)

    def wants_mirror(self) -> bool:
        return bool(self._active_any or self.desired.any())

    def post_mirror(self, cur_sp, cur_tp, tgt_sp, tgt_tp) -> None:
        """Called from the device worker thread right after a step's
        state commit: one atomic tuple swap, consumed at the next
        retier on the event loop."""
        self._pending_mirror = (cur_sp, cur_tp, tgt_sp, tgt_tp)

    # -- tick boundary ----------------------------------------------------
    def tick_boundary(self, ingest):
        """Runs in _stage_host immediately before the drain (one atomic
        event-loop slice with it): close the ending window, re-tier, and
        take over the closing window's packets for freshly promoted
        rooms. Returns (rows, words, log) for the StagedTick — the rooms
        whose fast-path subscriber bits the batched fan-out must skip,
        and the send log for the replay ring."""
        rows, words, log_batches = self._close_window()
        newly = self._retier()
        if len(newly):
            mark = len(self._log)
            self._takeover(newly, ingest)
            log_batches.extend(self._log[mark:])
            del self._log[mark:]
            rows = np.concatenate([rows, newly.astype(np.int32)])
            words = np.vstack([words, self.words[newly]])
        return rows, words, ExpressLog.merge(log_batches)

    def _close_window(self):
        rows = np.nonzero(self.active)[0].astype(np.int32)
        words = self.words[rows].copy()
        log, self._log = self._log, []
        return rows, words, log

    def _retier(self) -> np.ndarray:
        """Recompute the express set from the effective control tensors
        (governor shed + quarantine applied — the seams bind here) and
        the freshest device mirror. Returns newly promoted room ids."""
        rt = self.rt
        mirror = self._pending_mirror
        if mirror is not None:
            self._pending_mirror = None
            m_csp, m_ctp, m_tsp, m_ttp = mirror
            # Targets: always refresh (≤1-tick staleness bound).
            self.tgt_sp[...] = m_tsp
            self.tgt_tp[...] = m_ttp
            # Currents: only rooms NOT actively scanning — the lane's own
            # scan is the exact continuation for active ones.
            inactive = ~self.active
            self.cur_sp[inactive] = m_csp[inactive]
            self.cur_tp[inactive] = m_ctp[inactive]
            self.mirror_ok[:] = True
        eff = rt._effective_ctrl()
        meta = rt.meta
        subs_count = eff.subscribed.any(axis=1).sum(axis=1)
        has_svc = (meta.is_svc & meta.published).any(axis=1)
        eligible = (
            ((subs_count > 0) & (subs_count <= self.max_subs))
            | ((self.pin > 0) & (subs_count > 0))
        ) & ~has_svc & (self.pin >= 0)
        if rt.fault is not None:
            # Chaos injection routes packets through the scalar push path
            # (no batch staging stash) — express stands down entirely.
            eligible[:] = False
        if rt.ingest.frozen_rows:
            # A frozen row is mid-migration: its lanes must stay byte-
            # for-byte at the snapshot. Arrivals are already filtered at
            # push_batch; demote so nothing re-activates under the bridge.
            eligible[list(rt.ingest.frozen_rows)] = False
        idx = np.nonzero(eligible)[0]
        if len(idx) > self.max_rooms:
            # Capacity cap: keep currently active rooms (no churn), then
            # lowest room ids.
            keep = idx[np.argsort(~self.active[idx], kind="stable")]
            eligible = np.zeros_like(eligible)
            eligible[keep[: self.max_rooms]] = True
        self.desired = eligible
        new_active = eligible & self.mirror_ok
        newly = new_active & ~self.active
        dropped = self.active & ~new_active
        # Re-promotion after a demotion waits for a FRESH mirror (posted
        # after at least one more device step) so currents re-seed.
        self.mirror_ok[dropped] = False
        n_pro = int(newly.sum())
        n_dem = int(dropped.sum())
        self.stats["promotes"] += n_pro
        self.stats["demotes"] += n_dem
        if n_pro or n_dem:
            # Tier transitions are rare (churn events) — black-box them
            # per room. The no-transition tick stays allocation-free.
            bb = getattr(rt, "blackbox", None)
            if bb is not None:
                from livekit_server_tpu.runtime.trace import EV_DEMOTE, EV_PROMOTE

                for r in np.nonzero(newly)[0]:
                    bb.emit(int(r), EV_PROMOTE)
                for r in np.nonzero(dropped)[0]:
                    bb.emit(int(r), EV_DEMOTE)
        self.active = new_active
        self._active_any = bool(new_active.any())
        sub_ok = eff.subscribed.any(axis=1)  # [R, S]
        if self.sub_provider is not None:
            sub_ok = sub_ok & self.sub_provider()
        es = sub_ok & self.active[:, None]
        self.express_subs = es
        # Pack to the device mask convention (ops/bits.pack_bits: bit
        # s%32 of word s//32) so `& ~words` at fan-out clears exactly
        # these subscribers' bits.
        W = self.words.shape[1]
        S = es.shape[1]
        padded = np.zeros((es.shape[0], W * 32), bool)
        padded[:, :S] = es
        self.words = (
            padded.reshape(es.shape[0], W, 32).astype(np.uint32)
            << np.arange(32, dtype=np.uint32)
        ).sum(axis=2, dtype=np.uint32).view(np.int32)
        self.base = (
            eff.subscribed & ~eff.sub_muted
            & (meta.published & ~meta.pub_muted)[:, :, None]
            & es[:, None, :]
        )
        return np.nonzero(newly)[0]

    def _takeover(self, rooms: np.ndarray, ingest) -> None:
        """Process a freshly promoted room's already-staged window
        packets synchronously at the boundary, so the munger lanes
        advance in arrival order across the tier switch and the closing
        tick's batched fan-out can skip the room entirely."""
        valid = np.asarray(ingest.valid[rooms], bool)
        ri, ti, ki = np.nonzero(valid)
        if not len(ri):
            return
        n0 = self.stats["express_pkts"]
        self.on_arrivals(rooms[ri], ti, ki, ingest)
        self.stats["takeover_pkts"] += self.stats["express_pkts"] - n0

    # -- the hot path -----------------------------------------------------
    def on_arrivals(self, r_, t_, k_, ingest):
        """Decide + munge (+ send) one receive batch's packets for active
        rooms. (r_, t_, k_) are the staging coordinates push_batch just
        wrote. Returns the ExpressColumns handled, or None."""
        if not self._active_any:
            return None
        r_ = np.asarray(r_)
        m = self.active[r_]
        integ = self.rt.integrity
        if integ is not None and integ.quarantined:
            # Live quarantine check (the audit lands on the worker thread
            # mid-window; the ctrl mute only binds at the next retier).
            q = np.zeros(len(self.active), bool)
            q[[r for r in integ.quarantined if r < len(q)]] = True
            m = m & ~q[r_]
        if not m.any():
            return None
        r_ = r_[m]
        t_ = np.asarray(t_)[m]
        k_ = np.asarray(k_)[m]
        R, T, K, S = self.rt.dims
        flat = r_.astype(np.int64) * T + t_
        uniq, inv = np.unique(flat, return_inverse=True)
        G = len(uniq)
        gr = (uniq // T).astype(np.int64)
        gt = (uniq % T).astype(np.int64)
        # Arrival-order rank of each packet within its (room, track)
        # group → a dense [G, Kb] layout (Kb = largest group).
        order = np.argsort(inv, kind="stable")
        cnt = np.bincount(inv, minlength=G)
        Kb = int(cnt.max())
        starts = np.zeros(G, np.int64)
        np.cumsum(cnt[:-1], out=starts[1:])
        rank = np.arange(len(flat)) - starts[inv[order]]
        idx2d = np.zeros((G, Kb), np.int64)
        pvalid = np.zeros((G, Kb), bool)
        idx2d[inv[order], rank] = order
        pvalid[inv[order], rank] = True

        fi = flat * K + k_  # flat index into the [R, T, K] staging arrays

        def g2(arr, dtype=None):
            v = np.asarray(arr).reshape(-1)[fi][idx2d]
            return v if dtype is None else v.astype(dtype)

        sp = g2(ingest.layer, np.int32)
        tp = g2(ingest.temporal, np.int32)
        kf = g2(ingest.keyframe, bool)
        sync = g2(ingest.layer_sync, bool)
        bp = g2(ingest.begin_pic, bool)
        sn = g2(ingest.sn, np.int64)
        ts = g2(ingest.ts, np.int64)
        jump = g2(ingest.ts_jump, np.int64)
        pid = g2(ingest.pid, np.int64)
        tl0 = g2(ingest.tl0, np.int64)
        ki = g2(ingest.keyidx, np.int64)
        pvalid &= g2(ingest.valid, bool)
        self.stats["express_pkts"] += int(pvalid.sum())

        # Gathered per-lane working state ([G, S]); scattered back below.
        sim_sp = self.cur_sp[gr, gt].copy()
        sim_tp = self.cur_tp[gr, gt].copy()
        tgt_sp = self.tgt_sp[gr, gt]
        tgt_tp = self.tgt_tp[gr, gt]
        base_g = self.base[gr, gt]
        is_vid = self.rt.meta.is_video[gr, gt][:, None]
        paused = tgt_sp < 0

        fwd = np.zeros((G, Kb, S), bool)
        drp = np.zeros((G, Kb, S), bool)
        sw_out = np.zeros((G, Kb, S), bool)
        for k in range(Kb):
            valk = pvalid[:, k][:, None]
            sp_k = sp[:, k][:, None]
            tp_k = tp[:, k][:, None]
            kf_k = kf[:, k][:, None]
            sy_k = sync[:, k][:, None]
            # ops/selector.py simulcast scan, verbatim on [G, S] lanes.
            want = (tgt_sp != sim_sp) & (tgt_sp >= 0)
            sw = valk & kf_k & want & (sp_k == tgt_sp)
            c_sp = np.where(sw, tgt_sp, sim_sp)
            c_tp = np.where(sw, tgt_tp, sim_tp)
            on_cur = valk & (sp_k == c_sp) & (c_sp >= 0)
            can_up = on_cur & sy_k & (tp_k <= tgt_tp)
            c_tp = np.where(can_up & (tp_k > c_tp), tp_k, c_tp)
            c_tp = np.where(on_cur & (tgt_tp < c_tp), tgt_tp, c_tp)
            fwd_sim = on_cur & (tp_k <= c_tp) & ~paused
            drp_sim = (on_cur & ~(tp_k <= c_tp)) | (on_cur & paused)
            sim_sp = np.where(paused, -1, c_sp)
            sim_tp = c_tp
            fwd[:, k, :] = np.where(is_vid, fwd_sim, valk) & base_g
            drp[:, k, :] = np.where(is_vid, drp_sim, False) & base_g
            sw_out[:, k, :] = np.where(is_vid, sw, False) & base_g
        # Selector state advances PRE-base-merge, exactly like the kernel
        # (base only ANDs the output masks).
        self.cur_sp[gr, gt] = sim_sp
        self.cur_tp[gr, gt] = sim_tp

        o_sn, o_ts, o_pid, o_tl0, o_ki = self.rt.munger.apply_arrivals(
            gr, gt, sn, ts, jump, pid, tl0, ki, bp, pvalid, fwd, drp, sw_out,
        )
        gg, jj, ss = np.nonzero(fwd & pvalid[:, :, None])
        if not len(gg):
            return None
        ej = idx2d[gg, jj]
        cols = ExpressColumns(
            rooms=gr[gg].astype(np.int32),
            tracks=gt[gg].astype(np.int32),
            ks=k_[ej].astype(np.int32),
            subs=ss.astype(np.int32),
            sn=o_sn[gg, jj, ss].astype(np.int32),
            ts=(o_ts[gg, jj, ss] & 0xFFFFFFFF).astype(np.uint32).view(np.int32),
            pid=o_pid[gg, jj, ss].astype(np.int32),
            tl0=o_tl0[gg, jj, ss].astype(np.int32),
            keyidx=o_ki[gg, jj, ss].astype(np.int32),
            orig_sn=(sn[gg, jj] & 0xFFFF).astype(np.int32),
            pay_off=g2(ingest.pay_off, np.int64)[gg, jj],
            pay_len=g2(ingest.pay_len, np.int32)[gg, jj],
            marker=g2(ingest.marker, np.uint8)[gg, jj],
            t_arr=g2(ingest.t_arr, np.float64)[gg, jj],
            slab=ingest._slab,
        )
        self._log.append(cols)
        self.stats["express_entries"] += len(cols)
        if self.sender is not None:
            self.stats["express_dgrams"] += int(self.sender(cols))
        return cols

    # -- migration / lifecycle --------------------------------------------
    def clear_room(self, room: int) -> None:
        """Room teardown / migration restore: tier state must not leak
        into the next tenant (or past a migration snapshot — the
        destination re-mirrors from its own device)."""
        self.pin[room] = 0
        self.desired[room] = False
        self.active[room] = False
        self.mirror_ok[room] = False
        self.express_subs[room] = False
        self.base[room] = False
        self.words[room] = 0
        self.cur_sp[room] = -1
        self.cur_tp[room] = 0
        self.tgt_sp[room] = -1
        self.tgt_tp[room] = 0
        self._active_any = bool(self.active.any())

    def debug(self) -> dict:
        return {
            "max_subs": self.max_subs,
            "max_rooms": self.max_rooms,
            "active_rooms": np.nonzero(self.active)[0].tolist(),
            "desired_rooms": np.nonzero(self.desired)[0].tolist(),
            **{k: int(v) for k, v in self.stats.items()},
        }
