"""Recompile watchdog: count XLA compilations, fail drills that retrace.

The static half (GC11) catches retrace hazards it can see in the AST;
this module catches the ones it can't — a shape that escapes the pow2
padding buckets, a weak-type flip, a new donate spec — by counting what
the backend actually does. `jax.monitoring` fires a duration event per
*backend compile* (`/jax/core/compile/backend_compile_duration`); cache
hits emit only trace events, so filtering on "backend_compile" counts
real XLA compilations and nothing else.

Usage: the runtime installs the listener at construction, the server
calls `mark_warm()` after the warmup step, and from then on
`post_warmup` must stay 0 on the steady-state tick path — pager churn
runs through pow2 buckets precisely so that it does. The seeded tier-1
drills (grow-on-join, compaction, governor shed, express retier,
migration) assert that; `/debug/compiles` and the
`livekit_xla_compiles_total` gauge expose the same ledger in prod.

jax.monitoring has no unregister API, so the ledger is a process-wide
singleton: one listener, installed once, shared by every runtime in the
process (tests reset the counters, not the listener).
"""

from __future__ import annotations

import threading
from collections import deque

import jax


class CompileLedger:
    """Process-wide XLA compile counter with a warmup watermark."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._installed = False
        self.total = 0
        self.total_ms = 0.0
        self._warm_total = 0
        self._warm_ms = 0.0
        # (event, ms) ring for /debug/compiles — enough to see what
        # recompiled without growing unbounded
        self.recent: deque[tuple[str, float]] = deque(maxlen=64)

    def install(self) -> "CompileLedger":
        with self._lock:
            if not self._installed:
                jax.monitoring.register_event_duration_secs_listener(
                    self._on_event
                )
                self._installed = True
        return self

    def _on_event(self, event: str, duration_secs: float, **kw) -> None:
        if "backend_compile" not in event:
            return
        with self._lock:
            self.total += 1
            self.total_ms += duration_secs * 1e3
            self.recent.append((event, round(duration_secs * 1e3, 2)))

    def mark_warm(self) -> None:
        """Set the watermark: compiles after this are steady-state
        recompiles — the thing the watchdog exists to catch."""
        with self._lock:
            self._warm_total = self.total
            self._warm_ms = self.total_ms

    @property
    def post_warmup(self) -> int:
        with self._lock:
            return self.total - self._warm_total

    @property
    def warmup_ms(self) -> float:
        """Compile time spent before the watermark."""
        with self._lock:
            return self._warm_ms if self._warm_total else self.total_ms

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "xla_compiles_total": self.total,
                "xla_compiles_post_warmup": self.total - self._warm_total,
                "xla_compile_ms": round(self.total_ms, 1),
                "xla_warmup_compile_ms": round(
                    self._warm_ms if self._warm_total else self.total_ms, 1
                ),
                "recent": list(self.recent)[-8:],
            }

    def reset(self) -> None:
        """Test seam: zero the counters (the listener stays — there is
        no unregister)."""
        with self._lock:
            self.total = 0
            self.total_ms = 0.0
            self._warm_total = 0
            self._warm_ms = 0.0
            self.recent.clear()


LEDGER = CompileLedger()
