"""Standard-wire WebRTC gateway: ICE-lite + DTLS-SRTP on the media socket.

Reference parity: this is the seam the reference fills with Pion —
ICE/DTLS/SRTP termination per participant (pkg/rtc/transport.go:253-374)
and SDP negotiation (pkg/rtc/participant_sdp.go, mediaengine.go). The
TPU SFU keeps its sealed bulk lane (runtime/crypto.py) for SDK clients;
this gateway is the STANDARDS lane that lets a stock WebRTC client
(browser / aiortc / Pion) join with no custom code:

    client offer ─→ create_peer() ─→ ICE-lite answer (interop/sdp)
    STUN binding  ─→ handle_datagram() answers + latches the address
    DTLS flight   ─→ handle_datagram() drives interop/dtls (OpenSSL)
    keys exported ─→ interop/srtp sessions (AEAD_AES_128_GCM)
    SRTP media    ─→ unprotected per packet → the SAME vectorized ingest
                     the sealed lane uses (_process_media_arrays)
    egress        ─→ ("srtp", peer) lane in UDPMediaTransport._sendto →
                     protect_rtp/protect_rtcp → wire

Per-packet Python crypto makes this lane ~10-50k pps per core — the
interop lane, not the bulk lane (the sealed path's native batch AES-GCM
carries the north-star load). The reference has the same split ambition
(Pion per-packet writes); we simply keep both lanes explicit.
"""

from __future__ import annotations

import secrets
import time

import numpy as np

from livekit_server_tpu.interop import dtls as dtls_mod
from livekit_server_tpu.interop import sdp as sdp_mod
from livekit_server_tpu.interop import stun as stun_mod
from livekit_server_tpu.interop.srtp import SrtpSession

__all__ = ["WebRtcGateway", "GatewayPeer"]

# Handshake retransmit cadence (DTLS timer service).
TIMER_MS = 100.0
# Abandoned-handshake TTL (pion ICE disconnectedTimeout neighborhood): a
# peer created at answer time that never reaches SRTP keys within this
# window is torn down by service_timers.
PEER_HANDSHAKE_TTL_S = 30.0


class GatewayPeer:
    """One remote WebRTC endpoint: ICE creds, DTLS association, SRTP
    sessions, latched address, and its plane coordinates."""

    def __init__(self, gateway: "WebRtcGateway", ufrag: str, pwd: str):
        self.gateway = gateway
        self.ufrag = ufrag                  # our (local) ufrag for this peer
        self.pwd = pwd                      # our ice-pwd (keys STUN integrity)
        self.remote_ufrag = ""
        self.remote_pwd = ""
        self.remote_fingerprint = ""        # "AB:CD:..." from the offer
        self.dtls: dtls_mod.DtlsEndpoint | None = None
        self.srtp_tx: SrtpSession | None = None
        self.srtp_rx: SrtpSession | None = None
        self.addr: tuple | None = None      # latched via authenticated STUN
        self.addr_code: int = 0
        # Plane coordinates.
        self.publish: list[tuple] = []      # (ssrc, room, track, layer)
        self.sub: tuple | None = None       # (room, sub)
        self.sub_registered = False         # egress lane live (post-DTLS)
        # Session-pinning handle for ingest (key_id+1 scode); minted from
        # the transport's crypto registry when present.
        self.pin_session = None
        self.created_s = time.monotonic()
        self._last_timer = 0.0

    @property
    def srtp_ready(self) -> bool:
        return self.srtp_tx is not None

    def scode(self) -> int:
        return (self.pin_session.key_id + 1) if self.pin_session else 0


class WebRtcGateway:
    """Node-level gateway state; owned by UDPMediaTransport."""

    def __init__(self, transport):
        self.transport = transport
        cert, key, fp = dtls_mod.generate_certificate()
        self.cert_pem, self.key_pem, self.fingerprint = cert, key, fp
        self.peers_by_ufrag: dict[str, GatewayPeer] = {}
        self.peers_by_addr: dict[int, GatewayPeer] = {}
        # Tuple-keyed mirror: integer addr codes for IPv6 are synthetic
        # and can be pruned/re-minted, so cold-path lookups (_sendto) key
        # by the address tuple itself.
        self.peers_by_tuple: dict[tuple, GatewayPeer] = {}
        self.stats = {
            "stun_rx": 0, "stun_bad": 0, "dtls_rx": 0, "dtls_done": 0,
            "srtp_rx": 0, "srtp_bad": 0, "srtp_tx": 0, "srtcp_rx": 0,
        }

    # -- signalling-side API ---------------------------------------------

    def create_peer(
        self,
        offer_sdp: str,
        publish: list[dict] | None = None,
        subscribe: tuple | None = None,
        advertise_addr: tuple | None = None,
    ) -> tuple[str, GatewayPeer]:
        """Negotiate one peer. `publish` maps offer media sections to
        plane tracks: [{"mid": "0", "room": r, "track": t, "mime": "vp8",
        "svc": False}] — every a=ssrc in that section binds to the track
        (SIM groups become simulcast layers). `subscribe` = (room, sub)
        registers the peer for egress. Returns (answer_sdp, peer)."""
        offer = sdp_mod.parse_sdp(offer_sdp)
        if not offer.media:
            raise ValueError("offer has no media sections")
        ufrag = secrets.token_urlsafe(4)
        pwd = secrets.token_urlsafe(18)     # ≥22 chars per RFC 8445
        peer = GatewayPeer(self, ufrag, pwd)
        peer.remote_ufrag = offer.ice_ufrag or (
            offer.media and offer.media_ufrag(offer.media[0])
        ) or ""
        peer.remote_pwd = offer.media_pwd(offer.media[0]) if offer.media else ""
        fp = offer.media_fingerprint(offer.media[0])
        if fp.lower().startswith("sha-256 "):
            peer.remote_fingerprint = fp.split(None, 1)[1]
        peer.dtls = dtls_mod.DtlsEndpoint(
            "server", self.cert_pem, self.key_pem,
            peer_fingerprint=peer.remote_fingerprint or None,
        )
        crypto = getattr(self.transport, "crypto", None)
        if crypto is not None:
            peer.pin_session = crypto.mint()

        by_mid = {m.mid: m for m in offer.media}
        for spec in publish or []:
            m = by_mid.get(str(spec.get("mid", "")))
            if m is None:
                continue
            room, track = int(spec["room"]), int(spec["track"])
            mime = spec.get("mime", "vp8" if m.kind == "video" else "opus")
            svc = bool(spec.get("svc", False))
            is_video = m.kind == "video"
            # SIM group = simulcast layers in order; otherwise the
            # declared SSRCs minus RTX partners, layer 0 first.
            sim = next(
                (g[1] for g in m.ssrc_groups if g[0] == "SIM"), None
            )
            rtx_partners = {
                g[1][1] for g in m.ssrc_groups
                if g[0] == "FID" and len(g[1]) == 2
            }
            layers = sim if sim else [
                s for s in m.ssrcs if s not in rtx_partners
            ]
            for layer, ssrc in enumerate(layers):
                if self.transport.bind_client_ssrc(
                    int(ssrc), room, track, is_video, layer=layer,
                    session=peer.pin_session, svc=svc, mime=mime,
                ):
                    peer.publish.append((int(ssrc), room, track, layer))
        if subscribe is not None:
            # Egress registration is DEFERRED until the DTLS handshake
            # completes: overwriting a live (room, sub) address at offer
            # time would black-out a subscriber whose DTLS never happens
            # (keys don't exist yet, so nothing could be sent anyway).
            peer.sub = (int(subscribe[0]), int(subscribe[1]))

        self.peers_by_ufrag[ufrag] = peer
        sock = self.transport.transport.get_extra_info("sockname") if (
            self.transport.transport is not None
        ) else ("127.0.0.1", 0)
        addr = advertise_addr or (sock[0], sock[1])
        # Declare our egress SSRCs inside the matching send-capable
        # (client-recv) m-sections so strict receivers need no
        # unsignalled-SSRC latching: the first recv section of each kind
        # carries that kind's subscriber SSRCs.
        ssrc_by_mid: dict = {}
        if peer.sub is not None:
            by_kind: dict = {"audio": [], "video": []}
            for (rm, tr), kind_is_video in sorted(
                self.transport.track_kind.items()
            ):
                if rm == peer.sub[0]:
                    by_kind["video" if kind_is_video else "audio"].append(
                        self.transport.subscriber_ssrc(rm, peer.sub[1], tr)
                    )
            for m in offer.media:
                if (
                    m.kind in by_kind
                    and m.direction in ("recvonly", "sendrecv")
                    and by_kind[m.kind]
                ):
                    ssrc_by_mid[m.mid] = by_kind.pop(m.kind)
        answer = sdp_mod.build_answer(
            offer, ufrag, pwd, self.fingerprint, addr,
            ssrc_by_mid=ssrc_by_mid,
        )
        return answer, peer

    def close_peer(self, peer: GatewayPeer) -> None:
        self.peers_by_ufrag.pop(peer.ufrag, None)
        if peer.addr_code:
            self.peers_by_addr.pop(peer.addr_code, None)
        if peer.addr is not None:
            self.peers_by_tuple.pop(peer.addr, None)
        if peer.sub is not None and peer.sub_registered:
            self.transport.release_subscriber(*peer.sub)
        for ssrc, *_ in peer.publish:
            self.transport.release_ssrc(ssrc)
        if peer.dtls is not None:
            peer.dtls.close()
        crypto = getattr(self.transport, "crypto", None)
        if crypto is not None and peer.pin_session is not None:
            crypto.remove(peer.pin_session.key_id)

    # -- wire-side demux (called from UDPMediaTransport) ------------------

    def owns_addr(self, addr_code: int) -> bool:
        return addr_code in self.peers_by_addr

    def handle_datagram(self, data: bytes, addr) -> bool:
        """STUN/DTLS demux (RFC 7983 first-byte ranges). Returns True if
        consumed."""
        if stun_mod.is_stun(data):
            self._handle_stun(data, addr)
            return True
        if dtls_mod.is_dtls(data):
            return self._handle_dtls(data, addr)
        return False

    def _handle_stun(self, data: bytes, addr) -> None:
        self.stats["stun_rx"] += 1
        msg = stun_mod.parse_stun(data)
        if msg is None or msg.msg_type != stun_mod.BINDING_REQUEST:
            return
        user = msg.username or ""
        local = user.split(":", 1)[0]
        peer = self.peers_by_ufrag.get(local)
        if peer is None:
            self.stats["stun_bad"] += 1
            return
        # Verify MESSAGE-INTEGRITY under OUR ice-pwd (short-term creds).
        checked = stun_mod.parse_stun(data, integrity_key=peer.pwd.encode())
        if checked is None or checked.integrity_ok is not True:
            self.stats["stun_bad"] += 1
            return
        resp = stun_mod.build_binding_response(
            msg, addr, peer.pwd.encode()
        )
        self._raw_send(resp, addr)
        # Latch/confirm the peer's address (ICE-lite: the first
        # authenticated binding wins; USE-CANDIDATE refreshes are idempotent).
        code = self.transport._addr_code_of(addr)
        if peer.addr_code and peer.addr_code != code:
            self.peers_by_addr.pop(peer.addr_code, None)
        if peer.addr is not None and peer.addr != addr:
            self.peers_by_tuple.pop(peer.addr, None)
        peer.addr = addr
        peer.addr_code = code
        self.peers_by_addr[code] = peer
        self.peers_by_tuple[addr] = peer
        # A re-registered subscriber address: egress flows to the latched
        # address via the ("srtp", ufrag) indirection, nothing to update.

    def _handle_dtls(self, data: bytes, addr) -> bool:
        code = self.transport._addr_code_of(addr)
        peer = self.peers_by_addr.get(code)
        if peer is None or peer.dtls is None:
            return False
        self.stats["dtls_rx"] += 1
        try:
            out = peer.dtls.feed(data)
        except dtls_mod.DtlsError:
            self.stats["stun_bad"] += 1
            return True
        for d in out:
            self._raw_send(d, addr)
        if peer.dtls.handshake_complete and peer.srtp_tx is None:
            (lk, ls), (rk, rs) = peer.dtls.export_srtp_keys()
            peer.srtp_tx = SrtpSession(master_key=lk, master_salt=ls)
            peer.srtp_rx = SrtpSession(master_key=rk, master_salt=rs)
            self.stats["dtls_done"] += 1
            if peer.sub is not None and not peer.sub_registered:
                # Keys exist now — only now may egress routing switch to
                # the SRTP lane.
                peer.sub_registered = True
                self.transport.register_subscriber(
                    *peer.sub, ("srtp", peer.ufrag)
                )
        return True

    def service_timers(self) -> None:
        """DTLS retransmission timers (call ~100 ms cadence) + abandoned
        handshake reaping: a peer that never completes DTLS within
        PEER_HANDSHAKE_TTL_S holds an ufrag slot, a DTLS endpoint, and a
        minted crypto session forever (the signalling side has no
        disconnect to observe for a client that answered the offer and
        vanished) — reap it. Peers with established SRTP are NEVER
        reaped here; their lifetime belongs to the signalling plane."""
        now = time.monotonic()
        for peer in list(self.peers_by_ufrag.values()):
            if (
                peer.dtls is not None
                and not peer.dtls.handshake_complete
                and peer.addr is not None
                and now - peer._last_timer >= TIMER_MS / 1000.0
            ):
                peer._last_timer = now
                for d in peer.dtls.handle_timeout():
                    self._raw_send(d, peer.addr)
            if (
                not peer.srtp_ready
                and now - peer.created_s >= PEER_HANDSHAKE_TTL_S
            ):
                self.stats["peers_reaped"] = (
                    self.stats.get("peers_reaped", 0) + 1
                )
                self.close_peer(peer)

    # -- SRTP media -------------------------------------------------------

    def unprotect_media(
        self, pkts: list
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """[(bytes, addr_code)] SRTP datagrams → staged cleartext arrays
        (blob, offs, lens, addr_codes, scodes) for _process_media_arrays.
        SRTCP is dispatched to the transport's RTCP handler inline."""
        out: list[bytes] = []
        codes: list[int] = []
        scodes: list[int] = []
        for data, code in pkts:
            peer = self.peers_by_addr.get(int(code))
            if peer is None or peer.srtp_rx is None:
                self.stats["srtp_bad"] += 1
                continue
            if len(data) >= 2 and 192 <= data[1] <= 223:
                clear = peer.srtp_rx.unprotect_rtcp(data)
                if clear is None:
                    self.stats["srtp_bad"] += 1
                    continue
                self.stats["srtcp_rx"] += 1
                self.transport._handle_rtcp(clear, peer.addr)
                continue
            clear = peer.srtp_rx.unprotect_rtp(data)
            if clear is None:
                self.stats["srtp_bad"] += 1
                continue
            self.stats["srtp_rx"] += 1
            out.append(clear)
            codes.append(int(code))
            scodes.append(peer.scode())
        if not out:
            z = np.zeros(0, np.int64)
            return np.zeros(0, np.uint8), z, z.astype(np.int32), z, z
        lens = np.array([len(d) for d in out], np.int32)
        offs = np.zeros(len(out), np.int64)
        if len(out) > 1:
            np.cumsum(lens[:-1].astype(np.int64), out=offs[1:])
        blob = np.frombuffer(b"".join(out), np.uint8)
        return (
            blob, offs, lens,
            np.array(codes, np.int64), np.array(scodes, np.int64),
        )

    def protect_and_send(self, data: bytes, peer_key: str) -> None:
        """Egress lane for ("srtp", ufrag) subscriber addresses: SRTP for
        RTP, SRTCP for RTCP, to the peer's latched address."""
        self._send_protected(self.peers_by_ufrag.get(peer_key), data)

    def send_to_peer_addr(self, data: bytes, addr) -> bool:
        """Protect+send data bound for a gateway peer's latched address
        (server-originated RTCP toward publishers). Returns False when the
        address belongs to no peer (caller falls through to cleartext)."""
        peer = self.peers_by_tuple.get(addr)
        if peer is None:
            return False
        self._send_protected(peer, data)
        return True

    def _send_protected(self, peer: GatewayPeer | None, data: bytes) -> None:
        if peer is None or peer.srtp_tx is None or peer.addr is None:
            return
        if len(data) >= 2 and 192 <= data[1] <= 223:
            wire = peer.srtp_tx.protect_rtcp(data)
        else:
            wire = peer.srtp_tx.protect_rtp(data)
        self.stats["srtp_tx"] += 1
        self._raw_send(wire, peer.addr)

    def _raw_send(self, data: bytes, addr) -> None:
        t = self.transport.transport
        if t is not None:
            t.sendto(data, addr)

    def debug_summary(self) -> dict:
        return {
            "peers": len(self.peers_by_ufrag),
            "latched": len(self.peers_by_addr),
            "srtp_ready": sum(
                1 for p in self.peers_by_ufrag.values() if p.srtp_ready
            ),
            **self.stats,
        }
