"""Flight-recorder tracing plane: tick spans, sampled wire-latency
attribution, and per-room black-box event rings.

Every diagnosis so far (late-tick causes, the egress wall, the BENCH_r07
wire-p99 floor analysis) was reconstructed by hand from scattered
`recent_ticks` fields and bench printouts. This module turns that into a
standing capability with a hard overhead budget — everything on the
per-tick path is a handful of scalar stores into preallocated numpy
arrays (no dict/f-string/list construction; graftcheck GC07 enforces the
discipline at the call sites):

- **TickTraceRing** — one record per tick in a fixed ring: the dispatch
  edge, per-stage start/duration pairs (stage_host with its nested
  express retier, ctrl upload, device step, fan-out, egress send), wake
  overshoot, depth, lateness, and per-egress-shard munge/send walls.
  `telemetry/trace_export.py` renders the ring as Chrome/Perfetto
  trace-event JSON (/debug/trace?ticks=N, tools/trace).
- **LatencyAttribution** — a deterministic 1-in-K sample of egress
  packets (sampled on the munged SN, so the set is stable across runs)
  whose arrival stamp (`IngestBuffer.t_arr`) is decomposed at the wire
  into staging / device / egress stage latencies, plus the express
  tier's arrival→wire latency. Feeds `livekit_wire_latency_stage_ms`
  and the previously-unfed `livekit_forward_latency_ms` histograms.
- **BlackBox** — per-room ring of the last M lifecycle / governor /
  integrity / migration / express events, dumped to the log (and kept
  for /debug/blackbox/{room}) on quarantine, repair failure, supervisor
  restart, migration rollback, or a NACK storm — the post-mortem no
  longer depends on whatever counters happened to be scraped.
"""

from __future__ import annotations

import threading
import time
from typing import Any

import numpy as np

# Egress-shard lanes a tick record can hold (EgressPlane caps at 16).
MAX_SHARDS = 16

# -- black-box event codes -------------------------------------------------
# Scalar int codes so the hot-path emit is a pure store; names resolve at
# dump time only.
EV_ROOM_OPEN = 1        # a = room row
EV_ROOM_CLOSE = 2       # a = room row
EV_JOIN = 3             # a = participant count after join
EV_LEAVE = 4            # a = participant count after leave
EV_GOV_LEVEL = 10       # a = old level, b = new level
EV_QUARANTINE = 20      # a = tick index
EV_REPAIR_OK = 21       # a = tick index
EV_REPAIR_FAIL = 22     # a = repair failures total
EV_ESCALATE = 23        # node lane; a = escalations total
EV_RESTART = 30         # node lane; a = attempt number
EV_MIG_FREEZE = 40      # a = epoch
EV_MIG_COMMIT = 41      # a = epoch
EV_MIG_ABORT = 42       # a = epoch
EV_NACK_STORM = 50      # a = subscriber, b = NACKed SN count
EV_PROMOTE = 60         # express tier promotion
EV_DEMOTE = 61          # express tier demotion

EVENT_NAMES = {
    EV_ROOM_OPEN: "room_open", EV_ROOM_CLOSE: "room_close",
    EV_JOIN: "join", EV_LEAVE: "leave",
    EV_GOV_LEVEL: "governor_level",
    EV_QUARANTINE: "quarantine", EV_REPAIR_OK: "repair_ok",
    EV_REPAIR_FAIL: "repair_fail", EV_ESCALATE: "escalate",
    EV_RESTART: "restart",
    EV_MIG_FREEZE: "migration_freeze", EV_MIG_COMMIT: "migration_commit",
    EV_MIG_ABORT: "migration_abort",
    EV_NACK_STORM: "nack_storm",
    EV_PROMOTE: "express_promote", EV_DEMOTE: "express_demote",
}

# Wire-latency stages in attribution order. `staging` is arrival →
# device dispatch (slab wait + tick-queueing), `device` the step itself,
# `egress` device commit → kernel send (munge/assemble/seal/send plus the
# pipeline's deferred fan-out wait); `total` is their measured (not
# composed) arrival→wire sum and `express` the arrival-driven tier's
# whole path — kept separate so the batched tail never buries it.
STAGES = ("staging", "device", "egress", "total", "express")
_S_STAGING, _S_DEVICE, _S_EGRESS, _S_TOTAL, _S_EXPRESS = range(len(STAGES))


class TickTraceRing:
    """Fixed ring of per-tick span records, preallocated columns.

    Single writer (the event loop's `_complete`); `record_tick` and
    `set_shard` are scalar stores only — the GC07-checked bounded API.
    `snapshot` (cold path: /debug/trace, tools/trace) materializes the
    newest records as dicts for the exporter."""

    def __init__(self, cap: int = 512):
        cap = max(8, int(cap))
        self.cap = cap
        self.idx = np.full(cap, -1, np.int64)
        self.edge = np.zeros(cap, np.float64)
        self.stage_t0 = np.zeros(cap, np.float64)
        self.stage_dur = np.zeros(cap, np.float64)
        self.retier_dur = np.zeros(cap, np.float64)
        self.upload_t0 = np.zeros(cap, np.float64)
        self.upload_dur = np.zeros(cap, np.float64)
        self.device_t0 = np.zeros(cap, np.float64)
        self.device_dur = np.zeros(cap, np.float64)
        # Paged-kernel slice of the device span (phase-0 decide dispatch,
        # runtime/paged_runtime.py); 0 when the stock tick ran.
        self.kernel_dur = np.zeros(cap, np.float64)
        self.fanout_t0 = np.zeros(cap, np.float64)
        self.fanout_dur = np.zeros(cap, np.float64)
        self.send_dur = np.zeros(cap, np.float64)
        self.wake_over_us = np.zeros(cap, np.float32)
        self.depth = np.zeros(cap, np.int8)
        self.late = np.zeros(cap, np.int8)
        self.n_shards = np.zeros(cap, np.int8)
        self.shard_munge_ms = np.zeros((cap, MAX_SHARDS), np.float32)
        self.shard_send_ms = np.zeros((cap, MAX_SHARDS), np.float32)
        self._pos = 0
        self.recorded = 0

    def record_tick(self, idx: int, edge: float, stage_t0: float,
                    stage_s: float, retier_s: float, upload_t0: float,
                    upload_s: float, device_t0: float, device_s: float,
                    fanout_t0: float, fanout_s: float, send_s: float,
                    wake_over_us: float, depth: int, late: bool,
                    kernel_s: float = 0.0) -> int:
        slot = self._pos
        self.idx[slot] = idx
        self.edge[slot] = edge
        self.stage_t0[slot] = stage_t0
        self.stage_dur[slot] = stage_s
        self.retier_dur[slot] = retier_s
        self.upload_t0[slot] = upload_t0
        self.upload_dur[slot] = upload_s
        self.device_t0[slot] = device_t0
        self.device_dur[slot] = device_s
        self.kernel_dur[slot] = kernel_s
        self.fanout_t0[slot] = fanout_t0
        self.fanout_dur[slot] = fanout_s
        self.send_dur[slot] = send_s
        self.wake_over_us[slot] = wake_over_us
        self.depth[slot] = depth
        self.late[slot] = late
        self.n_shards[slot] = 0
        self._pos = (slot + 1) % self.cap
        self.recorded += 1
        return slot

    def set_shard(self, slot: int, lane: int, munge_ms: float,
                  send_ms: float) -> None:
        if lane >= MAX_SHARDS:
            return
        self.shard_munge_ms[slot, lane] = munge_ms
        self.shard_send_ms[slot, lane] = send_ms
        if lane + 1 > self.n_shards[slot]:
            self.n_shards[slot] = lane + 1

    def snapshot(self, n: int | None = None) -> list[dict[str, Any]]:
        """Newest `n` records (all when None), oldest first — cold path."""
        have = min(self.recorded, self.cap)
        take = have if n is None else max(0, min(int(n), have))
        out: list[dict[str, Any]] = []
        for i in range(take):
            slot = (self._pos - take + i) % self.cap
            if self.idx[slot] < 0:
                continue
            ns = int(self.n_shards[slot])
            out.append({
                "tick": int(self.idx[slot]),
                "edge": float(self.edge[slot]),
                "stage_t0": float(self.stage_t0[slot]),
                "stage_s": float(self.stage_dur[slot]),
                "retier_s": float(self.retier_dur[slot]),
                "upload_t0": float(self.upload_t0[slot]),
                "upload_s": float(self.upload_dur[slot]),
                "device_t0": float(self.device_t0[slot]),
                "device_s": float(self.device_dur[slot]),
                "kernel_s": float(self.kernel_dur[slot]),
                "fanout_t0": float(self.fanout_t0[slot]),
                "fanout_s": float(self.fanout_dur[slot]),
                "send_s": float(self.send_dur[slot]),
                "wake_over_us": float(self.wake_over_us[slot]),
                "depth": int(self.depth[slot]),
                "late": bool(self.late[slot]),
                "shard_munge_ms": [
                    float(x) for x in self.shard_munge_ms[slot, :ns]
                ],
                "shard_send_ms": [
                    float(x) for x in self.shard_send_ms[slot, :ns]
                ],
            })
        return out


class LatencyAttribution:
    """Deterministic 1-in-K sampled per-stage wire-latency recorder.

    The sample predicate is `sn % sample_every == 0` on the munged
    sequence number of already-stamped entries (`t_arr > 0`): no RNG on
    the hot path, the same packets sample on every run, and the cost is
    one vectorized mask per send call. Sampled stage latencies land in
    small per-stage rings of raw millisecond values; `drain()` hands the
    new samples to telemetry (histograms), `summary()` computes exact
    percentiles over the retained window for bench/debug.

    Thread-safety: observe_* are called from the event loop AND the
    pacer worker (udp.do_send runs off-loop when paced), so pushes
    serialize on a lock — one uncontended acquire per send call."""

    CAP = 4096  # retained samples per stage (at 1-in-64 this is minutes)

    def __init__(self, sample_every: int = 64):
        self.sample_every = max(1, int(sample_every))
        n = len(STAGES)
        self.ring = np.zeros((n, self.CAP), np.float32)
        self.total = np.zeros(n, np.int64)       # lifetime samples pushed
        self._drained = np.zeros(n, np.int64)    # consumed watermark
        self._lock = threading.Lock()

    def _push(self, stage: int, vals_ms: np.ndarray) -> None:
        m = len(vals_ms)
        if not m:
            return
        if m > self.CAP:
            vals_ms = vals_ms[-self.CAP:]
            m = self.CAP
        with self._lock:
            pos = int(self.total[stage]) % self.CAP
            end = pos + m
            if end <= self.CAP:
                self.ring[stage, pos:end] = vals_ms
            else:
                k = self.CAP - pos
                self.ring[stage, pos:] = vals_ms[:k]
                self.ring[stage, : end - self.CAP] = vals_ms[k:]
            self.total[stage] += m

    def _mask(self, sn: np.ndarray, t_arr: np.ndarray) -> np.ndarray:
        return (sn % self.sample_every == 0) & (t_arr > 0.0)

    def observe_batch(self, sn, t_arr, t_dispatch: float,
                      t_device_end: float, now: float) -> None:
        """Batched-tier send: decompose each sampled entry's arrival→wire
        latency at the tick's dispatch and device-commit boundaries.
        No-ops when the batch predates the stamps (t_dispatch == 0)."""
        if t_arr is None or t_dispatch <= 0.0 or t_device_end <= 0.0:
            return
        sn = np.asarray(sn)
        t_arr = np.asarray(t_arr, np.float64)
        m = self._mask(sn, t_arr)
        if not m.any():
            return
        ta = t_arr[m]
        # A packet can arrive after the tick it rides was dispatched
        # (late slab stragglers): clip, the stage split stays >= 0.
        staging = np.maximum(t_dispatch - ta, 0.0) * 1e3
        device_ms = max(t_device_end - t_dispatch, 0.0) * 1e3
        egress_ms = max(now - t_device_end, 0.0) * 1e3
        self._push(_S_STAGING, staging.astype(np.float32))
        self._push(_S_DEVICE, np.full(len(ta), device_ms, np.float32))
        self._push(_S_EGRESS, np.full(len(ta), egress_ms, np.float32))
        self._push(_S_TOTAL, ((now - ta) * 1e3).astype(np.float32))

    def observe_express(self, sn, t_arr, now: float) -> None:
        """Express-tier send: one arrival→wire stage (the lane skips the
        tick entirely); also feeds `total` so the combined forward-latency
        histogram covers both tiers."""
        sn = np.asarray(sn)
        t_arr = np.asarray(t_arr, np.float64)
        m = self._mask(sn, t_arr)
        if not m.any():
            return
        lat = ((now - t_arr[m]) * 1e3).astype(np.float32)
        self._push(_S_EXPRESS, lat)
        self._push(_S_TOTAL, lat)

    def reset(self) -> None:
        """Discard the retained window (bench measurement-window start:
        warmup/compile-era samples would poison the percentiles)."""
        with self._lock:
            self.total[:] = 0
            self._drained[:] = 0

    def drain(self) -> dict[str, np.ndarray]:
        """New samples per stage since the last drain (telemetry scrape).
        A burst past CAP between drains keeps the newest CAP."""
        out: dict[str, np.ndarray] = {}
        with self._lock:
            for s, name in enumerate(STAGES):
                total = int(self.total[s])
                new = total - int(self._drained[s])
                if new <= 0:
                    continue
                new = min(new, self.CAP)
                pos = total % self.CAP
                lo = (pos - new) % self.CAP
                if lo + new <= self.CAP:
                    vals = self.ring[s, lo:lo + new].copy()
                else:
                    vals = np.concatenate(
                        [self.ring[s, lo:], self.ring[s, : pos]]
                    )
                self._drained[s] = total
                out[name] = vals
        return out

    def summary(self) -> dict[str, dict[str, float]]:
        """Exact percentiles over each stage's retained window (bench and
        /debug/trace sidecar; cold path)."""
        out: dict[str, dict[str, float]] = {}
        with self._lock:
            for s, name in enumerate(STAGES):
                n = int(min(self.total[s], self.CAP))
                if not n:
                    continue
                w = self.ring[s, :n].astype(np.float64)
                out[name] = {
                    "n": int(self.total[s]),
                    "p50_ms": round(float(np.percentile(w, 50)), 3),
                    "p90_ms": round(float(np.percentile(w, 90)), 3),
                    "p99_ms": round(float(np.percentile(w, 99)), 3),
                    "mean_ms": round(float(w.mean()), 3),
                }
        return out


class BlackBox:
    """Per-room flight recorder: ring of the last M (t, code, a, b)
    events per room row, plus one node lane (row R) for room-less events
    (governor level moves, supervisor restarts).

    `emit` is the GC07-checked hot-path API: four scalar stores and a
    monotonic stamp, no allocation. `dump`/`dump_to` are cold paths that
    materialize a lane as dicts, log it, and retain the last few dumps
    for /debug/blackbox/{room}."""

    NODE = -1  # emit(room=NODE, ...) targets the node lane

    def __init__(self, rooms: int, events: int = 64, log=None):
        self.rooms = int(rooms)
        self.events = max(4, int(events))
        lanes = self.rooms + 1
        self.t = np.zeros((lanes, self.events), np.float64)
        self.code = np.zeros((lanes, self.events), np.int16)
        self.a = np.zeros((lanes, self.events), np.float64)
        self.b = np.zeros((lanes, self.events), np.float64)
        self.pos = np.zeros(lanes, np.int32)
        self.total = np.zeros(lanes, np.int64)
        self.log = log
        from collections import deque

        # Bounded dump retention for /debug/blackbox (GC05: explicit cap).
        self.last_dumps: deque = deque(maxlen=8)
        self.dumps = 0

    def _lane(self, room: int) -> int:
        if 0 <= room < self.rooms:
            return room
        return self.rooms

    def emit(self, room: int, code: int, a: float = 0.0,
             b: float = 0.0) -> None:
        lane = self._lane(room)
        slot = self.pos[lane]
        self.t[lane, slot] = time.monotonic()
        self.code[lane, slot] = code
        self.a[lane, slot] = a
        self.b[lane, slot] = b
        self.pos[lane] = (slot + 1) % self.events
        self.total[lane] += 1

    def dump(self, room: int) -> list[dict[str, Any]]:
        """One lane's events, oldest first (cold path)."""
        lane = self._lane(room)
        have = int(min(self.total[lane], self.events))
        pos = int(self.pos[lane])
        out = []
        for i in range(have):
            slot = (pos - have + i) % self.events
            code = int(self.code[lane, slot])
            out.append({
                "t": round(float(self.t[lane, slot]), 6),
                "event": EVENT_NAMES.get(code, str(code)),
                "a": float(self.a[lane, slot]),
                "b": float(self.b[lane, slot]),
            })
        return out

    def dump_to(self, room: int, reason: str) -> list[dict[str, Any]]:
        """Dump a lane on a trigger (quarantine, repair failure, restart,
        migration rollback, NACK storm): log it and retain it for
        /debug/blackbox. Returns the dumped events."""
        events = self.dump(room)
        record = {
            "room": int(room),
            "reason": reason,
            "at": round(time.monotonic(), 6),
            "events": events,
        }
        self.last_dumps.append(record)
        self.dumps += 1
        if self.log is not None:
            self.log.warn(
                "black-box dump", room=int(room), reason=reason,
                n_events=len(events), events=events[-16:],
            )
        return events
