"""Host BWE probe controller: padding-based bandwidth discovery.

Reference parity: pkg/sfu/streamallocator/probe_controller.go:33-295 (probe
initiation rules, goal computation, settle/backoff timing) and
prober.go:143-600 (cluster pacing), with the padding bytes themselves
synthesized by the device munger (ops/rtpmunger.padding_tick — the
WritePaddingRTP analog, downtrack.go:764-859).

TPU-first re-design: the reference runs one prober goroutine per
participant; here the whole node's probe state machine is a handful of
numpy arrays over [R, S] advanced once per tick on the host (it's control
logic on ~10 Hz cadence — the device does the per-packet work). Outputs
feed TickInputs.pad_num / pad_track; results come back through the BWE
estimate samples the probed client reports (REMB/TWCC).

State machine per (room, subscriber):
  IDLE    --deficient & clear channel & cooldown elapsed-->  PROBING
  PROBING --estimate >= goal-->       IDLE (success; short settle)
  PROBING --congested-->              IDLE (abort; exponential backoff)
  PROBING --duration exceeded-->      IDLE (no answer; backoff)
"""

from __future__ import annotations

import numpy as np

from livekit_server_tpu.models import plane

IDLE, PROBING = 0, 1

PAD_BYTES = 255             # payload bytes per padding packet (max RTP pad run)
PROBE_DURATION_MS = 400     # how long one probe cluster runs
SETTLE_MS = 2_000           # wait after success before probing again
BACKOFF_BASE_MS = 3_000     # first wait after an aborted/unanswered probe
BACKOFF_MAX = 8.0           # exponential cap (probe_controller.go doubling)
GOAL_FACTOR = 1.5           # probe to 1.5× committed…
GOAL_MIN_STEP = 200_000.0   # …or at least +200 kbps


class ProbeController:
    """Vectorized probe scheduling over every (room, subscriber)."""

    def __init__(self, dims: plane.PlaneDims, tick_ms: int):
        R, S = dims.rooms, dims.subs
        self.tick_ms = tick_ms
        self.state = np.zeros((R, S), np.int8)
        self.goal = np.zeros((R, S), np.float64)
        self.end_ms = np.zeros((R, S), np.int64)
        self.next_allowed_ms = np.zeros((R, S), np.int64)
        self.backoff = np.ones((R, S), np.float64)
        self.stats = {"started": 0, "succeeded": 0, "aborted": 0, "expired": 0}

    def update(
        self,
        now_ms: int,
        committed: np.ndarray,       # [R, S] float — allocator budget (bwe)
        congested: np.ndarray,       # [R, S] bool — last tick's congestion
        deficient: np.ndarray,       # [R, S] bool — allocation under-served
        estimate: np.ndarray,        # [R, S] float — staged estimate samples
        estimate_valid: np.ndarray,  # [R, S] bool
        pad_track: np.ndarray,       # [R, S] int — downtrack for padding (-1 none)
    ) -> np.ndarray:
        """Advance the state machine; returns pad_num [R, S] int32 for this
        tick (0 where not probing)."""
        probing = self.state == PROBING

        # Abort: congestion during a probe means the channel answered "no".
        abort = probing & congested
        if abort.any():
            self.state[abort] = IDLE
            self.next_allowed_ms[abort] = now_ms + (
                BACKOFF_BASE_MS * self.backoff[abort]
            ).astype(np.int64)
            self.backoff[abort] = np.minimum(self.backoff[abort] * 2, BACKOFF_MAX)
            self.stats["aborted"] += int(abort.sum())

        # Success: a fresh estimate sample at (or near) the goal.
        succ = probing & ~abort & estimate_valid & (estimate >= self.goal * 0.95)
        if succ.any():
            self.state[succ] = IDLE
            self.next_allowed_ms[succ] = now_ms + SETTLE_MS
            self.backoff[succ] = 1.0
            self.stats["succeeded"] += int(succ.sum())

        # Unanswered: the cluster ran its course without the estimate moving.
        expired = probing & ~abort & ~succ & (now_ms >= self.end_ms)
        if expired.any():
            self.state[expired] = IDLE
            self.next_allowed_ms[expired] = now_ms + (
                BACKOFF_BASE_MS * self.backoff[expired]
            ).astype(np.int64)
            self.backoff[expired] = np.minimum(self.backoff[expired] * 2, BACKOFF_MAX)
            self.stats["expired"] += int(expired.sum())

        # Initiate: under-served allocation on a clear channel, cooldown
        # elapsed, and a video downtrack available to carry the padding.
        start = (
            (self.state == IDLE)
            & deficient
            & ~congested
            & (now_ms >= self.next_allowed_ms)
            & (pad_track >= 0)
        )
        if start.any():
            self.goal[start] = np.maximum(
                committed[start] * GOAL_FACTOR, committed[start] + GOAL_MIN_STEP
            )
            self.end_ms[start] = now_ms + PROBE_DURATION_MS
            self.state[start] = PROBING
            self.stats["started"] += int(start.sum())

        # Padding volume: fill the (goal − committed) gap this tick.
        probing = self.state == PROBING
        extra_bps = np.where(probing, self.goal - committed, 0.0)
        n = np.ceil(extra_bps * (self.tick_ms / 1000.0) / 8.0 / PAD_BYTES)
        return np.clip(n, 0, plane.PAD_MAX).astype(np.int32)

    def clear_room(self, room: int) -> None:
        self.state[room] = IDLE
        self.backoff[room] = 1.0
        self.next_allowed_ms[room] = 0
