"""Tensor-slot allocation for rooms, tracks, and subscribers.

No direct reference equivalent — this replaces Go's dynamic object graph
(map[string]*Room, slices of DownTracks) with static tensor coordinates:
every live room owns a row r ∈ [0, R), every published track in it a
column t ∈ [0, T), every participant a subscriber column s ∈ [0, S).
The media plane is compiled once for (R, T, K, S); occupancy is masked.

The capacity gates here are the TPU analog of the reference's node limits
(config LimitConfig, selector.LimitsReached — rtcservice.go:162): a node
refuses work when its tensor is full, and the node selector routes new
rooms elsewhere (plane_rooms_used/capacity in NodeStats).
"""

from __future__ import annotations

from dataclasses import dataclass, field


class CapacityError(Exception):
    """Raised when the plane tensor has no free row/column."""


@dataclass
class _Pool:
    capacity: int
    free: list[int] = field(default_factory=list)

    def __post_init__(self):
        self.free = list(range(self.capacity - 1, -1, -1))  # pop() yields 0 first

    def alloc(self, what: str) -> int:
        if not self.free:
            raise CapacityError(f"no free {what} slot")
        return self.free.pop()

    def release(self, idx: int) -> None:
        self.free.append(idx)

    @property
    def used(self) -> int:
        return self.capacity - len(self.free)


@dataclass
class RoomSlots:
    """Per-room slot maps: track sid → col, participant sid → sub col."""

    row: int
    tracks: _Pool
    subs: _Pool
    track_of: dict[str, int] = field(default_factory=dict)
    sub_of: dict[str, int] = field(default_factory=dict)

    def alloc_track(self, track_sid: str) -> int:
        if track_sid in self.track_of:
            return self.track_of[track_sid]
        idx = self.tracks.alloc("track")
        self.track_of[track_sid] = idx
        return idx

    def release_track(self, track_sid: str) -> int | None:
        idx = self.track_of.pop(track_sid, None)
        if idx is not None:
            self.tracks.release(idx)
        return idx

    def alloc_sub(self, participant_sid: str) -> int:
        if participant_sid in self.sub_of:
            return self.sub_of[participant_sid]
        idx = self.subs.alloc("subscriber")
        self.sub_of[participant_sid] = idx
        return idx

    def release_sub(self, participant_sid: str) -> int | None:
        idx = self.sub_of.pop(participant_sid, None)
        if idx is not None:
            self.subs.release(idx)
        return idx


class SlotAllocator:
    """Node-wide allocator of room rows and per-room track/sub columns."""

    def __init__(self, rooms: int, tracks_per_room: int, subs_per_room: int):
        self.capacity = rooms
        self.tracks_per_room = tracks_per_room
        self.subs_per_room = subs_per_room
        self._rows = _Pool(rooms)
        self._rooms: dict[str, RoomSlots] = {}

    def alloc_room(self, room_name: str) -> RoomSlots:
        if room_name in self._rooms:
            return self._rooms[room_name]
        row = self._rows.alloc("room")
        slots = RoomSlots(
            row=row,
            tracks=_Pool(self.tracks_per_room),
            subs=_Pool(self.subs_per_room),
        )
        self._rooms[room_name] = slots
        return slots

    def get(self, room_name: str) -> RoomSlots | None:
        return self._rooms.get(room_name)

    def release_room(self, room_name: str) -> None:
        slots = self._rooms.pop(room_name, None)
        if slots is not None:
            self._rows.release(slots.row)

    @property
    def rooms_used(self) -> int:
        return self._rows.used
