"""Tensor-slot allocation for rooms, tracks, and subscribers.

No direct reference equivalent — this replaces Go's dynamic object graph
(map[string]*Room, slices of DownTracks) with static tensor coordinates:
every live room owns a row r ∈ [0, R), every published track in it a
column t ∈ [0, T), every participant a subscriber column s ∈ [0, S).
The media plane is compiled once for (R, T, K, S); occupancy is masked.

The capacity gates here are the TPU analog of the reference's node limits
(config LimitConfig, selector.LimitsReached — rtcservice.go:162): a node
refuses work when its tensor is full, and the node selector routes new
rooms elsewhere (plane_rooms_used/capacity in NodeStats).
"""

from __future__ import annotations

from dataclasses import dataclass, field


class CapacityError(Exception):
    """Raised when the plane tensor has no free row/column."""


@dataclass
class _Pool:
    capacity: int
    free: list[int] = field(default_factory=list)

    def __post_init__(self):
        self.free = list(range(self.capacity - 1, -1, -1))  # pop() yields 0 first

    def alloc(self, what: str) -> int:
        if not self.free:
            raise CapacityError(f"no free {what} slot")
        return self.free.pop()

    def release(self, idx: int) -> None:
        self.free.append(idx)

    def grow(self, n: int) -> None:
        """Widen the pool by `n` fresh slots (paged mode: a page-extent
        grow makes the new page's columns allocatable)."""
        self.free.extend(range(self.capacity + n - 1, self.capacity - 1, -1))
        self.capacity += n

    @property
    def used(self) -> int:
        return self.capacity - len(self.free)


@dataclass
class RoomSlots:
    """Per-room slot maps: track sid → col, participant sid → sub col."""

    row: int
    tracks: _Pool
    subs: _Pool
    track_of: dict[str, int] = field(default_factory=dict)
    sub_of: dict[str, int] = field(default_factory=dict)

    def alloc_track(self, track_sid: str) -> int:
        if track_sid in self.track_of:
            return self.track_of[track_sid]
        idx = self.tracks.alloc("track")
        self.track_of[track_sid] = idx
        return idx

    def release_track(self, track_sid: str) -> int | None:
        idx = self.track_of.pop(track_sid, None)
        if idx is not None:
            self.tracks.release(idx)
        return idx

    def alloc_sub(self, participant_sid: str) -> int:
        if participant_sid in self.sub_of:
            return self.sub_of[participant_sid]
        idx = self.subs.alloc("subscriber")
        self.sub_of[participant_sid] = idx
        return idx

    def release_sub(self, participant_sid: str) -> int | None:
        idx = self.sub_of.pop(participant_sid, None)
        if idx is not None:
            self.subs.release(idx)
        return idx

    def occupancy(self) -> dict:
        return {
            "tracks_used": self.tracks.used,
            "tracks_capacity": self.tracks.capacity,
            "subs_used": self.subs.used,
            "subs_capacity": self.subs.capacity,
        }


class SlotAllocator:
    """Node-wide allocator of room rows and per-room track/sub columns."""

    def __init__(self, rooms: int, tracks_per_room: int, subs_per_room: int):
        self.capacity = rooms
        self.tracks_per_room = tracks_per_room
        self.subs_per_room = subs_per_room
        self._rows = _Pool(rooms)
        self._rooms: dict[str, RoomSlots] = {}

    def alloc_room(self, room_name: str) -> RoomSlots:
        if room_name in self._rooms:
            return self._rooms[room_name]
        row = self._rows.alloc("room")
        slots = RoomSlots(
            row=row,
            tracks=_Pool(self.tracks_per_room),
            subs=_Pool(self.subs_per_room),
        )
        self._rooms[room_name] = slots
        return slots

    def get(self, room_name: str) -> RoomSlots | None:
        return self._rooms.get(room_name)

    def release_room(self, room_name: str) -> None:
        slots = self._rooms.pop(room_name, None)
        if slots is not None:
            self._rows.release(slots.row)

    @property
    def rooms_used(self) -> int:
        return self._rows.used

    def occupancy(self) -> dict:
        """Per-RESOURCE occupancy, not just room count: a node whose rooms
        are large can run out of track/sub columns long before its row
        pool does (and vice versa), so admission and the node selector
        need all three axes. Dense mode: every room pre-pays the full
        per-room column pools, so capacity is rooms × per-room."""
        tracks_used = sum(s.tracks.used for s in self._rooms.values())
        subs_used = sum(s.subs.used for s in self._rooms.values())
        return {
            "rooms_used": self._rows.used,
            "rooms_capacity": self.capacity,
            "tracks_used": tracks_used,
            "tracks_capacity": self.capacity * self.tracks_per_room,
            "subs_used": subs_used,
            "subs_capacity": self.capacity * self.subs_per_room,
            "admittable_rooms": self.capacity - self._rows.used,
        }


class PagedRoomSlots(RoomSlots):
    """RoomSlots over a pager-backed room: the column pools start at the
    room's initial page extent and GROW page-at-a-time through the pager
    when a track publish / participant join crosses a page boundary.
    CapacityError propagates from the pager when the pool is exhausted —
    the same admission-denial surface as a full dense tensor."""

    def __init__(self, row: int, pager):
        ext = pager.extent(row)
        super().__init__(
            row=row, tracks=_Pool(ext.tracks), subs=_Pool(ext.subs)
        )
        self._pager = pager

    def alloc_track(self, track_sid: str) -> int:
        if track_sid in self.track_of:
            return self.track_of[track_sid]
        if not self.tracks.free:
            grown = self._pager.grow_room(self.row, tracks=self.tracks.capacity + 1)
            self.tracks.grow(grown.tracks - self.tracks.capacity)
        return super().alloc_track(track_sid)

    def alloc_sub(self, participant_sid: str) -> int:
        if participant_sid in self.sub_of:
            return self.sub_of[participant_sid]
        if not self.subs.free:
            grown = self._pager.grow_room(self.row, subs=self.subs.capacity + 1)
            self.subs.grow(grown.subs - self.subs.capacity)
        return super().alloc_sub(participant_sid)


class PagedSlotAllocator:
    """SlotAllocator facade over a RoomPager (runtime/paged_runtime.py
    wires one in as `runtime.slots`): same alloc/release/occupancy API as
    the dense allocator, but rooms claim page-grid footprints from the
    pooled HBM buffer instead of pre-paying worst-case column pools."""

    def __init__(self, pager):
        self.pager = pager
        self.capacity = pager.num_rooms
        self._rows = _Pool(pager.num_rooms)
        self._rooms: dict[str, PagedRoomSlots] = {}

    def alloc_room(self, room_name: str) -> PagedRoomSlots:
        if room_name in self._rooms:
            return self._rooms[room_name]
        row = self._rows.alloc("room")
        try:
            self.pager.alloc_room(row)
        except CapacityError:
            self._rows.release(row)
            raise
        slots = PagedRoomSlots(row, self.pager)
        self._rooms[room_name] = slots
        return slots

    def get(self, room_name: str) -> PagedRoomSlots | None:
        return self._rooms.get(room_name)

    def release_room(self, room_name: str) -> None:
        slots = self._rooms.pop(room_name, None)
        if slots is not None:
            self.pager.release_room(slots.row)
            self._rows.release(slots.row)

    @property
    def rooms_used(self) -> int:
        return self._rows.used

    def occupancy(self) -> dict:
        """Page-pool occupancy: column capacity is what the allocated
        page grids currently cover (it grows with demand), and the
        admission headroom is REAL page headroom — free pages divided by
        a minimal room's footprint, whichever of rows/pages runs out
        first (the governor's L4 key)."""
        st = self.pager.stats()
        tracks_used = sum(s.tracks.used for s in self._rooms.values())
        subs_used = sum(s.subs.used for s in self._rooms.values())
        return {
            "rooms_used": self._rows.used,
            "rooms_capacity": self.capacity,
            "tracks_used": tracks_used,
            "tracks_capacity": sum(
                s.tracks.capacity for s in self._rooms.values()
            ),
            "subs_used": subs_used,
            "subs_capacity": sum(s.subs.capacity for s in self._rooms.values()),
            "pages_used": st["pages_used"],
            "pages_free": st["pages_free"],
            "pages_total": st["pages_total"],
            "fragmentation_ratio": st["fragmentation_ratio"],
            "admittable_rooms": min(
                self.capacity - self._rows.used,
                st["pages_free"] // self.pager.min_room_pages,
            ),
        }
