"""Host runtime: the bridge between the control plane and the device plane.

The reference's media plane is a web of goroutines per packet/track/
subscriber (pkg/sfu). Here the media plane is one jitted JAX program
stepped at a fixed tick; this package owns everything host-side that feeds
and drains it:

  - slots    — allocation of (room row, track col, subscriber col) tensor
               coordinates to live control-plane objects
  - ingest   — per-tick packing of received packets into TickInputs
  - plane_runtime — the tick loop: apply control mutations, step the
               sharded plane, fan out TickOutputs (egress, speakers,
               keyframe requests, congestion)
"""

from livekit_server_tpu.runtime.slots import CapacityError, SlotAllocator
from livekit_server_tpu.runtime.ingest import IngestBuffer
from livekit_server_tpu.runtime.plane_runtime import PlaneRuntime
from livekit_server_tpu.runtime.supervisor import PlaneSupervisor
from livekit_server_tpu.runtime.faultinject import FaultInjector, FaultSpec
from livekit_server_tpu.runtime.governor import OverloadGovernor

__all__ = [
    "CapacityError",
    "FaultInjector",
    "FaultSpec",
    "IngestBuffer",
    "OverloadGovernor",
    "PlaneRuntime",
    "PlaneSupervisor",
    "SlotAllocator",
]
