"""Framework version.

Reference parity: version/version.go:17 (reference v1.5.2).
"""

__version__ = "0.1.0"
