"""Opus codec via the system libopus (ctypes) — the MCU seat's codec.

Reference parity: the reference is an SFU and never decodes Opus
(pkg/sfu/audio/audiolevel.go reads only the header extension). This
build's BASELINE config 2 commits to a *batched active-speaker mix* —
an MCU capability — which requires real Opus decode/encode at the
server. The codec work is inherently host-side and stateful (Opus
carries inter-frame prediction state); the MIX itself is the batched
tensor op (ops/mix.py einsum) that scales on the device.

No headers are shipped in this image; the ABI here is the stable public
libopus API (opus_decoder_create/opus_decode/opus_encode), loaded from
libopus.so.0.
"""

from __future__ import annotations

import ctypes
import ctypes.util

import numpy as np

__all__ = ["OpusDecoder", "OpusEncoder", "available", "OpusError"]

SAMPLE_RATE = 48000
FRAME_MS = 20
FRAME_SAMPLES = SAMPLE_RATE * FRAME_MS // 1000  # 960

OPUS_APPLICATION_VOIP = 2048
OPUS_SET_BITRATE_REQUEST = 4002
OPUS_SET_INBAND_FEC_REQUEST = 4012


class OpusError(Exception):
    pass


_lib = None
_lib_missing = False


def _load():
    global _lib, _lib_missing
    if _lib is not None or _lib_missing:
        return _lib
    name = ctypes.util.find_library("opus") or "libopus.so.0"
    try:
        lib = ctypes.CDLL(name)
    except OSError:
        _lib_missing = True
        return None
    P = ctypes.c_void_p
    lib.opus_decoder_create.restype = P
    lib.opus_decoder_create.argtypes = [
        ctypes.c_int32, ctypes.c_int, ctypes.POINTER(ctypes.c_int)
    ]
    lib.opus_decode.restype = ctypes.c_int
    lib.opus_decode.argtypes = [
        P, ctypes.c_char_p, ctypes.c_int32, P, ctypes.c_int, ctypes.c_int
    ]
    lib.opus_decoder_destroy.restype = None
    lib.opus_decoder_destroy.argtypes = [P]
    lib.opus_encoder_create.restype = P
    lib.opus_encoder_create.argtypes = [
        ctypes.c_int32, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int),
    ]
    lib.opus_encode.restype = ctypes.c_int32
    lib.opus_encode.argtypes = [
        P, P, ctypes.c_int, ctypes.c_char_p, ctypes.c_int32
    ]
    lib.opus_encoder_destroy.restype = None
    lib.opus_encoder_destroy.argtypes = [P]
    # varargs ctl: declare the (int request, int value) shape we use.
    lib.opus_encoder_ctl.restype = ctypes.c_int
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


class OpusDecoder:
    """One stream's stateful decoder → mono 48 kHz int16 frames."""

    def __init__(self, channels: int = 1):
        lib = _load()
        if lib is None:
            raise OpusError("libopus not available")
        err = ctypes.c_int(0)
        self._lib = lib
        self._dec = lib.opus_decoder_create(
            SAMPLE_RATE, channels, ctypes.byref(err)
        )
        if not self._dec or err.value != 0:
            raise OpusError(f"opus_decoder_create: {err.value}")
        self.channels = channels
        self._buf = np.zeros(FRAME_SAMPLES * channels * 6, np.int16)

    def decode(self, packet: bytes | None) -> np.ndarray:
        """One packet → int16 PCM [samples]; packet=None runs packet-loss
        concealment for a 20 ms gap."""
        n = self._lib.opus_decode(
            self._dec,
            packet if packet is not None else None,
            len(packet) if packet is not None else 0,
            self._buf.ctypes.data_as(ctypes.c_void_p),
            # PLC (packet=None) synthesizes exactly the frame size asked
            # for — ask for one 20 ms frame, not the whole scratch buffer.
            len(self._buf) // self.channels if packet is not None
            else FRAME_SAMPLES,
            0,
        )
        if n < 0:
            raise OpusError(f"opus_decode: {n}")
        return self._buf[: n * self.channels].copy()

    def close(self):
        if self._dec:
            self._lib.opus_decoder_destroy(self._dec)
            self._dec = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class OpusEncoder:
    """One mixed-output stream's stateful encoder (mono 48 kHz VoIP)."""

    def __init__(self, bitrate: int = 32000, channels: int = 1):
        lib = _load()
        if lib is None:
            raise OpusError("libopus not available")
        err = ctypes.c_int(0)
        self._lib = lib
        self._enc = lib.opus_encoder_create(
            SAMPLE_RATE, channels, OPUS_APPLICATION_VOIP, ctypes.byref(err)
        )
        if not self._enc or err.value != 0:
            raise OpusError(f"opus_encoder_create: {err.value}")
        self.channels = channels
        # varargs call: no argtypes apply, so the pointer MUST be wrapped
        # (a bare Python int would be passed as a truncated 32-bit C int).
        lib.opus_encoder_ctl(
            ctypes.c_void_p(self._enc), OPUS_SET_BITRATE_REQUEST,
            ctypes.c_int(bitrate),
        )
        self._out = ctypes.create_string_buffer(4000)

    def encode(self, pcm: np.ndarray) -> bytes:
        """int16 PCM [FRAME_SAMPLES*channels] → one Opus packet."""
        pcm = np.ascontiguousarray(pcm, np.int16)
        n = self._lib.opus_encode(
            self._enc,
            pcm.ctypes.data_as(ctypes.c_void_p),
            len(pcm) // self.channels,
            self._out,
            len(self._out),
        )
        if n < 0:
            raise OpusError(f"opus_encode: {n}")
        return self._out.raw[:n]

    def close(self):
        if self._enc:
            self._lib.opus_encoder_destroy(self._enc)
            self._enc = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
