"""STUN for ICE-lite (RFC 5389 wire format, RFC 8445 lite role).

Reference parity: the reference's ICE agent lives in Pion
(pion/ice via pkg/rtc/transport.go); LiveKit servers run full ICE. A
public SFU with a fixed address only *needs* the lite role (RFC 8445
§2.5): answer Binding requests on the media socket, never originate
checks. That is exactly the address-validation capability the sealed
transport's punch provides — this module speaks the standard wire for
stock clients.

Only the attributes ICE uses are implemented: USERNAME,
MESSAGE-INTEGRITY (HMAC-SHA1 over the adjusted header), FINGERPRINT
(CRC-32 ^ 0x5354554e), XOR-MAPPED-ADDRESS, USE-CANDIDATE, PRIORITY,
ICE-CONTROLLING/CONTROLLED, ERROR-CODE. Validated against the RFC 5769
test vectors (tests/test_interop_stun.py).
"""

from __future__ import annotations

import hashlib
import hmac
import secrets
import socket
import struct
import zlib
from dataclasses import dataclass, field

MAGIC_COOKIE = 0x2112A442
HEADER_LEN = 20

BINDING_REQUEST = 0x0001
BINDING_SUCCESS = 0x0101
BINDING_ERROR = 0x0111

ATTR_MAPPED_ADDRESS = 0x0001
ATTR_USERNAME = 0x0006
ATTR_MESSAGE_INTEGRITY = 0x0008
ATTR_ERROR_CODE = 0x0009
ATTR_XOR_MAPPED_ADDRESS = 0x0020
ATTR_PRIORITY = 0x0024
ATTR_USE_CANDIDATE = 0x0025
ATTR_FINGERPRINT = 0x8028
ATTR_ICE_CONTROLLED = 0x8029
ATTR_ICE_CONTROLLING = 0x802A

FINGERPRINT_XOR = 0x5354554E


def is_stun(data: bytes) -> bool:
    """RFC 5764 §5.1.2 demux: first byte 0-3 + magic cookie."""
    return (
        len(data) >= HEADER_LEN
        and data[0] < 4
        and int.from_bytes(data[4:8], "big") == MAGIC_COOKIE
    )


@dataclass
class StunMessage:
    msg_type: int
    txn_id: bytes
    attrs: list[tuple[int, bytes]] = field(default_factory=list)
    # Set by parse_stun when the wire message carried them (verification
    # needs the raw bytes up to each attribute's offset).
    integrity_ok: bool | None = None
    fingerprint_ok: bool | None = None

    def attr(self, typ: int) -> bytes | None:
        for t, v in self.attrs:
            if t == typ:
                return v
        return None

    @property
    def username(self) -> str | None:
        u = self.attr(ATTR_USERNAME)
        return u.decode("utf-8", "replace") if u is not None else None


def _pad4(n: int) -> int:
    return (n + 3) & ~3


def parse_stun(data: bytes, integrity_key: bytes | None = None) -> StunMessage | None:
    """Parse + (optionally) verify MESSAGE-INTEGRITY and FINGERPRINT.

    Integrity per RFC 5389 §15.4: HMAC-SHA1 over the message up to (not
    including) the integrity attribute, with the header's length field
    rewritten to end just after that attribute.
    """
    if not is_stun(data):
        return None
    msg_type, length = struct.unpack("!HH", data[:4])
    if HEADER_LEN + length != len(data) or length % 4:
        return None
    txn_id = data[8:20]
    attrs: list[tuple[int, bytes]] = []
    msg = StunMessage(msg_type, txn_id, attrs)
    off = HEADER_LEN
    while off + 4 <= len(data):
        t, alen = struct.unpack("!HH", data[off : off + 4])
        val = data[off + 4 : off + 4 + alen]
        if len(val) < alen:
            return None
        if t == ATTR_MESSAGE_INTEGRITY and integrity_key is not None:
            adjusted = (
                struct.pack("!HH", msg_type, off + 4 + 20 - HEADER_LEN)
                + data[4:off]
            )
            want = hmac.new(integrity_key, adjusted, hashlib.sha1).digest()
            msg.integrity_ok = hmac.compare_digest(want, val)
        elif t == ATTR_FINGERPRINT:
            crc = zlib.crc32(
                struct.pack("!HH", msg_type, off + 4 + 4 - HEADER_LEN)
                + data[4:off]
            ) ^ FINGERPRINT_XOR
            msg.fingerprint_ok = val == struct.pack("!I", crc & 0xFFFFFFFF)
        attrs.append((t, val))
        off += 4 + _pad4(alen)
    return msg


def _xor_address(addr: tuple, txn_id: bytes) -> bytes:
    # AF_INET6 sockets report 4-tuples (host, port, flowinfo, scope_id).
    ip, port = addr[0], addr[1]
    xport = port ^ (MAGIC_COOKIE >> 16)
    if ":" in ip:
        # Dual-stack sockets report v4 peers as ::ffff:a.b.c.d and
        # link-local peers with a %zone suffix — unmap/strip before
        # encoding so v4 clients get a family-0x01 address they can route.
        ip = ip.split("%", 1)[0]
        if ip.lower().startswith("::ffff:") and "." in ip:
            ip = ip.rsplit(":", 1)[1]
    if ":" in ip:
        # RFC 5389 §15.2 family 0x02: 128-bit address XORed against
        # magic-cookie ‖ transaction-id.
        packed = socket.inet_pton(socket.AF_INET6, ip)
        mask = struct.pack("!I", MAGIC_COOKIE) + txn_id
        family = 0x02
    else:
        packed = socket.inet_pton(socket.AF_INET, ip)
        mask = struct.pack("!I", MAGIC_COOKIE)
        family = 0x01
    xip = bytes(a ^ b for a, b in zip(packed, mask))
    return struct.pack("!BBH", 0, family, xport) + xip


def build_message(
    msg_type: int,
    txn_id: bytes,
    attrs: list[tuple[int, bytes]],
    integrity_key: bytes | None = None,
    fingerprint: bool = True,
) -> bytes:
    body = b"".join(
        struct.pack("!HH", t, len(v)) + v + b"\x00" * (_pad4(len(v)) - len(v))
        for t, v in attrs
    )
    if integrity_key is not None:
        hdr = struct.pack(
            "!HHI", msg_type, len(body) + 24, MAGIC_COOKIE
        ) + txn_id
        mac = hmac.new(integrity_key, hdr + body, hashlib.sha1).digest()
        body += struct.pack("!HH", ATTR_MESSAGE_INTEGRITY, 20) + mac
    if fingerprint:
        hdr = struct.pack(
            "!HHI", msg_type, len(body) + 8, MAGIC_COOKIE
        ) + txn_id
        crc = (zlib.crc32(hdr + body) ^ FINGERPRINT_XOR) & 0xFFFFFFFF
        body += struct.pack("!HHI", ATTR_FINGERPRINT, 4, crc)
    return (
        struct.pack("!HHI", msg_type, len(body), MAGIC_COOKIE) + txn_id + body
    )


def build_binding_response(
    req: StunMessage, src_addr: tuple[str, int], integrity_key: bytes
) -> bytes:
    """ICE-lite answer: success + XOR-MAPPED-ADDRESS, integrity under the
    local ice-pwd (short-term credential)."""
    return build_message(
        BINDING_SUCCESS,
        req.txn_id,
        [(ATTR_XOR_MAPPED_ADDRESS, _xor_address(src_addr, req.txn_id))],
        integrity_key=integrity_key,
    )


def build_binding_request(
    username: str, integrity_key: bytes, controlling: bool = True,
    use_candidate: bool = True, priority: int = 1 << 24,
) -> bytes:
    """Client-side request (tests + the gateway's keepalive probes)."""
    attrs: list[tuple[int, bytes]] = [
        (ATTR_USERNAME, username.encode()),
        (
            ATTR_ICE_CONTROLLING if controlling else ATTR_ICE_CONTROLLED,
            secrets.token_bytes(8),
        ),
        (ATTR_PRIORITY, struct.pack("!I", priority)),
    ]
    if use_candidate and controlling:
        attrs.append((ATTR_USE_CANDIDATE, b""))
    return build_message(
        BINDING_REQUEST, secrets.token_bytes(12), attrs,
        integrity_key=integrity_key,
    )
