"""SRTP/SRTCP with AEAD_AES_128_GCM (RFC 3711 framework, RFC 7714 AEAD).

Reference parity: the reference's media packets ride pion/srtp contexts
created from the DTLS-SRTP exporter (pkg/rtc/transport.go DTLS role →
srtp.Config). This is the same protection profile WebRTC negotiates by
default (SRTP_AEAD_AES_128_GCM, profile 0x0007).

Implements:
  * RFC 3711 §4.3 key derivation (AES-CM PRF) for the AEAD profile's
    key/salt lengths (RFC 7714 §5.1: 16-byte key, 12-byte salt).
  * RFC 7714 §8/§9 RTP+RTCP IV construction, AAD, encrypt/decrypt.
  * ROC (rollover counter) estimation per RFC 3711 §3.3.1 and a 64-bit
    replay window for inbound streams.

Validated against the RFC 7714 §16/§17 test vectors
(tests/test_interop_srtp.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes
from cryptography.hazmat.primitives.ciphers.aead import AESGCM

PROFILE_AEAD_AES_128_GCM = 0x0007
KEY_LEN = 16
SALT_LEN = 12
TAG_LEN = 16

LABEL_RTP_KEY = 0x00
LABEL_RTP_SALT = 0x02
LABEL_RTCP_KEY = 0x03
LABEL_RTCP_SALT = 0x05


def _aes_cm_derive(master_key: bytes, master_salt: bytes, label: int,
                   out_len: int) -> bytes:
    """RFC 3711 §4.3.1/§4.3.3 key derivation (kdr = 0)."""
    x = bytearray(master_salt + b"\x00\x00")        # salt is 112-bit aligned
    x[7] ^= label
    enc = Cipher(algorithms.AES(master_key), modes.ECB()).encryptor()
    out = b""
    block = 0
    while len(out) < out_len:
        ctr = bytes(x[:14]) + block.to_bytes(2, "big")
        out += enc.update(ctr)
        block += 1
    return out[:out_len]


def derive_srtp_keys(master_key: bytes, master_salt: bytes):
    """master (from the DTLS-SRTP exporter) → (rtp_key, rtp_salt,
    rtcp_key, rtcp_salt)."""
    return (
        _aes_cm_derive(master_key, master_salt, LABEL_RTP_KEY, KEY_LEN),
        _aes_cm_derive(master_key, master_salt, LABEL_RTP_SALT, SALT_LEN),
        _aes_cm_derive(master_key, master_salt, LABEL_RTCP_KEY, KEY_LEN),
        _aes_cm_derive(master_key, master_salt, LABEL_RTCP_SALT, SALT_LEN),
    )


def _estimate_roc(roc: int, s_l: int, seq: int) -> int:
    """RFC 3711 §3.3.1 ROC estimate, SIGNED (caller masks for the IV).

    Run on BOTH sides: the receiver to guess an inbound packet's ROC, and
    the sender on its own stream — protecting each packet under exactly
    the value a standard receiver will guess is the only choice that
    keeps the two in lockstep for every SN pattern (wraps with arbitrary
    gaps, cross-wrap RTX, app-level jumps)."""
    if s_l < 32768:
        return roc - 1 if seq - s_l > 32768 else roc
    return roc + 1 if s_l - seq > 32768 else roc


def _replay_accept(cur: int, window: int, started: bool, idx: int):
    """64-bit sliding replay window (RFC 3711 §3.3.2) over a monotone
    packet index — shared by the SRTP ((roc<<16)|seq) and SRTCP (31-bit
    index) paths. Returns (accepted, new_highest, new_window)."""
    if not started:
        return True, idx, 1
    if idx > cur:
        shift = idx - cur
        return True, idx, ((window << min(shift, 64)) | 1) & ((1 << 64) - 1)
    off = cur - idx
    if off >= 64 or (window >> off) & 1:
        return False, cur, window
    return True, cur, window | (1 << off)


def _rtp_iv(salt: bytes, ssrc: int, roc: int, seq: int) -> bytes:
    """RFC 7714 §8.1: 12-byte IV = (0²‖ssrc‖roc‖seq) XOR salt."""
    raw = (
        b"\x00\x00"
        + ssrc.to_bytes(4, "big")
        + roc.to_bytes(4, "big")
        + seq.to_bytes(2, "big")
    )
    return bytes(a ^ b for a, b in zip(raw, salt))


def _rtcp_iv(salt: bytes, ssrc: int, index: int) -> bytes:
    """RFC 7714 §9.1: IV = (0²‖ssrc‖0²‖0‖31-bit index) XOR salt."""
    raw = (
        b"\x00\x00"
        + ssrc.to_bytes(4, "big")
        + b"\x00\x00"
        + index.to_bytes(4, "big")
    )
    return bytes(a ^ b for a, b in zip(raw, salt))


@dataclass
class SrtpSession:
    """One direction's SRTP+SRTCP protection contexts."""

    master_key: bytes
    master_salt: bytes
    rtp_key: bytes = b""
    rtp_salt: bytes = b""
    rtcp_key: bytes = b""
    rtcp_salt: bytes = b""
    # Outbound state
    rtcp_index: int = 0
    # Inbound per-SSRC ROC/replay state: ssrc → [roc, highest_seq, window]
    _rx: dict = field(default_factory=dict)
    # Outbound per-SSRC ROC: ssrc → [roc, highest_seq, started] — st[1]
    # must stay the HIGHEST SN of the current ROC era (backward/RTX steps
    # leave it untouched), or the wrap detection desyncs.
    _tx: dict = field(default_factory=dict)
    # Inbound SRTCP replay state (RFC 3711 §3.3.2): ssrc →
    # [highest_index, window, started]
    _rx_rtcp: dict = field(default_factory=dict)

    def __post_init__(self):
        (self.rtp_key, self.rtp_salt, self.rtcp_key, self.rtcp_salt) = (
            derive_srtp_keys(self.master_key, self.master_salt)
        )
        self._rtp_aead = AESGCM(self.rtp_key)
        self._rtcp_aead = AESGCM(self.rtcp_key)

    # -- RTP --------------------------------------------------------------
    def protect_rtp(self, packet: bytes, roc: int | None = None) -> bytes:
        """Clear RTP → SRTP. ROC tracks per-SSRC automatically; pass an
        explicit roc for vector tests."""
        hdr_len = self._rtp_header_len(packet)
        seq = int.from_bytes(packet[2:4], "big")
        ssrc = int.from_bytes(packet[8:12], "big")
        if roc is None:
            st = self._tx.setdefault(ssrc, [0, seq, False])
            sguess = _estimate_roc(st[0], st[1], seq) if st[2] else st[0]
            roc = sguess & 0xFFFFFFFF
            # Advance exactly like the receiver does (signed index so a
            # roc-1 guess at roc=0 can't masquerade as a huge step).
            if not st[2] or ((sguess << 16) | seq) > ((st[0] << 16) | st[1]):
                st[0], st[1] = roc, seq
            st[2] = True
        iv = _rtp_iv(self.rtp_salt, ssrc, roc, seq)
        ct = self._rtp_aead.encrypt(iv, packet[hdr_len:], packet[:hdr_len])
        return packet[:hdr_len] + ct

    def unprotect_rtp(self, packet: bytes, roc: int | None = None) -> bytes | None:
        """SRTP → clear RTP, or None (bad tag / replay). ROC estimation
        per RFC 3711 §3.3.1; 64-bit replay window."""
        if len(packet) < 12 + TAG_LEN:
            return None
        hdr_len = self._rtp_header_len(packet)
        seq = int.from_bytes(packet[2:4], "big")
        ssrc = int.from_bytes(packet[8:12], "big")
        if roc is not None:
            sguess = roc
            st = None
        else:
            st = self._rx.setdefault(ssrc, [0, seq, 0, False])
            sguess = _estimate_roc(st[0], st[1], seq) if st[3] else st[0]
        iv = _rtp_iv(self.rtp_salt, ssrc, sguess & 0xFFFFFFFF, seq)
        try:
            pt = self._rtp_aead.decrypt(iv, packet[hdr_len:], packet[:hdr_len])
        except Exception:  # InvalidTag
            return None
        if st is not None:
            # Signed index: a roc-1 guess at roc=0 goes negative and is
            # (correctly) rejected as too old, instead of wrapping into an
            # astronomically-large index that would corrupt the state.
            idx = (sguess << 16) | seq
            cur = (st[0] << 16) | st[1]
            ok, new_cur, st[2] = _replay_accept(cur, st[2], st[3], idx)
            if not ok:
                return None  # replay
            st[0], st[1], st[3] = new_cur >> 16, new_cur & 0xFFFF, True
        return packet[:hdr_len] + pt

    @staticmethod
    def _rtp_header_len(packet: bytes) -> int:
        cc = packet[0] & 0x0F
        n = 12 + 4 * cc
        if packet[0] & 0x10 and len(packet) >= n + 4:  # extension
            ext_words = int.from_bytes(packet[n + 2 : n + 4], "big")
            n += 4 + 4 * ext_words
        return n

    # -- RTCP -------------------------------------------------------------
    def protect_rtcp(self, packet: bytes, index: int | None = None) -> bytes:
        """Clear RTCP → SRTCP (E=1). AAD = header ‖ E+index trailer
        (RFC 7714 §9.3)."""
        if index is None:
            self.rtcp_index = (self.rtcp_index + 1) & 0x7FFFFFFF
            index = self.rtcp_index
        ssrc = int.from_bytes(packet[4:8], "big")
        iv = _rtcp_iv(self.rtcp_salt, ssrc, index)
        trailer = ((1 << 31) | index).to_bytes(4, "big")
        aad = packet[:8] + trailer
        ct = self._rtcp_aead.encrypt(iv, packet[8:], aad)
        return packet[:8] + ct + trailer

    def unprotect_rtcp(self, packet: bytes) -> bytes | None:
        if len(packet) < 8 + TAG_LEN + 4:
            return None
        trailer = packet[-4:]
        index = int.from_bytes(trailer, "big") & 0x7FFFFFFF
        if not packet[-4] & 0x80:
            return None  # unencrypted SRTCP not accepted
        ssrc = int.from_bytes(packet[4:8], "big")
        iv = _rtcp_iv(self.rtcp_salt, ssrc, index)
        aad = packet[:8] + trailer
        try:
            pt = self._rtcp_aead.decrypt(iv, packet[8:-4], aad)
        except Exception:
            return None
        # SRTCP replay protection (RFC 3711 §3.3.2): sliding 64-bit window
        # over the 31-bit index, per sender SSRC — checked only after the
        # tag authenticates, so an attacker can't poison the window.
        st = self._rx_rtcp.setdefault(ssrc, [0, 0, False])
        ok, st[0], st[1] = _replay_accept(st[0], st[1], st[2], index)
        if not ok:
            return None  # replayed or too-old index
        st[2] = True
        return packet[:8] + pt
