"""Minimal SDP offer/answer for the WebRTC gateway (RFC 8866 + JSEP).

Reference parity: the reference negotiates SDP through Pion with LiveKit
fixups (pkg/rtc/participant_sdp.go codec/extension munging,
pkg/rtc/mediaengine.go:30-150 registered codecs). This module implements
the subset the ICE-lite gateway needs:

  * parse a browser offer — ICE credentials, DTLS fingerprint + setup
    role, BUNDLE group, per-m-section codecs (rtpmap/fmtp), header
    extensions, SSRCs (incl. simulcast groups), directions;
  * build the answer — ICE-lite, our fingerprint, `a=setup:passive`
    (the offerer is always the DTLS client then), rtcp-mux, one host
    candidate, and OUR canonical payload-type numbers for the codecs
    both sides support (per RFC 3264 the peer sends with the PT map
    from its remote description — i.e. ours — which keeps the wire PTs
    aligned with the fixed demux map in runtime/udp.py).

Header extensions are answered only when the offered id matches the
server's fixed id (runtime/udp.py AUDIO_LEVEL_EXT_ID etc.); mismatched
ids are omitted rather than remapped — the native parser reads fixed
ids, and JSEP permits the answerer to reject any extension.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# Canonical codec names → our fixed payload types (runtime/udp.py).
CODEC_PT = {
    "vp8": 96,
    "vp9": 98,
    "av1": 99,
    "h264": 100,
    "opus": 111,
    "red": 63,
}
CLOCK = {"vp8": 90000, "vp9": 90000, "av1": 90000, "h264": 90000,
         "opus": 48000, "red": 48000}
CHANNELS = {"opus": 2, "red": 2}
# Our fixed header-extension ids (must mirror runtime/udp.py).
EXT_IDS = {
    "urn:ietf:params:rtp-hdrext:ssrc-audio-level": 1,
    "http://www.webrtc.org/experiments/rtp-hdrext/playout-delay": 6,
    "https://aomediacodec.org/av1-rtp-spec/#dependency-descriptor": 8,
}


@dataclass
class MediaSection:
    kind: str                      # "audio" | "video" | other (rejected)
    mid: str = ""
    port: int = 9
    codecs: dict = field(default_factory=dict)    # pt -> codec name (lower)
    fmtp: dict = field(default_factory=dict)      # pt -> fmtp line
    extmap: dict = field(default_factory=dict)    # id -> uri
    ssrcs: list = field(default_factory=list)     # declared SSRCs, in order
    ssrc_groups: list = field(default_factory=list)  # (semantics, [ssrc...])
    direction: str = "sendrecv"
    ice_ufrag: str = ""
    ice_pwd: str = ""
    fingerprint: str = ""          # "sha-256 AB:CD:..."
    setup: str = ""
    rtcp_mux: bool = False

    def pts_for(self, name: str) -> list[int]:
        return [pt for pt, c in self.codecs.items() if c == name]


@dataclass
class SessionDesc:
    media: list = field(default_factory=list)
    bundle: list = field(default_factory=list)
    ice_ufrag: str = ""
    ice_pwd: str = ""
    fingerprint: str = ""
    setup: str = ""
    ice_lite: bool = False

    def media_ufrag(self, m: MediaSection) -> str:
        return m.ice_ufrag or self.ice_ufrag

    def media_pwd(self, m: MediaSection) -> str:
        return m.ice_pwd or self.ice_pwd

    def media_fingerprint(self, m: MediaSection) -> str:
        return m.fingerprint or self.fingerprint


def parse_sdp(text: str) -> SessionDesc:
    sess = SessionDesc()
    cur: MediaSection | None = None
    for raw in text.replace("\r\n", "\n").split("\n"):
        line = raw.strip()
        if len(line) < 2 or line[1] != "=":
            continue
        typ, val = line[0], line[2:]
        if typ == "m":
            parts = val.split()
            cur = MediaSection(kind=parts[0])
            try:
                cur.port = int(parts[1])
            except (IndexError, ValueError):
                pass
            sess.media.append(cur)
        elif typ != "a":
            continue
        elif val.startswith("group:BUNDLE"):
            sess.bundle = val.split()[1:]
        elif val == "ice-lite":
            sess.ice_lite = True
        else:
            _parse_attr(sess, cur, val)
    return sess


def _parse_attr(sess: SessionDesc, m: MediaSection | None, val: str) -> None:
    tgt = m if m is not None else sess
    if val.startswith("ice-ufrag:"):
        tgt.ice_ufrag = val[10:]
    elif val.startswith("ice-pwd:"):
        tgt.ice_pwd = val[8:]
    elif val.startswith("fingerprint:"):
        tgt.fingerprint = val[12:]
    elif val.startswith("setup:"):
        tgt.setup = val[6:]
    elif m is None:
        return
    elif val.startswith("mid:"):
        m.mid = val[4:]
    elif val == "rtcp-mux":
        m.rtcp_mux = True
    elif val in ("sendrecv", "sendonly", "recvonly", "inactive"):
        m.direction = val
    elif val.startswith("rtpmap:"):
        try:
            pt_s, spec = val[7:].split(" ", 1)
            m.codecs[int(pt_s)] = spec.split("/")[0].lower()
        except ValueError:
            pass
    elif val.startswith("fmtp:"):
        try:
            pt_s, params = val[5:].split(" ", 1)
            m.fmtp[int(pt_s)] = params
        except ValueError:
            pass
    elif val.startswith("extmap:"):
        try:
            id_s, uri = val[7:].split(" ", 1)
            m.extmap[int(id_s.split("/")[0])] = uri.strip()
        except ValueError:
            pass
    elif val.startswith("ssrc-group:"):
        parts = val[11:].split()
        try:
            m.ssrc_groups.append((parts[0], [int(x) for x in parts[1:]]))
        except ValueError:
            pass
    elif val.startswith("ssrc:"):
        try:
            ssrc = int(val[5:].split()[0])
        except (ValueError, IndexError):
            return
        if ssrc not in m.ssrcs:
            m.ssrcs.append(ssrc)


# -- answer construction ----------------------------------------------------

_FMTP = {
    "opus": "minptime=10;useinbandfec=1",
    "vp9": "profile-id=0",
    "h264": (
        "level-asymmetry-allowed=1;packetization-mode=1;"
        "profile-level-id=42e01f"
    ),
}


def _wanted_codecs(m: MediaSection) -> list[str]:
    offered = set(m.codecs.values())
    if m.kind == "audio":
        return [c for c in ("opus", "red") if c in offered]
    if m.kind == "video":
        return [c for c in ("vp8", "vp9", "av1", "h264") if c in offered]
    return []


def build_answer(
    offer: SessionDesc,
    ice_ufrag: str,
    ice_pwd: str,
    fingerprint: str,
    addr: tuple,
    session_id: int = 1,
    ssrc_by_mid: dict | None = None,
) -> str:
    """ICE-lite answer accepting every audio/video m-section whose codec
    list intersects ours. `fingerprint` is the bare hex-colon digest
    (generate_certificate's third return); addr is the media socket's
    (ip, port). `ssrc_by_mid` declares our egress SSRCs inside their
    send-capable m-sections (mid → [ssrc...])."""
    ip, port = addr[0], addr[1]
    lines = [
        "v=0",
        f"o=- {session_id} 2 IN IP4 {ip}",
        "s=-",
        "t=0 0",
        "a=ice-lite",
        "a=msid-semantic: WMS *",
    ]
    mids = [m.mid or str(i) for i, m in enumerate(offer.media)]
    # JSEP: rejected (port-0) m-sections must NOT appear in the BUNDLE
    # group — browsers fail setRemoteDescription otherwise (a stock offer
    # always carries m=application for the datachannel, which we reject).
    accepted_mids = [
        mids[i] for i, m in enumerate(offer.media) if _wanted_codecs(m)
    ]
    if accepted_mids:
        lines.append("a=group:BUNDLE " + " ".join(accepted_mids))
    for i, m in enumerate(offer.media):
        wanted = _wanted_codecs(m)
        if not wanted:
            # Rejected m-section: port 0, repeat the offered PTs (JSEP).
            pts = " ".join(str(pt) for pt in m.codecs) or "0"
            lines.append(f"m={m.kind} 0 UDP/TLS/RTP/SAVPF {pts}")
            lines.append(f"a=mid:{mids[i]}")
            lines.append("a=inactive")
            continue
        pts = [CODEC_PT[c] for c in wanted]
        lines.append(
            f"m={m.kind} {port} UDP/TLS/RTP/SAVPF "
            + " ".join(str(p) for p in pts)
        )
        lines.append(f"c=IN IP4 {ip}")
        lines.append("a=rtcp-mux")
        lines.append(f"a=mid:{mids[i]}")
        lines.append(f"a=ice-ufrag:{ice_ufrag}")
        lines.append(f"a=ice-pwd:{ice_pwd}")
        lines.append(f"a=fingerprint:sha-256 {fingerprint}")
        lines.append("a=setup:passive")
        if m.direction == "sendonly":
            lines.append("a=recvonly")
        elif m.direction == "recvonly":
            lines.append("a=sendonly")
        else:
            lines.append("a=sendrecv")
        for c in wanted:
            pt = CODEC_PT[c]
            clock = CLOCK[c]
            ch = CHANNELS.get(c)
            spec = f"{c.upper() if c != 'opus' else 'opus'}/{clock}"
            if c == "av1":
                spec = f"AV1/{clock}"
            if ch:
                spec += f"/{ch}"
            lines.append(f"a=rtpmap:{pt} {spec}")
            if c == "red":
                lines.append(f"a=fmtp:{pt} {CODEC_PT['opus']}/{CODEC_PT['opus']}")
            elif c in _FMTP:
                lines.append(f"a=fmtp:{pt} {_FMTP[c]}")
            if c in ("vp8", "vp9", "h264", "av1"):
                lines.append(f"a=rtcp-fb:{pt} nack")
                lines.append(f"a=rtcp-fb:{pt} nack pli")
                lines.append(f"a=rtcp-fb:{pt} goog-remb")
        # Extensions: only ids that already match our fixed map.
        for ext_id, uri in sorted(m.extmap.items()):
            if EXT_IDS.get(uri) == ext_id:
                lines.append(f"a=extmap:{ext_id} {uri}")
        # Our egress SSRCs, declared inside THIS section (receivers map
        # streams per m-section; a global append would misattribute them).
        for ssrc in (ssrc_by_mid or {}).get(mids[i], []):
            lines.append(f"a=ssrc:{ssrc} cname:tpu-sfu")
        lines.append(
            f"a=candidate:1 1 udp 2130706431 {ip} {port} typ host"
        )
        lines.append("a=end-of-candidates")
    return "\r\n".join(lines) + "\r\n"
