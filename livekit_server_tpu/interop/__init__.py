"""Standard-client wire interop: ICE-lite/STUN, DTLS 1.2, SRTP, SDP.

Reference parity: the reference terminates real WebRTC via Pion —
ICE/DTLS/SRTP (pkg/rtc/transport.go:167-374), media engine codec
negotiation (pkg/rtc/mediaengine.go:30-150), TURN (pkg/service/turn.go).
This package is the thin gateway the r3 verdict asked for: it terminates
the standard wire (STUN connectivity checks, DTLS-SRTP key exchange,
SRTP packet protection, SDP offer/answer) in front of the UNCHANGED
sealed media plane, plugging in at the runtime/udp.py
assign_ssrc/register_subscriber seam.

Interop validation without a browser in the image: DTLS handshakes are
exercised against OpenSSL's independent stack (`openssl s_client
-dtls1_2 -use_srtp`), SRTP against RFC 7714 test vectors, STUN against
RFC 5769 test vectors.
"""

from livekit_server_tpu.interop.stun import (  # noqa: F401
    StunMessage,
    build_binding_response,
    parse_stun,
)
