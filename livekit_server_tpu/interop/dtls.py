"""DTLS-SRTP endpoint over OpenSSL (libssl.so.3) via ctypes.

Reference parity: the reference terminates real WebRTC DTLS through Pion
(pkg/rtc/transport.go:253-374 — DTLS handshake → SRTP key export →
pion/srtp contexts). This module is the same seam for the TPU SFU: an
in-memory DTLS state machine (datagrams in/out, no sockets of its own)
that negotiates `use_srtp` (RFC 5764) and exports AEAD_AES_128_GCM
keying material for `interop.srtp.SrtpSession`.

Design notes
  * ctypes against the system libssl/libcrypto — this image ships no
    OpenSSL headers, so a compiled shim is not an option; the crypto
    itself still runs in OpenSSL's C, only the BIO plumbing is Python.
  * Memory BIOs carry the handshake: DTLS records are self-framing, so
    the transport (runtime/udp.py) just feeds received datagrams in and
    ships produced records out. Flights are split on record boundaries
    into ≤ MTU-ish datagrams for the wire.
  * The server side is ICE-gated (the gateway only feeds DTLS from
    addresses that passed a STUN binding with our ice-pwd), so the
    DTLSv1_listen cookie exchange is deliberately skipped — same
    stance as Pion's ICE-integrated DTLS.
  * Certificates are ephemeral self-signed ECDSA P-256 (what browsers
    generate); authentication is by SDP fingerprint pinning (RFC 8122),
    not CA chains.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import datetime
import hashlib
import threading

__all__ = [
    "DtlsEndpoint",
    "DtlsError",
    "SRTP_PROFILE_NAME",
    "generate_certificate",
    "is_dtls",
]

SRTP_PROFILE_NAME = b"SRTP_AEAD_AES_128_GCM"
SRTP_PROFILE_ID = 0x0007  # RFC 7714 DTLS-SRTP protection profile id
KEY_LEN = 16
SALT_LEN = 12
EXPORT_LABEL = b"EXTRACTOR-dtls_srtp"  # RFC 5764 §4.2
MTU = 1200

# libssl constants
SSL_ERROR_WANT_READ = 2
SSL_ERROR_WANT_WRITE = 3
SSL_ERROR_ZERO_RETURN = 6
SSL_VERIFY_PEER = 0x01
SSL_OP_NO_QUERY_MTU = 0x00001000
SSL_CTRL_SET_MTU = 17
DTLS_CTRL_GET_TIMEOUT = 73
DTLS_CTRL_HANDLE_TIMEOUT = 74
BIO_C_SET_BUF_MEM_EOF_RETURN = 130


def is_dtls(data: bytes) -> bool:
    """RFC 7983 §7 demux: first byte in [20, 63]."""
    return len(data) > 0 and 20 <= data[0] <= 63


class DtlsError(Exception):
    pass


class _Lib:
    """Lazy singleton for the libssl/libcrypto handles + prototypes."""

    _instance = None
    _lock = threading.Lock()

    @classmethod
    def get(cls) -> "_Lib":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def __init__(self):
        self.ssl = ctypes.CDLL("libssl.so.3")
        self.crypto = ctypes.CDLL("libcrypto.so.3")
        s, c = self.ssl, self.crypto
        P = ctypes.c_void_p
        for name, res, arg in [
            ("DTLS_method", P, []),
            ("SSL_CTX_new", P, [P]),
            ("SSL_CTX_free", None, [P]),
            ("SSL_CTX_use_certificate", ctypes.c_int, [P, P]),
            ("SSL_CTX_use_PrivateKey", ctypes.c_int, [P, P]),
            ("SSL_CTX_set_tlsext_use_srtp", ctypes.c_int, [P, ctypes.c_char_p]),
            ("SSL_CTX_set_verify", None, [P, ctypes.c_int, P]),
            ("SSL_CTX_set_options", ctypes.c_uint64, [P, ctypes.c_uint64]),
            ("SSL_new", P, [P]),
            ("SSL_free", None, [P]),
            ("SSL_set_bio", None, [P, P, P]),
            ("SSL_set_accept_state", None, [P]),
            ("SSL_set_connect_state", None, [P]),
            ("SSL_do_handshake", ctypes.c_int, [P]),
            ("SSL_get_error", ctypes.c_int, [P, ctypes.c_int]),
            ("SSL_is_init_finished", ctypes.c_int, [P]),
            ("SSL_read", ctypes.c_int, [P, P, ctypes.c_int]),
            ("SSL_write", ctypes.c_int, [P, P, ctypes.c_int]),
            ("SSL_ctrl", ctypes.c_long, [P, ctypes.c_int, ctypes.c_long, P]),
            ("SSL_get_selected_srtp_profile", P, [P]),
            ("SSL_export_keying_material", ctypes.c_int,
             [P, P, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
              P, ctypes.c_size_t, ctypes.c_int]),
            ("SSL_get1_peer_certificate", P, [P]),
            ("SSL_shutdown", ctypes.c_int, [P]),
        ]:
            f = getattr(s, name)
            f.restype, f.argtypes = res, arg
        for name, res, arg in [
            ("BIO_new", P, [P]),
            ("BIO_s_mem", P, []),
            ("BIO_free", ctypes.c_int, [P]),
            ("BIO_write", ctypes.c_int, [P, P, ctypes.c_int]),
            ("BIO_read", ctypes.c_int, [P, P, ctypes.c_int]),
            ("BIO_ctrl_pending", ctypes.c_size_t, [P]),
            ("BIO_ctrl", ctypes.c_long, [P, ctypes.c_int, ctypes.c_long, P]),
            ("PEM_read_bio_X509", P, [P, P, P, P]),
            ("PEM_read_bio_PrivateKey", P, [P, P, P, P]),
            ("X509_free", None, [P]),
            ("EVP_PKEY_free", None, [P]),
            ("X509_digest", ctypes.c_int,
             [P, P, P, ctypes.POINTER(ctypes.c_uint)]),
            ("EVP_sha256", P, []),
            ("ERR_get_error", ctypes.c_ulong, []),
            ("ERR_error_string_n", None,
             [ctypes.c_ulong, ctypes.c_char_p, ctypes.c_size_t]),
        ]:
            f = getattr(c, name)
            f.restype, f.argtypes = res, arg
        # The verify callback must outlive every SSL_CTX using it.
        self.verify_cb = ctypes.CFUNCTYPE(
            ctypes.c_int, ctypes.c_int, ctypes.c_void_p
        )(lambda ok, store: 1)  # fingerprint pinning replaces CA checks

    def last_error(self) -> str:
        buf = ctypes.create_string_buffer(256)
        e = self.crypto.ERR_get_error()
        if not e:
            return "no OpenSSL error queued"
        self.crypto.ERR_error_string_n(e, buf, 256)
        return buf.value.decode("ascii", "replace")


_SRTP_PROFILE_STRUCT_ID_OFFSET = ctypes.sizeof(ctypes.c_void_p)


def generate_certificate(common_name: str = "tpu-sfu") -> tuple[bytes, bytes, str]:
    """Ephemeral self-signed ECDSA P-256 cert (what WebRTC stacks mint).

    Returns (cert_pem, key_pem, sha256_fingerprint) with the fingerprint
    in SDP `a=fingerprint` form (upper-hex, colon-separated, RFC 8122).
    """
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(days=1))
        .not_valid_after(now + datetime.timedelta(days=30))
        .sign(key, hashes.SHA256())
    )
    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    fp = cert.fingerprint(hashes.SHA256()).hex().upper()
    fingerprint = ":".join(fp[i : i + 2] for i in range(0, len(fp), 2))
    return cert_pem, key_pem, fingerprint


def _split_records(blob: bytes, mtu: int = MTU) -> list[bytes]:
    """Split a mem-BIO drain (possibly several coalesced DTLS records)
    into wire datagrams: records are grouped greedily up to ~mtu, never
    split mid-record (a record must arrive whole in one datagram)."""
    out: list[bytes] = []
    cur = b""
    off = 0
    n = len(blob)
    while off + 13 <= n:
        rec_len = 13 + int.from_bytes(blob[off + 11 : off + 13], "big")
        rec = blob[off : off + rec_len]
        if cur and len(cur) + len(rec) > mtu:
            out.append(cur)
            cur = b""
        cur += rec
        off += rec_len
    if off < n:  # trailing garbage / truncated record: ship as-is
        cur += blob[off:]
    if cur:
        out.append(cur)
    return out


class DtlsEndpoint:
    """One DTLS association as a pure datagram state machine.

    Usage:
        ep = DtlsEndpoint(role="server", cert_pem=..., key_pem=...)
        to_wire = ep.pump()              # client role: initial flight
        to_wire = ep.feed(datagram)      # on every received datagram
        if ep.handshake_complete: keys = ep.export_srtp_keys()
    """

    def __init__(
        self,
        role: str,
        cert_pem: bytes,
        key_pem: bytes,
        peer_fingerprint: str | None = None,
    ):
        if role not in ("server", "client"):
            raise ValueError(role)
        self.role = role
        self.peer_fingerprint = peer_fingerprint
        self.handshake_complete = False
        self._lib = _Lib.get()
        s, c = self._lib.ssl, self._lib.crypto

        self._ctx = s.SSL_CTX_new(s.DTLS_method())
        if not self._ctx:
            raise DtlsError(f"SSL_CTX_new: {self._lib.last_error()}")
        try:
            # Cert + key from PEM through mem BIOs (no temp files).
            x509 = self._pem_obj(cert_pem, c.PEM_read_bio_X509)
            try:
                if s.SSL_CTX_use_certificate(self._ctx, x509) != 1:
                    raise DtlsError(
                        f"use_certificate: {self._lib.last_error()}"
                    )
            finally:
                c.X509_free(x509)
            pkey = self._pem_obj(key_pem, c.PEM_read_bio_PrivateKey)
            try:
                if s.SSL_CTX_use_PrivateKey(self._ctx, pkey) != 1:
                    raise DtlsError(
                        f"use_PrivateKey: {self._lib.last_error()}"
                    )
            finally:
                c.EVP_PKEY_free(pkey)
            # use_srtp returns 0 on SUCCESS (documented quirk).
            if s.SSL_CTX_set_tlsext_use_srtp(self._ctx, SRTP_PROFILE_NAME):
                raise DtlsError(
                    f"set_tlsext_use_srtp: {self._lib.last_error()}"
                )
            # WebRTC authenticates by certificate fingerprint from the
            # signalled SDP, not a CA chain: demand a peer cert, accept
            # any chain, pin the digest after the handshake.
            s.SSL_CTX_set_verify(
                self._ctx, SSL_VERIFY_PEER, self._lib.verify_cb
            )
            s.SSL_CTX_set_options(self._ctx, SSL_OP_NO_QUERY_MTU)

            self._ssl = s.SSL_new(self._ctx)
            if not self._ssl:
                raise DtlsError(f"SSL_new: {self._lib.last_error()}")
            self._rbio = c.BIO_new(c.BIO_s_mem())
            self._wbio = c.BIO_new(c.BIO_s_mem())
            # Empty mem BIO must read as retry-later, not EOF.
            c.BIO_ctrl(self._rbio, BIO_C_SET_BUF_MEM_EOF_RETURN, -1, None)
            c.BIO_ctrl(self._wbio, BIO_C_SET_BUF_MEM_EOF_RETURN, -1, None)
            s.SSL_set_bio(self._ssl, self._rbio, self._wbio)  # owns BIOs
            s.SSL_ctrl(self._ssl, SSL_CTRL_SET_MTU, MTU, None)
            if role == "server":
                s.SSL_set_accept_state(self._ssl)
            else:
                s.SSL_set_connect_state(self._ssl)
        except Exception:
            s.SSL_CTX_free(self._ctx)
            self._ctx = None
            raise

    def _pem_obj(self, pem: bytes, reader):
        c = self._lib.crypto
        bio = c.BIO_new(c.BIO_s_mem())
        try:
            c.BIO_write(bio, pem, len(pem))
            obj = reader(bio, None, None, None)
            if not obj:
                raise DtlsError(f"PEM parse: {self._lib.last_error()}")
            return obj
        finally:
            c.BIO_free(bio)

    # -- datagram pump ----------------------------------------------------

    def feed(self, datagram: bytes) -> list[bytes]:
        """Process one received DTLS datagram; returns datagrams to send."""
        if self._ctx is None:
            return []
        c = self._lib.crypto
        buf = ctypes.create_string_buffer(datagram, len(datagram))
        c.BIO_write(self._rbio, buf, len(datagram))
        return self.pump()

    def pump(self) -> list[bytes]:
        """Advance the state machine; returns produced wire datagrams."""
        if self._ctx is None:
            return []
        s = self._lib.ssl
        if not self.handshake_complete:
            ret = s.SSL_do_handshake(self._ssl)
            if ret == 1:
                self._finish_handshake()
            else:
                err = s.SSL_get_error(self._ssl, ret)
                if err not in (SSL_ERROR_WANT_READ, SSL_ERROR_WANT_WRITE):
                    raise DtlsError(
                        f"handshake: ssl_error={err} {self._lib.last_error()}"
                    )
        else:
            # Drain any post-handshake application/alert records so
            # retransmitted flights or close_notify don't wedge the BIO.
            scratch = ctypes.create_string_buffer(4096)
            while s.SSL_read(self._ssl, scratch, 4096) > 0:
                pass
        return self._drain()

    def _drain(self) -> list[bytes]:
        c = self._lib.crypto
        pending = c.BIO_ctrl_pending(self._wbio)
        if not pending:
            return []
        buf = ctypes.create_string_buffer(int(pending))
        n = c.BIO_read(self._wbio, buf, int(pending))
        if n <= 0:
            return []
        return _split_records(buf.raw[:n])

    def handle_timeout(self) -> list[bytes]:
        """DTLS retransmission timer; call at ~every 100 ms while the
        handshake is in flight. Returns retransmitted datagrams."""
        if self._ctx is None or self.handshake_complete:
            return []
        s = self._lib.ssl
        s.SSL_ctrl(self._ssl, DTLS_CTRL_HANDLE_TIMEOUT, 0, None)
        return self._drain()

    def _finish_handshake(self) -> None:
        s = self._lib.ssl
        prof = s.SSL_get_selected_srtp_profile(self._ssl)
        if not prof:
            raise DtlsError("peer did not negotiate use_srtp")
        # SRTP_PROTECTION_PROFILE struct = {const char *name; long id}.
        pid = ctypes.cast(
            ctypes.c_void_p(prof + _SRTP_PROFILE_STRUCT_ID_OFFSET),
            ctypes.POINTER(ctypes.c_ulong),
        ).contents.value
        if pid != SRTP_PROFILE_ID:
            raise DtlsError(f"unexpected SRTP profile {pid:#x}")
        if self.peer_fingerprint is not None:
            got = self.peer_fingerprint_sha256()
            if got is None or got.lower() != self.peer_fingerprint.lower():
                raise DtlsError(
                    f"peer fingerprint mismatch: {got} != "
                    f"{self.peer_fingerprint}"
                )
        self.handshake_complete = True

    # -- post-handshake ---------------------------------------------------

    def peer_fingerprint_sha256(self) -> str | None:
        s, c = self._lib.ssl, self._lib.crypto
        x509 = s.SSL_get1_peer_certificate(self._ssl)
        if not x509:
            return None
        try:
            md = ctypes.create_string_buffer(32)
            n = ctypes.c_uint(0)
            if c.X509_digest(x509, c.EVP_sha256(), md, ctypes.byref(n)) != 1:
                return None
            fp = md.raw[: n.value].hex().upper()
            return ":".join(fp[i : i + 2] for i in range(0, len(fp), 2))
        finally:
            c.X509_free(x509)

    def export_srtp_keys(self):
        """RFC 5764 §4.2 exporter → ((local_key, local_salt),
        (remote_key, remote_salt)) oriented by our role: `local` protects
        what WE send."""
        if not self.handshake_complete:
            raise DtlsError("handshake not complete")
        s = self._lib.ssl
        total = 2 * (KEY_LEN + SALT_LEN)
        out = ctypes.create_string_buffer(total)
        if s.SSL_export_keying_material(
            self._ssl, out, total, EXPORT_LABEL, len(EXPORT_LABEL),
            None, 0, 0,
        ) != 1:
            raise DtlsError(f"export: {self._lib.last_error()}")
        m = out.raw
        ck, sk = m[:KEY_LEN], m[KEY_LEN : 2 * KEY_LEN]
        cs = m[2 * KEY_LEN : 2 * KEY_LEN + SALT_LEN]
        ss = m[2 * KEY_LEN + SALT_LEN :]
        if self.role == "server":
            return (sk, ss), (ck, cs)
        return (ck, cs), (sk, ss)

    def close(self) -> None:
        if self._ctx is None:
            return
        s = self._lib.ssl
        try:
            s.SSL_shutdown(self._ssl)
        finally:
            s.SSL_free(self._ssl)      # frees the BIOs it owns
            s.SSL_CTX_free(self._ctx)
            self._ctx = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass

    @property
    def dtls_cookie_note(self) -> str:
        return (
            "cookie exchange skipped: DTLS is only fed from "
            "STUN-validated addresses (ICE-gated, like Pion's usage)"
        )
