"""Room/session control plane.

Reference parity: pkg/rtc (SURVEY.md §2.4) — Room, ParticipantImpl, signal
dispatch, subscription management, dynacast. Control stays host-side and
thin; every media-affecting decision lands as a mask/state write into the
PlaneRuntime host mirrors (runtime/plane_runtime.py), applied at the next
tick boundary — the TPU replacement for the reference's lock-guarded
object graph mutation.
"""

from livekit_server_tpu.rtc.participant import Participant, PublishedTrack
from livekit_server_tpu.rtc.room import Room
from livekit_server_tpu.rtc.signalhandler import handle_participant_signal

__all__ = ["Participant", "PublishedTrack", "Room", "handle_participant_signal"]
