"""Signal dispatch: SignalRequest variants → participant/room operations.

Reference parity: pkg/rtc/signalhandler.go:24-97 — the switch over the 14
SignalRequest oneof arms. SDP offer/answer and ICE trickle are accepted
and acknowledged at this layer (the media transport in this build binds
publishers by token + slot coordinates rather than DTLS — see
service/media once the UDP path lands); everything else maps 1:1 to the
reference's behavior.
"""

from __future__ import annotations

import time

from livekit_server_tpu.protocol.signal import SignalRequest
from livekit_server_tpu.protocol import models as pm
from livekit_server_tpu.rtc.participant import Participant


def handle_participant_signal(room, participant: Participant, req: SignalRequest) -> None:
    """One inbound signal message (rtcSessionWorker loop body analog)."""
    kind, data = req.kind, req.data

    if kind == "offer":
        # Publisher SDP. A real SDP (carries ICE credentials) negotiates
        # through the standards-lane WebRTC gateway: ICE-lite + DTLS-SRTP
        # on the media socket (runtime/webrtc_gateway.py; the reference's
        # Pion seat, pkg/rtc/transport.go + participant_sdp.go). Anything
        # else keeps the legacy reflect behavior for the slot-addressed
        # sealed transport's protocol-conformant SDKs.
        sdp_text = data.get("sdp", "")
        udp = getattr(room, "udp", None)
        sealed_active = (
            participant.crypto_session is not None
            and getattr(participant.crypto_session, "client_active", False)
        )
        if udp is not None and "a=ice-ufrag" in sdp_text and not sealed_active:
            answer = _negotiate_gateway_offer(room, participant, sdp_text)
            if answer is not None:
                participant.send("answer", {"type": "answer", "sdp": answer})
                return
        participant.send("answer", {"type": "answer", "sdp": sdp_text})
    elif kind == "answer":
        pass  # subscriber-side answer: nothing to reconcile host-side
    elif kind == "trickle":
        pass  # ICE candidates are not used by the slot-addressed transport
    elif kind == "add_track":
        info = participant.add_track_request(data)
        # UDP media: bind the tensor slot now and hand the client an SSRC
        # (the WS-media path instead binds on first BINARY frame).
        udp = getattr(room, "udp", None)
        if info is not None and data.get("transport") == "udp" and udp is not None:
            track = participant.publish_pending(data.get("cid", ""))
            if track is not None:
                # One SSRC per simulcast spatial layer (mediatrack.go layer
                # SSRC bookkeeping); single-layer tracks get exactly one.
                # SVC codecs (VP9/AV1) are single-stream: ONE SSRC, layers
                # ride the dependency descriptor (receiver.go IsSvcCodec).
                is_svc = pm.is_svc_mime(track.info.mime_type, track.is_video)
                n_layers = (
                    1 if is_svc or not track.is_video
                    else max(1, len(track.info.layers))
                )
                layer_ssrcs = [
                    udp.assign_ssrc(
                        room.slots.row, track.track_col, track.is_video, layer=l,
                        session=participant.crypto_session, svc=is_svc,
                        mime=track.info.mime_type,
                    )
                    for l in range(n_layers)
                ]
                track.ssrc = layer_ssrcs[0]
                participant.send(
                    "request_response",
                    {
                        "udp_media": {
                            "track_sid": track.info.sid,
                            "ssrc": layer_ssrcs[0],
                            "layer_ssrcs": layer_ssrcs,
                        }
                    },
                )
    elif kind == "mute":
        sid = data.get("sid", "")
        participant.set_track_muted(sid, bool(data.get("muted", False)))
        participant.send("mute", {"sid": sid, "muted": bool(data.get("muted", False))})
    elif kind == "subscription":
        udp = getattr(room, "udp", None)
        if (
            udp is not None
            and (data.get("udp_addr") or data.get("udp"))
            and participant.sub_col >= 0
        ):
            # A client-supplied address is never registered verbatim (it
            # would let any subscriber aim the server's media stream at a
            # third party — traffic reflection). Hand back a punch id; the
            # address latches when a PUNCH datagram carrying it arrives
            # from the client's actual socket (ICE-consent analog).
            # `udp_repunch` rotates a latched id after a NAT rebind.
            punch = udp.assign_subscriber_punch(
                room.slots.row,
                participant.sub_col,
                rotate=bool(data.get("udp_repunch", False)),
            )
            participant.send("request_response", {"udp_punch": {"punch_id": punch}})
        if udp is not None and participant.sub_col >= 0 and "red" in data:
            # RED capability opt-in (RFC 2198 Opus redundancy; the
            # reference negotiates RED in SDP — redreceiver.go).
            udp.set_sub_red(room.slots.row, participant.sub_col, bool(data["red"]))
        if udp is not None and participant.sub_col >= 0 and "audio_mix" in data:
            # MCU seat opt-in (runtime/mixer.py): the subscriber receives
            # ONE server-mixed Opus stream with their own voice excluded;
            # they typically unsubscribe the individual audio tracks in
            # the same message. An opt-out on a node with no mixer is a
            # no-op — it must not instantiate one.
            mixer = None
            if data["audio_mix"] or udp.audio_mixer is not None:
                try:
                    mixer = udp.enable_audio_mixer()
                except Exception:  # noqa: BLE001 — libopus absent: ignore
                    mixer = None
            if mixer is not None:
                own = next(
                    (t.track_col for t in participant.published.values()
                     if not t.is_video),
                    -1,
                )
                mixer.enable_sub(
                    room.slots.row, participant.sub_col,
                    bool(data["audio_mix"]), exclude_track=own,
                )
        for sid in data.get("track_sids", []):
            if data.get("subscribe", True):
                room.subscribe(participant, sid)
            else:
                room.unsubscribe(participant, sid)
        for pt in data.get("participant_tracks", []):
            for sid in pt.get("track_sids", []):
                if data.get("subscribe", True):
                    room.subscribe(participant, sid)
                else:
                    room.unsubscribe(participant, sid)
    elif kind == "track_setting":
        for sid in data.get("track_sids", []):
            room.update_track_settings(participant, sid, data)
    elif kind == "update_layers":
        pass  # deprecated upstream; dynacast handles layer pausing
    elif kind == "subscription_permission":
        _handle_subscription_permission(room, participant, data)
    elif kind == "sync_state":
        _handle_sync_state(room, participant, data)
    elif kind == "simulate":
        _handle_simulate(room, participant, data)
    elif kind == "ping":
        participant.send(
            "pong",
            {"last_ping_timestamp": data.get("timestamp", 0), "timestamp": int(time.time() * 1000)},
        )
    elif kind == "request_relay":
        # Media-relay allocation (turn.go:47 capability): hand back the
        # relay address + a token bound to this participant's media-crypto
        # session. The relay is blind; the token only admits forwarding.
        udp = getattr(room, "udp", None)
        info = getattr(udp, "relay_info", None) if udp is not None else None
        sess = participant.crypto_session
        if info is not None and sess is not None:
            from livekit_server_tpu.runtime.relay import mint_relay_token

            host, port, secret, ttl = info
            token = mint_relay_token(secret, sess.key_id, ttl)
            participant.send(
                "request_response",
                {"relay_info": {
                    "host": host, "port": port, "token": token.hex(),
                    "ttl_s": ttl,
                }},
            )
        else:
            participant.send("request_response", {"relay_info": None})
    elif kind == "update_metadata":
        if participant.permission.can_update_metadata:
            participant.metadata = data.get("metadata", participant.metadata)
            participant.name = data.get("name", participant.name)
            participant.attributes.update(data.get("attributes", {}))
            participant.version += 1
            room.broadcast_participant_state(participant)
    elif kind == "leave":
        room.remove_participant(participant, pm.DisconnectReason.CLIENT_INITIATED)


def _negotiate_gateway_offer(room, participant: Participant, offer_text: str):
    """SDP offer → gateway peer + ICE-lite answer (participant_sdp.go
    seat). Send-capable m-sections bind to plane track columns: pending
    tracks (announced via add_track) are matched by media kind in order;
    sections with no matching announce auto-publish a track named after
    their mid. recv-capable sections register the participant's
    subscriber column for SRTP egress."""
    from livekit_server_tpu.interop import sdp as sdp_mod

    udp = room.udp
    gw = udp.enable_gateway()
    try:
        offer = sdp_mod.parse_sdp(offer_text)
    except Exception:  # noqa: BLE001 — malformed SDP: fall back to legacy
        return None
    if not offer.media:
        return None
    old = getattr(participant, "gateway_peer", None)
    if old is not None:
        # Renegotiation: the old association's keys die with it.
        gw.close_peer(old)
        participant.gateway_peer = None

    # Tracks claimed by a previous gateway negotiation: reuse them by
    # kind on renegotiation (onnegotiationneeded fires for ICE restarts
    # and device changes — duplicating columns each time would exhaust
    # the room after a handful of re-offers).
    prior = {
        sid: t for sid, t in participant.published.items()
        if getattr(t, "via_gateway", False)
    }
    reused: set = set()
    publish = []
    for m in offer.media:
        if m.kind not in ("audio", "video"):
            continue
        if m.direction not in ("sendonly", "sendrecv") or not m.ssrcs:
            continue
        want_video = m.kind == "video"
        track = None
        for sid, t in prior.items():
            if sid not in reused and t.is_video == want_video:
                track = t
                reused.add(sid)
                break
        if track is None:
            for cid, info in list(participant.pending_tracks.items()):
                if (info.type == pm.TrackType.VIDEO) == want_video:
                    track = participant.publish_pending(cid)
                    break
        if track is None:
            cid = f"sdp-{m.mid or len(publish)}"
            codec = next(iter(m.codecs.values()), "")
            info = participant.add_track_request({
                "cid": cid,
                "type": int(pm.TrackType.VIDEO if want_video
                            else pm.TrackType.AUDIO),
                "name": cid,
                "mime_type": f"{m.kind}/{codec}" if codec else "",
            })
            if info is None:
                continue
            track = participant.publish_pending(cid)
        if track is None:
            continue
        track.via_gateway = True
        mime = next(
            (c for c in ("vp8", "vp9", "av1", "h264", "opus")
             if c in m.codecs.values()),
            "vp8" if want_video else "opus",
        )
        publish.append({
            "mid": m.mid, "room": room.slots.row,
            "track": track.track_col, "mime": mime,
            "svc": mime in ("vp9", "av1") and not any(
                g[0] == "SIM" for g in m.ssrc_groups
            ),
        })
    # Gateway tracks from the previous negotiation that this offer no
    # longer carries: unpublish, or they linger as ghost columns.
    for sid in list(prior):
        if sid not in reused:
            participant.unpublish_track(sid)
    subscribe = None
    if participant.sub_col >= 0 and any(
        m.direction in ("recvonly", "sendrecv") for m in offer.media
    ):
        subscribe = (room.slots.row, participant.sub_col)
    try:
        answer, peer = gw.create_peer(
            offer_text, publish=publish, subscribe=subscribe
        )
    except Exception:  # noqa: BLE001
        return None
    participant.gateway_peer = peer
    return answer


def _handle_subscription_permission(room, participant: Participant, data: dict) -> None:
    """UpdateSubscriptionPermission (uptrackmanager.go): restrict who may
    subscribe to this publisher's tracks."""
    # proto3 JSON omits false bools: a missing key means NOT all (the
    # restrictive reading — matching livekit.SubscriptionPermission).
    all_participants = bool(data.get("all_participants", False))
    # livekit.TrackPermission semantics: an entry with empty track_sids
    # grants that participant ALL of the publisher's tracks; a non-empty
    # list restricts the grant to exactly those track sids.
    allow_all: set = set()
    allow_by_track: dict[str, set] = {}
    for tp in data.get("track_permissions", []):
        who = tp.get("participant_sid") or tp.get("participant_identity")
        if not who:
            continue
        sids = tp.get("track_sids") or []
        if sids:
            for tsid in sids:
                allow_by_track.setdefault(tsid, set()).add(who)
        else:
            allow_all.add(who)
    for sid, (pub, track) in room.tracks.items():
        if pub.sid != participant.sid:
            continue
        track_allowed = allow_by_track.get(sid, set())
        for p in room.participants.values():
            if p.sid == pub.sid:
                continue
            ok = (
                all_participants
                or p.sid in allow_all
                or p.identity in allow_all
                or p.sid in track_allowed
                or p.identity in track_allowed
            )
            if not ok and sid in p.subscribed_tracks:
                room.unsubscribe(p, sid)
                p.send("subscription_permission_update", {
                    "participant_sid": pub.sid, "track_sid": sid, "allowed": False,
                })
            elif ok and p.auto_subscribe and sid not in p.subscribed_tracks:
                room.subscribe(p, sid)


def _handle_sync_state(room, participant: Participant, data: dict) -> None:
    """Resume path (room.go:648): replay desired subscription state."""
    sub = data.get("subscription", {})
    for sid in sub.get("track_sids", []):
        room.subscribe(participant, sid)
    for pub_track in data.get("publish_tracks", []):
        cid = pub_track.get("cid", "")
        if cid and cid not in participant.pending_tracks:
            participant.add_track_request(pub_track.get("track", {}) | {"cid": cid})


def _handle_simulate(room, participant: Participant, data: dict) -> None:
    """Fault injection (room.go:850-911 SimulateScenario)."""
    if "speaker_update" in data:
        pass  # speaker simulation handled by the audio path naturally
    if data.get("node_failure"):
        participant.close(pm.DisconnectReason.STATE_MISMATCH)
    if data.get("server_leave"):
        room.remove_participant(participant, pm.DisconnectReason.SERVER_SHUTDOWN)
    if "subscriber_bandwidth" in data:
        bw = float(data["subscriber_bandwidth"])
        if participant.sub_col >= 0 and bw > 0:
            room.runtime.ingest.push_feedback(
                room.slots.row, participant.sub_col, estimate=bw
            )
