"""Participant: one connected client's session state.

Reference parity: pkg/rtc/participant.go (ParticipantImpl — signal
handling, track publication state machine, permissions, subscription
intents) and pkg/rtc/uptrackmanager.go (published-track registry). The
reference's two PCTransports + Pion plumbing collapse here into the media
slot coordinates: a published track is a (room row, track col) in the
plane tensor; a subscription is a True in the ctrl.subscribed mask; media
I/O happens via the runtime's ingest/egress (packets are pushed by the
transport layer with those coordinates).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from livekit_server_tpu.protocol import models as pm
from livekit_server_tpu.protocol.signal import SignalResponse, encode_signal_response
from livekit_server_tpu.routing.messagechannel import ChannelClosed, ChannelFull, MessageChannel
from livekit_server_tpu.utils import ids


@dataclass
class PublishedTrack:
    """UpTrackManager entry: TrackInfo + tensor coordinates."""

    info: pm.TrackInfo
    track_col: int
    cid: str = ""              # client's local id until published
    ssrc: int = 0              # UDP-transport media binding (0 = WS media)
    via_gateway: bool = False  # claimed by a standards-lane negotiation

    @property
    def is_video(self) -> bool:
        return self.info.type == pm.TrackType.VIDEO


class Participant:
    """Control-plane participant (ParticipantImpl analog, host-side)."""

    def __init__(
        self,
        identity: str,
        room,                     # rtc.Room (avoid circular type import)
        response_sink: MessageChannel | None = None,
        grants: dict | None = None,
        name: str = "",
        auto_subscribe: bool = True,
        client_info: dict | None = None,
    ):
        self.sid = ids.new_participant_id()
        self.identity = identity
        self.name = name
        self.room = room
        self.response_sink = response_sink
        self.grants = grants or {}
        self.auto_subscribe = auto_subscribe
        self.client_info = client_info or {}
        # Device/SDK quirk config matched at join (pkg/clientconfiguration
        # conf.go GetConfiguration); rides the JoinResponse and gates
        # resume + publish codecs server-side.
        from livekit_server_tpu.clientconfig import ClientConfigurationManager

        self.client_config = ClientConfigurationManager().get_configuration(
            self.client_info
        )
        self.state = pm.ParticipantState.JOINING
        self.joined_at = int(time.time())
        self.metadata = ""
        self.attributes: dict[str, str] = {}
        self.sub_col: int = -1          # subscriber column in the room row
        self.crypto_session = None      # media-wire AEAD session (join-minted)
        self.gateway_peer = None        # standards-lane DTLS-SRTP peer
        # Last signaled allocator stream state per subscribed track sid
        # (streamallocator.go StreamStateUpdate change detection).
        self.stream_paused: dict[str, bool] = {}
        self.permission = pm.ParticipantPermission()
        self._apply_grant_permissions()
        self.published: dict[str, PublishedTrack] = {}   # track sid → entry
        self.pending_tracks: dict[str, pm.TrackInfo] = {}  # cid → info
        self.pending_since: dict[str, float] = {}  # cid → announce time
        # (supervisor/participant_supervisor.go publication watchdog)
        self.subscribed_tracks: set[str] = set()         # track sids
        self.disconnected = asyncio.Event()
        self.close_reason = pm.DisconnectReason.UNKNOWN_REASON
        self._media_out: Callable[[Any], None] | None = None
        self.media_queue: asyncio.Queue | None = None  # set by the transport
        # Bumped on every signal-sink swap (resume); a stale session worker
        # compares its captured epoch before tearing the participant down.
        self.session_epoch = 0
        self.version = 0

    # -- permissions (participant.go SetPermission / canPublishSource) ----
    def _apply_grant_permissions(self) -> None:
        video = self.grants.get("video", {}) if self.grants else {}
        def tri(key, default=True):
            v = video.get(key)
            return default if v is None else bool(v)
        self.permission = pm.ParticipantPermission(
            can_subscribe=tri("canSubscribe"),
            can_publish=tri("canPublish"),
            can_publish_data=tri("canPublishData"),
            hidden=bool(video.get("hidden", False)),
            recorder=bool(video.get("recorder", False)),
            can_update_metadata=tri("canUpdateOwnMetadata", False),
            agent=bool(video.get("agent", False)),
        )

    def set_permission(self, perm: pm.ParticipantPermission) -> bool:
        """Admin UpdateParticipant path; revoking publish closes tracks."""
        old = self.permission
        self.permission = perm
        if old.can_publish and not perm.can_publish:
            for sid in list(self.published):
                self.unpublish_track(sid)
            self.pending_tracks.clear()  # announced-but-unbound tracks too
            self.pending_since.clear()
        self.version += 1
        return True

    # -- signaling out ----------------------------------------------------
    def send(self, kind: str, data: dict) -> None:
        """Queue a SignalResponse; drop-on-overflow like the reference's
        bounded signal sinks (a stuck client can't block the room)."""
        if self.response_sink is None or self.response_sink.is_closed:
            return
        try:
            self.response_sink.write_message(
                encode_signal_response(SignalResponse(kind, data))
            )
        except (ChannelFull, ChannelClosed):
            pass

    def to_info(self) -> pm.ParticipantInfo:
        return pm.ParticipantInfo(
            sid=self.sid,
            identity=self.identity,
            state=self.state,
            tracks=[t.info for t in self.published.values()],
            metadata=self.metadata,
            joined_at=self.joined_at,
            name=self.name,
            version=self.version,
            permission=self.permission,
            is_publisher=bool(self.published),
            attributes=dict(self.attributes),
        )

    # -- publication state machine (participant.go AddTrack → addMediaTrack)
    def add_track_request(self, req: dict) -> pm.TrackInfo | None:
        """AddTrackRequest → pending track + track_published response."""
        if not self.permission.can_publish:
            return None
        cid = req.get("cid", "")
        if not cid or cid in self.pending_tracks:
            return None
        mime = str(req.get("mime_type", "")).lower()
        if self.client_config is not None and mime and mime in {
            m.lower()
            for m in self.client_config.disabled_codecs
            + self.client_config.disabled_publish_codecs
        }:
            # Codec publish disabled for this device/SDK combination
            # (clientconfiguration staticconfiguration.go). Answer
            # explicitly — dead air would hang the SDK's publish().
            self.send(
                "request_response",
                {"error": {"reason": "codec_disabled_for_client", "cid": cid,
                           "mime_type": mime}},
            )
            return None
        deny = getattr(self.room, "admission", None)
        reason = deny("publish") if deny is not None else ""
        if reason:
            # Node admission (governor L4 / LimitConfig track cap / node
            # ingress rate): answer explicitly — same contract as the
            # codec rejection above, dead air would hang the SDK.
            self.send(
                "request_response",
                {"error": {"reason": "node_overloaded", "cid": cid,
                           "message": reason}},
            )
            return None
        try:
            track_type = pm.TrackType(int(req.get("type", 0)))
            source = pm.TrackSource(int(req.get("source", 0)))
        except (ValueError, TypeError):
            return None  # malformed enum from client: reject, don't crash
        info = pm.TrackInfo(
            sid=ids.new_track_id(),
            type=track_type,
            name=req.get("name", ""),
            muted=req.get("muted", False),
            width=req.get("width", 0),
            height=req.get("height", 0),
            simulcast=len(req.get("layers", [])) > 1,
            source=source,
            layers=[
                pm.SimulcastLayer(
                    quality=pm.VideoQuality(l.get("quality", 0)),
                    width=l.get("width", 0),
                    height=l.get("height", 0),
                )
                for l in req.get("layers", [])
            ],
            mime_type=req.get("mime_type", ""),
            stereo=req.get("stereo", False),
            disable_red=req.get("disable_red", False),
        )
        self.pending_tracks[cid] = info
        self.pending_since[cid] = time.time()
        self.send("track_published", {"cid": cid, "track": info.to_dict()})
        return info

    def reap_stale_publications(self, wait_s: float = 30.0) -> list[str]:
        """Publication watchdog (supervisor/publication_monitor.go:30
        publishWaitDuration): an announced track whose media never arrived
        is abandoned and the client told, instead of a ghost entry living
        in pending_tracks forever. Returns the reaped cids."""
        now = time.time()
        stale = [
            cid for cid, t0 in self.pending_since.items()
            if now - t0 > wait_s and cid in self.pending_tracks
        ]
        for cid in stale:
            info = self.pending_tracks.pop(cid, None)
            self.pending_since.pop(cid, None)
            if info is not None:
                self.send(
                    "track_unpublished",
                    {"track_sid": info.sid, "participant_sid": self.sid,
                     "reason": "publish_timeout"},
                )
        return stale

    def publish_pending(self, cid: str) -> PublishedTrack | None:
        """Media arrived for a pending track (the reference's onMediaTrack
        → mediaTrackReceived): allocate the tensor column, flip the mask."""
        if not self.permission.can_publish:
            # Permission may have been revoked between announce and media.
            self.pending_tracks.pop(cid, None)
            self.pending_since.pop(cid, None)
            return None
        info = self.pending_tracks.pop(cid, None)
        if info is None:
            return None
        track = self.room.publish_track(self, info)
        if track is None:
            self.pending_tracks[cid] = info  # no capacity; retry later
            # Media IS arriving — restart the watchdog clock so an active
            # publish blocked on capacity is never reaped as abandoned.
            self.pending_since[cid] = time.time()
            return None
        self.pending_since.pop(cid, None)
        track.cid = cid
        self.published[info.sid] = track
        self.state = pm.ParticipantState.ACTIVE
        self.version += 1
        return track

    def unpublish_track(self, track_sid: str) -> None:
        track = self.published.pop(track_sid, None)
        if track is not None:
            self.room.unpublish_track(self, track)
            self.version += 1

    def set_track_muted(self, track_sid: str, muted: bool) -> None:
        track = self.published.get(track_sid)
        if track is None:
            # may still be pending (mute before media arrives)
            for info in self.pending_tracks.values():
                if info.sid == track_sid:
                    info.muted = muted
            return
        track.info.muted = muted
        self.room.set_track_muted(self, track, muted)
        self.version += 1

    # -- media egress hookup ---------------------------------------------
    def on_media(self, cb: Callable[[Any], None]) -> None:
        """Transport registers its egress writer (EgressPacket consumer)."""
        self._media_out = cb

    def deliver_media(self, pkt) -> None:
        if self._media_out is not None:
            self._media_out(pkt)

    # -- teardown ---------------------------------------------------------
    def close(self, reason: pm.DisconnectReason) -> None:
        if self.state == pm.ParticipantState.DISCONNECTED:
            return
        self.state = pm.ParticipantState.DISCONNECTED
        self.close_reason = reason
        if self.response_sink is not None:
            self.response_sink.close()
        self.disconnected.set()
