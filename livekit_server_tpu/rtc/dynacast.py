"""Dynacast: pause simulcast layers nobody is watching.

Reference parity: pkg/rtc/dynacastmanager.go:35-264 + dynacastquality.go —
aggregate every subscriber's desired max quality per track, notify the
publisher to stop encoding unused layers (subscribed_quality_update
signal), with debounced downgrades (dynacastPauseDelay) so brief
subscriber churn doesn't flap the encoder.

TPU twist: desired state already lives in the ctrl.max_spatial host
mirror, so aggregation is a masked max over the subscriber axis of the
control tensors — no per-subscriber bookkeeping objects.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

DOWNGRADE_DELAY_S = 5.0  # dynacastPauseDelay (dynacastmanager.go)


@dataclass
class DynacastState:
    """Per-track last-signaled max quality + pending downgrade timer."""

    last_sent: dict[str, int] = field(default_factory=dict)      # sid → quality
    pending_down: dict[str, tuple[int, float]] = field(default_factory=dict)


def compute_max_quality(
    subscribed: np.ndarray,    # [T, S] bool (room slice of ctrl.subscribed)
    sub_muted: np.ndarray,     # [T, S] bool
    max_spatial: np.ndarray,   # [T, S] int32
) -> np.ndarray:
    """Per-track max desired spatial layer over active subscribers; -1 when
    nobody subscribes (⇒ publisher may pause the track entirely)."""
    active = subscribed & ~sub_muted
    desired = np.where(active, max_spatial, -1)
    return desired.max(axis=-1)


def reconcile(
    state: DynacastState,
    room,
    now: float | None = None,
) -> list[tuple[object, str, int]]:
    """Compare aggregated desire against what was last signaled; returns
    [(publisher, track_sid, max_quality)] to notify. Upgrades fire
    immediately; downgrades wait DOWNGRADE_DELAY_S (dynacastquality.go
    debounce)."""
    now = time.time() if now is None else now
    row = room.slots.row
    rt = room.runtime
    sub = room.runtime.ctrl.subscribed[row]
    mut = room.runtime.ctrl.sub_muted[row]
    cap = room.runtime.ctrl.max_spatial[row]
    maxq = compute_max_quality(sub, mut, cap)

    notify = []
    for sid, (publisher, track) in room.tracks.items():
        if not track.is_video:
            continue
        q = int(maxq[track.track_col])
        last = state.last_sent.get(sid)
        if last is None or q > last:
            state.pending_down.pop(sid, None)
            state.last_sent[sid] = q
            notify.append((publisher, sid, q))
        elif q < last:
            pend = state.pending_down.get(sid)
            if pend is None:
                state.pending_down[sid] = (q, now)
            elif pend[0] != q:
                state.pending_down[sid] = (q, min(pend[1], now))
            elif now - pend[1] >= DOWNGRADE_DELAY_S:
                state.pending_down.pop(sid, None)
                state.last_sent[sid] = q
                notify.append((publisher, sid, q))
        else:
            state.pending_down.pop(sid, None)
    # Drop state for unpublished tracks.
    gone = set(state.last_sent) - set(room.tracks)
    for sid in gone:
        state.last_sent.pop(sid, None)
        state.pending_down.pop(sid, None)
    return notify
