"""Room: participant registry + subscription fan-out + per-tick events.

Reference parity: pkg/rtc/room.go (Room struct :76-122, Join :313-472,
RemoveParticipant :546-620, onTrackPublished :963-1041,
subscribeToExistingTracks :1074-1099, audioUpdateWorker :1278,
broadcastParticipantState :1101, data fan-out :1455) plus the
subscription-manager reconcile (subscriptionmanager.go) collapsed into
mask writes: desired state IS the ctrl.subscribed tensor, so reconcile is
a single assignment rather than a retry loop.

A Room owns one room row in the node's PlaneRuntime; its handle_tick
receives the row's slice of TickResult (egress packets, speakers,
keyframe needs) from the node dispatcher.
"""

from __future__ import annotations

import time
from typing import Callable

from livekit_server_tpu.protocol import models as pm
from livekit_server_tpu.rtc.participant import Participant, PublishedTrack
from livekit_server_tpu.runtime.plane_runtime import PlaneRuntime
from livekit_server_tpu.runtime.slots import CapacityError, RoomSlots
from livekit_server_tpu.utils import ids


class Room:
    def __init__(
        self,
        name: str,
        runtime: PlaneRuntime,
        info: pm.RoomInfo | None = None,
    ):
        self.name = name
        self.runtime = runtime
        self.slots: RoomSlots = runtime.slots.alloc_room(name)
        self.info = info or pm.RoomInfo(sid=ids.new_room_id(), name=name)
        self.info.name = name
        self.participants: dict[str, Participant] = {}   # identity → P
        self.by_sid: dict[str, Participant] = {}
        self.tracks: dict[str, tuple[Participant, PublishedTrack]] = {}
        self.created_at = time.time()
        self.last_left_at = 0.0
        self.closed = False
        self.udp = None  # UDPMediaTransport when the node serves UDP media
        self.crypto = None  # MediaCryptoRegistry (join-time key minting)
        # Node admission gate (RoomManager._admission_denied): returns a
        # non-empty rejection reason when new work must be refused.
        # None (tests constructing rooms directly) admits everything.
        self.admission = None
        # Incremental indexes for the per-tick hot path (no per-packet
        # dict rebuilds): sub col → participant, track col → track sid.
        self.sub_index: dict[int, Participant] = {}
        self.col_to_sid: dict[int, str] = {}
        # Hooks fired on publish (room.go onTrackPublished callbacks —
        # used for publisher agent jobs and track egress launch).
        self.on_track_published: list[Callable] = []
        self._on_close: list[Callable[[], None]] = []
        self._active_speakers: list[dict] = []
        self._last_pli: dict[int, float] = {}  # track col → monotonic s
        from livekit_server_tpu.rtc.dynacast import DynacastState

        self.dynacast = DynacastState()

    # -- join / leave (room.go Join :313) ---------------------------------
    def join(self, participant: Participant) -> dict:
        """Admit the participant; returns the JoinResponse payload."""
        if self.closed:
            raise RuntimeError("room closed")
        existing = self.participants.get(participant.identity)
        if existing is not None:
            # duplicate identity ⇒ disconnect the older session
            # (room.go:331 DuplicateIdentity)
            self.remove_participant(existing, pm.DisconnectReason.DUPLICATE_IDENTITY)
        participant.sub_col = self.slots.alloc_sub(participant.sid)
        self.participants[participant.identity] = participant
        self.by_sid[participant.sid] = participant
        self.sub_index[participant.sub_col] = participant
        participant.state = pm.ParticipantState.JOINED
        self.info.num_participants = len(self.participants)

        # auto-subscribe to existing tracks (room.go:1074)
        if participant.auto_subscribe and participant.permission.can_subscribe:
            for sid in self.tracks:
                self.subscribe(participant, sid)

        self.broadcast_participant_state(participant)
        others = [
            p.to_info().to_dict()
            for p in self.participants.values()
            if p.sid != participant.sid and not p.permission.hidden
        ]
        resp = {
            "room": self.info.to_dict(),
            "participant": participant.to_info().to_dict(),
            "other_participants": others,
            "server_info": {"edition": "tpu", "protocol": 12},
        }
        if self.crypto is not None:
            # Media-wire key exchange over the authenticated signal channel
            # (the DTLS-SRTP handshake seat — transport.go:167): the
            # session seals every UDP/TCP media datagram both directions.
            import base64

            from livekit_server_tpu.runtime.crypto import ALGO

            session = self.crypto.mint()
            session.room = self.slots.row
            session.sub = participant.sub_col
            participant.crypto_session = session
            if self.udp is not None:
                self.udp.bind_sub_session(
                    self.slots.row, participant.sub_col, session
                )
            resp["media_crypto"] = {
                "key_id": session.key_id,
                "key": base64.b64encode(session.key).decode(),
                "algo": ALGO,
            }
        return resp

    def remove_participant(
        self, participant: Participant, reason: pm.DisconnectReason
    ) -> None:
        p = self.participants.get(participant.identity)
        if p is None or p.sid != participant.sid:
            return
        for sid in list(p.published):
            p.unpublish_track(sid)
        # drop their subscriptions column
        if p.sub_col >= 0:
            for _, (_, track) in self.tracks.items():
                self.runtime.set_subscription(
                    self.slots.row, track.track_col, p.sub_col, subscribed=False
                )
            self.slots.release_sub(p.sid)
            self.sub_index.pop(p.sub_col, None)
            if self.udp is not None:
                self.udp.release_subscriber(self.slots.row, p.sub_col)
        if self.crypto is not None and getattr(p, "crypto_session", None) is not None:
            self.crypto.remove(p.crypto_session.key_id)
        peer = getattr(p, "gateway_peer", None)
        if peer is not None and self.udp is not None and self.udp.gateway is not None:
            # Standards-lane client: tear down the DTLS association and
            # its SSRC bindings with the participant.
            self.udp.gateway.close_peer(peer)
            p.gateway_peer = None
        del self.participants[p.identity]
        self.by_sid.pop(p.sid, None)
        self.info.num_participants = len(self.participants)
        self.last_left_at = time.time()
        p.send("leave", {"reason": int(reason), "can_reconnect": False})
        p.close(reason)
        self.broadcast_participant_state(p)

    # -- publication (room.go onTrackPublished :963) ----------------------
    def publish_track(
        self, publisher: Participant, info: pm.TrackInfo
    ) -> PublishedTrack | None:
        try:
            col = self.slots.alloc_track(info.sid)
        except CapacityError:
            return None
        track = PublishedTrack(info=info, track_col=col)
        self.tracks[info.sid] = (publisher, track)
        self.col_to_sid[col] = info.sid
        is_svc = pm.is_svc_mime(info.mime_type, info.type == pm.TrackType.VIDEO)
        self.runtime.set_track(
            self.slots.row,
            col,
            published=True,
            is_video=info.type == pm.TrackType.VIDEO,
            pub_muted=info.muted,
            is_svc=is_svc,
            pub_sub=publisher.sub_col,
        )
        if self.udp is not None:
            self.udp.set_track_kind(self.slots.row, col, info.type == pm.TrackType.VIDEO)
            if (
                self.udp.audio_mixer is not None
                and info.type != pm.TrackType.VIDEO
                and publisher.sub_col >= 0
            ):
                # Keep mixer self-exclusion current when the opt-in
                # preceded the publish (or the mic republished).
                self.udp.audio_mixer.set_publisher_track(
                    self.slots.row, publisher.sub_col, col
                )
        # Count distinct publishers from the track registry (the caller's
        # published dict is updated only after this returns).
        self.info.num_publishers = len({pub.sid for pub, _t in self.tracks.values()})
        # fan out subscriptions to everyone else (room.go:1028)
        for p in self.participants.values():
            if p.sid == publisher.sid:
                continue
            if p.auto_subscribe and p.permission.can_subscribe:
                self.subscribe(p, info.sid)
        self.broadcast_participant_state(publisher)
        for cb in self.on_track_published:
            cb(publisher, track)
        return track

    def unpublish_track(self, publisher: Participant, track: PublishedTrack) -> None:
        sid = track.info.sid
        if sid not in self.tracks:
            return
        del self.tracks[sid]
        self.col_to_sid.pop(track.track_col, None)
        self.runtime.set_track(
            self.slots.row, track.track_col, published=False, is_video=track.is_video
        )
        if self.udp is not None:
            self.udp.release_track(self.slots.row, track.track_col)
        self.slots.release_track(sid)
        for p in self.participants.values():
            p.subscribed_tracks.discard(sid)
            p.stream_paused.pop(sid, None)   # sids never reuse; no growth
            if p.sid != publisher.sid:
                p.send("track_unpublished", {"track_sid": sid, "participant_sid": publisher.sid})
        self.broadcast_participant_state(publisher)

    def set_track_muted(self, publisher: Participant, track: PublishedTrack, muted: bool) -> None:
        self.runtime.set_track(
            self.slots.row,
            track.track_col,
            published=True,
            is_video=track.is_video,
            pub_muted=muted,
        )
        self.broadcast_participant_state(publisher)

    # -- subscription (subscriptionmanager.go collapsed) ------------------
    def subscribe(self, subscriber: Participant, track_sid: str) -> bool:
        ent = self.tracks.get(track_sid)
        if ent is None or subscriber.sub_col < 0:
            return False
        if not subscriber.permission.can_subscribe:
            subscriber.send(
                "subscription_response",
                {"track_sid": track_sid, "err": 1},  # ERR_NO_PERMISSION
            )
            return False
        _pub, track = ent
        self.runtime.set_subscription(
            self.slots.row, track.track_col, subscriber.sub_col, subscribed=True
        )
        subscriber.subscribed_tracks.add(track_sid)
        subscriber.send("track_subscribed", {"track_sid": track_sid})
        return True

    def unsubscribe(self, subscriber: Participant, track_sid: str) -> None:
        ent = self.tracks.get(track_sid)
        subscriber.subscribed_tracks.discard(track_sid)
        # Forget the signaled pause state: a later re-subscribe starts from
        # the implicit 'active' baseline, so a still-paused allocation is
        # re-signaled instead of silently suppressed.
        subscriber.stream_paused.pop(track_sid, None)
        if ent is None or subscriber.sub_col < 0:
            return
        _pub, track = ent
        self.runtime.set_subscription(
            self.slots.row, track.track_col, subscriber.sub_col, subscribed=False
        )

    def update_track_settings(
        self, subscriber: Participant, track_sid: str, settings: dict
    ) -> None:
        """UpdateTrackSettings: mute/quality/dimensions → layer caps
        (mediatrackreceiver.go GetQualityForDimension analog)."""
        ent = self.tracks.get(track_sid)
        if ent is None or subscriber.sub_col < 0:
            return
        _pub, track = ent
        disabled = settings.get("disabled", False)
        quality = settings.get("quality")
        width = settings.get("width", 0)
        height = settings.get("height", 0)
        fps = settings.get("fps", 0)
        if "pinned" in settings:
            # Pinned subscriptions (screen share, active speaker) are
            # exempt from the governor's L3 video pause.
            self.runtime.set_pinned(
                self.slots.row, track.track_col, subscriber.sub_col,
                bool(settings["pinned"]),
            )
        self.runtime.set_subscription(
            self.slots.row,
            track.track_col,
            subscriber.sub_col,
            subscribed=track_sid in subscriber.subscribed_tracks,
            sub_muted=disabled,
        )
        # Only update layer caps when the settings actually carry layer
        # intent — a disabled-only update must not clobber a previous cap.
        max_spatial = None
        if quality is not None:
            max_spatial = min(int(quality), 2)
        elif width or height:
            # dimension → quality: smallest layer covering the request
            # (mediatrackreceiver.go GetQualityForDimension)
            max_spatial = 0
            for i, layer in enumerate(sorted(track.info.layers, key=lambda l: l.width)):
                max_spatial = min(i, 2)
                if layer.width >= width and layer.height >= height:
                    break
        # fps → temporal layer, assuming ~30 fps at the top layer with
        # rate halving per layer (temporallayerselector semantics).
        max_temporal = None
        if fps:
            max_temporal = 0 if fps <= 8 else 1 if fps <= 15 else 2 if fps <= 25 else 3
        if max_spatial is not None or max_temporal is not None:
            coords = (self.slots.row, track.track_col, subscriber.sub_col)
            if max_spatial is None:  # keep the current cap for the unset axis
                max_spatial = int(self.runtime.ctrl.max_spatial[coords])
            if max_temporal is None:
                max_temporal = int(self.runtime.ctrl.max_temporal[coords])
            self.runtime.set_layer_caps(*coords, max_spatial=max_spatial, max_temporal=max_temporal)

    # -- broadcast (room.go broadcastParticipantState :1101) --------------
    def broadcast_participant_state(self, participant: Participant) -> None:
        if participant.permission.hidden:
            return
        info = participant.to_info().to_dict()
        for p in self.participants.values():
            p.send("update", {"participants": [info]})

    def broadcast_data(
        self,
        sender: Participant | None,
        payload: str,
        kind: int = 0,
        destination_sids: list[str] | None = None,
        topic: str = "",
    ) -> None:
        """Data-channel fan-out (room.go:1455 BroadcastDataPacketForRoom).
        Data packets bypass the media plane (reference: SCTP, not RTP)."""
        if sender is not None and not sender.permission.can_publish_data:
            return
        targets = (
            [self.by_sid[s] for s in destination_sids if s in self.by_sid]
            if destination_sids
            else list(self.participants.values())
        )
        msg = {
            "participant_sid": sender.sid if sender else "",
            "payload": payload,
            "kind": kind,
            "topic": topic,
        }
        for p in targets:
            if sender is not None and p.sid == sender.sid:
                continue
            p.send("data_packet", msg)

    # -- per-tick events from the dispatcher ------------------------------
    def handle_speakers(self, speakers: list[tuple[int, float]]) -> None:
        """Room-row speaker ranking → speakers_changed broadcast
        (room.go audioUpdateWorker :1278)."""
        spk = []
        for track_col, level in speakers:
            sid = self.col_to_sid.get(track_col)
            if sid is None or sid not in self.tracks:
                continue
            pub, _t = self.tracks[sid]
            spk.append({"sid": pub.sid, "level": level, "active": True})
        if spk != self._active_speakers:
            self._active_speakers = spk
            for p in self.participants.values():
                p.send("speakers_changed", {"speakers": spk})

    def handle_keyframe_request(self, track_col: int) -> None:
        """Device says a subscriber needs a keyframe ⇒ PLI to publisher
        (receiver.go SendPLI / mediatrack.go), throttled per track so a
        persistent need_keyframe or a PLI-spamming subscriber cannot
        force a keyframe storm (buffer pliThrottle analog)."""
        from livekit_server_tpu.runtime.udp import PLI_THROTTLE_MS

        now = time.monotonic()
        if now - self._last_pli.get(track_col, -1e12) < PLI_THROTTLE_MS / 1000.0:
            return
        self._last_pli[track_col] = now
        sid = self.col_to_sid.get(track_col)
        if sid and sid in self.tracks:
            pub, track = self.tracks[sid]
            pub.send("request_response", {"pli": {"track_sid": sid}})

    def deliver_egress(self, pkt) -> None:
        """Route one EgressPacket to the right subscriber's transport."""
        p = self.sub_index.get(pkt.sub)
        if p is not None:
            p.deliver_media(pkt)

    def handle_quality(self, track_quality, track_mos, sub_quality) -> None:
        """Per-window connection-quality fan-out (room.go:1318-1396
        connectionQualityWorker): each participant's quality = worst of its
        published tracks' E-model scores and its subscriber-side state,
        broadcast as a connection_quality update."""
        updates = []
        from livekit_server_tpu.ops.quality import QUALITY_EXCELLENT, QUALITY_LOST

        for p in self.participants.values():
            qs: list[int] = []
            scores: list[float] = []
            for sid in p.published:
                ent = self.tracks.get(sid)
                if ent is None:
                    continue
                col = ent[1].track_col
                qs.append(int(track_quality[col]))
                scores.append(float(track_mos[col]))
            if p.sub_col >= 0 and p.subscribed_tracks:
                qs.append(int(sub_quality[p.sub_col]))
            # LOST only dominates when everything is LOST
            # (ParticipantImpl.GetConnectionQuality aggregation).
            live = [q for q in qs if q != QUALITY_LOST]
            if qs and not live:
                q = QUALITY_LOST
            elif live:
                q = min(live)
            else:
                q = QUALITY_EXCELLENT  # signal-only participant
            updates.append(
                {
                    "participant_sid": p.sid,
                    "quality": q,
                    "score": round(min(scores), 2) if scores else 5.0,
                }
            )
        if not updates:
            return
        for p in self.participants.values():
            p.send("connection_quality", {"updates": updates})

    def update_stream_states(self, target_layers) -> None:
        """Allocator pause/resume transitions → stream_state_update
        (streamallocator.go StreamStateUpdate → signal): a subscriber whose
        video allocation went to -1 (congestion pause, caps, mute) learns
        the stream is intentionally stopped, not lost. Only transitions are
        signaled; the initial active state is implicit."""
        for p in self.participants.values():
            if p.sub_col < 0 or not p.subscribed_tracks:
                continue
            states = []
            for sid in list(p.subscribed_tracks):
                ent = self.tracks.get(sid)
                if ent is None or not ent[1].is_video:
                    continue
                paused = int(target_layers[p.sub_col, ent[1].track_col]) < 0
                prev = p.stream_paused.get(sid)
                if prev is None:
                    p.stream_paused[sid] = paused
                    if not paused:
                        continue  # initial active is implicit
                elif prev == paused:
                    continue
                p.stream_paused[sid] = paused
                states.append({
                    "track_sid": sid,
                    "state": "paused" if paused else "active",
                })
            if states:
                p.send("stream_state_update", {"stream_states": states})

    def reconcile_dynacast(self) -> None:
        """Aggregate subscriber layer demand → subscribed_quality_update to
        publishers so they stop encoding unwatched simulcast layers
        (dynacastmanager.go:187-255; debounced downgrades inside
        rtc.dynacast.reconcile)."""
        from livekit_server_tpu.rtc.dynacast import reconcile

        for publisher, sid, maxq in reconcile(self.dynacast, self):
            publisher.send(
                "subscribed_quality_update",
                {
                    "track_sid": sid,
                    "subscribed_qualities": [
                        {"quality": q, "enabled": q <= maxq} for q in range(3)
                    ],
                },
            )

    # -- lifecycle --------------------------------------------------------
    @property
    def is_empty(self) -> bool:
        return not self.participants

    def should_close(self, now: float | None = None) -> bool:
        """Idle-room reaping (server.go backgroundWorker + CloseIdleRooms):
        empty_timeout applies to rooms nobody ever joined; once the last
        participant departs, the (much shorter) departure_timeout governs."""
        now = now or time.time()
        if self.closed:
            return True
        if not self.is_empty:
            return False
        if self.last_left_at:
            return now - self.last_left_at > self.info.departure_timeout
        return now - self.created_at > self.info.empty_timeout

    def on_close(self, cb: Callable[[], None]) -> None:
        self._on_close.append(cb)

    def close(self, reason: pm.DisconnectReason = pm.DisconnectReason.ROOM_DELETED) -> None:
        if self.closed:
            return
        self.closed = True
        for p in list(self.participants.values()):
            self.remove_participant(p, reason)
        if self.udp is not None:
            self.udp.release_room(self.slots.row)
        self.runtime.clear_room(self.slots.row)
        self.runtime.slots.release_room(self.name)
        for cb in self._on_close:
            cb()
