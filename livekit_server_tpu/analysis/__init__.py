"""graftcheck: AST-based invariant checker for the media plane.

The single-jitted-tick design concentrates failure: one tracer impurity,
donation misuse, lock-order inversion, or ad-hoc retry loop is a
whole-plane defect, not a local one. The invariants the runtime relies
on are all *statically visible*, so this package encodes them as AST
analyzers wired into the tier-1 gate:

  GC01 donation-safety — the donated device state (`PlaneRuntime.state`)
       and its staging methods may only be touched under `state_lock`
       (or from a function the config allowlists as lock-held).
  GC02 tracer-purity — no host side effects (time, random, logging,
       numpy materialization, threading, bus I/O) inside any function
       reachable from a jax.jit / shard_map / pallas_call wrap site.
  GC03 lock-discipline — the asyncio lock acquisition graph
       (state_lock / _ckpt_lock / _create_locks) must be acyclic, and
       no blocking sync call may run while an asyncio lock is held.
  GC04 retry-policy — network dials/sends in routing/ and the media
       relay must route through utils/backoff.retry_async; bare
       while+sleep retry loops are findings.
  GC05 bounded-queues — every asyncio.Queue / collections.deque
       constructed in runtime/ and routing/ carries an explicit bound
       (maxsize=/maxlen=); unbounded buffers turn overload into memory
       growth instead of counted drops.
  GC06 checkpoint-hygiene — serialization in the checkpoint-bearing
       modules must pair with the utils/checksum codec in the same
       function; unverified bytes never scatter into donated state.
  GC07 emit-hygiene — flight-recorder emits on the tick hot path
       (record_tick / set_shard / BlackBox.emit / observe_*) must pass
       scalars only: no f-string, container display, comprehension, or
       .format in the emit's arguments outside a sampled branch.
  GC08 page-handle-discipline — device page indices minted from the
       pager (`pages_of_room`) are epoch-scoped; using one across an
       await or a state_lock release without `check_epoch` (or a
       re-mint) is a finding — alloc/grow/compaction may have remapped
       the pages behind the handle.
  GC09 fencing-discipline — room-ownership KV state (room_checkpoint:/
       room_snapshot:/room_epoch: keys, the room_node_map pin hash)
       may only be mutated through the epoch-fenced writer API
       (RoomFence guarded writes, the KVRouter pin movers); a raw bus
       mutation on a literal fenced key bypasses the epoch CAS that
       keeps a stale owner from clobbering the takeover winner.
  GC10 donation-discipline — every jax.jit wrap site's donate spec must
       be live: a donate index naming an unused (or nonexistent)
       parameter aliases nothing, and a traced tick that takes and
       returns the plane state without donating it copies the whole
       buffer per call. The AST half lives in gc10.py; the semantic
       half (do donated leaves actually alias an output of matching
       shape/dtype at canonical dims?) runs in devicecheck.py over the
       `@device_entry` registry.
  GC11 retrace-stability — static args to jit wraps must be hashable
       and cache-stable: mutable literals at static call sites, typo'd
       static_argnames, mutable static defaults, and per-call
       `jax.jit(f)(x)` wrappers are findings. The runtime half is the
       recompile watchdog (runtime/compile_ledger.py): post-warmup XLA
       compile counts at /debug/compiles + livekit_xla_compiles_total,
       asserted zero by the seeded tier-1 drills.
  GC12 host-sync-hygiene — blocking device reads (block_until_ready,
       device_get, .item(), np.asarray/float()/int() on device-named
       values) reachable from the tick-path roots outside the declared
       drain/telemetry seams stall the pipeline mid-tick; the one
       sanctioned round trip per tick is the drain seam itself.

The devicecheck pass (analysis/devicecheck.py, jax required, invoked by
tools/check) complements these with abstract-eval compile contracts:
every `@device_entry` point is eval_shape'd at canonical north-star and
paged dims, and output shapes/dtypes/shardings plus jaxpr-derived
FLOP/byte costs are pinned in tools/devicecheck_baseline.json.

Suppressions: `# graftcheck: disable=GC01` on the finding's exact line
(with a justification comment), `# graftcheck: disable-file=GC02` for a
whole file, or a committed baseline for pre-existing findings — the
baseline only shrinks (a stale entry fails the run), and so do the
suppressions themselves (a disable= that no longer matches any finding
is reported as stale).

Entry point: `python -m tools.check` (see tools/check.py).
"""

from livekit_server_tpu.analysis.core import (
    Config,
    Finding,
    Project,
    diff_baseline,
    load_baseline,
    load_project,
    run_all,
    write_baseline,
)

__all__ = [
    "Config",
    "Finding",
    "Project",
    "diff_baseline",
    "load_baseline",
    "load_project",
    "run_all",
    "write_baseline",
]
