"""GC04 — retry policy.

Network sends and dials in the routing plane and the media relay must
route through `utils/backoff.retry_async` (with a `BackoffPolicy` and,
for persistent peers, a `CircuitBreaker`). Hand-rolled
`while: try/except(ConnectionError): sleep()` loops retry instantly
under partitions, synchronize reconnect storms across nodes, and never
trip a breaker — exactly the shape PR 1's fault injection punishes.

Two findings:

  * a `while` loop that catches a network error class and sleeps *as
    backoff* is an ad-hoc retry loop. "As backoff" means the sleep sits
    at or after the net-catching `try` — inside the handler, or at the
    loop tail after a swallowed failure. A periodic poll worker that
    sleeps at the TOP of its body (the sleep is the schedule, not a
    reaction to failure) and then tolerates a net error until the next
    interval is NOT a finding — that shape already has bounded, fixed
    cadence and cannot storm;
  * a direct dial call (`asyncio.open_connection`,
    `create_datagram_endpoint`, ...) in a function that is not itself
    passed to `retry_async` is an unmanaged dial. Listen-side binds and
    deliberate fail-fast initial dials carry an inline
    `# graftcheck: disable=GC04` with a justification.

Bounded in-process polls (no network except handler) are not findings.
"""

from __future__ import annotations

import ast

from livekit_server_tpu.analysis.callgraph import dotted_name
from livekit_server_tpu.analysis.core import Finding, Project

_SLEEPS = {"asyncio.sleep", "time.sleep"}


def _handler_names(handler: ast.ExceptHandler, cg, modname: str) -> set[str]:
    t = handler.type
    exprs = t.elts if isinstance(t, ast.Tuple) else [t] if t else []
    out = set()
    for e in exprs:
        dotted = dotted_name(e)
        if dotted:
            full = cg.expand_alias(dotted, modname)
            out.add(full)
            out.add(full.rsplit(".", 1)[-1])
    return out


def _retry_wrapped_names(sf, cg, cfg) -> set[str]:
    """Names of functions passed to retry helpers anywhere in the module
    (`await retry_async(dial, policy, ...)` marks `dial` as managed)."""
    out: set[str] = set()
    if sf.tree is None:
        return out
    helpers = set(cfg["retry_helpers"])
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = dotted_name(node.func)
        if dotted is None or dotted.rsplit(".", 1)[-1] not in helpers:
            continue
        for arg in node.args:
            if isinstance(arg, ast.Name):
                out.add(arg.id)
    return out


def run(project: Project, cfg: dict) -> list[Finding]:
    cg = project.callgraph
    net_errors = set(cfg["net_errors"])
    dial_calls = set(cfg["dial_calls"])
    findings: list[Finding] = []

    for sf in project.under(cfg["paths"]):
        if sf.tree is None:
            continue
        managed = _retry_wrapped_names(sf, cg, cfg)

        # ad-hoc retry loops: while + except(net error) + sleep-as-backoff.
        # The sleep must sit at or after the net-catching try (inside the
        # handler, or at the loop tail behind a swallowed failure); a
        # schedule-sleep at the top of a poll worker's body is cadence,
        # not backoff, and does not fire.
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.While):
                continue
            caught: set[str] = set()
            net_try_line: int | None = None
            sleep_lines: list[int] = []
            for sub in ast.walk(node):
                if isinstance(sub, ast.Try):
                    for h in sub.handlers:
                        got = _handler_names(h, cg, sf.modname) & net_errors
                        if got:
                            caught |= got
                            if net_try_line is None or sub.lineno < net_try_line:
                                net_try_line = sub.lineno
                elif isinstance(sub, ast.Call):
                    dotted = dotted_name(sub.func)
                    if dotted and cg.expand_alias(
                        dotted, sf.modname
                    ) in _SLEEPS:
                        sleep_lines.append(sub.lineno)
            if caught and net_try_line is not None and any(
                ln >= net_try_line for ln in sleep_lines
            ):
                findings.append(
                    Finding(
                        "GC04", sf.rel, node.lineno,
                        "ad-hoc retry loop: catches "
                        f"{sorted(caught)} and sleeps inline",
                        hint="route the attempt through "
                        "utils.backoff.retry_async with a BackoffPolicy "
                        "(+ CircuitBreaker for persistent peers)",
                    )
                )

        # unmanaged direct dials
        for (mod, qual), fi in cg.funcs.items():
            if mod != sf.modname:
                continue
            if fi.name in managed:
                continue
            body = getattr(fi.node, "body", [])
            stack = list(body) if isinstance(body, list) else [body]
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    continue  # nested fn is its own FuncInfo
                if isinstance(node, ast.Call):
                    dotted = dotted_name(node.func)
                    if dotted is not None:
                        full = cg.expand_alias(dotted, sf.modname)
                        tail = full.rsplit(".", 1)[-1]
                        if full in dial_calls or tail in dial_calls:
                            findings.append(
                                Finding(
                                    "GC04", sf.rel, node.lineno,
                                    f"direct dial `{dotted}` in {fi.qual} "
                                    "outside retry_async",
                                    hint="wrap the dial in a closure passed "
                                    "to utils.backoff.retry_async, or "
                                    "disable with a justification if this "
                                    "is a listen-side bind / deliberate "
                                    "fail-fast path",
                                )
                            )
                stack.extend(ast.iter_child_nodes(node))
    return findings
