"""graftcheck engine: finding model, source index, config, baseline.

The engine is deliberately dependency-free (ast + stdlib only) so the
checker can run in any environment the package imports in — including
the tier-1 pytest gate, where tests/test_static_analysis.py runs the
full suite over the real tree and asserts zero non-baselined findings.
"""

from __future__ import annotations

import ast
import fnmatch
import io
import json
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable

# -- finding model ----------------------------------------------------------

RULES = (
    "GC01", "GC02", "GC03", "GC04", "GC05", "GC06", "GC07", "GC08", "GC09",
    "GC10", "GC11", "GC12",
)

# Parse/config failures surface as findings too (rule GC00) so the runner
# has one reporting path; compileall in tools/check.py catches the rest.
PARSE_RULE = "GC00"


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int          # 1-based
    message: str
    hint: str = ""     # fix hint shown to the developer

    def render(self) -> str:
        s = f"{self.path}:{self.line}: {self.rule}: {self.message}"
        if self.hint:
            s += f"  [hint: {self.hint}]"
        return s


# -- source files + suppressions -------------------------------------------

_DISABLE_RE = re.compile(r"#\s*graftcheck:\s*disable=([A-Z0-9,\s]+)")
_DISABLE_FILE_RE = re.compile(r"#\s*graftcheck:\s*disable-file=([A-Z0-9,\s]+)")


def _rule_list(raw: str) -> set[str]:
    return {r.strip() for r in raw.split(",") if r.strip()}


class SourceFile:
    """One parsed module: AST + raw lines + suppression directives."""

    def __init__(self, abspath: Path, rel: str, modname: str, text: str):
        self.abspath = abspath
        self.rel = rel
        self.modname = modname
        self.text = text
        self.lines = text.splitlines()
        self.parse_error: SyntaxError | None = None
        try:
            self.tree: ast.Module | None = ast.parse(text)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = e
        # line (1-based) → rules disabled on exactly that line
        self.line_disables: dict[int, set[str]] = {}
        self.file_disables: set[str] = set()
        for i, line in self._directive_lines():
            m = _DISABLE_FILE_RE.search(line)
            if m:
                self.file_disables |= _rule_list(m.group(1))
                continue
            m = _DISABLE_RE.search(line)
            if m:
                self.line_disables.setdefault(i, set()).update(
                    _rule_list(m.group(1))
                )

    def _directive_lines(self):
        """(lineno, comment text) for real COMMENT tokens only.

        Tokenizing (rather than scanning raw lines) keeps directive text
        quoted inside docstrings — e.g. the suppression docs in
        analysis/__init__.py — from registering as live suppressions,
        which matters now that a suppression matching no finding is
        itself an error. Falls back to the raw-line scan when the file
        doesn't tokenize (it then has a parse_error finding anyway).
        """
        try:
            return [
                (tok.start[0], tok.string)
                for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline
                )
                if tok.type == tokenize.COMMENT and "graftcheck" in tok.string
            ]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return [
                (i, line)
                for i, line in enumerate(self.lines, start=1)
                if "graftcheck" in line
            ]

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_disables:
            return True
        return rule in self.line_disables.get(line, set())

    def line_content(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


class Project:
    """Every scanned module, indexed by relative path and module name."""

    def __init__(self, root: Path, files: list[SourceFile]):
        self.root = root
        self.files = files
        self.by_rel = {f.rel: f for f in files}
        self.by_mod = {f.modname: f for f in files}
        self._callgraph = None

    def under(self, prefixes: list[str]) -> list[SourceFile]:
        """Files whose relative path starts with any prefix (a prefix may
        also name a single file exactly)."""
        out = []
        for f in self.files:
            for p in prefixes:
                p = p.rstrip("/")
                if f.rel == p or f.rel.startswith(p + "/"):
                    out.append(f)
                    break
        return out

    @property
    def callgraph(self):
        if self._callgraph is None:
            from livekit_server_tpu.analysis.callgraph import CallGraph

            self._callgraph = CallGraph(self)
        return self._callgraph


def load_project(root: Path, paths: list[str]) -> Project:
    root = Path(root)
    files: list[SourceFile] = []
    seen: set[str] = set()
    for p in paths:
        base = root / p
        candidates = [base] if base.is_file() else sorted(base.rglob("*.py"))
        for f in candidates:
            rel = f.relative_to(root).as_posix()
            if rel in seen:
                continue
            seen.add(rel)
            modname = rel[:-3].replace("/", ".")
            if modname.endswith(".__init__"):
                modname = modname[: -len(".__init__")]
            files.append(SourceFile(f, rel, modname, f.read_text()))
    return Project(root, files)


# -- config -----------------------------------------------------------------

DEFAULT_CONFIG: dict = {
    "paths": ["livekit_server_tpu"],
    "baseline": "tools/graftcheck_baseline.json",
    "gc01": {
        "paths": ["livekit_server_tpu/runtime", "livekit_server_tpu/service"],
        # self.state is guarded inside these classes — plus any class whose
        # body mentions a guarded lock (a class carrying the donation lock
        # must use it).
        "state_classes": ["PlaneRuntime"],
        # attribute tails that denote a PlaneRuntime held by another object
        # (self.runtime.state, rt.state, ...)
        "runtime_names": ["runtime", "rt"],
        # methods that touch the donated state on behalf of the caller —
        # calling one requires the lock exactly like touching state does.
        # The three-stage split keeps _stage_host/_schedule_probe OUT of
        # this set: they read host mirrors only and run lock-free,
        # overlapped with the in-flight device step.
        "state_methods": [
            "snapshot", "snapshot_room", "restore", "restore_room",
            "repair_room_row", "_upload_ctrl", "_device_step",
        ],
        "lock_names": ["state_lock"],
        # lock-held-by-contract: bodies may touch state because every
        # caller holds state_lock (enforced via the state_methods check).
        # IntegrityMonitor.maybe_audit and FaultInjector.maybe_bitflip run
        # inside _device_step (itself lock-held) on the worker thread.
        "lock_held": [
            "PlaneRuntime.__init__",
            "PlaneRuntime._upload_ctrl",
            "PlaneRuntime._device_step",
            "PlaneRuntime.snapshot",
            "PlaneRuntime.snapshot_room",
            "PlaneRuntime.restore",
            "PlaneRuntime.restore_room",
            "PlaneRuntime.repair_room_row",
            "IntegrityMonitor.maybe_audit",
            "FaultInjector.maybe_bitflip",
        ],
    },
    "gc02": {
        "paths": ["livekit_server_tpu"],
        # extra jit roots by qualified name when the wrap site is dynamic
        "extra_roots": [],
        "banned_prefixes": [
            "time.", "random.", "numpy.random.", "threading.", "socket.",
            "logging.", "asyncio.", "subprocess.", "os.path.",
        ],
        "banned_exact": [
            "print", "open", "numpy.asarray", "numpy.array",
            "numpy.save", "numpy.load", "input",
        ],
        "banned_methods": ["item", "tolist", "block_until_ready"],
        # attribute segment that marks structured-logging / bus receivers:
        # self.log.warn(...), log.info(...), bus.publish(...)
        "banned_receivers": ["log", "logger", "bus"],
    },
    "gc03": {
        "paths": ["livekit_server_tpu"],
        "lock_names": ["state_lock", "_ckpt_lock", "_create_locks"],
        "blocking_calls": [
            "time.sleep", "socket.create_connection", "os.system",
            "subprocess.run", "subprocess.call", "subprocess.check_output",
            "requests.", "urllib.request.",
        ],
    },
    "gc04": {
        "paths": [
            "livekit_server_tpu/routing",
            "livekit_server_tpu/runtime/relay.py",
            "livekit_server_tpu/service",
        ],
        "net_errors": [
            "ConnectionError", "ConnectionResetError", "ConnectionRefusedError",
            "BrokenPipeError", "OSError", "TimeoutError", "IncompleteReadError",
            "socket.error", "asyncio.TimeoutError", "asyncio.IncompleteReadError",
        ],
        "dial_calls": [
            "asyncio.open_connection", "open_connection",
            "create_datagram_endpoint", "create_connection",
        ],
        "retry_helpers": ["retry_async", "CircuitBreaker"],
    },
    "gc05": {
        "paths": [
            "livekit_server_tpu/runtime",
            "livekit_server_tpu/routing",
        ],
        "queue_calls": ["Queue", "LifoQueue", "PriorityQueue"],
        "deque_calls": ["deque"],
    },
    "gc06": {
        # Checkpoint-bearing modules: where serialized state meets the KV
        # bus or the supervisor's snapshot store.
        "paths": [
            "livekit_server_tpu/runtime/plane_runtime.py",
            "livekit_server_tpu/runtime/supervisor.py",
            "livekit_server_tpu/runtime/integrity.py",
            "livekit_server_tpu/service/roommanager.py",
            "livekit_server_tpu/service/store.py",
            "livekit_server_tpu/routing",
        ],
        "exempt": ["livekit_server_tpu/utils/checksum.py"],
        "serializer_calls": [
            "pickle.dumps", "pickle.dump", "marshal.dumps", "marshal.dump",
            "numpy.save", "np.save",
        ],
        "serializer_tails": ["savez", "savez_compressed", "tobytes"],
        "codec_calls": [
            "encode_frame", "encode_frame_b64",
            "decode_frame", "decode_frame_b64",
        ],
    },
    "gc07": {
        # Flight-recorder emit hygiene: the tick loop and the planes it
        # drives synchronously. service/ is included because roommanager
        # emits lifecycle events from the dispatch path.
        "paths": [
            "livekit_server_tpu/runtime",
            "livekit_server_tpu/service",
        ],
        # method tails that are bounded non-allocating recorders — their
        # ARGUMENTS must not allocate either.
        "emit_calls": [
            "record_tick", "set_shard", "emit",
            "observe_batch", "observe_express",
        ],
        # identifier substrings that mark a decimating `if` — inside such
        # a branch the allocation is paid 1-in-K times, which is fine.
        "sample_guards": ["sample", "sampled", "mask", "stamped"],
    },
    "gc08": {
        # Page-handle staleness: anywhere that can mint device page
        # indices from the pager. runtime/ holds the paged runtime and
        # integrity/migration consumers; service/ holds roommanager.
        "paths": [
            "livekit_server_tpu/runtime",
            "livekit_server_tpu/service",
        ],
        # call tails whose result is an epoch-scoped page handle
        "mint_calls": ["pages_of_room"],
        # call tails that re-validate a held handle's epoch
        "revalidate_calls": ["check_epoch"],
        # lock names whose `with` exit is a staleness boundary (another
        # thread may compact once the state lock drops)
        "lock_names": ["state_lock"],
    },
    "gc09": {
        # Fencing discipline: room-ownership KV state may only be
        # mutated through the epoch-fenced writer API. routing/ holds
        # the fence and the pin movers; service/ holds checkpoint and
        # failover writers.
        "paths": [
            "livekit_server_tpu/routing",
            "livekit_server_tpu/service",
        ],
        # literal key prefixes that are epoch-fenced
        "fenced_prefixes": [
            "room_checkpoint:",
            "room_snapshot:",
            "room_epoch:",
        ],
        # hash literals / module constants that hold room→node pins
        "pin_hashes": ["room_node_map"],
        "pin_hash_names": ["NODE_ROOM_KEY"],
        # the sanctioned writers: the fence itself plus the pin movers
        # that claim/transfer an epoch before touching the hash
        "allowed_in": [
            "RoomFence.*",
            "KVRouter.set_node_for_room",
            "KVRouter.clear_room_state",
            "FailoverOrchestrator.run_once",
        ],
    },
    "gc10": {
        # Donation discipline at jit wrap sites. The semantic half (do
        # donated leaves actually alias an output of matching shape/
        # dtype?) runs in devicecheck.py against the entry registry;
        # this AST half catches the wrap-site shapes the registry can't
        # see: a mutated-state tick jitted WITHOUT donation (a silent
        # whole-buffer copy per tick) and donate indices that point at
        # missing or unused parameters.
        "paths": ["livekit_server_tpu"],
        # parameter names that denote the mutated plane buffer: a traced
        # function taking AND returning one must donate it.
        "state_params": ["state"],
        # wrap sites inside these functions (fnmatch on Class.method /
        # outer.inner) may legitimately skip donation: init/restore
        # paths run once and often need the un-donated source intact.
        "allow_missing": [
            "*restore*", "*init*", "*_build_live_decide*",
        ],
    },
    "gc11": {
        # Retrace stability: jit wrappers whose static args or wrap
        # pattern cause per-call retraces. The runtime half is the
        # CompileLedger watchdog (runtime/compile_ledger.py).
        "paths": ["livekit_server_tpu"],
        # decorators that make a per-call jit construction safe (the
        # wrapper is built once and memoized)
        "cache_decorators": ["lru_cache", "cache"],
    },
    "gc12": {
        # Host-sync hygiene: blocking device reads reachable from the
        # tick path must happen only at the declared drain/telemetry
        # seams. Roots are the per-tick driver methods; seams are the
        # sanctioned device→host transfer points (fnmatch quals).
        "paths": ["livekit_server_tpu/runtime"],
        "roots": [
            "PlaneRuntime._device_step",
            "PlaneRuntime._stage_host",
            "PlaneRuntime._upload_ctrl",
            "PlaneRuntime._complete",
            "PagedPlaneRuntime._device_step",
            "PagedPlaneRuntime._live_step",
            "PagedPlaneRuntime._sync_pages",
            "PagedPlaneRuntime._upload_ctrl",
        ],
        "seams": [
            "*._unpack_outputs",
            "*._sel_mirror",
            "*.maybe_audit",
            "*.maybe_bitflip",
            "*._audit_page_table",
            "*.map_audit_mask",
            "*.post_mirror",
            "*.record_tick",
        ],
        # np.asarray / np.array / float() / int() are host-side no-ops
        # on host data; they only block when fed a device array. Flag
        # them when the argument expression mentions one of these names
        # (device-resident by convention in the runtime).
        "device_names": ["state", "out", "buf", "dec", "table"],
    },
    "devicecheck": {
        # Compile-contract registry (analysis/devicecheck.py): entries,
        # canonical dims and the committed baseline live there; this
        # table only carries the knobs.
        "baseline": "tools/devicecheck_baseline.json",
        # relative tolerance on the jaxpr-derived flop/byte estimates —
        # shapes and dtypes compare exactly, cost drifts only fail past
        # this band (a broadcast blow-up moves cost by integer factors).
        "cost_rtol": 0.25,
        # entries allowed to skip donation entirely (init/constant/
        # compact-extent paths where outputs cannot alias inputs)
        "allow_no_donate": [
            "plane.init_state", "paged.page_init_template",
            "paged.dead_page_outputs", "paged_kernel.decide_pages",
            "mix.mix_tick", "mix.decode_tick", "mixer.device_mix",
        ],
        # minimum leaf size (bytes) above which a mutated-and-returned
        # buffer must be donated
        "min_donate_bytes": 1048576,
    },
}


@dataclass
class Config:
    root: Path
    paths: list[str] = field(default_factory=lambda: ["livekit_server_tpu"])
    baseline: str = "tools/graftcheck_baseline.json"
    rules: dict = field(default_factory=dict)

    def rule(self, name: str) -> dict:
        """Per-rule table: defaults overlaid with pyproject overrides."""
        merged = dict(DEFAULT_CONFIG.get(name, {}))
        merged.update(self.rules.get(name, {}))
        return merged


def load_config(root: Path) -> Config:
    """[tool.graftcheck] from pyproject.toml over the built-in defaults."""
    raw: dict = {}
    pyproject = Path(root) / "pyproject.toml"
    if pyproject.exists():
        try:
            import tomllib  # py311+
        except ImportError:
            import tomli as tomllib  # this image ships tomli on 3.10
        raw = (
            tomllib.loads(pyproject.read_text())
            .get("tool", {})
            .get("graftcheck", {})
        )
    cfg = Config(root=Path(root))
    cfg.paths = raw.get("paths", DEFAULT_CONFIG["paths"])
    cfg.baseline = raw.get("baseline", DEFAULT_CONFIG["baseline"])
    cfg.rules = {k: v for k, v in raw.items() if isinstance(v, dict)}
    return cfg


def qual_allowed(qual: str, patterns: list[str]) -> bool:
    """fnmatch a function qualname (`Class.method` / `outer.inner`)
    against the config allowlist."""
    return any(fnmatch.fnmatchcase(qual, pat) for pat in patterns)


# -- engine -----------------------------------------------------------------

def run_all(
    project: Project, config: Config, rules: list[str] | None = None,
    stale_suppressions: list[Finding] | None = None,
) -> list[Finding]:
    """Run the analyzers, apply per-line/file suppressions, sort.

    When `stale_suppressions` is passed, inline `# graftcheck: disable=`
    directives that suppressed NOTHING for a rule that ran are appended
    to it as GC00 findings — the shrink-only contract for the baseline,
    extended to suppressions: a directive may only exist while its
    finding does.
    """
    from livekit_server_tpu.analysis import (
        gc01,
        gc02,
        gc03,
        gc04,
        gc05,
        gc06,
        gc07,
        gc08,
        gc09,
        gc10,
        gc11,
        gc12,
    )

    impls: dict[str, Callable[[Project, dict], list[Finding]]] = {
        "GC01": gc01.run,
        "GC02": gc02.run,
        "GC03": gc03.run,
        "GC04": gc04.run,
        "GC05": gc05.run,
        "GC06": gc06.run,
        "GC07": gc07.run,
        "GC08": gc08.run,
        "GC09": gc09.run,
        "GC10": gc10.run,
        "GC11": gc11.run,
        "GC12": gc12.run,
    }
    findings: list[Finding] = []
    for f in project.files:
        if f.parse_error is not None:
            findings.append(
                Finding(
                    PARSE_RULE, f.rel, f.parse_error.lineno or 0,
                    f"syntax error: {f.parse_error.msg}",
                )
            )
    ran = list(rules or list(impls))
    for rule in ran:
        findings.extend(impls[rule](project, config.rule(rule.lower())))
    kept = []
    hit: set[tuple[str, int, str]] = set()      # (path, line, rule) used
    hit_file: set[tuple[str, str]] = set()      # (path, rule) used
    for fd in findings:
        sf = project.by_rel.get(fd.path)
        if sf is not None and sf.suppressed(fd.rule, fd.line):
            hit_file.add((fd.path, fd.rule))
            if fd.rule in sf.line_disables.get(fd.line, set()):
                hit.add((fd.path, fd.line, fd.rule))
            continue
        kept.append(fd)
    if stale_suppressions is not None:
        ran_set = set(ran)
        for sf in project.files:
            for line, ruleset in sorted(sf.line_disables.items()):
                for rule in sorted(ruleset & ran_set):
                    if (sf.rel, line, rule) not in hit:
                        stale_suppressions.append(Finding(
                            PARSE_RULE, sf.rel, line,
                            f"stale suppression: disable={rule} matches "
                            "no finding on this line",
                            hint="the finding it silenced is gone — "
                            "delete the directive",
                        ))
            for rule in sorted(sf.file_disables & ran_set):
                if (sf.rel, rule) not in hit_file:
                    stale_suppressions.append(Finding(
                        PARSE_RULE, sf.rel, 1,
                        f"stale suppression: disable-file={rule} matches "
                        "no finding in this file",
                        hint="the findings it silenced are gone — "
                        "delete the directive",
                    ))
    kept.sort(key=lambda fd: (fd.path, fd.line, fd.rule, fd.message))
    return kept


# -- baseline ---------------------------------------------------------------
#
# Entries key on (rule, path, stripped line content) rather than line
# numbers, so unrelated edits above a baselined finding don't churn the
# file. Identical lines are disambiguated by an occurrence counter.

def _baseline_key(fd: Finding, project: Project) -> tuple[str, str, str]:
    sf = project.by_rel.get(fd.path)
    content = sf.line_content(fd.line) if sf is not None else ""
    return (fd.rule, fd.path, content)


def load_baseline(path: Path) -> list[dict]:
    p = Path(path)
    if not p.exists():
        return []
    data = json.loads(p.read_text())
    return data.get("findings", [])


def write_baseline(path: Path, findings: list[Finding], project: Project) -> None:
    entries = [
        {"rule": r, "path": p, "content": c}
        for (r, p, c) in sorted(_baseline_key(fd, project) for fd in findings)
    ]
    Path(path).write_text(
        json.dumps({"version": 1, "findings": entries}, indent=1) + "\n"
    )


def diff_baseline(
    findings: list[Finding], baseline: list[dict], project: Project
) -> tuple[list[Finding], list[dict]]:
    """→ (new findings not covered by the baseline, stale baseline entries
    whose finding no longer exists). Stale entries FAIL the run: the
    baseline may only shrink, never silently rot."""
    from collections import Counter

    have = Counter(
        (e.get("rule", ""), e.get("path", ""), e.get("content", ""))
        for e in baseline
    )
    new: list[Finding] = []
    for fd in findings:
        key = _baseline_key(fd, project)
        if have.get(key, 0) > 0:
            have[key] -= 1
        else:
            new.append(fd)
    stale = [
        {"rule": r, "path": p, "content": c}
        for (r, p, c), n in have.items()
        for _ in range(n)
        if n > 0
    ]
    return new, stale
