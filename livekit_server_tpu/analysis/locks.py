"""Lexical lock-region analysis shared by GC01 and GC03.

Locks are identified by the final attribute segment of the guarded
expression (`self.runtime.state_lock` → "state_lock"), which matches how
this codebase names them: one donation lock per runtime, one checkpoint
lock per manager. A name bound from a lock container
(`lock = self._create_locks.setdefault(...)`) aliases to the container's
name.

Two acquisition shapes are recognized:

  * ``async with <lockexpr>:`` / ``with <lockexpr>:`` — held for the body
  * ``await <lockexpr>.acquire()`` … ``<lockexpr>.release()`` — held for
    the statements between them in the same block (the serving loop's
    explicit-acquire shape in PlaneRuntime._run); a release inside a
    ``finally`` closes the region after its try statement, so the try
    body itself is analyzed as held

Nested function bodies do NOT inherit the enclosing held set: a closure
defined under a lock runs whenever it is later called, not while the
lock is held.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from livekit_server_tpu.analysis.callgraph import dotted_name


def lock_aliases(func_node: ast.AST, lock_names: set[str]) -> dict[str, str]:
    """Local names bound from expressions that mention a lock container:
    `lock = self._create_locks.setdefault(n, Lock())` → {lock: _create_locks}."""
    out: dict[str, str] = {}
    for node in ast.walk(func_node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Attribute) and sub.attr in lock_names:
                    out[node.targets[0].id] = sub.attr
                elif isinstance(sub, ast.Name) and sub.id in lock_names:
                    out[node.targets[0].id] = sub.id
    return out


def match_lock(expr: ast.AST, lock_names: set[str],
               aliases: dict[str, str]) -> str | None:
    """Lock name if `expr` denotes one of the configured locks."""
    dotted = dotted_name(expr)
    if dotted is None:
        return None
    tail = dotted.rsplit(".", 1)[-1]
    if tail in lock_names:
        return tail
    return aliases.get(dotted)


@dataclass
class LockInfo:
    """Per-function lexical lock facts."""

    # id(ast node) → frozenset of lock names held at that node
    held_at: dict[int, frozenset] = field(default_factory=dict)
    # (lock, node, held-before) for every acquisition site
    acquisitions: list[tuple[str, ast.AST, frozenset]] = field(
        default_factory=list
    )
    # (call node, held) for every call made while ≥1 lock is held
    locked_calls: list[tuple[ast.Call, frozenset]] = field(
        default_factory=list
    )

    def held(self, node: ast.AST) -> frozenset:
        return self.held_at.get(id(node), frozenset())


def _acquire_of(stmt: ast.stmt, lock_names, aliases) -> str | None:
    """Lock name when stmt is `await <lock>.acquire()` (possibly assigned)."""
    expr = stmt.value if isinstance(stmt, (ast.Expr, ast.Assign)) else None
    if isinstance(expr, ast.Await):
        expr = expr.value
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute) \
            and expr.func.attr == "acquire":
        return match_lock(expr.func.value, lock_names, aliases)
    return None


def _releases_in(stmt: ast.stmt, lock_names, aliases) -> set[str]:
    """Locks released anywhere inside stmt (e.g. in its finally block)."""
    out: set[str] = set()
    for node in ast.walk(stmt):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "release":
            lock = match_lock(node.func.value, lock_names, aliases)
            if lock:
                out.add(lock)
    return out


_NESTED = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


_COMPOUND = (ast.If, ast.While, ast.For, ast.AsyncFor, ast.Try)


def analyze_function(func_node: ast.AST, lock_names) -> LockInfo:
    lock_names = set(lock_names)
    aliases = lock_aliases(func_node, lock_names)
    info = LockInfo()

    def mark(node: ast.AST, held: frozenset) -> None:
        """Annotate an expression/simple-statement subtree. Nested defs
        restart at ∅; nested with-statements restate their own held sets."""
        stack = [node]
        while stack:
            n = stack.pop()
            info.held_at[id(n)] = held
            if isinstance(n, ast.Call) and held:
                info.locked_calls.append((n, held))
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    visit_block(child.body, frozenset())
                elif isinstance(child, ast.Lambda):
                    mark(child.body, frozenset())
                elif isinstance(child, (ast.With, ast.AsyncWith)):
                    visit_with(child, held)
                else:
                    stack.append(child)

    def visit_with(node: ast.With | ast.AsyncWith, held: frozenset) -> None:
        info.held_at[id(node)] = held
        acquired = set()
        for item in node.items:
            mark(item.context_expr, held)
            lock = match_lock(item.context_expr, lock_names, aliases)
            if lock:
                info.acquisitions.append((lock, node, held))
                acquired.add(lock)
        visit_block(node.body, held | frozenset(acquired))

    def visit_stmt(stmt: ast.stmt, held: frozenset) -> frozenset:
        """Process one statement; return the held set after it."""
        acq = _acquire_of(stmt, lock_names, aliases)
        if acq is not None:
            info.held_at[id(stmt)] = held
            info.acquisitions.append((acq, stmt, held))
            return held | {acq}
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            info.held_at[id(stmt)] = held
            visit_block(stmt.body, frozenset())
            return held
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            visit_with(stmt, held)
            return held - _releases_in(stmt, lock_names, aliases)
        if isinstance(stmt, _COMPOUND):
            info.held_at[id(stmt)] = held
            # header expressions (test / iter) run with the entry set
            for child in ast.iter_child_nodes(stmt):
                if not isinstance(child, (ast.stmt, ast.ExceptHandler)):
                    mark(child, held)
            if isinstance(stmt, ast.Try):
                # An acquire in the try body stays held through the
                # finally (where this codebase releases it); handlers
                # may be entered before the acquire, so they start at
                # the entry set — conservative both ways.
                h = visit_block(stmt.body, held)
                for handler in stmt.handlers:
                    info.held_at[id(handler)] = held
                    visit_block(handler.body, held)
                visit_block(stmt.orelse, h)
                visit_block(stmt.finalbody, h)
                return h - _releases_in(stmt, lock_names, aliases)
            visit_block(stmt.body, held)
            visit_block(getattr(stmt, "orelse", []), held)
            # a branch-local acquire does not propagate out; releases do
            return held - _releases_in(stmt, lock_names, aliases)
        mark(stmt, held)
        return held - _releases_in(stmt, lock_names, aliases)

    def visit_block(body, held: frozenset) -> frozenset:
        if not isinstance(body, list):
            mark(body, held)  # Lambda body expression
            return held
        for stmt in body:
            held = visit_stmt(stmt, held)
        return held

    visit_block(getattr(func_node, "body", []), frozenset())
    return info
