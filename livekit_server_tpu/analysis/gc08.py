"""GC08 — page-handle staleness across await / lock-release boundaries.

Device page indices minted from the pager (`pages_of_room(...)` and
friends) are only valid for the page-table epoch they were minted at:
any structural pager change — alloc, grow, release, compaction — bumps
`RoomPager.epoch` and may remap or free the pages behind the handle.
Inside one locked, synchronous region that is safe by construction;
the hazard is a handle that SURVIVES a scheduling boundary:

- an `await` between mint and use (the event loop may run an admission
  or a drain that reallocates the pages), or
- minting inside a `with state_lock:` block and using the handle after
  the block exits (another thread may compact between).

This rule flags any use of a minted handle after such a boundary,
unless a configured revalidation call (`check_epoch(...)` by default)
or a re-mint sits between the boundary and the use. Epoch-pinned
wrappers (`LayoutXlate`) re-validate internally and are not handles.

Deliberate exceptions carry `# graftcheck: disable=GC08` with a
justification.
"""

from __future__ import annotations

import ast

from livekit_server_tpu.analysis.callgraph import dotted_name
from livekit_server_tpu.analysis.core import Finding, Project

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _call_tail(node: ast.expr) -> str | None:
    if not isinstance(node, ast.Call):
        return None
    dotted = dotted_name(node.func)
    return dotted.rsplit(".", 1)[-1] if dotted else None


def _walk_skip_nested(fn: ast.AST):
    """Walk a function body without descending into nested defs (their
    handles live in their own scope and are analyzed separately)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _FUNCS):
            continue
        stack.extend(ast.iter_child_nodes(node))


_WITHS = (ast.With, ast.AsyncWith)


def _lock_with(node: ast.With | ast.AsyncWith, lock_names: set[str]) -> bool:
    for item in node.items:
        for sub in ast.walk(item.context_expr):
            if isinstance(sub, ast.Attribute) and sub.attr in lock_names:
                return True
            if isinstance(sub, ast.Name) and sub.id in lock_names:
                return True
    return False


def run(project: Project, cfg: dict) -> list[Finding]:
    mint_calls = set(cfg["mint_calls"])
    revalidate = set(cfg["revalidate_calls"])
    lock_names = set(cfg["lock_names"])
    findings: list[Finding] = []
    for sf in project.under(cfg["paths"]):
        if sf.tree is None:
            continue
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, _FUNCS):
                continue
            # mints: var name -> [(mint line, enclosing lock-with end or 0)]
            mints: dict[str, list[tuple[int, int]]] = {}
            awaits: list[int] = []
            revals: list[int] = []
            uses: list[tuple[int, str]] = []
            lock_spans: list[tuple[int, int]] = []
            for node in ast.walk(fn):
                if isinstance(node, _WITHS) and _lock_with(node, lock_names):
                    lock_spans.append((node.lineno, node.end_lineno or node.lineno))
            for node in _walk_skip_nested(fn):
                if isinstance(node, ast.Await):
                    awaits.append(node.lineno)
                elif isinstance(node, ast.Call):
                    tail = _call_tail(node)
                    if tail in revalidate:
                        revals.append(node.lineno)
                elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt = node.targets[0]
                    if isinstance(tgt, ast.Name) and _call_tail(node.value) in mint_calls:
                        # earliest lock release after the mint = first
                        # point the handle can go stale under contention
                        span_end = 0
                        for lo, hi in lock_spans:
                            if lo <= node.lineno <= hi:
                                span_end = hi if not span_end else min(span_end, hi)
                        mints.setdefault(tgt.id, []).append((node.lineno, span_end))
                elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                    uses.append((node.lineno, node.id))
            if not mints:
                continue
            flagged: set[str] = set()
            for use, name in sorted(uses):
                if name in flagged or name not in mints:
                    continue
                # a use is scoped to the LATEST mint before it (a re-mint
                # starts a fresh epoch-valid handle)
                prior = [m for m in mints[name] if m[0] < use]
                if not prior:
                    continue
                mint_line, lock_end = max(prior)
                boundary = 0
                for aw in awaits:
                    if mint_line < aw <= use:
                        boundary = max(boundary, aw)
                if lock_end and use > lock_end:
                    boundary = max(boundary, lock_end)
                if not boundary:
                    continue
                if any(boundary < rv <= use for rv in revals):
                    continue
                kind = (
                    "an await" if boundary in awaits
                    else f"the {'/'.join(sorted(lock_names))} release"
                )
                findings.append(
                    Finding(
                        "GC08", sf.rel, use,
                        f"page handle `{name}` (minted line {mint_line}) "
                        f"used across {kind} without epoch revalidation",
                        hint="the pager may alloc/grow/compact at any "
                        "scheduling boundary; call pager.check_epoch(...) "
                        "or re-fetch the pages after the boundary",
                    )
                )
                # one finding per handle keeps the output readable
                flagged.add(name)
    return findings
