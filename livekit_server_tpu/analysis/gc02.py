"""GC02 — tracer purity.

Functions traced by `jax.jit` / `shard_map` / `pl.pallas_call` execute
once at trace time and then replay as compiled XLA: host side effects
inside them (wall clocks, RNG, numpy materialization, logging, bus I/O,
threading) silently freeze into the graph or fire at trace time only.
This rule walks the wrapper call graph from every wrap site — including
the nested-closure shapes the runtime uses (`_build_step`'s `tick`,
mesh.make_sharded_tick's `shard_map` + `jit` rebinding, partial-wrapped
Pallas kernels, `@functools.partial(jax.jit, ...)` decorators) — and
flags banned calls anywhere in the reachable set. Everything lexically
inside a traced function (lambdas, nested defs) is traced with it, so
nested bodies are scanned too.
"""

from __future__ import annotations

import ast

from livekit_server_tpu.analysis.callgraph import (
    FuncInfo,
    body_calls,
    dotted_name,
    local_assignments,
)
from livekit_server_tpu.analysis.core import Finding, Project

_WRAPPERS = {"jit", "shard_map", "pallas_call"}


def _wrapper_tail(expr: ast.AST, cg, modname: str) -> str | None:
    dotted = dotted_name(expr)
    if dotted is None:
        return None
    tail = cg.expand_alias(dotted, modname).rsplit(".", 1)[-1]
    return tail if tail in _WRAPPERS else None


def _roots(project: Project, cfg: dict) -> list[tuple[FuncInfo, str]]:
    """(traced function, wrap-site description) for every wrap site."""
    cg = project.callgraph
    roots: list[tuple[FuncInfo, str]] = []

    def try_root(expr: ast.AST, scope, sf, assigns, site: str) -> None:
        target = cg.resolve(expr, scope, sf, assigns)
        if target is not None:
            roots.append((target, site))

    for sf in project.under(cfg["paths"]):
        if sf.tree is None:
            continue
        for (mod, qual), fi in cg.funcs.items():
            if mod != sf.modname:
                continue
            assigns = local_assignments(fi.node)
            # decorator roots: @jax.jit / @functools.partial(jax.jit, ...)
            for dec in getattr(fi.node, "decorator_list", []):
                wrapped = None
                if _wrapper_tail(dec, cg, sf.modname):
                    wrapped = fi
                elif isinstance(dec, ast.Call):
                    inner = dec.args[0] if dec.args else None
                    if _wrapper_tail(dec.func, cg, sf.modname) or (
                        inner is not None
                        and _wrapper_tail(inner, cg, sf.modname)
                    ):
                        wrapped = fi
                if wrapped is not None:
                    site = f"{sf.rel}:{fi.node.lineno} (@decorator)"
                    roots.append((wrapped, site))
            # call roots inside this function: jit(f) / shard_map(f, ...)
            for call in body_calls(fi.node):
                if _wrapper_tail(call.func, cg, sf.modname) and call.args:
                    site = f"{sf.rel}:{call.lineno}"
                    try_root(call.args[0], fi, sf, assigns, site)
        # module-level wrap sites
        for stmt in sf.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for call in ast.walk(stmt):
                if isinstance(call, ast.Call) and \
                        _wrapper_tail(call.func, cg, sf.modname) and call.args:
                    try_root(call.args[0], None, sf,
                             None, f"{sf.rel}:{call.lineno}")
    for qual in cfg.get("extra_roots", []):
        mod, _, name = qual.rpartition(".")
        fi = cg.funcs.get((mod, name))
        if fi is not None:
            roots.append((fi, f"extra_roots:{qual}"))
    return roots


def _banned(call: ast.Call, cg, modname: str, cfg: dict) -> str | None:
    """Reason string when this call is impure in traced code."""
    dotted = dotted_name(call.func)
    if dotted is not None:
        full = cg.expand_alias(dotted, modname)
        if full in cfg["banned_exact"]:
            return f"`{dotted}` materializes host state"
        for p in cfg["banned_prefixes"]:
            if full.startswith(p):
                return f"`{dotted}` is host-side ({p}*)"
        parts = dotted.split(".")
        for seg in parts[:-1]:
            if seg in cfg["banned_receivers"]:
                return f"`{dotted}` is logging/bus I/O"
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr in cfg["banned_methods"]:
        return f"`.{call.func.attr}()` forces a host sync"
    return None


def run(project: Project, cfg: dict) -> list[Finding]:
    cg = project.callgraph
    findings: list[Finding] = []
    seen_funcs: set[int] = set()
    seen_sites: set[tuple[str, int, str]] = set()
    queue = _roots(project, cfg)
    while queue:
        fi, site = queue.pop()
        if id(fi) in seen_funcs:
            continue
        seen_funcs.add(id(fi))
        sf = fi.module
        assigns = local_assignments(fi.node)
        # everything lexically inside a traced function is traced with it
        for call in body_calls(fi.node, include_nested=True):
            why = _banned(call, cg, sf.modname, cfg)
            if why is not None:
                key = (sf.rel, call.lineno, why)
                if key not in seen_sites:
                    seen_sites.add(key)
                    findings.append(
                        Finding(
                            "GC02", sf.rel, call.lineno,
                            f"{why} inside `{fi.qual}`, which is traced "
                            f"(jit/shard_map/pallas wrap at {site})",
                            hint="hoist the host effect out of the traced "
                            "function; pass results in as arguments",
                        )
                    )
                continue
            callee = cg.resolve(call.func, fi, sf, assigns)
            if callee is not None:
                queue.append((callee, site))
    return findings
