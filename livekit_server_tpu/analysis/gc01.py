"""GC01 — donation safety.

`PlaneRuntime.state` is a tree of DONATED device buffers:
`jax.jit(tick, donate_argnums=(0,))` invalidates the input buffers the
moment a step launches, and the step runs on a worker thread. Any host
read or write of `self.state` (or a call into a staging method that
touches it) that is not serialized behind `state_lock` can observe or
replace donated memory mid-step — the PR 1 failover race class.

The rule is lexical: the access must sit inside an
`async with ...state_lock:` block (or the explicit
`await state_lock.acquire()` … `release()` region the serving loop
uses), or the enclosing function must be allowlisted in
`[tool.graftcheck.gc01] lock_held` — functions whose *callers* are
required to hold the lock. That contract is itself checked: calling a
state method on a runtime object without the lock is a finding too.
"""

from __future__ import annotations

import ast

from livekit_server_tpu.analysis.callgraph import dotted_name
from livekit_server_tpu.analysis.core import Finding, Project, qual_allowed
from livekit_server_tpu.analysis.locks import analyze_function


def _scoped_classes(sf, cfg) -> set[str]:
    """Classes whose `self.state` is donation-guarded: the configured
    state classes plus any class whose body mentions a guarded lock (a
    class that carries the donation lock must be using it)."""
    out = set(cfg["state_classes"])
    if sf.tree is None:
        return out
    lock_names = set(cfg["lock_names"])
    for node in sf.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and sub.attr in lock_names:
                out.add(node.name)
                break
    return out


def run(project: Project, cfg: dict) -> list[Finding]:
    cg = project.callgraph
    lock_names = set(cfg["lock_names"])
    runtime_names = set(cfg["runtime_names"])
    state_attrs = set(cfg.get("state_attrs", ["state"]))
    state_methods = set(cfg["state_methods"])
    findings: list[Finding] = []

    for sf in project.under(cfg["paths"]):
        if sf.tree is None:
            continue
        scoped = _scoped_classes(sf, cfg)
        for (mod, qual), fi in cg.funcs.items():
            if mod != sf.modname or fi.parent is not None:
                continue
            if qual_allowed(fi.qual, cfg["lock_held"]):
                continue
            info = analyze_function(fi.node, lock_names)
            for node in ast.walk(fi.node):
                dotted = None
                if isinstance(node, ast.Attribute) and node.attr in state_attrs:
                    dotted = dotted_name(node)
                    kind = f"access of `{dotted}`"
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in state_methods:
                    dotted = dotted_name(node.func)
                    kind = f"call to state method `{dotted}()`"
                if dotted is None:
                    continue
                parts = dotted.split(".")
                recv = parts[:-1]
                # self.state inside a donation-guarded class, or
                # <anything>.runtime.state / rt.state from outside it
                mine = recv == ["self"] and fi.cls in scoped
                theirs = recv and recv[-1] in runtime_names
                if not (mine or theirs):
                    continue
                if lock_names & info.held(node):
                    continue
                findings.append(
                    Finding(
                        "GC01", sf.rel, node.lineno,
                        f"{kind} outside state_lock in {fi.qual} — "
                        "the state tree is donated to the device step",
                        hint="wrap in `async with ...state_lock:` or add the "
                        "function to [tool.graftcheck.gc01] lock_held with "
                        "a caller-holds-the-lock contract",
                    )
                )
    return findings
