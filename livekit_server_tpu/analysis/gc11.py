"""GC11 — retrace stability (the static half of the recompile watchdog).

XLA caches compiled executables per (jaxpr, static-arg values): a
static argument that is unhashable kills the wrapper at call time, a
mutable one that callers rebuild per call (fresh list/dict) defeats the
cache and retraces every tick, and a `jax.jit(f)` constructed inside a
hot function is a *new* wrapper — and a new cache — per invocation.
None of these fail loudly; they show up as a compile storm on real
hardware. The runtime half is `runtime/compile_ledger.py`, which counts
post-warmup XLA compilations during seeded drills.

Statically flagged:

  * mutable static — a call site passes a list/dict/set literal (or
    comprehension) for a parameter the jit wrap declared static via
    `static_argnums`/`static_argnames`, or the traced function gives a
    static parameter a mutable default.
  * per-call jit — `jax.jit(f)(...)` invoked immediately inside a
    function body, where the enclosing function is not memoized with a
    `cache_decorators` decorator (`lru_cache`/`cache`). Builders that
    store the wrapper (`self.x = jax.jit(f)`, `cache["fn"] = ...`)
    construct once and are fine.
  * unknown static name — `static_argnames` naming a parameter the
    traced function does not have (the typo compiles until called).
"""

from __future__ import annotations

import ast

from livekit_server_tpu.analysis.callgraph import (
    FuncInfo,
    dotted_name,
    local_assignments,
)
from livekit_server_tpu.analysis.core import Finding, Project

_MUTABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
            ast.SetComp)


def _is_jit(expr: ast.AST, cg, modname: str) -> bool:
    dotted = dotted_name(expr)
    if dotted is None:
        return False
    return cg.expand_alias(dotted, modname).rsplit(".", 1)[-1] == "jit"


def _static_spec(call: ast.Call) -> tuple[list[int], list[str]]:
    nums: list[int] = []
    names: list[str] = []
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            nums = [v.value for v in vals
                    if isinstance(v, ast.Constant) and isinstance(v.value, int)]
        elif kw.arg == "static_argnames":
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            names = [v.value for v in vals
                     if isinstance(v, ast.Constant) and isinstance(v.value, str)]
    return nums, names


def _params(fn_node: ast.AST) -> list[str]:
    a = getattr(fn_node, "args", None)
    if a is None:
        return []
    return [p.arg for p in a.posonlyargs + a.args]


def _defaults(fn_node: ast.AST) -> dict[str, ast.AST]:
    a = getattr(fn_node, "args", None)
    if a is None:
        return {}
    out: dict[str, ast.AST] = {}
    pos = a.posonlyargs + a.args
    for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        out[p.arg] = d
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if d is not None:
            out[p.arg] = d
    return out


def _is_cached(fn_node: ast.AST, cache_decs: set[str]) -> bool:
    for dec in getattr(fn_node, "decorator_list", []):
        expr = dec.func if isinstance(dec, ast.Call) else dec
        dotted = dotted_name(expr)
        if dotted is not None and dotted.rsplit(".", 1)[-1] in cache_decs:
            return True
    return False


def _jit_targets(project: Project, cfg: dict):
    """Map traced FuncInfo id → (static param names, wrap lineno, rel)
    for every jit wrap with a static spec."""
    cg = project.callgraph
    out: dict[int, tuple[FuncInfo, set[str], int, str]] = {}

    def record(call: ast.Call, target: FuncInfo | None, sf):
        if target is None:
            return
        nums, names = _static_spec(call)
        params = _params(target.node)
        statics = set(names)
        for i in nums:
            if i < len(params):
                statics.add(params[i])
        if statics:
            out[id(target)] = (target, statics, call.lineno, sf.rel)

    for sf in project.under(cfg["paths"]):
        if sf.tree is None:
            continue
        for (mod, _), fi in cg.funcs.items():
            if mod != sf.modname:
                continue
            assigns = local_assignments(fi.node)
            for dec in getattr(fi.node, "decorator_list", []):
                if isinstance(dec, ast.Call):
                    inner = dec.args[0] if dec.args else None
                    if _is_jit(dec.func, cg, sf.modname) or (
                        inner is not None and _is_jit(inner, cg, sf.modname)
                    ):
                        record(dec, fi, sf)
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call) and \
                        _is_jit(node.func, cg, sf.modname) and node.args:
                    record(node, cg.resolve(node.args[0], fi, sf, assigns), sf)
        for stmt in sf.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) and \
                        _is_jit(node.func, cg, sf.modname) and node.args:
                    record(node, cg.resolve(node.args[0], None, sf, None), sf)
    return out


def run(project: Project, cfg: dict) -> list[Finding]:
    cg = project.callgraph
    findings: list[Finding] = []
    seen: set[tuple[str, int, str]] = set()
    cache_decs = set(cfg.get("cache_decorators", ["lru_cache", "cache"]))

    def emit(rel, line, msg, hint, tag):
        key = (rel, line, tag)
        if key not in seen:
            seen.add(key)
            findings.append(Finding("GC11", rel, line, msg, hint=hint))

    targets = _jit_targets(project, cfg)

    # mutable defaults / unknown names on the static spec itself
    for target, statics, wline, wrel in targets.values():
        params = set(_params(target.node))
        defaults = _defaults(target.node)
        for name in sorted(statics):
            if name not in params:
                emit(wrel, wline,
                     f"static_argnames names `{name}`, which is not a "
                     f"parameter of `{target.qual}`",
                     "fix the name — the typo only fails at call time",
                     f"unknown:{name}")
            elif name in defaults and isinstance(defaults[name], _MUTABLE):
                emit(target.module.rel, target.node.lineno,
                     f"static parameter `{name}` of `{target.qual}` has a "
                     "mutable default — unhashable at the jit cache key",
                     "use a tuple/frozen value for static defaults",
                     f"default:{name}")

    by_info = {k: (t, s) for k, (t, s, _l, _r) in targets.items()}

    for sf in project.under(cfg["paths"]):
        if sf.tree is None:
            continue
        for (mod, _), fi in cg.funcs.items():
            if mod != sf.modname:
                continue
            assigns = local_assignments(fi.node)
            cached = _is_cached(fi.node, cache_decs)
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                # per-call jit: jax.jit(f)(...) immediately invoked
                if isinstance(node.func, ast.Call) and \
                        _is_jit(node.func.func, cg, sf.modname) and \
                        not cached:
                    emit(sf.rel, node.lineno,
                         f"`jax.jit(...)` built and called in one "
                         f"expression inside `{fi.qual}` — a fresh "
                         "wrapper (and compile cache) per invocation",
                         "build the jitted function once (module level, "
                         "lru_cache'd builder, or self attribute) and "
                         "reuse it",
                         "percall")
                    continue
                # unhashable literal passed for a static parameter
                callee = cg.resolve(node.func, fi, sf, assigns)
                if callee is None or id(callee) not in by_info:
                    continue
                target, statics = by_info[id(callee)]
                params = _params(target.node)
                for i, arg in enumerate(node.args):
                    if i < len(params) and params[i] in statics and \
                            isinstance(arg, _MUTABLE):
                        emit(sf.rel, node.lineno,
                             f"mutable literal passed for static "
                             f"parameter `{params[i]}` of `{target.qual}`"
                             " — unhashable (TypeError) or a retrace "
                             "per call",
                             "pass a hashable value (tuple/int/str)",
                             f"staticarg:{params[i]}")
                for kw in node.keywords:
                    if kw.arg in statics and isinstance(kw.value, _MUTABLE):
                        emit(sf.rel, node.lineno,
                             f"mutable literal passed for static "
                             f"parameter `{kw.arg}` of `{target.qual}` — "
                             "unhashable (TypeError) or a retrace per "
                             "call",
                             "pass a hashable value (tuple/int/str)",
                             f"staticarg:{kw.arg}")
    return findings
