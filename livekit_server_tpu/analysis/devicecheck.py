"""devicecheck — abstract-eval compile contracts for the device plane.

Every hot jit/shard_map/pallas entry point registered via
`@device_entry` (analysis/registry.py) is `jax.eval_shape`'d against
canonical dims derived from `config/config.py` defaults — the dense
north-star plane and the paged pool it maps to — entirely on CPU, with
no device execution and no backend compile. Three artifacts come out
per entry:

  * the output contract: leaf shapes + dtypes (and, for the mesh entry,
    the room-axis partition specs) — catches accidental f64 promotion,
    broadcast blow-ups and lost shardings at review time;
  * a jaxpr-derived FLOP/byte estimate — a deterministic walk of the
    traced jaxpr (dot_general counted as 2·M·N·K, everything else as
    output elements; bytes as in+out leaf sizes). Not a profiler — a
    drift tripwire: a broadcast that materializes a [P,T,K,S] dense
    mask moves these numbers by integer factors;
  * the donation contract (GC10 semantic half): each donated input
    leaf must alias an output leaf of matching shape/dtype (dead
    donations flagged), and any ≥1 MB input leaf whose shape/dtype
    reappears in the outputs must be donated (missing donations
    flagged, `allow_no_donate` for init/constant/compact-extent
    entries).

Contracts snapshot into the committed `tools/devicecheck_baseline.json`
(shrink-only, like the graftcheck baseline: drift or stale entries fail
`tools/check`; re-snapshot intentional changes with
`python -m tools.check --resnapshot`).
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path
from typing import Any, Callable

from livekit_server_tpu.analysis.core import Finding
from livekit_server_tpu.analysis import registry

BASELINE_VERSION = 1

# rule id for contract drift (GC10 keeps the donation findings)
DRIFT_RULE = "DEVC"


# -- canonical dims ---------------------------------------------------------

def canonical_dims():
    """(dense PlaneDims, PagedDims) from the PlaneConfig defaults — the
    same derivation service/roommanager.py uses (pool_pages=0 → dense-
    equivalent capacity)."""
    from livekit_server_tpu.config.config import PlaneConfig
    from livekit_server_tpu.models import paged, plane

    pc = PlaneConfig()
    dense = plane.PlaneDims(
        pc.rooms, pc.tracks_per_room, pc.pkts_per_track, pc.subs_per_room
    )
    pool = pc.pager_pool_pages or (
        pc.rooms
        * (pc.tracks_per_room // pc.pager_tpage)
        * (pc.subs_per_room // pc.pager_spage)
    )
    pdims = paged.PagedDims(
        pc.rooms, pc.tracks_per_room, pc.pkts_per_track, pc.subs_per_room,
        pc.pager_tpage, pc.pager_spage, pool,
    )
    return dense, pdims


def _zero_inputs(dims):
    """Abstract-buildable zero TickInputs at `dims` (traced shapes only —
    call under jax.eval_shape)."""
    import jax.numpy as jnp

    from livekit_server_tpu.models import plane

    R, T, K, S = dims
    z = jnp.zeros
    return plane.TickInputs(
        sn=z((R, T, K), jnp.int32), ts=z((R, T, K), jnp.int32),
        layer=z((R, T, K), jnp.int32), temporal=z((R, T, K), jnp.int32),
        keyframe=z((R, T, K), bool), layer_sync=z((R, T, K), bool),
        begin_pic=z((R, T, K), bool), end_frame=z((R, T, K), bool),
        pid=z((R, T, K), jnp.int32), tl0=z((R, T, K), jnp.int32),
        keyidx=z((R, T, K), jnp.int32), size=z((R, T, K), jnp.int32),
        frame_ms=z((R, T, K), jnp.int32), audio_level=z((R, T, K), jnp.int32),
        arrival_rtp=z((R, T, K), jnp.int32), ts_jump=z((R, T, K), jnp.int32),
        valid=z((R, T, K), bool),
        estimate=z((R, S), jnp.float32), estimate_valid=z((R, S), bool),
        nacks=z((R, S), jnp.float32), pub_rtt_ms=z((R, T), jnp.float32),
        fb_delay_ms=z((R, S), jnp.float32),
        fb_recv_bps=z((R, S), jnp.float32),
        fb_valid=z((R, S), bool), fb_enabled=z((R, S), bool),
        sub_reset=z((R, S), bool), pad_num=z((R, S), jnp.int32),
        pad_track=z((R, S), jnp.int32),
        tick_ms=jnp.int32(10), roll_quality=jnp.int32(0),
    )


# -- entry specs ------------------------------------------------------------
#
# Each spec: a thunk returning (fn, args tuple, kwargs) where args are
# built INSIDE jax.eval_shape (so north-star dims never allocate), plus
# the donation contract the runtime applies when it jits the entry.

class EntrySpec:
    def __init__(self, name: str, build: Callable[[], tuple],
                 donate: tuple[int, ...] = (), mesh_sharded: bool = False,
                 cost_from: str | None = None):
        self.name = name
        self.build = build        # () -> (fn, abstract args tuple, kwargs)
        self.donate = donate
        self.mesh_sharded = mesh_sharded
        # name of an earlier entry this one's contract derives from
        # without tracing: make_sharded_tick shard_maps that same tick,
        # so its out tree, cost and (shape-derived) partition specs are
        # the referenced entry's by construction — re-tracing it through
        # shard_map costs >1 s of the <5 s budget for no new info
        self.cost_from = cost_from


def _specs() -> list[EntrySpec]:
    import jax
    import jax.numpy as jnp

    registry.import_all()
    from livekit_server_tpu.models import paged, plane

    dense, pdims = canonical_dims()
    pooled = pdims.pooled()

    def abstract(th):
        return jax.eval_shape(th)

    def dense_state():
        return abstract(lambda: plane.init_state(dense))

    def dense_inp():
        return abstract(lambda: _zero_inputs(dense))

    def pooled_state():
        return abstract(lambda: plane.init_state(pooled))

    def pooled_inp():
        return abstract(lambda: _zero_inputs(pooled))

    def table():
        return abstract(lambda: paged.init_table(pdims))

    P = pdims.pool_pages
    NL = max(1, P // 2)   # compact live extent: half the pool live
    sds = jax.ShapeDtypeStruct

    def live_rows():
        return sds((NL,), jnp.int32)

    def live_inv():
        return sds((P,), jnp.int32)

    def decide():
        from livekit_server_tpu.ops import paged_kernel
        from livekit_server_tpu.ops import pacer

        st = pooled_state()
        return jax.eval_shape(
            lambda s, i, lr: paged_kernel.decide_pages(
                s.sel, s.meta.is_svc, s.meta.is_video,
                s.ctrl.subscribed & ~s.ctrl.sub_muted
                & (s.meta.published & ~s.meta.pub_muted)[:, :, None],
                i, lr, wire_overhead=pacer.WIRE_OVERHEAD_BYTES,
                use_pallas=False,
            ),
            st, pooled_inp(), sds((NL,), jnp.int32),
        )

    specs = [
        EntrySpec(
            "plane.init_state",
            lambda: (lambda: registry.entry("plane.init_state")(dense),
                     (), {}),
        ),
        EntrySpec(
            "plane.media_plane_tick",
            lambda: (registry.entry("plane.media_plane_tick"),
                     (dense_state(), dense_inp()), {}),
            donate=(0,),
        ),
        EntrySpec(
            "plane.apply_ctrl_delta",
            lambda: (registry.entry("plane.apply_ctrl_delta"),
                     (dense_state(), sds((8,), jnp.int32),
                      sds((4, 8, dense.tracks), jnp.int32),
                      sds((4, 8, dense.tracks, dense.subs), jnp.int32)), {}),
            donate=(0,),
        ),
        EntrySpec(
            "paged.page_init_template",
            lambda: (lambda: registry.entry("paged.page_init_template")(
                         pdims),
                     (), {}),
        ),
        EntrySpec(
            "paged.paged_plane_tick",
            lambda: (registry.entry("paged.paged_plane_tick"),
                     (pooled_state(), pooled_inp(), table()), {}),
            donate=(0,),
        ),
        EntrySpec(
            "paged.paged_plane_tick_live",
            lambda: (registry.entry("paged.paged_plane_tick_live"),
                     (pooled_state(), pooled_inp(), table(),
                      live_rows(), live_inv(), decide()), {}),
            donate=(0,),
        ),
        EntrySpec(
            "paged.paged_plane_tick_fused",
            lambda: (registry.entry("paged.paged_plane_tick_fused"),
                     (pooled_state(), pooled_inp(), table(),
                      live_rows(), live_inv()), {"use_pallas": False}),
            donate=(0,),
        ),
        EntrySpec(
            "paged.dead_page_outputs",
            lambda: (lambda inp: registry.entry("paged.dead_page_outputs")(
                         pdims.max_tpages, pdims.tpage, pdims.pkts,
                         pdims.spage, inp),
                     (pooled_inp(),), {}),
        ),
        EntrySpec(
            "paged.apply_table_delta",
            lambda: (registry.entry("paged.apply_table_delta"),
                     (table(), sds((16,), jnp.int32),
                      sds((16, pdims.max_tpages), jnp.int32),
                      sds((16,), jnp.int32), sds((16,), jnp.int32),
                      sds((16,), jnp.int32), sds((8,), jnp.int32),
                      sds((8, pdims.max_tpages * pdims.max_spages),
                          jnp.int32)), {}),
            donate=(0,),
        ),
        EntrySpec(
            "paged.reinit_pages",
            lambda: (registry.entry("paged.reinit_pages"),
                     (pooled_state(), sds((16,), jnp.int32),
                      abstract(lambda: paged.page_init_template(pdims))),
                     {}),
            donate=(0,),
        ),
        EntrySpec(
            "paged.move_state_rows",
            lambda: (registry.entry("paged.move_state_rows"),
                     (pooled_state(), sds((16,), jnp.int32),
                      sds((16,), jnp.int32)), {}),
            donate=(0,),
        ),
        EntrySpec(
            "paged_kernel.decide_pages",
            lambda: (_decide_entry(),
                     (pooled_state(), pooled_inp(), live_rows()), {}),
        ),
        EntrySpec(
            "mix.mix_tick",
            lambda: (registry.entry("mix.mix_tick"),
                     (sds((dense.rooms, dense.tracks, 240), jnp.float32),
                      sds((dense.rooms, dense.tracks), jnp.float32),
                      sds((dense.rooms, dense.tracks), bool),
                      sds((dense.rooms, dense.subs), jnp.int32),
                      sds((dense.rooms, dense.tracks), jnp.float32)), {}),
        ),
        EntrySpec(
            "mix.decode_tick",
            lambda: (registry.entry("mix.decode_tick"),
                     (sds((dense.rooms, dense.tracks, 240), jnp.uint8),
                      sds((dense.rooms, dense.tracks), jnp.int32)), {}),
        ),
        EntrySpec(
            "mixer.device_mix",
            lambda: (registry.entry("mixer.device_mix")(
                         dense.tracks, dense.subs, 240),
                     (sds((dense.rooms, dense.tracks, 240), jnp.float32),
                      sds((dense.rooms, dense.tracks), bool),
                      sds((dense.rooms, dense.subs), jnp.int32)), {}),
        ),
        EntrySpec(
            "mesh.sharded_tick",
            lambda: (_mesh_entry(), (dense_state(), dense_inp()), {}),
            donate=(0,), mesh_sharded=True,
            cost_from="plane.media_plane_tick",
        ),
    ]
    return specs


def _decide_entry():
    """decide_pages with the state unpacked the way the runtime calls it
    (fallback path — the Pallas path needs a TPU; the contract covers
    shapes, which are mode-invariant by the parity tests)."""
    from livekit_server_tpu.ops import pacer, paged_kernel

    def f(state, inp, live_rows):
        base = (
            state.ctrl.subscribed & ~state.ctrl.sub_muted
            & (state.meta.published & ~state.meta.pub_muted)[:, :, None]
        )
        return paged_kernel.decide_pages(
            state.sel, state.meta.is_svc, state.meta.is_video, base, inp,
            live_rows, wire_overhead=pacer.WIRE_OVERHEAD_BYTES,
            use_pallas=False,
        )

    return f


def _mesh_entry():
    from livekit_server_tpu.parallel import mesh

    m = mesh.make_mesh(n_devices=1)
    return mesh.make_sharded_tick(m)


# -- contract computation ---------------------------------------------------

def _leaf_contract(leaf) -> dict:
    return {"shape": list(leaf.shape), "dtype": str(leaf.dtype)}


def _jaxpr_cost(jaxpr) -> tuple[int, int]:
    """Deterministic (flops, bytes) estimate: dot_general as 2·M·N·K,
    every other eqn as its output element count; bytes as in+out leaf
    bytes of the top-level jaxpr. Recurses into pjit/scan/while/cond
    sub-jaxprs (counted once — an estimator, not a simulator)."""
    import numpy as np

    def aval_elems(v) -> int:
        try:
            return int(np.prod(v.aval.shape))
        except Exception:
            return 0

    def walk(jx) -> int:
        flops = 0
        for eqn in jx.eqns:
            subs = [
                p for p in eqn.params.values()
                if hasattr(p, "jaxpr") or hasattr(p, "eqns")
            ]
            if subs:
                for s in subs:
                    flops += walk(s.jaxpr if hasattr(s, "jaxpr") else s)
                continue
            out_elems = sum(aval_elems(v) for v in eqn.outvars)
            if eqn.primitive.name == "dot_general":
                dn = eqn.params["dimension_numbers"]
                (lc, _), _ = dn
                lhs = eqn.invars[0].aval.shape
                k = int(np.prod([lhs[i] for i in lc])) or 1
                flops += 2 * k * out_elems
            else:
                flops += out_elems
        return flops

    core = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    flops = walk(core)

    def leaf_bytes(v) -> int:
        try:
            return int(np.prod(v.aval.shape)) * v.aval.dtype.itemsize
        except Exception:
            return 0

    nbytes = sum(leaf_bytes(v) for v in core.invars) + sum(
        leaf_bytes(v) for v in core.outvars
    )
    return flops, nbytes


def _mesh_specs(tree) -> list[str]:
    """Textual partition specs the mesh entry promises: the room axis
    sharded on every non-scalar leaf (the same derivation
    make_sharded_tick uses for its out_specs)."""
    import jax

    return [
        "P()" if getattr(leaf, "ndim", 0) == 0 else "P('rooms')"
        for leaf in jax.tree.leaves(tree)
    ]


def entry_contract(spec: EntrySpec) -> dict:
    """Trace one entry: output contract + cost + donation audit input."""
    import jax

    fn, args, kwargs = spec.build()
    # kwargs are static policy knobs (use_pallas=False, ...): close over
    # them so eval_shape never sees — and never traces — a python bool
    wrapped = (lambda *a: fn(*a, **kwargs)) if kwargs else fn
    # one trace yields both the output pytree and the jaxpr (a
    # separate eval_shape would re-trace every entry and blow the
    # <5 s budget)
    jaxpr, out = jax.make_jaxpr(wrapped, return_shape=True)(*args)
    flops, nbytes = _jaxpr_cost(jaxpr)
    contract = {
        "out": [_leaf_contract(leaf) for leaf in jax.tree.leaves(out)],
        "flops": int(flops),
        "bytes": int(nbytes),
        "donate": list(spec.donate),
    }
    if spec.mesh_sharded:
        contract["sharding"] = _mesh_specs(out)
    return contract, args, out


def audit_donation(
    args, out, donate: tuple[int, ...], *,
    min_bytes: int = 1 << 20, allow_no_donate: bool = False,
) -> list[str]:
    """GC10 semantic audit over abstract in/out trees. Returns human
    reasons ('' prefix dead:/missing:) — the caller attaches file/line.
    """
    import jax
    import numpy as np

    def leaves(tree):
        return [
            leaf for leaf in jax.tree.leaves(tree)
            if hasattr(leaf, "shape")
        ]

    def key(leaf):
        return (tuple(leaf.shape), str(leaf.dtype))

    def size(leaf):
        return int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize

    avail = Counter(key(leaf) for leaf in leaves(out))
    problems: list[str] = []
    for i in donate:
        if i >= len(args):
            problems.append(f"dead: donate index {i} out of range")
            continue
        for leaf in leaves(args[i]):
            if avail.get(key(leaf), 0) > 0:
                avail[key(leaf)] -= 1
            else:
                problems.append(
                    f"dead: donated arg {i} leaf {key(leaf)[0]}/"
                    f"{key(leaf)[1]} aliases no output of matching "
                    "shape/dtype"
                )
    if not allow_no_donate:
        for i, a in enumerate(args):
            if i in donate:
                continue
            for leaf in leaves(a):
                if size(leaf) >= min_bytes and avail.get(key(leaf), 0) > 0:
                    avail[key(leaf)] -= 1
                    problems.append(
                        f"missing: arg {i} leaf {key(leaf)[0]}/"
                        f"{key(leaf)[1]} "
                        f"({size(leaf) // 1024} KiB) matches an output "
                        "but is not donated — a full copy per call"
                    )
    return problems


# -- baseline + runner ------------------------------------------------------

def load_baseline(path: Path) -> dict:
    p = Path(path)
    if not p.exists():
        return {}
    return json.loads(p.read_text()).get("entries", {})


def write_baseline(path: Path, contracts: dict) -> None:
    Path(path).write_text(
        json.dumps(
            {"version": BASELINE_VERSION,
             "entries": dict(sorted(contracts.items()))},
            indent=1, sort_keys=False,
        ) + "\n"
    )


def _entry_site(name: str) -> tuple[str, int]:
    """(repo-relative path, lineno) of the registered entry, for
    file:line findings."""
    import inspect

    registry.import_all()
    info = registry.DEVICE_ENTRIES.get(name)
    if info is None:
        return ("livekit_server_tpu/analysis/devicecheck.py", 1)
    try:
        fn = info["fn"]
        fn = inspect.unwrap(fn)
        code = getattr(fn, "__code__", None) or getattr(
            getattr(fn, "__wrapped__", None), "__code__", None
        )
        src = inspect.getsourcefile(fn) or ""
        line = (code.co_firstlineno if code is not None
                else inspect.getsourcelines(fn)[1])
        idx = src.find("livekit_server_tpu")
        return (src[idx:] if idx >= 0 else src, line)
    except (TypeError, OSError):
        return ("livekit_server_tpu/analysis/devicecheck.py", 1)


def compute_contracts() -> tuple[dict, list[Finding]]:
    """Trace every registered entry; returns (contracts by name,
    donation findings)."""
    cfg = _cfg()
    contracts: dict[str, dict] = {}
    findings: list[Finding] = []
    allow = set(cfg.get("allow_no_donate", []))
    min_bytes = int(cfg.get("min_donate_bytes", 1 << 20))
    for spec in _specs():
        if spec.cost_from is not None and spec.cost_from in contracts:
            # derived entry: contract copied from the entry it wraps;
            # partition specs follow _mesh_specs' shape rule. The
            # donation audit already ran on the referenced entry.
            ref = contracts[spec.cost_from]
            contract = {
                "out": [dict(leaf) for leaf in ref["out"]],
                "flops": ref["flops"],
                "bytes": ref["bytes"],
                "donate": list(spec.donate),
            }
            if spec.mesh_sharded:
                contract["sharding"] = [
                    "P()" if not leaf["shape"] else "P('rooms')"
                    for leaf in ref["out"]
                ]
            contracts[spec.name] = contract
            continue
        contract, args, out = entry_contract(spec)
        contracts[spec.name] = contract
        path, line = _entry_site(spec.name)
        for why in audit_donation(
            args, out, spec.donate, min_bytes=min_bytes,
            allow_no_donate=spec.name in allow,
        ):
            findings.append(Finding(
                "GC10", path, line,
                f"devicecheck entry `{spec.name}`: {why}",
                hint="fix the donation contract, or allowlist the "
                "entry under [tool.graftcheck.devicecheck] "
                "allow_no_donate if outputs genuinely cannot alias",
            ))
    return contracts, findings


def _cfg() -> dict:
    from livekit_server_tpu.analysis.core import DEFAULT_CONFIG, load_config

    root = Path(__file__).resolve().parents[2]
    try:
        return load_config(root).rule("devicecheck")
    except Exception:
        return dict(DEFAULT_CONFIG["devicecheck"])


def diff_contracts(
    contracts: dict, baseline: dict, *, cost_rtol: float = 0.25,
) -> tuple[list[Finding], list[str]]:
    """(drift findings, stale baseline entry names). Shapes/dtypes/
    shardings compare exactly; flops/bytes within ±cost_rtol."""
    findings: list[Finding] = []
    for name, got in contracts.items():
        path, line = _entry_site(name)
        want = baseline.get(name)
        if want is None:
            findings.append(Finding(
                DRIFT_RULE, path, line,
                f"entry `{name}` has no committed contract",
                hint="python -m tools.check --resnapshot",
            ))
            continue
        if got["out"] != want.get("out"):
            findings.append(Finding(
                DRIFT_RULE, path, line,
                f"entry `{name}` output contract drifted: "
                f"{_shape_diff(want.get('out', []), got['out'])}",
                hint="shape/dtype drift — fix the regression, or "
                "re-snapshot if intentional (--resnapshot)",
            ))
        if got.get("sharding") != want.get("sharding"):
            findings.append(Finding(
                DRIFT_RULE, path, line,
                f"entry `{name}` output sharding drifted",
                hint="the mesh entry lost/changed a room-axis "
                "partition spec",
            ))
        if list(got.get("donate", [])) != list(want.get("donate", [])):
            findings.append(Finding(
                DRIFT_RULE, path, line,
                f"entry `{name}` donation contract drifted: "
                f"{want.get('donate')} → {got.get('donate')}",
                hint="--resnapshot if intentional",
            ))
        for k in ("flops", "bytes"):
            w, g = want.get(k, 0), got.get(k, 0)
            if w and abs(g - w) > cost_rtol * w:
                findings.append(Finding(
                    DRIFT_RULE, path, line,
                    f"entry `{name}` {k} drifted {w} → {g} "
                    f"(>{int(cost_rtol * 100)}% — broadcast blow-up or "
                    "dtype promotion?)",
                    hint="inspect the jaxpr; --resnapshot if "
                    "intentional",
                ))
    stale = sorted(set(baseline) - set(contracts))
    return findings, stale


def _shape_diff(want: list[dict], got: list[dict]) -> str:
    if len(want) != len(got):
        return f"{len(want)} output leaves → {len(got)}"
    for i, (w, g) in enumerate(zip(want, got)):
        if w != g:
            return (f"leaf {i}: {w.get('shape')}/{w.get('dtype')} → "
                    f"{g.get('shape')}/{g.get('dtype')}")
    return "contract changed"


def run_check(
    root: Path | None = None, *, resnapshot: bool = False,
) -> tuple[list[Finding], list[str]]:
    """The tools/check entry: (findings, stale baseline names). With
    `resnapshot`, rewrite the baseline from the live tree first (the
    sanctioned way to land an intentional contract change)."""
    cfg = _cfg()
    root = Path(root) if root is not None else Path(
        __file__).resolve().parents[2]
    bpath = root / cfg.get("baseline", "tools/devicecheck_baseline.json")
    contracts, findings = compute_contracts()
    if resnapshot:
        write_baseline(bpath, contracts)
    drift, stale = diff_contracts(
        contracts, load_baseline(bpath),
        cost_rtol=float(cfg.get("cost_rtol", 0.25)),
    )
    return findings + drift, stale
