"""GC09 — fencing discipline for room-ownership KV state.

The fleet plane (routing/fleet.py) makes room ownership an epoch CAS:
every checkpoint/snapshot write and every pin move must ride the fenced
writer API (RoomFence.guarded_set / guarded_delete, or the KVRouter pin
movers that claim/transfer the epoch), so a stale owner's write LOSES
instead of clobbering the takeover winner's state. A raw ``bus.set`` /
``bus.delete`` on a room-checkpoint/snapshot/epoch key — or a raw
``bus.hset`` / ``bus.hdel`` on the room-pin hash — silently bypasses
the fence and reintroduces exactly the split-brain clobber the epoch
exists to prevent.

This rule flags any bus mutation whose key is a string literal (or an
f-string with a literal head) carrying a fenced prefix, or the room-pin
hash name, outside the allowlisted writer functions. Variable-keyed
calls inside the writer API itself are the sanctioned indirection and
are invisible to the rule by construction — the point is that every
LITERAL fenced key in the tree must sit behind the API.

Deliberate exceptions carry ``# graftcheck: disable=GC09`` with a
justification.
"""

from __future__ import annotations

import ast

from livekit_server_tpu.analysis.callgraph import dotted_name
from livekit_server_tpu.analysis.core import Finding, Project, qual_allowed

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)
_KV_MUTATORS = ("set", "delete", "setnx", "cas")
_HASH_MUTATORS = ("hset", "hdel")


def _literal_head(node: ast.expr) -> str | None:
    """The literal string head of a key expression: a str constant, or
    an f-string's leading constant segment. None = not statically known
    (the sanctioned writer-API indirection passes keys as variables)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        head = node.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value
    return None


def _iter_funcs(tree: ast.AST):
    """(qualname, function node) for every def, nested via dotted path."""
    def rec(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNCS):
                qual = f"{prefix}{child.name}"
                yield qual, child
                yield from rec(child, f"{qual}.")
            elif isinstance(child, ast.ClassDef):
                yield from rec(child, f"{prefix}{child.name}.")
            else:
                yield from rec(child, prefix)
    yield from rec(tree, "")


def run(project: Project, cfg: dict) -> list[Finding]:
    prefixes = tuple(cfg["fenced_prefixes"])
    pin_hashes = set(cfg["pin_hashes"])
    pin_hash_names = set(cfg["pin_hash_names"])
    allowed = cfg["allowed_in"]
    findings: list[Finding] = []
    for sf in project.under(cfg["paths"]):
        if sf.tree is None:
            continue
        for qual, fn in _iter_funcs(sf.tree):
            if qual_allowed(qual, allowed):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                dotted = dotted_name(node.func)
                if not dotted or "." not in dotted:
                    continue
                recv, tail = dotted.rsplit(".", 1)
                if "bus" not in recv.rsplit(".", 1)[-1]:
                    continue
                key = node.args[0]
                bad = ""
                if tail in _KV_MUTATORS:
                    head = _literal_head(key)
                    if head is not None and head.startswith(prefixes):
                        bad = f"key {head!r}…"
                elif tail in _HASH_MUTATORS:
                    head = _literal_head(key)
                    if head is not None and head in pin_hashes:
                        bad = f"hash {head!r}"
                    elif (
                        isinstance(key, ast.Name) and key.id in pin_hash_names
                    ):
                        bad = f"hash {key.id}"
                if not bad:
                    continue
                findings.append(
                    Finding(
                        "GC09", sf.rel, node.lineno,
                        f"unfenced bus.{tail} on ownership-fenced {bad} "
                        f"in `{qual}`",
                        hint="route room-checkpoint/snapshot/epoch writes "
                        "through RoomFence.guarded_set/guarded_delete and "
                        "pin moves through the KVRouter fenced movers, so "
                        "a stale owner's write loses the epoch CAS instead "
                        "of clobbering the takeover winner",
                    )
                )
    return findings
