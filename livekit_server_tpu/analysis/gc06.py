"""GC06 — checkpoint hygiene.

Every serialized snapshot that leaves process memory (KV-bus room
checkpoints, supervisor checkpoint generations, handoff payloads) must
ride inside the utils/checksum frame: a restore path that scatters
unverified bytes into donated device state turns one flipped bit into a
silently-wrong media plane. The rule enforces the mechanical half of
that contract statically: in the checkpoint-bearing modules, a function
that SERIALIZES (`pickle.dumps`, `marshal.dumps`, `np.savez*`,
`np.save`, `.tobytes()`) must also call the checksum codec
(`encode_frame`/`decode_frame` or their b64 variants) in the same
function — the codec call is the evidence the bytes were framed before
(or verified after) crossing the process boundary. Module-level
serializer calls are always flagged: there is no enclosing function to
carry the pairing.

utils/checksum.py itself is exempt (it IS the codec), as is any path in
cfg["exempt"]. Deliberate raw serialization (debug dumps) carries an
inline `# graftcheck: disable=GC06` with a justification.
"""

from __future__ import annotations

import ast

from livekit_server_tpu.analysis.callgraph import dotted_name
from livekit_server_tpu.analysis.core import Finding, Project


def _collect_calls(
    node: ast.AST,
    current: ast.AST | None,
    per_func: dict,
    module_calls: list,
) -> None:
    """Assign every Call to its nearest enclosing function (or the module
    body), so the codec-call pairing is judged per function scope."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            per_func.setdefault(child, [])
            _collect_calls(child, child, per_func, module_calls)
            continue
        if isinstance(child, ast.Call):
            if current is not None:
                per_func[current].append(child)
            else:
                module_calls.append(child)
        _collect_calls(child, current, per_func, module_calls)


def run(project: Project, cfg: dict) -> list[Finding]:
    serializer_calls = set(cfg["serializer_calls"])   # exact dotted names
    serializer_tails = set(cfg["serializer_tails"])   # method/function tails
    codec_calls = set(cfg["codec_calls"])
    exempt = set(cfg.get("exempt", []))

    def is_serializer(call: ast.Call) -> str | None:
        dotted = dotted_name(call.func)
        if dotted is None:
            return None
        tail = dotted.rsplit(".", 1)[-1]
        if dotted in serializer_calls or tail in serializer_tails:
            return dotted
        # `pickle.dumps` via a bound alias (`import pickle as p`) still
        # ends in `.dumps`; require a module-ish receiver so data-class
        # `.dumps` methods don't false-positive.
        if tail in ("dumps", "dump") and dotted.split(".", 1)[0] in (
            "pickle", "cPickle", "marshal"
        ):
            return dotted
        return None

    def has_codec(calls: list) -> bool:
        for call in calls:
            dotted = dotted_name(call.func)
            if dotted is not None and dotted.rsplit(".", 1)[-1] in codec_calls:
                return True
        return False

    findings: list[Finding] = []
    for sf in project.under(cfg["paths"]):
        if sf.tree is None or sf.rel in exempt:
            continue
        per_func: dict = {}
        module_calls: list = []
        _collect_calls(sf.tree, None, per_func, module_calls)
        for call in module_calls:
            dotted = is_serializer(call)
            if dotted is not None:
                findings.append(
                    Finding(
                        "GC06", sf.rel, call.lineno,
                        f"module-level `{dotted}(...)` serializes checkpoint "
                        "bytes outside any function — cannot pair with the "
                        "checksum codec",
                        hint="serialize inside a function that frames the "
                        "bytes with utils/checksum.encode_frame",
                    )
                )
        for func, calls in per_func.items():
            if has_codec(calls):
                continue
            for call in calls:
                dotted = is_serializer(call)
                if dotted is None:
                    continue
                findings.append(
                    Finding(
                        "GC06", sf.rel, call.lineno,
                        f"`{dotted}(...)` in {func.name}() serializes "
                        "checkpoint bytes without the utils/checksum codec "
                        "in the same function",
                        hint="frame the bytes with checksum.encode_frame / "
                        "encode_frame_b64 (or verify with decode_frame) "
                        "before they reach the KV bus or snapshot store; "
                        "disable with a justification if the bytes never "
                        "leave process memory",
                    )
                )
    return findings
