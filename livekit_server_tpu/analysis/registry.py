"""Device-entry registry for the devicecheck compile-contract pass.

`@device_entry("name")` marks a function (or a builder returning a
jitted callable) as a device-plane entry point. The decorator only
records the callable in a module-level table and returns it unchanged —
zero runtime cost, no jax import — so models/ops/runtime modules can
register themselves without pulling the analysis stack into the tick
path. `analysis/devicecheck.py` owns the per-entry argument specs and
runs `jax.eval_shape` contracts against this table.

Names are stable contract keys: they appear in the committed
`tools/devicecheck_baseline.json`, so renaming one is a contract change
(re-snapshot with `python -m tools.check --resnapshot`).
"""

from __future__ import annotations

from typing import Callable

# name → {"fn": callable, "module": str, "qualname": str, "builder": bool}
DEVICE_ENTRIES: dict[str, dict] = {}


def device_entry(name: str, *, builder: bool = False) -> Callable:
    """Register a device entry point under a stable contract name.

    `builder=True` marks a factory whose RETURN VALUE is the traced
    callable (e.g. runtime/mixer._device_mix, parallel/mesh.
    make_sharded_tick); devicecheck calls the factory with canonical
    params before eval_shape'ing the result.
    """

    def wrap(fn: Callable) -> Callable:
        DEVICE_ENTRIES[name] = {
            "fn": fn,
            "module": getattr(fn, "__module__", ""),
            "qualname": getattr(fn, "__qualname__", name),
            "builder": builder,
        }
        return fn

    return wrap


def entry(name: str) -> Callable:
    """Resolve a registered entry, importing the hosting modules on
    first use (registration happens at import time)."""
    if name not in DEVICE_ENTRIES:
        import_all()
    return DEVICE_ENTRIES[name]["fn"]


def import_all() -> None:
    """Import every module that registers device entries."""
    import livekit_server_tpu.models.paged  # noqa: F401
    import livekit_server_tpu.models.plane  # noqa: F401
    import livekit_server_tpu.ops.mix  # noqa: F401
    import livekit_server_tpu.ops.paged_kernel  # noqa: F401
    import livekit_server_tpu.parallel.mesh  # noqa: F401
    import livekit_server_tpu.runtime.mixer  # noqa: F401
