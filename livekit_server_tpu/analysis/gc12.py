"""GC12 — host-sync hygiene on the tick path.

The tick window budget assumes exactly one blocking device round trip
per tick, at the declared drain seam. Any other blocking read —
`jax.block_until_ready`, `jax.device_get`, `.item()`, or
`np.asarray`/`float()`/`int()` fed a device array — inserts a hidden
pipeline bubble: the host stalls mid-tick waiting on the device stream,
and on real hardware the stall covers the whole in-flight dispatch, not
just the one array.

The rule walks the call graph from the configured tick-path roots
(`PlaneRuntime._device_step`, the paged live step, the upload/stage
slices), skipping the declared seams (`_unpack_outputs`, `_sel_mirror`,
the integrity audit, ...), and flags blocking reads anywhere in the
reachable set. `block_until_ready` / `device_get` / `.item()` are
flagged unconditionally; `np.asarray` / `np.array` / `float()` /
`int()` are host no-ops on host data, so they only flag when the
argument expression mentions a `device_names` identifier (`state`,
`out`, `buf`, `dec`, `table` — device-resident by convention in
runtime/).
"""

from __future__ import annotations

import ast

from livekit_server_tpu.analysis.callgraph import dotted_name
from livekit_server_tpu.analysis.core import Finding, Project, qual_allowed

_NP_SINKS = {"numpy.asarray", "numpy.array", "numpy.copy"}
_CAST_SINKS = {"float", "int", "bool"}


def _mentions_device(node: ast.AST, device_names: set[str]) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in device_names:
            return True
        if isinstance(n, ast.Attribute) and n.attr in device_names:
            return True
    return False


def _blocking(call: ast.Call, cg, modname: str, cfg: dict) -> str | None:
    """Reason string when this call is a blocking device read."""
    device_names = set(cfg.get("device_names", []))
    dotted = dotted_name(call.func)
    if dotted is not None:
        full = cg.expand_alias(dotted, modname)
        tail = full.rsplit(".", 1)[-1]
        if tail == "block_until_ready":
            return f"`{dotted}` blocks on the device stream"
        if tail == "device_get":
            return f"`{dotted}` is a blocking device→host copy"
        if full in _NP_SINKS and call.args and _mentions_device(
            call.args[0], device_names
        ):
            return (f"`{dotted}` on a device-resident value forces a "
                    "blocking transfer")
        if full in _CAST_SINKS and call.args and _mentions_device(
            call.args[0], device_names
        ):
            return (f"`{dotted}()` on a device-resident value forces a "
                    "blocking scalar read")
    if isinstance(call.func, ast.Attribute) and call.func.attr == "item":
        return "`.item()` forces a blocking scalar read"
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr == "block_until_ready":
        return "`.block_until_ready()` blocks on the device stream"
    # np.asarray passed as a callback (jax.tree.map(np.asarray, out))
    for arg in call.args:
        d = dotted_name(arg)
        if d is not None and cg.expand_alias(d, modname) in _NP_SINKS:
            if _mentions_device(call, device_names):
                return (f"`{d}` mapped over a device tree forces a "
                        "blocking transfer")
    return None


def run(project: Project, cfg: dict) -> list[Finding]:
    cg = project.callgraph
    findings: list[Finding] = []
    seams = cfg.get("seams", [])
    roots = []
    for sf in project.under(cfg["paths"]):
        for (mod, qual), fi in cg.funcs.items():
            if mod == sf.modname and qual in cfg.get("roots", []):
                roots.append(fi)
    seen: set[int] = set()
    seen_sites: set[tuple[str, int, str]] = set()
    queue = [(fi, fi.qual) for fi in roots]
    while queue:
        fi, root = queue.pop()
        if id(fi) in seen:
            continue
        seen.add(id(fi))
        sf = fi.module
        # walk the whole body incl. nested defs: closures run on the
        # same thread when called from here
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            why = _blocking(node, cg, sf.modname, cfg)
            if why is not None:
                key = (sf.rel, node.lineno, why)
                if key not in seen_sites:
                    seen_sites.add(key)
                    findings.append(Finding(
                        "GC12", sf.rel, node.lineno,
                        f"{why} on the tick path (reachable from "
                        f"`{root}`)",
                        hint="move the read to a declared drain/"
                        "telemetry seam, or defer it off the tick "
                        "thread",
                    ))
                continue
            callee = cg.resolve_unique(node.func, fi, sf)
            if callee is None:
                continue
            if qual_allowed(callee.qual, seams):
                continue
            # only descend into runtime-path callees; library helpers
            # outside cfg paths are out of scope
            if callee.module.rel.startswith(tuple(
                p.rstrip("/") for p in cfg["paths"]
            )):
                queue.append((callee, root))
    return findings
