"""GC03 — lock discipline.

Builds an acquisition-order graph over the media plane's asyncio locks
(`state_lock`, `_ckpt_lock`, the per-room `_create_locks` entries) and
flags:

  * lock-order cycles — two code paths acquiring the same pair of locks
    in opposite orders will deadlock under contention;
  * re-acquisition of a non-reentrant asyncio lock already held on the
    same path (directly, or through a call into a function that
    acquires it);
  * blocking synchronous calls (`time.sleep`, sync sockets, subprocess)
    made while an asyncio lock is held — they stall the entire event
    loop for every other lock waiter.

Edges are collected both lexically (acquire inside an outer lock's
region) and interprocedurally via a conservative `acquires*` fixed
point over resolvable callees. Unresolvable calls contribute no edge:
a fabricated edge would invent deadlocks, so only unique-name matches
count.
"""

from __future__ import annotations

from livekit_server_tpu.analysis.callgraph import (
    FuncInfo,
    body_calls,
    dotted_name,
)
from livekit_server_tpu.analysis.core import Finding, Project
from livekit_server_tpu.analysis.locks import LockInfo, analyze_function


def _blocking(full: str, patterns: list[str]) -> bool:
    return any(
        full.startswith(p) if p.endswith(".") else full == p
        for p in patterns
    )


def run(project: Project, cfg: dict) -> list[Finding]:
    cg = project.callgraph
    lock_names = set(cfg["lock_names"])
    findings: list[Finding] = []

    infos: dict[int, tuple[FuncInfo, LockInfo]] = {}
    for sf in project.under(cfg["paths"]):
        if sf.tree is None:
            continue
        for (mod, qual), fi in cg.funcs.items():
            if mod == sf.modname and fi.parent is None:
                infos[id(fi)] = (fi, analyze_function(fi.node, lock_names))

    # acquires*(f): locks f may take, directly or through callees
    direct = {k: {l for (l, _, _) in info.acquisitions}
              for k, (fi, info) in infos.items()}
    callees: dict[int, list[int]] = {}
    for k, (fi, info) in infos.items():
        outs = []
        for call in body_calls(fi.node, include_nested=True):
            target = cg.resolve_unique(call.func, fi, fi.module)
            if target is not None and id(target) in infos:
                outs.append(id(target))
        callees[k] = outs
    star = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for k, outs in callees.items():
            for o in outs:
                if not star[o] <= star[k]:
                    star[k] |= star[o]
                    changed = True

    # edges: held-lock → acquired-lock, with a representative site each
    edges: dict[tuple[str, str], tuple[str, int, str]] = {}

    def add_edge(h: str, l: str, sf_rel: str, line: int, qual: str) -> None:
        edges.setdefault((h, l), (sf_rel, line, qual))

    for k, (fi, info) in infos.items():
        rel = fi.module.rel
        for lock, node, held in info.acquisitions:
            if lock in held:
                findings.append(
                    Finding(
                        "GC03", rel, node.lineno,
                        f"re-acquisition of `{lock}` already held in "
                        f"{fi.qual} — asyncio locks are not reentrant",
                        hint="split the locked section or pass state in",
                    )
                )
            for h in held:
                add_edge(h, lock, rel, node.lineno, fi.qual)
        for call, held in info.locked_calls:
            dotted = dotted_name(call.func)
            if dotted is not None:
                full = cg.expand_alias(dotted, fi.module.modname)
                if _blocking(full, cfg["blocking_calls"]):
                    findings.append(
                        Finding(
                            "GC03", rel, call.lineno,
                            f"blocking call `{dotted}` while holding "
                            f"{sorted(held)} in {fi.qual} — stalls the "
                            "event loop for every lock waiter",
                            hint="use the async equivalent or move the "
                            "call outside the locked region",
                        )
                    )
            target = cg.resolve_unique(call.func, fi, fi.module)
            if target is None or id(target) not in infos:
                continue
            for l in star[id(target)]:
                if l in held:
                    findings.append(
                        Finding(
                            "GC03", rel, call.lineno,
                            f"call into `{target.qual}` (which may acquire "
                            f"`{l}`) while `{l}` is already held in "
                            f"{fi.qual}",
                            hint="hoist the inner acquisition to the caller "
                            "or document a lock-held contract",
                        )
                    )
                for h in held:
                    if h != l:
                        add_edge(h, l, rel, call.lineno, fi.qual)

    # cycle detection over the lock-order graph
    graph: dict[str, set[str]] = {}
    for (h, l) in edges:
        graph.setdefault(h, set()).add(l)
    reported: set[frozenset] = set()
    for start in sorted(graph):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    cyc = frozenset(path)
                    if cyc in reported:
                        continue
                    reported.add(cyc)
                    sites = " ; ".join(
                        f"{a}->{b} at {edges[(a, b)][0]}:{edges[(a, b)][1]}"
                        for a, b in zip(path, path[1:] + [start])
                    )
                    rel, line, qual = edges[(path[-1], start)]
                    findings.append(
                        Finding(
                            "GC03", rel, line,
                            "lock-order cycle "
                            f"{' -> '.join(path + [start])} ({sites})",
                            hint="pick one global acquisition order and "
                            "restructure the later acquisition",
                        )
                    )
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))
    return findings
