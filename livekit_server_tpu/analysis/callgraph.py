"""Module/function index + heuristic call resolution for graftcheck.

Static resolution is deliberately conservative: it follows the shapes
this codebase actually uses (module-alias calls, `from X import f`,
nested closures handed to jax.jit / shard_map / pallas_call / vmap,
`self.method()` within a class, simple `g = wrapper(f)` rebinding).
Anything it cannot resolve, it skips — rules built on top must treat an
unresolved call as "not an edge", never as an error.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from livekit_server_tpu.analysis.core import Project, SourceFile


def dotted_name(expr: ast.AST) -> str | None:
    """`a.b.c` for Name/Attribute chains; None for anything dynamic."""
    parts: list[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class FuncInfo:
    qual: str                  # Class.method / outer.inner (module-local)
    name: str
    node: ast.AST              # FunctionDef | AsyncFunctionDef | Lambda
    module: SourceFile
    cls: str | None = None     # enclosing class name, if a method
    parent: "FuncInfo | None" = None   # enclosing function (closures)
    # names of functions defined directly inside this one
    locals_: dict[str, "FuncInfo"] = field(default_factory=dict)

    @property
    def global_qual(self) -> str:
        return f"{self.module.modname}.{self.qual}"


class CallGraph:
    """Index of every function/method/closure plus import alias maps."""

    def __init__(self, project: Project):
        self.project = project
        # (modname, qual) → FuncInfo; module-level name → FuncInfo
        self.funcs: dict[tuple[str, str], FuncInfo] = {}
        self.module_scope: dict[str, dict[str, FuncInfo]] = {}
        # modname → alias → real dotted target ("np" → "numpy",
        # "plane" → "livekit_server_tpu.models.plane",
        # "retry_async" → "livekit_server_tpu.utils.backoff.retry_async")
        self.aliases: dict[str, dict[str, str]] = {}
        # function simple name → [FuncInfo] across the project (for the
        # unique-name fallback the lock analyzer uses)
        self.by_name: dict[str, list[FuncInfo]] = {}
        for sf in project.files:
            if sf.tree is None:
                continue
            self.module_scope[sf.modname] = {}
            self.aliases[sf.modname] = self._collect_imports(sf.tree)
            self._index_body(sf, sf.tree.body, cls=None, parent=None)

    # -- indexing ---------------------------------------------------------
    def _collect_imports(self, tree: ast.Module) -> dict[str, str]:
        # Function-local and try/except-guarded imports are folded into
        # one per-module map: an alias map approximates name binding, and
        # this codebase never rebinds an import alias across scopes.
        out: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
                for a in node.names:
                    out[a.asname or a.name] = f"{node.module}.{a.name}"
        return out

    def _index_body(self, sf, body, cls, parent, prefix=""):
        for node in body:
            if isinstance(node, ast.ClassDef):
                self._index_body(sf, node.body, cls=node.name, parent=None,
                                 prefix=f"{prefix}{node.name}.")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{node.name}"
                fi = FuncInfo(qual, node.name, node, sf, cls=cls, parent=parent)
                self.funcs[(sf.modname, qual)] = fi
                self.by_name.setdefault(node.name, []).append(fi)
                if parent is not None:
                    parent.locals_[node.name] = fi
                elif cls is None:
                    self.module_scope[sf.modname][node.name] = fi
                self._index_body(sf, node.body, cls=cls, parent=fi,
                                 prefix=f"{qual}.")
            else:
                # defs nested under if/try/with still belong to this scope
                for fname in ("body", "orelse", "finalbody"):
                    sub = getattr(node, fname, None)
                    if isinstance(sub, list):
                        self._index_body(sf, sub, cls, parent, prefix)
                for h in getattr(node, "handlers", []) or []:
                    self._index_body(sf, h.body, cls, parent, prefix)

    # -- resolution -------------------------------------------------------
    def expand_alias(self, dotted: str, modname: str) -> str:
        """Rewrite the leading segment through the module's import map:
        np.asarray → numpy.asarray, plane.media_plane_tick →
        livekit_server_tpu.models.plane.media_plane_tick."""
        head, _, rest = dotted.partition(".")
        target = self.aliases.get(modname, {}).get(head)
        if target is None:
            return dotted
        return f"{target}.{rest}" if rest else target

    def _lookup_scoped(self, name: str, scope: FuncInfo | None,
                       modname: str) -> FuncInfo | None:
        """Python name lookup for a bare function name: enclosing closures
        outward, then module scope, then `from X import f` targets."""
        fi = scope
        while fi is not None:
            if name in fi.locals_:
                return fi.locals_[name]
            fi = fi.parent
        mod = self.module_scope.get(modname, {})
        if name in mod:
            return mod[name]
        target = self.aliases.get(modname, {}).get(name)
        if target and "." in target:
            tmod, _, tname = target.rpartition(".")
            got = self.funcs.get((tmod, tname))
            if got is not None:
                return got
        return None

    def resolve(self, expr: ast.AST, scope: FuncInfo | None,
                sf: SourceFile,
                local_assigns: dict[str, ast.AST] | None = None,
                _depth: int = 0) -> FuncInfo | None:
        """Resolve a callable expression to a FuncInfo, or None.

        Handles: bare names (closures → module → imports), module-alias
        attributes (plane.f), `self.method`, functools.partial(f, ...),
        and names rebound from simple wrap calls (`g = shard_map(f, ...)`).
        """
        if _depth > 8:
            return None
        if isinstance(expr, ast.Call):
            # functools.partial(f, ...) / wrapper(f, ...) → first arg.
            # Alias-expand first: `from ... import shard_map as _shard_map`
            # must still unwrap.
            inner = dotted_name(expr.func)
            if inner is not None and expr.args and self.expand_alias(
                inner, sf.modname
            ).rsplit(".", 1)[-1] in (
                "partial", "wraps", "jit", "shard_map", "checkpoint", "vmap",
                "pallas_call",
            ):
                return self.resolve(expr.args[0], scope, sf, local_assigns,
                                    _depth + 1)
            return None
        if isinstance(expr, ast.Name):
            if local_assigns and expr.id in local_assigns:
                return self.resolve(local_assigns[expr.id], scope, sf,
                                    None, _depth + 1)
            return self._lookup_scoped(expr.id, scope, sf.modname)
        if isinstance(expr, ast.Attribute):
            dotted = dotted_name(expr)
            if dotted is None:
                return None
            # self.method() → method of the enclosing class
            if dotted.startswith("self.") and dotted.count(".") == 1:
                fi = scope
                while fi is not None and fi.cls is None:
                    fi = fi.parent
                if fi is not None:
                    return self.funcs.get((sf.modname, f"{fi.cls}.{expr.attr}"))
                return None
            full = self.expand_alias(dotted, sf.modname)
            tmod, _, tname = full.rpartition(".")
            return self.funcs.get((tmod, tname))
        return None

    def resolve_unique(self, expr: ast.AST, scope: FuncInfo | None,
                       sf: SourceFile) -> FuncInfo | None:
        """resolve(), falling back to project-wide unique simple-name
        match for attribute calls (`self.runtime.snapshot_room` →
        PlaneRuntime.snapshot_room when only one `snapshot_room` exists).
        Used by the lock analyzer, where a missed edge hides a deadlock
        but a duplicated name would fabricate one — hence *unique* only."""
        got = self.resolve(expr, scope, sf)
        if got is not None:
            return got
        if isinstance(expr, ast.Attribute):
            cands = self.by_name.get(expr.attr, [])
            if len(cands) == 1:
                return cands[0]
        return None


def local_assignments(func_node: ast.AST) -> dict[str, ast.AST]:
    """name → RHS for simple single-target assignments directly in this
    function's body blocks (no nested function bodies)."""
    out: dict[str, ast.AST] = {}

    def walk(body):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                out[node.targets[0].id] = node.value
            for fname in ("body", "orelse", "finalbody"):
                sub = getattr(node, fname, None)
                if isinstance(sub, list):
                    walk(sub)
            for h in getattr(node, "handlers", []) or []:
                walk(h.body)

    walk(getattr(func_node, "body", []))
    return out


def body_calls(func_node: ast.AST, include_nested: bool = False):
    """Yield every Call in the function body. By default nested function /
    lambda / class bodies are skipped (separate graph nodes); the purity
    rule passes include_nested=True because everything lexically inside a
    traced function body is traced with it."""
    body = getattr(func_node, "body", [])
    stack = list(body) if isinstance(body, list) else [body]
    while stack:
        node = stack.pop()
        if not include_nested and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef)
        ):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))
