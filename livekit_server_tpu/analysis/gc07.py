"""GC07 — allocation-free trace emits on the tick hot path.

The flight-recorder APIs (`TickTraceRing.record_tick`, `set_shard`,
`BlackBox.emit`, `LatencyAttribution.observe_batch` /
`observe_express`) are designed as fixed-ring scalar stores precisely
so they can run inside the tick loop at zero steady-state allocation.
That property dies at the *call site*: an f-string, dict/list/set
display, comprehension, or `.format(...)` built just to pass into the
recorder allocates on every tick even though the recorder itself does
not. This rule flags any allocating expression in the arguments of a
configured emit call, unless the call sits inside a sampling branch —
an `if` whose condition mentions a configured sampling name (sample /
sampled / mask / stamped, by default) or a `%` decimation test — where
the allocation is paid only 1-in-K times by construction.

Formatting belongs in `dump`/`dump_to`/`snapshot` (the cold read side),
not in the emit. Deliberate exceptions carry an inline
`# graftcheck: disable=GC07` with a justification.
"""

from __future__ import annotations

import ast

from livekit_server_tpu.analysis.callgraph import dotted_name
from livekit_server_tpu.analysis.core import Finding, Project

# Expression nodes whose evaluation allocates a fresh container/str.
_ALLOC_NODES = (
    ast.JoinedStr,       # f-string
    ast.Dict,
    ast.List,
    ast.Set,
    ast.ListComp,
    ast.SetComp,
    ast.DictComp,
    ast.GeneratorExp,
)


def _alloc_in(node: ast.expr) -> tuple[int, str] | None:
    """(line, kind) of the first allocating construct inside `node`."""
    for sub in ast.walk(node):
        if isinstance(sub, _ALLOC_NODES):
            kind = {
                ast.JoinedStr: "f-string",
                ast.Dict: "dict display",
                ast.List: "list display",
                ast.Set: "set display",
            }.get(type(sub), "comprehension")
            return sub.lineno, kind
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "format"
        ):
            return sub.lineno, "str.format(...)"
        if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Mod) and (
            isinstance(sub.left, (ast.Constant, ast.JoinedStr))
            and isinstance(getattr(sub.left, "value", None), str)
        ):
            return sub.lineno, "%-format"
    return None


def _is_sampling_test(test: ast.expr, guard_names: set[str]) -> bool:
    """A condition that decimates: mentions a sampling name or takes
    `x % k` — the idiom of deterministic 1-in-K selection."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Name) and any(
            g in sub.id.lower() for g in guard_names
        ):
            return True
        if isinstance(sub, ast.Attribute) and any(
            g in sub.attr.lower() for g in guard_names
        ):
            return True
        if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Mod):
            # exclude str % tuple formatting, which _alloc_in flags
            if not (
                isinstance(sub.left, ast.Constant)
                and isinstance(sub.left.value, str)
            ):
                return True
    return False


def run(project: Project, cfg: dict) -> list[Finding]:
    emit_calls = set(cfg["emit_calls"])
    guard_names = {g.lower() for g in cfg["sample_guards"]}
    findings: list[Finding] = []
    for sf in project.under(cfg["paths"]):
        if sf.tree is None:
            continue
        # parent links so a flagged call can look up enclosing ifs
        parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(sf.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            if dotted is None or dotted.rsplit(".", 1)[-1] not in emit_calls:
                continue
            hit = None
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                hit = _alloc_in(arg)
                if hit is not None:
                    break
            if hit is None:
                continue
            # exempt when any enclosing `if` is a sampling/decimation test
            sampled = False
            cur: ast.AST | None = parents.get(node)
            while cur is not None:
                if isinstance(cur, ast.If) and _is_sampling_test(
                    cur.test, guard_names
                ):
                    sampled = True
                    break
                cur = parents.get(cur)
            if sampled:
                continue
            line, kind = hit
            findings.append(
                Finding(
                    "GC07", sf.rel, line,
                    f"allocating {kind} in `{dotted}(...)` args outside a "
                    "sampled branch",
                    hint="trace/black-box emits on the tick hot path must "
                    "pass scalars only (format in dump/snapshot, the cold "
                    "side), or guard the emit behind the 1-in-K sampling "
                    "test",
                )
            )
    return findings
