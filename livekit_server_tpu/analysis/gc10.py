"""GC10 — donation discipline at jit wrap sites.

A jitted tick that takes the plane state and returns its successor
without `donate_argnums` forces XLA to materialize the output in fresh
HBM every call — a whole-pool copy per tick for the paged plane. The
inverse bug is quieter: a donate index naming a parameter the function
never uses (or that doesn't exist) donates a buffer XLA can't alias,
silently freeing the caller's array for nothing.

This rule walks every `jax.jit` wrap site (call form, decorator form,
`functools.partial(jax.jit, ...)` decorators) and checks, per traced
function:

  * missing donation — a parameter named in `state_params` (default:
    `state`) is taken and flows into the return value, but the wrap has
    no donate spec. Allowlisted for init/restore-style builders
    (`allow_missing` fnmatch on the enclosing function's qual).
  * dead donation — a literal donate index that is out of range, or
    names a parameter the function body never references.

The semantic half — do donated leaves actually alias an output of
matching shape/dtype at canonical dims? — runs in devicecheck.py over
the `@device_entry` registry, where real avals are available.
"""

from __future__ import annotations

import ast

from livekit_server_tpu.analysis.callgraph import (
    FuncInfo,
    dotted_name,
    local_assignments,
)
from livekit_server_tpu.analysis.core import Finding, Project, qual_allowed


def _is_jit(expr: ast.AST, cg, modname: str) -> bool:
    dotted = dotted_name(expr)
    if dotted is None:
        return False
    return cg.expand_alias(dotted, modname).rsplit(".", 1)[-1] == "jit"


def _donate_spec(call: ast.Call) -> tuple[bool, list[int]]:
    """(has donate kwarg at all, literal int indices when statically
    known). A dynamic spec (`(0,) if donate else ()`) counts as
    donating — conditional donation is a caller policy, not a bug."""
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            idxs: list[int] = []
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                for el in kw.value.elts:
                    if isinstance(el, ast.Constant) and isinstance(
                        el.value, int
                    ):
                        idxs.append(el.value)
            elif isinstance(kw.value, ast.Constant) and isinstance(
                kw.value.value, int
            ):
                idxs.append(kw.value.value)
            return True, idxs
    return False, []


def _params(fn_node: ast.AST) -> list[str]:
    a = getattr(fn_node, "args", None)
    if a is None:
        return []
    return [p.arg for p in a.posonlyargs + a.args]


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _returned_names(fn_node: ast.AST) -> set[str]:
    """Names appearing anywhere in this function's return expressions
    (nested defs excluded — they return for themselves)."""
    out: set[str] = set()
    stack = list(getattr(fn_node, "body", []))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(node, ast.Return) and node.value is not None:
            out |= _names_in(node.value)
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


def _body_names(fn_node: ast.AST) -> set[str]:
    out: set[str] = set()
    for node in getattr(fn_node, "body", []):
        out |= _names_in(node)
    return out


def _wrap_sites(project: Project, cfg: dict):
    """(jit Call node | decorator, traced FuncInfo, enclosing qual,
    SourceFile, lineno, has_donate, donate_idxs) per jit wrap site."""
    cg = project.callgraph
    sites = []

    def add_call(call: ast.Call, scope, sf, assigns):
        if not call.args:
            return
        target = cg.resolve(call.args[0], scope, sf, assigns)
        if target is None:
            return
        has, idxs = _donate_spec(call)
        qual = scope.qual if scope is not None else "<module>"
        sites.append((call, target, qual, sf, call.lineno, has, idxs))

    for sf in project.under(cfg["paths"]):
        if sf.tree is None:
            continue
        for (mod, _), fi in cg.funcs.items():
            if mod != sf.modname:
                continue
            assigns = local_assignments(fi.node)
            # decorator form: @jax.jit / @partial(jax.jit, ...)
            for dec in getattr(fi.node, "decorator_list", []):
                if _is_jit(dec, cg, sf.modname):
                    sites.append((dec, fi, fi.qual, sf, fi.node.lineno,
                                  False, []))
                elif isinstance(dec, ast.Call):
                    inner = dec.args[0] if dec.args else None
                    if _is_jit(dec.func, cg, sf.modname):
                        has, idxs = _donate_spec(dec)
                        sites.append((dec, fi, fi.qual, sf,
                                      fi.node.lineno, has, idxs))
                    elif inner is not None and _is_jit(inner, cg, sf.modname):
                        # functools.partial(jax.jit, ...) decorator
                        has, idxs = _donate_spec(dec)
                        sites.append((dec, fi, fi.qual, sf,
                                      fi.node.lineno, has, idxs))
            # call form inside this function: jax.jit(f, ...)
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call) and node is not fi.node and \
                        _is_jit(node.func, cg, sf.modname):
                    add_call(node, fi, sf, assigns)
        # module-level wrap calls
        for stmt in sf.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) and \
                        _is_jit(node.func, cg, sf.modname):
                    add_call(node, None, sf, None)
    return sites


def run(project: Project, cfg: dict) -> list[Finding]:
    findings: list[Finding] = []
    seen: set[tuple[str, int, str]] = set()
    state_params = set(cfg.get("state_params", ["state"]))
    allow = cfg.get("allow_missing", [])
    for (_, target, encl_qual, sf, lineno, has_donate,
         idxs) in _wrap_sites(project, cfg):
        params = _params(target.node)
        if not params:
            continue
        if has_donate:
            body_names = _body_names(target.node)
            for i in idxs:
                if i >= len(params):
                    key = (sf.rel, lineno, f"range{i}")
                    if key not in seen:
                        seen.add(key)
                        findings.append(Finding(
                            "GC10", sf.rel, lineno,
                            f"dead donation: donate index {i} is out of "
                            f"range for `{target.qual}` "
                            f"({len(params)} positional params)",
                            hint="point donate_argnums at the mutated "
                            "buffer parameter",
                        ))
                elif params[i] not in body_names:
                    key = (sf.rel, lineno, f"unused{i}")
                    if key not in seen:
                        seen.add(key)
                        findings.append(Finding(
                            "GC10", sf.rel, lineno,
                            f"dead donation: `{target.qual}` never uses "
                            f"donated parameter `{params[i]}` — XLA "
                            "cannot alias it to any output",
                            hint="donate the buffer the function "
                            "actually mutates and returns",
                        ))
        else:
            mutated = [
                p for p in params
                if p in state_params and p in _returned_names(target.node)
            ]
            if mutated and not (
                qual_allowed(encl_qual, allow)
                or qual_allowed(target.qual, allow)
            ):
                key = (sf.rel, lineno, "missing")
                if key not in seen:
                    seen.add(key)
                    findings.append(Finding(
                        "GC10", sf.rel, lineno,
                        f"missing donation: `{target.qual}` takes and "
                        f"returns plane buffer `{mutated[0]}` but the "
                        "jit wrap does not donate it — every call "
                        "copies the whole buffer",
                        hint=f"jit with donate_argnums="
                        f"({params.index(mutated[0])},), or allowlist "
                        "the wrap site if it is an init/restore path",
                    ))
    return findings
