"""GC05 — bounded queues.

Every `asyncio.Queue` (and stdlib `queue.Queue` variants) or
`collections.deque` constructed in the runtime and routing planes must
carry an explicit bound (`maxsize=` / `maxlen=`, or the corresponding
positional argument). An unbounded buffer between a producer that never
blocks and a consumer that can fall behind converts overload into
unbounded memory growth — the failure the overload governor exists to
prevent, and one that no drop counter will ever report because nothing
is ever dropped. Deliberately unbounded structures carry an inline
`# graftcheck: disable=GC05` with a justification.

A bound of literal `0` (asyncio's "infinite" sentinel) or `maxlen=None`
is flagged the same as a missing bound: it spells unbounded while
looking like a choice.
"""

from __future__ import annotations

import ast

from livekit_server_tpu.analysis.callgraph import dotted_name
from livekit_server_tpu.analysis.core import Finding, Project


def _is_unbounded_literal(node: ast.expr | None) -> bool:
    """True when the bound expression is literally 0 or None."""
    return isinstance(node, ast.Constant) and (
        node.value is None or node.value == 0
    )


def _bound_arg(call: ast.Call, kw_name: str, pos_index: int) -> ast.expr | None:
    """The expression supplying the bound, or None when absent."""
    for kw in call.keywords:
        if kw.arg == kw_name:
            return kw.value
    if len(call.args) > pos_index:
        return call.args[pos_index]
    return None


def run(project: Project, cfg: dict) -> list[Finding]:
    queue_calls = set(cfg["queue_calls"])
    deque_calls = set(cfg["deque_calls"])
    findings: list[Finding] = []
    for sf in project.under(cfg["paths"]):
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            if dotted is None:
                continue
            tail = dotted.rsplit(".", 1)[-1]
            if tail in queue_calls:
                kw_name, pos_index = "maxsize", 0
            elif tail in deque_calls:
                kw_name, pos_index = "maxlen", 1
            else:
                continue
            if any(kw.arg is None for kw in node.keywords):
                continue  # **kwargs splat: can't prove absence statically
            bound = _bound_arg(node, kw_name, pos_index)
            if bound is None:
                findings.append(
                    Finding(
                        "GC05", sf.rel, node.lineno,
                        f"unbounded `{dotted}(...)`: no {kw_name}= given",
                        hint=f"pass an explicit {kw_name}= (overload must "
                        "surface as counted drops, not memory growth); "
                        "disable with a justification if unbounded is "
                        "deliberate",
                    )
                )
            elif _is_unbounded_literal(bound):
                findings.append(
                    Finding(
                        "GC05", sf.rel, node.lineno,
                        f"`{dotted}(...)` bound is literally unbounded "
                        f"({kw_name}={ast.unparse(bound)})",
                        hint=f"use a positive {kw_name} — 0/None spell "
                        "infinite while looking like a bound",
                    )
                )
    return findings
