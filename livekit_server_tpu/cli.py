"""CLI entry point.

Reference parity: cmd/server/main.go (startServer :250-304, getConfig
:191) and commands.go (generate-keys, create-join-token, list-nodes,
ports). Flags are generated from the config schema exactly like the
reference's GenerateCLIFlags (main.go:126).

Usage:
    python -m livekit_server_tpu serve --config livekit.yaml
    python -m livekit_server_tpu generate-keys
    python -m livekit_server_tpu create-join-token --room r --identity i
    python -m livekit_server_tpu list-nodes
    python -m livekit_server_tpu ports
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys

from livekit_server_tpu.auth import AccessToken, VideoGrant
from livekit_server_tpu.config import Config, generate_cli_flags, load_config
from livekit_server_tpu.utils import ids
from livekit_server_tpu.version import __version__


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="livekit-server-tpu")
    p.add_argument("--version", action="version", version=__version__)
    sub = p.add_subparsers(dest="command")

    serve = sub.add_parser("serve", help="run the server")
    serve.add_argument("--config", help="path to YAML config")
    serve.add_argument("--dev", action="store_true", help="development mode")
    generate_cli_flags(serve)

    sub.add_parser("generate-keys", help="generate an API key/secret pair")

    tok = sub.add_parser("create-join-token", help="mint a join token")
    tok.add_argument("--room", required=True)
    tok.add_argument("--identity", required=True)
    tok.add_argument("--config", help="path to YAML config (for keys)")
    tok.add_argument("--key", help="API key (defaults to first config key)")

    sub.add_parser("ports", help="print the ports the server uses")

    bus = sub.add_parser(
        "bus", help="run the standalone message bus (the multi-node KV seat)"
    )
    bus.add_argument("--host", default="127.0.0.1",
                     help="bind address; a non-loopback bind requires --token")
    bus.add_argument("--port", type=int, default=7850)
    bus.add_argument("--token", default=os.environ.get("LIVEKIT_BUS_TOKEN", ""),
                     help="shared auth secret (env LIVEKIT_BUS_TOKEN); the bus "
                          "is the cluster control plane — never expose it bare")

    nodes = sub.add_parser("list-nodes", help="list cluster nodes")
    nodes.add_argument("--config", help="path to YAML config")

    drain = sub.add_parser(
        "drain",
        help="ask a node to migrate its rooms off and stop admitting "
             "(the live-migration plane's node drain)",
    )
    drain.add_argument("--config", help="path to YAML config (for the bus)")
    drain.add_argument("--node", required=True,
                       help="node id to drain (see list-nodes)")
    return p


def main(argv: list[str] | None = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.command == "generate-keys":
        print(f"API Key: {ids.new_api_key()}")
        print(f"API Secret: {ids.new_api_secret()}")
        return 0
    if args.command == "bus":

        if args.host not in ("127.0.0.1", "localhost", "::1") and not args.token:
            print("refusing to bind the bus beyond loopback without --token",
                  flush=True)
            return 2

        async def run_bus():
            from livekit_server_tpu.routing.tcpbus import BusServer

            srv = BusServer(token=args.token)
            await srv.start(args.host, args.port)
            print(f"bus listening on {args.host}:{srv.port}", flush=True)
            stop = asyncio.Event()
            loop = asyncio.get_running_loop()
            for sig in (signal.SIGINT, signal.SIGTERM):
                loop.add_signal_handler(sig, stop.set)
            await stop.wait()
            srv.close()

        asyncio.run(run_bus())
        return 0
    if args.command == "ports":
        cfg = Config()
        print(f"http/ws: {cfg.port}")
        print(f"rtc udp: {cfg.rtc.udp_port}")
        print(f"rtc tcp: {cfg.rtc.tcp_port}")
        print(f"port range: {cfg.rtc.port_range_start}-{cfg.rtc.port_range_end}")
        return 0
    if args.command == "create-join-token":
        cfg = load_config(
            yaml_path=args.config if args.config else None,
            yaml_text=None if args.config else "development: true",
        )
        key = args.key or next(iter(cfg.keys))
        tok = AccessToken(key, cfg.keys[key])
        tok.identity = args.identity
        tok.grant = VideoGrant(room_join=True, room=args.room)
        print(tok.to_jwt())
        return 0
    if args.command == "list-nodes":
        cfg = load_config(
            yaml_path=args.config if args.config else None,
            yaml_text=None if args.config else "development: true",
        )
        from livekit_server_tpu.service.server import connect_bus, create_server

        async def run():
            # Without the shared bus the router falls back to a private
            # in-memory registry and only ever lists this invocation.
            server = create_server(cfg, bus=await connect_bus(cfg))
            await server.router.register_node()
            for n in await server.router.list_nodes():
                print(json.dumps(n.to_dict()))
            await server.router.unregister_node()

        asyncio.run(run())
        return 0
    if args.command == "drain":
        cfg = load_config(
            yaml_path=args.config if args.config else None,
            yaml_text=None if args.config else "development: true",
        )
        from livekit_server_tpu.service.server import connect_bus

        async def run_drain():
            bus = await connect_bus(cfg)
            if bus is None:
                print("drain needs a shared bus (kv.kind='tcp'); a "
                      "single-node server just stops", flush=True)
                return 2
            n = await bus.publish(f"node_migrate:{args.node}", {"kind": "drain"})
            if n == 0:
                print(f"node {args.node} is not listening (already gone?)",
                      flush=True)
                return 1
            print(f"drain requested on {args.node}", flush=True)
            return 0

        return asyncio.run(run_drain())
    if args.command == "serve":
        yaml_text = None if args.config else (
            "development: true" if args.dev else None
        )
        cfg = load_config(yaml_path=args.config, yaml_text=yaml_text, cli_args=args)
        return asyncio.run(_serve(cfg))
    _build_parser().print_help()
    return 1


async def _serve(cfg: Config) -> int:
    from livekit_server_tpu.service.server import connect_bus, create_server

    server = create_server(cfg, bus=await connect_bus(cfg))
    await server.start()
    print(
        f"livekit-server-tpu v{__version__} listening on "
        f"{cfg.bind_addresses}:{cfg.port} "
        f"(plane: {cfg.plane.rooms}r×{cfg.plane.tracks_per_room}t×"
        f"{cfg.plane.subs_per_room}s @ {cfg.plane.tick_ms}ms)",
        flush=True,
    )
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    print("shutting down...", flush=True)
    await server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
