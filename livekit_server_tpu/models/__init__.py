"""Media-plane models: the tick-driven batched SFU programs.

The "flagship model" is `plane.media_plane_tick` — one tick of the whole
SFU data plane for a node's rooms: layer selection, SN/TS/VP8 munging,
audio-level mixing, RTP stats, BWE, and bandwidth allocation, as a single
fused XLA program over `[rooms × tracks × pkts × subscribers]` tensors.
This replaces the reference's per-packet goroutine hot path
(pkg/sfu/receiver.go:635 forwardRTP → downtrack.go:680 WriteRTP).
"""
