"""The batched SFU media plane — flagship model.

One `media_plane_tick` call advances the entire media plane of a node by one
tick (~5-20 ms): for every room, every published track, every subscriber, it

  1. folds received packets into per-stream RTP stats
     (reference: buffer.Buffer.calc — pkg/sfu/buffer/buffer.go:417)
  2. updates per-layer bitrate estimates
     (reference: StreamTrackerManager Bitrates — streamtrackermanager.go)
  3. runs BWE trend detection + congestion per subscriber
     (reference: StreamAllocator event loop — streamallocator.go:563)
  4. allocates layers across tracks under the committed channel budget
     (reference: allocateAllTracks + Forwarder provisional algebra)
  5. selects simulcast/temporal layers per packet per subscriber
     (reference: videolayerselector — the Select half of WriteRTP)
  6. mixes audio levels into active-speaker rankings per room
     (reference: audio.AudioLevel + Room.audioUpdateWorker)

The whole thing is jit-compiled once; the room axis is vmapped and shards
over the device mesh (livekit_server_tpu.parallel). The host control plane
mutates subscription/mute masks and reads egress outputs between ticks.

Decide on device, rewrite on host (round-5 split)
-------------------------------------------------
The tick's egress product is three BIT-PACKED mask tensors — send / drop /
switch per (track, packet, subscriber), ⌈S/32⌉ words each — NOT per-send
SN/TS values. The SN/TS/VP8 offset rewriting (rtpmunger.go +
codecmunger/vp8.go semantics) runs on the HOST (runtime/munge.py + the
native walker), in the egress path that already touches every outgoing
packet's bytes — exactly where the reference runs it. Device tracing
showed the former device-side compaction (`jnp.nonzero` + six value
gathers) WAS the tick at scale: TPUs have no vector gather, so the
gathers cost ~29 ms of a 38 ms cfg4 tick, and at the 10k-room north-star
shape any multi-pass op over the dense [R,T,K,S] value tensors is
unaffordable. Masks are one elementwise pass and 32× smaller on the wire.

Shape glossary (static per compiled program):
  R rooms · T tracks/room · K packets/track/tick · S subscribers/room
  streams N = T (one SN space per simulcast layer is carried in the packet
  `layer` field; per-layer stats use T*L rows with L = MAX_LAYERS).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from livekit_server_tpu.analysis.registry import device_entry
from livekit_server_tpu.ops import (
    allocation,
    audio,
    bwe,
    pacer,
    quality,
    red,
    rtpstats,
    scanops,
    selector,
    streamtracker,
)

MAX_LAYERS = 3          # simulcast spatial layers (reference: 3 — receiver.go)
MAX_TEMPORAL = 4        # temporal sublayers tracked per spatial layer
SPEAKER_TOP_K = 3
SLAB_WINDOW = 64        # ticks of payload history the host retains for RTX
                        # (sequencer.go rtt-bounded ring; 64×10 ms = 640 ms —
                        # NACK resolution itself is host-side: see
                        # runtime/plane_runtime.py HostSequencer)
PAD_MAX = 8             # max probe-padding packets per subscriber per tick
                        # (8 × 255 B / 10 ms ≈ 1.6 Mbps of probe headroom)
# Cold-start per-temporal-sublayer bitrate shares, used only until measured
# per-temporal byte attribution (state.temporal_bytes) accumulates — the
# live path derives the [4][4] Bitrates matrix from observed traffic like
# the reference's StreamTrackerManager (streamtrackermanager.go:60-732).
TEMPORAL_FRACTIONS = (0.45, 0.65, 0.85, 1.0)


class PlaneDims(NamedTuple):
    rooms: int = 1
    tracks: int = 4        # per room
    pkts: int = 4          # per track per tick
    subs: int = 4          # per room


class TrackMeta(NamedTuple):
    """Host-written per-track control tensors, [R, T]."""

    is_video: jax.Array     # bool
    published: jax.Array    # bool — track exists and is live
    pub_muted: jax.Array    # bool — publisher muted
    is_svc: jax.Array       # bool — single-stream SVC (VP9/AV1) vs simulcast
                            # (receiver.go IsSvcCodec :142-150)


class SubControl(NamedTuple):
    """Host-written per-(track, subscriber) control tensors, [R, T, S]."""

    subscribed: jax.Array    # bool — SubscriptionManager desired state
    sub_muted: jax.Array     # bool — subscriber-requested mute
    max_spatial: jax.Array   # int32 — adaptive-stream cap
    max_temporal: jax.Array  # int32


class PlaneState(NamedTuple):
    """Full media-plane state, all leading axis [R] (sharded over mesh).

    SN/TS/VP8 munger state lives on the HOST (runtime/munge.py HostMunger)
    since the round-5 decide-on-device/rewrite-on-host split; the device
    carries only decision state.
    """

    meta: TrackMeta
    ctrl: SubControl
    stats: rtpstats.StreamStats          # [R, T*L] per (track, layer) stream
    audio_state: audio.AudioLevelState   # [R, T]
    sel: selector.SelectorState          # [R, T, S]
    bwe_state: bwe.BWEState              # [R, S]
    delay_bwe: bwe.DelayBWEState         # [R, S] — TWCC send-side estimator
    tracker: streamtracker.TrackerState  # [R, T*L] per (track, layer) stream
    pacer_state: pacer.PacerState        # [R, S] — leaky-bucket egress pacing
    red_state: red.REDState              # [R, T, D] — RED history rings
    temporal_bytes: jax.Array            # [R, T, L, MAX_TEMPORAL] float32 —
                                         # per-temporal byte/tick EMA (the
                                         # measured Bitrates attribution)


class TickInputs(NamedTuple):
    """Per-tick ingest tensors (host-packed; static shapes)."""

    # Packet fields, [R, T, K]:
    sn: jax.Array          # int32, 16-bit
    ts: jax.Array          # int32, 32-bit
    layer: jax.Array       # int32 — spatial/simulcast layer (0 for audio)
    temporal: jax.Array    # int32 — temporal id (0 if none)
    keyframe: jax.Array    # bool
    layer_sync: jax.Array  # bool — temporal upswitch point (VP8 Y bit)
    begin_pic: jax.Array   # bool — first packet of a picture / frame
    end_frame: jax.Array   # bool — last packet of the frame (RTP marker;
                           # SVC downswitch boundary — vp9.go)
    pid: jax.Array         # int32 — VP8 picture id (0 for audio)
    tl0: jax.Array         # int32 — VP8 TL0PICIDX
    keyidx: jax.Array      # int32 — VP8 KEYIDX
    size: jax.Array        # int32 — payload bytes
    frame_ms: jax.Array    # int32 — media duration carried by the packet
                           # (Opus ptime; 0 for video — levels are audio-only)
    audio_level: jax.Array # int32 — RFC6464 dBov (127 if none)
    arrival_rtp: jax.Array # int32 — arrival time in RTP units
    ts_jump: jax.Array     # int32 — TS advance at a source switch landing on
                           # this packet; -1 = host SR-normalized the TS onto
                           # the track's common timeline (no re-anchor)
    valid: jax.Array       # bool
    # Per-subscriber feedback, [R, S]:
    estimate: jax.Array        # float32 — TWCC/REMB estimate sample
    estimate_valid: jax.Array  # bool
    nacks: jax.Array           # float32 — NACK count this tick (BWE loss
                               # channel; resolution is host-side — see
                               # runtime HostSequencer)
    # Publisher-path RTT per track, [R, T] float32: measured host-side from
    # RTCP SR/RR (ingest.rtt_ms, RFC 3550 A.8) and gathered through the
    # track→publisher-slot mapping. Feeds the E-model delay term
    # (scorer.go:45-120 includes RTT); 0 where unknown.
    pub_rtt_ms: jax.Array
    # TWCC feedback reductions, [R, S] (see ops/bwe delay estimator):
    fb_delay_ms: jax.Array    # float32 — mean delay-variation this tick
    fb_recv_bps: jax.Array    # float32 — acked receive rate sample
    fb_valid: jax.Array       # bool — feedback arrived this tick
    fb_enabled: jax.Array     # bool — sub is on the sealed UDP path
    sub_reset: jax.Array      # [R, S] bool — slot released this tick:
                              # reset its per-sub device state (BWE/
                              # delay/pacer) before this tick's update
    # BWE probe padding (probe_controller → WritePaddingRTP), [R, S]:
    pad_num: jax.Array         # int32 — padding packets to synthesize (≤ PAD_MAX)
    pad_track: jax.Array       # int32 — track whose downtrack carries them (-1 none)
    # Scalars:
    tick_ms: jax.Array     # int32
    roll_quality: jax.Array  # int32 bool-ish — close the stats window this
                             # tick (host sets it ~1/s; the quality outputs
                             # always score the accumulating window)


class TickOutputs(NamedTuple):
    """Egress + signal tensors pulled by the host after each tick.

    Egress is three BIT-PACKED mask tensors (send / drop / switch), one bit
    per (track, packet, subscriber), W = ⌈S/32⌉ words on the minor axis.
    One elementwise pass to produce, ~32× smaller than dense bools on the
    device→host wire, and no gathers anywhere (see module docstring). The
    host (runtime/munge.py + native walker) expands the bits it forwards
    and applies the SN/TS/VP8 rewrites with host-owned state.
    """

    send_bits: jax.Array      # [R, T, K, W] int32 — forward pkt k to sub s
    drop_bits: jax.Array      # [R, T, K, W] int32 — current-stream drop
                              #   (SN-gap compaction event, rtpmunger.go
                              #   PacketDropped)
    switch_bits: jax.Array    # [R, T, K, W] int32 — source-switch re-anchor
                              #   (forwarder.go processSourceSwitch)
    need_keyframe: jax.Array   # [R, T, S] bool — host sends PLI upstream
    speaker_levels: jax.Array  # [R, SPEAKER_TOP_K] float32
    speaker_tracks: jax.Array  # [R, SPEAKER_TOP_K] int32 — room-local track idx
    congested: jax.Array       # [R, S] bool
    target_layers: jax.Array   # [R, S, T] int32 — flat layer targets
    fwd_packets: jax.Array     # [R] int32 — packets forwarded (telemetry)
    fwd_bytes: jax.Array       # [R] int32
    # Connection quality (ops/quality E-model; room.go:1318 worker feed):
    track_mos: jax.Array       # [R, T] float32 — publisher-side MOS
    track_quality: jax.Array   # [R, T] int32 — ConnectionQuality enum
    sub_quality: jax.Array     # [R, S] int32 — subscriber-side enum
    # Per-(track, layer) stream liveness (streamtracker; dynacast feed):
    layer_live: jax.Array      # [R, T, L] int32 — STOPPED/LIVE
    layer_fps: jax.Array       # [R, T, L] float32 — measured frame rate
                               # (fps.go; frame-tracker variant output)
    # Windowed per-track receive stats (telemetry; rolled by roll_quality):
    track_loss_pct: jax.Array  # [R, T] float32
    track_jitter_ms: jax.Array # [R, T] float32
    track_bps: jax.Array       # [R, T] float32 — summed live-layer bitrate
    # (Probe padding synthesis moved host-side with the munger state —
    # runtime/munge.py HostMunger.padding.)
    # Allocator budget per subscriber (probe goal baseline + telemetry):
    committed_bps: jax.Array   # [R, S] float32
    pacer_allowed: jax.Array   # [R, S] float32 — leaky-bucket byte budget
                               # the host egress may write this tick
    deficient: jax.Array       # [R, S] bool — allocation under-served this
                               # sub (probe trigger; streamallocator
                               # "deficient" state)
    # RED encapsulation plan for audio packets (redreceiver.go): per
    # packet, the D candidate redundancy blocks by source SN, their 14-bit
    # TS offsets, and RFC 2198 fit. Host egress assembles bytes for
    # RED-negotiated subscribers from its payload ring.
    red_sn: jax.Array          # [R, T, K, D] int32
    red_off: jax.Array         # [R, T, K, D] int32
    red_ok: jax.Array          # [R, T, K, D] bool


@device_entry("plane.init_state")
def init_state(dims: PlaneDims) -> PlaneState:
    R, T, K, S = dims
    L = MAX_LAYERS

    def tile(x, *lead):
        return jnp.broadcast_to(x, lead + x.shape).copy()

    meta = TrackMeta(
        is_video=jnp.zeros((R, T), jnp.bool_),
        published=jnp.zeros((R, T), jnp.bool_),
        pub_muted=jnp.zeros((R, T), jnp.bool_),
        is_svc=jnp.zeros((R, T), jnp.bool_),
    )
    ctrl = SubControl(
        subscribed=jnp.zeros((R, T, S), jnp.bool_),
        sub_muted=jnp.zeros((R, T, S), jnp.bool_),
        max_spatial=jnp.full((R, T, S), MAX_LAYERS - 1, jnp.int32),
        max_temporal=jnp.full((R, T, S), 3, jnp.int32),
    )
    return PlaneState(
        meta=meta,
        ctrl=ctrl,
        stats=jax.tree.map(lambda x: tile(x, R), rtpstats.init_state(T * L)),
        audio_state=jax.tree.map(lambda x: tile(x, R), audio.init_state(T)),
        sel=jax.tree.map(lambda x: tile(x, R, T), selector.init_state(S)),
        bwe_state=jax.tree.map(lambda x: tile(x, R), bwe.init_state(S)),
        delay_bwe=jax.tree.map(lambda x: tile(x, R), bwe.delay_init_state(S)),
        tracker=jax.tree.map(lambda x: tile(x, R), streamtracker.init_state(T * L)),
        pacer_state=jax.tree.map(lambda x: tile(x, R), pacer.init_state(S)),
        red_state=jax.tree.map(lambda x: tile(x, R), red.init_state(T)),
        temporal_bytes=jnp.zeros((R, T, L, MAX_TEMPORAL), jnp.float32),
    )


# Bit-mask helpers live in ops/bits.py (shared with the decision kernel's
# CPU fallback); re-exported here for the runtime and tests.
from livekit_server_tpu.ops.bits import (  # noqa: E402
    mask_words,
    pack_bits as _pack_bits,
    unpack_bits,
)


def _room_tick(
    state: PlaneState,
    inp: TickInputs,
    send_bits: jax.Array,    # [T, K, W] — phase-0 decision kernel outputs
    drop_bits: jax.Array,
    switch_bits: jax.Array,
    need_kf: jax.Array,      # [T, S] bool, base-merged
    pkts_sent_i: jax.Array,  # [S] int32
    sent_bytes_i: jax.Array, # [S] int32 (wire overhead included)
    fwd_packets_i: jax.Array,  # [] int32
    fwd_bytes_i: jax.Array,    # [] int32
    audio_params: audio.AudioLevelParams,
    bwe_params: bwe.BWEParams,
    red_enabled: bool = True,
    *,
    routed_stats=None,
):
    """Phase-1 core tick for ONE room; every field has its leading R axis
    stripped. The forward decision (phase 0) and allocation (phase 2) run
    room-batched in `media_plane_tick`; this returns `bitrates` for phase
    2 and placeholder zeros for the allocation-derived output fields.

    `routed_stats`, when given, is `(st [5, T*L, K], tr_sums [3, T*L])` —
    the stats/tracker routing selects precomputed by the live-page fused
    kernel (ops/paged_kernel.py) with the identical int algebra; the
    in-place computation below is then skipped bit-for-bit."""
    T, K = inp.sn.shape
    S = state.ctrl.subscribed.shape[-1]
    L = MAX_LAYERS

    # ---- 1. RTP stats per (track, layer) stream -------------------------
    # Simulcast layers are independent RTP streams (own SN spaces) and get
    # one stats row each; an SVC track carries every spatial layer in ONE
    # stream/SN space, so all its packets fold into row 0 — per-layer rows
    # would misread the interleaved SNs as massive loss.
    lanes = jnp.arange(L, dtype=jnp.int32)[None, None, :]            # [1,1,L]
    if routed_stats is None:
        eff_layer = jnp.where(
            state.meta.is_svc[:, None], 0, jnp.clip(inp.layer, 0, L - 1)
        )
        # Route packets into [T*L, K] rows by (track, layer) — as an
        # elementwise one-hot select, NOT a scatter: k is preserved, so
        # (t, k) → (t, eff_layer, k) can never collide, and
        # data-dependent scatters serialize per element on TPU while
        # this select/transpose fuses (the cfg4-scale tick was dominated
        # by exactly this scatter).
        # One stacked routed select for all five stats fields (sn/ts/
        # size/arrival/valid) — five separate [T,K,L] selects each
        # materialize their own routing compare + transpose; stacked
        # they share it and fuse into one pass (same discipline as the
        # tracker's tr_vals stack below). Every field's "not this lane"
        # fill is 0 (valid rides as int32 0/1), so a single zero fill
        # serves the stack.
        st_vals = jnp.stack(
            [inp.sn, inp.ts, inp.size, inp.arrival_rtp,
             inp.valid.astype(jnp.int32)]
        )                                                            # [5,T,K]
        st_routed = jnp.where(
            (eff_layer[:, :, None] == lanes)[None], st_vals[:, :, :, None], 0
        )                                                            # [5,T,K,L]
        st = st_routed.transpose(0, 1, 3, 2).reshape(5, T * L, K)
        # Tracker rows route by each packet's TRUE spatial layer (see
        # the section-2 comment below); computed here so the fused
        # kernel can hand BOTH routings in via `routed_stats`.
        true_layer = jnp.clip(inp.layer, 0, L - 1)
        t_lane = true_layer[:, :, None] == lanes                    # [T,K,L]
        # One stacked routed-sum for (pkts, bytes, frames) — three
        # separate reduces cost ~0.9 ms/tick at cfg4; stacked they share
        # the routing select and fuse into one pass.
        ones_k = jnp.ones((T, K), jnp.int32)
        tr_vals = jnp.stack([ones_k, inp.size, ones_k])             # [3,T,K]
        tr_pred = jnp.stack(
            [inp.valid, inp.valid, inp.valid & inp.begin_pic]
        )                                                           # [3,T,K]
        routed = jnp.where(
            t_lane[None] & tr_pred[:, :, :, None], tr_vals[:, :, :, None], 0
        )                                                           # [3,T,K,L]
        tr_sums = jnp.sum(routed, axis=2).reshape(3, T * L)
    else:
        st, tr_sums = routed_stats
    stats = rtpstats.update_tick(
        state.stats, st[0], st[1], st[2], st[3], st[4].astype(jnp.bool_)
    )

    # ---- 2. per-layer liveness + measured [4][4] bitrate matrix ---------
    # StreamTracker rows per (track, layer). Unlike the stats rows above,
    # tracker rows route by each packet's TRUE spatial layer — for SVC
    # tracks that's the DD/VP9-refined layer, which IS the reference's
    # DD-driven tracker variant (streamtracker_dd.go): an SVC layer's row
    # goes LIVE/STOPPED as decode targets appear/vanish. Frame starts
    # feed the frame-rate rule + fps estimation (streamtracker_frame.go,
    # fps.go). (The routed sums themselves are computed above, next to
    # the stats routing, so `routed_stats` can replace both at once.)
    st_pkts, st_bytes, st_frames = tr_sums[0], tr_sums[1], tr_sums[2]
    tracker, layer_status, _status_changed, tracker_bps, layer_fps = (
        streamtracker.update_tick(
            state.tracker, streamtracker.TrackerParams(), st_pkts, st_bytes,
            inp.tick_ms, frames=st_frames,
        )
    )
    # Per-(layer, temporal) byte attribution EMA — the measured version of
    # the reference's Bitrates matrix (streamtrackermanager.go:60).
    layer_oh = jax.nn.one_hot(jnp.clip(inp.layer, 0, L - 1), L, dtype=jnp.float32)
    tm_oh = jax.nn.one_hot(
        jnp.clip(inp.temporal, 0, MAX_TEMPORAL - 1), MAX_TEMPORAL, dtype=jnp.float32
    )
    vbytes = jnp.where(inp.valid, inp.size, 0).astype(jnp.float32)
    tick_bytes_lt = jnp.einsum("tk,tkl,tkm->tlm", vbytes, layer_oh, tm_oh)  # [T,L,4]
    temporal_bytes = state.temporal_bytes * 0.9 + tick_bytes_lt * 0.1
    tick_s = jnp.maximum(inp.tick_ms.astype(jnp.float32), 1.0) / 1000.0
    # Layer bitrate: tracker cycles once committed; per-tick EMA bootstraps
    # the first cycle so allocation starts on the first packets. SVC tracks
    # keep the EMA attribution even though tracker rows are now per true
    # spatial layer (the DD-variant liveness feed): their temporal splits
    # come from temporal_bytes either way, and the faster EMA avoids a
    # 500 ms tracker-cycle lag on the onion's cumulative costs.
    boot_bps = jnp.sum(temporal_bytes, axis=-1) * 8.0 / tick_s        # [T, L]
    layer_bps = jnp.where(
        ~state.meta.is_svc[:, None] & (tracker_bps.reshape(T, L) > 0),
        tracker_bps.reshape(T, L),
        boot_bps,
    )
    # Cumulative temporal shares from measured bytes; cold-start fractions
    # until any bytes attribute. (scanops: jnp.cumsum lowers to a
    # reduce-window that measured ~2.7 ms/tick at cfg4 on these tiny axes.)
    tot = jnp.sum(temporal_bytes, axis=-1, keepdims=True)             # [T, L, 1]
    cum = scanops.cumsum_small(temporal_bytes, axis=-1)               # [T, L, 4]
    frac0 = jnp.asarray(TEMPORAL_FRACTIONS, jnp.float32)
    frac = jnp.where(tot > 0, cum / jnp.maximum(tot, 1e-6), frac0[None, None, :])
    bitrates = jnp.zeros((T, 4, 4), jnp.float32)
    bitrates = bitrates.at[:, :L, :].set(layer_bps[:, :, None] * frac)
    # SVC onion: forwarding spatial s sends every layer <= s, so the cost
    # of an SVC entry is the cumulative sum over spatial layers (the
    # reference reports cumulative SVC bitrates) — without this the
    # allocator over-commits the channel by the lower layers' bps.
    bitrates = jnp.where(
        state.meta.is_svc[:, None, None],
        scanops.cumsum_small(bitrates, axis=1),
        bitrates,
    )
    # Audio has a single "layer": zero the matrix so allocation skips it.
    bitrates = jnp.where(state.meta.is_video[:, None, None], bitrates, 0.0)

    # ---- 3+6. forward decision: computed in media_plane_tick's phase 0
    # as ONE room-batched Pallas kernel (selection + subscription/mute
    # base merge + audio path + egress bit packing + send sums) and
    # passed in — the dense [T,K,S] masks never materialize. The SN/TS/
    # VP8 value rewrites happen host-side (runtime/munge.py) from the
    # send/drop/switch bits + host-owned offset state; NACK/RTX replay is
    # likewise host-side (HostSequencer), and probe padding synthesis
    # (WritePaddingRTP, downtrack.go:764) rides the same host state.

    # ---- BWE per subscriber (uses this tick's actual send counts) ------
    # Released slots reset their per-sub state first: the next occupant
    # must not inherit a decayed rate or a sticky feedback latch.
    def _reset_rows(cur_tree, init_tree, mask):
        def f(c, i):
            m = mask.reshape(mask.shape + (1,) * (c.ndim - mask.ndim))
            return jnp.where(m, i, c)
        return jax.tree.map(f, cur_tree, init_tree)

    bwe_prev = _reset_rows(state.bwe_state, bwe.init_state(S), inp.sub_reset)
    delay_prev = _reset_rows(
        state.delay_bwe, bwe.delay_init_state(S), inp.sub_reset
    )
    pacer_prev = _reset_rows(
        state.pacer_state, pacer.init_state(S), inp.sub_reset
    )
    pkts_sent = pkts_sent_i.astype(jnp.float32)                 # [S]
    bwe_state, congested, trend, budget = bwe.update_tick(
        bwe_prev, bwe_params, inp.estimate, inp.estimate_valid,
        pkts_sent, inp.nacks,
    )
    # TWCC send-side estimate (transport.go:253-374 seat): where active,
    # it CAPS the budget — allocation then never exceeds what the sender
    # itself measured from feedback, however optimistic (or absent) the
    # client's volunteered estimates are.
    delay_bwe, delay_rate, delay_over, delay_active = bwe.delay_update_tick(
        delay_prev, bwe.DelayBWEParams(), inp.fb_delay_ms,
        inp.fb_recv_bps, inp.fb_valid, inp.fb_enabled, pkts_sent, inp.tick_ms,
    )
    budget = jnp.where(delay_active, jnp.minimum(budget, delay_rate), budget)
    congested = congested | delay_over

    # ---- leaky-bucket egress pacing (pacer/leaky_bucket.go:47-200) ------
    # Budgets from the allocator's committed rate gate the HOST egress
    # (runtime/udp.py _pacer_gate) when rtc.pacer == "leaky-bucket"; in
    # other modes the output is simply unused.
    pacer_state, pacer_allowed, _pacer_backlog = pacer.update_tick(
        pacer_prev, pacer.PacerParams(), sent_bytes_i.astype(jnp.float32),
        budget, inp.tick_ms,
    )

    # (Cross-track allocation happens in media_plane_tick's phase 2 as one
    # room-batched Pallas kernel; this core returns `bitrates` for it.)

    # ---- connection quality (scorer.go E-model; room.go:1318 worker) ----
    # Scored every tick over the accumulating stats window; the host rolls
    # the window ~1/s via inp.roll_quality.
    expected = rtpstats.expected_packets(stats)                       # [T*L]
    exp_d = jnp.maximum(expected - stats.snap_expected, 0).reshape(T, L)
    rcv_d = jnp.maximum(stats.received - stats.snap_received, 0).reshape(T, L)
    exp_t = jnp.sum(exp_d, axis=-1)
    rcv_t = jnp.sum(rcv_d, axis=-1)
    loss_pct = jnp.where(
        exp_t > 0, 100.0 * (exp_t - rcv_t) / jnp.maximum(exp_t, 1), 0.0
    ).astype(jnp.float32)
    jitter_rtp = jnp.max((stats.jitter_q4 >> 4).reshape(T, L), axis=-1)
    clock_khz = jnp.where(state.meta.is_video, 90.0, 48.0)
    jitter_ms = jitter_rtp.astype(jnp.float32) / clock_khz
    has_pkts = (rcv_t > 0) & state.meta.published
    track_mos, track_q = quality.connection_quality(
        loss_pct, inp.pub_rtt_ms, jitter_ms, has_pkts
    )
    # A pub-muted track legitimately sends nothing — it must not read as
    # LOST (connectionstats.go excludes muted tracks from LOST detection).
    track_mos = jnp.where(state.meta.pub_muted, 5.0, track_mos)
    track_q = jnp.where(
        state.meta.pub_muted, quality.QUALITY_EXCELLENT, track_q
    )
    track_q = jnp.where(state.meta.published, track_q, quality.QUALITY_LOST)
    roll = inp.roll_quality > 0
    stats = stats._replace(
        snap_received=jnp.where(roll, stats.received, stats.snap_received),
        snap_expected=jnp.where(roll, expected, stats.snap_expected),
    )

    # ---- RED encapsulation plan (redreceiver.go) -----------------------
    # Audio-only: which previous packets can ride as RFC 2198 redundancy
    # blocks on each primary; the host assembles bytes per RED subscriber.
    # Statically gated: with audio/red not in the enabled codecs, the plan
    # tensors are zero-K so the per-tick device→host transfer pays nothing.
    if red_enabled:
        red_state, red_sn, red_off, _red_len, red_ok = red.encode_plan_tick(
            state.red_state, inp.sn, inp.ts, inp.size,
            inp.valid & ~state.meta.is_video[:, None],
        )
    else:
        red_state = state.red_state
        red_sn = jnp.zeros((T, 0, red.RED_DISTANCE), jnp.int32)
        red_off = jnp.zeros((T, 0, red.RED_DISTANCE), jnp.int32)
        red_ok = jnp.zeros((T, 0, red.RED_DISTANCE), jnp.bool_)

    # ---- 7. audio levels + active speakers -----------------------------
    is_audio_pkt = inp.valid & ~state.meta.is_video[:, None]
    audio_state, linear, is_active = audio.observe_tick(
        state.audio_state, audio_params,
        jnp.where(is_audio_pkt, inp.audio_level, 127),
        inp.frame_ms,
        is_audio_pkt,
        inp.tick_ms,
    )
    k = min(SPEAKER_TOP_K, T)
    spk_levels, spk_tracks = audio.top_speakers(
        jnp.where(is_active & state.meta.published, linear, 0.0), k
    )
    if k < SPEAKER_TOP_K:
        pad = SPEAKER_TOP_K - k
        spk_levels = jnp.pad(spk_levels, (0, pad))
        spk_tracks = jnp.pad(spk_tracks, (0, pad), constant_values=-1)

    new_state = PlaneState(
        meta=state.meta,
        ctrl=state.ctrl,
        stats=stats,
        audio_state=audio_state,
        sel=state.sel,  # phase 2 installs the post-selection, re-targeted
                        # selector state (this leaf is replaced there)
        bwe_state=bwe_state,
        delay_bwe=delay_bwe,
        tracker=tracker,
        pacer_state=pacer_state,
        red_state=red_state,
        temporal_bytes=temporal_bytes,
    )
    zero_s = jnp.zeros((S,), jnp.int32)
    outputs = TickOutputs(
        send_bits=send_bits,
        drop_bits=drop_bits,
        switch_bits=switch_bits,
        need_keyframe=need_kf,
        speaker_levels=spk_levels,
        speaker_tracks=spk_tracks,
        congested=congested,
        target_layers=jnp.zeros((S, T), jnp.int32),  # phase 2
        fwd_packets=fwd_packets_i,
        fwd_bytes=fwd_bytes_i,
        track_mos=track_mos,
        track_quality=track_q,
        sub_quality=zero_s,                          # phase 2
        layer_live=layer_status.reshape(T, L),
        layer_fps=layer_fps.reshape(T, L),
        track_loss_pct=loss_pct,
        track_jitter_ms=jitter_ms,
        track_bps=jnp.sum(layer_bps, axis=-1),
        committed_bps=budget,
        pacer_allowed=pacer_allowed,
        deficient=zero_s.astype(bool),               # phase 2
        red_sn=red_sn.astype(jnp.int32),
        red_off=red_off.astype(jnp.int32),
        red_ok=red_ok,
    )
    return new_state, outputs, bitrates


@device_entry("plane.media_plane_tick")
def media_plane_tick(
    state: PlaneState,
    inp: TickInputs,
    audio_params: audio.AudioLevelParams = audio.AudioLevelParams(),
    bwe_params: bwe.BWEParams = bwe.BWEParams(),
    red_enabled: bool = True,
):
    """One tick of the full media plane.

    Three phases: (0) room-BATCHED layer selection (Pallas kernel, rooms
    on the vector lanes — a vmapped per-room kernel pays per-grid-step
    fixed costs ×R); (1) the per-room core, vmapped; (2) room-BATCHED
    cross-track allocation, whose targets feed the NEXT tick's selection
    (the reference's allocator lags forwarding the same way —
    streamallocator.go ticks at 100 ms).

    jit this (donating `state`) and step it from the runtime loop;
    `red_enabled` is static per compile. The [R] axis is the mesh-sharded
    axis (see livekit_server_tpu.parallel.mesh — sharded via shard_map,
    so the Pallas grids stay shard-local).
    """
    L = MAX_LAYERS

    # ---- phase 0: forward decision over all rooms ----------------------
    # ONE room-batched Pallas kernel: selection, subscription/mute base
    # merge, audio path, egress bit packing, and the per-subscriber send
    # sums — dense [R,T,K,S] masks never exist in HBM.
    base = (
        state.ctrl.subscribed
        & ~state.ctrl.sub_muted
        & (state.meta.published & ~state.meta.pub_muted)[:, :, None]
    )                                                           # [R, T, S]
    (sel_state, send_bits, drop_bits, switch_bits, need_kf,
     pkts_sent, sent_bytes, fwd_packets, fwd_bytes) = selector.decide_rooms(
        state.sel, state.meta.is_svc, state.meta.is_video, base,
        inp.layer, inp.temporal, inp.keyframe, inp.layer_sync,
        inp.end_frame, inp.valid, inp.size,
        wire_overhead=pacer.WIRE_OVERHEAD_BYTES,
    )

    # ---- phase 1: per-room core (vmapped) ------------------------------
    def tick_one(st, i, sb, db, wb, nk, ps, sby, fp, fby):
        return _room_tick(st, i, sb, db, wb, nk, ps, sby, fp, fby,
                          audio_params, bwe_params, red_enabled)

    inp_axes = TickInputs(**{f: 0 for f in TickInputs._fields})._replace(
        tick_ms=None, roll_quality=None
    )
    new_state, outputs, bitrates = jax.vmap(
        tick_one, in_axes=(0, inp_axes, 0, 0, 0, 0, 0, 0, 0, 0)
    )(state, inp, send_bits, drop_bits, switch_bits, need_kf,
      pkts_sent, sent_bytes, fwd_packets, fwd_bytes)

    # ---- phase 2: allocation over all rooms → next tick's targets ------
    video_active = (
        state.meta.is_video & state.meta.published & ~state.meta.pub_muted
    )
    alloc_muted = ~(
        state.ctrl.subscribed & video_active[:, :, None]
        & ~state.ctrl.sub_muted
    ).transpose(0, 2, 1)                                        # [R, S, T]
    target_flat, _used, deficient = allocation.allocate_budget_rooms(
        bitrates,
        state.ctrl.max_spatial.transpose(0, 2, 1),
        state.ctrl.max_temporal.transpose(0, 2, 1),
        alloc_muted,
        outputs.committed_bps,
    )                                                           # [R, S, T]
    tgt_ts = target_flat.transpose(0, 2, 1)                     # [R, T, S]
    sel_state = selector.set_target(
        sel_state,
        jnp.clip(allocation.spatial_of(tgt_ts), -1, L - 1),
        allocation.temporal_of(tgt_ts),
    )
    any_deficient = jnp.any(deficient, axis=-1)                 # [R, S]
    sub_q = jnp.where(
        outputs.congested,
        quality.QUALITY_POOR,
        jnp.where(any_deficient, quality.QUALITY_GOOD,
                  quality.QUALITY_EXCELLENT),
    ).astype(jnp.int32)
    new_state = new_state._replace(sel=sel_state)
    outputs = outputs._replace(
        target_layers=target_flat,
        deficient=any_deficient,
        sub_quality=sub_q,
    )
    return new_state, outputs


# ---------------------------------------------------------------------------
# Wire packing: one upload + one fetch per tick.
#
# A remote/tunneled device (and even PCIe) pays per-transfer latency, so the
# runtime ships TickInputs as ONE stacked int32 array (+ one float32 feedback
# array) and receives TickOutputs as ONE flat int32 buffer, unpacked by known
# offsets on host. The reference has no analog — its packets stay in host
# memory — this is the TPU build's host↔HBM DMA discipline (SURVEY.md §7
# "double-buffered DMA").
# ---------------------------------------------------------------------------

# Fields uploaded to the device. TickInputs also carries HOST-ONLY fields
# (pid / tl0 / keyidx / ts_jump / pad_num / pad_track) consumed by the
# host munger + padding synthesis (runtime/munge.py) — the device tick
# never reads them, so they are not packed onto the wire.
PKT_FIELDS = (
    "sn", "ts", "layer", "temporal", "keyframe", "layer_sync", "begin_pic",
    "end_frame", "size", "frame_ms", "audio_level", "arrival_rtp", "valid",
)
_BOOL_FIELDS = {"keyframe", "layer_sync", "begin_pic", "end_frame", "valid"}
HOST_ONLY_PKT_FIELDS = ("pid", "tl0", "keyidx", "ts_jump")


def pack_tick_inputs(inp: TickInputs):
    """Host-side: TickInputs → (pkt [F,R,T,K] i32, fb [8,R,S] f32,
    tf [1,R,T] f32, tick_ms, roll_quality)."""
    import numpy as np

    pkt = np.stack([np.asarray(getattr(inp, f)).astype(np.int32) for f in PKT_FIELDS])
    fb = np.stack(
        [
            np.asarray(inp.estimate, np.float32),
            np.asarray(inp.estimate_valid).astype(np.float32),
            np.asarray(inp.nacks, np.float32),
            np.asarray(inp.fb_delay_ms, np.float32),
            np.asarray(inp.fb_recv_bps, np.float32),
            np.asarray(inp.fb_valid).astype(np.float32),
            np.asarray(inp.fb_enabled).astype(np.float32),
            np.asarray(inp.sub_reset).astype(np.float32),
        ]
    )
    tf = np.asarray(inp.pub_rtt_ms, np.float32)[None]
    return (
        pkt, fb, tf,
        np.int32(inp.tick_ms), np.int32(inp.roll_quality),
    )


def unpack_tick_inputs(
    pkt: jax.Array, fb: jax.Array, tf: jax.Array,
    tick_ms: jax.Array, roll_quality: jax.Array,
) -> TickInputs:
    """Device-side (traced): stacked arrays → TickInputs.

    Host-only fields are filled with zeros: the device algebra never reads
    them (XLA dead-code-eliminates the placeholders)."""
    fields = {}
    for i, name in enumerate(PKT_FIELDS):
        x = pkt[i]
        fields[name] = x.astype(jnp.bool_) if name in _BOOL_FIELDS else x
    z_pkt = jnp.zeros_like(pkt[0])
    for name in HOST_ONLY_PKT_FIELDS:
        fields[name] = z_pkt
    z_sub = jnp.zeros(fb.shape[1:], jnp.int32)
    return TickInputs(
        **fields,
        estimate=fb[0],
        estimate_valid=fb[1] > 0.5,
        nacks=fb[2],
        pub_rtt_ms=tf[0],
        pad_num=z_sub,
        pad_track=z_sub - 1,
        fb_delay_ms=fb[3],
        fb_recv_bps=fb[4],
        fb_valid=fb[5] > 0.5,
        fb_enabled=fb[6] > 0.5,
        sub_reset=fb[7] > 0.5,
        tick_ms=tick_ms,
        roll_quality=roll_quality,
    )


def pack_ctrl_rows(meta: TrackMeta, ctrl: SubControl, rows, pad_to: int | None = None):
    """Host-side half of the dirty-row control upload: gather the dirtied
    room rows of the host mirrors into two stacked int32 arrays.

    Returns (rows [n] i32, meta_rows [4, n, T] i32, ctrl_rows [4, n, T, S]
    i32) — O(dirty rows) bytes, not O(R·T·S). `pad_to` repeats the first
    row up to a bucket size so the device scatter compiles once per
    bucket instead of once per distinct dirty count (duplicate indices
    carry identical values, so the scatter stays deterministic).
    """
    import numpy as np

    rows = np.asarray(sorted(rows), np.int32)
    if pad_to is not None and len(rows) < pad_to:
        rows = np.concatenate([rows, np.repeat(rows[:1], pad_to - len(rows))])
    meta_rows = np.stack([np.asarray(m)[rows].astype(np.int32) for m in meta])
    ctrl_rows = np.stack([np.asarray(c)[rows].astype(np.int32) for c in ctrl])
    return rows, meta_rows, ctrl_rows


@device_entry("plane.apply_ctrl_delta")
def apply_ctrl_delta(state: PlaneState, rows, meta_rows, ctrl_rows) -> PlaneState:
    """Device-side (traced) half: scatter the dirtied rows into the
    control tensors via `.at[rows].set(...)` — the delta-upload analog of
    the full `_replace` in PlaneRuntime._upload_ctrl. Jitted with the
    state donated, so the row writes are in-place in HBM."""
    meta = TrackMeta(
        *[
            leaf.at[rows].set(meta_rows[i].astype(leaf.dtype))
            for i, leaf in enumerate(state.meta)
        ]
    )
    ctrl = SubControl(
        *[
            leaf.at[rows].set(ctrl_rows[i].astype(leaf.dtype))
            for i, leaf in enumerate(state.ctrl)
        ]
    )
    return state._replace(meta=meta, ctrl=ctrl)


def pack_tick_outputs(out: TickOutputs) -> jax.Array:
    """Device-side (traced): TickOutputs → one flat int32 buffer.

    float32 leaves travel as bit patterns (bitcast), bools as 0/1.
    """
    def flat(x):
        if x.dtype == jnp.float32:
            x = jax.lax.bitcast_convert_type(x, jnp.int32)
        return x.astype(jnp.int32).reshape(-1)

    return jnp.concatenate([flat(getattr(out, f)) for f in TickOutputs._fields])


def unpack_tick_outputs(
    buf, dims: PlaneDims, red_enabled: bool = True
) -> TickOutputs:
    """Host-side: flat int32 numpy buffer → TickOutputs of numpy arrays."""
    import numpy as np

    R, T, K, S = dims
    W = mask_words(S)
    shapes = {
        "send_bits": (R, T, K, W),
        "drop_bits": (R, T, K, W),
        "switch_bits": (R, T, K, W),
        "need_keyframe": (R, T, S),
        "speaker_levels": (R, SPEAKER_TOP_K),
        "speaker_tracks": (R, SPEAKER_TOP_K),
        "congested": (R, S),
        "target_layers": (R, S, T),
        "fwd_packets": (R,),
        "fwd_bytes": (R,),
        "track_mos": (R, T),
        "track_quality": (R, T),
        "sub_quality": (R, S),
        "layer_live": (R, T, MAX_LAYERS),
        "layer_fps": (R, T, MAX_LAYERS),
        "track_loss_pct": (R, T),
        "track_jitter_ms": (R, T),
        "track_bps": (R, T),
        "committed_bps": (R, S),
        "pacer_allowed": (R, S),
        "deficient": (R, S),
        "red_sn": (R, T, K if red_enabled else 0, red.RED_DISTANCE),
        "red_off": (R, T, K if red_enabled else 0, red.RED_DISTANCE),
        "red_ok": (R, T, K if red_enabled else 0, red.RED_DISTANCE),
    }
    floats = {"speaker_levels", "track_mos", "track_loss_pct", "track_jitter_ms",
              "track_bps", "committed_bps", "pacer_allowed", "layer_fps"}
    bools = {"need_keyframe", "congested", "deficient", "red_ok"}
    buf = np.asarray(buf)
    pieces, off = {}, 0
    for name in TickOutputs._fields:
        n = int(np.prod(shapes[name]))
        x = buf[off : off + n].reshape(shapes[name])
        off += n
        if name in floats:
            x = x.view(np.float32)
        elif name in bools:
            x = x.astype(bool)
        pieces[name] = x
    return TickOutputs(**pieces)


def masks_to_dense(out: TickOutputs, dims: PlaneDims):
    """Unpack the bit-packed egress masks to dense [R,T,K,S] bools
    (host/test helper; the runtime's fan-out uses the same expansion)."""
    S = dims.subs
    return (
        unpack_bits(out.send_bits, S),
        unpack_bits(out.drop_bits, S),
        unpack_bits(out.switch_bits, S),
    )
