"""Paged media plane: the dense tick re-based onto pooled HBM pages.

The dense plane (models/plane.py) is `[R, T, K, S]` — every room pays
the configured worst case. Here the device state is ONE pool of P
fixed-shape PAGES, each a `[tpage, K, spage]` block of some room's
(track × subscriber) plane, plus a device-resident page table the tick
indirects through (runtime/pager.py owns the host allocator and the
canonical table mirrors). A 2-person room holds one page; the 50-sub
north star holds its full grid — rooms/chip follows the actual room-size
distribution (pooled-page layout per Ragged Paged Attention, PAPERS.md).

The trick that makes this nearly free: the dense tick is already almost
everywhere PER-(track, sub)-ELEMENT or separable per track / per sub, so
a page is just a small dense room and the pooled tick IS the dense tick
at dims `[P, TP, K, SP]`. Exactly two couplings cross pages, and both
are row-granular gathers through `tmembers` (the page ids of one room's
sub column across its track pages):

  1. per-subscriber send totals (BWE/pacer input): summed over the
     room's track pages — disjoint (track, pkt) blocks, so integer sums
     are exact;
  2. phase-2 cross-track allocation: each page gathers its room's FULL
     track axis (bitrates + ctrl, `MT·TP == T` entries, missing rows
     filled with the dense init values) so the budget algebra sees the
     same operands as the dense plane, then keeps its own-tp slice of
     the targets.

Cross-page consistency is by construction — DUPLICATE EVERYWHERE, READ
FROM ONE: the host stages a track's packets into every sp-page of its
track group and a sub's feedback into every tp-page of its sub group, so
per-track state (stats/tracker/audio/RED) computes identically in all
sp-duplicates (read back from sp==0) and per-sub state (BWE/pacer)
identically in all tp-duplicates (read back from tp==0). Free pages get
zeroed inputs and init ctrl, hence no sends — and the tick PINS their
state to its pre-tick values (a zero-input tick would still advance
pacer tokens / BWE sample age / tracker windows), so a free page always
holds pristine init state. That enforced invariant is what lets the
live-extent fused path (`paged_plane_tick_live` + ops/paged_kernel.py)
skip dead pages entirely: their state needs no writes and their outputs
are one shared constant computed from the init template.

This module also owns the host-side layout translation (pooled ↔ logical
numpy) used by checkpoints, integrity repair, the express mirror, and
the dense-vs-paged parity tests: every PlaneState leaf is one of three
KINDS — "track" `[R, T·m, …]`, "sub" `[R, S, …]`, "track_sub"
`[R, T, S, …]` — and each kind is a pure index-arithmetic reshape +
fancy-index against the page table. Checkpoints serialize the LOGICAL
form, which is what keeps them byte-identical across pool layouts.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.analysis.registry import device_entry
from livekit_server_tpu.models import plane
from livekit_server_tpu.models.plane import (
    MAX_LAYERS,
    SPEAKER_TOP_K,
    PlaneDims,
    PlaneState,
    TickInputs,
    TickOutputs,
)
from livekit_server_tpu.ops import allocation, audio, bwe, pacer, quality, selector
from livekit_server_tpu.ops.bits import mask_words


class PagedDims(NamedTuple):
    """Logical plane dims + the page geometry over them.

    `tpage`/`spage` must divide `tracks`/`subs` (pow2, spage | 32 so a
    sub page never straddles a bit-mask word boundary): the logical
    plane is exactly an MT × MS grid of page-shaped tiles, which keeps
    logical↔pooled translation pure index arithmetic.
    """

    rooms: int
    tracks: int
    pkts: int
    subs: int
    tpage: int
    spage: int
    pool_pages: int

    @property
    def max_tpages(self) -> int:
        return self.tracks // self.tpage

    @property
    def max_spages(self) -> int:
        return self.subs // self.spage

    @property
    def logical(self) -> PlaneDims:
        return PlaneDims(self.rooms, self.tracks, self.pkts, self.subs)

    def pooled(self) -> PlaneDims:
        """The pool as the PlaneDims the ops stack compiles against:
        pages are the batch axis, a page is a [tpage, K, spage] room."""
        return PlaneDims(self.pool_pages, self.tpage, self.pkts, self.spage)


class PageTable(NamedTuple):
    """Device-resident page table (host canonical copy lives in the
    pager; this is the delta-uploaded device mirror).

    `rooms_pages` is the ISSUE's `[R, max_pages]` room→pages view (host
    debug/audit walks); the tick itself indirects through the inverse
    maps, which is what a static-shape gather wants:
    """

    rooms_pages: jax.Array  # [R, MT*MS] int32 — room's grid, -1 empty
    tmembers: jax.Array     # [P, MT] int32 — same-(room, sp) pages by tp
    pg_room: jax.Array      # [P] int32 — owning room (-1 free)
    pg_tp: jax.Array        # [P] int32 — track-page index within room
    pg_sp: jax.Array        # [P] int32 — sub-page index within room


def init_table(dims: PagedDims) -> PageTable:
    P = dims.pool_pages
    return PageTable(
        rooms_pages=jnp.full(
            (dims.rooms, dims.max_tpages * dims.max_spages), -1, jnp.int32
        ),
        tmembers=jnp.full((P, dims.max_tpages), -1, jnp.int32),
        pg_room=jnp.full((P,), -1, jnp.int32),
        pg_tp=jnp.full((P,), -1, jnp.int32),
        pg_sp=jnp.full((P,), -1, jnp.int32),
    )


# ---------------------------------------------------------------------------
# The paged tick
# ---------------------------------------------------------------------------


@device_entry("paged.paged_plane_tick")
def paged_plane_tick(
    state: PlaneState,
    inp: TickInputs,
    table: PageTable,
    audio_params: audio.AudioLevelParams = audio.AudioLevelParams(),
    bwe_params: bwe.BWEParams = bwe.BWEParams(),
    red_enabled: bool = True,
):
    """One tick over the page pool; same three phases as
    `media_plane_tick` with pages as the batch axis and the two genuine
    cross-page couplings routed through `tmembers` gathers (module doc).
    State/inputs are at `dims.pooled()`; jit with `state` donated.
    """
    L = MAX_LAYERS
    P, MT = table.tmembers.shape
    TP = state.meta.is_video.shape[1]
    SP = state.ctrl.subscribed.shape[2]
    mem = jnp.clip(table.tmembers, 0, P - 1)      # [P, MT]
    mvalid = table.tmembers >= 0                  # [P, MT]

    # ---- phase 0: forward decision, pages batched ----------------------
    # Per-(track, pkt, sub)-element — page-local by construction. Free
    # pages have init ctrl (subscribed=False) → no sends.
    base = (
        state.ctrl.subscribed
        & ~state.ctrl.sub_muted
        & (state.meta.published & ~state.meta.pub_muted)[:, :, None]
    )
    (sel_state, send_bits, drop_bits, switch_bits, need_kf,
     pkts_sent, sent_bytes, fwd_packets, fwd_bytes) = selector.decide_rooms(
        state.sel, state.meta.is_svc, state.meta.is_video, base,
        inp.layer, inp.temporal, inp.keyframe, inp.layer_sync,
        inp.end_frame, inp.valid, inp.size,
        wire_overhead=pacer.WIRE_OVERHEAD_BYTES,
    )

    # Cross-page coupling #1: a subscriber's true send totals span every
    # track page of its room. Gather-sum over tmembers — the (track,
    # pkt) blocks are disjoint, so the int sums are exactly the dense
    # per-sub sums; every page of the same (room, sp) column computes
    # the same value, keeping the tp-duplicated BWE/pacer state in sync.
    def gsum(x):  # [P, SP] int32 → [P, SP]
        return jnp.sum(jnp.where(mvalid[:, :, None], x[mem], 0), axis=1)

    pkts_sent_g = gsum(pkts_sent)
    sent_bytes_g = gsum(sent_bytes)

    # ---- phase 1: per-page core (vmapped dense room tick) --------------
    def tick_one(st, i, sb, db, wb, nk, ps, sby, fp, fby):
        return plane._room_tick(st, i, sb, db, wb, nk, ps, sby, fp, fby,
                                audio_params, bwe_params, red_enabled)

    inp_axes = TickInputs(**{f: 0 for f in TickInputs._fields})._replace(
        tick_ms=None, roll_quality=None
    )
    new_state, outputs, bitrates = jax.vmap(
        tick_one, in_axes=(0, inp_axes, 0, 0, 0, 0, 0, 0, 0, 0)
    )(state, inp, send_bits, drop_bits, switch_bits, need_kf,
      pkts_sent_g, sent_bytes_g, fwd_packets, fwd_bytes)

    # ---- phase 2: allocation with the room's FULL track axis -----------
    # Cross-page coupling #2: the budget algebra ranks layers across all
    # of a room's tracks. Each page gathers its room's MT·TP (== logical
    # T) track entries through tmembers; rows the room never allocated
    # get the dense-init fill values (bitrates 0, unsubscribed, caps at
    # init), so the operand set is bit-identical to the dense plane's.
    def gtrack(x, fill):  # [P, TP, ...] per-track-page → [P, MT, TP, ...]
        g = x[mem]
        m = mvalid.reshape((P, MT) + (1,) * (g.ndim - 2))
        return jnp.where(m, g, fill)

    def to_st(x):  # [P, MT, TP, SP] → [P, SP, MT*TP]
        return x.transpose(0, 3, 1, 2).reshape(P, SP, MT * TP)

    bit_g = gtrack(bitrates, 0.0).reshape(P, MT * TP, 4, 4)
    sub_g = to_st(gtrack(state.ctrl.subscribed, False))
    mut_g = to_st(gtrack(state.ctrl.sub_muted, False))
    msp_g = to_st(gtrack(state.ctrl.max_spatial, L - 1))
    mtp_g = to_st(gtrack(state.ctrl.max_temporal, 3))
    video_active = (
        state.meta.is_video & state.meta.published & ~state.meta.pub_muted
    )
    va_g = gtrack(video_active, False).reshape(P, MT * TP)
    alloc_muted = ~(sub_g & va_g[:, None, :] & ~mut_g)        # [P, SP, MT*TP]
    target_full, _used, deficient = allocation.allocate_budget_rooms(
        bit_g, msp_g, mtp_g, alloc_muted, outputs.committed_bps
    )                                                          # [P, SP, MT*TP]
    # Keep only this page's own tracks: every (tp, sp) block is computed
    # by exactly one page, so the logical [R, S, T] targets reassemble
    # from the pool without duplication.
    tgt4 = target_full.reshape(P, SP, MT, TP)
    own_tp = jnp.clip(table.pg_tp, 0, MT - 1)
    tgt_own = jnp.take_along_axis(
        tgt4, own_tp[:, None, None, None], axis=2
    )[:, :, 0, :]                                              # [P, SP, TP]
    tgt_ts = tgt_own.transpose(0, 2, 1)                        # [P, TP, SP]
    sel_state = selector.set_target(
        sel_state,
        jnp.clip(allocation.spatial_of(tgt_ts), -1, L - 1),
        allocation.temporal_of(tgt_ts),
    )
    any_deficient = jnp.any(deficient, axis=-1)                # [P, SP]
    sub_q = jnp.where(
        outputs.congested,
        quality.QUALITY_POOR,
        jnp.where(any_deficient, quality.QUALITY_GOOD,
                  quality.QUALITY_EXCELLENT),
    ).astype(jnp.int32)
    new_state = new_state._replace(sel=sel_state)
    outputs = outputs._replace(
        target_layers=tgt_own,
        deficient=any_deficient,
        sub_quality=sub_q,
    )
    # Freeze unmapped pages: zero inputs alone do NOT make a free page a
    # fixed point (pacer tokens, BWE sample age, and tracker windows all
    # advance per tick — unbounded counter drift), so pin dead rows to
    # their pre-tick values. This makes the module invariant — a free
    # page always holds pristine init state — a property of the tick
    # itself rather than of reinit-on-free alone, and it is the contract
    # the live-extent path relies on to skip dead pages entirely.
    live = table.pg_room >= 0                                      # [P]

    def _freeze(n, o):
        return jnp.where(live.reshape((P,) + (1,) * (n.ndim - 1)), n, o)

    new_state = jax.tree.map(_freeze, new_state, state)
    return new_state, outputs


# ---------------------------------------------------------------------------
# Live-extent fused tick: pay compute only for mapped pages.
#
# The stock pooled tick above computes every pool row and masks the dead
# ones. This variant takes the LIVE page extents as explicit operands —
# `live_rows [NL]` (pool ids of mapped pages, host-derived from the same
# device-table mirror the upload pinned, padded to a pow2 bucket by
# repeating a LIVE row) and `live_inv [P]` (pool id → compact index,
# 0 for dead rows, only ever read masked) — and runs every phase over
# the compact [NL] batch:
#
#   phase 0  ops/paged_kernel.decide_pages — one Pallas grid step per
#            live page (the page table is the scalar-prefetch operand;
#            dead pages are never *scheduled*, not merely masked), fusing
#            the selector algebra, egress bit packing, send sums, and the
#            [5,T,K,L] stats/tracker routing selects into one pass.
#   phase 1  the vmapped dense room core over [NL] rows, with the
#            kernel's routed stats passed through (`routed_stats`).
#   phase 2  the cross-track allocation over [NL] rows; a live page's
#            tmembers only ever reference live pages, so the gather
#            stays inside the compact batch via `live_inv`.
#
# Dead rows: state is untouched (the stock tick's freeze makes pristine
# init a fixed point) and outputs are one shared constant — a 1-page
# representative free page ticked in-trace from the init template, so
# traced scalars (tick_ms, roll_quality) flow into it and the result is
# bit-identical to what the stock tick computes for every dead row.
# ---------------------------------------------------------------------------


@device_entry("paged.dead_page_outputs")
def dead_page_outputs(
    MT: int, TP: int, K: int, SP: int,
    inp: TickInputs,
    audio_params: audio.AudioLevelParams = audio.AudioLevelParams(),
    bwe_params: bwe.BWEParams = bwe.BWEParams(),
    red_enabled: bool = True,
) -> TickOutputs:
    """TickOutputs of ONE free page under this tick's scalar inputs.

    Free pages hold pristine init state (enforced by the tick's freeze)
    and zero inputs, so every dead row's outputs equal this constant.
    Computed in-trace on a 1-page pool with the SAME MT (the phase-2
    gather width) so the operand set matches a dead row bit-for-bit.
    """
    rep_dims = PagedDims(
        rooms=1, tracks=MT * TP, pkts=K, subs=SP,
        tpage=TP, spage=SP, pool_pages=1,
    )
    rep_state = page_init_template(rep_dims)

    def z(a):
        return jnp.zeros((1,) + a.shape[1:], a.dtype)

    rep_inp = TickInputs(**{
        f: (getattr(inp, f) if f in ("tick_ms", "roll_quality")
            else z(getattr(inp, f)))
        for f in TickInputs._fields
    })
    _, rep_out = paged_plane_tick(
        rep_state, rep_inp, init_table(rep_dims),
        audio_params, bwe_params, red_enabled=red_enabled,
    )
    return rep_out


def broadcast_dead_outputs(rep_out: TickOutputs, P: int) -> TickOutputs:
    """Tile the representative free page's outputs to the full pool."""
    return jax.tree.map(
        lambda r: jnp.broadcast_to(r, (P,) + r.shape[1:]), rep_out
    )


@device_entry("paged.paged_plane_tick_live")
def paged_plane_tick_live(
    state: PlaneState,
    inp: TickInputs,
    table: PageTable,
    live_rows: jax.Array,   # [NL] int32 pool ids, pow2-padded with live dups
    live_inv: jax.Array,    # [P] int32 pool id → compact index (dead → 0)
    decide,                 # ops/paged_kernel.LiveDecide (compact phase 0)
    audio_params: audio.AudioLevelParams = audio.AudioLevelParams(),
    bwe_params: bwe.BWEParams = bwe.BWEParams(),
    red_enabled: bool = True,
):
    """Phases 1–2 of the live-extent tick over the compact [NL] batch,
    plus the scatter back to pool shape. `decide` is phase 0's output
    (ops/paged_kernel.decide_pages). Requires NL >= 1 — the all-dead
    pool is the caller's trivial case (state unchanged, dead fill).

    Bit-parity with `paged_plane_tick`: every op here is the stock op
    over a gathered row subset — int algebra is order-independent and
    the float chains are per-row identical across batch shapes — and
    padded duplicate rows scatter identical values.
    """
    L = MAX_LAYERS
    P, MT = table.tmembers.shape
    TP = state.meta.is_video.shape[1]
    SP = state.ctrl.subscribed.shape[2]
    NL = live_rows.shape[0]

    tm_c = table.tmembers[live_rows]                  # [NL, MT]
    mvalid = tm_c >= 0
    # A live page's valid tmembers always name live pages, so the
    # cross-page gathers stay inside the compact batch.
    mem = live_inv[jnp.clip(tm_c, 0, P - 1)]          # [NL, MT]

    # Cross-page coupling #1 (see paged_plane_tick): per-sub send totals
    # across the room's track pages, now over compact rows.
    def gsum(x):  # [NL, SP] int32 → [NL, SP]
        return jnp.sum(jnp.where(mvalid[:, :, None], x[mem], 0), axis=1)

    pkts_sent_g = gsum(decide.pkts_sent)
    sent_bytes_g = gsum(decide.sent_bytes)

    state_c = jax.tree.map(lambda a: a[live_rows], state)
    inp_c = inp._replace(**{
        f: getattr(inp, f)[live_rows]
        for f in TickInputs._fields if f not in ("tick_ms", "roll_quality")
    })

    # ---- phase 1: per-page core over live rows only --------------------
    inp_axes = TickInputs(**{f: 0 for f in TickInputs._fields})._replace(
        tick_ms=None, roll_quality=None
    )

    def tick_one(st, i, sb, db, wb, nk, ps, sby, fp, fby, rs):
        return plane._room_tick(st, i, sb, db, wb, nk, ps, sby, fp, fby,
                                audio_params, bwe_params, red_enabled,
                                routed_stats=rs)

    rs = (decide.st, decide.tr) if decide.st is not None else None
    rs_axes = (0, 0) if rs is not None else None
    new_c, outputs_c, bitrates = jax.vmap(
        tick_one, in_axes=(0, inp_axes, 0, 0, 0, 0, 0, 0, 0, 0, rs_axes)
    )(state_c, inp_c, decide.send_bits, decide.drop_bits,
      decide.switch_bits, decide.need_kf, pkts_sent_g, sent_bytes_g,
      decide.fwd_packets, decide.fwd_bytes, rs)

    # ---- phase 2: allocation with the room's FULL track axis -----------
    # The stock phase 2 verbatim, with the tmembers gather routed through
    # live_inv so it reads compact rows.
    def gtrack(x, fill):  # [NL, TP, ...] → [NL, MT, TP, ...]
        g = x[mem]
        m = mvalid.reshape((NL, MT) + (1,) * (g.ndim - 2))
        return jnp.where(m, g, fill)

    def to_st(x):  # [NL, MT, TP, SP] → [NL, SP, MT*TP]
        return x.transpose(0, 3, 1, 2).reshape(NL, SP, MT * TP)

    bit_g = gtrack(bitrates, 0.0).reshape(NL, MT * TP, 4, 4)
    sub_g = to_st(gtrack(state_c.ctrl.subscribed, False))
    mut_g = to_st(gtrack(state_c.ctrl.sub_muted, False))
    msp_g = to_st(gtrack(state_c.ctrl.max_spatial, L - 1))
    mtp_g = to_st(gtrack(state_c.ctrl.max_temporal, 3))
    video_active = (
        state_c.meta.is_video & state_c.meta.published
        & ~state_c.meta.pub_muted
    )
    va_g = gtrack(video_active, False).reshape(NL, MT * TP)
    alloc_muted = ~(sub_g & va_g[:, None, :] & ~mut_g)      # [NL, SP, MT*TP]
    target_full, _used, deficient = allocation.allocate_budget_rooms(
        bit_g, msp_g, mtp_g, alloc_muted, outputs_c.committed_bps
    )
    tgt4 = target_full.reshape(NL, SP, MT, TP)
    own_tp = jnp.clip(table.pg_tp[live_rows], 0, MT - 1)
    tgt_own = jnp.take_along_axis(
        tgt4, own_tp[:, None, None, None], axis=2
    )[:, :, 0, :]                                           # [NL, SP, TP]
    tgt_ts = tgt_own.transpose(0, 2, 1)                     # [NL, TP, SP]
    sel_state = selector.set_target(
        decide.sel,
        jnp.clip(allocation.spatial_of(tgt_ts), -1, L - 1),
        allocation.temporal_of(tgt_ts),
    )
    any_deficient = jnp.any(deficient, axis=-1)             # [NL, SP]
    sub_q = jnp.where(
        outputs_c.congested,
        quality.QUALITY_POOR,
        jnp.where(any_deficient, quality.QUALITY_GOOD,
                  quality.QUALITY_EXCELLENT),
    ).astype(jnp.int32)
    new_c = new_c._replace(sel=sel_state)
    outputs_c = outputs_c._replace(
        target_layers=tgt_own,
        deficient=any_deficient,
        sub_quality=sub_q,
    )

    # ---- scatter back to pool shape ------------------------------------
    # Dead state rows are untouched (frozen at pristine init by
    # contract); dead output rows get the shared representative fill.
    # Padded duplicate live rows scatter identical values.
    new_state = jax.tree.map(
        lambda full, c: full.at[live_rows].set(c), state, new_c
    )
    rep_out = dead_page_outputs(
        MT, TP, inp.sn.shape[2], SP, inp,
        audio_params, bwe_params, red_enabled,
    )
    outputs = jax.tree.map(
        lambda r, c: jnp.broadcast_to(
            r, (P,) + r.shape[1:]
        ).at[live_rows].set(c),
        rep_out, outputs_c,
    )
    return new_state, outputs


@device_entry("paged.paged_plane_tick_fused")
def paged_plane_tick_fused(
    state: PlaneState,
    inp: TickInputs,
    table: PageTable,
    live_rows,
    live_inv,
    audio_params: audio.AudioLevelParams = audio.AudioLevelParams(),
    bwe_params: bwe.BWEParams = bwe.BWEParams(),
    red_enabled: bool = True,
    use_pallas: bool | None = None,
    interpret: bool = False,
):
    """The whole live-extent tick in one trace: phase-0 kernel + live
    phases 1–2 + scatter. The runtime splits phase 0 into its own
    dispatch for span timing; tests and bench use this entry."""
    from livekit_server_tpu.ops import paged_kernel

    live_rows = jnp.asarray(live_rows, jnp.int32)
    live_inv = jnp.asarray(live_inv, jnp.int32)
    if live_rows.shape[0] == 0:
        TP = state.meta.is_video.shape[1]
        SP = state.ctrl.subscribed.shape[2]
        P, MT = table.tmembers.shape
        rep = dead_page_outputs(
            MT, TP, inp.sn.shape[2], SP, inp,
            audio_params, bwe_params, red_enabled,
        )
        return state, broadcast_dead_outputs(rep, P)
    base = (
        state.ctrl.subscribed
        & ~state.ctrl.sub_muted
        & (state.meta.published & ~state.meta.pub_muted)[:, :, None]
    )
    dec = paged_kernel.decide_pages(
        state.sel, state.meta.is_svc, state.meta.is_video, base, inp,
        live_rows, wire_overhead=pacer.WIRE_OVERHEAD_BYTES,
        use_pallas=use_pallas, interpret=interpret,
    )
    return paged_plane_tick_live(
        state, inp, table, live_rows, live_inv, dec,
        audio_params, bwe_params, red_enabled,
    )


# ---------------------------------------------------------------------------
# Page-table delta lane (device side) — the page analog of
# pack_ctrl_rows/apply_ctrl_delta: alloc/free/grow/compact events upload
# O(dirty pages) table rows, never the whole table.
# ---------------------------------------------------------------------------


def pack_table_delta(pager, delta, pad_pages_to=None, pad_rooms_to=None):
    """Host half: gather the table rows dirtied by a drained PageDelta
    from the pager's canonical numpy mirrors. Dirty pages = fresh +
    freed + both ends of every move + every current page of a dirty
    room (tmembers of ALL of a room's pages change when its grid grows).
    Padding repeats row 0 (identical values → deterministic scatter) so
    the device applier compiles per pow2 bucket."""
    pages: set[int] = set(int(p) for p in delta.fresh_pages)
    pages.update(int(p) for p in delta.freed_pages)
    for src, dst in delta.moves:
        pages.add(int(src))
        pages.add(int(dst))
    for r in delta.rooms:
        pages.update(int(p) for p in pager.pages_of_room(int(r)))
    page_rows = np.asarray(sorted(pages), np.int32)
    room_rows = np.asarray(delta.rooms, np.int32)

    def pad(rows, to):
        if to is not None and 0 < len(rows) < to:
            rows = np.concatenate([rows, np.repeat(rows[:1], to - len(rows))])
        return rows

    page_rows = pad(page_rows, pad_pages_to)
    room_rows = pad(room_rows, pad_rooms_to)
    return (
        page_rows,
        pager.tmembers[page_rows],
        pager.pg_room[page_rows],
        pager.pg_tp[page_rows],
        pager.pg_sp[page_rows],
        room_rows,
        pager.rooms_pages[room_rows],
    )


@device_entry("paged.apply_table_delta")
def apply_table_delta(
    table: PageTable,
    page_rows, tmember_rows, pg_room_rows, pg_tp_rows, pg_sp_rows,
    room_rows, rooms_pages_rows,
) -> PageTable:
    """Device half (traced; jit with `table` donated): scatter the
    dirtied rows into the device table."""
    return PageTable(
        rooms_pages=table.rooms_pages.at[room_rows].set(rooms_pages_rows),
        tmembers=table.tmembers.at[page_rows].set(tmember_rows),
        pg_room=table.pg_room.at[page_rows].set(pg_room_rows),
        pg_tp=table.pg_tp.at[page_rows].set(pg_tp_rows),
        pg_sp=table.pg_sp.at[page_rows].set(pg_sp_rows),
    )


@device_entry("paged.page_init_template")
def page_init_template(dims: PagedDims) -> PlaneState:
    """A single init page ([1, TP, K, SP] PlaneState) — the scatter
    source for fresh/freed page re-init and the fill for unmapped
    regions in pooled→logical translation."""
    return plane.init_state(PlaneDims(1, dims.tpage, dims.pkts, dims.spage))


@device_entry("paged.reinit_pages")
def reinit_pages(state: PlaneState, rows, template: PlaneState) -> PlaneState:
    """Device side (traced): reset `rows` to pristine init state — run
    for freshly allocated pages (a new room must not inherit the prior
    tenant's cursors) AND freed pages (stale state must stop computing).
    Duplicate rows are fine (identical values)."""
    n = rows.shape[0]

    def f(leaf, tleaf):
        return leaf.at[rows].set(
            jnp.broadcast_to(tleaf, (n,) + tleaf.shape[1:]).astype(leaf.dtype)
        )

    return jax.tree.map(f, state, template)


@device_entry("paged.move_state_rows")
def move_state_rows(state: PlaneState, src, dst) -> PlaneState:
    """Device side (traced): replay compaction relocations as page-row
    copies. Gather-then-scatter on the functional pre-move state, so
    overlapping src/dst sets are safe; dst rows are unique by
    construction (pad by repeating move 0)."""

    def f(leaf):
        return leaf.at[dst].set(leaf[src])

    return jax.tree.map(f, state)


# ---------------------------------------------------------------------------
# Host-side layout translation: pooled ↔ logical (numpy).
#
# Leaf-kind table — every PlaneState leaf is one of:
#   "track":     [R, T·m, *tail]  (stats/tracker rows are t-major, so a
#                track page's m rows are one contiguous block)
#   "sub":       [R, S, *tail]
#   "track_sub": [R, T, S, *tail]
# and the pooled counterpart replaces (R, T, S) with (P, TP, SP).
# ---------------------------------------------------------------------------

_K_TRACK, _K_SUB, _K_TS = "track", "sub", "track_sub"


def _kind_tree(template: PlaneState) -> PlaneState:
    def const(tree, kind):
        return jax.tree.map(lambda _: kind, tree)

    return PlaneState(
        meta=const(template.meta, _K_TRACK),
        ctrl=const(template.ctrl, _K_TS),
        stats=const(template.stats, _K_TRACK),
        audio_state=const(template.audio_state, _K_TRACK),
        sel=const(template.sel, _K_TS),
        bwe_state=const(template.bwe_state, _K_SUB),
        delay_bwe=const(template.delay_bwe, _K_SUB),
        tracker=const(template.tracker, _K_TRACK),
        pacer_state=const(template.pacer_state, _K_SUB),
        red_state=const(template.red_state, _K_TRACK),
        temporal_bytes=_K_TRACK,
    )


class LayoutXlate:
    """Pooled ↔ logical translation for one page-table snapshot.

    Built from the pager's numpy mirrors; cache per pager epoch (the
    index arrays are the only state). Reads follow duplicate-everywhere
    /read-from-one: track kinds from sp==0 pages, sub kinds from tp==0
    pages, track_sub kinds from every page (each block is unique).
    Writes go to ALL of a room's pages, re-establishing the duplication
    invariant — which is exactly what restore and row repair need.
    """

    def __init__(self, dims: PagedDims, pg_room, pg_tp, pg_sp):
        self.dims = dims
        self.pg_room = np.asarray(pg_room, np.int64)
        self.pg_tp = np.asarray(pg_tp, np.int64)
        self.pg_sp = np.asarray(pg_sp, np.int64)
        self.occ = self.pg_room >= 0
        self.sp0 = self.occ & (self.pg_sp == 0)
        self.tp0 = self.occ & (self.pg_tp == 0)

    # -- generic state trees ---------------------------------------------

    def state_to_logical(self, pooled_tree, fill_tree):
        """Pooled PlaneState (numpy-able) → logical PlaneState of numpy
        arrays; unmapped regions come from `fill_tree` (the logical init
        state), which is what makes checkpoints layout-independent."""
        kinds = _kind_tree(fill_tree)
        return jax.tree.map(self._leaf_to_logical, kinds, pooled_tree, fill_tree)

    def state_to_pooled(self, logical_tree, pooled_init_tree):
        """Logical PlaneState → pooled PlaneState of numpy arrays; free
        pages keep `pooled_init_tree` values. Writes every page of every
        room (the duplication invariant holds by construction)."""
        kinds = _kind_tree(logical_tree)
        return jax.tree.map(self._leaf_to_pooled, kinds, logical_tree,
                            pooled_init_tree)

    def _views(self, kind, logical, pooled):
        d = self.dims
        R, T, S, P = d.rooms, d.tracks, d.subs, d.pool_pages
        MT, TP, MS, SP = d.max_tpages, d.tpage, d.max_spages, d.spage
        if kind == _K_TRACK:
            w = logical.size // (R * T)
            return (logical.reshape(R, MT, TP, w), pooled.reshape(P, TP, w))
        if kind == _K_SUB:
            w = logical.size // (R * S)
            return (logical.reshape(R, MS, SP, w), pooled.reshape(P, SP, w))
        w = logical.size // (R * T * S)
        return (
            logical.reshape(R, MT, TP, MS, SP, w),
            pooled.reshape(P, TP, SP, w),
        )

    def _leaf_to_logical(self, kind, pl, fill):
        pl = np.ascontiguousarray(np.asarray(pl))
        out = np.array(np.asarray(fill), copy=True)
        lv, pv = self._views(kind, out, pl)
        if kind == _K_TRACK:
            sel = self.sp0
            lv[self.pg_room[sel], self.pg_tp[sel]] = pv[sel]
        elif kind == _K_SUB:
            sel = self.tp0
            lv[self.pg_room[sel], self.pg_sp[sel]] = pv[sel]
        else:
            sel = self.occ
            lv[self.pg_room[sel], self.pg_tp[sel], :, self.pg_sp[sel]] = pv[sel]
        return out

    def _leaf_to_pooled(self, kind, lg, pooled_init):
        lg = np.ascontiguousarray(np.asarray(lg))
        out = np.array(np.asarray(pooled_init), copy=True)
        lv, pv = self._views(kind, lg, out)
        sel = self.occ
        if kind == _K_TRACK:
            pv[sel] = lv[self.pg_room[sel], self.pg_tp[sel]]
        elif kind == _K_SUB:
            pv[sel] = lv[self.pg_room[sel], self.pg_sp[sel]]
        else:
            pv[sel] = lv[self.pg_room[sel], self.pg_tp[sel], :, self.pg_sp[sel]]
        return out

    # -- tick I/O --------------------------------------------------------

    def stage_inputs(self, pkt, fb, tf):
        """Packed LOGICAL tick inputs → packed POOLED inputs, duplicating
        per the module-doc staging rule: a track page's packets go to
        every sp-duplicate (the formula only reads pg_tp) and a sub
        page's feedback to every tp-duplicate. Free pages read zeros."""
        d = self.dims
        R, MT, TP = d.rooms, d.max_tpages, d.tpage
        MS, SP, K = d.max_spages, d.spage, d.pkts
        roomc = np.where(self.occ, self.pg_room, 0)
        tpc = np.where(self.occ, self.pg_tp, 0)
        spc = np.where(self.occ, self.pg_sp, 0)
        F = pkt.shape[0]
        pkt_p = pkt.reshape(F, R, MT, TP, K)[:, roomc, tpc]
        pkt_p = np.where(self.occ[None, :, None, None], pkt_p, 0)
        fb_p = fb.reshape(fb.shape[0], R, MS, SP)[:, roomc, spc]
        fb_p = np.where(self.occ[None, :, None], fb_p, 0.0)
        tf_p = tf.reshape(tf.shape[0], R, MT, TP)[:, roomc, tpc]
        tf_p = np.where(self.occ[None, :, None], tf_p, 0.0)
        return pkt_p, fb_p, tf_p

    def outputs_to_logical(self, out: TickOutputs) -> TickOutputs:
        """Pooled TickOutputs (numpy) → logical TickOutputs. Bit masks
        re-pack into the logical ⌈S/32⌉ words (a sub page never
        straddles a word: spage | 32); per-room counters sum over the
        room's pages; speakers merge per room (exact — see
        merge_speakers)."""
        d = self.dims
        R, T, K, S = d.logical
        TP, SP, MT = d.tpage, d.spage, d.max_tpages
        L = MAX_LAYERS
        W = mask_words(S)
        rooms = self.pg_room[self.occ]
        tps = self.pg_tp[self.occ]
        sps = self.pg_sp[self.occ]

        def bits(pb):  # [P, TP, K, 1] → [R, T, K, W]
            lw = np.zeros(R * T * K * W, np.uint32)
            vals = np.asarray(pb)[self.occ][:, :, :, 0].astype(np.uint32)
            shift = ((sps * SP) % 32).astype(np.uint32)
            words = (sps * SP) // 32
            shifted = vals << shift[:, None, None]
            t_glob = tps[:, None] * TP + np.arange(TP)[None, :]      # [N, TP]
            flat_idx = (
                (rooms[:, None, None] * T + t_glob[:, :, None]) * K
                + np.arange(K)[None, None, :]
            ) * W + words[:, None, None]
            np.bitwise_or.at(lw, flat_idx, shifted)
            return lw.view(np.int32).reshape(R, T, K, W)

        def ts(x, fill=0):  # [P, TP, SP, ...] → [R, T, S, ...]
            x = np.asarray(x)
            lg = np.full((R, MT, TP, d.max_spages, SP) + x.shape[3:],
                         fill, x.dtype)
            lg[rooms, tps, :, sps] = x[self.occ]
            return lg.reshape((R, T, S) + x.shape[3:])

        def sub(x, fill=0):  # [P, SP, ...] → [R, S, ...]
            x = np.asarray(x)
            lg = np.full((R, d.max_spages, SP) + x.shape[2:], fill, x.dtype)
            s = self.tp0
            lg[self.pg_room[s], self.pg_sp[s]] = x[s]
            return lg.reshape((R, S) + x.shape[2:])

        def track(x, fill=0):  # [P, TP, ...] → [R, T, ...]
            x = np.asarray(x)
            lg = np.full((R, MT, TP) + x.shape[2:], fill, x.dtype)
            s = self.sp0
            lg[self.pg_room[s], self.pg_tp[s]] = x[s]
            return lg.reshape((R, T) + x.shape[2:])

        def room_sum(x):  # [P] → [R]
            lg = np.zeros(R, np.asarray(x).dtype)
            np.add.at(lg, rooms, np.asarray(x)[self.occ])
            return lg

        # target_layers: [P, SP, TP] own-track slices → [R, S, T]
        tgt = np.asarray(out.target_layers)
        tgt_lg = np.full((R, d.max_spages, SP, MT, TP), -1,
                         tgt.dtype)
        tgt_lg[rooms, sps, :, tps] = tgt[self.occ]
        tgt_lg = tgt_lg.reshape(R, S, T)

        spk_lv, spk_tr = self.merge_speakers(
            out.speaker_levels, out.speaker_tracks
        )
        red_k = np.asarray(out.red_sn).shape[2]
        return TickOutputs(
            send_bits=bits(out.send_bits),
            drop_bits=bits(out.drop_bits),
            switch_bits=bits(out.switch_bits),
            need_keyframe=ts(out.need_keyframe, False),
            speaker_levels=spk_lv,
            speaker_tracks=spk_tr,
            congested=sub(out.congested, False),
            target_layers=tgt_lg,
            fwd_packets=room_sum(out.fwd_packets),
            fwd_bytes=room_sum(out.fwd_bytes),
            track_mos=track(out.track_mos, 0.0),
            track_quality=track(out.track_quality, quality.QUALITY_LOST),
            sub_quality=sub(out.sub_quality, quality.QUALITY_LOST),
            layer_live=track(out.layer_live),
            layer_fps=track(out.layer_fps, 0.0),
            track_loss_pct=track(out.track_loss_pct, 0.0),
            track_jitter_ms=track(out.track_jitter_ms, 0.0),
            track_bps=track(out.track_bps, 0.0),
            committed_bps=sub(out.committed_bps, 0.0),
            pacer_allowed=sub(out.pacer_allowed, 0.0),
            deficient=sub(out.deficient, False),
            red_sn=(track(out.red_sn) if red_k
                    else np.zeros((R, T, 0, np.asarray(out.red_sn).shape[3]),
                                  np.int32)),
            red_off=(track(out.red_off) if red_k
                     else np.zeros((R, T, 0, np.asarray(out.red_off).shape[3]),
                                   np.int32)),
            red_ok=(track(out.red_ok).astype(bool) if red_k
                    else np.zeros((R, T, 0, np.asarray(out.red_ok).shape[3]),
                                  bool)),
        )

    def merge_speakers(self, levels_p, tracks_p):
        """Per-room merge of per-page top-k speaker rankings, EXACT vs
        the dense top-k: a page's top-min(3, TP) dominates every track
        it omits, so the union of page rankings contains the global
        top-3; stable argsort on -level reproduces lax.top_k's
        lowest-index tie-break (including the dense all-zero case, which
        yields tracks 0, 1, 2 at level 0)."""
        d = self.dims
        R, T, TP = d.rooms, d.tracks, d.tpage
        levels_p = np.asarray(levels_p)
        tracks_p = np.asarray(tracks_p)
        lv = np.zeros((R, T), np.float32)
        for p in np.nonzero(self.sp0)[0]:
            r, tp = self.pg_room[p], self.pg_tp[p]
            for i in range(levels_p.shape[1]):
                tr = tracks_p[p, i]
                if tr >= 0:
                    lv[r, tp * TP + tr] = levels_p[p, i]
        k = min(SPEAKER_TOP_K, T)
        order = np.argsort(-lv, axis=1, kind="stable")[:, :k]
        out_lv = np.take_along_axis(lv, order, axis=1).astype(np.float32)
        out_tr = order.astype(np.int32)
        if k < SPEAKER_TOP_K:
            pad = SPEAKER_TOP_K - k
            out_lv = np.pad(out_lv, ((0, 0), (0, pad)))
            out_tr = np.pad(out_tr, ((0, 0), (0, pad)), constant_values=-1)
        return out_lv, out_tr

    def sel_to_logical(self, sel_pooled, sel_fill):
        """Pooled SelectorState → logical (express-lane mirror): each
        leaf is track_sub kind."""
        return jax.tree.map(
            lambda pl, fl: self._leaf_to_logical(_K_TS, pl, fl),
            sel_pooled, sel_fill,
        )

    def page_mask_to_rooms(self, mask):
        """[P] per-page audit/violation mask → [R] per-room mask (OR of
        the room's pages) — the integrity monitor's map_audit_mask."""
        room_mask = np.zeros(self.dims.rooms, np.asarray(mask).dtype)
        np.bitwise_or.at(
            room_mask, self.pg_room[self.occ], np.asarray(mask)[self.occ]
        )
        return room_mask
