"""Synthetic RTP traffic for benchmarks and integration tests.

Reference parity: test/client/trackwriter.go — the reference's integration
tests drive the SFU with synthetic ivf/ogg/null-frame tracks written into
real Pion connections. Here the equivalent is a packet-*tensor* generator:
it synthesizes one tick's worth of plausible RTP field tensors (monotonic
SN/TS per stream, simulcast layer cycling, VP8 picture ids, RFC6464 audio
levels) directly in numpy, so benches and tests can drive
`media_plane_tick` without a network.

Deterministic given (seed, tick index): generation is pure numpy on host,
mirroring how the real runtime packs host-received UDP packets into the
ingest tensors (livekit_server_tpu.runtime.ingest).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from livekit_server_tpu.models import plane


class TrafficSpec(NamedTuple):
    """Which tracks exist and what they carry, per room (uniform rooms)."""

    video_tracks: int = 2      # simulcast VP8, 3 spatial layers
    audio_tracks: int = 2      # Opus w/ RFC6464 levels
    fps: int = 30
    tick_ms: int = 10
    video_kbps: int = 1500     # per track, summed over layers
    audio_kbps: int = 32
    svc: bool = False          # video tracks are SVC (VP9/AV1 DD path)
    # Per-subscriber channel estimate fed as BWE samples. 0 = auto: 1.25×
    # the full offered bitrate, so throughput configs measure an
    # UNCONGESTED channel (congestion behavior is exercised by tests and
    # by setting this explicitly).
    estimate_bps: float = 0.0


class TrafficState(NamedTuple):
    """Host-side per-(room, track) generator cursors."""

    sn: np.ndarray        # [R, T] uint16 cursor
    ts: np.ndarray        # [R, T] uint32 cursor
    pid: np.ndarray       # [R, T] VP8 picture id cursor
    tl0: np.ndarray       # [R, T]
    frame_phase: np.ndarray  # [R, T] ms since last frame start


def init_traffic(dims: plane.PlaneDims, spec: TrafficSpec, seed: int = 0) -> TrafficState:
    R, T = dims.rooms, dims.tracks
    rng = np.random.default_rng(seed)
    return TrafficState(
        sn=rng.integers(0, 1 << 16, (R, T)).astype(np.int64),
        ts=rng.integers(0, 1 << 31, (R, T)).astype(np.int64),
        pid=rng.integers(0, 1 << 14, (R, T)).astype(np.int64),
        tl0=rng.integers(0, 200, (R, T)).astype(np.int64),
        frame_phase=np.zeros((R, T), np.int64),
    )


def make_meta_ctrl(dims: plane.PlaneDims, spec: TrafficSpec):
    """TrackMeta / SubControl numpy tensors for a uniform fully-meshed node.

    Every room has `video_tracks` + `audio_tracks` published tracks and every
    subscriber subscribes to all of them (the reference's auto-subscribe
    default — room.go subscribeToExistingTracks).
    """
    R, T, _, S = dims
    nv = min(spec.video_tracks, T)
    used = min(nv + spec.audio_tracks, T)
    is_video = np.zeros((R, T), bool)
    is_video[:, :nv] = True
    published = np.zeros((R, T), bool)
    published[:, :used] = True
    meta = plane.TrackMeta(
        is_video=is_video,
        published=published,
        pub_muted=np.zeros((R, T), bool),
        is_svc=is_video.copy() if spec.svc else np.zeros((R, T), bool),
    )
    ctrl = plane.SubControl(
        subscribed=np.broadcast_to(published[:, :, None], (R, T, S)).copy(),
        sub_muted=np.zeros((R, T, S), bool),
        max_spatial=np.full((R, T, S), plane.MAX_LAYERS - 1, np.int32),
        max_temporal=np.full((R, T, S), 3, np.int32),
    )
    return meta, ctrl


def make_state(dims: plane.PlaneDims, spec: TrafficSpec) -> plane.PlaneState:
    """Device-ready PlaneState with this spec's tracks published and every
    subscriber subscribed (the standard bench/test/entry setup)."""
    import jax
    import jax.numpy as jnp

    meta, ctrl = make_meta_ctrl(dims, spec)
    state = plane.init_state(dims)
    return state._replace(
        meta=jax.tree.map(jnp.asarray, plane.TrackMeta(*meta)),
        ctrl=jax.tree.map(jnp.asarray, plane.SubControl(*ctrl)),
    )


def next_tick(
    state: TrafficState,
    dims: plane.PlaneDims,
    spec: TrafficSpec,
    tick_index: int,
    seed: int = 0,
) -> tuple[TrafficState, plane.TickInputs]:
    """Generate one tick of ingest tensors; pure host numpy."""
    R, T, K, S = dims
    rng = np.random.default_rng((seed << 20) ^ tick_index)
    nv = min(spec.video_tracks, T)
    used = min(nv + spec.audio_tracks, T)
    is_video = np.zeros((T,), bool)
    is_video[:nv] = True

    # Packets per tick per track: video ≈ bitrate/MTU, audio = one per 20 ms.
    v_pps = spec.video_kbps * 125 / 1200 / 1000 * spec.tick_ms  # pkts per tick
    a_pps = spec.tick_ms / 20.0
    want = np.where(is_video, v_pps, a_pps)
    want[used:] = 0.0
    counts = np.minimum(
        K, rng.poisson(np.broadcast_to(want, (R, T))).astype(np.int64)
    )
    k_idx = np.arange(K)
    valid = k_idx[None, None, :] < counts[:, :, None]  # [R, T, K]

    sn = (state.sn[:, :, None] + k_idx[None, None, :]) & 0xFFFF
    new_sn = (state.sn + counts) & 0xFFFF

    # Video: frame boundaries every 1000/fps ms; all packets in a tick share
    # a frame TS unless the frame rolls over mid-tick (coarse but plausible).
    frame_ms = max(1, 1000 // spec.fps)
    phase = state.frame_phase + spec.tick_ms
    new_frame = phase >= frame_ms
    phase = np.where(new_frame, phase % frame_ms, phase)
    ts_step_v = new_frame.astype(np.int64) * 90 * frame_ms
    ts_step_a = spec.tick_ms * 48  # 48 kHz Opus
    ts_step = np.where(is_video[None, :], ts_step_v, ts_step_a)
    new_ts = (state.ts + ts_step) & 0xFFFFFFFF
    ts = np.broadcast_to(new_ts[:, :, None], (R, T, K)).astype(np.int64)

    # Simulcast: packets cycle through spatial layers 0..2 weighted by size.
    layer = np.where(is_video[None, :, None], k_idx[None, None, :] % 3, 0)
    temporal = np.where(is_video[None, :, None], k_idx[None, None, :] % 2, 0)
    # Keyframe ticks mark the first packet of EVERY spatial layer (real
    # simulcast encoders key all layers together; the selector locks onto a
    # spatial layer only at a keyframe of that layer — simulcast.go:42).
    keyframe = np.logical_and(
        is_video[None, :, None],
        (tick_index % 100 == 0) & (k_idx[None, None, :] < 3),
    )
    begin_pic = np.logical_and(is_video[None, :, None], new_frame[:, :, None])
    layer_sync = keyframe | (begin_pic & (temporal == 0))

    # First packet of the new picture only (per spatial layer, one packet
    # carries begin_pic — layer == k for k < 3 under the k%3 cycling).
    begin_pic = begin_pic & (k_idx[None, None, :] == layer)

    pid_inc = new_frame.astype(np.int64)
    pid = (state.pid + pid_inc)[:, :, None] & 0x7FFF
    pid = np.broadcast_to(pid, (R, T, K))
    tl0 = (state.tl0 + pid_inc)[:, :, None] & 0xFF
    tl0 = np.broadcast_to(tl0, (R, T, K))

    mtu_v = 1200 + rng.integers(-400, 200, (R, T, K))
    size_a = rng.integers(60, 120, (R, T, K))
    size = np.where(is_video[None, :, None], mtu_v, size_a)

    # Audio levels: a rotating "speaker" per room is loud (~20 dBov), the
    # rest are quiet (~70) — exercises the active-speaker top-k.
    speaker = (tick_index // 50) % max(1, used - nv) + nv if used > nv else 0
    loud = np.full((R, T, K), 70, np.int64)
    loud[:, speaker, :] = 20 + rng.integers(-5, 5)
    audio_level = np.where(is_video[None, :, None], 127, loud)

    arrival = (ts + rng.integers(0, 90, (R, T, K))) & 0xFFFFFFFF

    est0 = spec.estimate_bps or 1.25 * 1000.0 * (
        spec.video_tracks * spec.video_kbps + spec.audio_tracks * spec.audio_kbps
    )
    estimate = rng.normal(est0, est0 * 0.05, (R, S)).clip(1e5)

    def full(x, dtype):
        return np.broadcast_to(x, (R, T, K)).astype(dtype)

    # Last generated packet of each track's tick is the frame end (coarse
    # marker-bit model; exact per-frame markers come from the wire parser).
    end_frame = valid & ~np.roll(valid, -1, axis=-1)
    end_frame[..., -1] = valid[..., -1]

    inp = plane.TickInputs(
        sn=full(sn, np.int32),
        ts=full(ts, np.int32),
        layer=full(layer, np.int32),
        temporal=full(temporal, np.int32),
        keyframe=full(keyframe, bool),
        layer_sync=full(layer_sync, bool),
        begin_pic=full(begin_pic | ~is_video[None, :, None], bool),
        end_frame=full(end_frame, bool),
        pid=full(pid, np.int32),
        tl0=full(tl0, np.int32),
        keyidx=np.zeros((R, T, K), np.int32),
        size=full(size, np.int32),
        frame_ms=full(np.where(is_video[None, :, None], 0, 20), np.int32),
        audio_level=full(audio_level, np.int32),
        arrival_rtp=full(arrival, np.int32),
        ts_jump=np.full((R, T, K), 3000, np.int32),
        valid=full(valid, bool),
        estimate=estimate.astype(np.float32),
        estimate_valid=np.ones((R, S), bool),
        nacks=np.zeros((R, S), np.float32),
        pub_rtt_ms=np.full((R, T), 50.0, np.float32),
        fb_delay_ms=np.zeros((R, S), np.float32),
        fb_recv_bps=np.zeros((R, S), np.float32),
        fb_valid=np.zeros((R, S), bool),
        fb_enabled=np.zeros((R, S), bool),
        sub_reset=np.zeros((R, S), bool),
        pad_num=np.zeros((R, S), np.int32),
        pad_track=np.full((R, S), -1, np.int32),
        tick_ms=np.int32(spec.tick_ms),
        roll_quality=np.int32(0),
    )
    new_state = TrafficState(
        sn=new_sn, ts=new_ts, pid=(state.pid + pid_inc) & 0x7FFF,
        tl0=(state.tl0 + pid_inc) & 0xFF, frame_phase=phase,
    )
    return new_state, inp
