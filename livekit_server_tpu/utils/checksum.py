"""Checksummed checkpoint framing — the single serialization codec for
every snapshot that leaves process memory (room handoff payloads,
failover KV checkpoints, supervisor restart seeds).

A restore path that scatters unverified bytes into DONATED device state
turns one flipped bit in a checkpoint into a silently-wrong media plane:
`restore_room` happily `.at[row].set()`s whatever deserializes. Every
serialized snapshot therefore rides inside a versioned frame:

    offset  size  field
    0       4     magic  b"LKCK"
    4       2     version (big-endian u16; readers reject unknown majors)
    6       2     flags   (reserved; must round-trip)
    8       8     payload length (big-endian u64)
    16      4     CRC32 of payload (zlib.crc32, big-endian u32)
    20      -     payload bytes

CRC32 is the strongest digest in the stdlib footprint this repo allows
(no xxhash wheel in the image); at checkpoint sizes (KBs..MBs) it
detects the single/multi-bit corruption class the bitflip fault model
injects. The graftcheck GC06 rule statically enforces that checkpoint-
bearing modules only serialize through this codec.

Verification failures raise ChecksumError; callers (supervisor,
RoomManager) fall back one checkpoint generation instead of committing
garbage — see runtime/supervisor.py and service/roommanager.py.
"""

from __future__ import annotations

import base64
import struct
import zlib

MAGIC = b"LKCK"
VERSION = 1
_HEADER = struct.Struct(">4sHHQI")
HEADER_SIZE = _HEADER.size  # 20 bytes


class ChecksumError(ValueError):
    """Frame failed verification (bad magic/version/length/CRC)."""


class CodecStats:
    """Process-wide codec counters, read at telemetry scrape time (the
    MessageChannel.total_dropped idiom)."""

    frames_encoded = 0
    frames_verified = 0
    verify_failures = 0


def encode_frame(payload: bytes, *, flags: int = 0) -> bytes:
    """Wrap serialized checkpoint bytes in the versioned+checksummed
    frame. The only sanctioned way to emit checkpoint bytes (GC06)."""
    CodecStats.frames_encoded += 1
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return _HEADER.pack(MAGIC, VERSION, flags, len(payload), crc) + payload


def decode_frame(frame: bytes) -> bytes:
    """Verify and strip the frame; raises ChecksumError on any mismatch
    BEFORE the caller deserializes (no np.load / scatter of bad bytes)."""
    if len(frame) < HEADER_SIZE:
        _fail(f"frame truncated: {len(frame)} bytes < {HEADER_SIZE} header")
    magic, version, _flags, length, crc = _HEADER.unpack(frame[:HEADER_SIZE])
    if magic != MAGIC:
        _fail(f"bad magic {magic!r}")
    if version != VERSION:
        _fail(f"unsupported frame version {version}")
    payload = frame[HEADER_SIZE:]
    if len(payload) != length:
        _fail(f"length mismatch: header says {length}, got {len(payload)}")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        _fail("CRC32 mismatch: checkpoint bytes corrupted")
    CodecStats.frames_verified += 1
    return payload


def encode_frame_b64(payload: bytes, *, flags: int = 0) -> str:
    """Framed payload as base64 text (the KV bus carries strings)."""
    return base64.b64encode(encode_frame(payload, flags=flags)).decode()


def decode_frame_b64(text: str) -> bytes:
    try:
        frame = base64.b64decode(text)
    except (ValueError, TypeError) as e:
        _fail(f"bad base64 framing: {e}")
    return decode_frame(frame)


def _fail(msg: str) -> None:
    CodecStats.verify_failures += 1
    raise ChecksumError(msg)
