"""Host-side utilities: IDs, time, logging, small data structures."""
