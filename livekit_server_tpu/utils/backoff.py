"""Uniform retry/timeout/backoff policy for every re-dial path.

One policy object replaces the ad-hoc sleep loops that used to live in
each caller (tcpbus._reconnect's bare exponential, relay's one-shot
bind): exponential backoff with full jitter (the AWS architecture-blog
shape — deterministic under a seeded rng for chaos tests), an attempt
cap, and a circuit breaker so a dependency that is hard-down stops
consuming the caller's event loop with futile dials.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass
from typing import Awaitable, Callable, TypeVar

T = TypeVar("T")


@dataclass(frozen=True)
class BackoffPolicy:
    """Exponential backoff with full jitter (default on).

    delay(n) ~ uniform(floor·cap, cap) with cap = min(base · mult^n,
    max_delay) — the AWS architecture-blog full-jitter shape, floored at
    `jitter_floor`·cap so a pathological draw cannot spin-dial at ~0 ms.
    Full jitter decorrelates a fleet of clients re-dialing the same dead
    bus after a regional cut: N clients draw independently across 90% of
    the cap instead of landing on the same deterministic beat and
    thundering the bus in synchronized waves. Pass a seeded
    `random.Random` for reproducible chaos drills (each simulated client
    gets its own seed; same seeds → byte-identical delay sequences).
    """

    base: float = 0.05
    max_delay: float = 5.0
    multiplier: float = 2.0
    max_attempts: int = 0        # 0 = unbounded
    jitter: bool = True
    jitter_floor: float = 0.1    # fraction of cap a draw can never go below

    def delay(self, attempt: int, rng: random.Random | None = None) -> float:
        cap = min(self.base * (self.multiplier ** attempt), self.max_delay)
        if not self.jitter:
            return cap
        r = rng.random() if rng is not None else random.random()
        return cap * (self.jitter_floor + (1.0 - self.jitter_floor) * r)

    def exhausted(self, attempt: int) -> bool:
        return bool(self.max_attempts) and attempt >= self.max_attempts


class CircuitBreaker:
    """Failure-rate trip switch shared by retry loops.

    closed → open after `threshold` consecutive failures; open rejects
    instantly (no dial, no sleep) until `cooldown_s` elapses, then one
    half-open probe is allowed through — success closes, failure re-opens.
    """

    def __init__(self, threshold: int = 8, cooldown_s: float = 10.0):
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.failures = 0
        self.opened_at = 0.0
        self.trips = 0

    @property
    def open(self) -> bool:
        if self.failures < self.threshold:
            return False
        return (time.monotonic() - self.opened_at) < self.cooldown_s

    def allow(self) -> bool:
        """True if a call may proceed (closed, or half-open probe)."""
        return not self.open

    def record_success(self) -> None:
        self.failures = 0

    def record_failure(self) -> None:
        self.failures += 1
        if self.failures == self.threshold:
            self.opened_at = time.monotonic()
            self.trips += 1
        elif self.failures > self.threshold:
            # Half-open probe failed: restart the cooldown window.
            self.opened_at = time.monotonic()


class CircuitOpen(ConnectionError):
    """Raised when the breaker rejects a call without attempting it."""


class RetryAborted(Exception):
    """Raised when `should_abort` turns true between attempts (e.g. the
    owning client was closed while its reconnect loop slept)."""


def _default_give_up(attempts: int, err: BaseException) -> None:
    from livekit_server_tpu.utils.logger import log

    log.warn(
        "retry_async giving up",
        attempts=attempts, error=type(err).__name__, detail=str(err),
    )


async def retry_async(
    fn: Callable[[], Awaitable[T]],
    policy: BackoffPolicy,
    *,
    retry_on: tuple[type[BaseException], ...] = (ConnectionError, OSError),
    timeout: float | None = None,
    breaker: CircuitBreaker | None = None,
    on_retry: Callable[[int, BaseException], None] | None = None,
    on_give_up: Callable[[int, BaseException], None] | None = None,
    wait_when_open: bool = False,
    should_abort: Callable[[], bool] | None = None,
    rng: random.Random | None = None,
) -> T:
    """Run `fn` under the policy: per-attempt `timeout`, backoff between
    attempts, breaker consulted before each. Raises the last error when
    attempts are exhausted, or CircuitOpen when the breaker rejects.

    `on_give_up(attempts, err)` fires once, just before the final raise
    at exhaustion (default: logs the attempt count — a silent give-up
    looks identical to a hang from the caller's side). `wait_when_open`
    turns a breaker rejection into a cooldown sleep instead of
    CircuitOpen — the shape a persistent reconnect loop wants.
    `should_abort` is polled before each attempt; True raises
    RetryAborted (e.g. the owning client was closed mid-backoff)."""
    attempt = 0
    while True:
        if should_abort is not None and should_abort():
            raise RetryAborted("aborted between retry attempts")
        if breaker is not None and not breaker.allow():
            if not wait_when_open:
                raise CircuitOpen("circuit breaker open")
            await asyncio.sleep(breaker.cooldown_s)
            continue
        try:
            if timeout is not None:
                result = await asyncio.wait_for(fn(), timeout)
            else:
                result = await fn()
        except retry_on + (asyncio.TimeoutError,) as e:  # noqa: PERF203
            if breaker is not None:
                breaker.record_failure()
            if policy.exhausted(attempt + 1):
                (on_give_up or _default_give_up)(attempt + 1, e)
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            await asyncio.sleep(policy.delay(attempt, rng))
            attempt += 1
            continue
        if breaker is not None:
            breaker.record_success()
        return result
