"""Uniform retry/timeout/backoff policy for every re-dial path.

One policy object replaces the ad-hoc sleep loops that used to live in
each caller (tcpbus._reconnect's bare exponential, relay's one-shot
bind): exponential backoff with full jitter (the AWS architecture-blog
shape — deterministic under a seeded rng for chaos tests), an attempt
cap, and a circuit breaker so a dependency that is hard-down stops
consuming the caller's event loop with futile dials.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass
from typing import Awaitable, Callable, TypeVar

T = TypeVar("T")


@dataclass(frozen=True)
class BackoffPolicy:
    """Exponential backoff with full jitter.

    delay(n) ~ uniform(0, min(base * mult^n, max_delay)) — full jitter
    decorrelates a fleet of clients re-dialing the same dead bus, where
    the old deterministic ladder had every node land on the same beat.
    """

    base: float = 0.05
    max_delay: float = 5.0
    multiplier: float = 2.0
    max_attempts: int = 0        # 0 = unbounded
    jitter: bool = True

    def delay(self, attempt: int, rng: random.Random | None = None) -> float:
        cap = min(self.base * (self.multiplier ** attempt), self.max_delay)
        if not self.jitter:
            return cap
        r = rng.random() if rng is not None else random.random()
        # Floor at half the ceiling: pure full-jitter can draw ~0 and spin.
        return cap * (0.5 + 0.5 * r)

    def exhausted(self, attempt: int) -> bool:
        return bool(self.max_attempts) and attempt >= self.max_attempts


class CircuitBreaker:
    """Failure-rate trip switch shared by retry loops.

    closed → open after `threshold` consecutive failures; open rejects
    instantly (no dial, no sleep) until `cooldown_s` elapses, then one
    half-open probe is allowed through — success closes, failure re-opens.
    """

    def __init__(self, threshold: int = 8, cooldown_s: float = 10.0):
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.failures = 0
        self.opened_at = 0.0
        self.trips = 0

    @property
    def open(self) -> bool:
        if self.failures < self.threshold:
            return False
        return (time.monotonic() - self.opened_at) < self.cooldown_s

    def allow(self) -> bool:
        """True if a call may proceed (closed, or half-open probe)."""
        return not self.open

    def record_success(self) -> None:
        self.failures = 0

    def record_failure(self) -> None:
        self.failures += 1
        if self.failures == self.threshold:
            self.opened_at = time.monotonic()
            self.trips += 1
        elif self.failures > self.threshold:
            # Half-open probe failed: restart the cooldown window.
            self.opened_at = time.monotonic()


class CircuitOpen(ConnectionError):
    """Raised when the breaker rejects a call without attempting it."""


async def retry_async(
    fn: Callable[[], Awaitable[T]],
    policy: BackoffPolicy,
    *,
    retry_on: tuple[type[BaseException], ...] = (ConnectionError, OSError),
    timeout: float | None = None,
    breaker: CircuitBreaker | None = None,
    on_retry: Callable[[int, BaseException], None] | None = None,
    rng: random.Random | None = None,
) -> T:
    """Run `fn` under the policy: per-attempt `timeout`, backoff between
    attempts, breaker consulted before each. Raises the last error when
    attempts are exhausted, or CircuitOpen when the breaker rejects."""
    attempt = 0
    while True:
        if breaker is not None and not breaker.allow():
            raise CircuitOpen("circuit breaker open")
        try:
            if timeout is not None:
                result = await asyncio.wait_for(fn(), timeout)
            else:
                result = await fn()
        except retry_on as e:  # noqa: PERF203 — retry loop by design
            if breaker is not None:
                breaker.record_failure()
            if policy.exhausted(attempt + 1):
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            await asyncio.sleep(policy.delay(attempt, rng))
            attempt += 1
            continue
        except asyncio.TimeoutError:
            if breaker is not None:
                breaker.record_failure()
            if policy.exhausted(attempt + 1):
                raise
            if on_retry is not None:
                on_retry(attempt, asyncio.TimeoutError())
            await asyncio.sleep(policy.delay(attempt, rng))
            attempt += 1
            continue
        if breaker is not None:
            breaker.record_success()
        return result
