"""Structured, scoped logging.

Reference parity: the livekit/protocol logger (zap-backed) the whole
reference codebase threads through — leveled, key-value structured, with
scoped child loggers carrying room/participant/track context (e.g.
rtc/room.go attaches "room"/"roomID" once and every log line under it
inherits the fields). Here: logfmt lines over stdlib logging, and
`with_fields()` returns a child logger with bound context.
"""

from __future__ import annotations

import logging
import sys
import time
from typing import Any

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}

_root = logging.getLogger("livekit")
_configured = False


def configure(level: str = "info", stream=None) -> None:
    """Install the logfmt handler (config.go LoggingConfig seat)."""
    global _configured
    _root.setLevel(_LEVELS.get(level.lower(), logging.INFO))
    _root.propagate = False
    for h in list(_root.handlers):
        _root.removeHandler(h)
    h = logging.StreamHandler(stream or sys.stderr)
    h.setFormatter(logging.Formatter("%(message)s"))
    _root.addHandler(h)
    _configured = True


def _fmt(v: Any) -> str:
    s = str(v)
    # Strip control characters first: identities/room names are client-
    # chosen, and a raw newline would forge log records (log injection).
    if any(ord(c) < 0x20 for c in s):
        s = "".join(c if ord(c) >= 0x20 else "\\x%02x" % ord(c) for c in s)
    if " " in s or '"' in s or "=" in s:
        s = '"' + s.replace('"', '\\"') + '"'
    return s


class Logger:
    """Bound-context logger (logger.Logger with Fields)."""

    __slots__ = ("fields",)

    def __init__(self, **fields: Any):
        self.fields = fields

    def with_fields(self, **fields: Any) -> "Logger":
        """Child logger inheriting + extending the bound fields (the
        room/participant-scoped loggers the reference creates once and
        passes down)."""
        merged = dict(self.fields)
        merged.update(fields)
        return Logger(**merged)

    def _emit(self, level: int, msg: str, kw: dict[str, Any]) -> None:
        if not _configured:
            configure()
        if not _root.isEnabledFor(level):
            return
        parts = [
            time.strftime("%Y-%m-%dT%H:%M:%S"),
            f"level={logging.getLevelName(level).lower()}",
            f"msg={_fmt(msg)}",
        ]
        for k, v in self.fields.items():
            parts.append(f"{k}={_fmt(v)}")
        for k, v in kw.items():
            parts.append(f"{k}={_fmt(v)}")
        _root.log(level, " ".join(parts))

    def debug(self, msg: str, **kw: Any) -> None:
        self._emit(logging.DEBUG, msg, kw)

    def info(self, msg: str, **kw: Any) -> None:
        self._emit(logging.INFO, msg, kw)

    def warn(self, msg: str, **kw: Any) -> None:
        self._emit(logging.WARNING, msg, kw)

    def error(self, msg: str, **kw: Any) -> None:
        self._emit(logging.ERROR, msg, kw)


log = Logger()
