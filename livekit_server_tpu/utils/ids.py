"""Typed object IDs.

Reference parity: livekit/protocol utils guid.go (RM_/PA_/TR_ prefixed GUIDs
used throughout pkg/service and pkg/rtc). Same surface, new implementation.
"""

from __future__ import annotations

import secrets
import string

_ALPHABET = string.ascii_letters + string.digits
_ID_LEN = 12

ROOM_PREFIX = "RM_"
PARTICIPANT_PREFIX = "PA_"
TRACK_PREFIX = "TR_"
API_KEY_PREFIX = "API"
NODE_PREFIX = "ND_"
CONNECTION_PREFIX = "CO_"
EGRESS_PREFIX = "EG_"
INGRESS_PREFIX = "IN_"
SIP_TRUNK_PREFIX = "ST_"
SIP_DISPATCH_RULE_PREFIX = "SDR_"
SIP_CALL_PREFIX = "SCL_"
AGENT_JOB_PREFIX = "AJ_"
AGENT_WORKER_PREFIX = "AW_"


def _rand(n: int = _ID_LEN) -> str:
    return "".join(secrets.choice(_ALPHABET) for _ in range(n))


def new_guid(prefix: str) -> str:
    return prefix + _rand()


def new_room_id() -> str:
    return new_guid(ROOM_PREFIX)


def new_participant_id() -> str:
    return new_guid(PARTICIPANT_PREFIX)


def new_track_id() -> str:
    return new_guid(TRACK_PREFIX)


def new_node_id() -> str:
    return new_guid(NODE_PREFIX)


def new_connection_id() -> str:
    return new_guid(CONNECTION_PREFIX)


def new_api_key() -> str:
    return API_KEY_PREFIX + _rand(11)


def new_api_secret() -> str:
    # 32 bytes of entropy, urlsafe — matches the reference's generate-keys
    # output shape (cmd/server/commands.go generate-keys).
    return secrets.token_urlsafe(32)
