"""JWT authentication: access tokens and grants.

Reference parity: livekit/protocol auth (JWT HS256 access tokens carrying
`video` grants) as enforced by pkg/service/auth.go:45-188 (middleware →
ClaimGrants in context; permission guards EnsureJoinPermission /
EnsureAdminPermission / …) and minted by cmd create-join-token.
"""

from livekit_server_tpu.auth.token import (
    AccessToken,
    ClaimGrants,
    TokenError,
    VideoGrant,
    ensure_admin_permission,
    ensure_create_permission,
    ensure_ingress_admin_permission,
    ensure_list_permission,
    ensure_record_permission,
    verify_token,
)

__all__ = [
    "AccessToken",
    "ClaimGrants",
    "TokenError",
    "VideoGrant",
    "ensure_admin_permission",
    "ensure_create_permission",
    "ensure_ingress_admin_permission",
    "ensure_list_permission",
    "ensure_record_permission",
    "verify_token",
]
