"""JWT authentication: access tokens and grants.

Reference parity: livekit/protocol auth (JWT HS256 access tokens carrying
`video` grants) as enforced by pkg/service/auth.go:45-188 (middleware →
ClaimGrants in context; permission guards EnsureJoinPermission /
EnsureAdminPermission / …) and minted by cmd create-join-token.
"""

from livekit_server_tpu.auth.token import (
    AccessToken,
    ClaimGrants,
    TokenError,
    VideoGrant,
    verify_token,
)

__all__ = ["AccessToken", "ClaimGrants", "TokenError", "VideoGrant", "verify_token"]
