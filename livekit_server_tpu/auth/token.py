"""HS256 JWT access tokens with video grants.

Reference parity: livekit/protocol auth package (AccessToken / VideoGrant /
ClaimGrants) used by the reference everywhere a request is authenticated:
pkg/service/auth.go:45-188 (HTTP middleware), rtcservice.go:106-194 (join
validation), roommanager.go:832-854 (refreshToken), turn.go long-term
credentials. Implemented on stdlib hmac/hashlib — same wire format as any
RFC 7519 HS256 JWT, no external jwt dependency.
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import hmac
import json
import time
from dataclasses import dataclass, field


class TokenError(Exception):
    pass


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64url(s: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


@dataclass
class VideoGrant:
    """The `video` claim (livekit/protocol auth/grants.go)."""

    room_create: bool = False
    room_join: bool = False
    room_list: bool = False
    room_record: bool = False
    room_admin: bool = False
    room: str = ""
    can_publish: bool | None = None
    can_subscribe: bool | None = None
    can_publish_data: bool | None = None
    can_publish_sources: list[str] = field(default_factory=list)
    can_update_own_metadata: bool | None = None
    hidden: bool = False
    recorder: bool = False
    agent: bool = False
    ingress_admin: bool = False

    def to_claim(self) -> dict:
        d = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v is None or v == "" or v == []:
                continue
            # Plain bool flags omit False; tri-state (None-default) fields
            # keep an explicit False — it means "deny", not "unset".
            if v is False and f.default is False:
                continue
            # proto JSON style: camelCase keys
            parts = f.name.split("_")
            d[parts[0] + "".join(p.title() for p in parts[1:])] = v
        return d

    @classmethod
    def from_claim(cls, d: dict) -> "VideoGrant":
        kw = {}
        for f in dataclasses.fields(cls):
            parts = f.name.split("_")
            camel = parts[0] + "".join(p.title() for p in parts[1:])
            if camel in d:
                kw[f.name] = d[camel]
        return cls(**kw)


@dataclass
class ClaimGrants:
    """Decoded token claims (auth/grants.go ClaimGrants)."""

    identity: str = ""
    name: str = ""
    video: VideoGrant = field(default_factory=VideoGrant)
    metadata: str = ""
    attributes: dict[str, str] = field(default_factory=dict)
    sha256: str = ""  # request-body integrity (webhooks)
    kind: str = ""    # standard | ingress | egress | sip | agent


def ensure_admin_permission(claims: ClaimGrants, room: str) -> bool:
    """Room-scoped admin (pkg/service/auth.go:133 EnsureAdminPermission):
    requires roomAdmin AND the token's room claim to name the target room.
    A bare roomAdmin token with no room claim administrates nothing."""
    return bool(claims.video.room_admin and room and room == claims.video.room)


def ensure_create_permission(claims: ClaimGrants) -> bool:
    """auth.go:146 EnsureCreatePermission — roomCreate grant."""
    return bool(claims.video.room_create)


def ensure_list_permission(claims: ClaimGrants) -> bool:
    """auth.go:154 EnsureListPermission — roomList grant."""
    return bool(claims.video.room_list)


def ensure_record_permission(claims: ClaimGrants) -> bool:
    """auth.go:162 EnsureRecordPermission — roomRecord grant (egress)."""
    return bool(claims.video.room_record)


def ensure_ingress_admin_permission(claims: ClaimGrants) -> bool:
    """auth.go:170 EnsureIngressAdminPermission — ingressAdmin grant."""
    return bool(claims.video.ingress_admin)


class AccessToken:
    """Mint HS256 JWTs (auth/access_token.go)."""

    def __init__(self, api_key: str, api_secret: str):
        self.api_key = api_key
        self.api_secret = api_secret
        self.identity = ""
        self.name = ""
        self.metadata = ""
        self.attributes: dict[str, str] = {}
        self.kind = ""
        self.grant = VideoGrant()
        self.sha256 = ""  # body-integrity claim (webhook signing)
        self.ttl = 6 * 3600  # auth defaultValidDuration

    def to_jwt(self, now: int | None = None) -> str:
        now = int(time.time()) if now is None else now
        header = {"alg": "HS256", "typ": "JWT"}
        payload: dict = {
            "iss": self.api_key,
            "nbf": now - 10,
            "exp": now + self.ttl,
            "video": self.grant.to_claim(),
        }
        if self.identity:
            payload["sub"] = self.identity
            payload["jti"] = self.identity
        elif self.grant.room_join:
            raise TokenError("identity is required for room join tokens")
        if self.name:
            payload["name"] = self.name
        if self.metadata:
            payload["metadata"] = self.metadata
        if self.attributes:
            payload["attributes"] = self.attributes
        if self.kind:
            payload["kind"] = self.kind
        if self.sha256:
            payload["sha256"] = self.sha256
        signing = _b64url(json.dumps(header, separators=(",", ":")).encode()) + "." + _b64url(
            json.dumps(payload, separators=(",", ":")).encode()
        )
        sig = hmac.new(self.api_secret.encode(), signing.encode(), hashlib.sha256).digest()
        return signing + "." + _b64url(sig)


def verify_token(token: str, key_provider, now: int | None = None) -> ClaimGrants:
    """Decode + verify an HS256 token.

    `key_provider`: mapping api_key -> api_secret (the config `keys` map,
    reference pkg/config/config.go Keys / auth.go UserVerifier).
    """
    now = int(time.time()) if now is None else now
    parts = token.split(".")
    if len(parts) != 3:
        raise TokenError("malformed token")
    try:
        header = json.loads(_unb64url(parts[0]))
        payload = json.loads(_unb64url(parts[1]))
        sig = _unb64url(parts[2])
    except Exception as e:  # noqa: BLE001 — any decode failure is the same error class
        raise TokenError(f"undecodable token: {e}") from e
    if header.get("alg") != "HS256":
        raise TokenError(f"unsupported alg: {header.get('alg')}")
    api_key = payload.get("iss", "")
    secret = key_provider.get(api_key) if hasattr(key_provider, "get") else None
    if not secret:
        raise TokenError("unknown API key")
    expect = hmac.new(secret.encode(), f"{parts[0]}.{parts[1]}".encode(), hashlib.sha256).digest()
    if not hmac.compare_digest(sig, expect):
        raise TokenError("invalid signature")
    if payload.get("exp", 0) < now:
        raise TokenError("token expired")
    if payload.get("nbf", 0) > now + 10:
        raise TokenError("token not yet valid")
    return ClaimGrants(
        identity=payload.get("sub", ""),
        name=payload.get("name", ""),
        video=VideoGrant.from_claim(payload.get("video", {})),
        metadata=payload.get("metadata", ""),
        attributes=payload.get("attributes", {}),
        sha256=payload.get("sha256", ""),
        kind=payload.get("kind", ""),
    )
