from livekit_server_tpu.cli import main
import sys

sys.exit(main())
