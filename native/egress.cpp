// Native batch egress — datagram assembly, AEAD sealing, and kernel send.
//
// Reference parity: the per-packet egress work the reference does per
// DownTrack in Go — header construction + payload write
// (pkg/sfu/downtrack.go:680 WriteRTP), VP8 descriptor munge application
// (pkg/sfu/codecmunger/vp8.go:161), SRTP protection (pion/srtp under
// pkg/rtc/transport.go), and the socket write behind the pacer
// (pkg/sfu/pacer) — executed as ONE native call per tick over the device
// plane's compacted egress arrays:
//
//   for each entry: 12-byte RTP header (SN/TS/SSRC/PT/M) + payload gather
//   from the ingest slab + in-place VP8 descriptor patch; optionally an
//   AES-128-GCM seal (frame layout must match runtime/crypto.py:
//   0x01 | key_id(4 BE) | dir(1)=S2C | counter(8 BE) | ct || tag,
//   nonce = dir | counter | 0^3, AAD = the 14-byte header); then
//   sendmmsg() in chunks, fanned over a few threads (seal + syscall both
//   parallelize; entries are pre-partitioned so threads never share
//   output ranges).
//
// AES-GCM uses OpenSSL's stable EVP C ABI. This image ships
// libcrypto.so.3 but not the headers, so the handful of prototypes used
// are declared here directly.
//
// Build: g++ -O2 -shared -fPIC -pthread -o libegress.so egress.cpp -l:libcrypto.so.3
// ABI: plain C, loaded via ctypes (no pybind11 in this image).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#include <errno.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

// UDP generic segmentation offload (Linux ≥ 4.18): one sendmsg carries a
// run of equal-size datagrams to one destination; the kernel splits them
// at xmit. This is the difference between ~3 µs/datagram (per-datagram
// sendmmsg, socket-lock bound) and amortizing that cost over a whole
// (subscriber, track) tick burst. Headers for it aren't guaranteed in
// this image, so define the ABI constants directly.
#ifndef SOL_UDP
#define SOL_UDP 17
#endif
#ifndef UDP_SEGMENT
#define UDP_SEGMENT 103
#endif

// ---- OpenSSL EVP prototypes (libcrypto.so.3; EVP ABI is stable) -----------
extern "C" {
typedef struct evp_cipher_ctx_st EVP_CIPHER_CTX;
typedef struct evp_cipher_st EVP_CIPHER;
typedef struct engine_st ENGINE;
EVP_CIPHER_CTX* EVP_CIPHER_CTX_new(void);
void EVP_CIPHER_CTX_free(EVP_CIPHER_CTX*);
const EVP_CIPHER* EVP_aes_128_gcm(void);
int EVP_EncryptInit_ex(EVP_CIPHER_CTX*, const EVP_CIPHER*, ENGINE*,
                       const unsigned char*, const unsigned char*);
int EVP_EncryptUpdate(EVP_CIPHER_CTX*, unsigned char*, int*,
                      const unsigned char*, int);
int EVP_EncryptFinal_ex(EVP_CIPHER_CTX*, unsigned char*, int*);
int EVP_DecryptInit_ex(EVP_CIPHER_CTX*, const EVP_CIPHER*, ENGINE*,
                       const unsigned char*, const unsigned char*);
int EVP_DecryptUpdate(EVP_CIPHER_CTX*, unsigned char*, int*,
                      const unsigned char*, int);
int EVP_DecryptFinal_ex(EVP_CIPHER_CTX*, unsigned char*, int*);
int EVP_CIPHER_CTX_ctrl(EVP_CIPHER_CTX*, int, int, void*);
}
#define EVP_CTRL_GCM_GET_TAG 0x10
#define EVP_CTRL_GCM_SET_TAG 0x11

namespace {

constexpr int SEAL_HEADER = 14;  // magic + key_id(4) + dir(1) + counter(8)
constexpr int SEAL_TAG = 16;
constexpr uint8_t SEAL_MAGIC = 0x01;
constexpr uint8_t DIR_S2C = 1;
constexpr int MAX_DGRAM = 2048;
constexpr int MMSG_CHUNK = 512;
// Bump when the exported symbol set or any signature changes; the ctypes
// loader and tools/check.py compare it against the Python-side constant.
constexpr int32_t EGRESS_ABI = 4;
// Kernel cap is UDP_MAX_SEGMENTS (64); stay under it and under 64 KB.
constexpr int GSO_MAX_SEGS = 60;
constexpr int64_t GSO_MAX_BYTES = 64000;

// First EINVAL/EOPNOTSUPP on a segmented send disables GSO process-wide
// (e.g. exotic kernels); every batch then rides the plain sendmmsg path.
std::atomic<bool> g_gso_ok{true};

struct Args {
  uint8_t* skip;  // [n] — entries the builder refused (oversized sealed)
  const uint8_t* slab;
  const int64_t* pay_off;
  const int32_t* pay_len;
  const uint8_t* marker;
  const uint8_t* pt;
  const uint8_t* vp8;
  // Pre-serialized RTP header-extension section per entry (profile +
  // length + elements + padding, built host-side: playout delay,
  // dependency descriptor, or both). ext_len 0 = no extension.
  const uint8_t* ext_blob;
  const int64_t* ext_off;
  const int32_t* ext_len;
  const uint16_t* sn;
  const uint32_t* ts;
  const uint32_t* ssrc;
  const int32_t* pid;
  const int32_t* tl0;
  const int32_t* kidx;
  const uint32_t* ip;    // host byte order
  const uint16_t* port;  // host byte order
  const uint8_t* seal;
  const int32_t* key_idx;
  const uint8_t* keys;      // [nkeys][16]
  const uint32_t* key_ids;  // [nkeys]
  const uint64_t* counters;
  uint8_t* out;
  const int64_t* out_off;
  const int32_t* out_len;
  int fd;
  // Pacer (pkg/sfu/pacer "no-queue" seat): spread each worker's sendmmsg
  // chunks across this window so a tick's burst doesn't hit receiver
  // buffers as one spike. 0 = no shaping. Chunking shrinks to PACE_CHUNK
  // when active so typical loads actually have gaps to spread.
  int pace_window_us;
};

constexpr int PACE_CHUNK = 64;

void be16(uint8_t* p, uint16_t v) { p[0] = v >> 8; p[1] = v & 0xFF; }
void be32(uint8_t* p, uint32_t v) {
  p[0] = v >> 24; p[1] = (v >> 16) & 0xFF; p[2] = (v >> 8) & 0xFF; p[3] = v & 0xFF;
}
void be64(uint8_t* p, uint64_t v) {
  for (int i = 0; i < 8; i++) p[i] = (v >> (56 - 8 * i)) & 0xFF;
}

// VP8 payload-descriptor patch on an assembled payload (same semantics as
// rewrite_rtp_vp8_batch in rtp_parser.cpp, but the payload location is
// already known). Field widths preserved; negative values skip a field.
void patch_vp8(uint8_t* d, int dl, int32_t pid, int32_t tl0, int32_t kidx) {
  if (dl < 1) return;
  int q = 0;
  uint8_t b0 = d[q++];
  if (!(b0 & 0x80)) return;  // no X ⇒ no pid/tl0/keyidx fields
  if (q >= dl) return;
  uint8_t xb = d[q++];
  bool I = xb & 0x80, L = xb & 0x40, T = xb & 0x20, K = xb & 0x10;
  if (I) {
    if (q >= dl) return;
    if (d[q] & 0x80) {  // 15-bit picture id
      if (q + 1 >= dl) return;
      if (pid >= 0) {
        d[q] = 0x80 | ((pid >> 8) & 0x7F);
        d[q + 1] = pid & 0xFF;
      }
      q += 2;
    } else {
      if (pid >= 0) d[q] = pid & 0x7F;
      q += 1;
    }
  }
  if (L) {
    if (q >= dl) return;
    if (tl0 >= 0) d[q] = tl0 & 0xFF;
    q += 1;
  }
  if (T || K) {
    if (q >= dl) return;
    if (kidx >= 0) d[q] = (d[q] & 0xE0) | (kidx & 0x1F);
    q += 1;
  }
}

// Per-datagram sendmmsg over built entries [lo, hi) — the portable path,
// also used for paced sends (pacing spreads individual datagrams; GSO
// would re-burst them).
int64_t send_plain(const Args& a, int lo, int hi) {
  int64_t sent = 0;
  mmsghdr msgs[MMSG_CHUNK];
  iovec iovs[MMSG_CHUNK];
  sockaddr_in sas[MMSG_CHUNK];
  int chunk = a.pace_window_us > 0 ? PACE_CHUNK : MMSG_CHUNK;
  // Sleep per inter-chunk gap, from THIS worker's real chunk count (the
  // caller only names the window; constants stay one-sided).
  int n_chunks = (hi - lo + chunk - 1) / chunk;
  int gap_us = n_chunks > 1 ? a.pace_window_us / (n_chunks - 1) : 0;
  int i = lo;
  while (i < hi) {
    int cnt = 0;
    while (i < hi && a.skip[i]) i++;
    for (; cnt < chunk && i + cnt < hi && !a.skip[i + cnt]; cnt++) {
      int j = i + cnt;
      std::memset(&sas[cnt], 0, sizeof(sockaddr_in));
      sas[cnt].sin_family = AF_INET;
      sas[cnt].sin_addr.s_addr = htonl(a.ip[j]);
      sas[cnt].sin_port = htons(a.port[j]);
      iovs[cnt].iov_base = a.out + a.out_off[j];
      iovs[cnt].iov_len = (size_t)a.out_len[j];
      std::memset(&msgs[cnt].msg_hdr, 0, sizeof(msghdr));
      msgs[cnt].msg_hdr.msg_name = &sas[cnt];
      msgs[cnt].msg_hdr.msg_namelen = sizeof(sockaddr_in);
      msgs[cnt].msg_hdr.msg_iov = &iovs[cnt];
      msgs[cnt].msg_hdr.msg_iovlen = 1;
    }
    int done = 0;
    int spins = 0;
    while (done < cnt) {
      int r = sendmmsg(a.fd, msgs + done, cnt - done, 0);
      if (r > 0) {
        done += r;
        sent += r;
        continue;
      }
      if ((errno == EAGAIN || errno == EWOULDBLOCK || errno == ENOBUFS) &&
          spins < 64) {
        spins++;
        usleep(50);  // socket buffer full: brief backoff, then drop rest
        continue;
      }
      break;  // hard error (or spun out): drop the remainder of the chunk
    }
    i += cnt;
    if (gap_us > 0 && i < hi) usleep(gap_us);
  }
  return sent;
}

// GSO send over built entries [lo, hi): consecutive entries to the same
// destination whose datagrams are equal-size (plus at most one shorter
// trailer — the UDP_SEGMENT contract) collapse into ONE message whose
// payload is their already-contiguous bytes in `out`. The caller sorts
// entries by (room, sub, track), so a (subscriber, track) tick burst is
// typically one message. On kernel refusal, *resume holds the first
// unsent entry and the caller falls back to send_plain.
int64_t send_gso(const Args& a, int lo, int hi, int* resume) {
  int64_t sent = 0;
  mmsghdr msgs[MMSG_CHUNK];
  iovec iovs[MMSG_CHUNK];
  sockaddr_in sas[MMSG_CHUNK];
  alignas(cmsghdr) static thread_local char
      ctrls[MMSG_CHUNK][CMSG_SPACE(sizeof(uint16_t))];
  int run_first[MMSG_CHUNK];
  int run_cnt[MMSG_CHUNK];
  *resume = -1;
  int i = lo;
  while (i < hi) {
    int m = 0;
    while (m < MMSG_CHUNK && i < hi) {
      while (i < hi && a.skip[i]) i++;
      if (i >= hi) break;
      int first = i;
      int32_t seg = a.out_len[i];
      int cnt = 1;
      int64_t bytes = seg;
      i++;
      // Runs break at skips too: a skipped entry leaves a hole in `out`,
      // so bytes on its far side are not contiguous with this run.
      while (i < hi && !a.skip[i] && cnt < GSO_MAX_SEGS &&
             a.ip[i] == a.ip[first] && a.port[i] == a.port[first] &&
             bytes + a.out_len[i] <= GSO_MAX_BYTES &&
             a.out_len[i] <= seg) {
        bytes += a.out_len[i];
        cnt++;
        bool last_short = a.out_len[i] < seg;
        i++;
        if (last_short) break;  // only the final segment may be shorter
      }
      std::memset(&sas[m], 0, sizeof(sockaddr_in));
      sas[m].sin_family = AF_INET;
      sas[m].sin_addr.s_addr = htonl(a.ip[first]);
      sas[m].sin_port = htons(a.port[first]);
      iovs[m].iov_base = a.out + a.out_off[first];
      iovs[m].iov_len = (size_t)bytes;
      std::memset(&msgs[m].msg_hdr, 0, sizeof(msghdr));
      msgs[m].msg_hdr.msg_name = &sas[m];
      msgs[m].msg_hdr.msg_namelen = sizeof(sockaddr_in);
      msgs[m].msg_hdr.msg_iov = &iovs[m];
      msgs[m].msg_hdr.msg_iovlen = 1;
      if (cnt > 1) {
        msgs[m].msg_hdr.msg_control = ctrls[m];
        msgs[m].msg_hdr.msg_controllen = CMSG_SPACE(sizeof(uint16_t));
        cmsghdr* cm = CMSG_FIRSTHDR(&msgs[m].msg_hdr);
        cm->cmsg_level = SOL_UDP;
        cm->cmsg_type = UDP_SEGMENT;
        cm->cmsg_len = CMSG_LEN(sizeof(uint16_t));
        uint16_t gs = (uint16_t)seg;
        std::memcpy(CMSG_DATA(cm), &gs, sizeof(uint16_t));
      }
      run_first[m] = first;
      run_cnt[m] = cnt;
      m++;
    }
    int done = 0;
    int spins = 0;
    while (done < m) {
      int r = sendmmsg(a.fd, msgs + done, m - done, 0);
      if (r > 0) {
        for (int q = done; q < done + r; q++) sent += run_cnt[q];
        done += r;
        continue;
      }
      if ((errno == EAGAIN || errno == EWOULDBLOCK || errno == ENOBUFS) &&
          spins < 64) {
        spins++;
        usleep(50);
        continue;
      }
      if (errno == EINVAL || errno == EOPNOTSUPP || errno == ENOTSUP ||
          errno == EMSGSIZE || errno == EIO) {
        if (run_cnt[done] > 1) {
          *resume = run_first[done];  // caller re-sends plain from here
          return sent;
        }
        // Single-datagram message carries no UDP_SEGMENT cmsg, so this
        // is a per-destination error (e.g. PMTU), not GSO refusal —
        // skip the entry and keep the GSO fast path alive.
        done++;
        continue;
      }
      return sent;  // hard error: drop the remainder
    }
  }
  return sent;
}

inline int64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (int64_t)ts.tv_sec * 1000000000ll + ts.tv_nsec;
}

// Multicast-shaped grouping (P3FA): entries that fan one source packet out
// to many subscribers share a canonical staging of its bytes. The group
// key is the packet's (track, k) slot; rooms are walked in order, so a
// slot is valid only for the room it was staged in. Matching on
// (room, pay_off, ext section) keeps the reuse sound when subscribers get
// different extension sections (per-sub layer caps).
struct CanonSlot {
  int32_t room = -1;     // staging scope; -1 = never staged
  int64_t pay_off = -1;
  int64_t ext_off = -1;
  int32_t ext_len = -1;
  int32_t clear_len = 0;
};

// Per-worker scratch that persists across jobs on pool threads — the
// canonical slab stays cache-hot between ticks.
struct WorkerScratch {
  std::vector<uint8_t> canon;
  std::vector<CanonSlot> slots;
  void ensure(int32_t n_slots) {
    if ((int32_t)slots.size() < n_slots) {
      slots.assign(n_slots, CanonSlot{});
      canon.assign((size_t)n_slots * MAX_DGRAM, 0);
    } else {
      for (auto& s : slots) s.room = -1;
    }
  }
};

// Build entries [lo, hi) into the shared out buffer (disjoint ranges) and
// send them. Returns datagrams handed to the kernel. When `grp` is given
// (multicast-shaped mode), entry i with grp[i] >= 0 stages its packet's
// bytes once per group in `scr` and later fan-out members copy from that
// hot canonical instead of re-gathering slab + extension bytes; the
// 12-byte RTP header (SN/TS/SSRC) and VP8 descriptor fields are patched
// per subscriber. The AEAD seal itself necessarily runs per datagram —
// every sealed frame carries its own counter, and a GCM nonce must never
// repeat under one key — so what the group shares is the staged
// cleartext, not the tag.
int64_t worker(const Args& a, int lo, int hi, const int32_t* grp,
               const int32_t* rooms, int32_t grp_slots,
               WorkerScratch* scr, int64_t* built_out) {
  EVP_CIPHER_CTX* ctx = EVP_CIPHER_CTX_new();
  const EVP_CIPHER* cipher = EVP_aes_128_gcm();
  bool ctx_inited = false;
  int32_t ctx_key = -1;
  uint8_t scratch[MAX_DGRAM];
  if (grp && scr) scr->ensure(grp_slots);
  int64_t built = 0;

  for (int i = lo; i < hi; i++) {
    uint8_t* dst = a.out + a.out_off[i];
    int plen = a.pay_len[i];
    int ext_len = a.ext_len[i];
    int hdr_len = 12 + ext_len;
    int clear_len = hdr_len + plen;
    bool sealed = a.seal[i] && a.key_idx[i] >= 0;
    if (plen < 0 || ext_len < 0 || (sealed && clear_len > MAX_DGRAM)) {
      // The sealed path stages cleartext in a fixed stack scratch; an
      // attacker-sized jumbo datagram must be refused, never overflowed.
      a.skip[i] = 1;
      continue;
    }
    uint8_t* build = sealed ? scratch : dst;
    const int32_t slot = (grp && scr) ? grp[i] : -1;
    if (slot >= 0 && slot < grp_slots && clear_len <= MAX_DGRAM) {
      CanonSlot& cs = scr->slots[slot];
      uint8_t* cb = scr->canon.data() + (size_t)slot * MAX_DGRAM;
      const int64_t eo = ext_len ? a.ext_off[i] : -1;
      if (cs.room != rooms[i] || cs.pay_off != a.pay_off[i] ||
          cs.ext_off != eo || cs.ext_len != ext_len) {
        // Stage the canonical once per (room, track, k[, ext]) group.
        cb[0] = 0x80 | (ext_len ? 0x10 : 0);
        cb[1] = (a.marker[i] ? 0x80 : 0) | (a.pt[i] & 0x7F);
        std::memset(cb + 2, 0, 10);  // SN/TS/SSRC are per-subscriber
        if (ext_len) std::memcpy(cb + 12, a.ext_blob + a.ext_off[i], ext_len);
        std::memcpy(cb + hdr_len, a.slab + a.pay_off[i], plen);
        cs.room = rooms[i];
        cs.pay_off = a.pay_off[i];
        cs.ext_off = eo;
        cs.ext_len = ext_len;
        cs.clear_len = clear_len;
      }
      std::memcpy(build, cb, clear_len);
      be16(build + 2, a.sn[i]);
      be32(build + 4, a.ts[i]);
      be32(build + 8, a.ssrc[i]);
    } else {
      build[0] = 0x80 | (ext_len ? 0x10 : 0);
      build[1] = (a.marker[i] ? 0x80 : 0) | (a.pt[i] & 0x7F);
      be16(build + 2, a.sn[i]);
      be32(build + 4, a.ts[i]);
      be32(build + 8, a.ssrc[i]);
      if (ext_len) std::memcpy(build + 12, a.ext_blob + a.ext_off[i], ext_len);
      std::memcpy(build + hdr_len, a.slab + a.pay_off[i], plen);
    }
    if (a.vp8[i]) patch_vp8(build + hdr_len, plen, a.pid[i], a.tl0[i], a.kidx[i]);
    built++;

    if (sealed) {
      const uint8_t* key = a.keys + 16 * a.key_idx[i];
      uint8_t* h = dst;
      h[0] = SEAL_MAGIC;
      be32(h + 1, a.key_ids[a.key_idx[i]]);
      h[5] = DIR_S2C;
      be64(h + 6, a.counters[i]);
      uint8_t nonce[12];
      nonce[0] = DIR_S2C;
      std::memcpy(nonce + 1, h + 6, 8);
      std::memset(nonce + 9, 0, 3);
      int outl = 0, fl = 0;
      // First init binds the cipher. Entries are destination-major, so
      // consecutive datagrams usually share a session key: re-initing
      // with IV only skips the AES key-schedule expansion per datagram.
      if (a.key_idx[i] != ctx_key) {
        EVP_EncryptInit_ex(ctx, ctx_inited ? nullptr : cipher, nullptr, key,
                           nonce);
        ctx_key = a.key_idx[i];
      } else {
        EVP_EncryptInit_ex(ctx, nullptr, nullptr, nullptr, nonce);
      }
      ctx_inited = true;
      EVP_EncryptUpdate(ctx, nullptr, &outl, h, SEAL_HEADER);  // AAD
      EVP_EncryptUpdate(ctx, dst + SEAL_HEADER, &outl, build, clear_len);
      EVP_EncryptFinal_ex(ctx, dst + SEAL_HEADER + outl, &fl);
      EVP_CIPHER_CTX_ctrl(ctx, EVP_CTRL_GCM_GET_TAG, SEAL_TAG,
                          dst + SEAL_HEADER + clear_len);
    }
  }
  EVP_CIPHER_CTX_free(ctx);
  if (built_out) *built_out = built;

  int64_t sent = 0;
  if (a.fd >= 0) {
    if (a.pace_window_us > 0 || !g_gso_ok.load(std::memory_order_relaxed)) {
      sent = send_plain(a, lo, hi);
    } else {
      int resume = -1;
      sent = send_gso(a, lo, hi, &resume);
      if (resume >= 0) {
        // Kernel refused segmentation: fall back for this and every
        // later batch, resuming from the first unsent entry.
        g_gso_ok.store(false, std::memory_order_relaxed);
        sent += send_plain(a, resume, hi);
      }
    }
  }
  return sent;
}

int64_t worker(const Args& a, int lo, int hi) {
  return worker(a, lo, hi, nullptr, nullptr, 0, nullptr, nullptr);
}

// ---- persistent shard pool -------------------------------------------------
//
// The one-shot egress_batch_send spawns threads per call; at a 5 ms tick
// that spawn/join overhead is a few percent of the window. The plane path
// instead parks a fixed crew of workers on a condvar and hands each tick's
// shard list to them: shard i owns entries [shard_lo[i], shard_hi[i]) —
// room-aligned, so group canonicals never straddle workers — and writes
// only its own disjoint out ranges. Workers keep their canonical slabs
// across ticks (cache-warm).

struct PlaneJob {
  const Args* a = nullptr;
  const int64_t* shard_lo = nullptr;
  const int64_t* shard_hi = nullptr;
  const int32_t* grp = nullptr;
  const int32_t* rooms = nullptr;
  int32_t grp_slots = 0;
  int n_shards = 0;
  int64_t* shard_sent = nullptr;
  int64_t* shard_built = nullptr;
  int64_t* shard_ns = nullptr;
};

class Pool {
 public:
  ~Pool() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
      cv_.notify_all();
    }
    for (auto& t : ths_) t.join();
  }

  void ensure(int n) {
    if (n > 16) n = 16;
    std::unique_lock<std::mutex> lk(mu_);
    while ((int)ths_.size() < n) {
      int id = (int)ths_.size();
      ths_.emplace_back([this, id] { loop(id); });
    }
  }

  int size() {
    std::unique_lock<std::mutex> lk(mu_);
    return (int)ths_.size();
  }

  // Runs the job on the pool and blocks until every shard completed.
  void run(PlaneJob& job) {
    std::unique_lock<std::mutex> lk(mu_);
    job_ = &job;
    next_.store(0, std::memory_order_relaxed);
    done_ = 0;
    gen_++;
    cv_.notify_all();
    cv_done_.wait(lk, [&] { return done_ >= job.n_shards; });
    job_ = nullptr;
  }

 private:
  void loop(int id) {
    (void)id;
    uint64_t seen = 0;
    WorkerScratch scr;
    for (;;) {
      // Copy the job descriptor under the lock: a straggler that loses the
      // last-shard race must never dereference the caller's stack frame
      // after run() returned. Claimed shards (s < n_shards) are always
      // processed before done_ releases the caller, so the pointed-to
      // arrays are alive wherever they are actually read.
      PlaneJob job;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] { return stop_ || gen_ != seen; });
        if (stop_) return;
        seen = gen_;
        if (!job_) continue;
        job = *job_;
      }
      for (;;) {
        int s = next_.fetch_add(1, std::memory_order_relaxed);
        if (s >= job.n_shards) break;
        const int64_t t0 = now_ns();
        int64_t built = 0;
        int64_t sent = worker(*job.a, (int)job.shard_lo[s],
                              (int)job.shard_hi[s], job.grp, job.rooms,
                              job.grp_slots, &scr, &built);
        job.shard_sent[s] = sent;
        job.shard_built[s] = built;
        job.shard_ns[s] = now_ns() - t0;
        {
          std::unique_lock<std::mutex> lk(mu_);
          if (++done_ >= job.n_shards) cv_done_.notify_all();
        }
      }
    }
  }

  std::vector<std::thread> ths_;
  std::mutex mu_;
  std::condition_variable cv_, cv_done_;
  uint64_t gen_ = 0;
  bool stop_ = false;
  PlaneJob* job_ = nullptr;
  int done_ = 0;
  std::atomic<int> next_{0};
};

Pool g_pool;

}  // namespace

extern "C" {

// Assemble (and seal, and send when fd >= 0) one tick's egress datagrams.
// All arrays have n entries; out/out_off/out_len are caller-allocated with
// per-entry destination ranges (disjoint). Returns datagrams sent, or n
// when fd < 0 (build-only mode, used by tests).
int64_t egress_batch_send(
    int fd, int n_threads, const uint8_t* slab, int32_t n,
    const int64_t* pay_off, const int32_t* pay_len, const uint8_t* marker,
    const uint8_t* pt, const uint8_t* vp8,
    const uint8_t* ext_blob, const int64_t* ext_off, const int32_t* ext_len,
    const uint16_t* sn,
    const uint32_t* ts, const uint32_t* ssrc, const int32_t* pid,
    const int32_t* tl0, const int32_t* kidx, const uint32_t* ip,
    const uint16_t* port, const uint8_t* seal, const int32_t* key_idx,
    const uint8_t* keys, const uint32_t* key_ids, const uint64_t* counters,
    uint8_t* out, const int64_t* out_off, const int32_t* out_len,
    int pace_window_us) {
  if (n <= 0) return 0;
  std::vector<uint8_t> skip(n, 0);
  Args a{skip.data(), slab, pay_off, pay_len, marker, pt, vp8,
         ext_blob, ext_off, ext_len,
         sn,  ts,
         ssrc,  pid,     tl0,     kidx,   ip,       port,    seal, key_idx,
         keys,  key_ids, counters, out,   out_off,  out_len, fd,
         pace_window_us};
  if (n_threads < 1) n_threads = 1;
  if (n_threads > 8) n_threads = 8;
  if (n < 2 * n_threads) n_threads = 1;

  int64_t total = 0;
  if (n_threads == 1) {
    total = worker(a, 0, n);
  } else {
    std::vector<int64_t> sent(n_threads, 0);
    std::vector<std::thread> th;
    int per = (n + n_threads - 1) / n_threads;
    for (int w = 0; w < n_threads; w++) {
      int lo = w * per, hi = lo + per < n ? lo + per : n;
      if (lo >= hi) break;
      th.emplace_back([&a, &sent, w, lo, hi] { sent[w] = worker(a, lo, hi); });
    }
    for (auto& t : th) t.join();
    for (int64_t s : sent) total += s;
  }
  if (fd >= 0) return total;
  int64_t built = 0;
  for (int i = 0; i < n; i++) built += skip[i] ? 0 : 1;
  return built;
}

int32_t egress_abi_version(void) { return EGRESS_ABI; }

// Pre-warm the persistent worker pool (idempotent; capped at 16). The
// plane path also calls this lazily, so warming is an optimization only.
void egress_pool_ensure(int n) { g_pool.ensure(n); }

int32_t egress_pool_size(void) { return g_pool.size(); }

// Sharded, multicast-shaped egress: the plane path. Entries arrive sorted
// by (room, sub, track, k); shards are contiguous, room-aligned entry
// ranges [shard_lo[i], shard_hi[i]) with disjoint out ranges, each run by
// one persistent pool worker (build + group-canonical reuse + seal +
// per-shard GSO/sendmmsg). `grp[i]` >= 0 names the entry's canonical
// cache slot (its packet's t*K+k), -1 forces the direct build; `rooms`
// scopes slot validity. Per-shard datagrams-sent / built / wall-ns land
// in shard_sent/shard_built/shard_ns. Returns total datagrams handed to
// the kernel, or total built when fd < 0 (build-only mode, used by the
// parity and determinism tests).
int64_t egress_plane_send(
    int fd, int n_shards, const int64_t* shard_lo, const int64_t* shard_hi,
    const uint8_t* slab, int32_t n,
    const int64_t* pay_off, const int32_t* pay_len, const uint8_t* marker,
    const uint8_t* pt, const uint8_t* vp8,
    const uint8_t* ext_blob, const int64_t* ext_off, const int32_t* ext_len,
    const uint16_t* sn,
    const uint32_t* ts, const uint32_t* ssrc, const int32_t* pid,
    const int32_t* tl0, const int32_t* kidx, const uint32_t* ip,
    const uint16_t* port, const uint8_t* seal, const int32_t* key_idx,
    const uint8_t* keys, const uint32_t* key_ids, const uint64_t* counters,
    uint8_t* out, const int64_t* out_off, const int32_t* out_len,
    const int32_t* rooms, const int32_t* grp, int32_t grp_slots,
    int pace_window_us,
    int64_t* shard_sent, int64_t* shard_built, int64_t* shard_ns) {
  if (n <= 0 || n_shards <= 0) return 0;
  std::vector<uint8_t> skip(n, 0);
  Args a{skip.data(), slab, pay_off, pay_len, marker, pt, vp8,
         ext_blob, ext_off, ext_len,
         sn,  ts,
         ssrc,  pid,     tl0,     kidx,   ip,       port,    seal, key_idx,
         keys,  key_ids, counters, out,   out_off,  out_len, fd,
         pace_window_us};
  for (int s = 0; s < n_shards; s++) {
    shard_sent[s] = 0;
    shard_built[s] = 0;
    shard_ns[s] = 0;
  }
  if (n_shards == 1) {
    // Single shard runs inline on the caller's thread: on small hosts the
    // cross-thread handoff would cost more than it buys.
    static thread_local WorkerScratch scr;
    const int64_t t0 = now_ns();
    int64_t built = 0;
    shard_sent[0] = worker(a, (int)shard_lo[0], (int)shard_hi[0], grp, rooms,
                           grp_slots, &scr, &built);
    shard_built[0] = built;
    shard_ns[0] = now_ns() - t0;
  } else {
    g_pool.ensure(n_shards);
    PlaneJob job;
    job.a = &a;
    job.shard_lo = shard_lo;
    job.shard_hi = shard_hi;
    job.grp = grp;
    job.rooms = rooms;
    job.grp_slots = grp_slots;
    job.n_shards = n_shards;
    job.shard_sent = shard_sent;
    job.shard_built = shard_built;
    job.shard_ns = shard_ns;
    g_pool.run(job);
  }
  int64_t total = 0;
  for (int s = 0; s < n_shards; s++) {
    total += fd >= 0 ? shard_sent[s] : shard_built[s];
  }
  return total;
}

// Express-lane egress: assemble+seal(+send) a SMALL batch (one receive
// window's worth of packets for interactive rooms) inline on the caller's
// thread, with none of the plane machinery — no shard planning, no pool
// handoff, no pacing. Reuses the same worker() walk as the sharded path,
// so the canonical-group staging (grp/rooms/grp_slots, may be null/0) and
// the per-thread key-schedule cache apply unchanged; output frames are
// byte-identical to what the batched path would build for the same
// entries. Returns datagrams handed to the kernel, or datagrams built
// when fd < 0; *built_out (optional) always receives the built count.
int64_t egress_express_send(
    int fd, const uint8_t* slab, int32_t n,
    const int64_t* pay_off, const int32_t* pay_len, const uint8_t* marker,
    const uint8_t* pt, const uint8_t* vp8,
    const uint8_t* ext_blob, const int64_t* ext_off, const int32_t* ext_len,
    const uint16_t* sn,
    const uint32_t* ts, const uint32_t* ssrc, const int32_t* pid,
    const int32_t* tl0, const int32_t* kidx, const uint32_t* ip,
    const uint16_t* port, const uint8_t* seal, const int32_t* key_idx,
    const uint8_t* keys, const uint32_t* key_ids, const uint64_t* counters,
    uint8_t* out, const int64_t* out_off, const int32_t* out_len,
    const int32_t* rooms, const int32_t* grp, int32_t grp_slots,
    int64_t* built_out) {
  if (n <= 0) {
    if (built_out) *built_out = 0;
    return 0;
  }
  std::vector<uint8_t> skip(n, 0);
  Args a{skip.data(), slab, pay_off, pay_len, marker, pt, vp8,
         ext_blob, ext_off, ext_len,
         sn,  ts,
         ssrc,  pid,     tl0,     kidx,   ip,       port,    seal, key_idx,
         keys,  key_ids, counters, out,   out_off,  out_len, fd,
         /*pace_window_us=*/0};
  static thread_local WorkerScratch scr;
  int64_t built = 0;
  int64_t sent = worker(a, 0, n, grp, rooms, grp_slots, &scr, &built);
  if (built_out) *built_out = built;
  return fd >= 0 ? sent : built;
}

// Send pre-built datagrams (contiguous blob + per-entry offset/length/
// destination) with the same GSO/sendmmsg machinery as the egress path.
// Used by load generators and relays that already hold wire-ready bytes —
// no RTP assembly, no sealing. Returns datagrams handed to the kernel.
int64_t send_raw(int fd, const uint8_t* blob, int32_t n,
                 const int64_t* offs, const int32_t* lens,
                 const uint32_t* ip, const uint16_t* port) {
  if (n <= 0 || fd < 0) return 0;
  std::vector<uint8_t> skip(n, 0);
  Args a{skip.data(), nullptr, nullptr, nullptr, nullptr, nullptr, nullptr,
         nullptr, nullptr, nullptr, nullptr, nullptr, nullptr, nullptr,
         nullptr, nullptr, ip,      port,    nullptr, nullptr, nullptr,
         nullptr, nullptr, const_cast<uint8_t*>(blob), offs, lens, fd, 0};
  if (!g_gso_ok.load(std::memory_order_relaxed)) return send_plain(a, 0, n);
  int resume = -1;
  int64_t sent = send_gso(a, 0, n, &resume);
  if (resume >= 0) {
    g_gso_ok.store(false, std::memory_order_relaxed);
    sent += send_plain(a, resume, n);
  }
  return sent;
}

}  // extern "C"

extern "C" {

// Batch receive: drain up to max_n datagrams from a non-blocking UDP
// socket with recvmmsg (the ingress twin of the batch sender — replaces
// one Python callback per datagram with one native call per wake).
// Returns the number received; fills per-datagram offsets/lengths into
// `buf` (caller-sized) and source ip/port (host byte order).
int32_t rx_batch(int fd, uint8_t* buf, int64_t cap, int32_t* offsets,
                 int32_t* lengths, uint32_t* ips, uint16_t* ports,
                 int32_t max_n, int32_t max_dgram) {
  constexpr int CHUNK = 64;
  mmsghdr msgs[CHUNK];
  iovec iovs[CHUNK];
  sockaddr_in sas[CHUNK];
  int32_t n = 0;
  int64_t off = 0;
  while (n < max_n && off + (int64_t)CHUNK * max_dgram <= cap) {
    int want = max_n - n < CHUNK ? max_n - n : CHUNK;
    for (int j = 0; j < want; j++) {
      iovs[j].iov_base = buf + off + (int64_t)j * max_dgram;
      iovs[j].iov_len = max_dgram;
      std::memset(&msgs[j].msg_hdr, 0, sizeof(msghdr));
      msgs[j].msg_hdr.msg_iov = &iovs[j];
      msgs[j].msg_hdr.msg_iovlen = 1;
      msgs[j].msg_hdr.msg_name = &sas[j];
      msgs[j].msg_hdr.msg_namelen = sizeof(sockaddr_in);
    }
    int r = recvmmsg(fd, msgs, want, MSG_DONTWAIT, nullptr);
    if (r <= 0) break;
    for (int j = 0; j < r; j++) {
      if (msgs[j].msg_hdr.msg_flags & MSG_TRUNC) {
        // Oversized datagram: delivering the truncated prefix as if
        // complete would feed corrupt payloads downstream — drop it
        // (length 0; the caller's valid-mask skips it).
        offsets[n] = (int32_t)(off + (int64_t)j * max_dgram);
        lengths[n] = 0;
        ips[n] = 0;
        ports[n] = 0;
        n++;
        continue;
      }
      offsets[n] = (int32_t)(off + (int64_t)j * max_dgram);
      lengths[n] = (int32_t)msgs[j].msg_len;
      ips[n] = ntohl(sas[j].sin_addr.s_addr);
      ports[n] = ntohs(sas[j].sin_port);
      n++;
    }
    off += (int64_t)r * max_dgram;
    if (r < want) break;  // socket drained
  }
  return n;
}

}  // extern "C"

extern "C" {

// Batch AEAD open for sealed ingress frames (the decrypt twin of the
// sealed egress path; layout per runtime/crypto.py:
// 0x01 | key_id(4 BE) | dir(1) | counter(8 BE) | ct || tag(16),
// nonce = dir | counter | 0^3, AAD = the 14-byte header). `key_idx` maps
// each frame to a row of `keys` (16-byte AES-128 keys); <0 = unknown key.
// Plaintext for frame i lands at out + out_off[i]; out_len[i] = plaintext
// length, or -1 on auth failure / wrong direction / runt. Caller handles
// replay windows (cheap per-frame bitmap in Python).
void open_batch(const uint8_t* buf, const int32_t* offsets,
                const int32_t* lengths, int32_t n, const int32_t* key_idx,
                const uint8_t* keys, uint8_t expect_dir,
                uint8_t* out, const int64_t* out_off, int32_t* out_len) {
  EVP_CIPHER_CTX* ctx = EVP_CIPHER_CTX_new();
  const EVP_CIPHER* cipher = EVP_aes_128_gcm();
  bool inited = false;
  for (int i = 0; i < n; i++) {
    out_len[i] = -1;
    int len = lengths[i];
    if (key_idx[i] < 0 || len < 14 + 16) continue;
    const uint8_t* f = buf + offsets[i];
    if (f[0] != 0x01 || f[5] != expect_dir) continue;
    uint8_t nonce[12];
    nonce[0] = f[5];
    std::memcpy(nonce + 1, f + 6, 8);
    std::memset(nonce + 9, 0, 3);
    int ctlen = len - 14 - 16;
    int outl = 0, fl = 0;
    EVP_DecryptInit_ex(ctx, inited ? nullptr : cipher, nullptr,
                       keys + 16 * key_idx[i], nonce);
    inited = true;
    EVP_DecryptUpdate(ctx, nullptr, &outl, f, 14);  // AAD
    EVP_DecryptUpdate(ctx, out + out_off[i], &outl, f + 14, ctlen);
    EVP_CIPHER_CTX_ctrl(ctx, EVP_CTRL_GCM_SET_TAG, 16,
                        const_cast<uint8_t*>(f + len - 16));
    if (EVP_DecryptFinal_ex(ctx, out + out_off[i] + outl, &fl) == 1) {
      out_len[i] = outl + fl;
    }
  }
  EVP_CIPHER_CTX_free(ctx);
}

}  // extern "C"
