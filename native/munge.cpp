// Host-side munge walker: expand bit-packed send/drop/switch masks and
// apply the SN/TS/VP8 offset rewrites in one pass.
//
// Reference parity: pkg/sfu/rtpmunger.go UpdateAndGetSnTs/PacketDropped and
// pkg/sfu/codecmunger/vp8.go UpdateAndGet — the per-packet CPU work the
// reference does in DownTrack.WriteRTP. Semantics are pinned bit-identical
// to ops/rtpmunger.py + ops/vp8.py (the jax scan spec) by
// tests/test_host_munge.py; the numpy implementation in runtime/munge.py
// is the readable fallback.
//
// Layout contract (see runtime/munge.py HostMunger):
//   packet fields  int32  [R*T*K]          (row-major r, t, k)
//   mask words     uint32 [R*T*K*W]        (bit s%32 of word s/32)
//   state arrays   int64  [R*T*S], bools uint8 [R*T*S] (updated in place)
//   outputs        int32 column arrays, capacity >= popcount(send&valid)
// Walk order matches np.nonzero: ascending (r, t, k, s).

#include <cstdint>

namespace {

constexpr int64_t M16 = 0xFFFF;
constexpr int64_t M32 = 0xFFFFFFFFll;
constexpr int64_t M15 = 0x7FFF;
constexpr int64_t M8 = 0xFF;
constexpr int64_t M5 = 0x1F;
constexpr int64_t REANCHOR_TS_THRESH = 900000;  // ops/rtpmunger.py
constexpr int64_t FALLBACK_TS_JUMP = 3000;

inline int64_t sdiff32(int64_t a, int64_t b) {
  int64_t d = (a - b + (1ll << 31)) & M32;
  return d - (1ll << 31);
}

}  // namespace

extern "C" {

// Returns the number of egress entries written, or -1 if cap would
// overflow. The capacity check happens in a COUNTING pre-pass before any
// state mutation: a mid-walk bailout would leave the munger offsets
// half-advanced, and the caller's fallback would then double-apply the
// tick (state corruption on every walked lane).
//
// -2 is the invariant-violation code: the mid-walk overflow guard fired
// AFTER mutation began (the pre-pass can only overcount — it includes
// ghost bits at s >= S that the walk skips — so this should be
// unreachable). It is distinct from -1 on purpose: -1 means "nothing
// touched, fall back to the dense path", while -2 means "state already
// half-advanced, a fallback would double-apply" — the Python wrapper
// raises on it instead of falling back.
int64_t munge_walk(
    int32_t R, int32_t T, int32_t K, int32_t S, int32_t W,
    const uint32_t* send_bits, const uint32_t* drop_bits,
    const uint32_t* switch_bits,
    const int32_t* sn, const int32_t* ts, const int32_t* ts_jump,
    const int32_t* pid, const int32_t* tl0, const int32_t* ki,
    const uint8_t* begin_pic, const uint8_t* valid,
    int64_t* st_sn_off, int64_t* st_ts_off, int64_t* st_last_sn,
    int64_t* st_last_ts, uint8_t* st_started, uint8_t* st_aligned,
    int64_t* st_pid_off, int64_t* st_tl0_off, int64_t* st_ki_off,
    int64_t* st_last_pid, int64_t* st_last_tl0, int64_t* st_last_ki,
    uint8_t* st_v_started,
    int32_t* out_rooms, int32_t* out_tracks, int32_t* out_ks,
    int32_t* out_subs, int32_t* out_sn, int32_t* out_ts, int32_t* out_pid,
    int32_t* out_tl0, int32_t* out_ki, int64_t cap) {
  int64_t need = 0;
  const int64_t words = (int64_t)R * T * K * W;
  for (int64_t rtk = 0; rtk < words / W; ++rtk) {
    if (!valid[rtk]) continue;
    for (int32_t w = 0; w < W; ++w) {
      need += __builtin_popcount(send_bits[rtk * W + w]);
    }
    if (need > cap) return -1;  // nothing mutated yet
  }
  int64_t n = 0;
  for (int32_t r = 0; r < R; ++r) {
    for (int32_t t = 0; t < T; ++t) {
      const int64_t rt = (int64_t)r * T + t;
      const int64_t pk_base = rt * K;
      const int64_t st_base = rt * S;
      for (int32_t k = 0; k < K; ++k) {
        if (!valid[pk_base + k]) continue;
        const int64_t wb = (pk_base + k) * W;
        // Visit only lanes with a send or drop bit (switch ⊆ send).
        bool any = false;
        for (int32_t w = 0; w < W; ++w) {
          if (send_bits[wb + w] | drop_bits[wb + w]) { any = true; break; }
        }
        if (!any) continue;
        const int64_t p_sn = (int64_t)(uint32_t)sn[pk_base + k] & M16;
        const int64_t p_ts = (int64_t)(uint32_t)ts[pk_base + k] & M32;
        const int64_t p_jump = ts_jump[pk_base + k];
        const bool pkt_aligned = p_jump < 0;
        const int64_t jump_eff = pkt_aligned ? FALLBACK_TS_JUMP : p_jump;
        const int64_t p_pid = (int64_t)(uint32_t)pid[pk_base + k] & M15;
        const int64_t p_tl0 = (int64_t)(uint32_t)tl0[pk_base + k] & M8;
        const int64_t p_ki = (int64_t)(uint32_t)ki[pk_base + k] & M5;
        const bool bp = begin_pic[pk_base + k] != 0;
        for (int32_t w = 0; w < W; ++w) {
          uint32_t bits = send_bits[wb + w] | drop_bits[wb + w];
          while (bits) {
            const int32_t b = __builtin_ctz(bits);
            bits &= bits - 1;
            const int32_t s = w * 32 + b;
            if (s >= S) break;
            const uint32_t m = 1u << b;
            const bool fwd = (send_bits[wb + w] & m) != 0;
            const bool drp = !fwd && (drop_bits[wb + w] & m) != 0;
            const bool sw = fwd && (switch_bits[wb + w] & m) != 0;
            const int64_t i = st_base + s;

            // ---- rtpmunger step (runtime/munge.py apply_dense) --------
            const bool fresh = fwd && !st_started[i];
            const bool resync = sw && st_started[i];
            if (resync) {
              st_sn_off[i] = (p_sn - ((st_last_sn[i] + 1) & M16)) & M16;
              int64_t sw_ts_off =
                  (p_ts - ((st_last_ts[i] + jump_eff) & M32)) & M32;
              if (pkt_aligned && st_aligned[i]) sw_ts_off = st_ts_off[i];
              st_ts_off[i] = sw_ts_off;
              st_aligned[i] = pkt_aligned;
            } else if (fresh) {
              st_sn_off[i] = 0;
              st_ts_off[i] = 0;
              st_aligned[i] = pkt_aligned;
            } else if (fwd && st_started[i]) {
              // Timeline shear guard (continuing forward only).
              const int64_t cur_out_ts = (p_ts - st_ts_off[i]) & M32;
              const int64_t shear = sdiff32(cur_out_ts, st_last_ts[i]);
              if (shear > REANCHOR_TS_THRESH || shear < -REANCHOR_TS_THRESH) {
                st_ts_off[i] =
                    (p_ts - ((st_last_ts[i] + FALLBACK_TS_JUMP) & M32)) & M32;
                st_aligned[i] = pkt_aligned;
              }
            }
            const int64_t o_sn = (p_sn - st_sn_off[i]) & M16;
            const int64_t o_ts = (p_ts - st_ts_off[i]) & M32;
            if (fwd) {
              st_last_sn[i] = o_sn;
              st_last_ts[i] = o_ts;
            }
            if (drp && st_started[i]) {
              st_sn_off[i] = (st_sn_off[i] + 1) & M16;
            }
            if (fwd) st_started[i] = 1;

            // ---- vp8 step ---------------------------------------------
            const bool v_fresh = fwd && !st_v_started[i];
            const bool v_resync = sw && st_v_started[i];
            if (v_resync) {
              st_pid_off[i] = (p_pid - ((st_last_pid[i] + 1) & M15)) & M15;
              st_tl0_off[i] = (p_tl0 - st_last_tl0[i] - 1) & M8;
              st_ki_off[i] = (p_ki - st_last_ki[i] - 1) & M5;
            } else if (v_fresh) {
              st_pid_off[i] = 0;
              st_tl0_off[i] = 0;
              st_ki_off[i] = 0;
            }
            const int64_t o_pid = (p_pid - st_pid_off[i]) & M15;
            const int64_t o_tl0 = (p_tl0 - st_tl0_off[i]) & M8;
            const int64_t o_ki = (p_ki - st_ki_off[i]) & M5;
            if (fwd && bp) {
              st_last_pid[i] = o_pid;
              st_last_tl0[i] = o_tl0;
              st_last_ki[i] = o_ki;
            }
            if (drp && bp && st_v_started[i]) {
              st_pid_off[i] = (st_pid_off[i] + 1) & M15;
            }
            if (fwd) st_v_started[i] = 1;

            if (fwd) {
              // Post-mutation guard: see -2 contract in the header comment.
              if (n >= cap) return -2;
              out_rooms[n] = r;
              out_tracks[n] = t;
              out_ks[n] = k;
              out_subs[n] = s;
              out_sn[n] = (int32_t)o_sn;
              out_ts[n] = (int32_t)(uint32_t)o_ts;
              out_pid[n] = (int32_t)o_pid;
              out_tl0[n] = (int32_t)o_tl0;
              out_ki[n] = (int32_t)o_ki;
              ++n;
            }
          }
        }
      }
    }
  }
  return n;
}

}  // extern "C"
