// Host-side munge walker: expand bit-packed send/drop/switch masks and
// apply the SN/TS/VP8 offset rewrites in one pass.
//
// Reference parity: pkg/sfu/rtpmunger.go UpdateAndGetSnTs/PacketDropped and
// pkg/sfu/codecmunger/vp8.go UpdateAndGet — the per-packet CPU work the
// reference does in DownTrack.WriteRTP. Semantics are pinned bit-identical
// to ops/rtpmunger.py + ops/vp8.py (the jax scan spec) by
// tests/test_host_munge.py; the numpy implementation in runtime/munge.py
// is the readable fallback.
//
// Layout contract (see runtime/munge.py HostMunger):
//   packet fields  int32  [R*T*K]          (row-major r, t, k)
//   mask words     uint32 [R*T*K*W]        (bit s%32 of word s/32)
//   state arrays   int64  [R*T*S], bools uint8 [R*T*S] (updated in place)
//   outputs        int32 column arrays, capacity >= popcount(send&valid)
// Walk order matches np.nonzero: ascending (r, t, k, s).
//
// Sharding (munge_walk_multi): the egress plane partitions the room axis
// into contiguous ranges, one per worker shard. State rows are indexed
// [R, T, S], so whole-room ownership makes every state write disjoint
// across shards; per-shard outputs are written at exact prefix-sum bases
// so the concatenated result is bit-identical to a single walk — shard
// count never changes the output (pinned by tests/test_egress_plane.py).

#include <atomic>
#include <cstdint>
#include <thread>
#include <vector>

#include <time.h>

namespace {

constexpr int64_t M16 = 0xFFFF;
constexpr int64_t M32 = 0xFFFFFFFFll;
constexpr int64_t M15 = 0x7FFF;
constexpr int64_t M8 = 0xFF;
constexpr int64_t M5 = 0x1F;
constexpr int64_t REANCHOR_TS_THRESH = 900000;  // ops/rtpmunger.py
constexpr int64_t FALLBACK_TS_JUMP = 3000;

// Bump when the exported symbol set or any signature changes; the ctypes
// loader and tools/check.py compare it against the Python-side constant.
constexpr int32_t MUNGE_ABI = 2;

inline int64_t sdiff32(int64_t a, int64_t b) {
  int64_t d = (a - b + (1ll << 31)) & M32;
  return d - (1ll << 31);
}

inline int64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (int64_t)ts.tv_sec * 1000000000ll + ts.tv_nsec;
}

struct WalkArgs {
  int32_t R, T, K, S, W;
  const uint32_t* send_bits;
  const uint32_t* drop_bits;
  const uint32_t* switch_bits;
  const int32_t* sn;
  const int32_t* ts;
  const int32_t* ts_jump;
  const int32_t* pid;
  const int32_t* tl0;
  const int32_t* ki;
  const uint8_t* begin_pic;
  const uint8_t* valid;
  int64_t* st_sn_off;
  int64_t* st_ts_off;
  int64_t* st_last_sn;
  int64_t* st_last_ts;
  uint8_t* st_started;
  uint8_t* st_aligned;
  int64_t* st_pid_off;
  int64_t* st_tl0_off;
  int64_t* st_ki_off;
  int64_t* st_last_pid;
  int64_t* st_last_tl0;
  int64_t* st_last_ki;
  uint8_t* st_v_started;
  int32_t* out_rooms;
  int32_t* out_tracks;
  int32_t* out_ks;
  int32_t* out_subs;
  int32_t* out_sn;
  int32_t* out_ts;
  int32_t* out_pid;
  int32_t* out_tl0;
  int32_t* out_ki;
};

// Exact count of egress entries rooms [r_lo, r_hi) will emit: send bits on
// valid lanes, ghost bits (s >= S) masked out of the last word so shard
// output bases computed from these counts leave no holes.
int64_t count_range(const WalkArgs& a, int32_t r_lo, int32_t r_hi) {
  const int32_t tail = a.S % 32;
  const uint32_t last_mask = tail ? ((1u << tail) - 1) : 0xFFFFFFFFu;
  int64_t need = 0;
  const int64_t lo = (int64_t)r_lo * a.T * a.K;
  const int64_t hi = (int64_t)r_hi * a.T * a.K;
  for (int64_t rtk = lo; rtk < hi; ++rtk) {
    if (!a.valid[rtk]) continue;
    for (int32_t w = 0; w < a.W; ++w) {
      uint32_t bits = a.send_bits[rtk * a.W + w];
      if (w == a.W - 1) bits &= last_mask;
      need += __builtin_popcount(bits);
    }
  }
  return need;
}

// Walk rooms [r_lo, r_hi), writing entries at out index `base`. Returns
// entries written, or -2 when the post-mutation guard fires (state already
// half-advanced — see the -2 contract on munge_walk below).
int64_t walk_range(const WalkArgs& a, int32_t r_lo, int32_t r_hi,
                   int64_t base, int64_t cap) {
  const int32_t T = a.T, K = a.K, S = a.S, W = a.W;
  int64_t n = base;
  const int64_t lim = base + cap;
  for (int32_t r = r_lo; r < r_hi; ++r) {
    for (int32_t t = 0; t < T; ++t) {
      const int64_t rt = (int64_t)r * T + t;
      const int64_t pk_base = rt * K;
      const int64_t st_base = rt * S;
      for (int32_t k = 0; k < K; ++k) {
        if (!a.valid[pk_base + k]) continue;
        const int64_t wb = (pk_base + k) * W;
        // Visit only lanes with a send or drop bit (switch ⊆ send).
        bool any = false;
        for (int32_t w = 0; w < W; ++w) {
          if (a.send_bits[wb + w] | a.drop_bits[wb + w]) { any = true; break; }
        }
        if (!any) continue;
        const int64_t p_sn = (int64_t)(uint32_t)a.sn[pk_base + k] & M16;
        const int64_t p_ts = (int64_t)(uint32_t)a.ts[pk_base + k] & M32;
        const int64_t p_jump = a.ts_jump[pk_base + k];
        const bool pkt_aligned = p_jump < 0;
        const int64_t jump_eff = pkt_aligned ? FALLBACK_TS_JUMP : p_jump;
        const int64_t p_pid = (int64_t)(uint32_t)a.pid[pk_base + k] & M15;
        const int64_t p_tl0 = (int64_t)(uint32_t)a.tl0[pk_base + k] & M8;
        const int64_t p_ki = (int64_t)(uint32_t)a.ki[pk_base + k] & M5;
        const bool bp = a.begin_pic[pk_base + k] != 0;
        for (int32_t w = 0; w < W; ++w) {
          uint32_t bits = a.send_bits[wb + w] | a.drop_bits[wb + w];
          while (bits) {
            const int32_t b = __builtin_ctz(bits);
            bits &= bits - 1;
            const int32_t s = w * 32 + b;
            if (s >= S) break;
            const uint32_t m = 1u << b;
            const bool fwd = (a.send_bits[wb + w] & m) != 0;
            const bool drp = !fwd && (a.drop_bits[wb + w] & m) != 0;
            const bool sw = fwd && (a.switch_bits[wb + w] & m) != 0;
            const int64_t i = st_base + s;

            // ---- rtpmunger step (runtime/munge.py apply_dense) --------
            const bool fresh = fwd && !a.st_started[i];
            const bool resync = sw && a.st_started[i];
            if (resync) {
              a.st_sn_off[i] = (p_sn - ((a.st_last_sn[i] + 1) & M16)) & M16;
              int64_t sw_ts_off =
                  (p_ts - ((a.st_last_ts[i] + jump_eff) & M32)) & M32;
              if (pkt_aligned && a.st_aligned[i]) sw_ts_off = a.st_ts_off[i];
              a.st_ts_off[i] = sw_ts_off;
              a.st_aligned[i] = pkt_aligned;
            } else if (fresh) {
              a.st_sn_off[i] = 0;
              a.st_ts_off[i] = 0;
              a.st_aligned[i] = pkt_aligned;
            } else if (fwd && a.st_started[i]) {
              // Timeline shear guard (continuing forward only).
              const int64_t cur_out_ts = (p_ts - a.st_ts_off[i]) & M32;
              const int64_t shear = sdiff32(cur_out_ts, a.st_last_ts[i]);
              if (shear > REANCHOR_TS_THRESH || shear < -REANCHOR_TS_THRESH) {
                a.st_ts_off[i] =
                    (p_ts - ((a.st_last_ts[i] + FALLBACK_TS_JUMP) & M32)) & M32;
                a.st_aligned[i] = pkt_aligned;
              }
            }
            const int64_t o_sn = (p_sn - a.st_sn_off[i]) & M16;
            const int64_t o_ts = (p_ts - a.st_ts_off[i]) & M32;
            if (fwd) {
              a.st_last_sn[i] = o_sn;
              a.st_last_ts[i] = o_ts;
            }
            if (drp && a.st_started[i]) {
              a.st_sn_off[i] = (a.st_sn_off[i] + 1) & M16;
            }
            if (fwd) a.st_started[i] = 1;

            // ---- vp8 step ---------------------------------------------
            const bool v_fresh = fwd && !a.st_v_started[i];
            const bool v_resync = sw && a.st_v_started[i];
            if (v_resync) {
              a.st_pid_off[i] = (p_pid - ((a.st_last_pid[i] + 1) & M15)) & M15;
              a.st_tl0_off[i] = (p_tl0 - a.st_last_tl0[i] - 1) & M8;
              a.st_ki_off[i] = (p_ki - a.st_last_ki[i] - 1) & M5;
            } else if (v_fresh) {
              a.st_pid_off[i] = 0;
              a.st_tl0_off[i] = 0;
              a.st_ki_off[i] = 0;
            }
            const int64_t o_pid = (p_pid - a.st_pid_off[i]) & M15;
            const int64_t o_tl0 = (p_tl0 - a.st_tl0_off[i]) & M8;
            const int64_t o_ki = (p_ki - a.st_ki_off[i]) & M5;
            if (fwd && bp) {
              a.st_last_pid[i] = o_pid;
              a.st_last_tl0[i] = o_tl0;
              a.st_last_ki[i] = o_ki;
            }
            if (drp && bp && a.st_v_started[i]) {
              a.st_pid_off[i] = (a.st_pid_off[i] + 1) & M15;
            }
            if (fwd) a.st_v_started[i] = 1;

            if (fwd) {
              // Post-mutation guard: see -2 contract on munge_walk.
              if (n >= lim) return -2;
              a.out_rooms[n] = r;
              a.out_tracks[n] = t;
              a.out_ks[n] = k;
              a.out_subs[n] = s;
              a.out_sn[n] = (int32_t)o_sn;
              a.out_ts[n] = (int32_t)(uint32_t)o_ts;
              a.out_pid[n] = (int32_t)o_pid;
              a.out_tl0[n] = (int32_t)o_tl0;
              a.out_ki[n] = (int32_t)o_ki;
              ++n;
            }
          }
        }
      }
    }
  }
  return n - base;
}

}  // namespace

extern "C" {

int32_t munge_abi_version(void) { return MUNGE_ABI; }

// Returns the number of egress entries written, or -1 if cap would
// overflow. The capacity check happens in a COUNTING pre-pass before any
// state mutation: a mid-walk bailout would leave the munger offsets
// half-advanced, and the caller's fallback would then double-apply the
// tick (state corruption on every walked lane).
//
// -2 is the invariant-violation code: the mid-walk overflow guard fired
// AFTER mutation began (the pre-pass counts exactly — ghost bits at
// s >= S are masked — so this should be unreachable). It is distinct from
// -1 on purpose: -1 means "nothing touched, fall back to the dense path",
// while -2 means "state already half-advanced, a fallback would
// double-apply" — the Python wrapper raises on it instead of falling back.
int64_t munge_walk(
    int32_t R, int32_t T, int32_t K, int32_t S, int32_t W,
    const uint32_t* send_bits, const uint32_t* drop_bits,
    const uint32_t* switch_bits,
    const int32_t* sn, const int32_t* ts, const int32_t* ts_jump,
    const int32_t* pid, const int32_t* tl0, const int32_t* ki,
    const uint8_t* begin_pic, const uint8_t* valid,
    int64_t* st_sn_off, int64_t* st_ts_off, int64_t* st_last_sn,
    int64_t* st_last_ts, uint8_t* st_started, uint8_t* st_aligned,
    int64_t* st_pid_off, int64_t* st_tl0_off, int64_t* st_ki_off,
    int64_t* st_last_pid, int64_t* st_last_tl0, int64_t* st_last_ki,
    uint8_t* st_v_started,
    int32_t* out_rooms, int32_t* out_tracks, int32_t* out_ks,
    int32_t* out_subs, int32_t* out_sn, int32_t* out_ts, int32_t* out_pid,
    int32_t* out_tl0, int32_t* out_ki, int64_t cap) {
  WalkArgs a{R, T, K, S, W, send_bits, drop_bits, switch_bits,
             sn, ts, ts_jump, pid, tl0, ki, begin_pic, valid,
             st_sn_off, st_ts_off, st_last_sn, st_last_ts, st_started,
             st_aligned, st_pid_off, st_tl0_off, st_ki_off, st_last_pid,
             st_last_tl0, st_last_ki, st_v_started,
             out_rooms, out_tracks, out_ks, out_subs, out_sn, out_ts,
             out_pid, out_tl0, out_ki};
  if (count_range(a, 0, R) > cap) return -1;  // nothing mutated yet
  return walk_range(a, 0, R, 0, cap);
}

// Sharded walk: n_shards contiguous room ranges [r_lo[i], r_hi[i]),
// walked concurrently. Phase 1 counts each shard exactly; after a
// barrier, outputs land at prefix-sum bases so the concatenation is
// bit-identical to one munge_walk over [0, R). Same return contract as
// munge_walk (-1 = cap overflow before any mutation; -2 = post-mutation
// guard, should be unreachable). shard_counts[i] receives each shard's
// entry count and shard_ns[i] its walk wall time (phase 2 only).
int64_t munge_walk_multi(
    int32_t n_shards, const int32_t* r_lo, const int32_t* r_hi,
    int64_t* shard_counts, int64_t* shard_ns,
    int32_t R, int32_t T, int32_t K, int32_t S, int32_t W,
    const uint32_t* send_bits, const uint32_t* drop_bits,
    const uint32_t* switch_bits,
    const int32_t* sn, const int32_t* ts, const int32_t* ts_jump,
    const int32_t* pid, const int32_t* tl0, const int32_t* ki,
    const uint8_t* begin_pic, const uint8_t* valid,
    int64_t* st_sn_off, int64_t* st_ts_off, int64_t* st_last_sn,
    int64_t* st_last_ts, uint8_t* st_started, uint8_t* st_aligned,
    int64_t* st_pid_off, int64_t* st_tl0_off, int64_t* st_ki_off,
    int64_t* st_last_pid, int64_t* st_last_tl0, int64_t* st_last_ki,
    uint8_t* st_v_started,
    int32_t* out_rooms, int32_t* out_tracks, int32_t* out_ks,
    int32_t* out_subs, int32_t* out_sn, int32_t* out_ts, int32_t* out_pid,
    int32_t* out_tl0, int32_t* out_ki, int64_t cap) {
  WalkArgs a{R, T, K, S, W, send_bits, drop_bits, switch_bits,
             sn, ts, ts_jump, pid, tl0, ki, begin_pic, valid,
             st_sn_off, st_ts_off, st_last_sn, st_last_ts, st_started,
             st_aligned, st_pid_off, st_tl0_off, st_ki_off, st_last_pid,
             st_last_tl0, st_last_ki, st_v_started,
             out_rooms, out_tracks, out_ks, out_subs, out_sn, out_ts,
             out_pid, out_tl0, out_ki};
  if (n_shards <= 0) return 0;
  if (n_shards == 1) {
    shard_counts[0] = count_range(a, r_lo[0], r_hi[0]);
    if (shard_counts[0] > cap) return -1;
    const int64_t t0 = now_ns();
    const int64_t n = walk_range(a, r_lo[0], r_hi[0], 0, cap);
    shard_ns[0] = now_ns() - t0;
    return n;
  }
  // One spawn per call with a spin barrier between count and walk: the
  // count phase is sub-100 µs at wire shapes, so a condvar round trip
  // would dominate it.
  std::atomic<int> counted{0};
  std::atomic<int> verdict{0};  // 0 = pending, 1 = go, -1 = overflow
  std::vector<int64_t> bases(n_shards, 0);
  std::vector<int64_t> results(n_shards, 0);
  std::vector<std::thread> ths;
  for (int w = 0; w < n_shards; ++w) {
    ths.emplace_back([&, w] {
      shard_counts[w] = count_range(a, r_lo[w], r_hi[w]);
      if (counted.fetch_add(1) + 1 == n_shards) {
        int64_t total = 0;
        for (int i = 0; i < n_shards; ++i) {
          bases[i] = total;
          total += shard_counts[i];
        }
        verdict.store(total > cap ? -1 : 1, std::memory_order_release);
      }
      int v;
      while ((v = verdict.load(std::memory_order_acquire)) == 0) {}
      if (v < 0) return;  // overflow: no shard mutates anything
      const int64_t t0 = now_ns();
      results[w] = walk_range(a, r_lo[w], r_hi[w], bases[w], shard_counts[w]);
      shard_ns[w] = now_ns() - t0;
    });
  }
  for (auto& t : ths) t.join();
  if (verdict.load() < 0) return -1;
  int64_t total = 0;
  for (int w = 0; w < n_shards; ++w) {
    if (results[w] < 0) return -2;
    total += results[w];
  }
  return total;
}

}  // extern "C"
