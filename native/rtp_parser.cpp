// Batch RTP parser — the native half of the ingest path.
//
// Reference parity: the parsing the reference does per packet in Go inside
// buffer.Buffer.calc (pkg/sfu/buffer/buffer.go:417-491: header fields,
// RFC 8285 one-byte header extensions incl. RFC 6464 audio level, VP8
// payload descriptor via buffer/vp8.go). Here it is a C++ batch routine:
// the UDP receiver hands a packed buffer of N datagrams and gets back
// column arrays ready to memcpy into the IngestBuffer's numpy tensors —
// one native call per receive batch instead of per-packet Go allocations.
//
// Build: g++ -O2 -shared -fPIC -o librtp_parser.so rtp_parser.cpp
// ABI: plain C, loaded via ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>

extern "C" {

// One parsed packet's fixed-width fields (keep in sync with native/__init__.py).
struct ParsedPacket {
  uint32_t ssrc;
  uint16_t sn;
  uint8_t pt;
  uint8_t marker;
  uint32_t ts;
  int32_t payload_off;   // offset of payload within the datagram
  int32_t payload_len;   // -1 on parse error
  uint8_t audio_level;   // RFC 6464 dBov (127 if absent)
  uint8_t voice;         // RFC 6464 V bit
  // VP8 payload descriptor (valid when is_vp8 != 0):
  uint8_t is_vp8;
  uint8_t keyframe;      // P bit == 0 on first payload byte & begin_pic
  uint8_t begin_pic;     // S bit & PID==0
  uint8_t tid;           // temporal id
  uint8_t layer_sync;    // Y bit
  int32_t picture_id;    // -1 if absent
  int32_t tl0picidx;     // -1 if absent
  int32_t keyidx;        // -1 if absent
  // AV1 dependency-descriptor header extension (RFC 8285), when
  // dd_ext_id > 0: offset/length of the DD payload within the batch
  // buffer (-1/0 when absent). Descriptor decode is host-side
  // (runtime/dd.py) — structures arrive only on keyframes.
  int32_t dd_off;
  int32_t dd_len;
  // Frame-end marker: RTP M bit by default; the VP9 descriptor's E bit
  // (per-spatial-layer frame end — vp9.go's downswitch boundary) where
  // parsed.
  uint8_t end_frame;
  // Plain-VP9 spatial layer id from the payload descriptor (SVC without
  // the DD extension — buffer.go:599-671 VP9 parse path); -1 if absent.
  int8_t sid;
};

// Parse `n` datagrams packed back-to-back in `buf`; `offsets`/`lengths`
// give each datagram's position. `audio_level_ext` is the negotiated
// RFC 8285 id for the audio-level extension (0 = disabled); packets whose
// PT is in `vp8_pts` (bitmask over 0..127) get VP8 descriptor parsing.
// Returns the number of successfully parsed packets.
int parse_rtp_batch(const uint8_t* buf, const int32_t* offsets,
                    const int32_t* lengths, int n, int audio_level_ext,
                    const uint8_t* vp8_pt_mask, ParsedPacket* out,
                    int dd_ext_id, const uint8_t* vp9_pt_mask,
                    const uint8_t* h264_pt_mask) {
  int ok = 0;
  for (int i = 0; i < n; i++) {
    const uint8_t* p = buf + offsets[i];
    int len = lengths[i];
    ParsedPacket& o = out[i];
    std::memset(&o, 0, sizeof(o));
    o.audio_level = 127;
    o.picture_id = -1;
    o.tl0picidx = -1;
    o.keyidx = -1;
    o.payload_len = -1;
    o.dd_off = -1;
    o.sid = -1;
    if (len < 12) continue;
    uint8_t v = p[0] >> 6;
    if (v != 2) continue;
    int cc = p[0] & 0x0F;
    bool has_ext = (p[0] >> 4) & 1;
    bool has_pad = (p[0] >> 5) & 1;
    o.marker = p[1] >> 7;
    o.pt = p[1] & 0x7F;
    o.sn = (uint16_t)((p[2] << 8) | p[3]);
    o.ts = ((uint32_t)p[4] << 24) | ((uint32_t)p[5] << 16) |
           ((uint32_t)p[6] << 8) | p[7];
    o.ssrc = ((uint32_t)p[8] << 24) | ((uint32_t)p[9] << 16) |
             ((uint32_t)p[10] << 8) | p[11];
    int off = 12 + cc * 4;
    if (off > len) continue;

    if (has_ext) {
      if (off + 4 > len) continue;
      uint16_t profile = (uint16_t)((p[off] << 8) | p[off + 1]);
      int ext_words = (p[off + 2] << 8) | p[off + 3];
      int ext_len = ext_words * 4;
      int ext_off = off + 4;
      if (ext_off + ext_len > len) continue;
      if (profile == 0xBEDE) {
        // RFC 8285 one-byte header extensions.
        int q = ext_off;
        int end = ext_off + ext_len;
        while (q < end) {
          uint8_t b = p[q];
          if (b == 0) { q++; continue; }  // padding
          int id = b >> 4;
          int elen = (b & 0x0F) + 1;
          if (id == 15) break;
          if (q + 1 + elen > end) break;
          if (audio_level_ext > 0 && id == audio_level_ext && elen >= 1) {
            o.voice = p[q + 1] >> 7;
            o.audio_level = p[q + 1] & 0x7F;
          }
          if (dd_ext_id > 0 && id == dd_ext_id) {
            o.dd_off = offsets[i] + q + 1;
            o.dd_len = elen;
          }
          q += 1 + elen;
        }
      } else if ((profile & 0xFFF0) == 0x1000) {
        // RFC 8285 two-byte header extensions (DD structures can exceed
        // the one-byte form's 16-byte data cap).
        int q = ext_off;
        int end = ext_off + ext_len;
        while (q + 1 < end) {
          uint8_t id = p[q];
          if (id == 0) { q++; continue; }  // padding
          int elen = p[q + 1];
          if (q + 2 + elen > end) break;
          if (audio_level_ext > 0 && id == audio_level_ext && elen >= 1) {
            o.voice = p[q + 2] >> 7;
            o.audio_level = p[q + 2] & 0x7F;
          }
          if (dd_ext_id > 0 && id == dd_ext_id) {
            o.dd_off = offsets[i] + q + 2;
            o.dd_len = elen;
          }
          q += 2 + elen;
        }
      }
      off = ext_off + ext_len;
    }

    int pad = 0;
    if (has_pad && len > off) pad = p[len - 1];
    int payload_len = len - off - pad;
    if (payload_len < 0) continue;
    o.payload_off = off;
    o.payload_len = payload_len;
    o.end_frame = o.marker;

    // VP8 payload descriptor (RFC 7741; buffer/vp8.go Unmarshal).
    if (vp8_pt_mask[o.pt >> 3] & (1 << (o.pt & 7))) {
      const uint8_t* d = p + off;
      int dl = payload_len;
      if (dl < 1) continue;
      o.is_vp8 = 1;
      int q = 0;
      uint8_t b0 = d[q++];
      bool X = b0 & 0x80;
      bool S = (b0 >> 4) & 1;
      uint8_t pid3 = b0 & 0x07;
      o.begin_pic = (S && pid3 == 0) ? 1 : 0;
      if (X) {
        if (q >= dl) continue;
        uint8_t xb = d[q++];
        bool I = xb & 0x80, L = xb & 0x40, T = xb & 0x20, K = xb & 0x10;
        if (I) {
          if (q >= dl) continue;
          uint8_t pb = d[q++];
          if (pb & 0x80) {  // 15-bit picture id
            if (q >= dl) continue;
            o.picture_id = ((pb & 0x7F) << 8) | d[q++];
          } else {
            o.picture_id = pb & 0x7F;
          }
        }
        if (L) {
          if (q >= dl) continue;
          o.tl0picidx = d[q++];
        }
        if (T || K) {
          if (q >= dl) continue;
          uint8_t tk = d[q++];
          o.tid = tk >> 6;
          o.layer_sync = (tk >> 5) & 1;
          o.keyidx = tk & 0x1F;
        }
      }
      // Keyframe: P bit of the first VP8 payload byte (after descriptor),
      // only meaningful on the first packet of the picture.
      if (o.begin_pic && q < dl) o.keyframe = (d[q] & 0x01) == 0 ? 1 : 0;
    } else if (vp9_pt_mask[o.pt >> 3] & (1 << (o.pt & 7))) {
      // VP9 payload descriptor (draft-ietf-payload-vp9; the selection
      // fields of pkg/sfu/buffer/buffer.go:599-671's VP9 parse feeding
      // videolayerselector/vp9.go:43).
      const uint8_t* d = p + off;
      int dl = payload_len;
      if (dl < 1) continue;
      int q = 0;
      uint8_t b0 = d[q++];
      bool I = b0 & 0x80, P = b0 & 0x40, L = b0 & 0x20, F = b0 & 0x10;
      bool B = b0 & 0x08, E = b0 & 0x04;
      o.begin_pic = B ? 1 : 0;
      o.end_frame = E ? 1 : 0;
      if (I) {
        if (q >= dl) continue;
        uint8_t pb = d[q++];
        if (pb & 0x80) {
          if (q >= dl) continue;
          o.picture_id = ((pb & 0x7F) << 8) | d[q++];
        } else {
          o.picture_id = pb & 0x7F;
        }
      }
      bool have_layer = false;
      if (L) {
        if (q >= dl) continue;
        uint8_t lb = d[q++];
        o.tid = lb >> 5;
        o.layer_sync = (lb >> 4) & 1;  // U: switching-up point
        o.sid = (int8_t)((lb >> 1) & 0x07);
        have_layer = true;
        if (!F) {
          if (q >= dl) continue;
          o.tl0picidx = d[q++];
        }
      }
      // vp9.go keyframe: !P && B && (SID == 0 || no layer indices).
      if (!P && B && (!have_layer || o.sid == 0)) o.keyframe = 1;
      if (o.keyframe) o.layer_sync = 1;
    } else if (h264_pt_mask[o.pt >> 3] & (1 << (o.pt & 7))) {
      // H264 (RFC 6184): NALU type drives keyframe detection — IDR (5)
      // or SPS (7), also inside STAP-A aggregates and at FU-A starts
      // (the reference's buffer.go:599-671 H264 keyframe scan).
      const uint8_t* d = p + off;
      int dl = payload_len;
      if (dl < 1) continue;
      uint8_t ntype = d[0] & 0x1F;
      if (ntype >= 1 && ntype <= 23) {           // single NALU
        o.begin_pic = 1;
        if (ntype == 5 || ntype == 7) o.keyframe = 1;
      } else if (ntype == 24) {                  // STAP-A
        o.begin_pic = 1;
        int q = 1;
        while (q + 2 <= dl) {
          int nsz = (d[q] << 8) | d[q + 1];
          if (q + 2 + nsz > dl || nsz < 1) break;
          uint8_t t = d[q + 2] & 0x1F;
          if (t == 5 || t == 7) o.keyframe = 1;
          q += 2 + nsz;
        }
      } else if ((ntype == 28 || ntype == 29) && dl >= 2) {  // FU-A/B
        uint8_t fu = d[1];
        bool start = fu & 0x80;
        uint8_t t = fu & 0x1F;
        o.begin_pic = start ? 1 : 0;
        if (start && (t == 5 || t == 7)) o.keyframe = 1;
      }
      if (o.keyframe) o.layer_sync = 1;
    }
    ok++;
  }
  return ok;
}

// Batch header rewrite for egress: patch SN/TS/SSRC in-place in the
// outgoing datagram buffer (the write half of the reference's
// DownTrack.WriteRTP header rewrite before pacing).
void rewrite_rtp_batch(uint8_t* buf, const int32_t* offsets, int n,
                       const uint16_t* sns, const uint32_t* tss,
                       const uint32_t* ssrcs) {
  for (int i = 0; i < n; i++) {
    uint8_t* p = buf + offsets[i];
    p[2] = sns[i] >> 8;
    p[3] = sns[i] & 0xFF;
    p[4] = tss[i] >> 24; p[5] = (tss[i] >> 16) & 0xFF;
    p[6] = (tss[i] >> 8) & 0xFF; p[7] = tss[i] & 0xFF;
    p[8] = ssrcs[i] >> 24; p[9] = (ssrcs[i] >> 16) & 0xFF;
    p[10] = (ssrcs[i] >> 8) & 0xFF; p[11] = ssrcs[i] & 0xFF;
  }
}

// Full egress rewrite: SN/TS/SSRC header patch plus, for packets flagged
// vp8, an in-place rewrite of the VP8 payload descriptor's picture-id /
// TL0PICIDX / KEYIDX from the device munger's outputs — the byte-level
// half of codecmunger/vp8.go:161 UpdateAndGet. Field widths are preserved
// (a 7-bit picture-id slot takes the low 7 bits, a 15-bit slot the low
// 15; both remain contiguous because the munged sequence is contiguous),
// since an in-place rewrite cannot grow the descriptor. pid/tl0/keyidx
// values < 0 skip that field; fields absent from the descriptor are left
// untouched.
void rewrite_rtp_vp8_batch(uint8_t* buf, const int32_t* offsets,
                           const int32_t* lengths, int n,
                           const uint16_t* sns, const uint32_t* tss,
                           const uint32_t* ssrcs, const int32_t* pids,
                           const int32_t* tl0s, const int32_t* keyidxs,
                           const uint8_t* vp8_flags) {
  for (int i = 0; i < n; i++) {
    uint8_t* p = buf + offsets[i];
    int len = lengths[i];
    if (len < 12) continue;
    p[2] = sns[i] >> 8;
    p[3] = sns[i] & 0xFF;
    p[4] = tss[i] >> 24; p[5] = (tss[i] >> 16) & 0xFF;
    p[6] = (tss[i] >> 8) & 0xFF; p[7] = tss[i] & 0xFF;
    p[8] = ssrcs[i] >> 24; p[9] = (ssrcs[i] >> 16) & 0xFF;
    p[10] = (ssrcs[i] >> 8) & 0xFF; p[11] = ssrcs[i] & 0xFF;
    if (!vp8_flags[i]) continue;

    // Locate the payload (same walk as the parser: CSRCs + extension).
    int cc = p[0] & 0x0F;
    bool has_ext = (p[0] >> 4) & 1;
    int off = 12 + cc * 4;
    if (off > len) continue;
    if (has_ext) {
      if (off + 4 > len) continue;
      int ext_words = (p[off + 2] << 8) | p[off + 3];
      off += 4 + ext_words * 4;
      if (off > len) continue;
    }
    uint8_t* d = p + off;
    int dl = len - off;
    if (dl < 1) continue;

    // Walk + patch the VP8 payload descriptor (RFC 7741).
    int q = 0;
    uint8_t b0 = d[q++];
    if (!(b0 & 0x80)) continue;  // no X ⇒ no pid/tl0/keyidx fields
    if (q >= dl) continue;
    uint8_t xb = d[q++];
    bool I = xb & 0x80, L = xb & 0x40, T = xb & 0x20, K = xb & 0x10;
    if (I) {
      if (q >= dl) continue;
      if (d[q] & 0x80) {  // 15-bit picture id
        if (q + 1 >= dl) continue;
        if (pids[i] >= 0) {
          d[q] = 0x80 | ((pids[i] >> 8) & 0x7F);
          d[q + 1] = pids[i] & 0xFF;
        }
        q += 2;
      } else {  // 7-bit picture id
        if (pids[i] >= 0) d[q] = pids[i] & 0x7F;
        q += 1;
      }
    }
    if (L) {
      if (q >= dl) continue;
      if (tl0s[i] >= 0) d[q] = tl0s[i] & 0xFF;
      q += 1;
    }
    if (T || K) {
      if (q >= dl) continue;
      // Preserve TID/Y (packet-intrinsic), replace KEYIDX (munged).
      if (keyidxs[i] >= 0) d[q] = (d[q] & 0xE0) | (keyidxs[i] & 0x1F);
      q += 1;
    }
  }
}

// Concatenate blob[starts[i] .. starts[i]+lens[i]) into out. The payload-
// slab staging gather (ingest.push_batch): a plain memcpy loop beats both
// per-range Python slicing and numpy's repeat/arange index trick by ~50×
// at tick sizes. Returns total bytes written.
int64_t gather_ranges(const uint8_t* blob, const int64_t* starts,
                      const int64_t* lens, int n, uint8_t* out) {
  int64_t o = 0;
  for (int i = 0; i < n; i++) {
    int64_t l = lens[i];
    if (l <= 0) continue;
    std::memcpy(out + o, blob + starts[i], (size_t)l);
    o += l;
  }
  return o;
}

}  // extern "C"
