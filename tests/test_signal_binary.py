"""Binary signal framing: the negotiated compact wire alongside JSON.

Reference parity: pkg/service/wsprotocol.go — the reference speaks JSON or
protobuf per WS connection (SDKs use the binary form). This build's binary
mode is msgpack with stable numeric kind tags, negotiated via
`?signal=binary` or the "signal-binary" WS subprotocol; media msgpack
frames share the BINARY channel behind a one-byte discriminator.
"""

import asyncio
import json

import aiohttp
import msgpack

from livekit_server_tpu.protocol.signal import (
    REQUEST_KINDS,
    RESPONSE_KINDS,
    SignalRequest,
    SignalResponse,
    decode_signal_request_bin,
    decode_signal_response_bin,
    encode_signal_request,
    encode_signal_request_bin,
    encode_signal_response_bin,
    is_binary_signal_frame,
)
from tests.test_service import SignalClient, running_server, token


def test_binary_codec_roundtrip_all_kinds():
    payload = {"sid": "TR_x", "muted": True, "n": 7, "list": [1, 2], "s": "é"}
    for kind in sorted(REQUEST_KINDS):
        wire = encode_signal_request_bin(SignalRequest(kind, dict(payload)))
        assert is_binary_signal_frame(wire)
        back = decode_signal_request_bin(wire)
        assert back.kind == kind and back.data == payload
    for kind in sorted(RESPONSE_KINDS):
        wire = encode_signal_response_bin(SignalResponse(kind, dict(payload)))
        back = decode_signal_response_bin(wire)
        assert back.kind == kind and back.data == payload
    # The point of the binary wire: smaller than the JSON framing.
    req = SignalRequest("subscription", {"track_sids": ["TR_a", "TR_b"], "subscribe": True})
    assert len(encode_signal_request_bin(req)) < len(encode_signal_request(req))


def test_binary_frame_demux_never_collides_with_media():
    # Media frames are msgpack maps: first byte 0x80-0x8f or 0xde/0xdf.
    media = msgpack.packb({"cid": "mic", "sn": 1, "payload": b"x" * 40})
    assert not is_binary_signal_frame(media)
    big = msgpack.packb({f"k{i}": i for i in range(40)})  # map16 form
    assert not is_binary_signal_frame(big)
    assert not is_binary_signal_frame(b"")
    # Malformed binary signal frames raise, never crash into media parsing.
    for bad in (b"\x00", b"\x00\xc1", b"\x00" + msgpack.packb([999, {}]),
                b"\x00" + msgpack.packb({"not": "a pair"}),
                b"\x00" + msgpack.packb([1, "not-a-map"])):
        try:
            decode_signal_request_bin(bad)
            raise AssertionError(f"accepted {bad!r}")
        except ValueError:
            pass


class BinarySignalClient(SignalClient):
    """SignalClient speaking the negotiated binary signal wire."""

    def __init__(self, session, port):
        super().__init__(session, port)
        self.text_frames = 0

    async def connect(self, room: str, identity: str, query: str = "", **grant_kw):
        self.ws = await self.session.ws_connect(
            f"ws://127.0.0.1:{self.port}/rtc?access_token="
            f"{token(identity, room, **grant_kw)}&signal=binary{query}"
        )
        self._reader = asyncio.ensure_future(self._read())
        return await self.wait_for("join")

    async def _read(self):
        async for msg in self.ws:
            if msg.type == aiohttp.WSMsgType.TEXT:
                self.text_frames += 1
            elif msg.type == aiohttp.WSMsgType.BINARY:
                if is_binary_signal_frame(msg.data):
                    resp = decode_signal_response_bin(msg.data)
                    self.signals.append({resp.kind: resp.data})
                else:
                    self.media.append(msgpack.unpackb(msg.data, raw=False))

    async def send_signal(self, kind: str, data: dict):
        await self.ws.send_bytes(encode_signal_request_bin(SignalRequest(kind, data)))


async def test_binary_signal_end_to_end():
    """A binary-mode client joins, pings, publishes and receives media —
    every signal frame BINARY, zero TEXT frames from the server."""
    async with running_server() as server:
        async with aiohttp.ClientSession() as s:
            pub = BinarySignalClient(s, server.port)
            join = await pub.connect("binroom", "alice")
            assert join["participant"]["identity"] == "alice"

            sub = SignalClient(s, server.port)  # JSON client in the same room
            await sub.connect("binroom", "bob")

            await pub.send_signal("ping", {"timestamp": 42})
            pong = await pub.wait_for("pong")
            assert pong["last_ping_timestamp"] == 42

            # Media still flows on the shared BINARY channel.
            await pub.send_signal(
                "add_track", {"cid": "mic", "type": 0, "name": "m"}
            )
            for i in range(3):
                await pub.send_media(cid="mic", sn=10 + i, ts=960 * i,
                                     payload=b"opus" + bytes([i]),
                                     audio_level=30, frame_ms=20)
                await asyncio.sleep(0.05)
            media = await sub.wait_media(1)
            assert media[0]["payload"].startswith(b"opus")

            assert pub.text_frames == 0  # negotiated: no JSON fell through
            await pub.close()
            await sub.close()


async def test_binary_subprotocol_negotiation():
    """The WS subprotocol header selects binary mode without the query."""
    async with running_server() as server:
        async with aiohttp.ClientSession() as s:
            ws = await s.ws_connect(
                f"ws://127.0.0.1:{server.port}/rtc?access_token="
                f"{token('carol', 'subproto')}",
                protocols=("signal-binary",),
            )
            assert ws.protocol == "signal-binary"
            got_join = False
            async for msg in ws:
                if msg.type == aiohttp.WSMsgType.BINARY and is_binary_signal_frame(msg.data):
                    resp = decode_signal_response_bin(msg.data)
                    if resp.kind == "join":
                        got_join = True
                        break
            assert got_join
            await ws.close()
