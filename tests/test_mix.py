"""Batched audio mixing (ops/mix — the BASELINE config-2 MCU seat).

Reference parity note: the reference SFU never decodes/mixes
(pkg/sfu/audio/audiolevel.go is level detection only); this capability
is additive. Codec math is validated by exact G.711 roundtrips.
"""

import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.ops import mix


def test_ulaw_roundtrip_all_bytes():
    """encode(decode(b)) == b for every µ-law byte (both G.711 halves of
    the codec agree bit-exactly)."""
    b = np.arange(256, dtype=np.uint8)
    pcm = jnp.asarray(mix.ULAW_TABLE)[b]
    out = np.asarray(mix.encode_ulaw(pcm))
    # 0x7F/0xFF both decode to ±0-ish values that re-encode canonically;
    # G.711 has two zero codes — compare via decoded values instead.
    dec1 = mix.ULAW_TABLE[b]
    dec2 = mix.ULAW_TABLE[out]
    np.testing.assert_allclose(dec1, dec2, atol=1e-6)


def test_ulaw_quantization_error_bounded():
    rng = np.random.default_rng(0)
    x = rng.uniform(-0.99, 0.99, 4096).astype(np.float32)
    enc = np.asarray(mix.encode_ulaw(jnp.asarray(x)))
    dec = mix.ULAW_TABLE[enc]
    # µ-law SNR: error bounded by segment step (~1/16 of magnitude + bias)
    err = np.abs(dec - x)
    assert np.all(err <= np.maximum(np.abs(x) / 8.0, 0.02))


def test_alaw_decode_known_values():
    # A-law 0x55-inverted code for zero: 0xD5 / 0x55 decode near zero.
    assert abs(float(mix.ALAW_TABLE[0xD5])) < 0.01
    assert abs(float(mix.ALAW_TABLE[0x55])) < 0.01
    # Sign symmetry: codes differing only in the sign bit mirror.
    for c in (0x01, 0x33, 0x7F):
        a = float(mix.ALAW_TABLE[c ^ 0x80])
        b = float(mix.ALAW_TABLE[c])
        assert abs(a + b) < 1e-6


def test_decode_tick_codec_routing():
    payload = jnp.asarray(np.full((1, 2, 4), 0x42, np.uint8))
    codec = jnp.asarray([[mix.CODEC_PCMU, mix.CODEC_PCMA]])
    out = np.asarray(mix.decode_tick(payload, codec))
    assert abs(out[0, 0, 0] - mix.ULAW_TABLE[0x42]) < 1e-6
    assert abs(out[0, 1, 0] - mix.ALAW_TABLE[0x42]) < 1e-6


def test_mix_excludes_self_and_inactive():
    R, T, S, N = 1, 3, 2, 8
    pcm = np.zeros((R, T, N), np.float32)
    pcm[0, 0, :] = 0.1   # track 0: sub 0's own voice
    pcm[0, 1, :] = 0.2   # track 1: another speaker
    pcm[0, 2, :] = 0.4   # track 2: INACTIVE — must not mix
    level = jnp.asarray([[0.5, 0.6, 0.9]])
    active = jnp.asarray([[True, True, False]])
    sub_track = jnp.asarray([[0, 1]])   # sub0 publishes track0, sub1 track1
    gain = jnp.ones((R, T), jnp.float32)
    out = np.asarray(mix.mix_tick(pcm, level, active, sub_track, gain))
    # sub0 hears track1 only; sub1 hears track0 only (self + inactive cut)
    np.testing.assert_allclose(out[0, 0], np.tanh(pcm[0, 1]), atol=1e-6)
    np.testing.assert_allclose(out[0, 1], np.tanh(pcm[0, 0]), atol=1e-6)


def test_mix_top_k_gates_speakers():
    R, T, S, N = 1, 5, 1, 4
    pcm = np.ones((R, T, N), np.float32) * 0.01
    level = jnp.asarray([[0.1, 0.9, 0.8, 0.7, 0.05]])
    active = jnp.ones((R, T), bool)
    sub_track = jnp.asarray([[-1]])     # pure listener
    gain = jnp.ones((R, T), jnp.float32)
    out = np.asarray(mix.mix_tick(pcm, level, active, sub_track, gain, top_k=3))
    # exactly the 3 loudest tracks mixed: 3 × 0.01
    np.testing.assert_allclose(out[0, 0], np.tanh(0.03 * np.ones(N)), atol=1e-6)


def test_mix_room_batch_shape():
    """The production shape compiles and runs batched (einsum → MXU)."""
    R, T, S, N = 32, 8, 6, 240
    rng = np.random.default_rng(1)
    out = mix.mix_tick(
        jnp.asarray(rng.standard_normal((R, T, N)), jnp.float32) * 0.1,
        jnp.asarray(rng.random((R, T)), jnp.float32),
        jnp.asarray(rng.random((R, T)) < 0.7),
        jnp.asarray(rng.integers(-1, T, (R, S)), jnp.int32),
        jnp.ones((R, T), jnp.float32),
    )
    assert out.shape == (R, S, N)
    assert np.isfinite(np.asarray(out)).all()


def test_runtime_device_mix_matches_host_sum():
    """runtime/mixer.py's batched einsum path (the 1000-room MCU form)
    is sample-exact against the per-room host policy it replaces:
    sum every present track, minus the subscriber's own column. int16
    samples summed in float32 stay below 2^24, so rounding recovers the
    integer sum bit-exactly."""
    from livekit_server_tpu.runtime import mixer as rtmixer

    rng = np.random.default_rng(5)
    R, T, S, N = 5, 3, 4, 64
    pcm_i = rng.integers(-32768, 32768, (R, T, N)).astype(np.int64)
    present = rng.random((R, T)) < 0.8
    pcm_i[~present] = 0
    exclude = rng.integers(0, T + 1, (R, S)).astype(np.int32)
    out = np.asarray(rtmixer._device_mix(T, S, N)(
        jnp.asarray(pcm_i.astype(np.float32)),
        jnp.asarray(present),
        jnp.asarray(exclude),
    ))
    for r in range(R):
        for s in range(S):
            ref = np.zeros(N, np.int64)
            for t in range(T):
                if present[r, t] and t != exclude[r, s]:
                    ref += pcm_i[r, t]
            assert np.array_equal(np.rint(out[r, s]).astype(np.int64), ref)
