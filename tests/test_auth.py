"""JWT auth tests (reference: pkg/service/auth_test.go + livekit/protocol
auth semantics)."""

import pytest

from livekit_server_tpu.auth import AccessToken, TokenError, VideoGrant, verify_token

KEYS = {"APIkey1": "secret1", "APIkey2": "secret2"}


def mint(**grant_kw):
    t = AccessToken("APIkey1", "secret1")
    t.identity = "alice"
    t.grant = VideoGrant(**grant_kw)
    return t


def test_round_trip_grants():
    tok = mint(room_join=True, room="lobby", can_publish=False).to_jwt()
    claims = verify_token(tok, KEYS)
    assert claims.identity == "alice"
    assert claims.video.room_join is True
    assert claims.video.room == "lobby"
    assert claims.video.can_publish is False
    assert claims.video.can_subscribe is None  # unset stays unset


def test_wrong_secret_rejected():
    tok = mint(room_join=True, room="x").to_jwt()
    with pytest.raises(TokenError, match="signature"):
        verify_token(tok, {"APIkey1": "wrong"})


def test_unknown_key_rejected():
    t = AccessToken("APIother", "s")
    t.identity = "a"
    with pytest.raises(TokenError, match="unknown API key"):
        verify_token(t.to_jwt(), KEYS)


def test_expired_rejected():
    t = mint(room_join=True, room="x")
    tok = t.to_jwt(now=1000)
    with pytest.raises(TokenError, match="expired"):
        verify_token(tok, KEYS, now=1000 + t.ttl + 1)
    # still valid just before expiry
    assert verify_token(tok, KEYS, now=1000 + t.ttl - 1).identity == "alice"


def test_tampered_payload_rejected():
    tok = mint(room_join=True, room="x").to_jwt()
    h, p, s = tok.split(".")
    import base64, json
    payload = json.loads(base64.urlsafe_b64decode(p + "=" * (-len(p) % 4)))
    payload["video"]["roomAdmin"] = True
    p2 = base64.urlsafe_b64encode(
        json.dumps(payload).encode()
    ).rstrip(b"=").decode()
    with pytest.raises(TokenError):
        verify_token(f"{h}.{p2}.{s}", KEYS)


def test_join_token_requires_identity():
    t = AccessToken("APIkey1", "secret1")
    t.grant = VideoGrant(room_join=True, room="x")
    with pytest.raises(TokenError, match="identity"):
        t.to_jwt()


def test_malformed_tokens():
    for bad in ["", "a.b", "a.b.c.d", "x.y.z"]:
        with pytest.raises(TokenError):
            verify_token(bad, KEYS)
