"""Traffic twin (runtime/traffic_twin.py): scenario DSL validation, the
byte-identical-timeline determinism contract, a full same-seed replay
equivalence check, the twin.* config knobs, and the bench last-line-JSON
absorption contract shared by the wire twin and fleet_twin sections."""

import json

import pytest

from livekit_server_tpu.config import ConfigError, load_config
from livekit_server_tpu.runtime.traffic_twin import (
    ChurnSegment,
    Incident,
    Scenario,
    ScenarioError,
    SizeClass,
    TrafficTwin,
    build_timeline,
    scenario_from_config,
    timeline_bytes,
    validate_scenario,
)

BASE_YAML = "keys:\n  k: s\n"


# -- scenario DSL -----------------------------------------------------------

def test_default_scenarios_validate():
    validate_scenario(Scenario())
    validate_scenario(Scenario.micro())
    validate_scenario(Scenario.standard())


def test_scenario_rejects_bad_shapes():
    good = Scenario.micro()
    with pytest.raises(ScenarioError):
        validate_scenario(Scenario(seed=1, segments=()))
    with pytest.raises(ScenarioError):
        validate_scenario(Scenario(
            seed=1, segments=good.segments,
            incidents=(Incident("meteor_strike", at=1, ticks=2),),
        ))
    with pytest.raises(ScenarioError):
        # Incident anchored past the end of the timeline.
        validate_scenario(Scenario(
            seed=1, segments=(ChurnSegment(ticks=10, join_rate=1.0),),
            incidents=(Incident("flash_crowd", at=50, ticks=2),),
        ))
    with pytest.raises(ScenarioError):
        validate_scenario(Scenario(
            seed=1, segments=good.segments,
            incidents=(Incident("flash_crowd", at=1, ticks=2,
                                magnitude=0.0),),
        ))
    with pytest.raises(ScenarioError):
        # Size-class weights must carry probability mass.
        validate_scenario(Scenario(
            seed=1, segments=good.segments,
            sizes=(SizeClass(0.0, 1, 2),),
        ))


def test_timeline_shape():
    sc = Scenario.standard(seed=41, ticks=60)
    events = build_timeline(sc, offered_load=1.0)
    assert events, "standard scenario produced no traffic"
    ticks = [e.tick for e in events]
    assert ticks == sorted(ticks)
    regions = {name for name, _ in sc.regions}
    kinds = {"join", "leave", "reconnect", "incident_begin", "incident_end"}
    for e in events:
        assert e.kind in kinds
        assert 0 <= e.tick < sc.total_ticks
        if e.kind == "join":
            assert e.region in regions
            assert e.participants >= 1
            # Codec mix: video rooms carry a codec, audio-only rooms opus.
            assert e.codec != "" if e.video else e.codec == "opus"
    assert any(e.kind == "incident_begin" for e in events)
    assert any(e.kind == "reconnect" for e in events)


# -- determinism contract ---------------------------------------------------

def test_timeline_bytes_deterministic():
    sc = Scenario.standard(seed=20, ticks=60)
    b1 = timeline_bytes(build_timeline(sc, 2.0))
    b2 = timeline_bytes(build_timeline(Scenario.standard(seed=20, ticks=60),
                                       2.0))
    assert b1 == b2, "same seed+load must be byte-identical"
    assert b1 != timeline_bytes(
        build_timeline(Scenario.standard(seed=21, ticks=60), 2.0)
    ), "different seed must perturb the timeline"
    assert b1 != timeline_bytes(build_timeline(sc, 4.0)), \
        "offered load is part of the derivation"


async def test_same_seed_runs_identical_slo_numbers():
    """Two full replays at one seed agree on every counter-derived SLO
    (deterministic_dict excludes the wall-clock members by design)."""
    def make():
        return TrafficTwin(
            Scenario.micro(seed=23), nodes=1,
            plane={"rooms": 8, "tracks_per_room": 4, "pkts_per_track": 8,
                   "subs_per_room": 4, "tick_ms": 10},
        )

    rep1 = await make().run(1.0)
    rep2 = await make().run(1.0)
    assert rep1.deterministic_dict() == rep2.deterministic_dict()
    assert rep1.joins_offered > 0
    assert rep1.audio_expected > 0


# -- twin.* config knobs ----------------------------------------------------

def test_twin_config_knobs_and_validation():
    cfg = load_config(yaml_text=BASE_YAML + (
        "twin:\n  enabled: true\n  seed: 7\n  ticks: 40\n"
        "  video_room_frac: 0.25\n"
    ))
    assert cfg.twin.seed == 7
    sc = scenario_from_config(cfg.twin)
    assert sc.seed == 7
    assert sc.total_ticks == 40
    assert sc.video_room_frac == 0.25

    for frag in (
        "twin:\n  nodes: 0\n",
        "twin:\n  ticks: -3\n",
        "twin:\n  probe_every: 0\n",
        "twin:\n  video_room_frac: 1.5\n",
        "twin:\n  loads: [1.0, -2.0, 3.0, 4.0]\n",
        "twin:\n  enabled: true\n  loads: [1.0, 2.0]\n",
        "twin:\n  no_such_knob: 1\n",
    ):
        with pytest.raises(ConfigError):
            load_config(yaml_text=BASE_YAML + frag)


# -- bench absorption contract ----------------------------------------------

def test_bench_absorb_twin_last_json_line_wins():
    from bench import absorb_twin_json

    out = "\n".join([
        "warmup chatter",
        json.dumps({"steps": [1]}),
        "progress: load x2.0",
        json.dumps({"steps": [1, 2], "partial": True}),
        json.dumps({"steps": [1, 2, 3], "capacity_knee_load": 2.0}),
    ])
    got = absorb_twin_json(out)
    assert got["capacity_knee_load"] == 2.0
    assert got["steps"] == [1, 2, 3]

    # A killed child that emitted only a partial curve still salvages it.
    partial = absorb_twin_json(out.rsplit("\n", 1)[0])
    assert partial["partial"] is True


def test_bench_absorb_twin_raises_without_json():
    from bench import absorb_twin_json

    for stdout in ("", "no json here\nstill none", None):
        with pytest.raises(ValueError, match="twin produced no JSON"):
            absorb_twin_json(stdout)
