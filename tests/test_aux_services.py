"""Egress / Ingress / SIP / Agent service tests.

Reference parity: pkg/service/egress.go, ingress.go, sip.go API shapes and
agentservice.go worker protocol (register → job offer → availability →
job updates), exercised over the real HTTP/WS server like test/agent_test.go.
"""

import asyncio
import json

import aiohttp

from livekit_server_tpu.auth import AccessToken, VideoGrant
from tests.test_service import API_KEY, API_SECRET, SignalClient, running_server


def service_token(**grant_kw) -> str:
    t = AccessToken(API_KEY, API_SECRET)
    t.identity = "svc"
    t.grant = VideoGrant(**grant_kw)
    return t.to_jwt()


async def test_egress_api_lifecycle():
    async with running_server() as server:
        base = f"http://127.0.0.1:{server.port}/twirp/livekit.Egress"
        hdr = {"Authorization": f"Bearer {service_token(room_record=True)}"}
        async with aiohttp.ClientSession() as s:
            # no worker listening → aborted with explicit error
            async with s.post(
                f"{base}/StartRoomCompositeEgress", json={"room_name": "r"}, headers=hdr
            ) as r:
                info = await r.json()
                assert info["egress_id"].startswith("EG_")
                assert info["status"] == 5  # ABORTED
                assert "no egress workers" in info["error"]

            # with a fake worker on the bus, the job dispatches + updates flow
            bus = getattr(server.router, "bus", None)
            if bus is not None:
                jobs = bus.subscribe("egress_jobs")
                async with s.post(
                    f"{base}/StartTrackEgress",
                    json={"room_name": "r2", "track_id": "TR_x"},
                    headers=hdr,
                ) as r:
                    info = await r.json()
                    assert info["status"] == 0  # STARTING
                job = json.loads(await jobs.read(timeout=2))
                assert job["kind"] == "start"
                egress = job["egress"]
                egress["status"] = 1  # ACTIVE
                await bus.publish("egress_updates", json.dumps(egress))
                await asyncio.sleep(0.05)
                async with s.post(f"{base}/ListEgress", json={}, headers=hdr) as r:
                    items = (await r.json())["items"]
                    st = {e["egress_id"]: e["status"] for e in items}
                    assert st[egress["egress_id"]] == 1
                async with s.post(
                    f"{base}/StopEgress", json={"egress_id": egress["egress_id"]}, headers=hdr
                ) as r:
                    assert (await r.json())["status"] == 2  # ENDING
                jobs.close()

            # permission guard
            bad = {"Authorization": f"Bearer {service_token(room_join=True, room='r')}"}
            async with s.post(f"{base}/ListEgress", json={}, headers=bad) as r:
                assert r.status == 403


async def test_ingress_api_crud():
    async with running_server() as server:
        base = f"http://127.0.0.1:{server.port}/twirp/livekit.Ingress"
        hdr = {"Authorization": f"Bearer {service_token(ingress_admin=True)}"}
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"{base}/CreateIngress",
                json={"name": "stream", "room_name": "live", "participant_identity": "obs",
                      "input_type": 0},
                headers=hdr,
            ) as r:
                info = await r.json()
                assert info["ingress_id"].startswith("IN_")
                assert info["stream_key"].startswith("SK_")
            async with s.post(
                f"{base}/UpdateIngress",
                json={"ingress_id": info["ingress_id"], "room_name": "live2"},
                headers=hdr,
            ) as r:
                assert (await r.json())["room_name"] == "live2"
            async with s.post(f"{base}/ListIngress", json={"room_name": "live2"}, headers=hdr) as r:
                assert len((await r.json())["items"]) == 1
            async with s.post(
                f"{base}/DeleteIngress", json={"ingress_id": info["ingress_id"]}, headers=hdr
            ) as r:
                assert r.status == 200
            async with s.post(f"{base}/ListIngress", json={}, headers=hdr) as r:
                assert (await r.json())["items"] == []


async def test_sip_api_crud_and_dispatch():
    async with running_server() as server:
        base = f"http://127.0.0.1:{server.port}/twirp/livekit.SIP"
        hdr = {"Authorization": f"Bearer {service_token(room_admin=True)}"}
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"{base}/CreateSIPOutboundTrunk",
                json={"name": "pstn", "address": "sip.example.com", "numbers": ["+15550100"]},
                headers=hdr,
            ) as r:
                trunk = await r.json()
                assert trunk["sip_trunk_id"].startswith("ST_")
                assert trunk["kind"] == "outbound"
            async with s.post(
                f"{base}/CreateSIPDispatchRule",
                json={"name": "direct", "trunk_ids": [trunk["sip_trunk_id"]],
                      "rule": {"dispatch_rule_direct": {"room_name": "callroom"}}},
                headers=hdr,
            ) as r:
                rule = await r.json()
                assert rule["sip_dispatch_rule_id"].startswith("SDR_")
            # outbound call with no SIP worker → 503
            async with s.post(
                f"{base}/CreateSIPParticipant",
                json={"sip_trunk_id": trunk["sip_trunk_id"], "sip_call_to": "+15550123",
                      "room_name": "callroom", "participant_identity": "caller"},
                headers=hdr,
            ) as r:
                assert r.status == 503
            # with a worker on the bus, the dial job dispatches
            bus = getattr(server.router, "bus", None)
            if bus is not None:
                jobs = bus.subscribe("sip_jobs")
                async with s.post(
                    f"{base}/CreateSIPParticipant",
                    json={"sip_trunk_id": trunk["sip_trunk_id"], "sip_call_to": "+15550123",
                          "room_name": "callroom", "participant_identity": "caller"},
                    headers=hdr,
                ) as r:
                    call = await r.json()
                    assert call["sip_call_id"].startswith("SCL_")
                job = json.loads(await jobs.read(timeout=2))
                assert job["kind"] == "dial" and job["call"]["sip_call_to"] == "+15550123"
                jobs.close()
            async with s.post(f"{base}/DeleteSIPTrunk", json={"sip_trunk_id": trunk["sip_trunk_id"]}, headers=hdr) as r:
                assert r.status == 200


async def test_agent_worker_room_job_flow():
    async with running_server() as server:
        async with aiohttp.ClientSession() as s:
            # agent worker registers
            ws = await s.ws_connect(
                f"ws://127.0.0.1:{server.port}/agent?access_token={service_token(agent=True)}"
            )
            await ws.send_str(json.dumps({"register": {"namespace": "default", "job_type": 0}}))
            reg = json.loads((await ws.receive()).data)["registered"]
            assert reg["worker_id"].startswith("AW_")

            # a participant joins → room created → job offered to the worker
            alice = SignalClient(s, server.port)
            await alice.connect("agent-room", "alice")
            offer = json.loads((await asyncio.wait_for(ws.receive(), 3)).data)["job_offer"]
            assert offer["job"]["room_name"] == "agent-room"
            assert offer["job"]["job_type"] == 0
            assert offer["token"]

            # worker accepts; job goes running
            await ws.send_str(
                json.dumps({"availability": {"job_id": offer["job"]["job_id"], "available": True}})
            )
            await asyncio.sleep(0.05)
            assert server.agents.jobs[offer["job"]["job_id"]].state == "running"

            # the agent can actually join the room with the offered token
            agent_ws = await s.ws_connect(
                f"ws://127.0.0.1:{server.port}/rtc?access_token={offer['token']}"
            )
            msg = json.loads((await agent_ws.receive()).data)
            # first frame is either join or update; look for join shortly
            for _ in range(5):
                if "join" in msg:
                    break
                msg = json.loads((await agent_ws.receive()).data)
            assert "join" in msg
            await agent_ws.close()

            # worker completes the job
            await ws.send_str(
                json.dumps({"job_update": {"job_id": offer["job"]["job_id"], "state": "done"}})
            )
            await asyncio.sleep(0.05)
            assert server.agents.jobs[offer["job"]["job_id"]].state == "done"
            await ws.close()
            await alice.close()


async def test_agent_rejects_non_agent_token():
    async with running_server() as server:
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://127.0.0.1:{server.port}/agent?access_token={service_token(room_join=True, room='x')}"
            ) as r:
                assert r.status == 401


async def test_egress_worker_updates_over_bus():
    """Full dispatch→active→ended flow with a fake worker on a real bus."""
    import socket

    from livekit_server_tpu.routing import MemoryBus
    from livekit_server_tpu.service.server import create_server
    from tests.test_service import make_config

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    cfg = make_config(port)
    cfg.kv.kind = "external"
    server = create_server(cfg, bus=MemoryBus())
    await server.start()
    try:
        bus = server.router.bus
        jobs = bus.subscribe("egress_jobs")
        base = f"http://127.0.0.1:{server.port}/twirp/livekit.Egress"
        hdr = {"Authorization": f"Bearer {service_token(room_record=True)}"}
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"{base}/StartWebEgress", json={"room_name": "w"}, headers=hdr
            ) as r:
                info = await r.json()
                assert info["status"] == 0
            job = json.loads(await jobs.read(timeout=2))
            egress = job["egress"]
            for status, event_count in ((1, 1), (3, 2)):  # ACTIVE then COMPLETE
                egress["status"] = status
                await bus.publish("egress_updates", json.dumps(egress))
                await asyncio.sleep(0.05)
            assert server.egress.egresses[egress["egress_id"]].status == 3
            events = [e["event"] for e in server.telemetry.events]
            assert "egress_started" in events and "egress_ended" in events
        jobs.close()
    finally:
        await server.stop(force=True)
