"""VP8 munger tests (reference: pkg/sfu/codecmunger/vp8_test.go semantics)."""

import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.ops import vp8


def _tick(state, pids, tl0s, kis, begin, fwd, drop_pic=None, switch=None):
    P = len(pids)
    S = state.pid_offset.shape[0]
    mk = lambda m: jnp.zeros((P, S), jnp.bool_) if m is None else jnp.asarray(m, jnp.bool_).reshape(P, S)
    return vp8.munge_tick(
        state,
        jnp.asarray(pids, jnp.int32),
        jnp.asarray(tl0s, jnp.int32),
        jnp.asarray(kis, jnp.int32),
        jnp.asarray(begin, jnp.bool_),
        jnp.ones((P,), jnp.bool_),
        mk(fwd),
        mk(drop_pic),
        mk(switch),
    )


def test_identity():
    st = vp8.init_state(1)
    st, pid, tl0, ki = _tick(st, [100, 100, 101], [7, 7, 7], [3, 3, 3], [1, 0, 1], [[1], [1], [1]])
    np.testing.assert_array_equal(np.asarray(pid)[:, 0], [100, 100, 101])
    assert int(st.last_pid[0]) == 101


def test_dropped_picture_compacts_pid():
    st = vp8.init_state(1)
    st, pid, *_ = _tick(
        st,
        [10, 11, 12],
        [1, 1, 1],
        [0, 0, 0],
        [1, 1, 1],
        fwd=[[1], [0], [1]],
        drop_pic=[[0], [1], [0]],
    )
    p = np.asarray(pid)[:, 0]
    assert p[0] == 10 and p[2] == 11


def test_pid_15bit_wrap():
    st = vp8.init_state(1)
    st, pid, *_ = _tick(st, [0x7FFE, 0x7FFF, 0], [1, 1, 1], [0, 0, 0], [1, 1, 1], [[1]] * 3)
    np.testing.assert_array_equal(np.asarray(pid)[:, 0], [0x7FFE, 0x7FFF, 0])


def test_switch_continues_pid_space():
    st = vp8.init_state(1)
    st, *_ = _tick(st, [200, 201], [5, 5], [2, 2], [1, 1], [[1], [1]])
    st, pid, tl0, ki = _tick(
        st, [9000, 9001], [77, 77], [9, 9], [1, 1], [[1], [1]], switch=[[1], [0]]
    )
    np.testing.assert_array_equal(np.asarray(pid)[:, 0], [202, 203])
    np.testing.assert_array_equal(np.asarray(tl0)[:, 0], [6, 6])
    np.testing.assert_array_equal(np.asarray(ki)[:, 0], [3, 3])


def test_tl0_8bit_wrap():
    st = vp8.init_state(1)
    st, *_ = _tick(st, [1], [255], [0], [1], [[1]])
    st, pid, tl0, ki = _tick(st, [2], [0], [0], [1], [[1]], switch=None)
    assert int(tl0[0, 0]) == 0
