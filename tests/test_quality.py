"""Connection-quality scorer tests (reference: pkg/sfu/connectionquality/scorer.go)."""

import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.ops import quality as q


def test_clean_channel_excellent():
    mos, qual = q.connection_quality(
        jnp.array([0.0]), jnp.array([50.0]), jnp.array([5.0]), jnp.array([True])
    )
    assert float(mos[0]) > 4.1
    assert int(qual[0]) == q.QUALITY_EXCELLENT


def test_heavy_loss_poor():
    mos, qual = q.connection_quality(
        jnp.array([15.0]), jnp.array([50.0]), jnp.array([5.0]), jnp.array([True])
    )
    assert int(qual[0]) == q.QUALITY_POOR


def test_high_rtt_degrades():
    mos_lo, _ = q.connection_quality(
        jnp.array([0.0]), jnp.array([50.0]), jnp.array([5.0]), jnp.array([True])
    )
    mos_hi, _ = q.connection_quality(
        jnp.array([0.0]), jnp.array([600.0]), jnp.array([40.0]), jnp.array([True])
    )
    assert float(mos_hi[0]) < float(mos_lo[0])


def test_no_packets_lost():
    _, qual = q.connection_quality(
        jnp.array([0.0]), jnp.array([0.0]), jnp.array([0.0]), jnp.array([False])
    )
    assert int(qual[0]) == q.QUALITY_LOST


def test_deficiency_penalty():
    mos_ok, _ = q.connection_quality(
        jnp.array([1.0]), jnp.array([80.0]), jnp.array([10.0]), jnp.array([True])
    )
    mos_def, _ = q.connection_quality(
        jnp.array([1.0]), jnp.array([80.0]), jnp.array([10.0]), jnp.array([True]),
        is_deficient=jnp.array([True]),
    )
    assert float(mos_def[0]) < float(mos_ok[0])


def test_aggregate_min():
    qual = jnp.array([[q.QUALITY_EXCELLENT, q.QUALITY_POOR, q.QUALITY_LOST]])
    mask = jnp.array([[True, True, True]])
    agg = q.aggregate_min(qual, mask)
    assert int(agg[0]) == q.QUALITY_POOR
    # All lost ⇒ LOST.
    qual = jnp.full((1, 3), q.QUALITY_LOST)
    agg = q.aggregate_min(qual, mask)
    assert int(agg[0]) == q.QUALITY_LOST
    # Masked-out entries ignored.
    qual = jnp.array([[q.QUALITY_EXCELLENT, q.QUALITY_POOR, q.QUALITY_EXCELLENT]])
    mask = jnp.array([[True, False, True]])
    agg = q.aggregate_min(qual, mask)
    assert int(agg[0]) == q.QUALITY_EXCELLENT
