"""Audio-level / active-speaker tests (reference: pkg/sfu/audio/audiolevel_test.go)."""

import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.ops import audio


PARAMS = audio.AudioLevelParams(
    active_level=35, min_percentile=40, observe_interval_ms=500, smooth_intervals=1
)


def _run_window(state, level, n_ticks=25, tick_ms=20, tracks=1):
    """Feed one 20ms frame per tick at the given dBov level."""
    for _ in range(n_ticks):
        state, linear, active = audio.observe_tick(
            state,
            PARAMS,
            jnp.full((tracks, 1), level, jnp.int32),
            jnp.full((tracks, 1), tick_ms, jnp.int32),
            jnp.ones((tracks, 1), jnp.bool_),
            jnp.int32(tick_ms),
        )
    return state, linear, active


def test_loud_track_becomes_active():
    st = audio.init_state(1)
    st, linear, active = _run_window(st, level=20)  # 20 dBov attenuation: loud
    assert bool(active[0])
    assert float(linear[0]) > 0.05


def test_silent_track_inactive():
    st = audio.init_state(1)
    st, linear, active = _run_window(st, level=127)
    assert not bool(active[0])
    assert float(linear[0]) == 0.0


def test_quiet_speech_below_threshold_inactive():
    st = audio.init_state(1)
    st, linear, active = _run_window(st, level=60)  # below ActiveLevel=35 threshold
    assert not bool(active[0])


def test_sparse_activity_below_percentile_inactive():
    # Active frames in only ~8% of the window < MinPercentile 40%.
    st = audio.init_state(1)
    for i in range(25):
        level = 20 if i % 12 == 0 else 127
        st, linear, active = audio.observe_tick(
            st,
            PARAMS,
            jnp.full((1, 1), level, jnp.int32),
            jnp.full((1, 1), 20, jnp.int32),
            jnp.ones((1, 1), jnp.bool_),
            jnp.int32(20),
        )
    assert not bool(active[0])


def test_top_speakers_order():
    lv = jnp.array([[0.1, 0.9, 0.0, 0.5]], jnp.float32)
    levels, idx = audio.top_speakers(lv, 3)
    np.testing.assert_array_equal(np.asarray(idx)[0], [1, 3, 0])


def test_smoothing_decay():
    st = audio.init_state(1)
    st, linear1, _ = _run_window(st, level=20)
    st, linear2, active = _run_window(st, level=127)
    # With smooth_intervals=1 the level resets after a silent window.
    assert float(linear2[0]) < float(linear1[0])
    assert not bool(active[0])
