"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding is exercised
without TPU hardware (the driver separately dry-runs the multichip path on
real/virtual devices).

Note: this image's sitecustomize registers the remote `axon` TPU backend and
forces `jax_platforms="axon,cpu"` via jax.config at interpreter start — env
vars alone don't stick. Tests must run CPU-only (the TPU tunnel is a single
shared chip), so we override the config value again here, before any backend
is initialized.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# The JAX persistent compilation cache is deliberately NOT enabled here.
# It was, once, to amortize the media-plane tick's compile across runs —
# and it produced the suite's nastiest flake family: on XLA:CPU, a cache
# entry written by a clean PASSING run could deserialize into a silently
# miscompiled executable on the next run. The bad executable scribbled
# rate-like garbage into state.ctrl tensors (constants like max_spatial
# read back as 163816.0) of rooms the test never touched; the cross-room
# allocator reads those rows, so forwarding wedged for tens to hundreds
# of ticks with bit-identical inputs, differently on every run. A cold
# compile in each process is slower but correct. If someone re-enables
# the cache (JAX_COMPILATION_CACHE_DIR), unexplained forwarding wedges
# mean: delete the cache dir before debugging the model.

# Minimal async-test support (pytest-asyncio isn't in this image): any
# `async def test_*` runs under asyncio.run, `@pytest.mark.asyncio` or not.
import asyncio  # noqa: E402
import inspect  # noqa: E402

# Per-test ceiling (seconds) for async tests. The whole tier-1 suite must
# fit one wall-clock budget, so a single wedged await must surface as ONE
# failing test, not eat the entire run: asyncio.wait_for cancels the test
# coroutine (its finally blocks still run teardown) and asyncio.run then
# reaps whatever tasks the test leaked. No timing-sensitive test should
# come anywhere near this — it is a hang backstop, not a perf budget.
ASYNC_TEST_TIMEOUT_S = float(os.environ.get("LK_TEST_TIMEOUT_S", "180"))


def pytest_pyfunc_call(pyfuncitem):
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(asyncio.wait_for(fn(**kwargs), ASYNC_TEST_TIMEOUT_S))
        return True
    return None


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: run test under asyncio.run")


def free_port(kind=None) -> int:
    """One-shot ephemeral port (the shared bind-port-0 idiom). Pass
    socket.SOCK_DGRAM when the port will be bound for UDP — a TCP-probed
    port can still be busy on the UDP side."""
    import socket

    s = socket.socket(socket.AF_INET, kind or socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
