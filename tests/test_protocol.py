"""Protocol model + signal framing tests (reference: livekit/protocol
types, pkg/service/wsprotocol.go JSON mode)."""

import json

import pytest

from livekit_server_tpu.protocol import (
    ParticipantInfo,
    ParticipantPermission,
    RoomInfo,
    SignalRequest,
    SignalResponse,
    TrackInfo,
    TrackType,
    decode_signal_request,
    decode_signal_response,
    encode_signal_request,
    encode_signal_response,
)


def test_model_round_trip():
    p = ParticipantInfo(
        sid="PA_abc",
        identity="alice",
        tracks=[TrackInfo(sid="TR_x", type=TrackType.VIDEO, simulcast=True)],
        permission=ParticipantPermission(can_publish=False),
    )
    d = p.to_dict()
    assert d["tracks"][0]["type"] == 1
    back = ParticipantInfo.from_dict(json.loads(json.dumps(d)))
    assert back.identity == "alice"
    assert back.tracks[0].sid == "TR_x"
    assert back.tracks[0].simulcast is True
    assert back.permission.can_publish is False


def test_room_info_defaults():
    r = RoomInfo(name="lobby")
    assert r.empty_timeout == 300
    assert RoomInfo.from_dict(r.to_dict()).name == "lobby"


def test_signal_request_round_trip():
    req = SignalRequest("add_track", {"cid": "c1", "type": 1, "name": "cam"})
    raw = encode_signal_request(req)
    assert json.loads(raw) == {"add_track": {"cid": "c1", "type": 1, "name": "cam"}}
    back = decode_signal_request(raw)
    assert back.kind == "add_track" and back.data["cid"] == "c1"


def test_signal_response_round_trip():
    resp = SignalResponse("speakers_changed", {"speakers": [{"sid": "PA_1", "level": 0.4}]})
    back = decode_signal_response(encode_signal_response(resp))
    assert back.data["speakers"][0]["sid"] == "PA_1"


def test_unknown_kind_rejected():
    with pytest.raises(ValueError):
        SignalRequest("bogus")
    with pytest.raises(ValueError):
        decode_signal_request('{"bogus": {}}')
    with pytest.raises(ValueError):
        decode_signal_request('{"offer": {}, "answer": {}}')
    with pytest.raises(ValueError):
        decode_signal_request('{"offer": 5}')


def test_every_reference_request_variant_supported():
    # signalhandler.go:24-97 dispatches these 14 oneof arms.
    for kind in [
        "offer", "answer", "trickle", "add_track", "mute", "subscription",
        "track_setting", "leave", "update_layers", "subscription_permission",
        "sync_state", "simulate", "ping", "update_metadata",
    ]:
        assert decode_signal_request(json.dumps({kind: {}})).kind == kind
