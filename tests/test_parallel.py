"""Multi-chip sharding tests on the virtual 8-device CPU mesh.

Mirrors the reference's multi-node tests (test/multinode_test.go — N full
servers in one process against one Redis): here, N = 8 logical devices in
one process, rooms sharded over the mesh, one jitted tick stepping all of
them (SURVEY.md §4 tier 4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from livekit_server_tpu.models import plane, synth
from livekit_server_tpu.parallel import make_mesh, make_sharded_tick, room_sharding, shard_tree


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= 8, "conftest must force 8 virtual CPU devices"
    return make_mesh(n_devices=8)


def _setup(dims, spec):
    return synth.make_state(dims, spec)


def test_sharded_tick_matches_single_device(mesh):
    dims = plane.PlaneDims(rooms=16, tracks=4, pkts=8, subs=4)
    spec = synth.TrafficSpec(video_tracks=2, audio_tracks=2)
    state = _setup(dims, spec)
    traffic = synth.init_traffic(dims, spec, seed=3)
    _, inp = synth.next_tick(traffic, dims, spec, tick_index=5, seed=3)
    inp = jax.tree.map(jnp.asarray, inp)

    ref_state, ref_out = jax.jit(plane.media_plane_tick)(state, inp)

    sh_state = shard_tree(state, mesh)
    sh_inp = shard_tree(inp, mesh)
    tick = make_sharded_tick(mesh, donate=False)
    new_state, out = tick(sh_state, sh_inp)

    # Sharding the room axis must not change any per-room result.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5),
        ref_out, out,
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5),
        ref_state, new_state,
    )


def test_state_actually_sharded(mesh):
    dims = plane.PlaneDims(rooms=8, tracks=2, pkts=4, subs=2)
    state = shard_tree(_setup(dims, synth.TrafficSpec(1, 1)), mesh)
    shardings = {s.sharding for s in jax.tree.leaves(state) if s.ndim > 0}
    assert shardings == {room_sharding(mesh)}
    # Each device holds exactly one room of the [8] room axis.
    first = jax.tree.leaves(state)[0]
    assert len(first.addressable_shards) == 8
    assert first.addressable_shards[0].data.shape[0] == 1


def test_multitick_sharded_run(mesh):
    dims = plane.PlaneDims(rooms=8, tracks=4, pkts=8, subs=4)
    spec = synth.TrafficSpec(video_tracks=2, audio_tracks=2)
    state = shard_tree(_setup(dims, spec), mesh)
    tick = make_sharded_tick(mesh, donate=True)
    traffic = synth.init_traffic(dims, spec)
    total = 0
    for i in range(5):
        traffic, inp = synth.next_tick(traffic, dims, spec, tick_index=i)
        state, out = tick(state, shard_tree(jax.tree.map(jnp.asarray, inp), mesh))
        total += int(out.fwd_packets.sum())
    assert total > 0


def test_graft_entry_contract():
    import __graft_entry__ as ge

    fn, example_args = ge.entry()
    out = jax.jit(fn)(*example_args)
    jax.block_until_ready(out)
    ge.dryrun_multichip(8)


def _interpreted_pallas_body() -> None:
    """Body of the interpret-mode equivalence check; run in a SUBPROCESS
    (see the test below) because jax's interpret-mode Pallas execution
    under pjit has intermittently SIGABRTed inside the XLA:CPU runtime
    while materializing results (native abort; Python stack ends in
    Array.__array__ — a jax/XLA runtime issue, no product path runs
    interpret-mode Pallas). In-process, that abort would kill the whole
    suite."""
    import functools

    from livekit_server_tpu.ops import allocation, selector

    mesh = make_mesh()
    dims = plane.PlaneDims(rooms=8, tracks=4, pkts=4, subs=4)
    spec = synth.TrafficSpec(video_tracks=2, audio_tracks=1)
    state = _setup(dims, spec)
    traffic = synth.init_traffic(dims, spec, seed=9)
    _, inp = synth.next_tick(traffic, dims, spec, tick_index=3, seed=9)
    inp = jax.tree.map(jnp.asarray, inp)

    sh_state = shard_tree(state, mesh)
    sh_inp = shard_tree(inp, mesh)
    ref_state, ref_out = make_sharded_tick(mesh, donate=False)(sh_state, sh_inp)

    # Force the PRODUCTION TPU kernels (the fused phase-0 decision kernel
    # + the room-batched phase-2 allocation) in interpret mode inside the
    # sharded tick.
    orig_ar, orig_dr = allocation.allocate_budget_rooms, selector.decide_rooms
    allocation.allocate_budget_rooms = functools.partial(orig_ar, interpret=True)
    selector.decide_rooms = functools.partial(orig_dr, interpret=True)
    try:
        p_state, p_out = make_sharded_tick(mesh, donate=False)(sh_state, sh_inp)
    finally:
        allocation.allocate_budget_rooms = orig_ar
        selector.decide_rooms = orig_dr

    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        ref_out, p_out,
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        ref_state, p_state,
    )


def test_pooled_tick_page_sharded_matches_single_chip(mesh):
    """parallel/mesh.py page_sharding's promised multi-chip run: the
    STOCK pooled paged tick under plain GSPMD jit with every leaf's
    page-pool axis split over the 8-device mesh (2 pages/device at
    pool=16), against the single-chip tick on identical state. The
    partitioner inserts the cross-shard tmembers gathers; per-page
    results must be bit-identical — pool sharding is a layout decision,
    not a numeric one. (The fused live-extent kernel stays single-chip:
    PagedPlaneRuntime forces paged_kernel off under a pool mesh.)"""
    from livekit_server_tpu.models import paged
    from livekit_server_tpu.parallel.mesh import page_sharding, shard_pool
    from tests.test_paged_kernel import (
        _populated_state,
        _rand_inputs,
        _table_and_rows,
    )

    rng_a = np.random.default_rng(31)
    rng_b = np.random.default_rng(31)
    table, live, _, _ = _table_and_rows()
    # shard_pool splits EVERY leaf's leading axis, including the
    # room-indexed rooms_pages directory (host-delta bookkeeping the
    # tick never reads) — widen it to one row per device so the 4-room
    # fixture shards over the 8-device mesh.
    table = table._replace(rooms_pages=jnp.full(
        (8, table.rooms_pages.shape[1]), -1, jnp.int32))
    ref_state = _populated_state(rng_a)
    sh_state = shard_pool(_populated_state(rng_b), mesh)
    sh_table = shard_pool(table, mesh)
    shardings = {
        s.sharding for s in jax.tree.leaves(sh_state) if s.ndim > 0
    }
    assert shardings == {page_sharding(mesh)}

    ref_tick = jax.jit(lambda s, i: paged.paged_plane_tick(s, i, table))
    sh_tick = jax.jit(paged.paged_plane_tick)
    for t in range(3):
        inp = _rand_inputs(rng_a, live)
        sh_inp = shard_pool(_rand_inputs(rng_b, live), mesh)
        ref_state, ref_out = ref_tick(ref_state, inp)
        sh_state, sh_out = sh_tick(sh_state, sh_inp, sh_table)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            ref_out, sh_out,
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            ref_state, sh_state,
        )


def test_sharded_tick_with_pallas_kernels_interpreted():
    """The TPU hot path runs the Pallas allocation + selection kernels
    INSIDE the room-vmapped, mesh-sharded tick (vmap batching rule under
    pjit). No multi-chip TPU is available here, so validate the
    composition in interpreter mode on the CPU mesh: kernels forced on,
    results must match the scan-formulation sharded tick exactly.

    Runs in a subprocess with one retry: the equivalence assertions run
    inside the child (a mismatch exits nonzero and fails here), while the
    XLA:CPU runtime's intermittent interpret-mode SIGABRT (see
    _interpreted_pallas_body) cannot take the suite down — a genuine
    kernel-mismatch failure is deterministic and survives the retry."""
    import os
    import subprocess
    import sys

    # sitecustomize forces the ambient platform via jax.config, so the
    # child must rewrite it before any jax operation (env alone won't).
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import tests.test_parallel as tp; tp._interpreted_pallas_body(); "
        "print('interpret-equivalence ok')"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    last = None
    for _attempt in range(2):
        last = subprocess.run(
            [sys.executable, "-c", code],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env=env, capture_output=True, text=True, timeout=600,
        )
        if last.returncode == 0:
            return
        # Negative returncode = killed by signal (the known XLA abort):
        # retry once. An assertion failure (rc=1) is real — fail fast.
        if last.returncode > 0:
            break
    raise AssertionError(
        f"interpret-mode equivalence subprocess failed rc={last.returncode}\n"
        f"{last.stdout[-2000:]}\n{last.stderr[-3000:]}"
    )


async def test_sharded_runtime_loop_matches_single_device(mesh):
    """VERDICT r3 #7: the PlaneRuntime stage/dispatch/complete loop —
    not just the jitted tick — against SHARDED state on the 8-device
    mesh. Packets flow into rooms living on different shards (host
    ingest fan-in crosses the shard boundary), egress fans out through
    the real UDP transport, and every forwarded (room, sub, sn, ts)
    matches a single-device runtime fed identically."""
    import asyncio
    import socket

    from livekit_server_tpu.runtime import PlaneRuntime
    from livekit_server_tpu.runtime.ingest import PacketIn
    from livekit_server_tpu.runtime.udp import start_udp_transport

    dims = plane.PlaneDims(rooms=8, tracks=2, pkts=4, subs=2)
    rt_m = PlaneRuntime(dims, tick_ms=10, mesh=mesh)
    rt_s = PlaneRuntime(dims, tick_ms=10)
    udp = await start_udp_transport(rt_m.ingest, "127.0.0.1", 0)
    sink = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sink.bind(("127.0.0.1", 0))
    sink.setblocking(False)
    try:
        rooms = (0, 3, 7)  # three different shards of the 8-way mesh
        for rt in (rt_m, rt_s):
            for r in rooms:
                rt.set_track(r, 0, published=True, is_video=False)
                rt.set_subscription(r, 0, 1, subscribed=True)
        for r in rooms:
            udp.assign_ssrc(r, 0, is_video=False)
            udp.register_subscriber(r, 1, sink.getsockname())
        rt_m.on_tick(lambda res: udp.send_egress_batch(res.egress_batch))

        def key(b):
            return sorted(zip(
                b.rooms.tolist(), b.subs.tolist(),
                (np.asarray(b.sn) & 0xFFFF).tolist(),
                np.asarray(b.ts).tolist(),
            ))

        for tick in range(4):
            for rt in (rt_m, rt_s):
                for r in rooms:
                    rt.ingest.push(PacketIn(
                        room=r, track=0, sn=100 + tick, ts=960 * tick,
                        size=40, payload=bytes([r]) * 40,
                    ))
            res_m = await rt_m.step_once()
            res_s = await rt_s.step_once()
            assert key(res_m.egress_batch) == key(res_s.egress_batch)
            assert len(res_m.egress_batch) == len(rooms)
        # Egress actually left on the wire (fan-out crossed every shard).
        await asyncio.sleep(0.05)
        got = 0
        while True:
            try:
                sink.recvfrom(2048)
                got += 1
            except BlockingIOError:
                break
        assert got >= 4 * len(rooms)

        # And the PRODUCTION serving loop runs against the sharded state:
        # real cadence, pipelined stage/dispatch/complete.
        rt_m.start()
        for tick in range(3):
            for r in rooms:
                rt_m.ingest.push(PacketIn(
                    room=r, track=0, sn=200 + tick, ts=960 * (10 + tick),
                    size=40, payload=b"y" * 40,
                ))
            await asyncio.sleep(0.05)
        assert rt_m.stats["ticks"] >= 2
        assert rt_m.stats["fwd_packets"] >= len(rooms)
    finally:
        await rt_m.stop()
        await rt_s.stop()
        udp.transport.close()
        sink.close()
