"""Multi-chip sharding tests on the virtual 8-device CPU mesh.

Mirrors the reference's multi-node tests (test/multinode_test.go — N full
servers in one process against one Redis): here, N = 8 logical devices in
one process, rooms sharded over the mesh, one jitted tick stepping all of
them (SURVEY.md §4 tier 4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from livekit_server_tpu.models import plane, synth
from livekit_server_tpu.parallel import make_mesh, make_sharded_tick, room_sharding, shard_tree


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= 8, "conftest must force 8 virtual CPU devices"
    return make_mesh(n_devices=8)


def _setup(dims, spec):
    return synth.make_state(dims, spec)


def test_sharded_tick_matches_single_device(mesh):
    dims = plane.PlaneDims(rooms=16, tracks=4, pkts=8, subs=4)
    spec = synth.TrafficSpec(video_tracks=2, audio_tracks=2)
    state = _setup(dims, spec)
    traffic = synth.init_traffic(dims, spec, seed=3)
    _, inp = synth.next_tick(traffic, dims, spec, tick_index=5, seed=3)
    inp = jax.tree.map(jnp.asarray, inp)

    ref_state, ref_out = jax.jit(plane.media_plane_tick)(state, inp)

    sh_state = shard_tree(state, mesh)
    sh_inp = shard_tree(inp, mesh)
    tick = make_sharded_tick(mesh, donate=False)
    new_state, out = tick(sh_state, sh_inp)

    # Sharding the room axis must not change any per-room result.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5),
        ref_out, out,
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5),
        ref_state, new_state,
    )


def test_state_actually_sharded(mesh):
    dims = plane.PlaneDims(rooms=8, tracks=2, pkts=4, subs=2)
    state = shard_tree(_setup(dims, synth.TrafficSpec(1, 1)), mesh)
    shardings = {s.sharding for s in jax.tree.leaves(state) if s.ndim > 0}
    assert shardings == {room_sharding(mesh)}
    # Each device holds exactly one room of the [8] room axis.
    first = jax.tree.leaves(state)[0]
    assert len(first.addressable_shards) == 8
    assert first.addressable_shards[0].data.shape[0] == 1


def test_multitick_sharded_run(mesh):
    dims = plane.PlaneDims(rooms=8, tracks=4, pkts=8, subs=4)
    spec = synth.TrafficSpec(video_tracks=2, audio_tracks=2)
    state = shard_tree(_setup(dims, spec), mesh)
    tick = make_sharded_tick(mesh, donate=True)
    traffic = synth.init_traffic(dims, spec)
    total = 0
    for i in range(5):
        traffic, inp = synth.next_tick(traffic, dims, spec, tick_index=i)
        state, out = tick(state, shard_tree(jax.tree.map(jnp.asarray, inp), mesh))
        total += int(out.fwd_packets.sum())
    assert total > 0


def test_graft_entry_contract():
    import __graft_entry__ as ge

    fn, example_args = ge.entry()
    out = jax.jit(fn)(*example_args)
    jax.block_until_ready(out)
    ge.dryrun_multichip(8)


def _interpreted_pallas_body() -> None:
    """Body of the interpret-mode equivalence check; run in a SUBPROCESS
    (see the test below) because jax's interpret-mode Pallas execution
    under pjit has intermittently SIGABRTed inside the XLA:CPU runtime
    while materializing results (native abort; Python stack ends in
    Array.__array__ — a jax/XLA runtime issue, no product path runs
    interpret-mode Pallas). In-process, that abort would kill the whole
    suite."""
    import functools

    from livekit_server_tpu.ops import allocation, selector

    mesh = make_mesh()
    dims = plane.PlaneDims(rooms=8, tracks=4, pkts=4, subs=4)
    spec = synth.TrafficSpec(video_tracks=2, audio_tracks=1)
    state = _setup(dims, spec)
    traffic = synth.init_traffic(dims, spec, seed=9)
    _, inp = synth.next_tick(traffic, dims, spec, tick_index=3, seed=9)
    inp = jax.tree.map(jnp.asarray, inp)

    sh_state = shard_tree(state, mesh)
    sh_inp = shard_tree(inp, mesh)
    ref_state, ref_out = make_sharded_tick(mesh, donate=False)(sh_state, sh_inp)

    orig_a, orig_s = allocation.allocate_budget_batch, selector.select_both_tick
    allocation.allocate_budget_batch = functools.partial(orig_a, interpret=True)
    selector.select_both_tick = functools.partial(orig_s, interpret=True)
    try:
        p_state, p_out = make_sharded_tick(mesh, donate=False)(sh_state, sh_inp)
    finally:
        allocation.allocate_budget_batch = orig_a
        selector.select_both_tick = orig_s

    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        ref_out, p_out,
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        ref_state, p_state,
    )


def test_sharded_tick_with_pallas_kernels_interpreted():
    """The TPU hot path runs the Pallas allocation + selection kernels
    INSIDE the room-vmapped, mesh-sharded tick (vmap batching rule under
    pjit). No multi-chip TPU is available here, so validate the
    composition in interpreter mode on the CPU mesh: kernels forced on,
    results must match the scan-formulation sharded tick exactly.

    Runs in a subprocess with one retry: the equivalence assertions run
    inside the child (a mismatch exits nonzero and fails here), while the
    XLA:CPU runtime's intermittent interpret-mode SIGABRT (see
    _interpreted_pallas_body) cannot take the suite down — a genuine
    kernel-mismatch failure is deterministic and survives the retry."""
    import os
    import subprocess
    import sys

    # sitecustomize forces the ambient platform via jax.config, so the
    # child must rewrite it before any jax operation (env alone won't).
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import tests.test_parallel as tp; tp._interpreted_pallas_body(); "
        "print('interpret-equivalence ok')"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    last = None
    for _attempt in range(2):
        last = subprocess.run(
            [sys.executable, "-c", code],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env=env, capture_output=True, text=True, timeout=600,
        )
        if last.returncode == 0:
            return
        # Negative returncode = killed by signal (the known XLA abort):
        # retry once. An assertion failure (rc=1) is real — fail fast.
        if last.returncode > 0:
            break
    raise AssertionError(
        f"interpret-mode equivalence subprocess failed rc={last.returncode}\n"
        f"{last.stdout[-2000:]}\n{last.stderr[-3000:]}"
    )
