"""Standards-lane WebRTC gateway: ICE-lite + DTLS-SRTP end-to-end.

The client here is an independent standard-wire endpoint: its own
certificate, ICE credentials, OpenSSL DTLS *client* role and RFC
7714 SRTP — it speaks only RFC wire formats (STUN/DTLS/SRTP/SDP) at the
server's real UDP socket, exactly like a stock WebRTC stack would
(aiortc/Pion are not in this image; OpenSSL's own DTLS state machine is
the independent conformance anchor on both ends).

Reference parity: pkg/rtc/transport.go:253-374 (DTLS → SRTP contexts),
test/client/client.go:147 (the reference's stock-client harness).
"""

import asyncio
import secrets
import socket
import time

import numpy as np

import pytest

pytest.importorskip("cryptography")  # OpenSSL-backed interop lane; absent in slim images

from livekit_server_tpu.interop import dtls, sdp, srtp, stun
from livekit_server_tpu.models import plane
from livekit_server_tpu.runtime import PlaneRuntime
from livekit_server_tpu.runtime.crypto import MediaCryptoRegistry
from livekit_server_tpu.runtime.udp import start_udp_transport
from tests.test_native import vp8_payload

DIMS = plane.PlaneDims(rooms=2, tracks=3, pkts=8, subs=3)


async def _recv(sock, timeout=5.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        try:
            return sock.recvfrom(65536)
        except BlockingIOError:
            await asyncio.sleep(0.005)
    raise TimeoutError("no datagram")


class StockWireClient:
    """A WebRTC endpoint built purely from RFC wire formats."""

    def __init__(self):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.setblocking(False)
        self.cert, self.key, self.fp = dtls.generate_certificate("client")
        self.ufrag = secrets.token_urlsafe(3)
        self.pwd = secrets.token_urlsafe(18)
        self.audio_ssrc = 0x1111AAAA
        self.video_ssrc = 0x2222BBBB
        self.dtls = None
        self.tx = None          # SrtpSession protecting what we send
        self.rx = None
        self.server_addr = None

    def offer(self) -> str:
        return (
            "v=0\r\no=- 1 2 IN IP4 127.0.0.1\r\ns=-\r\nt=0 0\r\n"
            "a=group:BUNDLE 0 1 2\r\n"
            f"a=ice-ufrag:{self.ufrag}\r\na=ice-pwd:{self.pwd}\r\n"
            f"a=fingerprint:sha-256 {self.fp}\r\na=setup:actpass\r\n"
            "m=audio 9 UDP/TLS/RTP/SAVPF 109\r\na=mid:0\r\na=sendonly\r\n"
            "a=rtcp-mux\r\na=rtpmap:109 opus/48000/2\r\n"
            "a=extmap:1 urn:ietf:params:rtp-hdrext:ssrc-audio-level\r\n"
            f"a=ssrc:{self.audio_ssrc} cname:cli\r\n"
            "m=video 9 UDP/TLS/RTP/SAVPF 120\r\na=mid:1\r\na=sendonly\r\n"
            "a=rtcp-mux\r\na=rtpmap:120 VP8/90000\r\n"
            f"a=ssrc:{self.video_ssrc} cname:cli\r\n"
            "m=video 9 UDP/TLS/RTP/SAVPF 120\r\na=mid:2\r\na=recvonly\r\n"
            "a=rtcp-mux\r\na=rtpmap:120 VP8/90000\r\n"
        )

    async def connect(self, answer_sdp: str):
        """STUN binding → DTLS handshake → SRTP sessions."""
        ans = sdp.parse_sdp(answer_sdp)
        assert ans.ice_lite
        m = ans.media[0]
        srv_ufrag = ans.media_ufrag(m)
        srv_pwd = ans.media_pwd(m)
        srv_fp = ans.media_fingerprint(m).split(None, 1)[1]
        # Candidate from the answer names the server media socket.
        cand = [ln for ln in answer_sdp.split("\r\n")
                if ln.startswith("a=candidate:")][0].split()
        self.server_addr = (cand[4], int(cand[5]))

        # ICE connectivity check: USERNAME = remote:local, MESSAGE-
        # INTEGRITY under the REMOTE (server) pwd — RFC 8445 §7.2.2.
        req = stun.build_binding_request(
            f"{srv_ufrag}:{self.ufrag}", srv_pwd.encode()
        )
        self.sock.sendto(req, self.server_addr)
        data, _ = await _recv(self.sock)
        resp = stun.parse_stun(data, integrity_key=srv_pwd.encode())
        assert resp is not None and resp.msg_type == stun.BINDING_SUCCESS
        assert resp.integrity_ok and resp.fingerprint_ok is not False
        xma = resp.attr(stun.ATTR_XOR_MAPPED_ADDRESS)
        assert xma is not None  # reflexive address echoed

        self.dtls = dtls.DtlsEndpoint(
            "client", self.cert, self.key, peer_fingerprint=srv_fp
        )
        for d in self.dtls.pump():
            self.sock.sendto(d, self.server_addr)
        t0 = time.monotonic()
        while not self.dtls.handshake_complete:
            assert time.monotonic() - t0 < 10, "DTLS handshake stuck"
            data, _ = await _recv(self.sock)
            if not dtls.is_dtls(data):
                continue
            for d in self.dtls.feed(data):
                self.sock.sendto(d, self.server_addr)
        (lk, ls), (rk, rs) = self.dtls.export_srtp_keys()
        self.tx = srtp.SrtpSession(master_key=lk, master_salt=ls)
        self.rx = srtp.SrtpSession(master_key=rk, master_salt=rs)

    def send_rtp(self, ssrc: int, pt: int, sn: int, ts: int,
                 payload: bytes, marker=True) -> None:
        pkt = (
            bytes([0x80, (0x80 if marker else 0) | pt])
            + (sn & 0xFFFF).to_bytes(2, "big")
            + (ts & 0xFFFFFFFF).to_bytes(4, "big")
            + ssrc.to_bytes(4, "big")
            + payload
        )
        self.sock.sendto(self.tx.protect_rtp(pkt), self.server_addr)

    def send_rtcp(self, pkt: bytes) -> None:
        self.sock.sendto(self.tx.protect_rtcp(pkt), self.server_addr)

    async def recv_media(self, timeout=5.0):
        """→ (kind, clear_packet): kind 'rtp' or 'rtcp'."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            data, _ = await _recv(self.sock, timeout)
            if len(data) >= 2 and 192 <= data[1] <= 223:
                clear = self.rx.unprotect_rtcp(data)
                if clear is not None:
                    return "rtcp", clear
            else:
                clear = self.rx.unprotect_rtp(data)
                if clear is not None:
                    return "rtp", clear
        raise TimeoutError("no media")

    def close(self):
        if self.dtls is not None:
            self.dtls.close()
        self.sock.close()


async def _setup(subscribe=True):
    runtime = PlaneRuntime(DIMS, tick_ms=10)
    reg = MediaCryptoRegistry()
    udp = await start_udp_transport(
        runtime.ingest, host="127.0.0.1", port=0, crypto=reg
    )
    gw = udp.enable_gateway()
    runtime.set_track(0, 0, published=True, is_video=False)
    runtime.set_track(0, 1, published=True, is_video=True)
    udp.set_track_kind(0, 0, False)
    udp.set_track_kind(0, 1, True)
    if subscribe:
        runtime.set_subscription(0, 0, 1, subscribed=True)
        runtime.set_subscription(0, 1, 1, subscribed=True)
    cli = StockWireClient()
    answer, peer = gw.create_peer(
        cli.offer(),
        publish=[
            {"mid": "0", "room": 0, "track": 0, "mime": "opus"},
            {"mid": "1", "room": 0, "track": 1, "mime": "vp8"},
        ],
        subscribe=(0, 1) if subscribe else None,
    )
    return runtime, udp, gw, cli, answer, peer


async def test_gateway_end_to_end_media():
    """A standard-wire client joins (STUN→DTLS→SRTP), publishes VP8 +
    Opus, and receives its subscribed media back as SRTP."""
    runtime, udp, gw, cli, answer, peer = await _setup()
    try:
        await cli.connect(answer)
        assert peer.dtls.handshake_complete
        assert peer.srtp_ready
        assert gw.stats["dtls_done"] == 1

        # Publish a CONTINUOUS stream (video layer liveness needs an
        # ongoing keyframe-bearing flow, not a one-shot burst); PTs come
        # from OUR answer (opus 111, vp8 96).
        vp8 = vp8_payload(keyframe=True) + b"\x42" * 40
        got_video = got_audio = False
        deadline = time.monotonic() + 30
        sn_seen = []
        i = 0
        while not (got_video and got_audio):
            assert time.monotonic() < deadline, (
                f"no egress; udp={udp.stats} gw={gw.stats}"
            )
            cli.send_rtp(cli.video_ssrc, 96, 100 + i, 3000 * i, vp8,
                         marker=True)
            cli.send_rtp(cli.audio_ssrc, 111, 200 + i, 960 * i,
                         b"\x51" * 30)
            i += 1
            await asyncio.sleep(0.02)
            res = await runtime.step_once()
            udp.send_egress_batch(res.egress_batch)
            try:
                while True:
                    kind, clear = await cli.recv_media(timeout=0.2)
                    if kind != "rtp":
                        continue
                    pt = clear[1] & 0x7F
                    ssrc = int.from_bytes(clear[8:12], "big")
                    if pt == 96:
                        got_video = True
                        assert ssrc == udp.subscriber_ssrc(0, 1, 1)
                        assert clear.endswith(b"\x42" * 40)  # frame bytes
                        sn_seen.append(
                            int.from_bytes(clear[2:4], "big")
                        )
                    elif pt == 111:
                        got_audio = True
                        assert ssrc == udp.subscriber_ssrc(0, 1, 0)
                        assert clear.endswith(b"\x51" * 30)
            except TimeoutError:
                pass
        assert gw.stats["srtp_rx"] >= 4
        assert gw.stats["srtp_tx"] >= 2
    finally:
        cli.close()
        udp.transport.close()
        await runtime.stop()


async def test_gateway_rtcp_both_directions():
    """Client SRTCP reaches the server RTCP handler; server PLI reaches
    the client as SRTCP."""
    runtime, udp, gw, cli, answer, peer = await _setup()
    try:
        await cli.connect(answer)
        # Client → server: a receiver report lands in the RTCP handler.
        base = udp.stats["rtcp_rx"]
        rr = (
            bytes([0x80, 201, 0, 1]) + (0xCAFE).to_bytes(4, "big")
        )
        cli.send_rtcp(rr)
        t0 = time.monotonic()
        while udp.stats["rtcp_rx"] == base:
            assert time.monotonic() - t0 < 5, f"gw={gw.stats}"
            await asyncio.sleep(0.01)
        assert gw.stats["srtcp_rx"] >= 1

        # Publish one video packet so the track's SSRC latches an addr.
        vp8 = vp8_payload(keyframe=True) + b"k" * 20
        cli.send_rtp(cli.video_ssrc, 96, 500, 9000, vp8)
        await asyncio.sleep(0.05)
        await runtime.step_once()
        # Server → client: PLI must arrive SRTCP-protected.
        udp.send_pli(0, 1)
        kind, clear = await cli.recv_media()
        while kind != "rtcp" or clear[1] != 206:
            kind, clear = await cli.recv_media()
        assert clear[1] == 206 and (clear[0] & 0x1F) == 1  # PSFB PLI
        assert int.from_bytes(clear[8:12], "big") == cli.video_ssrc
    finally:
        cli.close()
        udp.transport.close()
        await runtime.stop()


async def test_gateway_rejects_bad_stun_and_unknown_srtp():
    """Unauthenticated STUN gets no answer; SRTP from an unlatched
    address is dropped."""
    runtime, udp, gw, cli, answer, peer = await _setup(subscribe=False)
    try:
        ans = sdp.parse_sdp(answer)
        srv_ufrag = ans.media_ufrag(ans.media[0])
        cand = [ln for ln in answer.split("\r\n")
                if ln.startswith("a=candidate:")][0].split()
        server_addr = (cand[4], int(cand[5]))
        # Wrong integrity key → server must not answer.
        req = stun.build_binding_request(
            f"{srv_ufrag}:{cli.ufrag}", b"wrong-password-000000"
        )
        cli.sock.sendto(req, server_addr)
        try:
            await _recv(cli.sock, timeout=0.5)
            raise AssertionError("server answered unauthenticated STUN")
        except TimeoutError:
            pass
        t0 = time.monotonic()
        while gw.stats["stun_bad"] == 0:
            assert time.monotonic() - t0 < 5
            await asyncio.sleep(0.01)
        # A random SRTP-looking packet from an unlatched addr never
        # reaches the gateway lane: it falls to the normal media path and
        # dies as an unknown SSRC (or parse error) — not srtp_rx.
        before_rx = gw.stats["srtp_rx"]
        before_unknown = udp.stats["unknown_ssrc"] + udp.stats["parse_errors"]
        cli.sock.sendto(
            b"\x80\x60" + bytes(10) + secrets.token_bytes(60), server_addr
        )
        t0 = time.monotonic()
        while (udp.stats["unknown_ssrc"] + udp.stats["parse_errors"]
               == before_unknown):
            assert time.monotonic() - t0 < 5
            await asyncio.sleep(0.01)
        assert gw.stats["srtp_rx"] == before_rx
    finally:
        cli.close()
        udp.transport.close()
        await runtime.stop()


async def test_signal_offer_negotiates_gateway():
    """The signal-plane 'offer' arm: a real SDP offer creates a gateway
    peer, binds pending tracks + auto tracks, registers the subscriber
    lane, and answers ICE-lite; leave tears it all down."""
    from livekit_server_tpu.protocol.signal import SignalRequest
    from livekit_server_tpu.rtc import Room, handle_participant_signal
    from tests.test_rtc_runtime import drain_sink, make_participant

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    udp = await start_udp_transport(
        runtime.ingest, host="127.0.0.1", port=0,
        crypto=MediaCryptoRegistry(),
    )
    try:
        room = Room("gw", runtime)
        room.udp = udp
        cli = StockWireClient()
        p, sink = make_participant(room, "webrtc-user")
        room.join(p)
        # Announce ONE track (audio) — the video section auto-publishes.
        handle_participant_signal(room, p, SignalRequest(
            "add_track", {"cid": "mic", "type": 0, "name": "mic"}
        ))
        handle_participant_signal(room, p, SignalRequest(
            "offer", {"sdp": cli.offer()}
        ))
        msgs = drain_sink(sink)
        answers = [m for m in msgs if m.kind == "answer"]
        assert len(answers) == 1
        ans_text = answers[0].data["sdp"]
        assert "a=ice-lite" in ans_text
        ans = sdp.parse_sdp(ans_text)
        assert ans.media[0].codecs == {111: "opus"}
        assert ans.media[1].codecs == {96: "vp8"}
        # Peer exists with both SSRCs bound to plane columns.
        peer = p.gateway_peer
        assert peer is not None
        assert {s for s, *_ in peer.publish} == {
            cli.audio_ssrc, cli.video_ssrc
        }
        assert cli.audio_ssrc in udp.bindings
        assert udp.bindings[cli.video_ssrc].is_video
        # The announced pending track was consumed, an auto track added.
        assert not p.pending_tracks
        assert len(p.published) == 2
        # Subscriber lane is NOT registered yet: egress routing flips to
        # ("srtp", ufrag) only once DTLS completes — overwriting a live
        # address at offer time would black out an active subscriber.
        assert peer.sub == (room.slots.row, p.sub_col)
        assert (room.slots.row, p.sub_col) not in udp.sub_addrs
        # Renegotiation replaces the association and REUSES the gateway
        # tracks (no duplicate columns per onnegotiationneeded).
        handle_participant_signal(room, p, SignalRequest(
            "offer", {"sdp": cli.offer()}
        ))
        peer2 = p.gateway_peer
        assert peer2 is not None and peer2 is not peer
        assert peer.ufrag not in udp.gateway.peers_by_ufrag
        assert len(p.published) == 2
        assert {s for s, *_ in peer2.publish} == {
            cli.audio_ssrc, cli.video_ssrc
        }
        # Leave: bindings and peer die with the participant.
        from livekit_server_tpu.protocol import models as pm

        room.remove_participant(p, pm.DisconnectReason.CLIENT_INITIATED)
        assert cli.audio_ssrc not in udp.bindings
        assert not udp.gateway.peers_by_ufrag
        cli.close()
    finally:
        udp.transport.close()
        await runtime.stop()


def test_answer_rejects_datachannel_and_bundles_accepted_only():
    """A stock browser offer carries m=application (datachannel): the
    answer must reject it with port 0 and keep it OUT of the BUNDLE group
    (JSEP forbids bundling rejected sections)."""
    offer_text = (
        "v=0\r\no=- 1 2 IN IP4 127.0.0.1\r\ns=-\r\nt=0 0\r\n"
        "a=group:BUNDLE 0 1\r\n"
        "a=ice-ufrag:abcd\r\na=ice-pwd:0123456789012345678901\r\n"
        "a=fingerprint:sha-256 AA:BB\r\na=setup:actpass\r\n"
        "m=audio 9 UDP/TLS/RTP/SAVPF 109\r\na=mid:0\r\na=sendonly\r\n"
        "a=rtpmap:109 opus/48000/2\r\na=ssrc:7 cname:x\r\n"
        "m=application 9 UDP/DTLS/SCTP webrtc-datachannel\r\na=mid:1\r\n"
    )
    ans_text = sdp.build_answer(
        sdp.parse_sdp(offer_text), "u", "p" * 22, "AB:CD", ("1.2.3.4", 5)
    )
    bundle = [ln for ln in ans_text.split("\r\n")
              if ln.startswith("a=group:BUNDLE")][0]
    assert bundle == "a=group:BUNDLE 0"
    assert "m=application 0 " in ans_text


def test_answer_places_egress_ssrcs_in_matching_sections():
    """a=ssrc declarations must live INSIDE their kind's recv m-section,
    not appended at the end of the SDP."""
    offer_text = (
        "v=0\r\no=- 1 2 IN IP4 127.0.0.1\r\ns=-\r\nt=0 0\r\n"
        "a=ice-ufrag:abcd\r\na=ice-pwd:0123456789012345678901\r\n"
        "a=fingerprint:sha-256 AA:BB\r\na=setup:actpass\r\n"
        "m=audio 9 UDP/TLS/RTP/SAVPF 109\r\na=mid:0\r\na=recvonly\r\n"
        "a=rtpmap:109 opus/48000/2\r\n"
        "m=video 9 UDP/TLS/RTP/SAVPF 120\r\na=mid:1\r\na=recvonly\r\n"
        "a=rtpmap:120 VP8/90000\r\n"
    )
    ans_text = sdp.build_answer(
        sdp.parse_sdp(offer_text), "u", "p" * 22, "AB:CD", ("1.2.3.4", 5),
        ssrc_by_mid={"0": [111111], "1": [222222]},
    )
    audio_part = ans_text.split("m=audio")[1].split("m=video")[0]
    video_part = ans_text.split("m=video")[1]
    assert "a=ssrc:111111" in audio_part and "a=ssrc:222222" not in audio_part
    assert "a=ssrc:222222" in video_part and "a=ssrc:111111" not in video_part


async def test_gateway_traffic_survives_require_encryption_batch_path():
    """require_encryption drops cleartext — but STUN/DTLS/SRTP carry
    their own crypto and must still reach the gateway through the BATCH
    rx path (feed_batch), matching the per-datagram path's order."""
    runtime = PlaneRuntime(DIMS, tick_ms=10)
    udp = await start_udp_transport(
        runtime.ingest, host="127.0.0.1", port=0,
        crypto=MediaCryptoRegistry(), require_encryption=True,
    )
    gw = udp.enable_gateway()
    try:
        cli = StockWireClient()
        answer, peer = gw.create_peer(cli.offer())
        ans = sdp.parse_sdp(answer)
        srv_ufrag = ans.media_ufrag(ans.media[0])
        srv_pwd = ans.media_pwd(ans.media[0])
        req = stun.build_binding_request(
            f"{srv_ufrag}:{cli.ufrag}", srv_pwd.encode()
        )
        # Deliver through the BATCH path directly.
        blob = np.frombuffer(req, np.uint8)
        udp.feed_batch(
            blob, np.zeros(1, np.int64), np.array([len(req)], np.int32),
            np.array([0x7F000001], np.uint32),
            np.array([54321], np.uint16), 1,
        )
        assert gw.stats["stun_rx"] == 1
        assert peer.addr_code != 0  # latched via the batch path
        # A cleartext RTP datagram in the same mode still dies.
        rtp_like = b"\x80\x60" + bytes(50)
        blob = np.frombuffer(rtp_like, np.uint8)
        before = udp.stats["plaintext_drop"]
        udp.feed_batch(
            blob, np.zeros(1, np.int64),
            np.array([len(rtp_like)], np.int32),
            np.array([0x7F000001], np.uint32),
            np.array([54322], np.uint16), 1,
        )
        assert udp.stats["plaintext_drop"] == before + 1
        cli.close()
    finally:
        udp.transport.close()
        await runtime.stop()
