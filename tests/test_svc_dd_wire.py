"""AV1/VP9-SVC over the wire: dependency-descriptor parse, layer
selection, and bitmask rewrite end-to-end.

Reference parity: pkg/sfu/dependencydescriptor (byte parse/write),
videolayerselector/dependencydescriptor.go (DD-driven selection), and the
active-decode-targets bitmask restriction subscribers see when capped.
"""

import asyncio
import socket

import numpy as np

from livekit_server_tpu.models import plane
from livekit_server_tpu.runtime import PlaneRuntime, dd
from livekit_server_tpu.runtime.udp import (
    DD_EXT_ID,
    build_ext_section,
    start_udp_transport,
)

DIMS = plane.PlaneDims(rooms=1, tracks=4, pkts=8, subs=4)


def l1t2_structure():
    # 1 spatial x 2 temporal, 2 decode targets (dt0 = T0, dt1 = T0+T1).
    return dd.Structure(
        structure_id=0, num_decode_targets=2,
        templates=[
            dd.Template(spatial=0, temporal=0, dtis=[3, 3], fdiffs=[2]),
            dd.Template(spatial=0, temporal=1, dtis=[0, 3], fdiffs=[1]),
        ],
    )


def av1_packet(sn, ts, ssrc, dd_bytes, keyframe=False):
    """RTP with a DD header extension + a fake AV1 payload."""
    ext = build_ext_section([(DD_EXT_ID, dd_bytes)])
    hdr = bytearray(12)
    hdr[0] = 0x80 | 0x10
    hdr[1] = 0x80 | 99          # marker; AV1_PT (DD-only parse path)
    hdr[2:4] = sn.to_bytes(2, "big")
    hdr[4:8] = ts.to_bytes(4, "big")
    hdr[8:12] = ssrc.to_bytes(4, "big")
    return bytes(hdr) + ext + bytes([0x0A]) + bytes(900)


async def test_svc_dd_forwarding_and_mask_rewrite():
    runtime = PlaneRuntime(DIMS, tick_ms=10)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        runtime.set_track(0, 0, published=True, is_video=True, is_svc=True)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        # Subscriber capped to temporal 0 only.
        runtime.set_layer_caps(0, 0, 1, max_spatial=2, max_temporal=0)
        ssrc = transport.assign_ssrc(0, 0, is_video=True, svc=True, mime="video/av1")
        assert (0, 0) in transport._svc_tracks

        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())

        struct = l1t2_structure()
        caps = (runtime.ctrl.max_spatial, runtime.ctrl.max_temporal)
        got = []
        for i in range(24):
            tid = i % 2  # alternate T0 / T1 frames
            dd_bytes = dd.build(
                True, True, template_id=tid, frame_number=i,
                structure=struct if i == 0 else None,
                active_mask=0b11,
                mask_bits=2,
            )
            pub.sendto(
                av1_packet(1000 + i, 3000 * i, ssrc, dd_bytes, keyframe=i == 0),
                ("127.0.0.1", port),
            )
            await asyncio.sleep(0.02)
            res = await runtime.step_once()
            transport.send_egress_batch(res.egress_batch, layer_caps=caps)
            await asyncio.sleep(0.01)
            while True:
                try:
                    d = sub.recvfrom(4096)[0]
                    if not 192 <= d[1] <= 223:
                        got.append(d)
                    break
                except BlockingIOError:
                    break
        assert got, "no SVC packets forwarded"
        # The DD structure was learned from the wire.
        assert (0, 0) in transport._dd_structs
        parsed_tids = []
        from livekit_server_tpu.native import rtp as parser

        for d in got:
            out = parser.parse_batch(
                d, np.asarray([0], np.int32), np.asarray([len(d)], np.int32),
                dd_ext_id=DD_EXT_ID,
            )[0]
            assert int(out["dd_off"]) >= 0, "DD extension missing on egress"
            raw = d[int(out["dd_off"]) : int(out["dd_off"]) + int(out["dd_len"])]
            desc = dd.parse_with_structure(raw, struct)
            parsed_tids.append(desc.template_id)
            if desc.active_mask is not None:
                # Capped to temporal 0 ⇒ only decode target 0 active.
                assert desc.active_mask == 0b01, (
                    f"mask not restricted: {desc.active_mask:b}"
                )
        # Temporal cap honored: only T0 frames (template 0) forwarded.
        assert set(parsed_tids) == {0}, f"T1 leaked: {parsed_tids}"
        pub.close()
        sub.close()
    finally:
        transport.transport.close()
        await runtime.stop()


async def test_cold_cache_custom_dti_dd_forwarded_intact():
    """Structure cache cold (e.g. SFU restart mid-stream): a DD carrying
    custom dtis can't be interpreted (NeedStructure) but its BYTES must
    still ride the forwarded packet — stripping the descriptor would
    blind downstream decoders until the next keyframe."""
    runtime = PlaneRuntime(DIMS, tick_ms=10)
    from tests.conftest import free_port

    port = free_port(socket.SOCK_DGRAM)
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        runtime.set_track(0, 0, published=True, is_video=True, is_svc=True)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        ssrc = transport.assign_ssrc(0, 0, is_video=True, svc=True, mime="video/av1")
        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())

        struct = l1t2_structure()
        got = []
        for i in range(6):
            if i == 0:
                # Keyframe with structure starts the stream…
                dd_bytes = dd.build(True, True, template_id=0, frame_number=0,
                                    structure=struct, active_mask=0b11,
                                    mask_bits=2)
            else:
                # …then the "restart": structure cache wiped; every later
                # frame carries custom dtis, which need the lost cache.
                dd_bytes = dd.build(True, True, template_id=i % 2,
                                    frame_number=i, custom_dtis=[3, 3],
                                    mask_bits=2)
            pub.sendto(av1_packet(2000 + i, 3000 * i, ssrc, dd_bytes),
                       ("127.0.0.1", port))
            if i == 0:
                await asyncio.sleep(0.02)
                transport._dd_structs.clear()   # simulated restart
            await asyncio.sleep(0.02)
            res = await runtime.step_once()
            transport.send_egress_batch(res.egress_batch)
            await asyncio.sleep(0.01)
            while True:
                try:
                    d = sub.recvfrom(4096)[0]
                    if not 192 <= d[1] <= 223:
                        got.append(d)
                except BlockingIOError:
                    break
        assert len(got) >= 2, "no packets forwarded after cache loss"
        assert (0, 0) not in transport._dd_structs  # cache stayed cold
        from livekit_server_tpu.native import rtp as parser

        for d in got[1:]:
            out = parser.parse_batch(
                d, np.asarray([0], np.int32), np.asarray([len(d)], np.int32),
                dd_ext_id=DD_EXT_ID,
            )[0]
            assert int(out["dd_off"]) >= 0, "DD stripped on cold cache"
    finally:
        transport.transport.close()
        await runtime.stop()
