"""RTP munger tests (reference: pkg/sfu/rtpmunger_test.go semantics)."""

import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.ops import rtpmunger


def _tick(state, sns, tss, fwd, drop=None, switch=None, jump=None):
    P = len(sns)
    S = state.sn_offset.shape[0]
    fwd = jnp.asarray(fwd, jnp.bool_).reshape(P, S)
    drop = jnp.zeros((P, S), jnp.bool_) if drop is None else jnp.asarray(drop, jnp.bool_).reshape(P, S)
    switch = jnp.zeros((P, S), jnp.bool_) if switch is None else jnp.asarray(switch, jnp.bool_).reshape(P, S)
    jump = jnp.zeros((P,), jnp.int32) if jump is None else jnp.asarray(jump, jnp.int32)
    return rtpmunger.munge_tick(
        state,
        jnp.asarray(sns, jnp.int32),
        jnp.asarray(tss, jnp.int32),
        jnp.ones((P,), jnp.bool_),
        fwd,
        drop,
        switch,
        jump,
    )


def test_identity_passthrough():
    st = rtpmunger.init_state(1)
    st, sn, ts, send = _tick(st, [100, 101, 102], [1000, 1000, 2000], [[1], [1], [1]])
    np.testing.assert_array_equal(np.asarray(sn)[:, 0], [100, 101, 102])
    np.testing.assert_array_equal(np.asarray(ts)[:, 0], [1000, 1000, 2000])
    assert np.asarray(send).all()
    assert int(st.last_sn[0]) == 102


def test_gap_compaction():
    # Drop the middle packet: subsequent SNs shift down by one
    # (rtpmunger_test.go TestPacketDropped semantics).
    st = rtpmunger.init_state(1)
    st, sn, ts, send = _tick(
        st, [10, 11, 12, 13], [5, 5, 5, 5], [[1], [0], [1], [1]], drop=[[0], [1], [0], [0]]
    )
    got = np.asarray(sn)[:, 0]
    sent = np.asarray(send)[:, 0]
    assert list(got[sent]) == [10, 11, 12]
    assert int(st.sn_offset[0]) == 1


def test_gap_compaction_across_ticks():
    st = rtpmunger.init_state(1)
    st, *_ = _tick(st, [10], [5], [[1]])
    st, *_ = _tick(st, [11], [5], [[0]], drop=[[1]])
    st, sn, _, send = _tick(st, [12], [5], [[1]])
    assert int(sn[0, 0]) == 11
    assert bool(send[0, 0])


def test_source_switch_continues_sn_space():
    # Switch to a stream with a totally different SN space: output continues
    # at last_sn+1 (forwarder.go processSourceSwitch semantics).
    st = rtpmunger.init_state(1)
    st, *_ = _tick(st, [100, 101], [1000, 2000], [[1], [1]])
    st, sn, ts, send = _tick(
        st, [5000, 5001], [90000, 90500], [[1], [1]], switch=[[1], [0]], jump=[3000, 0]
    )
    np.testing.assert_array_equal(np.asarray(sn)[:, 0], [102, 103])
    # TS continues at last_ts + jump = 2000 + 3000 = 5000
    np.testing.assert_array_equal(np.asarray(ts)[:, 0], [5000, 5500])


def test_source_switch_aligned_timeline_keeps_ts_offset():
    """jump = -1: the host SR-normalized both layers onto one timeline, so
    a switch re-anchors SN but carries TS straight through — exact
    continuity instead of the one-frame guess (forwarder.go:1456)."""
    st = rtpmunger.init_state(1)
    st, *_ = _tick(st, [100, 101], [1000, 2000], [[1], [1]])
    # New stream, SNs from a different space but TS already normalized:
    # next frame on the shared timeline is 5000.
    st, sn, ts, send = _tick(
        st, [7000, 7001], [5000, 5090], [[1], [1]], switch=[[1], [0]], jump=[-1, -1]
    )
    np.testing.assert_array_equal(np.asarray(sn)[:, 0], [102, 103])
    np.testing.assert_array_equal(np.asarray(ts)[:, 0], [5000, 5090])
    # The offset survives a later non-switch tick too.
    st, _, ts, _ = _tick(st, [7002], [5180], [[1]])
    assert int(ts[0, 0]) == 5180


def test_sn_wraparound():
    st = rtpmunger.init_state(1)
    st, sn, _, _ = _tick(st, [65534, 65535, 0, 1], [0, 0, 0, 0], [[1]] * 4)
    np.testing.assert_array_equal(np.asarray(sn)[:, 0], [65534, 65535, 0, 1])
    assert int(st.last_sn[0]) == 1


def test_per_subscriber_independent_offsets():
    st = rtpmunger.init_state(2)
    # Sub 0 gets all packets; sub 1 joins at the second packet.
    st, sn, _, send = _tick(st, [50, 51], [0, 0], [[1, 0], [1, 1]])
    assert int(sn[0, 0]) == 50
    assert int(sn[1, 1]) == 51  # identity seed at join
    # Now sub 1 drops one, sub 0 forwards all.
    st, sn, _, send = _tick(st, [52, 53], [0, 0], [[1, 0], [1, 1]], drop=[[0, 1], [0, 0]])
    assert int(sn[1, 0]) == 53
    assert int(sn[1, 1]) == 52  # compacted for sub 1 only


def test_padding_generation():
    st = rtpmunger.init_state(2)
    st, *_ = _tick(st, [10], [100], [[1, 1]])
    st, pad_sn, pad_ts, valid = rtpmunger.padding_tick(
        st, jnp.array([2, 0], jnp.int32), 4, jnp.array([960, 960], jnp.int32)
    )
    v = np.asarray(valid)
    assert v[:, 0].sum() == 2 and v[:, 1].sum() == 0
    np.testing.assert_array_equal(np.asarray(pad_sn)[:2, 0], [11, 12])
    # Next real packet continues compactly after padding.
    st, sn, _, _ = _tick(st, [11], [1060], [[1, 1]])
    assert int(sn[0, 0]) == 13  # 11 - (-2) offset
    assert int(sn[0, 1]) == 11
