"""Overload governor (runtime/governor.py) + the shedding actuators.

The acceptance scenario this file pins: under a seeded 4x ingest flood
the governor climbs the ladder one rung per sustained-pressure streak,
audio rides through with 100% continuity while video sheds in ladder
order, the supervisor does NOT restart a governed-but-progressing plane
(the restart-storm regression), admission refusals arrive as explicit
signal responses over the wire, and once the flood clears the governor
walks back to L0 — one dwell per step, no flapping.
"""

import asyncio

import aiohttp
import numpy as np
import pytest

from livekit_server_tpu.config.config import Config, LimitsConfig
from livekit_server_tpu.models import plane
from livekit_server_tpu.runtime import (
    FaultInjector,
    OverloadGovernor,
    PlaneRuntime,
    PlaneSupervisor,
)
from livekit_server_tpu.runtime import governor as gov_mod
from livekit_server_tpu.runtime.faultinject import FaultSpec
from livekit_server_tpu.runtime.ingest import PacketIn
from livekit_server_tpu.utils.backoff import BackoffPolicy

from test_service import SignalClient, running_server, token

DIMS = plane.PlaneDims(rooms=2, tracks=4, pkts=4, subs=4)

# Synthetic tick verdicts for the pure ladder tests (tick_ms=10):
HOT = {"total_ms": 20.0, "late": True}    # work 2.0, deadline missed
CALM = {"total_ms": 1.0, "late": False}   # work 0.1, under exit threshold
MID = {"total_ms": 7.0, "late": False}    # work 0.7: inside the hysteresis band


def make_rt() -> PlaneRuntime:
    return PlaneRuntime(DIMS, tick_ms=10)


# -- ladder state machine ---------------------------------------------------

def test_ladder_escalates_and_recovers_one_step_per_streak():
    rt = make_rt()
    gov = OverloadGovernor(rt, escalate_ticks=3, dwell_ticks=5)
    rt.governor = gov

    # Each rung needs its own full hot streak: 4 rungs x 3 ticks.
    for i in range(12):
        gov.on_tick(HOT)
    assert gov.level == gov_mod.L_REJECT
    ups = [(t["from"], t["to"]) for t in gov.transitions]
    assert ups == [(0, 1), (1, 2), (2, 3), (3, 4)]

    # Capped at L_MAX no matter how long the pressure lasts.
    for _ in range(30):
        gov.on_tick(HOT)
    assert gov.level == gov_mod.L_MAX
    assert gov.escalations == 4

    # Recovery: one dwell per downward step, single-step transitions.
    for _ in range(20):
        gov.on_tick(CALM)
    assert gov.level == gov_mod.L_HEALTHY
    seq = [(t["from"], t["to"]) for t in gov.transitions]
    assert seq[4:] == [(4, 3), (3, 2), (2, 1), (1, 0)]
    assert gov.transition_count == 8


def test_oscillating_load_does_not_flap():
    rt = make_rt()
    gov = OverloadGovernor(rt, escalate_ticks=5, dwell_ticks=5)
    rt.governor = gov

    # 2 hot / 2 calm forever: neither streak ever reaches its threshold.
    for _ in range(20):
        for rec in (HOT, HOT, CALM, CALM):
            gov.on_tick(rec)
    assert gov.level == 0 and gov.transition_count == 0

    # The middle band resets BOTH streaks: 4 hot ticks then one
    # neither-hot-nor-calm tick, repeated — never escalates.
    for _ in range(10):
        for rec in (HOT, HOT, HOT, HOT, MID):
            gov.on_tick(rec)
    assert gov.level == 0 and gov.transition_count == 0

    # From an elevated level the same oscillation HOLDS the level
    # (monotonic under churn) instead of bouncing around it.
    gov._set_level(2, "test setup")
    for _ in range(20):
        for rec in (HOT, HOT, CALM, CALM):
            gov.on_tick(rec)
    assert gov.level == 2 and gov.transition_count == 1


def test_from_config_maps_limit_keys():
    rt = make_rt()
    lim = LimitsConfig(
        governor_enter_pressure=0.9, governor_exit_pressure=0.4,
        governor_escalate_ticks=7, governor_dwell_ticks=9,
        governor_ingress_pps=123.0, governor_ingress_burst=45.0,
    )
    gov = OverloadGovernor.from_config(rt, lim)
    assert gov.enter_pressure == 0.9 and gov.exit_pressure == 0.4
    assert gov.escalate_ticks == 7 and gov.dwell_ticks == 9
    assert gov.ingress_pps == 123.0 and gov.ingress_burst == 45.0


# -- actuators follow the ladder --------------------------------------------

def test_actuators_follow_ladder_levels():
    rt = make_rt()
    gov = OverloadGovernor(rt, ingress_pps=50.0, ingress_burst=10.0)
    rt.governor = gov
    rt.set_track(0, 0, published=True, is_video=True)
    rt.set_track(0, 1, published=True, is_video=False)
    rt.set_subscription(0, 0, 1, subscribed=True)
    rt.set_subscription(0, 1, 1, subscribed=True)
    rt.set_layer_caps(0, 0, 1, max_spatial=2)

    # L1: top layer shed, desired caps untouched.
    gov._set_level(1, "test")
    assert rt.shed_spatial_cap == plane.MAX_LAYERS - 2
    eff = rt._effective_ctrl()
    assert int(eff.max_spatial[0, 0, 1]) == plane.MAX_LAYERS - 2
    assert int(rt.ctrl.max_spatial[0, 0, 1]) == 2  # authoritative mirror intact
    assert rt.ingest._police_rate == 0.0

    # L2: base layer only + token-bucket policer armed on video.
    gov._set_level(2, "test")
    assert rt.shed_spatial_cap == 0
    assert rt.ingest._police_rate == 50.0
    assert rt.ingest._police_video is rt.meta.is_video

    # L3: non-pinned video subs muted; audio and pinned video stay live.
    gov._set_level(3, "test")
    eff = rt._effective_ctrl()
    assert bool(eff.sub_muted[0, 0, 1])          # video: paused
    assert not bool(eff.sub_muted[0, 1, 1])      # audio: untouched
    assert not bool(rt.ctrl.sub_muted[0, 0, 1])  # desired state intact
    rt.set_pinned(0, 0, 1, True)
    assert not bool(rt._effective_ctrl().sub_muted[0, 0, 1])  # pin exempts

    # L4: admission closes; existing sessions keep their gate open below.
    gov._set_level(4, "test")
    assert not gov.should_admit("room")
    assert not gov.should_admit("join")
    assert not gov.should_admit("publish")
    gov.note_rejection("join")
    assert gov.rejected == {"join": 1}

    # Full recovery restores every actuator.
    for lvl in (3, 2, 1, 0):
        gov._set_level(lvl, "test")
    assert rt.shed_spatial_cap == plane.MAX_LAYERS - 1
    assert not rt.shed_pause_video
    assert rt.ingest._police_rate == 0.0
    assert rt._effective_ctrl() is rt.ctrl  # overlay fully out of the way
    assert gov.should_admit("join")


# -- the acceptance scenario: 4x seeded flood -------------------------------

async def test_flood_sheds_video_keeps_audio_and_recovers():
    """Seeded 4x flood on one room: capacity drops drive the governor up
    the ladder in order; video sheds (pause at L3) while audio continuity
    stays 100%; p99 tick time stays bounded; after the flood clears the
    governor dwells back down to L0 and every actuator resets."""
    rt = make_rt()
    inj = FaultInjector(FaultSpec(seed=7, flood_mult=4.0))
    rt.fault = inj
    rt.ingest.fault = inj
    # Pressure thresholds pushed out of reach so only the deterministic
    # sensors (capacity-drop deltas) classify ticks — CPU speed of the
    # test host cannot flake the ladder. Policer rates set transparent so
    # the climb is driven end-to-end to L4.
    gov = OverloadGovernor(
        rt, enter_pressure=1e9, exit_pressure=1e8,
        escalate_ticks=3, dwell_ticks=10,
        ingress_pps=1e6, ingress_burst=1e6,
    )
    rt.governor = gov
    rt.set_track(0, 0, published=True, is_video=False)   # audio
    rt.set_track(0, 1, published=True, is_video=True)    # video
    rt.set_subscription(0, 0, 1, subscribed=True)
    rt.set_subscription(0, 1, 1, subscribed=True)

    audio_sns: list[int] = []
    video_per_tick: list[int] = []
    level_per_tick: list[int] = []
    sn_v = 5000

    async def one_tick(tick: int, video_pkts: int):
        nonlocal sn_v
        # One audio packet per tick: flood copies are same-SN duplicates,
        # so audio fills its K=4 slab exactly — zero audio capacity drops.
        rt.ingest.push(PacketIn(room=0, track=0, sn=100 + tick, ts=tick * 90,
                                size=20, payload=b"a"))
        # Offered video at 4x capacity: flood turns each push into 4.
        for _ in range(video_pkts):
            rt.ingest.push(PacketIn(
                room=0, track=1, sn=sn_v, ts=tick * 90, size=120,
                payload=b"v", keyframe=True, layer_sync=True,
                begin_pic=True, marker=True,
            ))
            sn_v += 1
        res = await rt.step_once()
        audio_sns.extend(p.sn for p in res.egress if p.track == 0)
        video_per_tick.append(sum(1 for p in res.egress if p.track == 1))
        level_per_tick.append(gov.level)

    flood_ticks = 40
    for tick in range(flood_ticks):
        await one_tick(tick, video_pkts=4)
        if tick == 19:
            # The ladder is at L4 by ~tick 12: every actuator (policer,
            # shed caps, pause) has fired and compiled its paths. The
            # rest of the flood and the whole recovery must then hold
            # the jit cache — shedding is a data change, not a shape
            # change (recompile watchdog, GC11 runtime half).
            rt.mark_warm()

    # Ladder climbed in order, one rung per 3-tick streak, to L4.
    ups = [(t["from"], t["to"]) for t in gov.transitions]
    assert ups == [(0, 1), (1, 2), (2, 3), (3, 4)]
    assert gov.level == gov_mod.L_REJECT
    assert not gov.should_admit("join")
    assert rt.ingest.dropped_capacity > 0
    # Drop split: this is genuine overflow, not policing or chaos faults.
    assert rt.ingest.dropped_fault == 0

    # Video flowed before the pause rung, then shed to zero.
    pause_at = level_per_tick.index(gov_mod.L_PAUSE)
    assert sum(video_per_tick[:pause_at]) > 0
    assert sum(video_per_tick[pause_at + 2:]) == 0

    # p99 tick time bounded (loose wall-clock bound: the plane kept
    # ticking, it did not degrade into multi-second stalls).
    totals = sorted(t["total_ms"] for t in rt.recent_ticks)
    p99 = totals[int(0.99 * (len(totals) - 1))]
    assert p99 < 20 * rt.tick_ms, f"p99 tick {p99}ms"

    # Flood clears; audio-only load from here.
    inj.spec.flood_mult = 1.0
    recovery_ticks = 55
    for tick in range(flood_ticks, flood_ticks + recovery_ticks):
        await one_tick(tick, video_pkts=0)

    # One dwell (10 calm ticks) per downward rung: L0 within 4 dwells.
    assert gov.level == gov_mod.L_HEALTHY
    downs = [(t["from"], t["to"]) for t in gov.transitions][4:]
    assert downs == [(4, 3), (3, 2), (2, 1), (1, 0)]
    assert rt.shed_spatial_cap == plane.MAX_LAYERS - 1
    assert not rt.shed_pause_video
    assert rt.ingest._police_rate == 0.0

    # Audio continuity 100%: every offered audio packet egressed exactly
    # once (flood duplicates deduped), munged SNs contiguous.
    uniq = sorted(set(audio_sns))
    assert len(uniq) == flood_ticks + recovery_ticks
    assert len(audio_sns) == len(uniq)
    assert all(b - a == 1 for a, b in zip(uniq, uniq[1:]))

    # Governor actuation up AND down the ladder never retraced the tick.
    assert rt.compile_ledger.post_warmup == 0


# -- supervisor interaction: governed lateness is not a stall ---------------

async def test_supervisor_spares_governed_plane_restarts_wedged_one():
    """Restart-storm regression: a governed plane ticking 2x over its
    stall deadline must NOT be restarted (the governor owns slowness);
    a genuinely wedged plane still is, through the widened deadline."""
    rt = make_rt()
    rt.set_track(0, 0, published=True, is_video=False)
    rt.set_subscription(0, 0, 1, subscribed=True)
    # Streak thresholds out of reach: the level stays where the test
    # puts it regardless of what the slow ticks look like.
    gov = OverloadGovernor(rt, escalate_ticks=10**6, dwell_ticks=10**6)
    rt.governor = gov
    gov._set_level(1, "governed for test")

    inj = FaultInjector(FaultSpec(stall_every=1, stall_s=0.12))
    rt.fault = inj
    sup = PlaneSupervisor(
        rt, tick_deadline_s=0.05, warmup_deadline_s=10.0,
        check_interval_s=0.02, checkpoint_interval_s=60.0,
        max_restarts=5, overload_grace=10.0,
        backoff=BackoffPolicy(base=0.02, max_delay=0.1),
    )
    await sup.checkpoint_now()
    rt.start()
    sup.start()
    try:
        async def until(cond, timeout=30.0):
            deadline = asyncio.get_running_loop().time() + timeout
            while not cond():
                assert asyncio.get_running_loop().time() < deadline, \
                    "timed out waiting for supervisor"
                await asyncio.sleep(0.01)

        # Every tick takes ~0.12s against a 0.05s deadline: ungoverned,
        # the watchdog would restart; governed, the widened deadline
        # (0.5s) reads it as slow-but-progressing.
        base = rt.stats["ticks"]
        await until(lambda: rt.stats["ticks"] >= base + 6)
        assert sup.restarts == 0
        assert not sup.gave_up

        # Genuine wedge: stalls longer than even the widened deadline.
        inj.spec.stall_s = 1.5
        await until(lambda: sup.restarts >= 1)
        rt.fault = None  # the hang clears; restarted plane runs clean
        base = rt.stats["ticks"]
        await until(lambda: rt.stats["ticks"] >= base + 5)
        assert not sup.gave_up
    finally:
        await sup.stop()
        await rt.stop()


# -- admission control over the wire ----------------------------------------

async def test_max_rooms_rejection_and_debug_endpoint():
    async with running_server(
        configure=lambda cfg: setattr(cfg.limits, "max_rooms", 1)
    ) as server:
        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, server.port)
            await alice.connect("one", "alice")

            # Second room trips max_rooms: explicit leave, not a hang.
            bob = SignalClient(s, server.port)
            bob.ws = await s.ws_connect(
                f"ws://127.0.0.1:{server.port}/rtc"
                f"?access_token={token('bob', 'two')}"
            )
            bob._reader = asyncio.ensure_future(bob._read())
            leave = await bob.wait_for("leave")
            assert leave["reason"] == 7  # JOIN_FAILURE

            async with s.get(
                f"http://127.0.0.1:{server.port}/debug/overload"
            ) as r:
                assert r.status == 200
                j = await r.json()
            assert j["governor"]["level"] == 0
            assert j["admission_rejected"].get("room") == 1
            assert j["limits"]["max_rooms"] == 1
            assert "dropped_capacity" in j["ingest"]
            # The same refusal, attributed to its canonical cause
            # ("max rooms on node" → no_capacity).
            assert j["admission_denied_reasons"].get("no_capacity") == 1

            # The reason-labelled counter reaches the scrape endpoint
            # once a tick's observe_overload has run.
            deadline = asyncio.get_running_loop().time() + 5.0
            while True:
                async with s.get(
                    f"http://127.0.0.1:{server.port}/metrics"
                ) as r:
                    text = await r.text()
                if 'livekit_admission_denied_total{reason="no_capacity"} 1' \
                        in text:
                    break
                assert asyncio.get_running_loop().time() < deadline, \
                    "denied_total{reason} never reached /metrics"
                await asyncio.sleep(0.02)

            await alice.close()
            await bob.close()


async def test_governor_l4_rejects_joins_and_publishes_over_wire():
    async with running_server() as server:
        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, server.port)
            await alice.connect("lobby", "alice")

            gov = server.room_manager.governor
            assert gov is not None  # enabled by default
            gov._set_level(4, "test overload")

            # New join: explicit JOIN_FAILURE leave.
            bob = SignalClient(s, server.port)
            bob.ws = await s.ws_connect(
                f"ws://127.0.0.1:{server.port}/rtc"
                f"?access_token={token('bob', 'lobby')}"
            )
            bob._reader = asyncio.ensure_future(bob._read())
            leave = await bob.wait_for("leave")
            assert leave["reason"] == 7

            # Existing participant stays connected but new publishes are
            # refused with an explicit request_response error.
            await alice.send_signal(
                "add_track", {"cid": "mic", "type": 0, "name": "mic"}
            )
            rr = await alice.wait_for("request_response")
            assert rr["error"]["reason"] == "node_overloaded"
            assert rr["error"]["cid"] == "mic"
            assert gov.rejected.get("join", 0) >= 1
            assert gov.rejected.get("publish", 0) >= 1

            # Recovery reopens admission.
            gov._set_level(0, "test recovered")
            carol = SignalClient(s, server.port)
            join = await carol.connect("lobby", "carol")
            assert join["participant"]["identity"] == "carol"

            await alice.close()
            await bob.close()
            await carol.close()


def test_failover_restore_bypasses_transient_overload_ladder():
    """A 'restore' (failover adoption of a dead node's room) is existing
    load the fleet already admitted — the transient L4 ladder must never
    refuse it, or a busy fleet orphans rooms permanently exactly when a
    flash crowd makes every survivor late. Hard gates still apply:
    drain_hold stops restores (this node is leaving)."""
    rt = make_rt()
    gov = OverloadGovernor(rt, escalate_ticks=3, dwell_ticks=5)
    rt.governor = gov
    for _ in range(12):
        gov.on_tick(HOT)
    assert gov.level == gov_mod.L_REJECT
    assert not gov.should_admit("room")
    assert not gov.should_admit("join")
    assert gov.should_admit("restore")
    gov.hold_max()
    assert not gov.should_admit("restore")
    gov.release_hold()
    assert gov.should_admit("restore")


async def test_room_manager_restores_room_at_l4():
    """End-to-end through get_or_create_room: at L4 a client-driven
    create is refused with an explicit reason, while the failover
    orchestrator's admission_kind='restore' create proceeds."""
    from livekit_server_tpu.runtime import CapacityError

    async with running_server() as server:
        rm = server.room_manager
        gov = rm.governor
        assert gov is not None
        gov._set_level(4, "test overload")
        with pytest.raises(CapacityError, match="node overloaded"):
            await rm.get_or_create_room("orphan")
        room = await rm.get_or_create_room("orphan", admission_kind="restore")
        assert room is rm.rooms["orphan"]
        assert rm.admission_denied_reasons.get("overload", 0) == 1


# -- ingest drop split + policer --------------------------------------------

def test_ingest_drop_split_and_rx_symmetry():
    rt = make_rt()
    buf = rt.ingest
    rt.set_track(0, 0, published=True, is_video=False)

    # Capacity overflow: K=4 slots, 6 arrivals.
    for i in range(6):
        buf.push(PacketIn(room=0, track=0, sn=i, ts=0, size=10, payload=b"x"))
    assert buf.dropped_capacity == 2
    assert buf.dropped_fault == 0 and buf.dropped_policed == 0
    assert buf.dropped == 2  # aggregate property sums the split
    assert int(buf.rx_pkts[0, 0]) == 6  # drops still arrived on the wire

    # Fault drops count rx too (the old asymmetry: fault path returned
    # before accounting, skewing rx rates against capacity drops).
    buf.fault = FaultInjector(FaultSpec(seed=0, drop_pct=1.0))
    assert buf.push(
        PacketIn(room=0, track=0, sn=50, ts=0, size=10, payload=b"x")
    ) is False
    assert buf.dropped_fault == 1
    assert int(buf.rx_pkts[0, 0]) == 7
    assert buf.dropped == 3


def test_policer_scalar_video_only_with_refill():
    rt = make_rt()
    buf = rt.ingest
    rt.set_track(0, 0, published=True, is_video=False)
    rt.set_track(0, 1, published=True, is_video=True)
    # 200 pps at tick_ms=10 → 2 tokens refilled per drain; burst 2.
    buf.set_policer(200.0, 2.0, is_video=rt.meta.is_video)

    got = [
        buf.push(PacketIn(room=0, track=1, sn=i, ts=0, size=10, payload=b"v"))
        for i in range(4)
    ]
    assert got == [True, True, False, False]
    assert buf.dropped_policed == 2

    # Audio bypasses the bucket entirely.
    for i in range(4):
        assert buf.push(
            PacketIn(room=0, track=0, sn=10 + i, ts=0, size=10, payload=b"a")
        )
    assert buf.dropped_policed == 2 and buf.dropped_capacity == 0

    # drain() refills: 2 fresh tokens admit 2 more video packets.
    buf.drain()
    assert buf.push(PacketIn(room=0, track=1, sn=20, ts=0, size=10, payload=b"v"))
    assert buf.push(PacketIn(room=0, track=1, sn=21, ts=0, size=10, payload=b"v"))
    assert not buf.push(
        PacketIn(room=0, track=1, sn=22, ts=0, size=10, payload=b"v")
    )
    assert buf.dropped_policed == 3

    # Disarm: everything admitted again (up to slab capacity).
    buf.clear_policer()
    buf.drain()
    for i in range(4):
        assert buf.push(
            PacketIn(room=0, track=1, sn=30 + i, ts=0, size=10, payload=b"v")
        )
    assert buf.dropped_policed == 3


def test_policer_batch_matches_scalar_semantics():
    rt = make_rt()
    buf = rt.ingest
    rt.set_track(0, 0, published=True, is_video=False)
    rt.set_track(0, 1, published=True, is_video=True)
    buf.set_policer(100.0, 3.0, is_video=rt.meta.is_video)

    # 6 video + 2 audio interleaved: quota floor(3.0)=3 admits the first
    # three video arrivals, polices the rest; audio is exempt.
    track = np.array([1, 1, 1, 0, 1, 1, 0, 1], np.int64)
    n = len(track)
    zeros = np.zeros(n, np.int64)
    fal = np.zeros(n, bool)
    staged = buf.push_batch(
        np.zeros(n, np.int64),            # room
        track,
        zeros,                            # layer
        np.arange(n, dtype=np.int64),     # sn
        zeros,                            # ts
        fal,                              # ts_aligned
        zeros,                            # temporal
        fal,                              # keyframe
        fal,                              # layer_sync
        fal,                              # begin_pic
        fal,                              # marker
        zeros,                            # pid
        zeros,                            # tl0
        zeros,                            # keyidx
        np.full(n, 10, np.int64),         # size
        np.full(n, 20, np.int64),         # frame_ms
        np.full(n, 127, np.int64),        # audio_level
        zeros,                            # arrival_rtp
        np.arange(n, dtype=np.int64),     # pay_start
        np.ones(n, np.int64),             # pay_length
        b"x" * n,                         # blob
    )
    assert staged == 5  # 3 video within quota + 2 exempt audio
    assert buf.dropped_policed == 3
    assert buf.dropped_capacity == 0
    assert int(buf.rx_pkts[0, 1]) == 6  # policed arrivals still counted rx


# -- flood fault mode --------------------------------------------------------

def test_flood_copies_seeded_and_room_filtered():
    # Fractional multiplier: the extra-copy draw is seeded.
    a = FaultInjector(FaultSpec(seed=3, flood_mult=2.5))
    b = FaultInjector(FaultSpec(seed=3, flood_mult=2.5))
    sa = [a.flood_copies(0) for _ in range(40)]
    assert sa == [b.flood_copies(0) for _ in range(40)]
    assert set(sa) == {1, 2}  # 2.5x → 1 or 2 extra copies
    assert a.stats.flooded == sum(sa)
    c = FaultInjector(FaultSpec(seed=4, flood_mult=2.5))
    assert [c.flood_copies(0) for _ in range(40)] != sa

    # Integer multiplier draws nothing: the drop/dup/delay verdict
    # sequence is alignment-identical to a non-flood run, same seed.
    plain = FaultInjector(FaultSpec(seed=9, drop_pct=0.2))
    ref = [plain.on_packet(None, i) for i in range(100)]
    flooded = FaultInjector(FaultSpec(seed=9, drop_pct=0.2, flood_mult=4.0))
    got = []
    for i in range(100):
        got.append(flooded.on_packet(None, i))
        flooded.flood_copies(0)
    assert got == ref

    # Room filter: only listed rooms flood.
    f = FaultInjector(FaultSpec(seed=0, flood_mult=4.0, flood_rooms=(1,)))
    assert f.flood_copies(0) == 0
    assert f.flood_copies(1) == 3


def test_flood_copies_staged_and_rx_counted():
    rt = make_rt()
    buf = rt.ingest
    buf.fault = FaultInjector(FaultSpec(seed=0, flood_mult=4.0))
    rt.set_track(0, 0, published=True, is_video=False)
    assert buf.push(PacketIn(room=0, track=0, sn=1, ts=0, size=10, payload=b"x"))
    # Original + 3 copies staged, all counted as wire arrivals.
    assert int(buf._count[0, 0]) == 4
    assert int(buf.rx_pkts[0, 0]) == 4
    assert buf.fault.stats.flooded == 3


# -- queue-overflow visibility ----------------------------------------------

async def test_queue_overflow_counters_and_gauges():
    from livekit_server_tpu.routing.kv import MemoryBus, Subscription
    from livekit_server_tpu.routing.messagechannel import (
        ChannelFull,
        MessageChannel,
    )
    from livekit_server_tpu.telemetry.service import TelemetryService

    # Class counters accumulate process-wide: assert deltas.
    mc_base = MessageChannel.total_dropped
    sub_base = Subscription.total_dropped

    ch = MessageChannel(size=1)
    ch.write_message({"n": 1})
    with pytest.raises(ChannelFull):
        ch.write_message({"n": 2})
    assert ch.dropped == 1
    assert MessageChannel.total_dropped == mc_base + 1

    bus = MemoryBus()
    sub = bus.subscribe("chan", size=1)
    await bus.publish("chan", "m1")
    await bus.publish("chan", "m2")  # overflow: silently counted, not lost-silently
    assert sub.dropped == 1
    assert Subscription.total_dropped == sub_base + 1

    telem = TelemetryService(Config())
    telem.observe_queue_drops()
    assert (
        telem.gauges["livekit_signal_channel_dropped_total"]
        == MessageChannel.total_dropped
    )
    assert (
        telem.gauges["livekit_bus_sub_dropped_total"]
        == Subscription.total_dropped
    )


def test_governor_telemetry_gauges():
    from livekit_server_tpu.telemetry.service import TelemetryService

    rt = make_rt()
    gov = OverloadGovernor(rt)
    rt.governor = gov
    gov._set_level(1, "test")
    gov.note_rejection("join")

    telem = TelemetryService(Config())
    telem.observe_overload(gov.stats_dict())
    assert telem.gauges["livekit_governor_level"] == 1
    assert telem.gauges["livekit_governor_escalations_total"] == 1
    assert telem.gauges['livekit_admission_rejected_total{kind="join"}'] == 1
    assert telem.gauges["livekit_ingest_dropped_capacity_total"] == 0

    # Reason-labelled denial breakdown (roommanager feeds this from
    # admission_denied_reasons via _dispatch_tick).
    telem.observe_overload({**gov.stats_dict(),
                            "denied_reasons": {"overload": 3, "draining": 1}})
    assert telem.gauges[
        'livekit_admission_denied_total{reason="overload"}'] == 3
    assert telem.gauges[
        'livekit_admission_denied_total{reason="draining"}'] == 1

    snap = gov.snapshot()
    assert snap["level"] == 1
    assert snap["transitions"][0]["to"] == 1
    assert snap["thresholds"]["dwell_ticks"] == gov.dwell_ticks


def test_denial_reason_labels_cover_every_refusal_string():
    """Every human-readable refusal `_admission_denied` can produce maps
    to one of the four canonical causes — an unmapped string would fall
    back to `overload` and silently misattribute the denial."""
    import inspect
    import re

    from livekit_server_tpu.service import roommanager
    from livekit_server_tpu.service.roommanager import DENIAL_REASON_LABELS

    assert set(DENIAL_REASON_LABELS.values()) <= {
        "overload", "draining", "no_capacity", "fenced"
    }
    src = inspect.getsource(roommanager.RoomManager._admission_denied)
    produced = set(re.findall(r'reason = "([^"]+)"', src))
    assert produced, "refusal strings moved; update this scrape"
    unmapped = produced - set(DENIAL_REASON_LABELS)
    assert not unmapped, f"refusal strings without a canonical label: {unmapped}"
    stale = set(DENIAL_REASON_LABELS) - produced
    assert not stale, f"labels for refusals that no longer exist: {stale}"
