"""Tier-1 cross-plane drills driven by the traffic twin.

Drill A — flash-crowd reconnect storm: a regional cut's reinvite storm
plus a seeded ingest flood drives the governor up the ladder ONE rung at
a time; new joins at L4 are refused with the explicit ``overload``
reason while every already-admitted subscriber keeps 100% audio
continuity with zero duplicate wire packets, and the governor walks back
to L0 after the storm. Re-run at the same seed, every counter-derived
SLO is identical.

Drill B — rolling drain under churn: one node of a two-node bus drains
while joins/leaves continue. Every room migrates off the draining node
exactly once (commits with zero rollbacks/timeouts), joins routed at the
draining node are refused with the ``draining`` reason, no duplicate
packets reach the wire through the handoff, and the load reappears on
the survivor.
"""

import pytest

from livekit_server_tpu.runtime.traffic_twin import (
    ChurnSegment,
    Incident,
    Scenario,
    TrafficTwin,
)

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def flash_crowd_scenario(seed: int = 29) -> Scenario:
    # 18 flood ticks at escalate_ticks=3 is enough runway to climb all
    # four rungs; the 50 post-storm ticks cover four dwell windows down.
    return Scenario(
        seed=seed,
        segments=(ChurnSegment(ticks=80, join_rate=0.8, leave_rate=0.01),),
        incidents=(Incident("flash_crowd", at=12, ticks=18,
                            region="us-east", magnitude=8.0),),
        regions=(("us-east", 1.0),),
        video_room_frac=0.5,
    )


def make_flash_twin() -> TrafficTwin:
    return TrafficTwin(
        flash_crowd_scenario(), nodes=1,
        plane={"rooms": 48, "tracks_per_room": 4, "pkts_per_track": 8,
               "subs_per_room": 4, "tick_ms": 10},
    )


async def test_flash_crowd_storm_sheds_in_ladder_order_audio_survives():
    twin = make_flash_twin()
    rep = await twin.run(1.0)

    # The governor climbed the ladder strictly one rung at a time, in
    # order, all the way to L4 (video sheds before anything else; joins
    # are only refused at the top rung).
    ups = [(t["from"], t["to"])
           for t in twin.debug["governor_transitions"][0]
           if t["to"] > t["from"]]
    assert ups[:4] == [(0, 1), (1, 2), (2, 3), (3, 4)], ups
    assert all(b - a == 1 for a, b in ups), f"skipped a rung: {ups}"
    assert rep.rung_residency.get("L4", 0) > 0

    # Admission refusals during the storm carry the explicit overload
    # reason (surfaced at /debug/overload and the denied_total metric).
    assert rep.denial_reasons.get("overload", 0) > 0, rep.denial_reasons

    # Every already-admitted subscriber rode through with 100% audio
    # continuity and exactly-once delivery.
    assert rep.audio_expected > 0
    assert rep.audio_gaps == 0
    assert rep.audio_continuity == 1.0
    assert rep.dup_wire_packets == 0

    # After the storm clears the ladder walks back down: recovery is
    # finite (not the -1 never-recovered sentinel).
    assert rep.recovery_ticks.get("flash_crowd", -1) >= 0, rep.recovery_ticks
    downs = [(t["from"], t["to"])
             for t in twin.debug["governor_transitions"][0]
             if t["to"] < t["from"]]
    assert all(a - b == 1 for a, b in downs), f"skipped down: {downs}"


async def test_flash_crowd_storm_deterministic_across_reruns():
    rep1 = await make_flash_twin().run(1.0)
    rep2 = await make_flash_twin().run(1.0)
    assert rep1.deterministic_dict() == rep2.deterministic_dict()


def drain_scenario(seed: int = 31) -> Scenario:
    return Scenario(
        seed=seed,
        segments=(ChurnSegment(ticks=50, join_rate=0.6, leave_rate=0.01),),
        incidents=(Incident("rolling_drain", at=20, ticks=10,
                            region="eu"),),
        regions=(("us-east", 0.55), ("eu", 0.45)),
        video_room_frac=0.3,
    )


async def test_rolling_drain_under_churn_migrates_each_room_once():
    twin = TrafficTwin(
        drain_scenario(), nodes=2,
        plane={"rooms": 24, "tracks_per_room": 4, "pkts_per_track": 8,
               "subs_per_room": 4, "tick_ms": 10},
    )
    rep = await twin.run(1.0)

    # Every room on the drained node moved exactly once: all commits, no
    # rollbacks or timeouts, and the twin's aggregate agrees.
    mig = twin.debug["migration_stats"]
    commits = sum(m.get("commits", 0) for m in mig)
    assert commits >= 1, mig
    assert sum(m.get("rollbacks", 0) for m in mig) == 0, mig
    assert sum(m.get("timeouts", 0) for m in mig) == 0, mig
    assert rep.migrations == commits

    # The drained node ends empty; the load reappears on the survivor.
    rooms_final = twin.debug["rooms_final"]
    assert rooms_final[1] == [], rooms_final
    assert len(rooms_final[0]) > 0

    # Joins routed at the draining node were refused with the explicit
    # reason, not black-holed.
    assert rep.denial_reasons.get("draining", 0) > 0, rep.denial_reasons

    # Exactly-once on the wire through the handoff.
    assert rep.dup_wire_packets == 0
    assert rep.audio_received > 0
