"""Fleet-plane primitives: epoch CAS, RoomFence, LeaseGuard, skew-
tolerant liveness, and the fleet/fault config surface.

These are the single-process units behind the split-brain drills in
tests/test_multinode.py: exactly-one-winner claims, fenced stale
writes, fence/recover transitions, and the monotonic-heartbeat
freshness rule — all on a MemoryBus, no sockets.
"""

from __future__ import annotations

import time

import pytest

from livekit_server_tpu.config import ConfigError, load_config
from livekit_server_tpu.routing.fleet import (
    ROOM_EPOCH_PREFIX,
    FencedWriteRejected,
    LeaseGuard,
    RoomFence,
)
from livekit_server_tpu.routing.kv import MemoryBus
from livekit_server_tpu.routing.node import (
    SKEW_ALLOWANCE_S,
    LocalNode,
    NodeState,
    NodeStats,
)
from livekit_server_tpu.runtime.faultinject import FaultInjector


# -- bus.cas ----------------------------------------------------------------

async def test_cas_absent_expect_and_mismatch():
    bus = MemoryBus()
    # expect=None means "key absent": only one creator wins.
    assert await bus.cas("k", None, "a")
    assert not await bus.cas("k", None, "b")
    assert await bus.get("k") == "a"
    # exact-string compare; a stale expect loses without writing
    assert not await bus.cas("k", "stale", "c")
    assert await bus.cas("k", "a", "c")
    assert await bus.get("k") == "c"


async def test_cas_expired_key_counts_as_absent():
    bus = MemoryBus()
    await bus.set("k", "a", ttl=0.01)
    time.sleep(0.03)
    assert not await bus.cas("k", "a", "b")   # value expired away
    assert await bus.cas("k", None, "b")


# -- RoomFence --------------------------------------------------------------

async def test_claim_is_exactly_one_winner():
    bus = MemoryBus()
    a = RoomFence(bus, "node-a")
    b = RoomFence(bus, "node-b")
    assert await a.claim("r")
    assert a.epoch_of("r") == 1
    # b claims over a's live record: epoch moves to 2 and a's guarded
    # writes are dead
    assert await b.claim("r")
    assert b.epoch_of("r") == 2
    assert (await a.read("r")) == (2, "node-b")
    # idempotent re-claim while the record still names us
    assert await b.claim("r")
    assert b.epoch_of("r") == 2
    assert b.stats["claims"] == 1


async def test_claim_race_from_same_record():
    bus = MemoryBus()
    a = RoomFence(bus, "node-a")
    b = RoomFence(bus, "node-b")
    dead = RoomFence(bus, "node-dead")
    assert await dead.claim("r")
    # both survivors race a takeover from the dead node's record: the
    # epoch CAS admits exactly one
    key = ROOM_EPOCH_PREFIX + "r"
    cur = await bus.get(key)
    won_a = await bus.cas(key, cur, '{"e":2,"n":"node-a"}')
    won_b = await bus.cas(key, cur, '{"e":2,"n":"node-b"}')
    assert [won_a, won_b].count(True) == 1


async def test_assume_adopts_but_never_steals():
    bus = MemoryBus()
    a = RoomFence(bus, "node-a")
    b = RoomFence(bus, "node-b")
    # unclaimed → assume claims
    assert await a.assume("r")
    assert a.owns("r")
    # record names someone else → a recovered fenced node must NOT
    # steal it back
    assert await b.claim("r")
    a.forget("r")
    assert not await a.assume("r")
    assert not a.owns("r")
    # record names me (the target side of a transfer) → adopt
    assert await b.transfer("r", "node-a")
    assert await a.assume("r")
    assert a.epoch_of("r") == 3


async def test_guarded_write_fences_stale_owner():
    bus = MemoryBus()
    a = RoomFence(bus, "node-a")
    b = RoomFence(bus, "node-b")
    lost: list[str] = []
    a.on_lost.append(lost.append)
    assert await a.claim("r")
    await a.guarded_set("r", "room_checkpoint:r:gen", "v1", 30.0)
    assert await b.claim("r")       # takeover while a is dark
    with pytest.raises(FencedWriteRejected):
        await a.guarded_set("r", "room_checkpoint:r:gen", "v2-stale", 30.0)
    # the stale write never landed, the loss was surfaced exactly once
    assert await bus.get("room_checkpoint:r:gen") == "v1"
    assert lost == ["r"]
    assert not a.owns("r")
    assert a.stats["writes_fenced"] == 1
    # and a's guarded deletes are equally dead
    with pytest.raises(FencedWriteRejected):
        await a.guarded_delete("r", "room_checkpoint:r:gen")


async def test_transfer_moves_epoch_and_kills_source_writes():
    bus = MemoryBus()
    src = RoomFence(bus, "node-src")
    dst = RoomFence(bus, "node-dst")
    assert await src.claim("r")
    assert await src.transfer("r", "node-dst")
    assert (await src.read("r")) == (2, "node-dst")
    assert not src.owns("r")
    assert await dst.assume("r")
    with pytest.raises(FencedWriteRejected):
        await src.guarded_set("r", "room_checkpoint:r:gen", "stale")


async def test_transfer_losing_cas_fires_on_lost():
    bus = MemoryBus()
    src = RoomFence(bus, "node-src")
    thief = RoomFence(bus, "node-thief")
    lost: list[str] = []
    src.on_lost.append(lost.append)
    assert await src.claim("r")
    assert await thief.claim("r")
    assert not await src.transfer("r", "node-dst")
    assert lost == ["r"]
    assert (await src.read("r")) == (2, "node-thief")


async def test_release_spares_racing_claimant():
    bus = MemoryBus()
    a = RoomFence(bus, "node-a")
    b = RoomFence(bus, "node-b")
    assert await a.claim("r")
    await a.release("r")
    assert (await a.read("r")) == (0, "")     # record gone
    # release after a racing claim must not delete the winner's record
    assert await a.claim("r")
    assert await b.claim("r")
    await a.release("r")
    assert (await b.read("r")) == (2, "node-b")


# -- LeaseGuard -------------------------------------------------------------

def test_lease_guard_fence_and_recover():
    clock = [0.0]
    g = LeaseGuard(fence_grace_s=5.0, clock=lambda: clock[0])
    assert g.observe(True) == ""
    clock[0] = 3.0
    assert g.observe(False) == ""          # within grace
    clock[0] = 5.5
    assert g.observe(False) == "fence"     # past grace: go silent
    assert g.fenced and g.fences == 1
    clock[0] = 9.0
    assert g.observe(False) == ""          # already fenced, no re-fire
    assert g.observe(True) == "recover"    # bus is back
    # the caller unfences only AFTER reconciling lost rooms
    assert g.fenced
    g.unfence()
    assert not g.fenced
    assert g.observe(True) == ""
    assert g.age() == 0.0


def test_lease_guard_blip_within_grace_never_fences():
    clock = [0.0]
    g = LeaseGuard(fence_grace_s=5.0, clock=lambda: clock[0])
    for t in (1.0, 2.0, 4.9):
        clock[0] = t
        assert g.observe(False) == ""
    clock[0] = 5.0
    assert g.observe(True) == ""           # refresh landed in time
    clock[0] = 9.0
    assert g.observe(False) == ""          # grace restarts from last ok


# -- skew-tolerant liveness -------------------------------------------------

def _peer(node_id: str, **stats) -> LocalNode:
    return LocalNode(
        node_id=node_id, state=NodeState.SERVING, stats=NodeStats(**stats)
    )


def test_is_available_monotonic_stamp_advances():
    LocalNode._freshness.clear()
    # wall clock is hours off — irrelevant while mono_at advances
    peer = _peer("n1", updated_at=time.time() - 7200.0, mono_at=100.0)
    assert peer.is_available(max_age=0.5)
    peer.stats.mono_at = 101.0
    assert peer.is_available(max_age=0.5)


def test_is_available_frozen_stamp_ages_on_receiver_clock():
    LocalNode._freshness.clear()
    peer = _peer("n2", updated_at=time.time(), mono_at=100.0)
    assert peer.is_available(max_age=0.05)   # first observation
    time.sleep(0.08)
    # stamp stopped advancing: dead by OUR clock, fresh wall time or not
    peer.stats.updated_at = time.time()
    assert not peer.is_available(max_age=0.05)


def test_is_available_stampless_fallback_widened_by_skew():
    LocalNode._freshness.clear()
    skewed = time.time() - 1.0 - SKEW_ALLOWANCE_S / 2
    peer = _peer("n3", updated_at=skewed, mono_at=0.0)
    assert peer.is_available(max_age=1.0)    # inside widened window
    peer.stats.updated_at = time.time() - 1.0 - SKEW_ALLOWANCE_S * 2
    assert not peer.is_available(max_age=1.0)


def test_is_available_not_serving_is_never_available():
    LocalNode._freshness.clear()
    peer = _peer("n4", updated_at=time.time(), mono_at=100.0)
    peer.state = NodeState.SHUTTING_DOWN
    assert not peer.is_available(max_age=30.0)


# -- config surface ---------------------------------------------------------

def _cfg(extra: str = ""):
    return load_config(yaml_text="development: true\n" + extra)


def test_fleet_config_defaults_and_validation():
    cfg = _cfg()
    assert cfg.fleet.enabled
    assert cfg.fleet.fence_grace_s <= 2 * cfg.kv.lease_ttl_s
    assert (
        cfg.fleet.fence_grace_s
        < cfg.kv.lease_ttl_s + cfg.kv.failover_interval_s
    )
    with pytest.raises(ConfigError, match="fence_grace_s"):
        _cfg("fleet:\n  fence_grace_s: 0\n")
    # grace beyond 2× lease_ttl: a blip could mute a healthy node too long
    with pytest.raises(ConfigError, match="fence_grace_s"):
        _cfg("fleet:\n  fence_grace_s: 100.0\n")
    # grace must beat the earliest takeover (lease_ttl + failover_interval)
    with pytest.raises(ConfigError, match="fence_grace_s"):
        _cfg(
            "fleet:\n  fence_grace_s: 8.0\n"
            "kv:\n  lease_ttl_s: 6.0\n  failover_interval_s: 1.0\n"
        )
    # disabled fleet skips the timeline coupling
    cfg = _cfg("fleet:\n  enabled: false\n  fence_grace_s: 100.0\n")
    assert not cfg.fleet.enabled


def test_fault_partition_config_maps_to_spec():
    cfg = _cfg(
        "faults:\n"
        "  enabled: true\n"
        "  seed: 7\n"
        "  bus_partition_groups: [[0, 1], [2]]\n"
        "  bus_partition_tick: 50\n"
        "  bus_heal_at_tick: 200\n"
        "  bus_asym_pairs: [[2, 0]]\n"
    )
    spec = FaultInjector.from_config(cfg.faults).spec
    assert spec.bus_partition_groups == ((0, 1), (2,))
    assert spec.bus_partition_tick == 50
    assert spec.bus_heal_at_tick == 200
    assert spec.bus_asym_pairs == ((2, 0),)
    with pytest.raises(ConfigError, match="bus_partition_tick"):
        _cfg("faults:\n  enabled: true\n  bus_partition_tick: -2\n")
