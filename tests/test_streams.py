"""Stream tracker, sequencer/NACK, RED, and pacer op tests.

Reference parity: streamtracker_packet_test.go shapes (live/stop cycles),
sequencer.go NACK replay semantics, redreceiver encode limits,
pacer/leaky_bucket drain behavior.
"""

import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.ops import pacer, red, sequencer, streamtracker


# ---- stream tracker ---------------------------------------------------

def test_tracker_live_and_stop_cycle():
    p = streamtracker.TrackerParams(cycle_ms=100, min_pkts=3, stop_ms=200)
    st = streamtracker.init_state(2)
    # stream 0 gets 2 pkts/tick, stream 1 silent.
    for _ in range(2):
        st, status, changed, bps = streamtracker.update_tick(
            st, p, jnp.asarray([2, 0]), jnp.asarray([2400, 0]), 50
        )
    assert status.tolist() == [streamtracker.LIVE, streamtracker.STOPPED]
    assert float(bps[0]) > 0
    # silence stops it after stop_ms
    for _ in range(4):
        st, status, changed, bps = streamtracker.update_tick(
            st, p, jnp.asarray([0, 0]), jnp.asarray([0, 0]), 50
        )
    assert status.tolist() == [streamtracker.STOPPED, streamtracker.STOPPED]
    assert float(bps[0]) == 0.0


def test_tracker_bitrate_tracks_input():
    p = streamtracker.TrackerParams(cycle_ms=100, min_pkts=1, bitrate_alpha=1.0)
    st = streamtracker.init_state(1)
    st, _, _, bps = streamtracker.update_tick(st, p, jnp.asarray([10]), jnp.asarray([12500]), 100)
    # 12500 B over 100 ms = 1 Mbps
    assert abs(float(bps[0]) - 1_000_000) < 1e-3


# ---- sequencer / NACK -------------------------------------------------

def _push(st, out_sn, sent, keys, now_ms, track=None, ts=None, meta=None):
    P, S = out_sn.shape
    track = track if track is not None else jnp.zeros((P,), jnp.int32)
    ts = ts if ts is not None else out_sn * 10
    meta = meta if meta is not None else jnp.zeros((P, S), jnp.int32)
    return sequencer.push_tick(st, out_sn, ts, meta, track, sent, keys, now_ms)


def _lookup(st, nacks, now_ms, rtt, track=None, max_age=1 << 30):
    track = track if track is not None else jnp.zeros_like(nacks)
    return sequencer.lookup_nacks(st, nacks, track, now_ms, rtt, max_age)


def test_sequencer_push_and_nack_replay():
    st = sequencer.init_state(2)
    out_sn = jnp.asarray([[100, 200], [101, 201]], jnp.int32)  # [P=2, S=2]
    sent = jnp.asarray([[True, True], [True, False]])
    st = _push(st, out_sn, sent, jnp.asarray([7, 8], jnp.int32), 1000)

    nacks = jnp.asarray([[100, 101], [200, 201]], jnp.int32)
    st, key, ts, meta, ok = _lookup(st, nacks, 1100, jnp.asarray([50, 50], jnp.int32))
    assert ok.tolist() == [[True, True], [True, False]]  # 201 never sent to sub1
    assert key.tolist() == [[7, 8], [7, -1]]
    assert int(ts[0, 0]) == 1000  # original munged TS travels with the slot


def test_sequencer_track_mismatch_rejected():
    st = sequencer.init_state(1)
    st = _push(
        st, jnp.asarray([[100]], jnp.int32), jnp.asarray([[True]]),
        jnp.asarray([7], jnp.int32), 0, track=jnp.asarray([2], jnp.int32),
    )
    # NACK for the same SN on a different track misses (shared-ring safety).
    st, key, _ts, _m, ok = _lookup(
        st, jnp.asarray([[100]], jnp.int32), 10, jnp.asarray([1], jnp.int32),
        track=jnp.asarray([[1]], jnp.int32),
    )
    assert not bool(ok[0, 0])
    st, key, _ts, _m, ok = _lookup(
        st, jnp.asarray([[100]], jnp.int32), 10, jnp.asarray([1], jnp.int32),
        track=jnp.asarray([[2]], jnp.int32),
    )
    assert bool(ok[0, 0]) and int(key[0, 0]) == 7


def test_sequencer_vp8_meta_roundtrip():
    pid, tl0, ki = 12345, 200, 17
    meta = sequencer.pack_meta(
        jnp.asarray(pid), jnp.asarray(tl0), jnp.asarray(ki)
    )
    p, t, k = sequencer.unpack_meta(int(meta))
    assert (p, t, k) == (pid, tl0, ki)


def test_sequencer_rtt_throttle():
    st = sequencer.init_state(1)
    st = _push(
        st, jnp.asarray([[500]], jnp.int32), jnp.asarray([[True]]),
        jnp.asarray([3], jnp.int32), 0,
    )
    nack = jnp.asarray([[500]], jnp.int32)
    st, key, _ts, _m, ok = _lookup(st, nack, 10, jnp.asarray([100], jnp.int32))
    assert bool(ok[0, 0])
    # immediate repeat within RTT → throttled
    st, key, _ts, _m, ok = _lookup(st, nack, 50, jnp.asarray([100], jnp.int32))
    assert not bool(ok[0, 0])
    # after RTT → replayable again
    st, key, _ts, _m, ok = _lookup(st, nack, 200, jnp.asarray([100], jnp.int32))
    assert bool(ok[0, 0])


def test_sequencer_age_gate():
    st = sequencer.init_state(1)
    st = _push(
        st, jnp.asarray([[500]], jnp.int32), jnp.asarray([[True]]),
        jnp.asarray([3], jnp.int32), 0,
    )
    # Entry older than the host slab window must not resolve.
    st, key, _ts, _m, ok = _lookup(
        st, jnp.asarray([[500]], jnp.int32), 700, jnp.asarray([10], jnp.int32),
        max_age=620,
    )
    assert not bool(ok[0, 0])


def test_sequencer_unknown_sn_rejected():
    st = sequencer.init_state(1)
    st, key, _ts, _m, ok = _lookup(
        st, jnp.asarray([[12345]], jnp.int32), 0, jnp.asarray([0], jnp.int32)
    )
    assert not bool(ok[0, 0]) and int(key[0, 0]) == -1


# ---- RED --------------------------------------------------------------

def test_red_plan_attaches_previous_packets():
    st = red.init_state(1)
    sn = jnp.asarray([[10, 11, 12]], jnp.int32)
    ts = jnp.asarray([[960, 1920, 2880]], jnp.int32)
    ln = jnp.asarray([[100, 100, 100]], jnp.int32)
    valid = jnp.ones((1, 3), bool)
    st, r_sn, r_off, r_len, r_ok = red.encode_plan_tick(st, sn, ts, ln, valid)
    # pkt 0 has no history; pkt 1 carries pkt 0; pkt 2 carries 1 and 0.
    assert not bool(r_ok[0, 0].any())
    assert bool(r_ok[0, 1, 0]) and int(r_sn[0, 1, 0]) == 10 and int(r_off[0, 1, 0]) == 960
    assert r_ok[0, 2].tolist() == [True, True]
    assert int(r_sn[0, 2, 1]) == 10 and int(r_off[0, 2, 1]) == 1920


def test_red_offset_limit():
    st = red.init_state(1)
    # Huge TS gap: redundancy no longer expressible in 14 bits.
    st, *_ = red.encode_plan_tick(
        st, jnp.asarray([[1]], jnp.int32), jnp.asarray([[0]], jnp.int32),
        jnp.asarray([[50]], jnp.int32), jnp.ones((1, 1), bool),
    )
    st, r_sn, r_off, r_len, r_ok = red.encode_plan_tick(
        st, jnp.asarray([[2]], jnp.int32), jnp.asarray([[20000]], jnp.int32),
        jnp.asarray([[50]], jnp.int32), jnp.ones((1, 1), bool),
    )
    assert not bool(r_ok[0, 0, 0])


# ---- pacer ------------------------------------------------------------

def test_pacer_drains_at_rate():
    p = pacer.PacerParams(burst_ms=100)
    st = pacer.init_state(1, initial_rate=800_000.0)  # 100 KB/s
    rate = jnp.asarray([800_000.0], jnp.float32)
    # enqueue 30 KB; at 100 KB/s and 100 ms ticks → 10 KB allowed per tick
    st, allowed, backlog = pacer.update_tick(st, p, jnp.asarray([30_000.0]), rate, 100)
    assert abs(float(allowed[0]) - 10_000) < 1
    assert abs(float(backlog[0]) - 20_000) < 1
    st, allowed, backlog = pacer.update_tick(st, p, jnp.asarray([0.0]), rate, 100)
    assert abs(float(allowed[0]) - 10_000) < 1
    st, allowed, backlog = pacer.update_tick(st, p, jnp.asarray([0.0]), rate, 100)
    assert abs(float(backlog[0])) < 1  # fully drained


def test_pacer_burst_cap():
    p = pacer.PacerParams(burst_ms=100)
    st = pacer.init_state(1, initial_rate=800_000.0)
    rate = jnp.asarray([800_000.0], jnp.float32)
    # long idle: tokens cap at burst depth (10 KB), not unbounded
    for _ in range(20):
        st, _, _ = pacer.update_tick(st, p, jnp.asarray([0.0]), rate, 100)
    st, allowed, _ = pacer.update_tick(st, p, jnp.asarray([50_000.0]), rate, 100)
    assert float(allowed[0]) <= 10_000 + 1