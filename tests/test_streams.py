"""Stream tracker, sequencer/NACK, RED, and pacer op tests.

Reference parity: streamtracker_packet_test.go shapes (live/stop cycles),
sequencer.go NACK replay semantics, redreceiver encode limits,
pacer/leaky_bucket drain behavior.
"""

import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.ops import pacer, red, streamtracker


# ---- stream tracker ---------------------------------------------------

def test_tracker_live_and_stop_cycle():
    p = streamtracker.TrackerParams(cycle_ms=100, min_pkts=3, stop_ms=200)
    st = streamtracker.init_state(2)
    # stream 0 gets 2 pkts/tick, stream 1 silent.
    for _ in range(2):
        st, status, changed, bps, _fps = streamtracker.update_tick(
            st, p, jnp.asarray([2, 0]), jnp.asarray([2400, 0]), 50
        )
    assert status.tolist() == [streamtracker.LIVE, streamtracker.STOPPED]
    assert float(bps[0]) > 0
    # silence stops it after stop_ms
    for _ in range(4):
        st, status, changed, bps, _fps = streamtracker.update_tick(
            st, p, jnp.asarray([0, 0]), jnp.asarray([0, 0]), 50
        )
    assert status.tolist() == [streamtracker.STOPPED, streamtracker.STOPPED]
    assert float(bps[0]) == 0.0


def test_tracker_bitrate_tracks_input():
    p = streamtracker.TrackerParams(cycle_ms=100, min_pkts=1, bitrate_alpha=1.0)
    st = streamtracker.init_state(1)
    st, _, _, bps, _fps = streamtracker.update_tick(st, p, jnp.asarray([10]), jnp.asarray([12500]), 100)
    # 12500 B over 100 ms = 1 Mbps
    assert abs(float(bps[0]) - 1_000_000) < 1e-3


# ---- host sequencer / NACK --------------------------------------------
# (pkg/sfu/sequencer.go semantics, host-side: the ring feeds from the
# egress batch and resolves NACKs at RTCP time — plane_runtime.HostSequencer)

def _mini_runtime():
    from livekit_server_tpu.models import plane as plane_mod
    from livekit_server_tpu.runtime import PlaneRuntime
    from livekit_server_tpu.runtime.ingest import PacketIn

    rt = PlaneRuntime(plane_mod.PlaneDims(1, 2, 4, 2), tick_ms=10)
    rt.set_track(0, 0, published=True, is_video=False)
    rt.set_subscription(0, 0, 1, subscribed=True)
    return rt, PacketIn


async def test_host_sequencer_resolve_replay():
    rt, PacketIn = _mini_runtime()
    for i in range(3):
        rt.ingest.push(PacketIn(room=0, track=0, sn=600 + i, ts=960 * i,
                                size=5, payload=b"opus" + bytes([i])))
        await rt.step_once()
    # The ring learned this tick's sends from the egress batch.
    reps = rt.resolve_nacks(0, 1, 0, [601])
    assert len(reps) == 1
    rp = reps[0]
    assert (rp.room, rp.sub, rp.track, rp.sn) == (0, 1, 0, 601)
    assert rp.payload == b"opus\x01"
    # Unknown SN and wrong track miss (shared-ring safety).
    assert rt.resolve_nacks(0, 1, 0, [9999]) == []
    assert rt.resolve_nacks(0, 1, 1, [600]) == []


async def test_host_sequencer_rtt_throttle_and_age_gate():
    from livekit_server_tpu.models import plane as plane_mod

    rt, PacketIn = _mini_runtime()
    rt.ingest.push(PacketIn(room=0, track=0, sn=700, ts=0, size=4, payload=b"pay!"))
    await rt.step_once()
    assert len(rt.resolve_nacks(0, 1, 0, [700])) == 1
    # Immediate duplicate within RTT (default 100 ms) → throttled.
    assert rt.resolve_nacks(0, 1, 0, [700]) == []
    # After the throttle clears → replayable again.
    slot = 700 & (rt.host_seq.RING - 1)
    rt.host_seq.last_ms[0, 1, slot] -= 10_000
    assert len(rt.resolve_nacks(0, 1, 0, [700])) == 1
    # Entry older than the slab window must not resolve (slot recycled).
    rt.host_seq.last_ms[0, 1, slot] -= 10_000
    rt.host_seq.at_tick[0, 1, slot] -= plane_mod.SLAB_WINDOW
    assert rt.resolve_nacks(0, 1, 0, [700]) == []


async def test_host_sequencer_ring_eviction():
    rt, PacketIn = _mini_runtime()
    RING = rt.host_seq.RING
    rt.ingest.push(PacketIn(room=0, track=0, sn=100, ts=0, size=1, payload=b"a"))
    await rt.step_once()
    # A later send whose SN aliases the same slot evicts the old entry.
    rt.ingest.push(PacketIn(room=0, track=0, sn=100 + RING, ts=10, size=1,
                            payload=b"b"))
    await rt.step_once()
    assert rt.resolve_nacks(0, 1, 0, [100]) == []           # evicted
    reps = rt.resolve_nacks(0, 1, 0, [(100 + RING) & 0xFFFF])
    assert len(reps) == 1 and reps[0].payload == b"b"


# ---- RED --------------------------------------------------------------

def test_red_plan_attaches_previous_packets():
    st = red.init_state(1)
    sn = jnp.asarray([[10, 11, 12]], jnp.int32)
    ts = jnp.asarray([[960, 1920, 2880]], jnp.int32)
    ln = jnp.asarray([[100, 100, 100]], jnp.int32)
    valid = jnp.ones((1, 3), bool)
    st, r_sn, r_off, r_len, r_ok = red.encode_plan_tick(st, sn, ts, ln, valid)
    # pkt 0 has no history; pkt 1 carries pkt 0; pkt 2 carries 1 and 0.
    assert not bool(r_ok[0, 0].any())
    assert bool(r_ok[0, 1, 0]) and int(r_sn[0, 1, 0]) == 10 and int(r_off[0, 1, 0]) == 960
    assert r_ok[0, 2].tolist() == [True, True]
    assert int(r_sn[0, 2, 1]) == 10 and int(r_off[0, 2, 1]) == 1920


def test_red_offset_limit():
    st = red.init_state(1)
    # Huge TS gap: redundancy no longer expressible in 14 bits.
    st, *_ = red.encode_plan_tick(
        st, jnp.asarray([[1]], jnp.int32), jnp.asarray([[0]], jnp.int32),
        jnp.asarray([[50]], jnp.int32), jnp.ones((1, 1), bool),
    )
    st, r_sn, r_off, r_len, r_ok = red.encode_plan_tick(
        st, jnp.asarray([[2]], jnp.int32), jnp.asarray([[20000]], jnp.int32),
        jnp.asarray([[50]], jnp.int32), jnp.ones((1, 1), bool),
    )
    assert not bool(r_ok[0, 0, 0])


# ---- pacer ------------------------------------------------------------

def test_pacer_drains_at_rate():
    p = pacer.PacerParams(burst_ms=100)
    st = pacer.init_state(1, initial_rate=800_000.0)  # 100 KB/s
    rate = jnp.asarray([800_000.0], jnp.float32)
    # enqueue 30 KB; at 100 KB/s and 100 ms ticks → 10 KB allowed per tick
    st, allowed, backlog = pacer.update_tick(st, p, jnp.asarray([30_000.0]), rate, 100)
    assert abs(float(allowed[0]) - 10_000) < 1
    assert abs(float(backlog[0]) - 20_000) < 1
    st, allowed, backlog = pacer.update_tick(st, p, jnp.asarray([0.0]), rate, 100)
    assert abs(float(allowed[0]) - 10_000) < 1
    st, allowed, backlog = pacer.update_tick(st, p, jnp.asarray([0.0]), rate, 100)
    assert abs(float(backlog[0])) < 1  # fully drained


def test_pacer_burst_cap():
    p = pacer.PacerParams(burst_ms=100)
    st = pacer.init_state(1, initial_rate=800_000.0)
    rate = jnp.asarray([800_000.0], jnp.float32)
    # long idle: tokens cap at burst depth (10 KB), not unbounded
    for _ in range(20):
        st, _, _ = pacer.update_tick(st, p, jnp.asarray([0.0]), rate, 100)
    st, allowed, _ = pacer.update_tick(st, p, jnp.asarray([50_000.0]), rate, 100)
    assert float(allowed[0]) <= 10_000 + 1

def test_low_fps_screenshare_stays_live_via_frame_rule():
    """streamtracker_frame.go seat: a 2 fps screenshare layer sends ~2
    packets per 500 ms cycle — below min_pkts — but its frame starts keep
    it LIVE; the packet rule alone would leave it STOPPED forever."""
    p = streamtracker.TrackerParams()
    st = streamtracker.init_state(1)
    statuses = []
    fps_vals = []
    # 10 s at 100 ms ticks: one 2-packet frame every 5th tick (2 fps).
    for i in range(100):
        frame = 1 if i % 5 == 0 else 0
        st, status, _ch, _bps, fps = streamtracker.update_tick(
            st, p,
            jnp.asarray([2 * frame]), jnp.asarray([500 * frame]), 100,
            frames=jnp.asarray([frame]),
        )
        statuses.append(int(status[0]))
        fps_vals.append(float(fps[0]))
    # Live by the end of the first cycle, and NEVER flaps back.
    first_live = statuses.index(streamtracker.LIVE)
    assert first_live <= 6, statuses[:10]
    assert all(s == streamtracker.LIVE for s in statuses[first_live:]), statuses
    # Measured fps converges near 2.
    assert 1.5 < fps_vals[-1] < 2.5, fps_vals[-1]
    # Control: with NO frame signal the packet rule never fires (2 pkts
    # < min_pkts=5 per cycle) — the old flap this variant fixes.
    st2 = streamtracker.init_state(1)
    for i in range(100):
        frame = 1 if i % 5 == 0 else 0
        st2, status2, *_ = streamtracker.update_tick(
            st2, p, jnp.asarray([2 * frame]), jnp.asarray([500 * frame]), 100,
        )
    assert int(status2[0]) == streamtracker.STOPPED
