"""STUN against the RFC 5769 test vectors + roundtrip properties."""

import binascii

from livekit_server_tpu.interop import stun

# RFC 5769 §2.1 — sample request (short-term credential
# username "evtj:h6vY", password "VOkJxbRl1RmTxUk/WvJxBt"; username
# padded with 0x20 per the RFC's deliberate non-zero padding).
REQ = binascii.unhexlify(
    "000100582112a442b7e7a701bc34d686fa87dfae"
    "80220010" "5354554e207465737420636c69656e74"
    "00240004" "6e0001ff"
    "80290008" "932ff9b151263b36"
    "00060009" "6576746a3a68367659202020"
    "00080014" "9aeaa70cbfd8cb56781ef2b5b2d3f249c1b571a2"
    "80280004" "e57a3bcf"
)
REQ_PASSWORD = b"VOkJxbRl1RmTxUk/WvJxBt"

# RFC 5769 §2.2 — sample IPv4 response (mapped 192.0.2.1:32853).
RESP = binascii.unhexlify(
    "0101003c2112a442b7e7a701bc34d686fa87dfae"
    "8022000b" "7465737420766563746f7220"
    "00200008" "0001a147e112a643"
    "00080014" "2b91f599fd9e90c38c7489f92af9ba53f06be7d7"
    "80280004" "c07d4c96"
)


def test_rfc5769_request_parses_and_verifies():
    msg = stun.parse_stun(REQ, integrity_key=REQ_PASSWORD)
    assert msg is not None
    assert msg.msg_type == stun.BINDING_REQUEST
    assert msg.username == "evtj:h6vY"
    assert msg.integrity_ok is True
    assert msg.fingerprint_ok is True
    assert msg.attr(stun.ATTR_PRIORITY) == bytes.fromhex("6e0001ff")


def test_rfc5769_request_tamper_detected():
    bad = bytearray(REQ)
    bad[30] ^= 0x01  # flip a byte inside SOFTWARE
    msg = stun.parse_stun(bytes(bad), integrity_key=REQ_PASSWORD)
    assert msg is not None and msg.integrity_ok is False


def test_rfc5769_response_parses():
    msg = stun.parse_stun(RESP, integrity_key=REQ_PASSWORD)
    assert msg is not None
    assert msg.msg_type == stun.BINDING_SUCCESS
    assert msg.fingerprint_ok is True
    assert msg.integrity_ok is True
    xma = msg.attr(stun.ATTR_XOR_MAPPED_ADDRESS)
    port = int.from_bytes(xma[2:4], "big") ^ (stun.MAGIC_COOKIE >> 16)
    ip = bytes(
        a ^ b for a, b in zip(xma[4:8], stun.MAGIC_COOKIE.to_bytes(4, "big"))
    )
    assert port == 32853
    assert ".".join(map(str, ip)) == "192.0.2.1"


def test_binding_roundtrip_with_integrity():
    pwd = b"local-ice-pwd-24-chars-x"
    req_raw = stun.build_binding_request("remote:local", pwd)
    req = stun.parse_stun(req_raw, integrity_key=pwd)
    assert req is not None
    assert req.integrity_ok is True and req.fingerprint_ok is True
    assert req.username == "remote:local"
    assert req.attr(stun.ATTR_USE_CANDIDATE) == b""

    resp_raw = stun.build_binding_response(req, ("203.0.113.7", 50123), pwd)
    resp = stun.parse_stun(resp_raw, integrity_key=pwd)
    assert resp is not None
    assert resp.msg_type == stun.BINDING_SUCCESS
    assert resp.txn_id == req.txn_id
    assert resp.integrity_ok is True and resp.fingerprint_ok is True
    xma = resp.attr(stun.ATTR_XOR_MAPPED_ADDRESS)
    port = int.from_bytes(xma[2:4], "big") ^ (stun.MAGIC_COOKIE >> 16)
    assert port == 50123


def test_demux_rejects_non_stun():
    assert stun.parse_stun(b"\x80\x60" + b"x" * 30) is None  # RTP-ish
    assert stun.parse_stun(b"\x16\xfe\xfd" + b"x" * 30) is None  # DTLS
    assert stun.parse_stun(b"") is None

def test_xor_mapped_address_ipv6():
    """RFC 5389 §15.2 family 0x02: 128-bit address XORed against
    cookie‖txn-id (v4-only _xor_address used to emit garbage here)."""
    import socket
    import struct

    req = stun.parse_stun(
        stun.build_binding_request("u:me", b"pw"), integrity_key=b"pw"
    )
    resp = stun.build_binding_response(req, ("2001:db8::1", 43210), b"pw")
    msg = stun.parse_stun(resp, integrity_key=b"pw")
    assert msg is not None and msg.integrity_ok
    xma = msg.attr(stun.ATTR_XOR_MAPPED_ADDRESS)
    assert xma[1] == 0x02 and len(xma) == 4 + 16
    port = struct.unpack("!H", xma[2:4])[0] ^ (stun.MAGIC_COOKIE >> 16)
    mask = struct.pack("!I", stun.MAGIC_COOKIE) + req.txn_id
    ip = bytes(a ^ b for a, b in zip(xma[4:], mask))
    assert port == 43210
    assert ip == socket.inet_pton(socket.AF_INET6, "2001:db8::1")


def test_xor_mapped_address_v4_mapped_and_scoped():
    """Dual-stack quirks: ::ffff:a.b.c.d must unmap to family 0x01; a
    %zone suffix must not crash the responder."""
    import struct

    req = stun.parse_stun(
        stun.build_binding_request("u:me", b"pw"), integrity_key=b"pw"
    )
    resp = stun.build_binding_response(
        req, ("::ffff:203.0.113.5", 1234), b"pw"
    )
    xma = stun.parse_stun(resp).attr(stun.ATTR_XOR_MAPPED_ADDRESS)
    assert xma[1] == 0x01 and len(xma) == 4 + 4
    ip = bytes(
        a ^ b
        for a, b in zip(xma[4:], struct.pack("!I", stun.MAGIC_COOKIE))
    )
    assert ip == bytes([203, 0, 113, 5])
    # Scoped link-local: must produce a family-0x02 answer, not raise.
    resp = stun.build_binding_response(req, ("fe80::1%eth0", 5), b"pw")
    assert stun.parse_stun(resp).attr(stun.ATTR_XOR_MAPPED_ADDRESS)[1] == 0x02


def test_binding_response_with_4tuple_addr():
    """AF_INET6 recvfrom yields (host, port, flowinfo, scope_id) — the
    responder must accept it directly."""
    req = stun.parse_stun(
        stun.build_binding_request("u:me", b"pw"), integrity_key=b"pw"
    )
    resp = stun.build_binding_response(req, ("2001:db8::2", 9, 0, 0), b"pw")
    assert stun.parse_stun(resp).attr(stun.ATTR_XOR_MAPPED_ADDRESS)[1] == 0x02
