"""Full-jitter backoff (utils/backoff.py): bounds, seeded reproducibility,
and the property the jitter exists for — N clients retrying concurrently
against the same dead dependency DE-correlate instead of thundering in
synchronized waves. Plus the tcpbus seam: every client's reconnect loop
draws from its own seedable rng."""

import asyncio
import random

import pytest

from livekit_server_tpu.routing.tcpbus import BusServer, TCPBusClient
from livekit_server_tpu.utils import backoff as backoff_mod
from livekit_server_tpu.utils.backoff import BackoffPolicy, retry_async


def test_full_jitter_bounds_and_cap():
    p = BackoffPolicy(base=0.05, max_delay=5.0, multiplier=2.0)
    rng = random.Random(3)
    for attempt in range(12):
        cap = min(0.05 * 2 ** attempt, 5.0)
        d = p.delay(attempt, rng)
        assert p.jitter_floor * cap <= d <= cap, (attempt, d, cap)
    # Deep attempts saturate at max_delay, never beyond.
    assert p.delay(50, rng) <= 5.0

    ladder = BackoffPolicy(base=0.05, max_delay=5.0, jitter=False)
    assert [ladder.delay(n) for n in range(4)] == [0.05, 0.1, 0.2, 0.4]


def test_seeded_sequences_reproducible_and_decorrelated():
    p = BackoffPolicy(base=0.05, max_delay=5.0)

    def seq(seed: int) -> list[float]:
        rng = random.Random(seed)
        return [p.delay(n, rng) for n in range(6)]

    seqs = [seq(100 + i) for i in range(8)]
    # Same seed, byte-identical sequence — the chaos-drill contract.
    assert seqs[0] == seq(100)
    # Different seeds de-correlate: no two clients share a sequence, and
    # at every attempt the fleet spreads instead of marching in step.
    for i in range(8):
        for j in range(i + 1, 8):
            assert seqs[i] != seqs[j], (i, j)
    for attempt in range(6):
        draws = {s[attempt] for s in seqs}
        assert len(draws) == 8, f"attempt {attempt} synchronized: {draws}"


async def test_concurrent_retries_decorrelate(monkeypatch):
    """Eight concurrent retry_async loops with fixed per-client seeds:
    each sleeps a distinct jittered schedule, and a rerun with the same
    seeds reproduces the schedules exactly."""

    async def run_fleet() -> list[list[float]]:
        recorded: dict[asyncio.Task, list[float]] = {}
        real_sleep = asyncio.sleep

        async def spy_sleep(delay, *a, **kw):
            task = asyncio.current_task()
            if task in recorded:
                recorded[task].append(delay)
                delay = 0
            return await real_sleep(delay and 0)

        monkeypatch.setattr(backoff_mod.asyncio, "sleep", spy_sleep)
        try:
            policy = BackoffPolicy(base=0.05, max_delay=5.0)

            def client(i: int):
                failures = [0]

                async def fn() -> str:
                    if failures[0] < 5:
                        failures[0] += 1
                        raise ConnectionError("bus down")
                    return "up"

                return retry_async(fn, policy, rng=random.Random(7000 + i))

            tasks = [asyncio.ensure_future(client(i)) for i in range(8)]
            for t in tasks:
                recorded[t] = []
            results = await asyncio.gather(*tasks)
            assert results == ["up"] * 8
            return [recorded[t] for t in tasks]
        finally:
            monkeypatch.setattr(backoff_mod.asyncio, "sleep", real_sleep)

    schedules = await run_fleet()
    assert all(len(s) == 5 for s in schedules)
    for i in range(8):
        for j in range(i + 1, 8):
            assert schedules[i] != schedules[j], (i, j)
    # Every retry wave spreads out — no synchronized thundering herd.
    for wave in zip(*schedules):
        assert len(set(wave)) == 8, wave

    assert await run_fleet() == schedules, "same seeds must replay exactly"


async def test_tcpbus_client_gets_seeded_dial_rng():
    bus = BusServer()
    await bus.start("127.0.0.1", 0)
    try:
        c1 = await TCPBusClient.connect("127.0.0.1", bus.port, jitter_seed=11)
        c2 = await TCPBusClient.connect("127.0.0.1", bus.port, jitter_seed=12)
        try:
            # The reconnect loop's rng is per-client and seed-determined:
            # seed 11 replays random.Random(11), and two clients with
            # different seeds will draw different dial schedules.
            draws1 = [c1._dial_rng.random() for _ in range(4)]
            draws2 = [c2._dial_rng.random() for _ in range(4)]
            ref = random.Random(11)
            assert draws1 == [ref.random() for _ in range(4)]
            assert draws1 != draws2
            assert c1._dial_backoff.jitter  # full jitter is default-on
        finally:
            await c1.close()
            await c2.close()
    finally:
        bus.close()


async def test_reconnect_passes_client_rng(monkeypatch):
    """The tcpbus reconnect path hands its seeded rng to retry_async —
    the seam the fleet decorrelation rides on."""
    bus = BusServer()
    await bus.start("127.0.0.1", 0)
    try:
        c = await TCPBusClient.connect("127.0.0.1", bus.port, jitter_seed=42)
        try:
            seen = {}

            async def spy_retry(fn, policy, **kwargs):
                seen.update(kwargs)
                return await fn()

            import livekit_server_tpu.routing.tcpbus as tcpbus_mod
            monkeypatch.setattr(tcpbus_mod, "retry_async", spy_retry)
            assert await c._reconnect()
            assert seen.get("rng") is c._dial_rng
        finally:
            await c.close()
    finally:
        bus.close()
