"""Routing layer tests.

Reference parity: pkg/routing/selector/*_test.go (policy tests with
synthetic node stats), router room pinning + signal relay
(pkg/routing/redisrouter.go), bounded channel drop semantics
(messagechannel.go).
"""

import asyncio
import time

import pytest

from livekit_server_tpu.config.config import NodeSelectorConfig, RegionConfig
from livekit_server_tpu.routing import (
    AnySelector,
    CPULoadSelector,
    ChannelClosed,
    ChannelFull,
    KVRouter,
    LocalNode,
    LocalRouter,
    MemoryBus,
    MessageChannel,
    NodeStats,
    ParticipantInit,
    RegionAwareSelector,
    create_selector,
)
from livekit_server_tpu.routing.selector import NoNodesAvailable


def node(nid="n1", region="", cpu=0.1, rooms_used=0, cap=0, fresh=True):
    n = LocalNode(node_id=nid, region=region)
    n.stats = NodeStats(
        updated_at=time.time() if fresh else time.time() - 120,
        cpu_load=cpu,
        plane_rooms_used=rooms_used,
        plane_rooms_capacity=cap,
    )
    return n


# ---- selectors (cpuload_test.go style) --------------------------------

def test_any_selector_skips_stale():
    live, stale = node("a"), node("b", fresh=False)
    assert AnySelector().select_node([stale, live]).node_id == "a"
    with pytest.raises(NoNodesAvailable):
        AnySelector().select_node([stale])


def test_cpu_load_selector():
    low, high = node("low", cpu=0.2), node("high", cpu=0.95)
    sel = CPULoadSelector(cpu_load_limit=0.9, sort_by="cpuload")
    assert sel.select_node([high, low]).node_id == "low"
    # all above limit ⇒ falls back rather than failing (reference behavior)
    assert sel.select_node([high]).node_id == "high"


def test_plane_capacity_gate():
    full = node("full", rooms_used=64, cap=64)
    free = node("free", rooms_used=3, cap=64)
    assert AnySelector().select_node([full, free]).node_id == "free"
    with pytest.raises(NoNodesAvailable):
        AnySelector().select_node([full])


def test_region_aware_selector():
    regions = [
        RegionConfig("us-west", 37.64, -122.43),
        RegionConfig("us-east", 40.68, -74.12),
        RegionConfig("eu", 53.43, 6.84),
    ]
    sel = RegionAwareSelector("us-west", regions, sort_by="cpuload")
    nodes = [node("east", region="us-east"), node("eu", region="eu"), node("west", region="us-west")]
    assert sel.select_node(nodes).node_id == "west"
    # no local-region node ⇒ nearest region wins (us-east < eu from us-west)
    assert sel.select_node(nodes[:2]).node_id == "east"


def test_create_selector_kinds():
    for kind in ("any", "cpuload", "sysload", "regionaware"):
        assert create_selector(NodeSelectorConfig(kind=kind)) is not None
    with pytest.raises(ValueError):
        create_selector(NodeSelectorConfig(kind="bogus"))


# ---- message channel --------------------------------------------------

@pytest.mark.asyncio
async def test_channel_drop_on_full_and_close():
    ch = MessageChannel(size=2)
    ch.write_message({"a": 1})
    ch.write_message({"a": 2})
    with pytest.raises(ChannelFull):
        ch.write_message({"a": 3})
    assert await ch.read_message() == {"a": 1}
    ch.close()
    assert await ch.read_message() == {"a": 2}
    with pytest.raises(ChannelClosed):
        await ch.read_message()
    with pytest.raises(ChannelClosed):
        ch.write_message({"a": 4})


# ---- routers ----------------------------------------------------------

async def echo_handler(room, init, req, resp):
    """Session handler: echoes requests with the room tag."""
    try:
        while True:
            msg = await req.read_message()
            resp.write_message({"room": room, "echo": msg, "identity": init["identity"]})
    except ChannelClosed:
        resp.close()


@pytest.mark.asyncio
async def test_local_router_session():
    router = LocalRouter(LocalNode(node_id="n1"))
    router.on_new_session(echo_handler)
    cid, req, resp = await router.start_participant_signal(
        "lobby", ParticipantInit(identity="alice")
    )
    assert cid.startswith("CO_")
    req.write_message({"ping": 1})
    out = await asyncio.wait_for(resp.read_message(), 2)
    assert out == {"room": "lobby", "echo": {"ping": 1}, "identity": "alice"}


@pytest.mark.asyncio
async def test_kv_router_cross_node_relay():
    """Two logical nodes, one bus — the reference's multinode test shape."""
    bus = MemoryBus()
    rtc_node = KVRouter(LocalNode(node_id="rtc"), bus)
    signal_node = KVRouter(LocalNode(node_id="sig"), bus)
    rtc_node.on_new_session(echo_handler)
    await rtc_node.register_node()
    await signal_node.register_node()
    try:
        nodes = {n.node_id for n in await signal_node.list_nodes()}
        assert nodes == {"rtc", "sig"}

        await signal_node.set_node_for_room("lobby", "rtc")
        assert await rtc_node.get_node_for_room("lobby") == "rtc"

        cid, req, resp = await signal_node.start_participant_signal(
            "lobby", ParticipantInit(identity="bob")
        )
        req.write_message({"offer": {"sdp": "x"}})
        out = await asyncio.wait_for(resp.read_message(), 2)
        assert out["echo"] == {"offer": {"sdp": "x"}}
        assert out["identity"] == "bob"

        await signal_node.clear_room_state("lobby")
        assert await rtc_node.get_node_for_room("lobby") == ""
    finally:
        await rtc_node.unregister_node()
        await signal_node.unregister_node()


@pytest.mark.asyncio
async def test_kv_router_heartbeat_and_reap():
    bus = MemoryBus()
    a = KVRouter(LocalNode(node_id="a"), bus, stats_interval=0.05)
    await a.register_node()
    try:
        t0 = (await a.list_nodes())[0].stats.updated_at
        await asyncio.sleep(0.12)
        t1 = (await a.list_nodes())[0].stats.updated_at
        assert t1 > t0  # heartbeat refreshed
        # dead-node reap
        stale = LocalNode(node_id="dead")
        stale.stats.updated_at = time.time() - 300
        import json
        await bus.hset("nodes", "dead", json.dumps(stale.to_dict()))
        await a.remove_dead_nodes()
        assert {n.node_id for n in await a.list_nodes()} == {"a"}
    finally:
        await a.unregister_node()
