"""Embedded media relay (the TURN seat): blind UDP forwarding for clients
whose direct path to the SFU media port is blocked.

Reference parity: pkg/service/turn.go:47 — the reference embeds a TURN
server so UDP-hostile networks still move media over a relay address.
Here the relay forwards this build's sealed frames verbatim; admission is
a token minted over the signal channel (the long-term-credential seat).
"""

import asyncio
import socket
import time

import numpy as np

from livekit_server_tpu.models import plane
from livekit_server_tpu.native import rtp as parser
from livekit_server_tpu.runtime import PlaneRuntime
from livekit_server_tpu.runtime.crypto import MediaCryptoClient, MediaCryptoRegistry
from livekit_server_tpu.runtime.relay import (
    BIND_ACK,
    BIND_ERR,
    BIND_REQ,
    RELAY_MAGIC,
    mint_relay_token,
    start_media_relay,
    verify_relay_token,
)
from livekit_server_tpu.runtime.udp import PUNCH_ACK, PUNCH_REQ, UDPMediaTransport
from tests.conftest import free_port
from tests.test_native import rtp_packet

DIMS = plane.PlaneDims(rooms=2, tracks=4, pkts=8, subs=4)
SECRET = b"relay-hmac-secret"


def _bind_via(sock: socket.socket, relay_addr, token: bytes) -> None:
    sock.sendto(RELAY_MAGIC + bytes([BIND_REQ]) + token, relay_addr)


def _recv(sock: socket.socket):
    out = []
    while True:
        try:
            out.append(sock.recvfrom(4096)[0])
        except BlockingIOError:
            return out


def test_relay_token_roundtrip():
    tok = mint_relay_token(SECRET, 0xDEADBEEF, 30.0)
    assert verify_relay_token(SECRET, tok) == 0xDEADBEEF
    # forged mac / wrong secret / expired → rejected
    assert verify_relay_token(b"other", tok) is None
    assert verify_relay_token(SECRET, tok[:-1] + bytes([tok[-1] ^ 1])) is None
    assert verify_relay_token(SECRET, mint_relay_token(SECRET, 7, -5.0)) is None


async def test_relay_end_to_end_sealed_media():
    """Publisher and subscriber that never touch the SFU port directly:
    BIND → sealed punch → sealed media both ways, all through the relay.
    The relay holds no media keys — every forwarded byte string is sealed."""
    runtime = PlaneRuntime(DIMS, tick_ms=10)
    reg = MediaCryptoRegistry()
    sfu_port, relay_port = free_port(socket.SOCK_DGRAM), free_port(socket.SOCK_DGRAM)
    loop = asyncio.get_running_loop()
    tr, transport = await loop.create_datagram_endpoint(
        lambda: UDPMediaTransport(runtime.ingest, crypto=reg, require_encryption=True),
        local_addr=("127.0.0.1", sfu_port),
    )
    relay = await start_media_relay(
        "127.0.0.1", relay_port, ("127.0.0.1", sfu_port), SECRET
    )
    relay_addr = ("127.0.0.1", relay_port)
    try:
        runtime.set_track(0, 0, published=True, is_video=False)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        pub_sess, sub_sess = reg.mint(), reg.mint()
        transport.bind_sub_session(0, 1, sub_sess)
        ssrc = transport.assign_ssrc(0, 0, is_video=False, session=pub_sess)
        alice = MediaCryptoClient(pub_sess.key_id, pub_sess.key)
        bob = MediaCryptoClient(sub_sess.key_id, sub_sess.key)

        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        pub.setblocking(False)
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)

        # Allocate: one BIND each, tokens bound to each session.
        _bind_via(pub, relay_addr, mint_relay_token(SECRET, pub_sess.key_id, 30))
        _bind_via(sub, relay_addr, mint_relay_token(SECRET, sub_sess.key_id, 30))
        await asyncio.sleep(0.05)
        assert _recv(pub) == [RELAY_MAGIC + bytes([BIND_ACK]) + pub_sess.key_id.to_bytes(4, "big")]
        assert _recv(sub) == [RELAY_MAGIC + bytes([BIND_ACK]) + sub_sess.key_id.to_bytes(4, "big")]
        assert len(relay.allocs) == 2

        # Sealed punch rides through the relay; the SFU latches the relay's
        # per-allocation source port, never bob's real address.
        pid = transport.assign_subscriber_punch(0, 1)
        sub.sendto(bob.seal(PUNCH_REQ + pid.to_bytes(4, "big")), relay_addr)
        await asyncio.sleep(0.05)
        acks = [bob.open(f) for f in _recv(sub)]
        assert PUNCH_ACK + pid.to_bytes(4, "big") in acks
        latched = transport.sub_addrs[(0, 1)]
        assert latched[0] == "127.0.0.1" and latched[1] != sub.getsockname()[1]

        # Sealed media: alice → relay → SFU → relay → bob.
        payload = b"relayed-opus"
        got = []
        for i in range(5):
            pub.sendto(
                alice.seal(rtp_packet(sn=100 + i, ts=960 * i, ssrc=ssrc,
                                      payload=payload + bytes([i]))),
                relay_addr,
            )
            await asyncio.sleep(0.02)
            res = await runtime.step_once()
            transport.send_egress(res.egress)
            await asyncio.sleep(0.02)
            for f in _recv(sub):
                assert f[0] == 0x01 and payload not in f  # still sealed on the wire
                inner = bob.open(f)
                if inner is not None and not (192 <= inner[1] <= 223):
                    got.append(inner)
        assert len(got) == 5
        out = parser.parse_batch(
            got[0], np.asarray([0], np.int32), np.asarray([len(got[0])], np.int32)
        )[0]
        assert int(out["sn"]) == 100
        off, ln = int(out["payload_off"]), int(out["payload_len"])
        assert got[0][off : off + ln] == payload + bytes([0])
        assert relay.stats["up_fwd"] >= 6 and relay.stats["down_fwd"] >= 6
        pub.close()
        sub.close()
    finally:
        relay.close()
        tr.close()


async def test_request_relay_signal_mints_token():
    """Signal plane: `request_relay` returns the relay address plus a token
    the relay accepts for THIS participant's media session — and a null
    relay_info when no relay is configured (client falls back to TCP)."""
    from livekit_server_tpu.protocol import decode_signal_response
    from livekit_server_tpu.protocol.signal import SignalRequest
    from livekit_server_tpu.routing.messagechannel import MessageChannel
    from livekit_server_tpu.rtc import Participant, Room, handle_participant_signal

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    reg = MediaCryptoRegistry()
    room = Room("relayroom", runtime)
    room.crypto = reg
    sink = MessageChannel(size=100)
    p = Participant("alice", room, response_sink=sink)
    room.join(p)
    assert p.crypto_session is not None

    class _FakeUdp:
        relay_info = ("203.0.113.9", 7885, SECRET, 30.0)

    room.udp = _FakeUdp()
    handle_participant_signal(room, p, SignalRequest("request_relay", {}))
    room.udp = None
    handle_participant_signal(room, p, SignalRequest("request_relay", {}))

    infos = []
    while True:
        try:
            msg = decode_signal_response(sink._q.get_nowait())
        except asyncio.QueueEmpty:
            break
        if msg.kind == "request_response" and "relay_info" in msg.data:
            infos.append(msg.data["relay_info"])
    assert len(infos) == 2 and infos[1] is None
    info = infos[0]
    assert (info["host"], info["port"]) == ("203.0.113.9", 7885)
    assert verify_relay_token(SECRET, bytes.fromhex(info["token"])) == p.crypto_session.key_id


async def test_relay_admission_and_rebind():
    """Forged tokens never allocate; a re-BIND from a new source address
    moves the allocation (NAT-rebind recovery) and revokes the old path."""
    runtime = PlaneRuntime(DIMS, tick_ms=10)
    reg = MediaCryptoRegistry()
    sfu_port, relay_port = free_port(socket.SOCK_DGRAM), free_port(socket.SOCK_DGRAM)
    loop = asyncio.get_running_loop()
    tr, transport = await loop.create_datagram_endpoint(
        lambda: UDPMediaTransport(runtime.ingest, crypto=reg, require_encryption=True),
        local_addr=("127.0.0.1", sfu_port),
    )
    relay = await start_media_relay(
        "127.0.0.1", relay_port, ("127.0.0.1", sfu_port), SECRET, ttl_s=30
    )
    relay_addr = ("127.0.0.1", relay_port)
    try:
        sess = reg.mint()
        c1 = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        c1.bind(("127.0.0.1", 0))
        c1.setblocking(False)

        # Forged / tampered / expired tokens → BIND_ERR, no allocation.
        _bind_via(c1, relay_addr, mint_relay_token(b"wrong", sess.key_id, 30))
        _bind_via(c1, relay_addr, mint_relay_token(SECRET, sess.key_id, -1))
        await asyncio.sleep(0.05)
        assert all(f == RELAY_MAGIC + bytes([BIND_ERR]) for f in _recv(c1))
        assert not relay.allocs and relay.stats["bad_bind"] == 2
        # Datagrams from an unbound address are dropped, not forwarded.
        c1.sendto(b"\x01" + b"x" * 40, relay_addr)
        await asyncio.sleep(0.05)
        assert relay.stats["dropped"] == 1 and relay.stats["up_fwd"] == 0

        token = mint_relay_token(SECRET, sess.key_id, 30)
        _bind_via(c1, relay_addr, token)
        await asyncio.sleep(0.05)
        assert _recv(c1)[-1][4] == BIND_ACK
        alloc = relay.allocs[sess.key_id]
        assert alloc.client_addr == c1.getsockname()

        # Same token, new socket: the allocation MOVES (one per session).
        c2 = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        c2.bind(("127.0.0.1", 0))
        c2.setblocking(False)
        _bind_via(c2, relay_addr, token)
        await asyncio.sleep(0.05)
        assert _recv(c2)[-1][4] == BIND_ACK
        assert len(relay.allocs) == 1
        assert relay.allocs[sess.key_id].client_addr == c2.getsockname()
        assert c1.getsockname() not in relay.by_client
        c1.close()
        c2.close()

        # BIND burst: many datagrams for one session land in a single
        # event-loop batch — exactly one upstream socket must exist (the
        # creation await must not let duplicates through the cap).
        sess2 = reg.mint()
        c3 = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        c3.bind(("127.0.0.1", 0))
        c3.setblocking(False)
        burst_token = mint_relay_token(SECRET, sess2.key_id, 30)
        for _ in range(8):
            _bind_via(c3, relay_addr, burst_token)
        await asyncio.sleep(0.1)
        assert len(relay.allocs) == 2  # sess (moved above) + sess2, no dupes
        assert not relay._pending
        c3.close()
    finally:
        relay.close()
        tr.close()


async def test_relay_idle_allocations_expire():
    runtime = PlaneRuntime(DIMS, tick_ms=10)
    reg = MediaCryptoRegistry()
    sfu_port, relay_port = free_port(socket.SOCK_DGRAM), free_port(socket.SOCK_DGRAM)
    loop = asyncio.get_running_loop()
    tr, transport = await loop.create_datagram_endpoint(
        lambda: UDPMediaTransport(runtime.ingest, crypto=reg, require_encryption=True),
        local_addr=("127.0.0.1", sfu_port),
    )
    relay = await start_media_relay(
        "127.0.0.1", relay_port, ("127.0.0.1", sfu_port), SECRET, ttl_s=0.1
    )
    try:
        sess = reg.mint()
        c = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        c.bind(("127.0.0.1", 0))
        c.setblocking(False)
        _bind_via(c, ("127.0.0.1", relay_port), mint_relay_token(SECRET, sess.key_id, 30))
        await asyncio.sleep(0.05)
        assert len(relay.allocs) == 1
        # Sweeper period is max(1s, ttl/4): idle past the ttl → reaped.
        deadline = time.monotonic() + 3.0
        while relay.allocs and time.monotonic() < deadline:
            await asyncio.sleep(0.1)
        assert not relay.allocs and relay.stats["expired"] == 1
        c.close()
    finally:
        relay.close()
        tr.close()


async def test_relay_through_full_server():
    """Service tier: a publisher AND subscriber that never touch the SFU
    media port — relay allocations minted over the signal channel, sealed
    punch + sealed media both ways through the embedded relay (turn.go:47
    capability through the whole product stack)."""
    import base64

    import aiohttp

    from tests.test_service import SignalClient, running_server

    relay_port = free_port(socket.SOCK_DGRAM)

    async def wait_rr(client, key, timeout=3.0):
        # wait_for returns the OLDEST request_response; pick by payload key
        # (relay_info responses precede the udp ones in the log).
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            for m in client.signals:
                rr = m.get("request_response")
                if rr and key in rr:
                    return rr
            await asyncio.sleep(0.01)
        raise TimeoutError(f"no request_response with {key!r}")

    def enable_relay(cfg):
        cfg.relay.enabled = True
        cfg.relay.udp_port = relay_port

    async with running_server(configure=enable_relay,
                              require_encryption=True) as server:
        relay_addr = ("127.0.0.1", relay_port)
        async with aiohttp.ClientSession() as s:
            alice = SignalClient(s, server.port)
            bob = SignalClient(s, server.port)
            join_a = await alice.connect("relay-room", "alice")
            join_b = await bob.connect("relay-room", "bob")
            a_crypt = MediaCryptoClient(
                join_a["media_crypto"]["key_id"],
                base64.b64decode(join_a["media_crypto"]["key"]),
            )
            b_crypt = MediaCryptoClient(
                join_b["media_crypto"]["key_id"],
                base64.b64decode(join_b["media_crypto"]["key"]),
            )

            # Both participants allocate on the relay.
            socks = {}
            for client, who in ((alice, "a"), (bob, "b")):
                await client.send_signal("request_relay", {})
                rr = await wait_rr(client, "relay_info")
                info = rr["relay_info"]
                assert (info["host"], info["port"]) == relay_addr
                sk = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                sk.bind(("127.0.0.1", 0))
                sk.setblocking(False)
                _bind_via(sk, relay_addr, bytes.fromhex(info["token"]))
                deadline = asyncio.get_event_loop().time() + 2
                while asyncio.get_event_loop().time() < deadline:
                    await asyncio.sleep(0.02)
                    try:
                        ack = sk.recvfrom(64)[0]
                        assert ack[4] == BIND_ACK
                        break
                    except BlockingIOError:
                        continue
                else:
                    raise TimeoutError("no BIND ack")
                socks[who] = sk

            # Publish over UDP-via-relay; subscribe likewise.
            await alice.send_signal(
                "add_track", {"cid": "mic", "type": 0, "name": "m",
                              "transport": "udp"}
            )
            rr = await wait_rr(alice, "udp_media")
            ssrc = rr["udp_media"]["ssrc"]
            track_sid = rr["udp_media"]["track_sid"]
            await bob.wait_for("track_subscribed")
            await bob.send_signal(
                "subscription",
                {"track_sids": [track_sid], "subscribe": True, "udp": True},
            )
            rr = await wait_rr(bob, "udp_punch")
            punch = int(rr["udp_punch"]["punch_id"])
            socks["b"].sendto(
                b_crypt.seal(PUNCH_REQ + punch.to_bytes(4, "big")), relay_addr
            )
            deadline = asyncio.get_event_loop().time() + 2
            while asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.02)
                try:
                    ack = b_crypt.open(socks["b"].recvfrom(2048)[0])
                    if ack == PUNCH_ACK + punch.to_bytes(4, "big"):
                        break
                except BlockingIOError:
                    continue
            else:
                raise TimeoutError("no punch ack through relay")

            got = []
            for i in range(20):
                socks["a"].sendto(
                    a_crypt.seal(rtp_packet(sn=300 + i, ts=960 * i, ssrc=ssrc,
                                            audio_level=20,
                                            payload=b"via-relay" + bytes([i]))),
                    relay_addr,
                )
                await asyncio.sleep(0.05)
                while True:
                    try:
                        inner = b_crypt.open(socks["b"].recvfrom(4096)[0])
                        if inner is not None and not (192 <= inner[1] <= 223):
                            got.append(inner)
                    except BlockingIOError:
                        break
                if len(got) >= 5:
                    break
            assert len(got) >= 5, f"only {len(got)} media packets via relay"
            assert any(b"via-relay" in g for g in got)
            for sk in socks.values():
                sk.close()

async def test_relay_move_requires_continuity_proof():
    """v2 BINDs pin a hash-chain commitment: a captured BIND datagram
    (v1 or v2) replayed from another address can no longer move the
    allocation; only the holder of the unrevealed preimage can."""
    import secrets as _secrets

    from livekit_server_tpu.runtime.relay import continuity_commit

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    reg = MediaCryptoRegistry()
    sfu_port, relay_port = free_port(socket.SOCK_DGRAM), free_port(socket.SOCK_DGRAM)
    loop = asyncio.get_running_loop()
    tr, _ = await loop.create_datagram_endpoint(
        lambda: UDPMediaTransport(runtime.ingest, crypto=reg, require_encryption=True),
        local_addr=("127.0.0.1", sfu_port),
    )
    relay = await start_media_relay(
        "127.0.0.1", relay_port, ("127.0.0.1", sfu_port), SECRET, ttl_s=30
    )
    relay_addr = ("127.0.0.1", relay_port)

    def mksock():
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.bind(("127.0.0.1", 0))
        s.setblocking(False)
        return s

    try:
        sess = reg.mint()
        token = mint_relay_token(SECRET, sess.key_id, 30)
        reveal1, reveal2 = _secrets.token_bytes(16), _secrets.token_bytes(16)
        commit1, commit2 = continuity_commit(reveal1), continuity_commit(reveal2)

        owner, mover, attacker = mksock(), mksock(), mksock()
        # First BIND (v2) pins commit1.
        first_bind = token + b"\x00" * 16 + commit1
        _bind_via(owner, relay_addr, first_bind)
        await asyncio.sleep(0.05)
        assert _recv(owner)[-1][4] == BIND_ACK
        assert relay.allocs[sess.key_id].client_addr == owner.getsockname()

        # Captured v1 BIND replayed from elsewhere: cannot move a pinned
        # allocation.
        _bind_via(attacker, relay_addr, token)
        # Captured first v2 BIND replayed verbatim: zeros don't hash to
        # the pin either.
        _bind_via(attacker, relay_addr, first_bind)
        await asyncio.sleep(0.05)
        assert all(f[4] == BIND_ERR for f in _recv(attacker))
        assert relay.allocs[sess.key_id].client_addr == owner.getsockname()

        # Legitimate move: reveal the pinned preimage, pin the next one.
        move_bind = token + reveal1 + commit2
        _bind_via(mover, relay_addr, move_bind)
        await asyncio.sleep(0.05)
        assert _recv(mover)[-1][4] == BIND_ACK
        assert relay.allocs[sess.key_id].client_addr == mover.getsockname()

        # Replaying the captured move datagram: reveal1 is spent (pin is
        # now commit2) — still cannot hijack.
        _bind_via(attacker, relay_addr, move_bind)
        await asyncio.sleep(0.05)
        assert all(f[4] == BIND_ERR for f in _recv(attacker))
        assert relay.allocs[sess.key_id].client_addr == mover.getsockname()

        # The chain continues: reveal2 moves it again.
        _bind_via(owner, relay_addr, token + reveal2 + continuity_commit(b"x" * 16))
        await asyncio.sleep(0.05)
        assert _recv(owner)[-1][4] == BIND_ACK
        assert relay.allocs[sess.key_id].client_addr == owner.getsockname()

        # A replayed frame must never PLANT a pin on an unpinned (v1)
        # allocation: it may move it (v1's documented risk model), but the
        # victim's plain v1 re-BIND must still reclaim the path.
        sessv1 = reg.mint()
        tokv1 = mint_relay_token(SECRET, sessv1.key_id, 30)
        _bind_via(owner, relay_addr, tokv1)           # v1 creation
        await asyncio.sleep(0.05)
        assert _recv(owner)[-1][4] == BIND_ACK
        # Attacker crafts a v2 move from the captured token: spent nonce,
        # no proof — it moves (unpinned) but must not pin.
        _bind_via(attacker, relay_addr, tokv1 + b"\x00" * 16 + continuity_commit(b"evil" * 4))
        await asyncio.sleep(0.05)
        assert _recv(attacker)[-1][4] == BIND_ACK  # moved (v1 semantics)...
        assert relay.allocs[sessv1.key_id].client_addr == attacker.getsockname()
        assert relay.allocs[sessv1.key_id].commit is None  # ...but no pin
        _bind_via(owner, relay_addr, tokv1)           # victim reclaims
        await asyncio.sleep(0.05)
        assert _recv(owner)[-1][4] == BIND_ACK
        assert relay.allocs[sessv1.key_id].client_addr == owner.getsockname()

        # Recovery: chain state lost (crash, or an attacker raced a move
        # and spent our reveal) — a FRESH token, mintable only over the
        # authenticated signal channel, re-pins without a proof...
        tok2 = mint_relay_token(SECRET, sess.key_id, 30)
        reveal3 = _secrets.token_bytes(16)
        rec_bind = tok2 + b"\x00" * 16 + continuity_commit(reveal3)
        _bind_via(mover, relay_addr, rec_bind)
        await asyncio.sleep(0.05)
        assert _recv(mover)[-1][4] == BIND_ACK
        assert relay.allocs[sess.key_id].client_addr == mover.getsockname()
        # ...and replaying the captured recovery BIND is useless: its
        # nonce was spent on arrival.
        _bind_via(attacker, relay_addr, rec_bind)
        await asyncio.sleep(0.05)
        assert all(f[4] == BIND_ERR for f in _recv(attacker))
        assert relay.allocs[sess.key_id].client_addr == mover.getsockname()
        for s in (owner, mover, attacker):
            s.close()
    finally:
        relay.close()
        tr.close()
