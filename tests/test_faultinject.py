"""Deterministic fault injection (runtime/faultinject.py).

The chaos harness is only useful if a failing run replays: every
probabilistic decision draws from one seeded Generator in arrival order,
so (seed, packet sequence) → identical fault pattern. These tests pin
that property, the delay release mechanics at the ingest boundary, the
stall cadence, and the default-off config gate.
"""

import asyncio

from livekit_server_tpu.config.config import Config
from livekit_server_tpu.models import plane
from livekit_server_tpu.runtime import FaultInjector, PlaneRuntime
from livekit_server_tpu.runtime.faultinject import FaultSpec
from livekit_server_tpu.runtime.ingest import PacketIn


def _verdicts(inj: FaultInjector, n: int = 300) -> list[str]:
    return [inj.on_packet(None, tick_index=i) for i in range(n)]


def test_same_seed_same_fault_pattern():
    spec = FaultSpec(seed=1234, drop_pct=0.1, dup_pct=0.05, delay_pct=0.1)
    a = _verdicts(FaultInjector(spec))
    b = _verdicts(FaultInjector(spec))
    assert a == b
    # All verdict kinds actually occur at these rates over 300 draws.
    assert {"drop", "dup", "delay", "pass"} <= set(a)


def test_different_seed_different_pattern():
    base = dict(drop_pct=0.1, dup_pct=0.05, delay_pct=0.1)
    a = _verdicts(FaultInjector(FaultSpec(seed=1, **base)))
    b = _verdicts(FaultInjector(FaultSpec(seed=2, **base)))
    assert a != b


def test_verdict_is_alignment_stable():
    """One uniform draw per packet: raising a probability changes WHICH
    verdict a packet gets, but never shifts the draw sequence for the
    packets after it — so chaos runs stay comparable across intensities."""
    a = _verdicts(FaultInjector(FaultSpec(seed=7, drop_pct=0.1)))
    b = _verdicts(FaultInjector(FaultSpec(seed=7, drop_pct=0.3)))
    # Every packet dropped at the low rate is also dropped at the high one.
    assert all(y == "drop" for x, y in zip(a, b) if x == "drop")


def test_stall_cadence_deterministic():
    inj = FaultInjector(FaultSpec(stall_every=3, stall_s=0.001))
    for _ in range(9):
        inj.maybe_stall()
    assert inj.stats.stalls == 3


async def test_delayed_packet_reenters_after_delay_ticks():
    """A delayed packet is invisible to the tick that would have carried
    it and re-enters the ingest exactly delay_ticks later, riding that
    tick's normal path (same staging, same munge) as real late arrival."""
    dims = plane.PlaneDims(rooms=2, tracks=4, pkts=4, subs=4)
    rt = PlaneRuntime(dims, tick_ms=10)
    inj = FaultInjector(FaultSpec(seed=0, delay_pct=1.0, delay_ticks=2))
    rt.fault = inj
    rt.ingest.fault = inj
    rt.set_track(0, 0, published=True, is_video=False)
    rt.set_subscription(0, 0, 1, subscribed=True)

    assert rt.ingest.push(PacketIn(room=0, track=0, sn=500, ts=0,
                                   size=20, payload=b"late")) is False
    assert inj.stats.delayed == 1

    arrived_at = None
    for tick in range(5):
        res = await rt.step_once()
        if any(p.sn == 500 for p in res.egress):
            arrived_at = tick
            break
    # Pushed before tick 0, held 2 ticks → egress on the tick after its
    # release is staged (the release rides the drain of that tick).
    assert arrived_at == 2, f"delayed packet arrived at tick {arrived_at}"


async def test_dropped_packets_never_arrive():
    dims = plane.PlaneDims(rooms=2, tracks=4, pkts=4, subs=4)
    rt = PlaneRuntime(dims, tick_ms=10)
    inj = FaultInjector(FaultSpec(seed=0, drop_pct=1.0))
    rt.fault = inj
    rt.ingest.fault = inj
    rt.set_track(0, 0, published=True, is_video=False)
    rt.set_subscription(0, 0, 1, subscribed=True)
    for i in range(3):
        rt.ingest.push(PacketIn(room=0, track=0, sn=600 + i, ts=0,
                                size=20, payload=b"x"))
    res = await rt.step_once()
    assert res.egress == []
    assert inj.stats.dropped == 3


async def test_duplicated_packet_stages_twice():
    dims = plane.PlaneDims(rooms=2, tracks=4, pkts=4, subs=4)
    rt = PlaneRuntime(dims, tick_ms=10)
    inj = FaultInjector(FaultSpec(seed=0, dup_pct=1.0))
    rt.fault = inj
    rt.ingest.fault = inj
    rt.set_track(0, 0, published=True, is_video=False)
    rt.set_subscription(0, 0, 1, subscribed=True)
    rt.ingest.push(PacketIn(room=0, track=0, sn=700, ts=0,
                            size=20, payload=b"d"))
    assert inj.stats.duplicated == 1
    # Both copies were staged into the tick (two k-slots, same SN) —
    # that is what a wire-duplicated datagram looks like to the plane.
    assert int(rt.ingest.rx_pkts[0, 0]) == 2
    res = await rt.step_once()
    # The selector dedups the repeated SN on the forward path, exactly as
    # it would a real duplicate: one egress copy, not a doubled stream.
    assert [p.sn for p in res.egress] == [700]


def test_faults_off_in_default_config():
    """The acceptance gate: no fault-injection flag is enabled in the
    default config path, and validation rejects nonsense rates."""
    cfg = Config()
    assert cfg.faults.enabled is False
    assert cfg.faults.drop_pct == cfg.faults.dup_pct == cfg.faults.delay_pct == 0.0
    # A runtime built the normal way has no injector attached.
    rt = PlaneRuntime(plane.PlaneDims(rooms=2, tracks=4, pkts=4, subs=4))
    assert rt.fault is None and rt.ingest.fault is None
