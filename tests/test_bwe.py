"""BWE / channel-observer tests (reference: pkg/sfu/streamallocator trend + nack)."""

import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.ops import bwe


P = bwe.BWEParams()


def _tick(st, est=None, pkts=0.0, nacks=0.0, n=1):
    S = st.last_estimate.shape[0]
    valid = jnp.full((S,), est is not None, jnp.bool_)
    e = jnp.full((S,), 0.0 if est is None else est, jnp.float32)
    return bwe.update_tick(
        st, P, e, valid, jnp.full((S,), pkts, jnp.float32), jnp.full((S,), nacks, jnp.float32)
    )


def test_steady_estimate_not_congested():
    st = bwe.init_state(1, initial_estimate=2e6)
    for _ in range(10):
        st, congested, trend, cap = _tick(st, est=2e6, pkts=100)
    assert not bool(congested[0])
    assert abs(float(cap[0]) - 2e6) < 1


def test_falling_estimate_detected_as_congestion():
    st = bwe.init_state(1, initial_estimate=2e6)
    est = 2e6
    for _ in range(bwe.WINDOW):
        est *= 0.8
        st, congested, trend, cap = _tick(st, est=est, pkts=100)
    assert int(trend[0]) == -1
    assert bool(congested[0])
    assert float(cap[0]) <= est * 1.01


def test_nack_storm_congests():
    st = bwe.init_state(1, initial_estimate=2e6)
    for _ in range(3):
        st, congested, trend, cap = _tick(st, est=2e6, pkts=100, nacks=30)
    assert bool(congested[0])


def test_recovery_restores_capacity():
    st = bwe.init_state(1, initial_estimate=2e6)
    est = 2e6
    for _ in range(bwe.WINDOW):
        est *= 0.8
        st, congested, *_ = _tick(st, est=est, pkts=100)
    assert bool(congested[0])
    for _ in range(bwe.WINDOW + 2):
        st, congested, trend, cap = _tick(st, est=3e6, pkts=100)
    assert not bool(congested[0])
    assert abs(float(cap[0]) - 3e6) < 1


def test_batched_independent_subscribers():
    st = bwe.init_state(2, initial_estimate=2e6)
    # Sub 0 falls, sub 1 steady.
    est = np.array([2e6, 2e6], np.float32)
    for _ in range(bwe.WINDOW):
        est[0] *= 0.8
        st, congested, trend, cap = bwe.update_tick(
            st, P, jnp.asarray(est), jnp.array([True, True]),
            jnp.array([100.0, 100.0]), jnp.array([0.0, 0.0]),
        )
    assert bool(congested[0]) and not bool(congested[1])


def test_delay_bwe_converges_to_channel_rate():
    """GCC-lite send-side estimator (TWCC seat): simulate a channel of
    capacity C — when the rate exceeds C the queue (delay-variation) grows,
    below C it drains. The estimator must converge near C with NO client
    estimate samples involved."""
    P = bwe.DelayBWEParams()
    C = 2_000_000.0
    st = bwe.delay_init_state(1, initial_rate=300_000.0)
    tick = jnp.int32(20)
    queue_ms = 0.0
    rate_hist = []
    # Multiplicative increase is 8 %/s (GCC's ramp): 300 kbps → 2 Mbps
    # needs ~24 s of simulated time at a 20 ms tick.
    for i in range(1600):
        rate = float(st.rate_bps[0])
        # Channel model: above capacity the queue builds (positive delay
        # variation); below it the queue drains only while non-empty
        # (negative variation), then variation is zero.
        change = (rate - C) / C * 20.0
        if change < 0:
            change = -min(queue_ms, -change)
        queue_ms = max(0.0, queue_ms + change)
        delay_var = change
        st, r, over, active = bwe.delay_update_tick(
            st, P,
            jnp.array([delay_var], jnp.float32),
            jnp.array([min(rate, C)], jnp.float32),   # acked recv rate
            jnp.array([True]),
            jnp.array([True]),
            jnp.array([100.0], jnp.float32),
            tick,
        )
        rate_hist.append(float(r[0]))
    tail = rate_hist[-100:]
    assert all(active), "feedback-active sub must activate the cap"
    assert 0.6 * C < sum(tail) / len(tail) < 1.3 * C, sum(tail) / len(tail)


def test_delay_bwe_silent_client_decays_lying_client_capped():
    """A sealed-path client that never acks (silent) decays toward the
    floor instead of keeping an optimistic budget; a client whose acks
    reveal a slow channel is capped by measurement even if it volunteers
    a huge REMB estimate (the cap is min(estimate, delay rate))."""
    P = bwe.DelayBWEParams()
    st = bwe.delay_init_state(1, initial_rate=5_000_000.0)
    tick = jnp.int32(20)
    # Silent: sends outstanding, no feedback ever.
    for _ in range(P.fb_timeout_ticks + 200):
        st, rate, over, active = bwe.delay_update_tick(
            st, P,
            jnp.zeros(1, jnp.float32), jnp.zeros(1, jnp.float32),
            jnp.array([False]), jnp.array([True]),
            jnp.array([50.0], jnp.float32), tick,
        )
    assert bool(active[0])
    assert float(rate[0]) < 1_000_000.0  # decayed well below initial

    # Lying-but-acking: the channel is 500 kbps; overuse shows in the acks.
    st2 = bwe.delay_init_state(1, initial_rate=5_000_000.0)
    for _ in range(200):
        rate = float(st2.rate_bps[0])
        delay_var = 5.0 if rate > 500_000.0 else -2.0
        st2, r2, _, act2 = bwe.delay_update_tick(
            st2, P,
            jnp.array([delay_var], jnp.float32),
            jnp.array([500_000.0], jnp.float32),
            jnp.array([True]), jnp.array([True]),
            jnp.array([100.0], jnp.float32), tick,
        )
    assert float(r2[0]) < 700_000.0  # converged near the real channel
