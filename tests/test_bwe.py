"""BWE / channel-observer tests (reference: pkg/sfu/streamallocator trend + nack)."""

import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.ops import bwe


P = bwe.BWEParams()


def _tick(st, est=None, pkts=0.0, nacks=0.0, n=1):
    S = st.last_estimate.shape[0]
    valid = jnp.full((S,), est is not None, jnp.bool_)
    e = jnp.full((S,), 0.0 if est is None else est, jnp.float32)
    return bwe.update_tick(
        st, P, e, valid, jnp.full((S,), pkts, jnp.float32), jnp.full((S,), nacks, jnp.float32)
    )


def test_steady_estimate_not_congested():
    st = bwe.init_state(1, initial_estimate=2e6)
    for _ in range(10):
        st, congested, trend, cap = _tick(st, est=2e6, pkts=100)
    assert not bool(congested[0])
    assert abs(float(cap[0]) - 2e6) < 1


def test_falling_estimate_detected_as_congestion():
    st = bwe.init_state(1, initial_estimate=2e6)
    est = 2e6
    for _ in range(bwe.WINDOW):
        est *= 0.8
        st, congested, trend, cap = _tick(st, est=est, pkts=100)
    assert int(trend[0]) == -1
    assert bool(congested[0])
    assert float(cap[0]) <= est * 1.01


def test_nack_storm_congests():
    st = bwe.init_state(1, initial_estimate=2e6)
    for _ in range(3):
        st, congested, trend, cap = _tick(st, est=2e6, pkts=100, nacks=30)
    assert bool(congested[0])


def test_recovery_restores_capacity():
    st = bwe.init_state(1, initial_estimate=2e6)
    est = 2e6
    for _ in range(bwe.WINDOW):
        est *= 0.8
        st, congested, *_ = _tick(st, est=est, pkts=100)
    assert bool(congested[0])
    for _ in range(bwe.WINDOW + 2):
        st, congested, trend, cap = _tick(st, est=3e6, pkts=100)
    assert not bool(congested[0])
    assert abs(float(cap[0]) - 3e6) < 1


def test_batched_independent_subscribers():
    st = bwe.init_state(2, initial_estimate=2e6)
    # Sub 0 falls, sub 1 steady.
    est = np.array([2e6, 2e6], np.float32)
    for _ in range(bwe.WINDOW):
        est[0] *= 0.8
        st, congested, trend, cap = bwe.update_tick(
            st, P, jnp.asarray(est), jnp.array([True, True]),
            jnp.array([100.0, 100.0]), jnp.array([0.0, 0.0]),
        )
    assert bool(congested[0]) and not bool(congested[1])
