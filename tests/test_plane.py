"""End-to-end media-plane tick tests.

Behavioral spec: BASELINE.md config 1 (single room, 2 participants, 1 Opus
audio track each — the reference's TestSinglePublisher scenario,
test/singlenode_test.go:140) plus a VP8 simulcast room.
"""

import jax
import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.models import plane
from livekit_server_tpu.ops import audio
from livekit_server_tpu.runtime.munge import HostMunger


class DenseOut:
    """Adapter: device decision masks + host munger → the dense grids the
    assertions use (the production split: decide on device, rewrite on
    host — runtime/munge.py)."""

    def __init__(self, out, dims, munger, inp):
        self.raw = out
        self.send, drop, switch = plane.masks_to_dense(
            jax.tree.map(np.asarray, out), dims
        )
        self.out_sn, self.out_ts, self.out_pid, self.out_tl0, self.out_keyidx = (
            munger.apply_dense(
                np.asarray(inp.sn), np.asarray(inp.ts), np.asarray(inp.ts_jump),
                np.asarray(inp.pid), np.asarray(inp.tl0), np.asarray(inp.keyidx),
                np.asarray(inp.begin_pic), np.asarray(inp.valid),
                self.send, drop, switch,
            )
        )
        for f in ("need_keyframe", "speaker_levels", "speaker_tracks",
                  "congested", "target_layers", "fwd_packets", "fwd_bytes"):
            setattr(self, f, getattr(out, f))


def dense_step(step, dims):
    """Stateful step wrapper: carries the host munger across ticks exactly
    like PlaneRuntime does."""
    munger = HostMunger(dims)

    def run(st, inp):
        st, out = step(st, inp)
        return st, DenseOut(out, dims, munger, inp)
    return run


def make_inputs(dims: plane.PlaneDims, **over):
    R, T, K, S = dims
    z = lambda dt: jnp.zeros((R, T, K), dt)
    inp = plane.TickInputs(
        sn=z(jnp.int32), ts=z(jnp.int32), layer=z(jnp.int32), temporal=z(jnp.int32),
        keyframe=z(jnp.bool_), layer_sync=jnp.ones((R, T, K), jnp.bool_),
        begin_pic=jnp.ones((R, T, K), jnp.bool_),
        end_frame=jnp.ones((R, T, K), jnp.bool_),
        pid=z(jnp.int32), tl0=z(jnp.int32), keyidx=z(jnp.int32),
        size=z(jnp.int32), frame_ms=jnp.full((R, T, K), 20, jnp.int32),
        audio_level=jnp.full((R, T, K), 127, jnp.int32),
        arrival_rtp=z(jnp.int32),
        ts_jump=jnp.full((R, T, K), 3000, jnp.int32),
        valid=jnp.zeros((R, T, K), jnp.bool_),
        estimate=jnp.zeros((R, S), jnp.float32),
        estimate_valid=jnp.zeros((R, S), jnp.bool_),
        nacks=jnp.zeros((R, S), jnp.float32),
        pub_rtt_ms=jnp.zeros((R, T), jnp.float32),
        fb_delay_ms=jnp.zeros((R, S), jnp.float32),
        fb_recv_bps=jnp.zeros((R, S), jnp.float32),
        fb_valid=jnp.zeros((R, S), jnp.bool_),
        fb_enabled=jnp.zeros((R, S), jnp.bool_),
        sub_reset=jnp.zeros((R, S), jnp.bool_),
        pad_num=jnp.zeros((R, S), jnp.int32),
        pad_track=jnp.full((R, S), -1, jnp.int32),
        tick_ms=jnp.int32(20),
        roll_quality=jnp.int32(0),
    )
    return inp._replace(**over)


def two_party_audio_state():
    """Room with participants A, B; track 0 published by A (sub: B=slot1),
    track 1 published by B (sub: A=slot0)."""
    dims = plane.PlaneDims(rooms=1, tracks=2, pkts=1, subs=2)
    st = plane.init_state(dims)
    pub = np.zeros((1, 2), bool); pub[0, :] = True
    subd = np.zeros((1, 2, 2), bool)
    subd[0, 0, 1] = True  # track0 → sub B
    subd[0, 1, 0] = True  # track1 → sub A
    st = st._replace(
        meta=st.meta._replace(published=jnp.asarray(pub)),
        ctrl=st.ctrl._replace(subscribed=jnp.asarray(subd)),
    )
    return dims, st


def test_two_party_audio_forwarding():
    dims, st = two_party_audio_state()
    step = dense_step(jax.jit(plane.media_plane_tick), dims)
    sn = 1000
    for i in range(5):
        inp = make_inputs(
            dims,
            sn=jnp.asarray([[[sn + i], [sn + i]]], jnp.int32),
            ts=jnp.asarray([[[960 * i], [960 * i]]], jnp.int32),
            size=jnp.full((1, 2, 1), 120, jnp.int32),
            audio_level=jnp.asarray([[[20], [90]]], jnp.int32),  # A loud, B quiet
            valid=jnp.ones((1, 2, 1), jnp.bool_),
        )
        st, out = step(st, inp)
        send = np.asarray(out.send)[0]  # [T, K, S]
        # Track 0 goes only to sub 1; track 1 only to sub 0.
        assert send[0, 0, 1] and not send[0, 0, 0]
        assert send[1, 0, 0] and not send[1, 0, 1]
        # Audio munging is identity for a continuous stream.
        assert int(out.out_sn[0, 0, 0, 1]) == sn + i
        assert int(out.out_ts[0, 0, 0, 1]) == 960 * i
    assert int(out.fwd_packets[0]) == 2


def test_two_party_active_speaker():
    dims, st = two_party_audio_state()
    step = dense_step(jax.jit(plane.media_plane_tick), dims)
    # 30 ticks × 20 ms = 600 ms > 500 ms window ⇒ speaker ranking updates.
    for i in range(30):
        inp = make_inputs(
            dims,
            sn=jnp.asarray([[[i], [i]]], jnp.int32),
            size=jnp.full((1, 2, 1), 120, jnp.int32),
            audio_level=jnp.asarray([[[20], [90]]], jnp.int32),
            valid=jnp.ones((1, 2, 1), jnp.bool_),
        )
        st, out = step(st, inp)
    levels = np.asarray(out.speaker_levels)[0]
    tracks = np.asarray(out.speaker_tracks)[0]
    assert tracks[0] == 0          # track 0 (loud) is top speaker
    assert levels[0] > 0.05
    assert levels[1] == 0.0        # quiet track below active threshold


def test_unsubscribed_not_forwarded():
    dims, st = two_party_audio_state()
    st = st._replace(ctrl=st.ctrl._replace(subscribed=jnp.zeros((1, 2, 2), jnp.bool_)))
    step = dense_step(jax.jit(plane.media_plane_tick), dims)
    inp = make_inputs(
        dims,
        valid=jnp.ones((1, 2, 1), jnp.bool_),
        size=jnp.full((1, 2, 1), 120, jnp.int32),
    )
    st, out = step(st, inp)
    assert not np.asarray(out.send).any()
    assert int(out.fwd_packets[0]) == 0


def test_pub_mute_stops_forwarding():
    dims, st = two_party_audio_state()
    st = st._replace(meta=st.meta._replace(pub_muted=jnp.asarray([[True, False]])))
    step = dense_step(jax.jit(plane.media_plane_tick), dims)
    inp = make_inputs(
        dims, valid=jnp.ones((1, 2, 1), jnp.bool_), size=jnp.full((1, 2, 1), 100, jnp.int32)
    )
    st, out = step(st, inp)
    send = np.asarray(out.send)[0]
    assert not send[0].any()       # muted track 0
    assert send[1, 0, 0]           # track 1 still flows


def video_room_state():
    """1 video track (simulcast 3-layer), 3 subscribers."""
    dims = plane.PlaneDims(rooms=1, tracks=1, pkts=3, subs=3)
    st = plane.init_state(dims)
    st = st._replace(
        meta=plane.TrackMeta(
            is_video=jnp.ones((1, 1), jnp.bool_),
            published=jnp.ones((1, 1), jnp.bool_),
            pub_muted=jnp.zeros((1, 1), jnp.bool_),
            is_svc=jnp.zeros((1, 1), jnp.bool_),
        ),
        ctrl=st.ctrl._replace(subscribed=jnp.ones((1, 1, 3), jnp.bool_)),
    )
    return dims, st


def test_simulcast_keyframe_lockon_and_munge():
    dims, st = video_room_state()
    # Targets: selector init targets spatial 2; sub caps limit sub0 to layer 0.
    sel = st.sel._replace(
        target_spatial=jnp.asarray([[[0, 2, 2]]], jnp.int32),
        target_temporal=jnp.full((1, 1, 3), 3, jnp.int32),
    )
    # Pin allocator caps so per-tick allocation preserves the intent.
    ctrl = st.ctrl._replace(max_spatial=jnp.asarray([[[0, 2, 2]]], jnp.int32))
    st = st._replace(sel=sel, ctrl=ctrl)
    step = dense_step(jax.jit(plane.media_plane_tick), dims)

    # Tick 1: keyframes on all three layers (one packet per layer).
    inp = make_inputs(
        dims,
        sn=jnp.asarray([[[100, 5000, 9000]]], jnp.int32),
        ts=jnp.asarray([[[10, 20, 30]]], jnp.int32),
        layer=jnp.asarray([[[0, 1, 2]]], jnp.int32),
        keyframe=jnp.ones((1, 1, 3), jnp.bool_),
        pid=jnp.asarray([[[7, 300, 900]]], jnp.int32),
        size=jnp.full((1, 1, 3), 500, jnp.int32),
        valid=jnp.ones((1, 1, 3), jnp.bool_),
    )
    st, out = step(st, inp)
    send = np.asarray(out.send)[0, 0]  # [K, S]
    assert send[0, 0] and not send[1, 0] and not send[2, 0]  # sub0 ← layer0
    assert send[2, 1] and send[2, 2]                          # subs 1,2 ← layer2
    assert not send[0, 1]
    # Identity munge on first packet.
    assert int(out.out_sn[0, 0, 0, 0]) == 100
    assert int(out.out_sn[0, 0, 2, 1]) == 9000

    # Tick 2: delta frames keep flowing on locked layers.
    inp2 = make_inputs(
        dims,
        sn=jnp.asarray([[[101, 5001, 9001]]], jnp.int32),
        ts=jnp.asarray([[[3010, 3020, 3030]]], jnp.int32),
        layer=jnp.asarray([[[0, 1, 2]]], jnp.int32),
        pid=jnp.asarray([[[8, 301, 901]]], jnp.int32),
        size=jnp.full((1, 1, 3), 500, jnp.int32),
        valid=jnp.ones((1, 1, 3), jnp.bool_),
    )
    st, out = step(st, inp2)
    send = np.asarray(out.send)[0, 0]
    assert send[0, 0] and send[2, 1] and send[2, 2]
    assert int(out.out_sn[0, 0, 0, 0]) == 101
    assert not np.asarray(out.need_keyframe).any()


def svc_room_state():
    """1 SVC (VP9-style) video track, 2 subscribers."""
    dims = plane.PlaneDims(rooms=1, tracks=1, pkts=3, subs=2)
    st = plane.init_state(dims)
    st = st._replace(
        meta=plane.TrackMeta(
            is_video=jnp.ones((1, 1), jnp.bool_),
            published=jnp.ones((1, 1), jnp.bool_),
            pub_muted=jnp.zeros((1, 1), jnp.bool_),
            is_svc=jnp.ones((1, 1), jnp.bool_),
        ),
        ctrl=st.ctrl._replace(subscribed=jnp.ones((1, 1, 2), jnp.bool_)),
    )
    return dims, st


def test_svc_onion_forwarding():
    """SVC tracks forward ALL spatial layers <= current (onion), unlike
    simulcast which forwards exactly one (videolayerselector/vp9.go:43)."""
    dims, st = svc_room_state()
    # sub0 capped at spatial 0, sub1 wants the full onion.
    st = st._replace(
        sel=st.sel._replace(target_spatial=jnp.asarray([[[0, 2]]], jnp.int32)),
        ctrl=st.ctrl._replace(max_spatial=jnp.asarray([[[0, 2]]], jnp.int32)),
    )
    step = dense_step(jax.jit(plane.media_plane_tick), dims)

    # Keyframe picture carrying spatial layers 0..2 in one stream.
    inp = make_inputs(
        dims,
        sn=jnp.asarray([[[100, 101, 102]]], jnp.int32),
        ts=jnp.full((1, 1, 3), 90, jnp.int32),
        layer=jnp.asarray([[[0, 1, 2]]], jnp.int32),
        keyframe=jnp.ones((1, 1, 3), jnp.bool_),
        size=jnp.full((1, 1, 3), 500, jnp.int32),
        valid=jnp.ones((1, 1, 3), jnp.bool_),
    )
    st, out = step(st, inp)
    send = np.asarray(out.send)[0, 0]  # [K, S]
    # sub0: only spatial 0; sub1: all three layers of the onion.
    assert send[0, 0] and not send[1, 0] and not send[2, 0]
    assert send[0, 1] and send[1, 1] and send[2, 1]
    # Single SN space: munged SNs stay contiguous for the full-onion sub.
    assert [int(out.out_sn[0, 0, k, 1]) for k in range(3)] == [100, 101, 102]

    # Delta picture: same onion behavior without keyframes.
    inp2 = make_inputs(
        dims,
        sn=jnp.asarray([[[103, 104, 105]]], jnp.int32),
        ts=jnp.full((1, 1, 3), 3090, jnp.int32),
        layer=jnp.asarray([[[0, 1, 2]]], jnp.int32),
        size=jnp.full((1, 1, 3), 500, jnp.int32),
        valid=jnp.ones((1, 1, 3), jnp.bool_),
    )
    st, out = step(st, inp2)
    send = np.asarray(out.send)[0, 0]
    assert send[0, 0] and not send[2, 0]
    assert send[0, 1] and send[1, 1] and send[2, 1]
    # sub0 dropped layers 1-2 compact its SN space: next SN follows 100.
    assert int(out.out_sn[0, 0, 0, 0]) == 101


def test_quality_outputs_and_window_roll():
    """Clean stream scores EXCELLENT; heavy loss scores worse; rolling the
    window resets the accumulators (scorer.go E-model + windows)."""
    dims, st = two_party_audio_state()
    step = dense_step(jax.jit(plane.media_plane_tick), dims)
    # 10 clean ticks.
    for i in range(10):
        inp = make_inputs(
            dims,
            sn=jnp.asarray([[[i], [i]]], jnp.int32),
            size=jnp.full((1, 2, 1), 120, jnp.int32),
            valid=jnp.ones((1, 2, 1), jnp.bool_),
        )
        st, out = step(st, inp)
    assert int(out.raw.track_quality[0, 0]) == 2  # EXCELLENT
    assert float(out.raw.track_mos[0, 0]) > 4.1
    assert float(out.raw.track_loss_pct[0, 0]) == 0.0

    # Roll the window, then deliver 1-in-5 packets (80% loss).
    inp = make_inputs(dims, roll_quality=jnp.int32(1))
    st, out = step(st, inp)
    for i in range(10):
        inp = make_inputs(
            dims,
            sn=jnp.asarray([[[10 + 5 * i], [10 + i]]], jnp.int32),
            size=jnp.full((1, 2, 1), 120, jnp.int32),
            valid=jnp.ones((1, 2, 1), jnp.bool_),
        )
        st, out = step(st, inp)
    assert float(out.raw.track_loss_pct[0, 0]) > 50.0
    assert int(out.raw.track_quality[0, 0]) == 0  # POOR
    assert int(out.raw.track_quality[0, 1]) == 2  # clean track unaffected


def test_rtt_lowers_mos():
    """Measured publisher-path RTT feeds the E-model delay term
    (scorer.go:45-120): the same clean stream scores a lower MOS on a
    high-RTT path than on a low-RTT one."""
    dims, st = two_party_audio_state()
    step = dense_step(jax.jit(plane.media_plane_tick), dims)
    st_hi = st
    for i in range(10):
        base = dict(
            sn=jnp.asarray([[[i], [i]]], jnp.int32),
            size=jnp.full((1, 2, 1), 120, jnp.int32),
            valid=jnp.ones((1, 2, 1), jnp.bool_),
        )
        st, out_lo = step(st, make_inputs(dims, **base))
        st_hi, out_hi = step(
            st_hi,
            make_inputs(
                dims, pub_rtt_ms=jnp.full((1, 2), 400.0, jnp.float32), **base
            ),
        )
    mos_lo = float(out_lo.raw.track_mos[0, 0])
    mos_hi = float(out_hi.raw.track_mos[0, 0])
    assert mos_hi < mos_lo - 0.2, (mos_lo, mos_hi)
    assert mos_lo > 4.1  # clean + zero RTT stays excellent


def test_svc_single_stream_stats_no_false_loss():
    """An SVC track interleaves spatial layers in ONE SN space; stats must
    fold into one stream row, or healthy traffic reads as ~66% loss."""
    dims, st = svc_room_state()
    step = dense_step(jax.jit(plane.media_plane_tick), dims)
    for i in range(10):
        inp = make_inputs(
            dims,
            sn=jnp.asarray([[[100 + 3 * i, 101 + 3 * i, 102 + 3 * i]]], jnp.int32),
            layer=jnp.asarray([[[0, 1, 2]]], jnp.int32),
            keyframe=jnp.full((1, 1, 3), i == 0, jnp.bool_),
            size=jnp.asarray([[[300, 600, 900]]], jnp.int32),
            valid=jnp.ones((1, 1, 3), jnp.bool_),
        )
        st, out = step(st, inp)
    assert float(out.raw.track_loss_pct[0, 0]) == 0.0
    assert int(out.raw.track_quality[0, 0]) == 2  # EXCELLENT
    # Onion cost: the allocator's layer-2 entry covers layers 0+1+2, so the
    # per-subscriber target cost is the full track bitrate, not layer 2's.
    bps = float(out.raw.track_bps[0, 0])
    assert bps > 0


def test_pub_muted_track_not_lost():
    """A muted publisher sends nothing by design — quality must not read
    LOST (connectionstats.go excludes muted tracks)."""
    dims, st = two_party_audio_state()
    st = st._replace(meta=st.meta._replace(pub_muted=jnp.asarray([[True, False]])))
    step = dense_step(jax.jit(plane.media_plane_tick), dims)
    for i in range(5):
        inp = make_inputs(
            dims,
            sn=jnp.asarray([[[0], [i]]], jnp.int32),
            size=jnp.full((1, 2, 1), 120, jnp.int32),
            valid=jnp.asarray([[[False], [True]]], jnp.bool_),
        )
        st, out = step(st, inp)
    assert int(out.raw.track_quality[0, 0]) == 2  # muted ⇒ EXCELLENT, not LOST
    assert int(out.raw.track_quality[0, 1]) == 2


def test_measured_bitrate_matrix():
    """The allocator's bitrate matrix comes from measured per-layer bytes
    (streamtracker), not hardcoded fractions."""
    dims, st = video_room_state()
    step = dense_step(jax.jit(plane.media_plane_tick), dims)
    # ~600ms of traffic at 20ms ticks: layer sizes 300/600/900 bytes.
    for i in range(30):
        inp = make_inputs(
            dims,
            sn=jnp.asarray([[[100 + 3 * i, 5000 + 3 * i, 9000 + 3 * i]]], jnp.int32),
            layer=jnp.asarray([[[0, 1, 2]]], jnp.int32),
            keyframe=jnp.full((1, 1, 3), i == 0, jnp.bool_),
            size=jnp.asarray([[[300, 600, 900]]], jnp.int32),
            valid=jnp.ones((1, 1, 3), jnp.bool_),
        )
        st, out = step(st, inp)
    # All three layers live after the tracker cycles.
    assert np.asarray(out.raw.layer_live)[0, 0].tolist() == [1, 1, 1]
    # Track bitrate reflects the 1800 B/tick → ~720 kbps load.
    bps = float(out.raw.track_bps[0, 0])
    assert 4e5 < bps < 1.1e6, bps


def test_multi_room_vmap_isolation():
    dims = plane.PlaneDims(rooms=2, tracks=1, pkts=1, subs=2)
    st = plane.init_state(dims)
    pub = jnp.asarray([[True], [True]])
    subd = np.zeros((2, 1, 2), bool)
    subd[0, 0, 1] = True   # room0: sub1 subscribed
    # room1: nobody subscribed
    st = st._replace(
        meta=st.meta._replace(published=pub),
        ctrl=st.ctrl._replace(subscribed=jnp.asarray(subd)),
    )
    step = dense_step(jax.jit(plane.media_plane_tick), dims)
    inp = make_inputs(
        dims, valid=jnp.ones((2, 1, 1), jnp.bool_), size=jnp.full((2, 1, 1), 99, jnp.int32)
    )
    st, out = step(st, inp)
    assert int(out.fwd_packets[0]) == 1
    assert int(out.fwd_packets[1]) == 0


def test_sub_reset_clears_per_sub_bwe_state():
    """A released subscriber slot must hand its successor FRESH per-sub
    state: a decayed delay-BWE floor rate (silent previous occupant) would
    otherwise cap the new subscriber's budget for up to a minute."""
    dims, st = two_party_audio_state()
    step = jax.jit(plane.media_plane_tick)
    # Starve sub 0: sealed path enabled, sends outstanding, never acks.
    inp = make_inputs(
        dims,
        valid=jnp.ones((1, 2, 1), jnp.bool_),
        size=jnp.full((1, 2, 1), 120, jnp.int32),
        fb_enabled=jnp.asarray([[True, False]]),
    )
    for _ in range(120):
        st, out = step(st, inp)
    decayed = float(st.delay_bwe.rate_bps[0, 0])
    assert decayed < 2_000_000.0  # well below the 7 Mbps initial
    # Slot released & reused: one tick with sub_reset set.
    st, out = step(st, inp._replace(sub_reset=jnp.asarray([[True, False]])))
    assert float(st.delay_bwe.rate_bps[0, 0]) > 6_000_000.0
    assert not bool(st.delay_bwe.ever_fb[0, 0])


async def test_watchdog_restarts_stalled_plane_from_snapshot():
    """Supervision: a wedged device step (injected stall) trips the tick
    watchdog; the supervisor abandons the stuck worker thread, restores
    the last checkpoint, and the plane resumes ticking within the restart
    budget — with munger state REWOUND to the snapshot (post-checkpoint
    packets would be re-issued as duplicates, never skipped)."""
    import asyncio

    from livekit_server_tpu.runtime import (
        FaultInjector,
        PlaneRuntime,
        PlaneSupervisor,
    )
    from livekit_server_tpu.runtime.faultinject import FaultSpec
    from livekit_server_tpu.runtime.ingest import PacketIn
    from livekit_server_tpu.utils.backoff import BackoffPolicy

    dims = plane.PlaneDims(rooms=2, tracks=4, pkts=4, subs=4)
    rt = PlaneRuntime(dims, tick_ms=10)
    rt.set_track(0, 0, published=True, is_video=False)
    rt.set_subscription(0, 0, 1, subscribed=True)
    for i in range(3):
        rt.ingest.push(PacketIn(room=0, track=0, sn=100 + i, ts=0,
                                size=20, payload=b"x"))
        await rt.step_once()

    sup = PlaneSupervisor(
        rt, tick_deadline_s=0.25, check_interval_s=0.02,
        checkpoint_interval_s=60.0, max_restarts=5,
        backoff=BackoffPolicy(base=0.02, max_delay=0.1),
    )
    await sup.checkpoint_now()
    at_checkpoint = int(rt.munger.last_sn[0, 0, 1])
    assert at_checkpoint == 102

    # Advance PAST the checkpoint so the restore is observable as a
    # rewind, not just "state unchanged".
    for i in range(2):
        rt.ingest.push(PacketIn(room=0, track=0, sn=103 + i, ts=0,
                                size=20, payload=b"x"))
        await rt.step_once()
    assert int(rt.munger.last_sn[0, 0, 1]) > at_checkpoint

    rt.fault = FaultInjector(FaultSpec(stall_every=1, stall_s=0.8))
    rt.start()
    sup.start()
    try:
        async def until(cond, timeout=30.0):
            deadline = asyncio.get_running_loop().time() + timeout
            while not cond():
                assert asyncio.get_running_loop().time() < deadline, \
                    "timed out waiting for supervisor"
                await asyncio.sleep(0.01)

        await until(lambda: sup.restarts >= 1)
        stalls = rt.fault.stats.stalls
        assert stalls >= 1
        rt.fault = None  # the hang "clears"; the restarted plane runs clean
        base = rt.stats["ticks"]
        await until(lambda: rt.stats["ticks"] >= base + 5)
        assert sup.restarts >= 1
        assert not sup.gave_up
        assert int(rt.munger.last_sn[0, 0, 1]) == at_checkpoint
    finally:
        await sup.stop()
        await rt.stop()
