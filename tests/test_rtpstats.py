"""RTP stats tests (reference: pkg/sfu/buffer/rtpstats_receiver_test.go semantics)."""

import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.ops import rtpstats


def _tick(st, sns, tss=None, sizes=None, arr=None, valid=None):
    N, K = 1, len(sns)
    tss = tss or [0] * K
    sizes = sizes or [100] * K
    arr = arr or tss
    valid = [True] * K if valid is None else valid
    return rtpstats.update_tick(
        st,
        jnp.asarray([sns], jnp.int32),
        jnp.asarray([tss], jnp.int32),
        jnp.asarray([sizes], jnp.int32),
        jnp.asarray([arr], jnp.int32),
        jnp.asarray([valid], jnp.bool_),
    )


def test_basic_counts():
    st = rtpstats.init_state(1)
    st = _tick(st, [100, 101, 102])
    assert int(st.received[0]) == 3
    assert int(st.bytes[0]) == 300
    assert int(rtpstats.expected_packets(st)[0]) == 3
    assert int(rtpstats.cumulative_lost(st)[0]) == 0


def test_loss_detection():
    st = rtpstats.init_state(1)
    st = _tick(st, [100, 103, 104])  # 101, 102 missing
    assert int(rtpstats.expected_packets(st)[0]) == 5
    assert int(rtpstats.cumulative_lost(st)[0]) == 2


def test_duplicates_counted():
    st = rtpstats.init_state(1)
    st = _tick(st, [100, 100, 101])
    assert int(st.dups[0]) == 1
    assert int(st.received[0]) == 3


def test_sn_wrap_expected():
    st = rtpstats.init_state(1)
    st = _tick(st, [65534, 65535, 0, 1])
    assert int(st.sn_cycles[0]) == 1
    assert int(rtpstats.expected_packets(st)[0]) == 4


def test_jitter_accumulates():
    st = rtpstats.init_state(1)
    # Packets 160 RTP units apart but arriving with increasing delay.
    st = _tick(st, [1, 2, 3, 4], tss=[0, 160, 320, 480], arr=[0, 200, 420, 700])
    assert int(st.jitter_q4[0]) > 0


def test_receiver_report_deltas():
    st = rtpstats.init_state(1)
    st = _tick(st, [10, 12])  # 1 lost
    st, rep = rtpstats.receiver_report(st)
    assert int(rep["cumulative_lost"][0]) == 1
    assert int(rep["fraction_lost_q8"][0]) == (1 << 8) // 3
    # Second window clean.
    st = _tick(st, [13, 14])
    st, rep = rtpstats.receiver_report(st)
    assert int(rep["fraction_lost_q8"][0]) == 0
    assert int(rep["cumulative_lost"][0]) == 1
