"""Media-wire AEAD frame tests (the DTLS-SRTP seat — runtime/crypto.py).

Reference parity: pion/srtp's protection profile behavior as used by
pkg/rtc/transport.go — authenticated encryption both directions, replay
rejection, and direction separation.
"""

from livekit_server_tpu.runtime.crypto import (
    HEADER_LEN,
    MediaCryptoClient,
    MediaCryptoRegistry,
    parse_key_id,
)


def make_pair():
    reg = MediaCryptoRegistry()
    server = reg.mint()
    client = MediaCryptoClient(server.key_id, server.key)
    return reg, server, client


def test_roundtrip_both_directions():
    _, server, client = make_pair()
    up = client.seal(b"rtp-upstream")
    assert parse_key_id(up) == server.key_id
    assert server.open(up) == b"rtp-upstream"
    down = server.seal(b"rtp-downstream")
    assert client.open(down) == b"rtp-downstream"


def test_tamper_rejected():
    _, server, client = make_pair()
    frame = bytearray(client.seal(b"payload"))
    frame[-1] ^= 0x01  # flip a tag bit
    assert server.open(bytes(frame)) is None
    frame2 = bytearray(client.seal(b"payload"))
    frame2[HEADER_LEN] ^= 0x01  # flip a ciphertext bit
    assert server.open(bytes(frame2)) is None
    frame3 = bytearray(client.seal(b"payload"))
    frame3[2] ^= 0x01  # flip a header (AAD) bit
    assert server.open(bytes(frame3)) is None


def test_replay_rejected():
    _, server, client = make_pair()
    f1 = client.seal(b"one")
    f2 = client.seal(b"two")
    assert server.open(f2) == b"two"
    assert server.open(f1) == b"one"  # out-of-order within window is fine
    assert server.open(f1) is None   # exact replay is not
    assert server.open(f2) is None


def test_replay_huge_counter_jump_bounded():
    """An attacker-chosen counter (authenticated but arbitrary) must not
    drive the replay bitmap shift — a 2^60 jump would otherwise try to
    allocate an exabyte-scale int from one datagram."""
    _, server, client = make_pair()
    assert server.open(client.seal(b"first")) == b"first"
    client.tx_counter = 1 << 60
    assert server.open(client.seal(b"jump")) == b"jump"  # no OOM
    # Everything far behind the window is now dead.
    client.tx_counter = 5
    assert server.open(client.seal(b"old")) is None


def test_direction_reflection_rejected():
    """A captured server→client frame replayed back must not open as
    client→server traffic (the nonce direction byte separates them)."""
    _, server, client = make_pair()
    down = server.seal(b"downstream")
    assert server.open(down) is None
    up = client.seal(b"upstream")
    assert client.open(up) is None


def test_wrong_key_rejected():
    reg, server, _client = make_pair()
    other = reg.mint()
    evil = MediaCryptoClient(server.key_id, other.key)  # right id, wrong key
    assert server.open(evil.seal(b"x")) is None


def test_registry_remove():
    reg, server, _ = make_pair()
    assert reg.get(server.key_id) is server
    reg.remove(server.key_id)
    assert reg.get(server.key_id) is None
