"""Simulcast layer-selector tests (reference: pkg/sfu/videolayerselector/simulcast.go)."""

import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.ops import selector


def _tick(state, spatial, temporal, keyframe, sync=None, valid=None):
    P = len(spatial)
    sync = [True] * P if sync is None else sync
    valid = [True] * P if valid is None else valid
    return selector.select_tick(
        state,
        jnp.asarray(spatial, jnp.int32),
        jnp.asarray(temporal, jnp.int32),
        jnp.asarray(keyframe, jnp.bool_),
        jnp.asarray(sync, jnp.bool_),
        jnp.asarray(valid, jnp.bool_),
    )


def test_locks_on_at_keyframe_of_target():
    st = selector.init_state(1, target_spatial=1, target_temporal=2)
    # Non-keyframe packets of the target layer are not forwarded before lock-on.
    st, fwd, drp, sw, need_kf = _tick(st, [1, 1], [0, 0], [False, False])
    assert not np.asarray(fwd).any()
    assert bool(need_kf[0])
    # Keyframe arrives: switch + forward.
    st, fwd, drp, sw, need_kf = _tick(st, [1, 1], [0, 0], [True, False])
    assert np.asarray(fwd)[:, 0].all()
    assert bool(sw[0, 0]) and not bool(sw[1, 0])
    assert not bool(need_kf[0])
    assert int(st.current_spatial[0]) == 1


def test_other_layers_ignored():
    st = selector.init_state(1, target_spatial=0, target_temporal=3)
    st, fwd, drp, sw, _ = _tick(st, [0, 1, 2, 0], [0, 0, 0, 0], [True, True, True, False])
    f = np.asarray(fwd)[:, 0]
    assert list(f) == [True, False, False, True]
    # Non-current layers are neither forwarded nor dropped (independent SN spaces).
    d = np.asarray(drp)[:, 0]
    assert not d.any()


def test_spatial_upgrade_waits_for_keyframe():
    st = selector.init_state(1, target_spatial=0, target_temporal=3)
    st, *_ = _tick(st, [0], [0], [True])
    st = selector.set_target(st, jnp.array([2], jnp.int32), jnp.array([3], jnp.int32))
    # Still forwarding layer 0 until a layer-2 keyframe shows up.
    st, fwd, _, sw, need_kf = _tick(st, [0, 2], [0, 0], [False, False])
    assert bool(fwd[0, 0]) and not bool(fwd[1, 0])
    assert bool(need_kf[0])
    st, fwd, _, sw, need_kf = _tick(st, [2, 0], [0, 0], [True, False])
    assert bool(fwd[0, 0]) and not bool(fwd[1, 0])  # switched to layer 2
    assert bool(sw[0, 0])
    assert int(st.current_spatial[0]) == 2


def test_temporal_filtering_drops_and_compacts():
    st = selector.init_state(1, target_spatial=0, target_temporal=0)
    st, fwd, drp, *_ = _tick(st, [0, 0, 0], [0, 2, 0], [True, False, False])
    f = np.asarray(fwd)[:, 0]
    d = np.asarray(drp)[:, 0]
    assert list(f) == [True, False, True]
    assert list(d) == [False, True, False]


def test_temporal_upgrade_at_sync_point():
    st = selector.init_state(1, target_spatial=0, target_temporal=0)
    st, *_ = _tick(st, [0], [0], [True])
    st = selector.set_target(st, jnp.array([0], jnp.int32), jnp.array([2], jnp.int32))
    # tid-2 packet without layer sync: still dropped.
    st, fwd, drp, *_ = _tick(st, [0], [2], [False], sync=[False])
    assert not bool(fwd[0, 0])
    # With layer sync: upgraded and forwarded.
    st, fwd, drp, *_ = _tick(st, [0], [2], [False], sync=[True])
    assert bool(fwd[0, 0])
    assert int(st.current_temporal[0]) == 2


def test_pause_stops_forwarding():
    st = selector.init_state(1, target_spatial=0, target_temporal=3)
    st, *_ = _tick(st, [0], [0], [True])
    st = selector.set_target(st, jnp.array([-1], jnp.int32), jnp.array([-1], jnp.int32))
    st, fwd, *_ = _tick(st, [0], [0], [False])
    assert not np.asarray(fwd).any()
    assert int(st.current_spatial[0]) == -1


def test_vmap_over_subscribers():
    st = selector.init_state(3, target_spatial=1, target_temporal=3)
    st = selector.set_target(
        st, jnp.array([0, 1, -1], jnp.int32), jnp.array([3, 3, -1], jnp.int32)
    )
    st, fwd, drp, sw, need_kf = _tick(st, [0, 1], [0, 0], [True, True])
    f = np.asarray(fwd)
    assert bool(f[0, 0]) and not bool(f[1, 0])   # sub0 on layer 0
    assert not bool(f[0, 1]) and bool(f[1, 1])   # sub1 on layer 1
    assert not f[:, 2].any()                     # sub2 paused


def test_pallas_decide_rooms_matches_fallback():
    """The fused forward-decision kernel (selection + base merge + audio
    path + bit packing + send sums — the production TPU phase 0) is
    bit-equivalent to the composed per-room fallback."""
    import numpy as np

    from livekit_server_tpu.ops import selector as sel

    rng = np.random.default_rng(17)
    for R, T, K, S in ((4, 3, 5, 7), (6, 4, 4, 33)):
        st = sel.SelectorState(
            current_spatial=jnp.asarray(rng.integers(-1, 3, (R, T, S)), jnp.int32),
            current_temporal=jnp.asarray(rng.integers(-1, 4, (R, T, S)), jnp.int32),
            target_spatial=jnp.asarray(rng.integers(-1, 3, (R, T, S)), jnp.int32),
            target_temporal=jnp.asarray(rng.integers(0, 4, (R, T, S)), jnp.int32),
        )
        is_svc = jnp.asarray(rng.random((R, T)) < 0.5)
        is_video = jnp.asarray(rng.random((R, T)) < 0.6)
        base = jnp.asarray(rng.random((R, T, S)) < 0.7)
        args = [jnp.asarray(rng.integers(0, 3, (R, T, K)), jnp.int32),
                jnp.asarray(rng.integers(0, 4, (R, T, K)), jnp.int32),
                jnp.asarray(rng.random((R, T, K)) < 0.3),
                jnp.asarray(rng.random((R, T, K)) < 0.5),
                jnp.asarray(rng.random((R, T, K)) < 0.4),
                jnp.asarray(rng.random((R, T, K)) < 0.9),
                jnp.asarray(rng.integers(40, 1300, (R, T, K)), jnp.int32)]
        a = sel.decide_rooms(st, is_svc, is_video, base, *args,
                             wire_overhead=46, use_pallas=False)
        b = sel.decide_rooms(st, is_svc, is_video, base, *args,
                             wire_overhead=46, interpret=True)
        for xv, yv in zip(a[0], b[0]):
            assert np.array_equal(np.asarray(xv), np.asarray(yv))
        for x, y in zip(a[1:], b[1:]):
            assert np.array_equal(np.asarray(x), np.asarray(y))


def test_pallas_decide_rooms_state_feedback_parity():
    """Sequence parity: the kernel's UPDATED selector state fed back
    across ticks stays bit-identical to the fallback's — single-call
    parity alone would miss drift that only compounds through the
    current/target feedback loop (the production steady state)."""
    import numpy as np

    from livekit_server_tpu.ops import selector as sel

    rng = np.random.default_rng(23)
    R, T, K, S = 4, 3, 4, 8
    st_a = st_b = sel.SelectorState(
        current_spatial=jnp.full((R, T, S), -1, jnp.int32),
        current_temporal=jnp.full((R, T, S), -1, jnp.int32),
        target_spatial=jnp.asarray(rng.integers(0, 3, (R, T, S)), jnp.int32),
        target_temporal=jnp.asarray(rng.integers(0, 4, (R, T, S)), jnp.int32),
    )
    is_svc = jnp.asarray(rng.random((R, T)) < 0.5)
    is_video = jnp.asarray(rng.random((R, T)) < 0.7)
    base = jnp.asarray(rng.random((R, T, S)) < 0.8)
    for tick in range(4):
        args = [jnp.asarray(rng.integers(0, 3, (R, T, K)), jnp.int32),
                jnp.asarray(rng.integers(0, 4, (R, T, K)), jnp.int32),
                jnp.asarray(rng.random((R, T, K)) < 0.3),
                jnp.asarray(rng.random((R, T, K)) < 0.6),
                jnp.asarray(rng.random((R, T, K)) < 0.4),
                jnp.asarray(rng.random((R, T, K)) < 0.9),
                jnp.asarray(rng.integers(40, 1300, (R, T, K)), jnp.int32)]
        a = sel.decide_rooms(st_a, is_svc, is_video, base, *args,
                             wire_overhead=46, use_pallas=False)
        b = sel.decide_rooms(st_b, is_svc, is_video, base, *args,
                             wire_overhead=46, interpret=True)
        st_a, st_b = a[0], b[0]
        for xv, yv in zip(st_a, st_b):
            assert np.array_equal(np.asarray(xv), np.asarray(yv)), tick
        for x, y in zip(a[1:], b[1:]):
            assert np.array_equal(np.asarray(x), np.asarray(y)), tick
