"""UDP media transport end-to-end: real sockets → native parse → plane →
rewrite → real sockets.

Reference parity: the media half of test/singlenode_test.go TestSinglePublisher
— but over this build's plain-RTP UDP wire instead of Pion WebRTC.
"""

import asyncio
import socket

import numpy as np

from livekit_server_tpu.models import plane
from livekit_server_tpu.native import rtp as parser
from livekit_server_tpu.runtime import PlaneRuntime
from livekit_server_tpu.runtime.udp import start_udp_transport
from tests.test_native import rtp_packet, vp8_payload

DIMS = plane.PlaneDims(rooms=2, tracks=4, pkts=8, subs=4)


async def test_udp_publish_forward_receive():
    runtime = PlaneRuntime(DIMS, tick_ms=10)
    # free port
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        # control plane: room row 0, track col 0 published (audio), sub 1
        runtime.set_track(0, 0, published=True, is_video=False)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        ssrc = transport.assign_ssrc(room=0, track=0, is_video=False)

        # publisher + subscriber client sockets
        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.bind(("127.0.0.1", 0))
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())

        got = []
        for i in range(5):
            pub.sendto(
                rtp_packet(sn=600 + i, ts=960 * i, ssrc=ssrc, audio_level=20,
                           payload=b"opus" + bytes([i])),
                ("127.0.0.1", port),
            )
            await asyncio.sleep(0.02)  # let datagram_received run
            res = await runtime.step_once()
            transport.send_egress(res.egress)
            await asyncio.sleep(0.01)
            while True:
                try:
                    data, _ = sub.recvfrom(2048)
                    got.append(data)
                except BlockingIOError:
                    break

        assert transport.stats["rx"] == 5
        assert transport.stats["parse_errors"] == 0
        assert len(got) == 5
        # received packets are valid RTP with the original SNs and payloads
        from livekit_server_tpu.native import rtp as parser
        for i, data in enumerate(got):
            out = parser.parse_batch(
                data, np.asarray([0], np.int32), np.asarray([len(data)], np.int32)
            )[0]
            assert int(out["sn"]) == 600 + i
            off, ln = int(out["payload_off"]), int(out["payload_len"])
            assert data[off : off + ln] == b"opus" + bytes([i])
        pub.close()
        sub.close()
    finally:
        transport.transport.close()


async def test_udp_vp8_rewrite_reaches_wire_across_layer_switch():
    """Simulcast layer switch: the device's rewritten picture ids must
    appear in the actual payload bytes on the wire, contiguous across the
    switch even though each source layer has its own pid space (the bug
    codecmunger/vp8.go:161 exists to prevent)."""
    runtime = PlaneRuntime(DIMS, tick_ms=10)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        runtime.set_track(0, 0, published=True, is_video=True)
        runtime.set_subscription(0, 0, 1, subscribed=True)
        ssrc0 = transport.assign_ssrc(room=0, track=0, is_video=True, layer=0)
        ssrc1 = transport.assign_ssrc(room=0, track=0, is_video=True, layer=1)

        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)
        transport.register_subscriber(0, 1, sub.getsockname())

        async def send_and_step(sn, ts, ssrc, pid, keyframe):
            pub.sendto(
                rtp_packet(
                    sn=sn, ts=ts, ssrc=ssrc, pt=96,
                    payload=vp8_payload(pid=pid, tl0=pid % 256, tid=0,
                                        keyidx=pid % 32, keyframe=keyframe),
                ),
                ("127.0.0.1", port),
            )
            await asyncio.sleep(0.02)
            res = await runtime.step_once()
            transport.send_egress(res.egress)
            await asyncio.sleep(0.01)

        # Layer 0: keyframe + deltas, pid space starting at 1000.
        for i in range(6):
            await send_and_step(100 + i, 90 * i, ssrc0, 1000 + i, i == 0)
        # Layer 1 appears with keyframes, its own pid space at 5000; once
        # its bitrate registers the allocator upgrades and the selector
        # switches at a layer-1 keyframe.
        for i in range(30):
            await send_and_step(500 + i, 90 * (6 + i), ssrc1, 5000 + i, True)

        got = []
        while True:
            try:
                got.append(sub.recvfrom(4096)[0])
            except BlockingIOError:
                break
        assert len(got) >= 10, f"only {len(got)} packets received"
        pids = []
        for data in got:
            out = parser.parse_batch(
                data, np.asarray([0], np.int32), np.asarray([len(data)], np.int32),
                vp8_pts={96},
            )[0]
            assert int(out["payload_len"]) > 0
            pids.append(int(out["picture_id"]))
        # Wire picture ids must be CONTIGUOUS across the source switch —
        # no 1000→5000 jump may survive to the payload bytes.
        diffs = [b - a for a, b in zip(pids, pids[1:])]
        assert all(d == 1 for d in diffs), f"pids not contiguous: {pids}"
        pub.close()
        sub.close()
    finally:
        transport.transport.close()


async def test_udp_punch_latches_only_real_source():
    """Egress addresses latch only from a punch datagram carrying a minted
    id, sent from the client's actual socket — a forged/unknown punch id is
    ignored (traffic-reflection hardening)."""
    from livekit_server_tpu.runtime.udp import PUNCH_ACK, PUNCH_REQ

    runtime = PlaneRuntime(DIMS, tick_ms=10)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        pid = transport.assign_subscriber_punch(0, 1)
        sub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub.bind(("127.0.0.1", 0))
        sub.setblocking(False)

        # wrong id: no latch, counted
        sub.sendto(PUNCH_REQ + (pid ^ 0xFFFF).to_bytes(4, "big"), ("127.0.0.1", port))
        await asyncio.sleep(0.05)
        assert (0, 1) not in transport.sub_addrs
        assert transport.stats["bad_punch"] == 1

        # right id from the real socket: latches + acked
        sub.sendto(PUNCH_REQ + pid.to_bytes(4, "big"), ("127.0.0.1", port))
        await asyncio.sleep(0.05)
        assert transport.sub_addrs[(0, 1)] == sub.getsockname()
        ack, _ = sub.recvfrom(2048)
        assert ack == PUNCH_ACK + pid.to_bytes(4, "big")

        # retry from the SAME socket (lost ack): re-acked, still latched
        sub.sendto(PUNCH_REQ + pid.to_bytes(4, "big"), ("127.0.0.1", port))
        await asyncio.sleep(0.05)
        ack, _ = sub.recvfrom(2048)
        assert ack == PUNCH_ACK + pid.to_bytes(4, "big")

        # replay of the latched id from a DIFFERENT socket (an observer of
        # the cleartext handshake): rejected, latch unchanged
        evil = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        evil.bind(("127.0.0.1", 0))
        evil.sendto(PUNCH_REQ + pid.to_bytes(4, "big"), ("127.0.0.1", port))
        await asyncio.sleep(0.05)
        assert transport.sub_addrs[(0, 1)] == sub.getsockname()
        assert transport.stats["bad_punch"] == 2
        evil.close()

        # the outstanding id is reused across subscription signals (even
        # after a latch — a routine second subscription must not kill an
        # id whose ack may still be in flight)
        assert transport.assign_subscriber_punch(0, 2) == transport.assign_subscriber_punch(0, 2)
        assert transport.assign_subscriber_punch(0, 1) == pid
        # …but an explicit re-punch request ROTATES it (NAT-rebind
        # recovery: old id dies, new unguessable one minted)
        pid2 = transport.assign_subscriber_punch(0, 1, rotate=True)
        assert pid2 != pid
        assert pid not in transport.punch_ids
        sub2 = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sub2.bind(("127.0.0.1", 0))
        sub2.setblocking(False)
        sub2.sendto(PUNCH_REQ + pid2.to_bytes(4, "big"), ("127.0.0.1", port))
        await asyncio.sleep(0.05)
        assert transport.sub_addrs[(0, 1)] == sub2.getsockname()
        sub2.close()

        # release clears the outstanding punch id too
        transport.release_subscriber(0, 1)
        assert pid2 not in transport.punch_ids
        assert (0, 1) not in transport._punch_by_sub
        sub.close()
    finally:
        transport.transport.close()


async def test_udp_unknown_ssrc_dropped():
    runtime = PlaneRuntime(DIMS, tick_ms=10)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    transport = await start_udp_transport(runtime.ingest, "127.0.0.1", port)
    try:
        pub = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pub.sendto(rtp_packet(ssrc=0xBEEF), ("127.0.0.1", port))
        pub.sendto(b"garbage", ("127.0.0.1", port))
        await asyncio.sleep(0.05)
        assert transport.stats["unknown_ssrc"] == 1
        assert transport.stats["parse_errors"] == 1
        assert not runtime.ingest.valid.any()
        pub.close()
    finally:
        transport.transport.close()
